"""RoI long-tail ops vs transcribed C++ oracles.

Oracles transcribe (SURVEY §4 OpTest style):
  prroi_pool_op.h (exact bilinear integral), deformable_psroi_pooling_op.h
  (offset sampling), roi_perspective_transform_op.cc (homography + in_quad),
  polygon_box_transform_op.cc.
"""
import numpy as np
import pytest

import jax

from paddle_tpu.nn import functional as F


def _bilinear(feat, h, w):
    H, W = feat.shape
    h0, w0 = int(np.floor(h)), int(np.floor(w))
    h0, w0 = max(0, min(h0, H - 1)), max(0, min(w0, W - 1))
    h1, w1 = min(h0 + 1, H - 1), min(w0 + 1, W - 1)
    lh, lw = h - h0, w - w0
    top = feat[h0, w0] + (feat[h0, w1] - feat[h0, w0]) * lw
    bot = feat[h1, w0] + (feat[h1, w1] - feat[h1, w0]) * lw
    return top + (bot - top) * lh


class TestPrRoIPool:
    def _integral_oracle(self, feat, x0, y0, x1, y1, n=400):
        """Numerical integral of the bilinear surface over the window
        (dense quadrature stands in for the closed form)."""
        H, W = feat.shape
        xs = np.linspace(x0, x1, n, endpoint=False) + (x1 - x0) / n / 2
        ys = np.linspace(y0, y1, n, endpoint=False) + (y1 - y0) / n / 2
        total = 0.0
        for y in ys:
            for x in xs:
                # hat-basis interpolation with zero outside the map
                v = 0.0
                for py in (int(np.floor(y)), int(np.floor(y)) + 1):
                    for px in (int(np.floor(x)), int(np.floor(x)) + 1):
                        if 0 <= py < H and 0 <= px < W:
                            wgt = max(0.0, 1 - abs(x - px)) * \
                                max(0.0, 1 - abs(y - py))
                            v += feat[py, px] * wgt
                total += v
        area = (x1 - x0) * (y1 - y0)
        return total * area / (n * n) / area if area > 0 else 0.0

    def test_vs_numerical_integral(self):
        rng = np.random.RandomState(0)
        feat = rng.rand(1, 1, 6, 6).astype(np.float32)
        rois = np.array([[0.7, 1.2, 4.3, 4.9]], np.float32)
        out = np.asarray(F.prroi_pool(feat, rois, 1.0, 2, 2))
        x0, y0, x1, y1 = rois[0]
        bw, bh = (x1 - x0) / 2, (y1 - y0) / 2
        for ph in range(2):
            for pw in range(2):
                want = self._integral_oracle(
                    feat[0, 0], x0 + pw * bw, y0 + ph * bh,
                    x0 + (pw + 1) * bw, y0 + (ph + 1) * bh)
                # mean over the window = integral / area
                np.testing.assert_allclose(out[0, 0, ph, pw], want,
                                           rtol=2e-3, atol=2e-3)

    def test_constant_field_is_identity(self):
        feat = np.full((1, 3, 8, 8), 2.5, np.float32)
        rois = np.array([[1.0, 1.0, 6.0, 6.0]], np.float32)
        out = np.asarray(F.prroi_pool(feat, rois, 1.0, 3, 3))
        np.testing.assert_allclose(out, 2.5, rtol=1e-5)

    def test_differentiable_in_rois(self):
        # the headline PrRoI property: gradients flow into coordinates
        rng = np.random.RandomState(1)
        feat = rng.rand(1, 1, 8, 8).astype(np.float32)

        def f(r):
            return F.prroi_pool(feat, r.reshape(1, 4), 1.0, 2, 2).sum()

        g = jax.grad(f)(np.array([1.0, 1.0, 6.0, 6.0], np.float32))
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).max() > 0

    def test_batch_roi_nums(self):
        rng = np.random.RandomState(2)
        feat = rng.rand(2, 1, 6, 6).astype(np.float32)
        rois = np.array([[0, 0, 4, 4], [0, 0, 4, 4]], np.float32)
        out = np.asarray(F.prroi_pool(feat, rois, 1.0, 2, 2,
                                      batch_roi_nums=np.array([1, 1])))
        # same roi, different images → different values
        assert np.abs(out[0] - out[1]).max() > 1e-4


class TestDeformableRoIPooling:
    def _oracle(self, x, roi, trans, no_trans, scale, PH, PW, gh, gw,
                part_h, part_w, sp, trans_std, ps):
        """Transcribes DeformablePSROIPoolForwardCPUKernel."""
        N, C, H, W = x.shape
        out_dim = C // (PH * PW) if ps else C
        nc = trans.shape[1] // 2 if not no_trans else 1
        cec = max(out_dim // nc, 1)
        x0 = round(roi[0]) * scale - 0.5
        y0 = round(roi[1]) * scale - 0.5
        x1 = (round(roi[2]) + 1.0) * scale - 0.5
        y1 = (round(roi[3]) + 1.0) * scale - 0.5
        rw, rh = max(x1 - x0, 0.1), max(y1 - y0, 0.1)
        bw, bh = rw / PW, rh / PH
        out = np.zeros((out_dim, PH, PW), np.float32)
        for ct in range(out_dim):
            for ph in range(PH):
                for pw in range(PW):
                    pth = int(np.floor(ph / PH * part_h))
                    ptw = int(np.floor(pw / PW * part_w))
                    cid = ct // cec
                    tx = 0.0 if no_trans else \
                        trans[0, 2 * cid, pth, ptw] * trans_std
                    ty = 0.0 if no_trans else \
                        trans[0, 2 * cid + 1, pth, ptw] * trans_std
                    ws = pw * bw + x0 + tx * rw
                    hs = ph * bh + y0 + ty * rh
                    if ps:
                        g_w = min(max(int(np.floor(pw * gw / PW)), 0), gw - 1)
                        g_h = min(max(int(np.floor(ph * gh / PH)), 0), gh - 1)
                        c = (ct * gh + g_h) * gw + g_w
                    else:
                        c = ct
                    s, n = 0.0, 0
                    for ih in range(sp):
                        for iw in range(sp):
                            w = ws + iw * (bw / sp)
                            h = hs + ih * (bh / sp)
                            if w < -0.5 or w > W - 0.5 or h < -0.5 \
                                    or h > H - 0.5:
                                continue
                            w = min(max(w, 0.0), W - 1.0)
                            h = min(max(h, 0.0), H - 1.0)
                            s += _bilinear(x[0, c], h, w)
                            n += 1
                    out[ct, ph, pw] = s / n if n else 0.0
        return out

    @pytest.mark.parametrize("ps", [False, True])
    def test_vs_oracle(self, ps):
        rng = np.random.RandomState(3)
        PH = PW = 2
        C = 8 if ps else 3
        x = rng.rand(1, C, 10, 10).astype(np.float32)
        roi = np.array([1.0, 2.0, 7.0, 8.0], np.float32)
        trans = rng.uniform(-1, 1, (1, 2, 2, 2)).astype(np.float32)
        kw = dict(no_trans=False, spatial_scale=1.0,
                  pooled_height=PH, pooled_width=PW, part_size=(2, 2),
                  sample_per_part=3, trans_std=0.2,
                  position_sensitive=ps,
                  group_size=(2, 2) if ps else (1, 1))
        out = np.asarray(F.deformable_roi_pooling(
            x, roi.reshape(1, 4), trans, **kw))
        want = self._oracle(x, roi, trans, False, 1.0, PH, PW,
                            2 if ps else 1, 2 if ps else 1, 2, 2, 3, 0.2, ps)
        np.testing.assert_allclose(out[0], want, rtol=1e-4, atol=1e-5)

    def test_no_trans_matches_zero_offsets(self):
        rng = np.random.RandomState(4)
        x = rng.rand(1, 2, 8, 8).astype(np.float32)
        roi = np.array([[1, 1, 6, 6]], np.float32)
        a = np.asarray(F.deformable_roi_pooling(
            x, roi, None, no_trans=True, pooled_height=2, pooled_width=2,
            sample_per_part=2))
        b = np.asarray(F.deformable_roi_pooling(
            x, roi, np.zeros((1, 2, 2, 2), np.float32), no_trans=False,
            pooled_height=2, pooled_width=2, part_size=(2, 2),
            sample_per_part=2))
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestRoiPerspectiveTransform:
    def test_axis_aligned_quad_matches_bilinear(self):
        # an axis-aligned rectangle quad degenerates to plain resampling
        rng = np.random.RandomState(5)
        x = rng.rand(1, 1, 10, 10).astype(np.float32)
        quad = np.array([[2, 2, 7, 2, 7, 6, 2, 6]], np.float32)
        TH = TW = 4
        out, mask, mat = F.roi_perspective_transform(x, quad, TH, TW)
        out = np.asarray(out)
        mat = np.asarray(mat)[0]
        # verify against the oracle homography sampling
        for oh in range(TH):
            for ow in range(TW):
                u = mat[0] * ow + mat[1] * oh + mat[2]
                v = mat[3] * ow + mat[4] * oh + mat[5]
                w = mat[6] * ow + mat[7] * oh + mat[8]
                in_w, in_h = u / w, v / w
                want = _bilinear(x[0, 0], in_h, in_w)
                if np.asarray(mask)[0, 0, oh, ow]:
                    np.testing.assert_allclose(out[0, 0, oh, ow], want,
                                               rtol=1e-4, atol=1e-5)

    def test_corners_map_to_quad_corners(self):
        x = np.zeros((1, 1, 20, 20), np.float32)
        quad = np.array([[3, 2, 14, 4, 15, 11, 2, 12]], np.float32)
        TH = TW = 8
        _, _, mat = F.roi_perspective_transform(x, quad, TH, TW)
        m = np.asarray(mat)[0]

        def src(ow, oh):
            u = m[0] * ow + m[1] * oh + m[2]
            v = m[3] * ow + m[4] * oh + m[5]
            w = m[6] * ow + m[7] * oh + m[8]
            return u / w, v / w

        # (0,0) maps to the first corner exactly (matrix[2], matrix[5])
        np.testing.assert_allclose(src(0, 0), (3, 2), atol=1e-4)

    def test_outside_is_masked_zero(self):
        x = np.ones((1, 1, 10, 10), np.float32)
        # tiny quad in the corner: most of the output grid maps outside
        quad = np.array([[0, 0, 2, 0, 2, 2, 0, 2]], np.float32)
        out, mask, _ = F.roi_perspective_transform(x, quad, 8, 8)
        out, mask = np.asarray(out), np.asarray(mask)
        assert (out[mask[:, :1] == 0] == 0).all() if mask.size else True


class TestPolygonBoxTransform:
    def test_vs_oracle(self):
        rng = np.random.RandomState(6)
        x = rng.rand(2, 4, 3, 5).astype(np.float32)
        out = np.asarray(F.polygon_box_transform(x))
        N, G, H, W = x.shape
        want = np.empty_like(x)
        for n in range(N):
            for g in range(G):
                for h in range(H):
                    for w in range(W):
                        if g % 2 == 0:
                            want[n, g, h, w] = 4 * w - x[n, g, h, w]
                        else:
                            want[n, g, h, w] = 4 * h - x[n, g, h, w]
        np.testing.assert_allclose(out, want, rtol=1e-6)

    def test_odd_channels_rejected(self):
        with pytest.raises(Exception):
            F.polygon_box_transform(np.zeros((1, 3, 2, 2), np.float32))


def test_prroi_reference_param_order():
    # fluid surface is (input, rois, spatial_scale, pooled_h, pooled_w)
    x = np.ones((1, 1, 8, 8), np.float32)
    rois = np.array([[0, 0, 8, 8]], np.float32)
    out = F.prroi_pool(x, rois, 0.5, 2, 2)  # positional like 1.x callers
    assert out.shape == (1, 1, 2, 2)


def test_fluid_layers_resolve():
    from paddle_tpu.fluid import layers as fl

    assert fl.prroi_pool is F.prroi_pool
    assert fl.deformable_roi_pooling is F.deformable_roi_pooling
    assert fl.roi_perspective_transform is F.roi_perspective_transform
    assert fl.polygon_box_transform is F.polygon_box_transform


class TestMultiBoxHead:
    @pytest.mark.parametrize("flip", [True, False])
    @pytest.mark.parametrize("mmaro", [False, True])
    def test_shapes_consistent(self, flip, mmaro):
        import paddle_tpu as paddle
        from paddle_tpu.vision.ops import MultiBoxHead

        paddle.seed(0)
        head = MultiBoxHead(
            in_channels=[6, 6, 6], base_size=300, num_classes=5,
            aspect_ratios=[[2.0], [2.0, 3.0], [1.0, 2.0]],
            min_ratio=20, max_ratio=90, flip=flip,
            min_max_aspect_ratios_order=mmaro)
        feats = [np.random.RandomState(i).rand(2, 6, s, s).astype(np.float32)
                 for i, s in enumerate((6, 4, 2))]
        img = np.zeros((2, 3, 300, 300), np.float32)
        locs, confs, boxes, vars_ = head(feats, img)
        assert locs.shape[0] == 2 and locs.shape[2] == 4
        assert confs.shape[2] == 5
        # the conv channel budget must agree with the generated priors
        assert locs.shape[1] == boxes.shape[0] == confs.shape[1] \
            == vars_.shape[0]

    def test_size_ladder_matches_reference_schedule(self):
        from paddle_tpu.vision.ops import MultiBoxHead

        head = MultiBoxHead(
            in_channels=[4, 4, 4, 4], base_size=200, num_classes=2,
            aspect_ratios=[[2.0]] * 4, min_ratio=20, max_ratio=80)
        ms = head._cfg["min_sizes"]
        # first rung is base*0.10, then base*ratio/100 in floor-steps
        np.testing.assert_allclose(ms[0], 20.0)
        np.testing.assert_allclose(ms[1], 40.0)

    def test_trains(self):
        import jax.numpy as jnp

        import paddle_tpu as paddle
        from paddle_tpu.nn.layer_base import functional_call
        from paddle_tpu.vision.ops import MultiBoxHead

        paddle.seed(1)
        head = MultiBoxHead(
            in_channels=[4], base_size=100, num_classes=3,
            aspect_ratios=[[2.0]], min_sizes=[[30.0]], max_sizes=[[60.0]])
        feat = jnp.asarray(
            np.random.RandomState(2).rand(1, 4, 4, 4).astype(np.float32))
        img = jnp.zeros((1, 3, 100, 100), jnp.float32)
        params = {k: v.value for k, v in head.named_parameters()}

        def loss(p):
            locs, confs, *_ = functional_call(head, p, [feat], img)
            return (locs ** 2).mean() + (confs ** 2).mean()

        g = jax.grad(loss)(params)
        assert all(np.isfinite(np.asarray(v)).all() for v in
                   jax.tree_util.tree_leaves(g))

"""paddle_tpu.resilience: fault injection, retry, circuit breaking,
crash-safe resume, and the preemption exit contract.

Chaos engineering needs deterministic chaos: every test here drives the
failure modes through seeded FaultPlans, injectable clocks/sleeps and
byte-level corruption, and asserts exact recovery behavior — no flaky
timing, no real devices harmed.
"""
import os
import signal
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.framework import serialization, trace_events
from paddle_tpu.framework.errors import (
    EnforceNotMet,
    InvalidArgumentError,
    TransientDeviceError,
    UnavailableError,
    is_transient,
    wrap_transient,
)
from paddle_tpu.incubate.checkpoint import AutoCheckpoint
from paddle_tpu.resilience import (
    PREEMPTION_EXIT_CODE,
    CircuitBreaker,
    FaultPlan,
    FaultRule,
    PreemptionHandler,
    RetryPolicy,
    fault_point,
)
from paddle_tpu.resilience import circuit as circuit_mod
from paddle_tpu.resilience import faults as faults_mod
from paddle_tpu.resilience import retry as retry_mod


def _model(seed=0):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    loss = nn.CrossEntropyLoss()
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2), loss=loss)
    return model


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(16, 4).astype(np.float32),
             rng.randint(0, 2, size=(16,)).astype(np.int32))
            for _ in range(n)]


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    faults_mod.remove()
    retry_mod.reset_stats()
    warm = retry_mod._warm
    retry_mod._warm = False
    yield
    faults_mod.remove()
    retry_mod._warm = warm


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------
class TestTransientTaxonomy:
    def test_typed_classification(self):
        assert is_transient(TransientDeviceError("x"))
        assert is_transient(UnavailableError("x"))
        assert not is_transient(InvalidArgumentError("x"))
        assert not is_transient(ValueError("x"))

    def test_runtime_message_patterns(self):
        assert is_transient(RuntimeError("RESOURCE_EXHAUSTED: hbm oom"))
        assert is_transient(OSError("Connection reset by peer"))
        assert not is_transient(RuntimeError("INVALID_ARGUMENT: bad shape"))

    def test_wrap_transient_chains_cause(self):
        src = RuntimeError("UNAVAILABLE: socket closed")
        wrapped = wrap_transient(src)
        assert isinstance(wrapped, TransientDeviceError)
        assert wrapped.__cause__ is src
        # already-typed and non-transient errors pass through untouched
        tde = TransientDeviceError("x")
        assert wrap_transient(tde) is tde
        fatal = ValueError("x")
        assert wrap_transient(fatal) is fatal


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------
class TestRetryPolicy:
    def test_retries_then_succeeds(self):
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise TransientDeviceError("hiccup")
            return "ok"

        pol = RetryPolicy(max_attempts=5, backoff_ms=10, name="t1",
                          sleep=sleeps.append)
        assert pol.call(flaky) == "ok"
        assert calls["n"] == 3
        assert len(sleeps) == 2
        s = retry_mod.stats("t1")
        assert s["attempts"] == 3 and s["retries"] == 2

    def test_fatal_error_propagates_on_attempt_one(self):
        calls = {"n": 0}

        def fatal():
            calls["n"] += 1
            raise InvalidArgumentError("config bug")

        pol = RetryPolicy(max_attempts=5, backoff_ms=1, name="t2",
                          sleep=lambda s: None)
        with pytest.raises(InvalidArgumentError):
            pol.call(fatal)
        assert calls["n"] == 1

    def test_gives_up_after_max_attempts(self):
        pol = RetryPolicy(max_attempts=3, backoff_ms=1, name="t3",
                          sleep=lambda s: None)
        with pytest.raises(TransientDeviceError):
            pol.call(lambda: (_ for _ in ()).throw(
                TransientDeviceError("always")))
        s = retry_mod.stats("t3")
        assert s["attempts"] == 3 and s["giveups"] == 1

    def test_backoff_schedule_is_seeded_deterministic(self):
        a = RetryPolicy(max_attempts=6, backoff_ms=100, seed=7)
        b = RetryPolicy(max_attempts=6, backoff_ms=100, seed=7)
        c = RetryPolicy(max_attempts=6, backoff_ms=100, seed=8)
        assert a.schedule() == b.schedule()
        assert a.schedule() != c.schedule()
        # exponential growth under the cap, jitter within +/-25%
        base = [0.1 * 2 ** i for i in range(5)]
        for got, want in zip(a.schedule(), base):
            assert want * 0.75 <= got <= want * 1.25

    def test_backoff_cap(self):
        pol = RetryPolicy(max_attempts=20, backoff_ms=100, jitter=0.0,
                          max_backoff_ms=400)
        assert max(pol.schedule()) <= 0.4 + 1e-9

    def test_deadline_abandons_retry(self):
        t = {"now": 0.0}
        pol = RetryPolicy(max_attempts=10, backoff_ms=500, jitter=0.0,
                          deadline_ms=800, name="t4",
                          sleep=lambda s: t.__setitem__("now", t["now"] + s),
                          clock=lambda: t["now"])
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            raise TransientDeviceError("x")

        with pytest.raises(TransientDeviceError):
            pol.call(flaky)
        # 0.5s + 1.0s backoffs: the second retry would cross the 0.8s
        # deadline, so exactly two attempts run
        assert calls["n"] == 2
        assert retry_mod.stats("t4")["deadline_giveups"] == 1

    def test_decorator_form(self):
        pol = RetryPolicy(max_attempts=2, backoff_ms=1, sleep=lambda s: None)
        calls = {"n": 0}

        @pol
        def once_flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise TransientDeviceError("x")
            return 42

        assert once_flaky() == 42

    def test_retry_on_tuple_of_types(self):
        pol = RetryPolicy(max_attempts=3, backoff_ms=1, retry_on=(KeyError,),
                          sleep=lambda s: None)
        calls = {"n": 0}

        def f():
            calls["n"] += 1
            if calls["n"] < 2:
                raise KeyError("x")
            return "ok"

        assert pol.call(f) == "ok"
        with pytest.raises(ValueError):
            pol.call(lambda: (_ for _ in ()).throw(ValueError("fatal")))


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------
class TestFaultInjection:
    def test_noop_without_plan(self):
        assert not faults_mod.active()
        fault_point("anything")  # must not raise, count, or allocate

    def test_nth_fires_exactly_once(self):
        plan = FaultPlan.parse("site=s,nth=3,error=TransientDeviceError")
        with plan:
            fault_point("s")
            fault_point("s")
            with pytest.raises(TransientDeviceError):
                fault_point("s")
            fault_point("s")  # past nth: silent
        assert plan.stats() == {"s": {"calls": 4, "fired": 1}}

    def test_every_with_times_cap(self):
        plan = FaultPlan.parse("site=s,every=2,times=2,error=OSError")
        fired = 0
        with plan:
            for _ in range(10):
                try:
                    fault_point("s")
                except OSError:
                    fired += 1
        assert fired == 2

    def test_probabilistic_pattern_is_seed_deterministic(self):
        def pattern(seed):
            plan = FaultPlan([FaultRule("s", p=0.5, seed=seed)])
            out = []
            with plan:
                for _ in range(20):
                    try:
                        fault_point("s")
                        out.append(0)
                    except EnforceNotMet:
                        out.append(1)
            return out

        assert pattern(3) == pattern(3)
        assert pattern(3) != pattern(4)

    def test_latency_rule_sleeps_instead_of_raising(self):
        plan = FaultPlan.parse("site=s,nth=1,latency_ms=30")
        with plan:
            t0 = time.monotonic()
            fault_point("s")  # must not raise
            assert time.monotonic() - t0 >= 0.025

    def test_parse_rejects_bad_specs(self):
        for bad in ("", "site=s", "site=s,nth=1,every=2",
                    "site=s,p=1.5", "nonsense", "site=s,nth=1,error=dict"):
            with pytest.raises(EnforceNotMet):
                FaultPlan.parse(bad)

    def test_plans_compose_multiple_sites(self):
        plan = FaultPlan.parse(
            "site=a,nth=1,error=OSError; site=b,nth=1,error=ValueError")
        with plan:
            with pytest.raises(OSError):
                fault_point("a")
            with pytest.raises(ValueError):
                fault_point("b")
            fault_point("c")  # no rule: untouched


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------
class TestCircuitBreaker:
    def _breaker(self, **kw):
        t = {"now": 0.0}
        kw.setdefault("failure_threshold", 0.5)
        kw.setdefault("window", 4)
        kw.setdefault("cooldown_ms", 1000)
        kw.setdefault("half_open_probes", 2)
        br = CircuitBreaker("test", clock=lambda: t["now"], **kw)
        return br, t

    def test_opens_only_on_full_window(self):
        br, _ = self._breaker()
        for _ in range(3):
            br.record_failure("k")  # 3 < window: never judged
        assert br.state("k") == circuit_mod.CLOSED
        br.record_failure("k")  # full window, 100% failure
        assert br.state("k") == circuit_mod.OPEN
        assert not br.allow("k")

    def test_below_threshold_stays_closed(self):
        br, _ = self._breaker()
        for ok in (True, True, True, False) * 3:
            (br.record_success if ok else br.record_failure)("k")
        assert br.state("k") == circuit_mod.CLOSED

    def test_half_open_probe_recovery(self):
        br, t = self._breaker()
        for _ in range(4):
            br.record_failure("k")
        assert not br.allow("k")
        t["now"] += 1.1  # cooldown elapsed
        assert br.allow("k")       # probe 1 admitted
        assert br.allow("k")       # probe 2 admitted
        assert not br.allow("k")   # probes exhausted: shed
        assert br.state("k") == circuit_mod.HALF_OPEN
        br.record_success("k")
        assert br.state("k") == circuit_mod.HALF_OPEN  # 1 of 2 probes
        br.record_success("k")
        assert br.state("k") == circuit_mod.CLOSED
        assert br.allow("k")

    def test_failed_probe_reopens(self):
        br, t = self._breaker(half_open_probes=1)
        for _ in range(4):
            br.record_failure("k")
        t["now"] += 1.1
        assert br.allow("k")
        br.record_failure("k")
        assert br.state("k") == circuit_mod.OPEN
        assert not br.allow("k")  # cooldown restarts from the re-open

    def test_keys_are_independent(self):
        br, _ = self._breaker()
        for _ in range(4):
            br.record_failure(0)
        assert not br.allow(0)
        assert br.allow(1)

    def test_stats_and_warm_flap_counter(self):
        br, t = self._breaker(half_open_probes=1)
        for _ in range(4):
            br.record_failure("k")
        retry_mod.mark_warm()
        t["now"] += 1.1
        br.allow("k")
        br.record_failure("k")  # re-open after warm: a flap
        s = br.stats()
        assert s["opens"] == 2 and s["opens_after_warm"] == 1
        assert s["open_keys"] == 1
        assert s["keys"]["k"]["state"] == circuit_mod.OPEN


# ---------------------------------------------------------------------------
# corruption fallback + crash-safe resume
# ---------------------------------------------------------------------------
class TestCorruptionFallback:
    def test_truncated_magic_file_raises_typed_error(self, tmp_path):
        p = str(tmp_path / "ck.pdparams")
        serialization.save({"w": np.ones(3, np.float32)}, p)
        with open(p, "rb") as f:
            blob = f.read()
        with open(p, "wb") as f:
            f.write(blob[: len(blob) // 2])
        with pytest.raises(InvalidArgumentError, match="corrupt"):
            serialization.load(p)

    def test_bitflip_detected_by_manifest(self, tmp_path):
        model = _model()
        acp = AutoCheckpoint(model, str(tmp_path), async_save=False)
        acp.save(epoch=0)
        d = acp.latest_dir()
        # flip one payload byte far from the pickle header: the file still
        # unpickles, only the digest catches it
        p = os.path.join(d, "m.pdparams")
        blob = bytearray(open(p, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(p, "wb").write(bytes(blob))
        with pytest.raises(EnforceNotMet):
            acp._load_verified(d)

    def test_resume_falls_back_and_quarantines(self, tmp_path):
        data = _batches(4)
        model = _model(seed=1)
        acp = AutoCheckpoint(model, str(tmp_path), keep_max=5,
                             async_save=False)
        for i, (x, y) in enumerate(data):
            model.train_batch([x], [y])
            acp.save(epoch=i)
        dirs = acp.committed_dirs()
        assert len(dirs) == 4
        good = acp._load_verified(dirs[1])  # second-newest, pre-corruption
        # corrupt the NEWEST checkpoint's params payload
        p = os.path.join(dirs[0], "m.pdparams")
        blob = bytearray(open(p, "rb").read())
        blob[-20] ^= 0x01
        open(p, "wb").write(bytes(blob))

        m2 = _model(seed=9)
        acp2 = AutoCheckpoint(m2, str(tmp_path))
        meta = acp2.resume()
        assert meta is not None
        # landed on the previous (healthy) checkpoint...
        assert meta["counter"] == good["meta"]["counter"]
        for k, v in good["params"].items():
            np.testing.assert_array_equal(
                np.asarray(m2.network.state_dict()[k]), v)
        # ...and the corrupt dir is quarantined, not deleted
        names = os.listdir(tmp_path)
        assert any(n.startswith("corrupt-") for n in names)
        assert os.path.basename(dirs[0]) not in names

    def test_all_corrupt_resumes_fresh(self, tmp_path):
        model = _model()
        acp = AutoCheckpoint(model, str(tmp_path), async_save=False)
        acp.save(epoch=0)
        p = os.path.join(acp.latest_dir(), "m.pdparams")
        open(p, "wb").write(b"garbage")
        m2 = _model(seed=3)
        acp2 = AutoCheckpoint(m2, str(tmp_path))
        assert acp2.resume() is None

    def test_meta_missing_file_detected(self, tmp_path):
        model = _model()
        acp = AutoCheckpoint(model, str(tmp_path), async_save=False)
        acp.save(epoch=0)
        d = acp.latest_dir()
        os.unlink(os.path.join(d, "m.pdopt"))
        with pytest.raises(EnforceNotMet):
            acp._load_verified(d)


class TestCheckpointWriterResilience:
    def test_transient_write_fault_is_retried(self, tmp_path):
        plan = FaultPlan.parse(
            "site=checkpoint.write,nth=1,error=TransientDeviceError")
        model = _model()
        acp = AutoCheckpoint(
            model, str(tmp_path), async_save=False,
            retry=RetryPolicy(max_attempts=3, backoff_ms=1,
                              name="ckpt-test", sleep=lambda s: None))
        with plan:
            acp.save(epoch=0)  # first write raises, retry lands it
        assert acp.latest_dir() is not None
        assert plan.stats()["checkpoint.write"]["fired"] == 1

    def test_worker_error_latched_and_later_saves_drain(self, tmp_path):
        # snapshot 1 fails fatally (retry can't help); snapshots 2 and 3
        # must still commit, and close() must raise the FIRST error
        plan = FaultPlan.parse(
            "site=checkpoint.write,nth=1,error=InvalidArgumentError")
        model = _model()
        acp = AutoCheckpoint(
            model, str(tmp_path),
            retry=RetryPolicy(max_attempts=2, backoff_ms=1,
                              name="ckpt-latch", sleep=lambda s: None))
        with plan:
            acp.save(epoch=0)
            acp.save(epoch=1)
            acp.save(epoch=2)
            with pytest.raises(InvalidArgumentError, match="injected"):
                acp.close()
        assert len(acp.committed_dirs()) == 2
        # the latch is cleared by close(); a fresh close is clean
        acp.close()

    def test_save_raises_latched_error_without_clearing(self, tmp_path):
        plan = FaultPlan.parse(
            "site=checkpoint.write,nth=1,error=InvalidArgumentError")
        model = _model()
        acp = AutoCheckpoint(
            model, str(tmp_path),
            retry=RetryPolicy(max_attempts=2, backoff_ms=1,
                              name="ckpt-latch2", sleep=lambda s: None))
        with plan:
            acp.save(epoch=0)
            deadline = time.monotonic() + 5
            while acp._worker_err is None and time.monotonic() < deadline:
                time.sleep(0.01)
            with pytest.raises(InvalidArgumentError):
                acp.save(epoch=1)
            with pytest.raises(InvalidArgumentError):  # still latched
                acp.close()


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------
class TestPreemption:
    def test_sigterm_saves_and_exits_75(self, tmp_path):
        model = _model()
        acp = AutoCheckpoint(model, str(tmp_path), async_save=False)
        acp.step(epoch=4)  # records last_epoch without saving
        codes = []
        h = PreemptionHandler(acp, _exit=codes.append)
        h.install()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 5
            while not codes and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            h.uninstall()
        assert codes == [PREEMPTION_EXIT_CODE]
        d = acp.latest_dir()
        assert d is not None
        meta = serialization.load(os.path.join(d, "meta.pdmeta"))
        assert meta["kind"] == "preempt" and meta["epoch"] == 4

    def test_failed_final_save_still_exits(self):
        class Broken:
            last_epoch = 0

            def final_save(self, epoch):
                raise OSError("disk gone")

        codes = []
        h = PreemptionHandler(Broken(), _exit=codes.append)
        h._on_sigterm(signal.SIGTERM, None)
        assert codes == [PREEMPTION_EXIT_CODE]

    def test_watch_preemption_exit_skips_restart_budget(self, tmp_path):
        from paddle_tpu.distributed.parallel import watch

        marker = tmp_path / "second_run"
        script = tmp_path / "trainer.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            marker = {str(marker)!r}
            if os.path.exists(marker):
                sys.exit(0)
            open(marker, "w").close()
            sys.exit({PREEMPTION_EXIT_CODE})
        """))
        # max_restarts=0: a crash exit would NOT be restarted, so rc == 0
        # proves the preemption exit bypassed the budget
        rc = watch([sys.executable, str(script)], max_restarts=0,
                   _sleep=0.05)
        assert rc == 0

    def test_watch_other_exit_codes_still_burn_budget(self, tmp_path):
        from paddle_tpu.distributed.parallel import watch

        script = tmp_path / "trainer.py"
        script.write_text("import sys; sys.exit(7)")
        rc = watch([sys.executable, str(script)], max_restarts=0)
        assert rc == 7


# ---------------------------------------------------------------------------
# serving integration: batcher deadline sweep, circuit, retry
# ---------------------------------------------------------------------------
class TestBatcherResilience:
    def test_deadline_sweep_without_traffic(self):
        from paddle_tpu.serving.batcher import MicroBatcher
        from paddle_tpu.framework.errors import ExecutionTimeoutError

        ran = []
        mb = MicroBatcher(lambda x: 0, lambda b, rs: ran.append(b) or
                          [r.inputs[0] for r in rs],
                          max_batch_size=8, max_queue_delay_ms=5000,
                          name="sweep-test")
        try:
            f = mb.submit([1], deadline_ms=50)
            t0 = time.monotonic()
            with pytest.raises(ExecutionTimeoutError):
                f.result(3)
            # with no sweep this would only fail after the 5s batch delay
            assert time.monotonic() - t0 < 1.0
            assert ran == []  # expired before wasting a device slot
        finally:
            mb.close(drain=False)

    def test_circuit_opens_sheds_and_recovers(self):
        from paddle_tpu.serving.batcher import MicroBatcher

        state = {"fail": True, "runs": 0}

        def runner(bucket, reqs):
            state["runs"] += 1
            if state["fail"]:
                raise RuntimeError("poisoned bucket")
            return [r.inputs[0] for r in reqs]

        br = CircuitBreaker("mb-test", failure_threshold=0.5, window=2,
                            cooldown_ms=80, half_open_probes=1)
        mb = MicroBatcher(lambda x: 0, runner, max_batch_size=1,
                          max_queue_delay_ms=1, breaker=br, name="cb-test")
        try:
            outcomes = []
            for i in range(5):
                try:
                    mb.submit([i]).result(2)
                    outcomes.append("ok")
                except UnavailableError:
                    outcomes.append("shed")
                except RuntimeError:
                    outcomes.append("err")
            assert outcomes[:2] == ["err", "err"]  # window fills
            assert set(outcomes[2:]) == {"shed"}   # then the circuit sheds
            runs_while_open = state["runs"]
            state["fail"] = False
            time.sleep(0.12)  # cooldown -> half-open probe next batch
            assert mb.submit([99]).result(2) == 99
            assert br.state(0) == circuit_mod.CLOSED
            assert state["runs"] == runs_while_open + 1
            assert mb._worker.is_alive()
            assert mb.metrics.snapshot()["circuit_shed"] >= 3
        finally:
            mb.close()

    def test_runner_retry_via_fault_plan(self):
        from paddle_tpu.serving.batcher import MicroBatcher

        plan = FaultPlan.parse(
            "site=serving.runner,nth=1,error=TransientDeviceError")
        mb = MicroBatcher(
            lambda x: 0, lambda b, rs: [r.inputs[0] for r in rs],
            max_batch_size=1, max_queue_delay_ms=1,
            retry=RetryPolicy(max_attempts=3, backoff_ms=1,
                              name="runner-test", sleep=lambda s: None),
            name="retry-test")
        try:
            with plan:
                assert mb.submit([7]).result(2) == 7
            assert plan.stats()["serving.runner"]["fired"] == 1
            assert retry_mod.stats("runner-test")["retries"] == 1
        finally:
            mb.close()


# ---------------------------------------------------------------------------
# executor integration
# ---------------------------------------------------------------------------
class TestExecutorRetry:
    def test_transient_dispatch_fault_is_retried(self):
        from paddle_tpu import fluid

        plan = FaultPlan.parse(
            "site=executor.dispatch,nth=1,error=TransientDeviceError")
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            out = fluid.layers.fc(x, 2)
        exe = fluid.Executor()
        exe.run(startup)
        with plan:
            res, = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                           fetch_list=[out])
        assert res.shape == (2, 2)
        assert plan.stats()["executor.dispatch"]["fired"] == 1
        assert retry_mod.stats(f"executor#{exe._idx}")["retries"] == 1
        assert exe.dispatches == 1  # the retried dispatch counts once


# ---------------------------------------------------------------------------
# observability: F801 + profiler section
# ---------------------------------------------------------------------------
class TestF801:
    def test_retry_storm_flagged_after_warm(self):
        from paddle_tpu.analysis import RetraceMonitor, render_text

        retry_mod.mark_warm()
        pol = RetryPolicy(max_attempts=2, backoff_ms=1, name="storm",
                          sleep=lambda s: None)
        with RetraceMonitor(budget=3) as mon:
            for _ in range(6):
                with pytest.raises(TransientDeviceError):
                    pol.call(lambda: (_ for _ in ()).throw(
                        TransientDeviceError("x")))
        diags = [d for d in mon.diagnostics() if d.rule == "F801"]
        assert len(diags) == 1
        assert "storm" in diags[0].message
        assert "F801" in render_text(diags)

    def test_circuit_flapping_flagged(self):
        from paddle_tpu.analysis import RetraceMonitor

        retry_mod.mark_warm()
        t = {"now": 0.0}
        br = CircuitBreaker("flappy", failure_threshold=0.5, window=1,
                            cooldown_ms=10, half_open_probes=1,
                            clock=lambda: t["now"])
        with RetraceMonitor(budget=8) as mon:
            br.record_failure("k")  # open 1
            for _ in range(3):      # three half-open probe failures
                t["now"] += 0.02
                assert br.allow("k")
                br.record_failure("k")
        diags = [d for d in mon.diagnostics() if d.rule == "F801"]
        assert len(diags) == 1
        assert "flappy" in diags[0].message

    def test_quiet_system_raises_nothing(self):
        from paddle_tpu.analysis import RetraceMonitor

        retry_mod.mark_warm()
        pol = RetryPolicy(max_attempts=3, backoff_ms=1, name="quiet",
                          sleep=lambda s: None)
        with RetraceMonitor(budget=8) as mon:
            pol.call(lambda: "fine")
        assert [d for d in mon.diagnostics() if d.rule == "F801"] == []

    def test_resilience_stats_accessor(self):
        from paddle_tpu.analysis import RetraceMonitor

        pol = RetryPolicy(max_attempts=2, backoff_ms=1, name="acc",
                          sleep=lambda s: None)
        with RetraceMonitor() as mon:
            with pytest.raises(TransientDeviceError):
                pol.call(lambda: (_ for _ in ()).throw(
                    TransientDeviceError("x")))
        assert mon.resilience_stats("retry:acc")["retries"] == 1


class TestProfilerSection:
    def test_faults_and_retries_section_renders(self):
        from paddle_tpu import profiler

        profiler.reset_profiler()
        pol = RetryPolicy(max_attempts=2, backoff_ms=1, name="prof-sec",
                          sleep=lambda s: None)
        with pytest.raises(TransientDeviceError):
            pol.call(lambda: (_ for _ in ()).throw(
                TransientDeviceError("x")))
        text = profiler.summary()
        assert "Faults & retries" in text
        assert "prof-sec" in text

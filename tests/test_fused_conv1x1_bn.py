"""Fused 1x1-conv + BN-stats Pallas kernel (ops/fused_conv1x1_bn.py).

Numerics vs the unfused XLA reference on the CPU interpreter-backed
pallas path; the performance question (does removing one pass over Y pay
on the bandwidth-bound 1x1 layers?) is answered on the real chip by
tools/resnet_epilogue_probe.py.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.fused_conv1x1_bn import (_bn_apply, bn_apply_relu,
                                             conv1x1_bn_relu,
                                             conv1x1_bn_stats)


def _ref_stats(x, w):
    y = x.astype(np.float32) @ w.astype(np.float32)
    return y, y.sum(0), (y * y).sum(0)


class TestConv1x1BnStats:
    @pytest.mark.parametrize("M,K,N", [(512, 256, 64), (1000, 64, 256),
                                       (256, 2048, 512), (77, 128, 100)])
    def test_matches_reference(self, M, K, N):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(M, K).astype(np.float32))
        w = jnp.asarray(rng.randn(K, N).astype(np.float32))
        y, s, q = conv1x1_bn_stats(x, w)
        ry, rs, rq = _ref_stats(np.asarray(x), np.asarray(w))
        # f32 accumulation-order differences grow with K (the dot and the
        # scratch accumulate in different orders than numpy)
        np.testing.assert_allclose(np.asarray(y), ry, rtol=1e-5,
                                   atol=1e-3 * np.sqrt(K / 64))
        np.testing.assert_allclose(np.asarray(s), rs, rtol=1e-5,
                                   atol=0.05 * np.sqrt(M * K / 1e4))
        np.testing.assert_allclose(np.asarray(q), rq, rtol=1e-5,
                                   atol=1.0 * M * K / 1e4)

    def test_bf16_inputs_f32_stats(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(384, 128), jnp.bfloat16)
        w = jnp.asarray(rng.randn(128, 256), jnp.bfloat16)
        y, s, q = conv1x1_bn_stats(x, w)
        assert y.dtype == jnp.bfloat16
        assert s.dtype == jnp.float32 and q.dtype == jnp.float32
        ry = np.asarray(x, np.float32) @ np.asarray(w, np.float32)
        np.testing.assert_allclose(np.asarray(y, np.float32), ry,
                                   rtol=2e-2, atol=2e-1)
        # stats accumulate the bf16-rounded MXU output in f32
        np.testing.assert_allclose(np.asarray(s),
                                   np.asarray(y, np.float32).sum(0),
                                   rtol=1e-3, atol=2.0)


class TestConv1x1BnRelu:
    def test_matches_unfused_train_bn(self):
        rng = np.random.RandomState(2)
        M, K, N = 512, 64, 128
        x = jnp.asarray(rng.randn(M, K).astype(np.float32))
        w = jnp.asarray(rng.randn(K, N).astype(np.float32))
        gamma = jnp.asarray(rng.rand(N).astype(np.float32) + 0.5)
        beta = jnp.asarray(rng.randn(N).astype(np.float32))
        res = jnp.asarray(rng.randn(M, N).astype(np.float32))
        rm = jnp.zeros((N,), jnp.float32)
        rv = jnp.ones((N,), jnp.float32)

        out, nrm, nrv = conv1x1_bn_relu(x, w, gamma, beta, residual=res,
                                        running_mean=rm, running_var=rv)

        y = np.asarray(x) @ np.asarray(w)
        mean, var = y.mean(0), y.var(0)
        want = (np.asarray(gamma) * (y - mean) / np.sqrt(var + 1e-5)
                + np.asarray(beta)) + np.asarray(res)
        want = np.maximum(want, 0.0)
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-4, atol=1e-4)
        unbiased = var * M / (M - 1)
        np.testing.assert_allclose(np.asarray(nrm), 0.1 * mean, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(nrv),
                                   0.9 * 1.0 + 0.1 * unbiased, rtol=1e-4)

    def test_padding_rows_do_not_skew_stats(self):
        # M=77 pads to a block multiple; padded zero rows must not enter
        # mean/var (they contribute zero to Σ and Σ² and M uses the true
        # row count)
        rng = np.random.RandomState(3)
        M, K, N = 77, 32, 48
        x = jnp.asarray(rng.randn(M, K).astype(np.float32))
        w = jnp.asarray(rng.randn(K, N).astype(np.float32))
        g = jnp.ones((N,), jnp.float32)
        b = jnp.zeros((N,), jnp.float32)
        out, _, _ = conv1x1_bn_relu(x, w, g, b)
        y = np.asarray(x) @ np.asarray(w)
        want = np.maximum((y - y.mean(0)) / np.sqrt(y.var(0) + 1e-5), 0.0)
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-4, atol=1e-4)


class TestBnApplyRelu:
    def test_all_candidates_match_unfused_tail(self):
        rng = np.random.RandomState(4)
        M, N = 200, 256
        y = jnp.asarray(rng.randn(M, N).astype(np.float32))
        scale = jnp.asarray(rng.rand(N).astype(np.float32) + 0.5)
        shift = jnp.asarray(rng.randn(N).astype(np.float32))
        res = jnp.asarray(rng.randn(M, N).astype(np.float32))
        want = np.maximum(np.asarray(y) * np.asarray(scale)
                          + np.asarray(shift) + np.asarray(res), 0.0)
        cands = _bn_apply.candidates(y, scale, shift, res)
        assert len(cands) >= 2
        for cfg in cands:
            out = bn_apply_relu(y, scale, shift, res, **cfg)
            np.testing.assert_allclose(np.asarray(out), want,
                                       rtol=1e-5, atol=1e-5)
        # no-residual leg
        out = bn_apply_relu(y, scale, shift)
        np.testing.assert_allclose(
            np.asarray(out),
            np.maximum(np.asarray(y) * np.asarray(scale)
                       + np.asarray(shift), 0.0),
            rtol=1e-5, atol=1e-5)

    def test_fused_epilogue_flag_is_value_preserving(self):
        rng = np.random.RandomState(5)
        M, K, N = 77, 32, 128  # ragged M exercises the padding path
        x = jnp.asarray(rng.randn(M, K).astype(np.float32))
        w = jnp.asarray(rng.randn(K, N).astype(np.float32))
        g = jnp.asarray(rng.rand(N).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(N).astype(np.float32))
        res = jnp.asarray(rng.randn(M, N).astype(np.float32))
        base, _, _ = conv1x1_bn_relu(x, w, g, b, residual=res)
        fused, _, _ = conv1x1_bn_relu(x, w, g, b, residual=res,
                                      fused_epilogue=True)
        np.testing.assert_allclose(np.asarray(base), np.asarray(fused),
                                   rtol=1e-5, atol=1e-5)

    def test_resnet_bottleneck_fused_tail_wiring(self):
        # the gate is TPU-only in production; forcing it open checks the
        # weight-layout/stat-update plumbing against the plain tail
        import paddle_tpu.nn as nn
        import paddle_tpu.ops.autotune as at
        from paddle_tpu.vision.models.resnet import BottleneckBlock

        blk = BottleneckBlock(
            256, 64, data_format="NHWC",
            norm_layer=lambda c: nn.BatchNorm2D(c, data_format="NHWC"))
        x = jnp.asarray(np.random.RandomState(6)
                        .randn(2, 8, 8, 256).astype(np.float32))
        assert blk._fused_tail(x, x) is None  # CPU: gate closed
        ref = blk(x)
        rm_ref = np.asarray(blk.bn3._mean.value)
        blk.bn3._mean.value = jnp.zeros_like(blk.bn3._mean.value)
        blk.bn3._variance.value = jnp.ones_like(blk.bn3._variance.value)
        orig = at.fused_epilogues_eligible
        at.fused_epilogues_eligible = lambda feature_dim=None: True
        try:
            fused = blk(x)
        finally:
            at.fused_epilogues_eligible = orig
        np.testing.assert_allclose(np.asarray(ref), np.asarray(fused),
                                   rtol=3e-5, atol=3e-5)
        # the fused tail updated bn3's running stats like the plain one
        np.testing.assert_allclose(np.asarray(blk.bn3._mean.value),
                                   rm_ref, rtol=1e-4, atol=1e-6)

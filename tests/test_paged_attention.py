"""Paged flash-decode kernel (ops/paged_attention.py).

Per-candidate numerical equivalence against the gather-then-attend
reference (the serving path's bit-identical CPU fallback) across float,
int8 and fp8-e4m3 pools, drop-page masking, ragged page counts and the
speculative ``1+k`` verify width — all on the CPU interpreter.  The
performance question lives on the real chip (bench.py gpt_generate).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.ops.paged_attention import (_paged_decode,
                                            paged_flash_decode,
                                            paged_flash_eligible)


def _ref_attend(q, k_pool, v_pool, tables, mask, k_scale=None, v_scale=None):
    """Gather-then-attend oracle: materialize each slot's logical cache
    from the pool (dequantizing in full, as the fallback path does), then
    plain masked softmax attention.  Fully-masked rows emit softmax over
    a uniform -1e30 row — garbage by construction — so callers compare
    valid rows only."""
    B, H, T, hd = q.shape
    page = k_pool.shape[2]
    tab = np.maximum(np.asarray(tables), 0)
    k = np.asarray(k_pool, np.float32)[tab]  # [B, G, H, page, hd]
    v = np.asarray(v_pool, np.float32)[tab]
    if k_scale is not None:
        k = k * np.asarray(k_scale, np.float32)[tab][..., None]
        v = v * np.asarray(v_scale, np.float32)[tab][..., None]
    B_, G = tab.shape
    k = k.transpose(0, 2, 1, 3, 4).reshape(B, H, G * page, hd)
    v = v.transpose(0, 2, 1, 3, 4).reshape(B, H, G * page, hd)
    s = np.einsum("bhtd,bhcd->bhtc", np.asarray(q, np.float32),
                  k) / np.sqrt(hd)
    s = np.where(np.asarray(mask)[:, None], s, -1e30)
    m = s.max(-1, keepdims=True)
    p = np.exp(s - m)
    return np.einsum("bhtc,bhcd->bhtd",
                     p / np.maximum(p.sum(-1, keepdims=True), 1e-30), v)


def _geometry(rng, B=3, H=4, hd=16, page=16, G=4, T=1, dtype=np.float32):
    """A ragged paged layout: slot b holds ``lengths[b]`` tokens across
    its first ceil(len/page) table entries; the rest are unmapped (-1)."""
    P = B * G  # enough physical pages for a 1:1 mapping + 1 drop page
    k_pool = rng.randn(P + 1, H, page, hd).astype(dtype)
    v_pool = rng.randn(P + 1, H, page, hd).astype(dtype)
    lengths = [G * page - 1 - 3 * b for b in range(B)]  # ragged, >= T
    tables = np.full((B, G), -1, np.int32)
    nxt = 0
    for b in range(B):
        for g in range(-(-lengths[b] // page)):
            tables[b, g] = nxt
            nxt += 1
    q = rng.randn(B, H, T, hd).astype(np.float32)
    kp = np.arange(G * page)
    mask = np.zeros((B, T, G * page), bool)
    for b in range(B):
        mapped = np.repeat(tables[b] >= 0, page)
        for t in range(T):
            mask[b, t] = mapped & (kp <= lengths[b] - T + t)
    return q, k_pool, v_pool, tables, mask


def _quantize(pool, dtype):
    """Per-(page entry, head) abs-max quantization, the serving layout:
    scale [P+1, H, page] f32 applied over hd."""
    amax = np.abs(pool).max(-1)
    if dtype == "int8":
        scale = amax / 127.0
        qp = np.clip(np.round(pool / np.maximum(scale, 1e-30)[..., None]),
                     -127, 127).astype(np.int8)
        qp = jnp.asarray(qp)
    else:  # fp8-e4m3
        scale = amax / 448.0
        qp = jnp.asarray(pool / np.maximum(scale, 1e-30)[..., None]
                         ).astype(jnp.float8_e4m3fn)
    return qp, jnp.asarray(scale.astype(np.float32))


def _clipped(tables):
    return jnp.maximum(jnp.asarray(tables), 0)


class TestEquivalence:
    def test_float_all_candidates(self):
        rng = np.random.RandomState(0)
        q, kp, vp, tab, mask = _geometry(rng)
        cands = _paged_decode.candidates(q, kp, vp, tab, mask, None, None)
        assert len(cands) >= 2  # H=4 -> at least block_h 1, 2, 4
        want = _ref_attend(q, kp, vp, tab, mask)
        for cfg in cands:
            out = paged_flash_decode(jnp.asarray(q), jnp.asarray(kp),
                                     jnp.asarray(vp), _clipped(tab),
                                     jnp.asarray(mask), **cfg)
            np.testing.assert_allclose(np.asarray(out), want,
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("qdtype", ["int8", "fp8"])
    def test_quantized_all_candidates(self, qdtype):
        rng = np.random.RandomState(1)
        q, kp, vp, tab, mask = _geometry(rng)
        kq, ks = _quantize(kp, qdtype)
        vq, vs = _quantize(vp, qdtype)
        # the oracle attends over the SAME dequantized values, so the
        # comparison isolates the kernel, not the quantizer
        want = _ref_attend(q, kq, vq, tab, mask, ks, vs)
        cands = _paged_decode.candidates(q, kq, vq, tab, mask, ks, vs)
        for cfg in cands:
            out = paged_flash_decode(jnp.asarray(q), kq, vq, _clipped(tab),
                                     jnp.asarray(mask), ks, vs, **cfg)
            np.testing.assert_allclose(np.asarray(out), want,
                                       rtol=2e-4, atol=2e-4)

    def test_speculative_verify_width(self):
        # T = 1+k (k=4) pads to the sublane tile inside the kernel; all
        # T rows are valid queries at staggered causal positions
        rng = np.random.RandomState(2)
        q, kp, vp, tab, mask = _geometry(rng, T=5)
        assert mask.all(-1).sum() == 0  # staggered causality is live
        want = _ref_attend(q, kp, vp, tab, mask)
        out = paged_flash_decode(jnp.asarray(q), jnp.asarray(kp),
                                 jnp.asarray(vp), _clipped(tab),
                                 jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=2e-4, atol=2e-5)

    def test_drop_page_and_unmapped_pages_never_contribute(self):
        rng = np.random.RandomState(3)
        q, kp, vp, tab, mask = _geometry(rng)
        out0 = paged_flash_decode(jnp.asarray(q), jnp.asarray(kp),
                                  jnp.asarray(vp), _clipped(tab),
                                  jnp.asarray(mask))
        # poison the write-drop page (last) AND every unmapped page: the
        # mask (not the data) must be what excludes them
        kp2, vp2 = kp.copy(), vp.copy()
        kp2[-1] = vp2[-1] = 1e4
        used = set(tab[tab >= 0].ravel())
        for p in range(kp.shape[0] - 1):
            if p not in used:
                kp2[p] = vp2[p] = -1e4
        out1 = paged_flash_decode(jnp.asarray(q), jnp.asarray(kp2),
                                  jnp.asarray(vp2), _clipped(tab),
                                  jnp.asarray(mask))
        np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))

    def test_fully_masked_row_emits_zeros(self):
        rng = np.random.RandomState(4)
        q, kp, vp, tab, mask = _geometry(rng, T=2)
        mask[1, 0, :] = False  # e.g. a slot mid-admission: no valid kv yet
        out = paged_flash_decode(jnp.asarray(q), jnp.asarray(kp),
                                 jnp.asarray(vp), _clipped(tab),
                                 jnp.asarray(mask))
        np.testing.assert_array_equal(np.asarray(out)[1, :, 0], 0.0)
        want = _ref_attend(q, kp, vp, tab, mask)
        vb, vt = np.nonzero(np.asarray(mask).any(-1))  # valid rows only
        np.testing.assert_allclose(np.asarray(out)[vb, :, vt],
                                   want[vb, :, vt], rtol=2e-4, atol=2e-5)

    def test_bf16_query_pool(self):
        rng = np.random.RandomState(5)
        q, kp, vp, tab, mask = _geometry(rng)
        qb = jnp.asarray(q, jnp.bfloat16)
        kb = jnp.asarray(kp, jnp.bfloat16)
        vb = jnp.asarray(vp, jnp.bfloat16)
        out = paged_flash_decode(qb, kb, vb, _clipped(tab),
                                 jnp.asarray(mask))
        assert out.dtype == jnp.bfloat16
        want = _ref_attend(np.asarray(qb, np.float32),
                           np.asarray(kb, np.float32),
                           np.asarray(vb, np.float32), tab, mask)
        np.testing.assert_allclose(np.asarray(out, np.float32), want,
                                   rtol=2e-2, atol=2e-2)

    def test_scale_pair_enforced(self):
        rng = np.random.RandomState(6)
        q, kp, vp, tab, mask = _geometry(rng)
        kq, ks = _quantize(kp, "int8")
        with pytest.raises(InvalidArgumentError):
            paged_flash_decode(jnp.asarray(q), kq, kq, _clipped(tab),
                               jnp.asarray(mask), k_scale=ks)


class TestEligibility:
    def test_cpu_backend_falls_back(self):
        # the gather path is the CPU reference; interpret-mode pallas
        # must never be the production dispatch
        assert jax.default_backend() != "tpu"
        assert not paged_flash_eligible(head_dim=64, page_size=16)

    def test_tpu_override_would_dispatch(self):
        assert paged_flash_eligible(head_dim=64, page_size=16,
                                    backend="tpu")

    def test_alignment_and_flag_gate(self):
        assert not paged_flash_eligible(head_dim=12, backend="tpu")
        assert not paged_flash_eligible(page_size=12, backend="tpu")
        set_flags({"paged_flash": False})
        try:
            assert not paged_flash_eligible(head_dim=64, backend="tpu")
        finally:
            set_flags({"paged_flash": True})

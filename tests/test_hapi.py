"""hapi Model + metrics tests — the 'ONE model' E2E milestone (SURVEY §7
stage 2): a synthetic-MNIST MLP trains to high accuracy through
Model.prepare/fit/evaluate/predict with checkpointing, mirroring the
reference's book/test_recognize_digits.py convergence gates."""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import io as pio
from paddle_tpu import metric as pmetric
from paddle_tpu import nn
from paddle_tpu import optimizer as popt


# -- metrics -----------------------------------------------------------------
class TestMetrics:
    def test_accuracy_top1(self):
        m = pmetric.Accuracy()
        pred = np.asarray([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
        label = np.asarray([1, 0, 0])
        m.update(m.compute(pred, label))
        np.testing.assert_allclose(m.accumulate(), 2 / 3)
        m.reset()
        assert m.accumulate() == 0.0

    def test_accuracy_topk(self):
        m = pmetric.Accuracy(topk=(1, 2))
        pred = np.asarray([[0.5, 0.3, 0.2], [0.1, 0.5, 0.4]])
        label = np.asarray([1, 2])
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert top1 == 0.0 and top2 == 1.0
        assert m.name() == ["acc_top1", "acc_top2"]

    def test_precision_recall(self):
        p, r = pmetric.Precision(), pmetric.Recall()
        preds = np.asarray([0.9, 0.8, 0.2, 0.7])
        labels = np.asarray([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        np.testing.assert_allclose(p.accumulate(), 2 / 3)
        np.testing.assert_allclose(r.accumulate(), 2 / 3)

    def test_auc_perfect_and_random(self, rng):
        m = pmetric.Auc()
        scores = np.concatenate([rng.uniform(0.6, 1.0, 500), rng.uniform(0.0, 0.4, 500)])
        labels = np.concatenate([np.ones(500), np.zeros(500)])
        m.update(scores, labels)
        assert m.accumulate() > 0.99
        m.reset()
        m.update(rng.uniform(size=2000), (rng.uniform(size=2000) > 0.5).astype(int))
        assert 0.45 < m.accumulate() < 0.55


import collections

Pair = collections.namedtuple("Pair", ["x", "y"])  # module scope: picklable


# -- model -------------------------------------------------------------------
def synthetic_mnist(rng, n=512, d=64, classes=10):
    """Linearly separable synthetic 'digits': class = argmax(Wx)."""
    W = rng.randn(d, classes).astype(np.float32)
    X = rng.randn(n, d).astype(np.float32)
    y = np.argmax(X @ W, axis=1).astype(np.int64)
    return X, y


class MLP(nn.Layer):
    def __init__(self, d=64, classes=10):
        super().__init__()
        self.fc1 = nn.Linear(d, 128)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(128, classes)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


class TestModel:
    def _fit(self, rng, epochs=25, **fit_kw):
        X, y = synthetic_mnist(rng)
        ds = pio.TensorDataset([X, y.reshape(-1, 1)])
        net = MLP()
        model = paddle.Model(net)
        model.prepare(
            optimizer=popt.Adam(learning_rate=5e-3),
            loss=nn.CrossEntropyLoss(),
            metrics=[pmetric.Accuracy()],
        )
        model.fit(ds, batch_size=64, epochs=epochs, verbose=0, **fit_kw)
        return model, (X, y)

    def test_mnist_mlp_converges(self, rng):
        model, (X, y) = self._fit(rng)
        logs = model.evaluate(pio.TensorDataset([X, y.reshape(-1, 1)]),
                              batch_size=64, verbose=0)
        assert logs["acc"] > 0.9, logs

    def test_predict_shapes_and_stack(self, rng):
        model, (X, y) = self._fit(rng, epochs=1)
        outs = model.predict(pio.TensorDataset([X[:10]]), batch_size=4)
        assert len(outs) == 3
        stacked = model.predict(pio.TensorDataset([X[:10]]), batch_size=4,
                                stack_outputs=True)
        assert np.asarray(stacked).shape == (10, 10)

    def test_train_batch_api(self, rng):
        X, y = synthetic_mnist(rng, n=64)
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.CrossEntropyLoss())
        l1, _ = model.train_batch([X], [y.reshape(-1, 1)])
        for _ in range(20):
            l2, _ = model.train_batch([X], [y.reshape(-1, 1)])
        assert l2 < l1

    def test_eval_batch_no_param_update(self, rng):
        X, y = synthetic_mnist(rng, n=32)
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.CrossEntropyLoss())
        before = [p.numpy().copy() for p in model.parameters()]
        model.eval_batch([X], [y.reshape(-1, 1)])
        for b, p in zip(before, model.parameters()):
            np.testing.assert_allclose(b, p.numpy())

    def test_save_load_roundtrip(self, rng, tmp_path):
        model, (X, y) = self._fit(rng, epochs=2)
        path = str(tmp_path / "ckpt" / "model")
        model.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")

        model2 = paddle.Model(MLP())
        model2.prepare(optimizer=popt.Adam(learning_rate=1e-3),
                       loss=nn.CrossEntropyLoss(), metrics=[pmetric.Accuracy()])
        model2.load(path)
        p1 = model.predict_batch([X[:4]])
        p2 = model2.predict_batch([X[:4]])
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2), rtol=1e-5)

    def test_load_mismatch_raises(self, rng, tmp_path):
        model, _ = self._fit(rng, epochs=1)
        path = str(tmp_path / "m")
        model.save(path)
        other = paddle.Model(nn.Linear(3, 2))
        with pytest.raises(Exception):
            other.load(path)

    def test_batchnorm_buffers_update_in_fit(self, rng):
        class BNNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)
                self.bn = nn.BatchNorm1D(8)
                self.out = nn.Linear(8, 2)

            def forward(self, x):
                return self.out(self.bn(self.fc(x)))

        X = rng.randn(64, 8).astype(np.float32) * 3 + 1
        y = (rng.uniform(size=64) > 0.5).astype(np.int64).reshape(-1, 1)
        net = BNNet()
        model = paddle.Model(net)
        model.prepare(optimizer=popt.SGD(learning_rate=0.01),
                      loss=nn.CrossEntropyLoss())
        before = {n: b.numpy().copy() for n, b in net.named_buffers()}
        model.fit(pio.TensorDataset([X, y]), batch_size=32, epochs=1, verbose=0)
        after = {n: b.numpy() for n, b in net.named_buffers()}
        moved = any(not np.allclose(before[n], after[n]) for n in before)
        assert moved, "BN running stats must update during training"

    def test_summary_counts(self, rng, capsys):
        model = paddle.Model(MLP(d=8, classes=2))
        info = model.summary()
        # fc1: 8*128+128, fc2: 128*2+2
        assert info["total_params"] == 8 * 128 + 128 + 128 * 2 + 2

    def test_callbacks_early_stopping(self, rng):
        X, y = synthetic_mnist(rng, n=128)
        ds = pio.TensorDataset([X, y.reshape(-1, 1)])
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=0.0),  # never improves
                      loss=nn.CrossEntropyLoss(), metrics=[pmetric.Accuracy()])
        es = paddle.callbacks.EarlyStopping(monitor="loss", patience=1,
                                            save_best_model=False, verbose=0)
        model.fit(ds, eval_data=ds, batch_size=64, epochs=10, verbose=0,
                  callbacks=[es])
        assert model.stop_training

    def test_lr_scheduler_steps_during_fit(self, rng):
        X, y = synthetic_mnist(rng, n=64)
        sched = popt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=sched),
                      loss=nn.CrossEntropyLoss())
        model.fit(pio.TensorDataset([X, y.reshape(-1, 1)]), batch_size=32,
                  epochs=1, verbose=0)
        assert sched.last_epoch >= 2  # stepped once per batch

    def test_model_checkpoint_callback(self, rng, tmp_path):
        X, y = synthetic_mnist(rng, n=64)
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=0.01),
                      loss=nn.CrossEntropyLoss())
        model.fit(pio.TensorDataset([X, y.reshape(-1, 1)]), batch_size=32,
                  epochs=2, verbose=0, save_dir=str(tmp_path))
        assert os.path.exists(str(tmp_path / "final.pdparams"))
        assert os.path.exists(str(tmp_path / "1.pdparams"))

    def test_dropout_rng_varies_across_steps(self, rng):
        class DropNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(16, 16)
                self.drop = nn.Dropout(0.5)

            def forward(self, x):
                return self.drop(self.fc(x))

        X = np.ones((4, 16), np.float32)
        y = np.zeros((4, 16), np.float32)
        model = paddle.Model(DropNet())
        model.prepare(optimizer=popt.SGD(learning_rate=0.0), loss=nn.MSELoss())
        paddle.seed(0)
        l1, _ = model.train_batch([X], [y])
        l2, _ = model.train_batch([X], [y])
        # same params (lr=0) but different dropout masks → different losses
        assert l1 != l2


class TestReviewRegressions:
    def test_seeded_shuffle_reproducible(self):
        paddle.seed(123)
        a = list(pio.RandomSampler(list(range(20))))
        paddle.seed(123)
        b = list(pio.RandomSampler(list(range(20))))
        assert a == b

    def test_save_load_restores_scheduler(self, rng, tmp_path):
        X, y = synthetic_mnist(rng, n=64)
        sched = popt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=sched),
                      loss=nn.CrossEntropyLoss())
        model.fit(pio.TensorDataset([X, y.reshape(-1, 1)]), batch_size=32,
                  epochs=1, verbose=0)
        lr_after = sched()
        assert lr_after < 0.1
        path = str(tmp_path / "m")
        model.save(path)

        sched2 = popt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        model2 = paddle.Model(MLP())
        model2.prepare(optimizer=popt.SGD(learning_rate=sched2),
                       loss=nn.CrossEntropyLoss())
        model2.load(path)
        assert sched2() == lr_after

    def test_fit_oneshot_iterator_multi_epoch_raises(self, rng):
        X, y = synthetic_mnist(rng, n=8)
        gen = iter([(X, y.reshape(-1, 1))])
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.CrossEntropyLoss())
        with pytest.raises(Exception, match="one-shot"):
            model.fit(gen, epochs=2, verbose=0)
        model.fit(iter([(X, y.reshape(-1, 1))]), epochs=1, verbose=0)  # ok

    def test_save_namedtuple(self, tmp_path):
        p = str(tmp_path / "nt")
        paddle.save({"cfg": Pair(x=jnp.ones(3), y=2)}, p)
        out = paddle.load(p)
        np.testing.assert_allclose(out["cfg"].x, 1.0)
        assert out["cfg"].y == 2

    def test_exhausted_loader_raises_not_hangs(self):
        dl = pio.DataLoader(pio.TensorDataset([np.zeros((4, 2), np.float32)]),
                            batch_size=2)
        it = iter(dl)
        list(it)
        for _ in range(3):
            with pytest.raises(StopIteration):
                next(it)

    def test_sampler_plus_shuffle_rejected(self):
        ds = pio.TensorDataset([np.zeros((4, 2), np.float32)])
        with pytest.raises(Exception, match="shuffle"):
            pio.DataLoader(ds, batch_size=2, shuffle=True,
                           sampler=pio.SequenceSampler(ds))


class TestMultiOutputMetricLogs:
    def test_topk_accuracy_batch_logs_both_names(self):
        """ADVICE r1: per-batch logs must pair flattened metric names with
        flattened results (Accuracy(topk=(1,2)) logs both, not a list
        under the first name)."""
        import paddle_tpu as paddle
        from paddle_tpu import metric as pmetric
        from paddle_tpu.hapi.callbacks import Callback

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 4))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.CrossEntropyLoss(),
                      metrics=[pmetric.Accuracy(topk=(1, 2))])

        seen = {}

        class Capture(Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.update(logs or {})

        rng = np.random.RandomState(0)
        X = rng.randn(16, 4).astype(np.float32)
        Y = rng.randint(0, 4, size=(16, 1)).astype(np.int32)
        from paddle_tpu.io import TensorDataset

        model.fit(TensorDataset([X, Y]), batch_size=8, epochs=1, verbose=0,
                  callbacks=[Capture()])
        assert "acc_top1" in seen and "acc_top2" in seen
        import numbers

        assert isinstance(seen["acc_top1"], numbers.Number)
        assert isinstance(seen["acc_top2"], numbers.Number)


class TestStepsPerExecution:
    """Keras-style steps_per_execution: k train steps per dispatch
    (lax.scan) — the host-RTT amortization that matters on TPU."""

    def _data(self, n=24, seed=0):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, 6).astype(np.float32)
        w = rng.randn(6, 1).astype(np.float32)
        return x, (x @ w + 0.1).astype(np.float32)

    def _model(self, spe):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 1))
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=popt.SGD(learning_rate=0.05), loss=nn.MSELoss(),
                  steps_per_execution=spe)
        return m

    def test_trajectory_matches_single_step(self):
        x, y = self._data()
        batches = [(x[i:i + 8], y[i:i + 8]) for i in range(0, 24, 8)]

        m1 = self._model(1)
        for bx, by in batches:
            m1.train_batch([bx], [by])

        m3 = self._model(3)
        losses = np.asarray(m3._train_batches_device(
            [(bx, by) for bx, by in batches]))
        assert losses.shape == (3,)
        p1 = {k: np.asarray(v.value)
              for k, v in m1.network.named_parameters()}
        p3 = {k: np.asarray(v.value)
              for k, v in m3.network.named_parameters()}
        for k in p1:
            np.testing.assert_allclose(p3[k], p1[k], rtol=1e-5, atol=1e-6,
                                       err_msg=k)

    def test_fit_with_ragged_tail(self):
        x, y = self._data(n=56)  # 7 batches of 8: 2 full groups + 1 single
        m = self._model(3)
        before = float(np.mean((np.asarray(m.predict_batch([x])) - y) ** 2))
        m.fit(paddle.io.TensorDataset([x, y]), batch_size=8, epochs=3,
              verbose=0)
        after = float(np.mean((np.asarray(m.predict_batch([x])) - y) ** 2))
        assert after < before * 0.8, (before, after)

    def test_partial_batch_inside_group(self):
        # 44 samples / batch 8 → 8,8,8,8,8,4: the 4-sample batch must NOT
        # be stacked into a full group (jnp.stack shape mismatch)
        x, y = self._data(n=44)
        m = self._model(3)
        m.fit(paddle.io.TensorDataset([x, y]), batch_size=8, epochs=2,
              verbose=0)
        pred = np.asarray(m.predict_batch([x]))
        assert np.isfinite(pred).all()

    def test_validation(self):
        net = nn.Linear(4, 1)
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(Exception, match="steps_per_execution"):
            m.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.MSELoss(), steps_per_execution=0)
        with pytest.raises(Exception, match="metrics"):
            m.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.MSELoss(),
                      metrics=[paddle.metric.Accuracy()],
                      steps_per_execution=2)

"""Deformable convolution v1/v2 vs a numpy loop oracle transcribing
modulated_deformable_im2col (operators/deformable_conv_op)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.framework.errors import InvalidArgumentError


def _bilinear_np(img, y, x):
    """Per-corner zero-padded bilinear (dmcn_im2col_bilinear)."""
    C, H, W = img.shape
    y0, x0 = int(np.floor(y)), int(np.floor(x))
    ly, lx = y - y0, x - x0
    out = np.zeros(C)
    for dy, dx, w in ((0, 0, (1 - ly) * (1 - lx)), (0, 1, (1 - ly) * lx),
                      (1, 0, ly * (1 - lx)), (1, 1, ly * lx)):
        yc, xc = y0 + dy, x0 + dx
        if 0 <= yc < H and 0 <= xc < W:
            out += img[:, yc, xc] * w
    return out


def _deform_np(x, offset, weight, stride, padding, dilation, dg, mask):
    N, Cin, H, W = x.shape
    Cout, _, kh, kw = weight.shape
    K = kh * kw
    Ho, Wo = offset.shape[2], offset.shape[3]
    off = offset.reshape(N, dg, K, 2, Ho, Wo)
    rep = Cin // dg
    out = np.zeros((N, Cout, Ho, Wo))
    for n in range(N):
        for ho in range(Ho):
            for wo in range(Wo):
                cols = np.zeros((Cin, K))
                for k in range(K):
                    i, j = divmod(k, kw)
                    for g in range(dg):
                        y = (ho * stride - padding + i * dilation
                             + off[n, g, k, 0, ho, wo])
                        xx = (wo * stride - padding + j * dilation
                              + off[n, g, k, 1, ho, wo])
                        v = _bilinear_np(x[n, g * rep:(g + 1) * rep], y, xx)
                        if mask is not None:
                            v = v * mask.reshape(
                                N, dg, K, Ho, Wo)[n, g, k, ho, wo]
                        cols[g * rep:(g + 1) * rep, k] = v
                out[n, :, ho, wo] = np.einsum(
                    "ck,ock->o", cols, weight.reshape(Cout, Cin, K))
    return out


class TestDeformConv2d:
    def _inputs(self, N=1, Cin=4, H=6, W=6, Cout=3, k=3, dg=2,
                with_mask=True):
        rng = np.random.RandomState(0)
        x = rng.randn(N, Cin, H, W).astype(np.float32)
        Ho = Wo = H - k + 1  # stride 1, pad 0
        offset = (rng.randn(N, 2 * dg * k * k, Ho, Wo) * 0.5).astype(
            np.float32)
        weight = rng.randn(Cout, Cin, k, k).astype(np.float32) * 0.2
        mask = (rng.uniform(0.2, 1.0, (N, dg * k * k, Ho, Wo)).astype(
            np.float32) if with_mask else None)
        return x, offset, weight, mask

    def test_v2_vs_oracle(self):
        x, offset, weight, mask = self._inputs()
        out = F.deform_conv2d(x, offset, weight, deformable_groups=2,
                              mask=mask)
        want = _deform_np(x, offset, weight, 1, 0, 1, 2, mask)
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)

    def test_v1_no_mask(self):
        x, offset, weight, _ = self._inputs(with_mask=False)
        out = F.deform_conv2d(x, offset, weight, deformable_groups=2)
        want = _deform_np(x, offset, weight, 1, 0, 1, 2, None)
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)

    def test_zero_offsets_match_plain_conv(self):
        """Zero offsets and unit mask reduce DCN to a standard conv."""
        rng = np.random.RandomState(1)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        w = rng.randn(5, 3, 3, 3).astype(np.float32)
        Ho = Wo = 6
        offset = np.zeros((2, 2 * 9, Ho, Wo), np.float32)
        out = F.deform_conv2d(x, offset, w, deformable_groups=1)
        want = F.conv2d(jnp.asarray(x), jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-4)

    def test_stride_padding_dilation(self):
        rng = np.random.RandomState(2)
        x = rng.randn(1, 2, 9, 9).astype(np.float32)
        w = rng.randn(2, 2, 3, 3).astype(np.float32)
        stride, pad, dil = 2, 1, 2
        Ho = (9 + 2 * pad - dil * 2 - 1) // stride + 1
        offset = (rng.randn(1, 18, Ho, Ho) * 0.3).astype(np.float32)
        out = F.deform_conv2d(x, offset, w, stride=stride, padding=pad,
                              dilation=dil)
        want = _deform_np(x, offset, w, stride, pad, dil, 1, None)
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)

    def test_grads_flow_to_offsets(self):
        x, offset, weight, mask = self._inputs()
        g_off = jax.grad(lambda o: jnp.sum(F.deform_conv2d(
            x, o, weight, deformable_groups=2, mask=mask) ** 2))(
            jnp.asarray(offset))
        assert np.isfinite(np.asarray(g_off)).all()
        assert float(jnp.abs(g_off).sum()) > 0

    def test_groups_and_bias(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 4, 5, 5).astype(np.float32)
        w = rng.randn(6, 2, 3, 3).astype(np.float32)  # groups=2
        offset = np.zeros((1, 18, 3, 3), np.float32)
        bias = np.array([1.0, 0, 0, 0, 0, 0], np.float32)
        out = F.deform_conv2d(x, offset, w, bias=bias, groups=2)
        want = F.conv2d(jnp.asarray(x), jnp.asarray(w), groups=2)
        np.testing.assert_allclose(
            np.asarray(out)[:, 0], np.asarray(want)[:, 0] + 1.0, atol=1e-4)

    def test_shape_validation(self):
        x = np.zeros((1, 4, 5, 5), np.float32)
        w = np.zeros((2, 4, 3, 3), np.float32)
        with pytest.raises(InvalidArgumentError):
            F.deform_conv2d(x, np.zeros((1, 7, 3, 3), np.float32), w)
        # offset at the wrong spatial resolution must be rejected
        with pytest.raises(InvalidArgumentError) as ei:
            F.deform_conv2d(x, np.zeros((1, 18, 5, 5), np.float32), w)
        assert "output resolution" in str(ei.value)

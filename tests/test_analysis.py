"""paddle_tpu.analysis — one deliberately-broken fixture per rule, each
asserting its rule fires exactly once, plus the zero-false-positive sweep
over the bundled model zoo and a slow self-check that the analyzer stays
warning-clean on examples/.

Reference capability: the IrGraph/pass_builder checkers the reference runs
inside the C++ IR — here hoisted to build time, over the recorded Program.
"""
import glob
import os
import subprocess
import sys
import textwrap

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.analysis import (RetraceMonitor, check_plan, lint_source,
                                 render_json, render_text, verify_program)
from paddle_tpu.analysis.runner import main as analysis_main
from paddle_tpu.distributed.fleet import ShardingPlan
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.dy2static import Dy2StaticError
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.static.graph import (Op, Variable, record_call,
                                     reset_default_programs)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_programs():
    paddle.seed(0)
    reset_default_programs()
    yield
    reset_default_programs()


def _rule_count(diags, rule):
    return sum(1 for d in diags if d.rule == rule)


def _programs():
    return fluid.Program(), fluid.Program()


# -- program verifier (V1xx) --------------------------------------------------
class TestVerifyProgram:
    def test_clean_program_no_findings(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.data("y", [-1, 1])
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        assert verify_program(main, fetch_list=[loss]) == []

    def test_v101_tampered_declaration(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            z = fluid.layers.relu(x)
        main.vars[z.name].shape = (None, 99)  # tamper after recording
        diags = verify_program(main)
        assert _rule_count(diags, "V101") == 1
        assert "99" in [d for d in diags if d.rule == "V101"][0].message

    def test_v102_op_fails_inference(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.data("y", [-1, 5])
        main.append_op(Op(lambda a, b: jnp.matmul(a, b), (x, y), {},
                          ["z"], True))
        diags = verify_program(main)
        assert _rule_count(diags, "V102") == 1

    def test_v103_foreign_program_capture(self):
        prog_a, prog_b = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog_a):
            x = fluid.data("x", [-1, 4])
        with fluid.program_guard(prog_b):
            record_call(lambda t: t + 1.0, x, out_names=["y"])
        diags = verify_program(prog_b)
        assert _rule_count(diags, "V103") == 1
        assert "different" in [d for d in diags
                               if d.rule == "V103"][0].message

    def test_v103_never_produced(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            ghost = Variable(main, "ghost", (2, 2), "float32")  # not added
            record_call(lambda t: t * 2.0, ghost, out_names=["y"])
        diags = verify_program(main)
        assert _rule_count(diags, "V103") == 1
        assert "no op produces" in [d for d in diags
                                    if d.rule == "V103"][0].message

    def test_v104_duplicate_names(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            fluid.data("x", [-1, 4])
            fluid.data("x", [-1, 8])
        diags = verify_program(main)
        assert _rule_count(diags, "V104") == 1

    def test_v105_dead_op(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            h = fluid.layers.relu(x)
            record_call(lambda t: t * 3.0, x,
                        out_names=["dead"])  # never reaches the fetch
            loss = fluid.layers.mean(h)
        diags = verify_program(main, fetch_list=[loss])
        assert _rule_count(diags, "V105") == 1
        assert _rule_count(diags, "V106") == 0  # dead op, not dangling

    def test_v106_dangling_output(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            u, v = record_call(lambda t: (t + 1.0, t * 2.0), x,
                               out_names=["u", "v"])
            loss = fluid.layers.mean(u)
        diags = verify_program(main, fetch_list=[loss])
        assert _rule_count(diags, "V106") == 1
        assert "'v'" in [d for d in diags if d.rule == "V106"][0].message

    def test_v107_param_mutated(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            fluid.layers.fc(x, 2)
        pname = next(iter(main.scope))
        shape = tuple(main.scope[pname].shape)
        main.append_op(Op(lambda: jnp.zeros(shape, jnp.float32), (), {},
                          [pname], True))
        diags = verify_program(main)
        assert _rule_count(diags, "V107") == 1

    def test_v108_fully_unknown_feed(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, -1])
            fluid.layers.relu(x)
        diags = verify_program(main)
        assert _rule_count(diags, "V108") == 1

    def test_no_roots_skips_reachability(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            fluid.layers.relu(x)  # a sink, but every sink is fetchable
        diags = verify_program(main)  # no fetch_list, no bound loss
        assert _rule_count(diags, "V105") == 0
        assert _rule_count(diags, "V106") == 0


# -- dy2static linter (D2xx/D3xx) --------------------------------------------
class TestLintDy2static:
    def test_d201_generator(self):
        diags = lint_source("""
            def f(x):
                for i in range(3):
                    yield x + i
        """)
        assert _rule_count(diags, "D201") == 1

    def test_d202_global_in_block(self):
        diags = lint_source("""
            def f(x):
                if x > 0:
                    global COUNT
                    COUNT = 1
                return x
        """)
        assert _rule_count(diags, "D202") == 1

    def test_d203_return_in_tensor_branch(self):
        diags = lint_source("""
            def f(x):
                if x.sum() > 0:
                    return x
                return -x
        """)
        d203 = [d for d in diags if d.rule == "D203"]
        assert len(d203) == 1
        assert d203[0].location.line == 4  # the `return x` line

    def test_d204_break_in_tensor_loop(self):
        diags = lint_source("""
            def f(x):
                while x > 0:
                    x = x - 1
                    if x.sum() < 3:
                        break
                return x
        """)
        assert _rule_count(diags, "D204") == 1

    def test_d301_host_sync_in_loop(self):
        diags = lint_source("""
            def f(x):
                s = 0.0
                for i in range(10):
                    s = s + float(x)
                return s
        """)
        assert _rule_count(diags, "D301") == 1

    def test_d302_print_traced_in_loop(self):
        diags = lint_source("""
            def f(x):
                for i in range(3):
                    print(x)
                return x
        """)
        assert _rule_count(diags, "D302") == 1

    def test_concrete_control_flow_is_clean(self):
        diags = lint_source("""
            def f(x, mode=None):
                if mode is None:
                    return x
                for i in range(len(x.shape)):
                    if x.shape[i] == 1:
                        continue
                n = 5
                while n > 0:
                    n -= 1
                    if n == 2:
                        break
                return x
        """)
        assert diags == []

    def test_executor_results_are_host_values(self):
        # regression: exe = fluid.Executor(); loss, = exe.run(...) must
        # NOT taint — Executor.run returns numpy (examples/ idiom)
        diags = lint_source("""
            def main():
                exe = fluid.Executor(fluid.CPUPlace())
                for step in range(20):
                    loss_v, = exe.run(prog, feed={}, fetch_list=[1])
                    print(f"loss {float(loss_v):.4f}")
        """)
        assert diags == []


# -- retrace hazard detector (R4xx) ------------------------------------------
class TestRetraceMonitor:
    def test_r401_jit_shape_churn(self):
        @paddle.jit.to_static
        def f(a):
            return a + 1.0

        with RetraceMonitor(budget=2) as mon:
            for n in range(1, 5):
                f(jnp.ones((n,), jnp.float32))
        diags = mon.diagnostics()
        r401 = [d for d in diags if d.rule == "R401"]
        assert len(r401) == 1
        assert "shape varies" in r401[0].message

    def test_r402_executor_feed_churn(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.layers.mean(fluid.layers.relu(x))
        exe = fluid.Executor()
        exe.run(startup)
        with RetraceMonitor(budget=2) as mon:
            for n in range(1, 5):
                exe.run(main, feed={"x": np.ones((n, 4), np.float32)},
                        fetch_list=[y])
        diags = mon.diagnostics()
        r402 = [d for d in diags if d.rule == "R402"]
        assert len(r402) == 1
        assert "shape varies" in r402[0].message

    def test_within_budget_is_silent(self):
        @paddle.jit.to_static
        def g(a):
            return a * 2.0

        with RetraceMonitor(budget=8) as mon:
            for _ in range(20):  # same signature every call
                g(jnp.ones((2,), jnp.float32))
        assert mon.distinct_signatures(
            "jit", g._orig.__qualname__ if hasattr(g, "_orig") else "g") <= 1
        assert mon.diagnostics() == []


# -- sharding plan checker (P5xx) --------------------------------------------
class _OneParam(nn.Layer):
    def __init__(self, shape, spec=None):
        super().__init__()
        self.w = self.create_parameter(list(shape))
        if spec is not None:
            self.w.partition_spec = spec


class TestCheckPlan:
    def test_p501_unknown_axis(self):
        mesh = build_mesh(dp=4, mp=2)
        plan = ShardingPlan(_OneParam((4, 4), spec=(None, "bogus")),
                            None, None, mesh=mesh)
        diags = check_plan(plan)
        assert _rule_count(diags, "P501") == 1

    def test_p502_not_divisible(self):
        mesh = build_mesh(dp=4, mp=2)
        plan = ShardingPlan(_OneParam((4, 3), spec=(None, "model")),
                            None, None, mesh=mesh)
        diags = check_plan(plan)
        assert _rule_count(diags, "P502") == 1

    def test_p503_axis_double_booked(self):
        mesh = build_mesh(dp=4, mp=2)
        plan = ShardingPlan(_OneParam((4, 4), spec=("model", "model")),
                            None, None, mesh=mesh)
        diags = check_plan(plan)
        assert _rule_count(diags, "P503") == 1

    def test_p504_rank_mismatch(self):
        mesh = build_mesh(dp=4, mp=2)
        plan = ShardingPlan(_OneParam((4,), spec=("model", None)),
                            None, None, mesh=mesh)
        diags = check_plan(plan)
        assert _rule_count(diags, "P504") == 1

    def test_p505_replicated_optimizer_state(self):
        mesh = build_mesh(dp=4, sharding=2)
        plan = ShardingPlan(_OneParam((3, 5)), popt.Momentum(),
                            None, mesh=mesh)
        diags = check_plan(plan)
        assert _rule_count(diags, "P505") == 1

    def test_valid_plan_is_clean(self):
        mesh = build_mesh(dp=4, mp=2)
        plan = ShardingPlan(_OneParam((4, 8), spec=(None, "model")),
                            None, None, mesh=mesh)
        assert check_plan(plan) == []

    def test_p506_expert_axis_on_non_expert_param(self):
        mesh = build_mesh(dp=4, ep=2)
        plan = ShardingPlan(_OneParam((4, 4), spec=("expert", None)),
                            None, None, mesh=mesh)
        diags = check_plan(plan)
        assert _rule_count(diags, "P506") == 1

    def test_p506_silent_on_expert_weights(self):
        class _Experts(nn.Layer):
            def __init__(self):
                super().__init__()
                self.expert_fc1 = self.create_parameter([2, 4, 4])
                self.expert_fc1.partition_spec = ("expert", None, None)

        mesh = build_mesh(dp=4, ep=2)
        assert check_plan(ShardingPlan(_Experts(), None, None,
                                       mesh=mesh)) == []


# -- diagnostics core ---------------------------------------------------------
class TestDiagnostics:
    def test_render_and_json(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            fluid.data("x", [-1, 4])
            fluid.data("x", [-1, 8])
        diags = verify_program(main)
        text = render_text(diags)
        assert "[V104]" in text and "error" in text
        import json
        parsed = json.loads(render_json(diags))
        assert parsed[0]["rule"] == "V104"
        assert parsed[0]["severity"] == "error"

    def test_exit_codes(self, tmp_path):
        bad = tmp_path / "bad_module.py"
        bad.write_text(textwrap.dedent("""
            from paddle_tpu.jit import to_static

            @to_static
            def f(x):
                if x.sum() > 0:
                    return x
                return -x
        """))
        # D203 is error severity → rc 1 even without --strict
        assert analysis_main(["--no-exec", str(bad)]) == 1
        ok = tmp_path / "ok_module.py"
        ok.write_text("def f(x):\n    return x + 1\n")
        assert analysis_main(["--no-exec", str(ok)]) == 0
        assert analysis_main(["--no-exec", "--all-functions",
                              str(ok)]) == 0


# -- satellite regressions ----------------------------------------------------
class TestVariableShapeValidation:
    def test_string_dim_raises(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            with pytest.raises(InvalidArgumentError, match="string"):
                fluid.data("x", ["batch", 4])

    def test_int_like_dims_normalize(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            v = fluid.data("x", [np.int64(3), -1])
        assert v.shape == (3, None)

    def test_non_int_dim_raises(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            with pytest.raises(InvalidArgumentError):
                fluid.data("x", [2.5, 4])


class TestDy2StaticErrorLocation:
    def test_location_attached(self):
        def culprit():
            raise Dy2StaticError("boom")

        with pytest.raises(Dy2StaticError) as ei:
            culprit()
        e = ei.value
        assert e.func_name == "culprit"
        assert e.filename and e.filename.endswith("test_analysis.py")
        assert isinstance(e.lineno, int)
        assert "[at " in str(e) and "culprit" in str(e)

    def test_explicit_location_wins(self):
        e = Dy2StaticError("bad", func_name="g", filename="m.py", lineno=7)
        assert (e.func_name, e.filename, e.lineno) == ("g", "m.py", 7)
        assert "m.py:7" in str(e)


# -- zero-false-positive sweeps ----------------------------------------------
ZOO = [
    "paddle_tpu.models.bert",
    "paddle_tpu.models.gpt",
    "paddle_tpu.vision.models.resnet",
    "paddle_tpu.vision.models.vgg",
    "paddle_tpu.vision.models.lenet",
    "paddle_tpu.vision.models.mobilenetv1",
    "paddle_tpu.vision.models.mobilenetv2",
]


class TestZeroFalsePositives:
    def test_model_zoo_is_clean(self, capsys):
        rc = analysis_main(["--strict"] + ZOO)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "no findings" in out

    @pytest.mark.slow
    def test_examples_are_warning_clean(self):
        scripts = sorted(glob.glob(os.path.join(REPO, "examples", "*.py")))
        assert scripts, "examples/ went missing"
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.analysis", "--no-exec",
             "--all-functions", "--strict"] + scripts,
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stdout + r.stderr


class _QuantMLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 8)

    def forward(self, x):
        return self.fc(x)


class TestQuantGuard:
    """Q801: quantization integrity (engine fallback / stale observers)."""

    def test_q801_engine_fallback(self):
        from paddle_tpu.framework import trace_events
        with RetraceMonitor() as mon:
            # the snapshot a quantized GenerationEngine emits when
            # post-warmup decode steps run with a float tree bound
            trace_events.notify(("quant", "engine#q"), {
                "kind": "engine", "mode": "int8", "quant_active": False,
                "fallback_steps_after_warm": 5})
        assert mon.quant_stats("engine#q")["fallback_steps_after_warm"] == 5
        diags = [d for d in mon.diagnostics() if d.rule == "Q801"]
        assert len(diags) == 1
        assert "non-quantized weight tree" in diags[0].message
        assert "swap_weights" in diags[0].hint

    def test_q801_uncalibrated_observers(self):
        from paddle_tpu.framework.errors import InvalidArgumentError
        from paddle_tpu.slim import PostTrainingQuantization
        with RetraceMonitor() as mon:
            ptq = PostTrainingQuantization(_QuantMLP())
            with pytest.raises(InvalidArgumentError):
                ptq.quantize()  # zero calibration batches collected
        diags = [d for d in mon.diagnostics() if d.rule == "Q801"]
        assert len(diags) == 1
        assert "uncalibrated" in diags[0].message
        assert "collect()" in diags[0].hint

    def test_calibrated_and_active_is_silent(self):
        from paddle_tpu.framework import trace_events
        from paddle_tpu.slim import PostTrainingQuantization
        with RetraceMonitor() as mon:
            ptq = PostTrainingQuantization(_QuantMLP())
            ptq.collect(paddle.to_tensor(
                np.ones((4, 8), np.float32)))
            ptq.quantize()
            trace_events.notify(("quant", "engine#ok"), {
                "kind": "engine", "mode": "int8", "quant_active": True,
                "fallback_steps_after_warm": 0})
        assert [d for d in mon.diagnostics() if d.rule == "Q801"] == []

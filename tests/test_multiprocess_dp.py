"""Two-process jax.distributed data parallelism on localhost.

Reference test strategy: python/paddle/fluid/tests/unittests/
test_dist_base.py:578,689-703 — spawn localhost trainer subprocesses,
run the distributed train loop, compare losses against the single-process
run.  Here the transport is jax.distributed's coordination service (the
NCCL-bootstrap replacement, SURVEY §7) with one CPU device per process:
a 2-process, 2-device global mesh.

Also exercises the cross-process liveness side-channel: each trainer
writes FileHeartbeat beats during the run (VERDICT r3 #7).
"""
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import env as penv
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.heartbeat import FileHeartbeat

rank = int(os.environ["PADDLE_TRAINER_ID"])
penv.init_parallel_env()  # wires jax.distributed from the env vars
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

hb = FileHeartbeat(os.environ["PT_TEST_HB"] + str(rank))

fleet._initialized = False
strategy = fleet.DistributedStrategy(dp_degree=2)
fleet.init(is_collective=True, strategy=strategy)

paddle.seed(0)
net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.05))
model = paddle.Model(net, inputs=["x"], labels=["y"])
model.prepare(optimizer=opt, loss=nn.MSELoss())

rng = np.random.RandomState(1)
x = rng.randn(8, 8).astype(np.float32)
y = rng.randn(8, 1).astype(np.float32)
losses = []
for _ in range(4):
    loss, _ = model.train_batch([x], [y])
    losses.append(float(np.asarray(loss)))
    hb.beat()

if rank == 0:
    with open(os.environ["PT_TEST_OUT"], "w") as f:
        json.dump(losses, f)
print("worker", rank, "done", losses)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_losses():
    """Same model/batch, plain single-process run, for parity."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as popt

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.SGD(learning_rate=0.05), loss=nn.MSELoss())
    rng = np.random.RandomState(1)
    x = rng.randn(8, 8).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    return [float(np.asarray(model.train_batch([x], [y])[0]))
            for _ in range(4)]


@pytest.mark.slow
def test_two_process_dp_matches_single_process(tmp_path):
    port = _free_port()
    out = str(tmp_path / "losses.json")
    hb_base = str(tmp_path / "beat")
    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as f:
        f.write(WORKER.format(repo=REPO))

    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel in workers
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PADDLE_TRAINER_ENDPOINTS": f"127.0.0.1:{port}",
            "PADDLE_TRAINERS_NUM": "2",
            "PADDLE_TRAINER_ID": str(rank),
            "PT_TEST_OUT": out,
            "PT_TEST_HB": hb_base,
        })
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))

    deadline = time.time() + 240
    for p in procs:
        timeout = max(1.0, deadline - time.time())
        try:
            stdout, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process DP run hung")
        assert p.returncode == 0, stdout.decode()[-3000:]

    with open(out) as f:
        dist_losses = json.load(f)
    single = _single_process_losses()
    # identical model, identical global batch, SPMD grad averaging ==
    # single-process gradient: loss-for-loss parity
    np.testing.assert_allclose(dist_losses, single, rtol=1e-5, atol=1e-6)

    # heartbeat side-channel: both trainers beat during the run
    for rank in range(2):
        assert os.path.exists(hb_base + str(rank))
        assert os.path.getsize(hb_base + str(rank)) > 0

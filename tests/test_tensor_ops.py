"""Tensor-function correctness vs the numpy oracle.

Mirrors the reference's OpTest pattern (unittests/op_test.py:184): declare
inputs, compute with the framework, compare against numpy reference outputs.
"""
import numpy as np
import pytest

import paddle_tpu as pt


def check(actual, expected, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(actual), expected, rtol=rtol, atol=atol)


class TestCreation:
    def test_to_tensor(self):
        x = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert x.shape == (2, 2)
        assert str(x.dtype) == "float32"

    def test_to_tensor_f64_downcast(self):
        x = pt.to_tensor(np.zeros((3,), np.float64))
        assert x.dtype == np.float32

    def test_zeros_ones_full(self):
        check(pt.zeros([2, 3]), np.zeros((2, 3)))
        check(pt.ones([2], "int32"), np.ones(2, np.int32))
        check(pt.full([2, 2], 7.0), np.full((2, 2), 7.0))

    def test_arange_linspace(self):
        check(pt.arange(5), np.arange(5))
        check(pt.arange(1, 10, 2), np.arange(1, 10, 2))
        check(pt.linspace(0, 1, 5), np.linspace(0, 1, 5, dtype=np.float32))

    def test_eye_diag_tri(self):
        check(pt.eye(3), np.eye(3, dtype=np.float32))
        check(pt.diag(pt.to_tensor([1.0, 2.0])), np.diag([1.0, 2.0]))
        a = np.arange(9, dtype=np.float32).reshape(3, 3)
        check(pt.tril(pt.to_tensor(a)), np.tril(a))
        check(pt.triu(pt.to_tensor(a), 1), np.triu(a, 1))

    def test_one_hot(self):
        out = pt.one_hot(pt.to_tensor([0, 2], "int32"), 3)
        check(out, np.array([[1, 0, 0], [0, 0, 1]], np.float32))

    def test_meshgrid(self):
        gx, gy = pt.meshgrid(pt.arange(2), pt.arange(3))
        ex, ey = np.meshgrid(np.arange(2), np.arange(3), indexing="ij")
        check(gx, ex)
        check(gy, ey)


class TestMath:
    def setup_method(self):
        rs = np.random.RandomState(42)
        self.a = rs.rand(3, 4).astype(np.float32)
        self.b = rs.rand(3, 4).astype(np.float32) + 0.5

    def test_binary(self):
        a, b = pt.to_tensor(self.a), pt.to_tensor(self.b)
        check(pt.add(a, b), self.a + self.b)
        check(pt.subtract(a, b), self.a - self.b)
        check(pt.multiply(a, b), self.a * self.b)
        check(pt.divide(a, b), self.a / self.b)
        check(pt.maximum(a, b), np.maximum(self.a, self.b))
        check(pt.pow(a, 2.0), self.a ** 2)

    def test_unary(self):
        a = pt.to_tensor(self.a)
        # XLA lowers transcendentals to fast approximations (~1e-4 rel err)
        check(pt.exp(a), np.exp(self.a), rtol=2e-4, atol=1e-5)
        check(pt.log(a + 1), np.log(self.a + 1), rtol=2e-4, atol=1e-5)
        check(pt.sqrt(a), np.sqrt(self.a))
        check(pt.rsqrt(a + 1), 1 / np.sqrt(self.a + 1), rtol=2e-4)
        # XLA lowers tanh/sigmoid to rational approximations (~1e-4 rel err)
        check(pt.tanh(a), np.tanh(self.a), rtol=2e-4, atol=1e-5)
        check(pt.sigmoid(a), 1 / (1 + np.exp(-self.a)), rtol=2e-4, atol=1e-5)
        check(pt.floor(a * 3), np.floor(self.a * 3))
        check(pt.abs(-a), np.abs(self.a))

    def test_int_unary_promotes(self):
        x = pt.to_tensor([1, 2, 3], "int32")
        out = pt.exp(x)
        assert out.dtype == np.float32

    def test_reductions(self):
        a = pt.to_tensor(self.a)
        check(pt.sum(a), self.a.sum(), rtol=1e-5)
        check(pt.sum(a, axis=1), self.a.sum(1), rtol=1e-5)
        check(pt.sum(a, axis=[0, 1]), self.a.sum(), rtol=1e-5)
        check(pt.mean(a, axis=0, keepdim=True), self.a.mean(0, keepdims=True), rtol=1e-5)
        check(pt.max(a), self.a.max())
        check(pt.min(a, axis=1), self.a.min(1))
        check(pt.prod(a, axis=0), self.a.prod(0), rtol=1e-4)
        check(pt.logsumexp(a), np.log(np.exp(self.a).sum()), rtol=1e-5)

    def test_cumulative(self):
        a = pt.to_tensor(self.a)
        check(pt.cumsum(a, axis=1), self.a.cumsum(1), rtol=1e-5)
        check(pt.cumsum(a), self.a.ravel().cumsum(), rtol=1e-5)
        check(pt.cumprod(a, dim=0), self.a.cumprod(0), rtol=1e-5)
        vals, idx = pt.cummax(pt.to_tensor([1.0, 3.0, 2.0, 5.0, 4.0]))
        check(vals, np.array([1, 3, 3, 5, 5], np.float32))
        check(idx, np.array([0, 1, 1, 3, 3]))

    def test_clip_scale(self):
        a = pt.to_tensor(self.a)
        check(pt.clip(a, 0.2, 0.8), np.clip(self.a, 0.2, 0.8))
        check(pt.scale(a, scale=2.0, bias=1.0), self.a * 2 + 1, rtol=1e-6)
        check(pt.scale(a, scale=2.0, bias=1.0, bias_after_scale=False), (self.a + 1) * 2, rtol=1e-6)

    def test_isnan_isinf(self):
        x = pt.to_tensor([1.0, float("nan"), float("inf")])
        check(pt.isnan(x), [False, True, False])
        check(pt.isinf(x), [False, False, True])
        check(pt.isfinite(x), [True, False, False])

    def test_lerp_addmm(self):
        a, b = pt.to_tensor(self.a), pt.to_tensor(self.b)
        check(pt.lerp(a, b, 0.3), self.a + 0.3 * (self.b - self.a), rtol=1e-5)
        m = np.eye(3, dtype=np.float32)
        check(
            pt.addmm(pt.to_tensor(m), pt.to_tensor(self.a), pt.to_tensor(self.b.T)),
            m + self.a @ self.b.T,
            rtol=1e-5,
        )


class TestManipulation:
    def setup_method(self):
        self.a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)

    def test_reshape_flatten(self):
        a = pt.to_tensor(self.a)
        assert pt.reshape(a, [6, 4]).shape == (6, 4)
        assert pt.flatten(a).shape == (24,)
        assert pt.flatten(a, 1, 2).shape == (2, 12)

    def test_squeeze_unsqueeze(self):
        a = pt.to_tensor(self.a[None])
        assert pt.squeeze(a, 0).shape == (2, 3, 4)
        assert pt.unsqueeze(pt.to_tensor(self.a), [0, 2]).shape == (1, 2, 1, 3, 4)

    def test_transpose_concat_split(self):
        a = pt.to_tensor(self.a)
        check(pt.transpose(a, [2, 0, 1]), self.a.transpose(2, 0, 1))
        check(pt.concat([a, a], axis=1), np.concatenate([self.a, self.a], 1))
        parts = pt.split(a, 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == (2, 1, 4)
        parts = pt.split(a, [1, -1], axis=1)
        assert parts[1].shape == (2, 2, 4)

    def test_stack_tile_expand(self):
        a = pt.to_tensor(self.a)
        check(pt.stack([a, a]), np.stack([self.a, self.a]))
        check(pt.tile(a, [1, 2, 1]), np.tile(self.a, (1, 2, 1)))
        b = pt.to_tensor(np.ones((1, 3), np.float32))
        assert pt.expand(b, [5, 3]).shape == (5, 3)

    def test_gather_scatter(self):
        a = pt.to_tensor(self.a.reshape(6, 4))
        check(pt.gather(a, pt.to_tensor([0, 2], "int32")), self.a.reshape(6, 4)[[0, 2]])
        x = pt.zeros([4, 2])
        out = pt.scatter(x, pt.to_tensor([1, 3], "int32"), pt.ones([2, 2]))
        expected = np.zeros((4, 2), np.float32)
        expected[[1, 3]] = 1
        check(out, expected)

    def test_gather_nd(self):
        a = pt.to_tensor(self.a)
        idx = pt.to_tensor([[0, 1], [1, 2]], "int32")
        check(pt.gather_nd(a, idx), self.a[[0, 1], [1, 2]])

    def test_take_along_put_along(self):
        a = pt.to_tensor(self.a.reshape(6, 4))
        idx = pt.to_tensor(np.array([[0], [1], [2], [3], [0], [1]]), "int64")
        check(pt.take_along_axis(a, idx, 1),
              np.take_along_axis(self.a.reshape(6, 4), np.array([[0], [1], [2], [3], [0], [1]]), 1))

    def test_pad_cast_flip(self):
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        out = pt.pad(a, [1, 1], value=5.0)  # pads last dim
        assert out.shape == (2, 4)
        assert np.asarray(out)[0, 0] == 5.0
        assert pt.cast(a, "int64").dtype == np.int64
        check(pt.flip(pt.to_tensor(self.a), [0]), self.a[::-1])

    def test_roll_chunk(self):
        a = pt.to_tensor(np.arange(6))
        check(pt.roll(a, 2), np.roll(np.arange(6), 2))
        chunks = pt.chunk(pt.to_tensor(self.a), 2, axis=2)
        assert chunks[0].shape == (2, 3, 2)

    def test_unique_masked(self):
        out = pt.unique(pt.to_tensor([3, 1, 2, 1, 3]))
        check(out, [1, 2, 3])
        sel = pt.masked_select(pt.to_tensor([1.0, 2.0, 3.0]), pt.to_tensor([True, False, True]))
        check(sel, [1.0, 3.0])
        filled = pt.masked_fill(pt.to_tensor([1.0, 2.0]), pt.to_tensor([True, False]), -1.0)
        check(filled, [-1.0, 2.0])

    def test_shard_index(self):
        out = pt.shard_index(pt.to_tensor([0, 5, 9, 3], "int64"), 10, 2, 0)
        check(out, [0, -1, -1, 3])


class TestLinalg:
    def setup_method(self):
        rs = np.random.RandomState(7)
        self.a = rs.rand(3, 4).astype(np.float32)
        self.b = rs.rand(4, 5).astype(np.float32)

    def test_matmul(self):
        check(pt.matmul(pt.to_tensor(self.a), pt.to_tensor(self.b)), self.a @ self.b, rtol=1e-5)
        check(pt.matmul(pt.to_tensor(self.a), pt.to_tensor(self.b.T), transpose_y=True),
              self.a @ self.b, rtol=1e-5)

    def test_matmul_bf16_accum(self):
        a = pt.cast(pt.to_tensor(self.a), "bfloat16")
        b = pt.cast(pt.to_tensor(self.b), "bfloat16")
        out = pt.matmul(a, b)
        assert out.dtype == pt.bfloat16
        check(pt.cast(out, "float32"), self.a @ self.b, rtol=2e-2, atol=2e-2)

    def test_norm_dist(self):
        a = pt.to_tensor(self.a)
        check(pt.norm(a), np.linalg.norm(self.a), rtol=1e-5)
        check(pt.norm(a, p=1, axis=1), np.abs(self.a).sum(1), rtol=1e-5)
        check(pt.dist(a, pt.zeros_like(a)), np.linalg.norm(self.a), rtol=1e-5)

    def test_solve_inv(self):
        m = np.eye(3, dtype=np.float32) * 2 + 0.1
        check(pt.inverse(pt.to_tensor(m)), np.linalg.inv(m), rtol=1e-4)
        y = np.ones((3,), np.float32)
        check(pt.solve(pt.to_tensor(m), pt.to_tensor(y)), np.linalg.solve(m, y), rtol=1e-4)
        check(pt.det(pt.to_tensor(m)), np.linalg.det(m), rtol=1e-4)

    def test_svd_qr_cholesky(self):
        m = self.a @ self.a.T + np.eye(3, dtype=np.float32)
        u, s, vt = pt.svd(pt.to_tensor(self.a))
        check(s, np.linalg.svd(self.a, compute_uv=False), rtol=1e-4)
        L = pt.cholesky(pt.to_tensor(m))
        check(pt.matmul(L, L, transpose_y=True), m, rtol=1e-4)
        q, r = pt.qr(pt.to_tensor(self.a))
        check(pt.matmul(q, r), self.a, rtol=1e-4, atol=1e-5)

    def test_einsum(self):
        check(pt.einsum("ij,jk->ik", pt.to_tensor(self.a), pt.to_tensor(self.b)),
              self.a @ self.b, rtol=1e-5)

    def test_bincount_histogram(self):
        check(pt.bincount(pt.to_tensor([0, 1, 1, 3], "int32")), [1, 2, 0, 1])
        h = pt.histogram(pt.to_tensor([0.0, 1.0, 2.0, 3.0]), bins=4, min=0, max=4)
        check(h, [1, 1, 1, 1])


class TestLogic:
    def test_compare(self):
        a = pt.to_tensor([1.0, 2.0, 3.0])
        b = pt.to_tensor([2.0, 2.0, 2.0])
        check(pt.equal(a, b), [False, True, False])
        check(pt.greater_than(a, b), [False, False, True])
        check(pt.less_equal(a, b), [True, True, False])
        assert bool(pt.equal_all(a, a))
        assert bool(pt.allclose(a, a + 1e-9))

    def test_logical_bitwise(self):
        t = pt.to_tensor([True, False])
        check(pt.logical_and(t, t), [True, False])
        check(pt.logical_not(t), [False, True])
        x = pt.to_tensor([1, 2], "int32")
        check(pt.bitwise_and(x, pt.to_tensor([3, 2], "int32")), [1, 2])
        check(pt.bitwise_left_shift(x, 1), [2, 4])

    def test_is_tensor(self):
        assert pt.is_tensor(pt.ones([1]))
        assert not pt.is_tensor([1.0])


class TestSearch:
    def setup_method(self):
        self.a = np.array([[3.0, 1.0, 2.0], [6.0, 5.0, 4.0]], np.float32)

    def test_argmax_sort(self):
        a = pt.to_tensor(self.a)
        check(pt.argmax(a, axis=1), [0, 0])
        check(pt.argmin(a, axis=1), [1, 2])
        check(pt.sort(a, axis=1), np.sort(self.a, 1))
        check(pt.argsort(a, axis=1), np.argsort(self.a, 1))
        check(pt.sort(a, axis=1, descending=True), -np.sort(-self.a, 1))

    def test_topk(self):
        vals, idx = pt.topk(pt.to_tensor(self.a), 2, axis=1)
        check(vals, [[3.0, 2.0], [6.0, 5.0]])
        check(idx, [[0, 2], [0, 1]])
        vals, idx = pt.topk(pt.to_tensor(self.a), 1, axis=1, largest=False)
        check(vals, [[1.0], [4.0]])

    def test_where_nonzero(self):
        a = pt.to_tensor(self.a)
        check(pt.where(pt.greater_than(a, 2.5), a, pt.zeros_like(a)),
              np.where(self.a > 2.5, self.a, 0))
        nz = pt.nonzero(pt.to_tensor([0, 1, 0, 2]))
        check(nz, [[1], [3]])

    def test_median_kth(self):
        x = pt.to_tensor([1.0, 3.0, 2.0, 4.0])
        check(pt.median(x), 2.5)
        vals, idx = pt.kthvalue(x, 2)
        check(vals, 2.0)
        check(pt.searchsorted(pt.to_tensor([1.0, 2.0, 3.0]), pt.to_tensor([2.5])), [2])

    def test_mode(self):
        vals, idx = pt.mode(pt.to_tensor([[1.0, 2.0, 2.0], [3.0, 3.0, 1.0]]))
        check(vals, [2.0, 3.0])


class TestStatRandom:
    def test_std_var(self):
        rs = np.random.RandomState(0)
        a = rs.rand(10, 5).astype(np.float32)
        check(pt.std(pt.to_tensor(a)), a.std(ddof=1), rtol=1e-4)
        check(pt.var(pt.to_tensor(a), axis=0), a.var(0, ddof=1), rtol=1e-4)
        check(pt.var(pt.to_tensor(a), unbiased=False), a.var(), rtol=1e-4)

    def test_quantile(self):
        a = np.arange(8, dtype=np.float32)
        check(pt.quantile(pt.to_tensor(a), 0.5), 3.5)

    def test_random_shapes_and_ranges(self):
        pt.seed(123)
        u = pt.uniform([100], min=0.0, max=2.0)
        arr = np.asarray(u)
        assert arr.shape == (100,) and (arr >= 0).all() and (arr < 2).all()
        n = pt.randn([1000])
        assert abs(float(np.asarray(n).mean())) < 0.2
        r = pt.randint(0, 5, [50])
        assert np.asarray(r).min() >= 0 and np.asarray(r).max() < 5
        p = pt.randperm(10)
        assert sorted(np.asarray(p).tolist()) == list(range(10))

    def test_seed_reproducible(self):
        pt.seed(7)
        a = np.asarray(pt.randn([4]))
        pt.seed(7)
        b = np.asarray(pt.randn([4]))
        np.testing.assert_array_equal(a, b)

    def test_bernoulli_multinomial(self):
        pt.seed(3)
        b = pt.bernoulli(pt.full([200], 0.5))
        frac = float(np.asarray(b).mean())
        assert 0.3 < frac < 0.7
        m = pt.multinomial(pt.to_tensor([0.1, 0.0, 0.9]), 5, replacement=True)
        assert 1 not in np.asarray(m)


class TestFramework:
    def test_default_dtype(self):
        assert pt.get_default_dtype() == np.float32
        pt.set_default_dtype("float64")
        try:
            assert pt.ones([1]).dtype == np.float64
        finally:
            pt.set_default_dtype("float32")

    def test_flags(self):
        pt.set_flags({"check_nan_inf": True})
        assert pt.get_flags("check_nan_inf")["check_nan_inf"] is True
        pt.set_flags({"check_nan_inf": False})
        with pytest.raises(Exception):
            pt.set_flags({"no_such_flag": 1})

    def test_device(self):
        dev = pt.get_device()
        assert ":" in dev
        assert pt.device_count("cpu") >= 1

    def test_dtype_convert(self):
        from paddle_tpu.framework.dtype import convert_dtype

        assert convert_dtype("fp16") == np.float16
        assert convert_dtype("bf16") == pt.bfloat16
        with pytest.raises(TypeError):
            convert_dtype("not_a_dtype")

    def test_finfo_iinfo(self):
        assert pt.finfo("float32").max > 1e38
        assert pt.iinfo("int8").max == 127

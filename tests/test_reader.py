"""paddle.reader decorators (1.x data pipeline).

Reference capability: python/paddle/reader/decorator.py — reader
creators compose; each decorator preserves the zero-arg-callable
contract and its documented semantics.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.errors import InvalidArgumentError

reader = paddle.reader


def _r(n, base=0):
    def _impl():
        return iter(range(base, base + n))

    return _impl


class TestReaderDecorators:
    def test_cache_replays(self):
        calls = []

        def once():
            calls.append(1)
            return iter([1, 2, 3])

        c = reader.cache(once)
        assert list(c()) == [1, 2, 3]
        assert list(c()) == [1, 2, 3]
        assert len(calls) == 1  # source consumed exactly once

    def test_map_readers(self):
        out = list(reader.map_readers(lambda a, b: a + b, _r(3), _r(3, 10))())
        assert out == [10, 12, 14]

    def test_shuffle_is_permutation(self):
        # order comes from python's global `random`, like the reference
        out = list(reader.shuffle(_r(20), buf_size=7)())
        assert sorted(out) == list(range(20))

    def test_chain(self):
        assert list(reader.chain(_r(2), _r(2, 5))()) == [0, 1, 5, 6]

    def test_compose_flattens_and_checks_alignment(self):
        def pairs():
            return iter([(1, 2), (3, 4)])

        out = list(reader.compose(pairs, _r(2, 9))())
        assert out == [(1, 2, 9), (3, 4, 10)]
        # the reference type (a ValueError) and the framework type both
        # catch it
        with pytest.raises(reader.ComposeNotAligned):
            list(reader.compose(_r(2), _r(3))())
        with pytest.raises(ValueError):
            list(reader.compose(_r(2), _r(3))())
        with pytest.raises(InvalidArgumentError):
            list(reader.compose(_r(2), _r(3))())
        assert len(list(reader.compose(_r(2), _r(3),
                                       check_alignment=False)())) == 2

    # (split_states/concat_states coverage lives with the other RNN tests
    # in tests/test_nn_layers.py)

    def test_buffered_and_firstn(self):
        assert list(reader.buffered(_r(10), size=3)()) == list(range(10))
        assert list(reader.firstn(_r(10), 4)()) == [0, 1, 2, 3]

    def test_buffered_propagates_producer_errors(self):
        def bad():
            yield 1
            raise IOError("corrupt shard")

        it = reader.buffered(lambda: bad(), size=2)()
        assert next(it) == 1
        with pytest.raises(IOError, match="corrupt shard"):
            list(it)

    def test_multiprocess_reader_propagates_errors(self):
        def bad():
            raise IOError("boom")
            yield  # pragma: no cover

        with pytest.raises(IOError, match="boom"):
            list(reader.multiprocess_reader([_r(3), lambda: bad()])())

    def test_early_exit_unblocks_producer(self):
        """firstn over a buffered reader must not leave the fill thread
        blocked on a full queue forever."""
        import threading
        import time

        n_before = threading.active_count()
        out = list(reader.firstn(reader.buffered(_r(1000), size=2), 3)())
        assert out == [0, 1, 2]
        deadline = time.time() + 5
        while threading.active_count() > n_before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= n_before

    def test_xmap_ordered(self):
        out = list(reader.xmap_readers(lambda x: x * x, _r(10),
                                       process_num=3, buffer_size=4,
                                       order=True)())
        assert out == [i * i for i in range(10)]

    def test_xmap_unordered_same_set(self):
        out = list(reader.xmap_readers(lambda x: x + 1, _r(10),
                                       process_num=2, buffer_size=3)())
        assert sorted(out) == list(range(1, 11))

    def test_multiprocess_reader_interleaves_all(self):
        out = list(reader.multiprocess_reader([_r(5), _r(5, 100)])())
        assert sorted(out) == sorted(list(range(5)) + list(range(100, 105)))

    def test_feeds_model_fit_via_iteration(self):
        """Readers plug into the training loop like the reference's
        train loop over reader() batches."""
        from paddle_tpu import nn, optimizer as popt

        rng = np.random.RandomState(0)
        data = [(rng.randn(8).astype(np.float32),
                 rng.randn(1).astype(np.float32)) for _ in range(32)]

        def creator():
            return iter(data)

        paddle.seed(0)
        net = nn.Linear(8, 1)
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=popt.SGD(learning_rate=0.05),
                  loss=nn.MSELoss())
        pipe = reader.buffered(reader.shuffle(creator, 16), 8)
        for _ in range(3):
            for x, y in pipe():
                m.train_batch([x[None]], [y[None]])
        # it trained
        l, _ = m.train_batch([data[0][0][None]], [data[0][1][None]])
        assert np.isfinite(l)

"""The lazy-graph Program/Executor: 1.x static-graph flows end to end.

Reference capability: fluid/framework.py Program + executor.py:575
Executor.run + backward.py:1275 append_backward (via minimize), exercised
the way the reference's book tests drive them
(python/paddle/fluid/tests/book/test_fit_a_line.py,
test_recognize_digits.py) — plus the block control flow (While:971,
StaticRNN:449) and the py_reader feed pipeline (layers/io.py:415).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.fluid as fluid
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.static.graph import reset_default_programs


@pytest.fixture(autouse=True)
def _fresh_programs():
    import paddle_tpu as paddle

    paddle.seed(0)  # builder param init draws from the global generator
    reset_default_programs()
    yield
    reset_default_programs()


def _programs():
    return fluid.Program(), fluid.Program()


class TestFitALine:
    """The canonical 1.x regression: data → fc → mse → SGD.minimize →
    exe.run loop (book/test_fit_a_line.py)."""

    def test_trains_to_low_loss(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 13])
            y = fluid.data("y", [-1, 1])
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = rng.rand(64, 13).astype(np.float32)
        Y = (X @ rng.randn(13))[:, None].astype(np.float32)
        first = last = None
        for _ in range(100):
            out, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            first = first if first is not None else float(out)
            last = float(out)
        assert last < first * 0.02, (first, last)

    def test_startup_rerun_reinitializes(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.data("y", [-1, 1])
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(x, 1), y))
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        w0 = dict(main.parameters_numpy())
        X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
        Y = np.ones((8, 1), np.float32)
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        assert any(not np.array_equal(v, main.parameters_numpy()[k])
                   for k, v in w0.items())
        exe.run(startup)  # back to init
        for k, v in w0.items():
            np.testing.assert_array_equal(v, main.parameters_numpy()[k])

    def test_init_values_are_donation_proof_host_copies(self):
        # ADVICE r4 (medium): the jitted train step donates scope arrays;
        # _init_values aliasing those jax Arrays meant a later
        # exe.run(startup) restored deleted buffers (TPU crash).  They must
        # be host (numpy) copies, re-uploaded on reinitialize.
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.data("y", [-1, 1])
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(x, 1), y))
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        assert main._init_values, "expected registered params"
        for v in main._init_values.values():
            assert isinstance(v, np.ndarray), type(v)
        exe = fluid.Executor()
        exe.run(startup)
        X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
        Y = np.ones((8, 1), np.float32)
        exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        exe.run(startup)  # restore — and train again on fresh buffers
        out, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        assert np.isfinite(out).all()

    def test_clone_snapshots_ops_and_gets_fresh_cache_key(self):
        # ADVICE r4: copy.copy shared the ops LIST — ops recorded after
        # cloning leaked into the clone while its cache key stayed stale.
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            out = fluid.layers.fc(x, 3)
        test_prog = main.clone(for_test=True)
        n_ops = len(test_prog.ops)
        assert test_prog.idx != main.idx
        with fluid.program_guard(main, startup):
            fluid.layers.mean(out)  # recorded on the ORIGINAL only
        assert len(test_prog.ops) == n_ops
        assert len(main.ops) == n_ops + 1

    def test_fetch_by_name_and_scope_read(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            out = fluid.layers.fc(x, 3)
        exe = fluid.Executor()
        X = np.ones((2, 4), np.float32)
        r1, = exe.run(main, feed={"x": X}, fetch_list=[out])
        r2, = exe.run(main, feed={"x": X}, fetch_list=[out.name])
        np.testing.assert_array_equal(r1, r2)
        # global_scope().find_var reads parameters (1.x idiom)
        pname = main.all_parameters()[0].name
        with fluid.program_guard(main, startup):
            t = fluid.global_scope().find_var(pname)
        assert t is not None and t.get_tensor().shape == (4, 3)


class TestRecognizeDigits:
    """conv2d → pool2d → batch_norm → fc(softmax) → cross_entropy, the
    book/test_recognize_digits.py conv variant."""

    def test_convnet_trains_and_bn_stats_update(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            img = fluid.data("img", [-1, 1, 12, 12])
            label = fluid.data("label", [-1, 1], dtype="int64")
            c = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                                    act="relu")
            p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2)
            b = fluid.layers.batch_norm(p)
            pred = fluid.layers.fc(b, size=10, act="softmax")
            loss = fluid.layers.mean(
                fluid.layers.cross_entropy(pred, label))
            fluid.optimizer.AdamOptimizer(
                learning_rate=0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        bufs0 = {k: np.asarray(v) for k, v in main.buffers.items()}
        rng = np.random.RandomState(0)
        protos = rng.rand(10, 1, 12, 12).astype(np.float32)
        yb = rng.randint(0, 10, 64)
        Xb = protos[yb] + 0.05 * rng.randn(64, 1, 12, 12).astype(np.float32)
        first = last = None
        for _ in range(25):
            out, = exe.run(main,
                           feed={"img": Xb,
                                 "label": yb[:, None].astype(np.int64)},
                           fetch_list=[loss])
            first = first if first is not None else float(out)
            last = float(out)
        assert last < first * 0.3, (first, last)
        # BN moving stats moved (buffer write-back through the jit)
        assert any(not np.array_equal(v, np.asarray(main.buffers[k]))
                   for k, v in bufs0.items())


class TestWhileBlock:
    def test_while_counts_and_mutates(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            i = fluid.layers.fill_constant([1], "int64", 0)
            limit = fluid.layers.fill_constant([1], "int64", 10)
            acc = fluid.layers.fill_constant([1], "float32", 0.0)
            cond = fluid.layers.less_than(i, limit)
            loop = fluid.layers.While(cond)
            with loop.block():
                fluid.layers.assign(acc + 1.5, output=acc)
                fluid.layers.increment(i, value=1)
                fluid.layers.less_than(i, limit, cond=cond)
            post = acc * 2.0  # post-loop ops see final values
        acc_v, i_v, post_v = fluid.Executor().run(
            main, feed={}, fetch_list=[acc, i, post])
        assert float(acc_v[0]) == 15.0
        assert int(i_v[0]) == 10
        assert float(post_v[0]) == 30.0


class TestStaticRNN:
    def test_cumsum_semantics(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            xseq = fluid.data("xseq", [6, 4, 3])  # [T, B, D] seq-major
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                w = rnn.step_input(xseq)
                prev = rnn.memory(shape=[4, 3], batch_ref=w)
                h = prev + w
                rnn.update_memory(prev, h)
                rnn.step_output(h)
            outs = rnn()
        X = np.random.RandomState(0).randn(6, 4, 3).astype(np.float32)
        o, = fluid.Executor().run(main, feed={"xseq": X},
                                  fetch_list=[outs])
        np.testing.assert_allclose(o, np.cumsum(X, axis=0), rtol=1e-5)

    def test_rnn_with_fc_params_trains(self):
        # parameters created INSIDE the step block train through the scan
        main, startup = _programs()
        T, B, D, H = 5, 8, 3, 4
        with fluid.program_guard(main, startup):
            xseq = fluid.data("xseq", [T, B, D])
            target = fluid.data("target", [B, H])
            rnn = fluid.layers.StaticRNN()
            with rnn.step():
                w = rnn.step_input(xseq)
                prev = fluid.layers.StaticRNN.memory  # noqa: B009 (doc)
                prev = rnn.memory(shape=[B, H], batch_ref=w)
                joined = fluid.layers.concat([w, prev], axis=1)
                h = fluid.layers.fc(joined, size=H, act="tanh")
                rnn.update_memory(prev, h)
                rnn.step_output(h)
            outs = rnn()
            last = outs[T - 1]
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(last, target))
            fluid.optimizer.AdamOptimizer(learning_rate=0.02).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = rng.randn(T, B, D).astype(np.float32)
        Y = np.tanh(rng.randn(B, H)).astype(np.float32)
        first = lastl = None
        for _ in range(60):
            out, = exe.run(main, feed={"xseq": X, "target": Y},
                           fetch_list=[loss])
            first = first if first is not None else float(out)
            lastl = float(out)
        assert lastl < first * 0.3, (first, lastl)


class TestPyReader:
    def test_feed_pipeline_with_eof(self):
        from paddle_tpu.fluid.core import EOFException

        main, startup = _programs()
        with fluid.program_guard(main, startup):
            reader = fluid.layers.py_reader(
                capacity=4, shapes=[[-1, 4], [-1, 1]],
                dtypes=["float32", "float32"])
            x, y = fluid.layers.read_file(reader)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(x, 1), y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)

        rng = np.random.RandomState(0)

        def gen():
            for _ in range(5):
                X = rng.rand(16, 4).astype(np.float32)
                yield [X, (X.sum(1, keepdims=True)).astype(np.float32)]

        reader.decorate_batch_generator(gen)
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for _epoch in range(3):
            reader.start()
            while True:
                try:
                    out, = exe.run(main, fetch_list=[loss])
                    losses.append(float(out))
                except EOFException:
                    break
        assert len(losses) == 15
        assert losses[-1] < losses[0]


class TestEagerControlFlow:
    """cond/while_loop/case/switch_case as plain functions — eager and
    under jit (the to_static contract for data-dependent control flow)."""

    def test_cond_eager_and_traced(self):
        t = lambda: jnp.asarray(1.0)  # noqa: E731
        f = lambda: jnp.asarray(-1.0)  # noqa: E731
        assert float(fluid.layers.cond(True, t, f)) == 1.0
        assert float(fluid.layers.cond(False, t, f)) == -1.0

        @jax.jit
        def fn(x):
            return fluid.layers.cond(x.mean() > 0, t, f)

        assert float(fn(jnp.ones(3))) == 1.0
        assert float(fn(-jnp.ones(3))) == -1.0

    def test_while_loop_eager_and_traced(self):
        c = lambda i, s: i < 5  # noqa: E731
        b = lambda i, s: (i + 1, s + i)  # noqa: E731
        i, s = fluid.layers.while_loop(c, b, [0, 0])
        assert (i, s) == (5, 10)

        @jax.jit
        def fn(x):
            i, s = fluid.layers.while_loop(
                c, b, [jnp.asarray(0), x])
            return s

        assert int(fn(jnp.asarray(0))) == 10

    def test_case_and_switch_case(self):
        one = lambda: jnp.asarray(1)  # noqa: E731
        two = lambda: jnp.asarray(2)  # noqa: E731
        три = lambda: jnp.asarray(3)  # noqa: E731
        assert int(fluid.layers.case([(False, one), (True, two)],
                                     default=три)) == 2
        assert int(fluid.layers.case([(False, one), (False, two)],
                                     default=три)) == 3
        assert int(fluid.layers.switch_case(1, {0: one, 1: two})) == 2

        @jax.jit
        def fn(i):
            return fluid.layers.switch_case(i, {0: one, 1: two},
                                            default=три)

        assert int(fn(jnp.asarray(1))) == 2
        assert int(fn(jnp.asarray(7))) == 3


class TestGraphContract:
    def test_builders_raise_outside_graph_mode(self):
        with pytest.raises(InvalidArgumentError, match="graph mode"):
            fluid.layers.fc(np.ones((2, 3), np.float32), 4)

    def test_symbolic_numpy_read_raises(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            with pytest.raises(InvalidArgumentError, match="fetch"):
                x.numpy()

    def test_state_dict_roundtrip(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            out = fluid.layers.fc(x, 2)
        exe = fluid.Executor()
        X = np.ones((2, 4), np.float32)
        r1, = exe.run(main, feed={"x": X}, fetch_list=[out])
        state = main.state_dict()
        state = {k: np.zeros_like(v) for k, v in state.items()}
        fluid.set_program_state(main, state)
        r2, = exe.run(main, feed={"x": X}, fetch_list=[out])
        np.testing.assert_array_equal(r2, np.zeros_like(r1))


class TestBuilderBatch3:
    """Round-4 graph builders: nce / center_loss / sequence_conv /
    hsigmoid / inplace_abn (ref: fluid/layers/nn.py nce, loss.py
    center_loss, nn.py sequence_conv/inplace_abn/hsigmoid)."""

    def test_nce_center_seqconv_hsigmoid_train(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 16])
            lbl = fluid.data("lbl", [-1, 1], dtype="int64")
            seq = fluid.data("seq", [-1, 6, 8])
            loss = (fluid.layers.mean(fluid.layers.nce(
                        x, lbl, num_total_classes=50, num_neg_samples=4))
                    + fluid.layers.mean(fluid.layers.center_loss(
                        x, lbl, num_classes=50, alpha=0.1))
                    + fluid.layers.mean(fluid.layers.sequence_conv(
                        seq, num_filters=4, filter_size=3))
                    + fluid.layers.mean(fluid.layers.hsigmoid(
                        x, lbl, num_classes=50)))
            fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(8, 16).astype(np.float32),
                "lbl": rng.randint(0, 50, (8, 1)).astype(np.int64),
                "seq": rng.randn(8, 6, 8).astype(np.float32)}
        first = last = None
        for _ in range(12):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            first = first if first is not None else float(v)
            last = float(v)
        assert last < first
        # center_loss maintains its centers BUFFER during training runs
        assert any("center" in k for k in main.buffers)

    def test_sequence_conv_matches_manual_context_projection(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            seq = fluid.data("seq", [2, 5, 3])
            out = fluid.layers.sequence_conv(seq, num_filters=2,
                                             filter_size=3)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = rng.randn(2, 5, 3).astype(np.float32)
        o, = exe.run(main, feed={"seq": X}, fetch_list=[out])
        w = next(v for k, v in main.scope.items() if "sequence_conv" in k
                 and np.asarray(v).ndim == 2 and np.asarray(v).shape[0] == 9)
        w = np.asarray(w)
        b = next((np.asarray(v) for k, v in main.scope.items()
                  if "sequence_conv" in k and np.asarray(v).ndim == 1), 0)
        Xp = np.pad(X, ((0, 0), (1, 1), (0, 0)))  # context window ±1
        ctx = np.concatenate([Xp[:, 0:5], Xp[:, 1:6], Xp[:, 2:7]], axis=-1)
        np.testing.assert_allclose(o, ctx @ w + b, rtol=1e-4, atol=1e-5)

    def test_inplace_abn_is_bn_with_act(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 3, 4, 4])
            out = fluid.layers.inplace_abn(x, act="relu")
        exe = fluid.Executor()
        exe.run(startup)
        X = np.random.RandomState(0).randn(2, 3, 4, 4).astype(np.float32)
        o, = exe.run(main, feed={"x": X}, fetch_list=[out])
        assert (o >= 0).all()  # activation applied


class TestBeamSearchAndLstm:
    def test_beam_search_dense_pruning_and_finished_beams(self):
        import paddle_tpu.fluid as fl

        pre_ids = np.array([[0], [2]], np.int64)      # beam 0 finished
        pre_scores = np.array([[-1.0], [-2.0]], np.float32)
        ids = np.array([[10, 11, 12], [20, 21, 22]], np.int64)
        scores = np.array([[-9, -9, -9], [-1.5, -2.1, -9]], np.float32)
        si, ss, pi = fl.layers.beam_search(
            pre_ids, pre_scores, ids, scores, beam_size=2, end_id=0,
            return_parent_idx=True)
        # finished beam re-emits end_id with its own score; live beam's
        # best expansion wins the other slot
        got = list(zip(np.asarray(si).ravel().tolist(),
                       np.asarray(pi).ravel().tolist()))
        assert (0, 0) in got and (20, 1) in got

    def test_lstm_builder_trains(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 5, 8])
            h0 = fluid.data("h0", [1, -1, 16])
            c0 = fluid.data("c0", [1, -1, 16])
            y = fluid.data("y", [-1, 16])
            out, lh, lc = fluid.layers.lstm(x, h0, c0, 5, 16, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(lh[0], y))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(4, 5, 8).astype(np.float32),
                "h0": np.zeros((1, 4, 16), np.float32),
                "c0": np.zeros((1, 4, 16), np.float32),
                "y": np.tanh(rng.randn(4, 16)).astype(np.float32)}
        first = last = None
        for _ in range(25):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            first = first if first is not None else float(v)
            last = float(v)
        assert last < first * 0.8


class TestBuilderBatch4:
    """Switch/IfElse block capture + data_norm + multi_box_head (ref:
    fluid control_flow Switch/IfElse, nn.py:3220 data_norm,
    detection.py multi_box_head)."""

    def test_switch_first_match_wins_and_default(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            t = fluid.data("t", [1])
            half = fluid.layers.fill_constant([1], "float32", 0.5)
            one5 = fluid.layers.fill_constant([1], "float32", 1.5)
            a = fluid.layers.fill_constant([1], "float32", 1.0)
            b = fluid.layers.fill_constant([1], "float32", 2.0)
            c = fluid.layers.fill_constant([1], "float32", 3.0)
            out = fluid.layers.fill_constant([1], "float32", 0.0)
            with fluid.layers.Switch() as sw:
                with sw.case(t < half):
                    fluid.layers.assign(a, output=out)
                with sw.case(t < one5):
                    fluid.layers.assign(b, output=out)
                with sw.default():
                    fluid.layers.assign(c, output=out)
        exe = fluid.Executor()
        for tv, want in [(0.1, 1.0), (1.0, 2.0), (9.0, 3.0)]:
            r, = exe.run(main, feed={"t": np.array([tv], np.float32)},
                         fetch_list=[out])
            assert float(r[0]) == want, (tv, float(r[0]))

    def test_ifelse_rowwise_merge(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            xv = fluid.data("x", [-1, 3])
            cond = fluid.data("c", [-1, 1], dtype="bool")
            ie = fluid.layers.IfElse(cond)
            with ie.true_block():
                ie.output(xv * 10.0)
            with ie.false_block():
                ie.output(-xv)
            res, = ie()
        X = np.arange(12).reshape(4, 3).astype(np.float32)
        C = np.array([[True], [False], [True], [False]])
        r, = fluid.Executor().run(main, feed={"x": X, "c": C},
                                  fetch_list=[res])
        np.testing.assert_allclose(r, np.where(C, X * 10, -X))

    def test_data_norm_trains_and_updates_summaries(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.data("y", [-1, 1])
            dn = fluid.layers.data_norm(x)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(
                fluid.layers.fc(dn, 1), y))
            fluid.optimizer.SGD(0.005).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = (rng.randn(32, 4) * 5 + 3).astype(np.float32)
        Y = rng.randn(32, 1).astype(np.float32)
        b0 = {k: np.asarray(v) for k, v in main.buffers.items()}
        first = last = None
        for _ in range(30):
            v, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            first = first if first is not None else float(v)
            last = float(v)
        assert last < first
        assert any(not np.array_equal(v, np.asarray(main.buffers[k]))
                   for k, v in b0.items())

    def test_multi_box_head_shapes_align(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            f1 = fluid.data("f1", [-1, 8, 8, 8])
            f2 = fluid.data("f2", [-1, 8, 4, 4])
            f3 = fluid.data("f3", [-1, 8, 2, 2])
            img = fluid.data("img", [-1, 3, 64, 64])
            locs, confs, boxes, vrs = fluid.layers.multi_box_head(
                [f1, f2, f3], img, base_size=64, num_classes=5,
                # 1.0 in the list exercises prior_box's dedup, which the
                # conv channel count must mirror exactly
                aspect_ratios=[[1.0, 2.0], [2.0], [2.0]],
                min_ratio=20, max_ratio=90, kernel_size=3, pad=1)
        r = fluid.Executor().run(main, feed={
            "f1": np.random.randn(2, 8, 8, 8).astype(np.float32),
            "f2": np.random.randn(2, 8, 4, 4).astype(np.float32),
            "f3": np.random.randn(2, 8, 2, 2).astype(np.float32),
            "img": np.zeros((2, 3, 64, 64), np.float32)},
            fetch_list=[locs, confs, boxes, vrs])
        assert r[0].shape[2] == 4 and r[1].shape[2] == 5
        assert r[2].shape == r[3].shape
        assert r[0].shape[1] == r[2].shape[0]  # priors align with locs

    def test_multi_box_head_two_maps_needs_explicit_sizes(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            f1 = fluid.data("f1", [-1, 8, 8, 8])
            f2 = fluid.data("f2", [-1, 8, 4, 4])
            img = fluid.data("img", [-1, 3, 64, 64])
            with pytest.raises(InvalidArgumentError, match="min_sizes"):
                fluid.layers.multi_box_head(
                    [f1, f2], img, base_size=64, num_classes=5,
                    aspect_ratios=[[2.0], [2.0]], min_ratio=20,
                    max_ratio=90)
            # explicit sizes work for any map count
            locs, confs, boxes, vrs = fluid.layers.multi_box_head(
                [f1, f2], img, base_size=64, num_classes=5,
                aspect_ratios=[[2.0], [2.0]],
                min_sizes=[12.8, 32.0], max_sizes=[32.0, 54.4],
                kernel_size=3, pad=1)
        r = fluid.Executor().run(main, feed={
            "f1": np.random.randn(2, 8, 8, 8).astype(np.float32),
            "f2": np.random.randn(2, 8, 4, 4).astype(np.float32),
            "img": np.zeros((2, 3, 64, 64), np.float32)},
            fetch_list=[locs, confs, boxes, vrs])
        assert r[0].shape[1] == r[2].shape[0]

    def test_switch_case_with_intermediate_expression(self):
        # temps created INSIDE a case must stay internal (review finding)
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            t = fluid.data("t", [1])
            half = fluid.layers.fill_constant([1], "float32", 0.5)
            base = fluid.layers.fill_constant([1], "float32", 3.0)
            out = fluid.layers.fill_constant([1], "float32", 0.0)
            with fluid.layers.Switch() as sw:
                with sw.case(t < half):
                    fluid.layers.assign(base * 2.0 + 1.0, output=out)
                with sw.default():
                    fluid.layers.assign(base - 1.0, output=out)
        exe = fluid.Executor()
        lo, = exe.run(main, feed={"t": np.array([0.1], np.float32)},
                      fetch_list=[out])
        hi, = exe.run(main, feed={"t": np.array([0.9], np.float32)},
                      fetch_list=[out])
        assert float(lo[0]) == 7.0 and float(hi[0]) == 2.0

    def test_switch_case_after_default_rejected(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            t = fluid.data("t", [1])
            half = fluid.layers.fill_constant([1], "float32", 0.5)
            out = fluid.layers.fill_constant([1], "float32", 0.0)
            sw = fluid.layers.Switch()
            with sw:
                with sw.default():
                    fluid.layers.assign(half, output=out)
                with pytest.raises(InvalidArgumentError,
                                   match="unreachable"):
                    sw.case(t < half)
                # give the block a valid ending
                sw._cases = [c for c in sw._cases]


class TestStepCounter:
    def test_autoincreased_step_counter_advances_per_run(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 2])
            step = fluid.layers.autoincreased_step_counter(begin=1)
        exe = fluid.Executor()
        feed = {"x": np.zeros((2, 2), np.float32)}
        vals = [int(exe.run(main, feed=feed, fetch_list=[step])[0])
                for _ in range(3)]
        assert vals == [1, 2, 3]

    def test_test_clone_freezes_buffers(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 2])
            step = fluid.layers.autoincreased_step_counter(begin=1)
        clone = main.clone(for_test=True)
        exe = fluid.Executor()
        feed = {"x": np.zeros((2, 2), np.float32)}
        v1 = int(exe.run(clone, feed=feed, fetch_list=[step])[0])
        v2 = int(exe.run(clone, feed=feed, fetch_list=[step])[0])
        assert v1 == v2 == 1  # frozen on the test clone


class TestDeformableConvBuilder:
    def test_dcn_v2_trains_in_graph_mode(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4, 8, 8])
            off = fluid.data("off", [-1, 18, 8, 8])
            msk = fluid.data("msk", [-1, 9, 8, 8])
            y = fluid.layers.deformable_conv(
                x, off, msk, num_filters=6, filter_size=3, padding=1)
            loss = fluid.layers.mean(y * y)
            fluid.optimizer.SGD(0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(2, 4, 8, 8).astype(np.float32),
                "off": (rng.randn(2, 18, 8, 8) * 0.1).astype(np.float32),
                "msk": np.ones((2, 9, 8, 8), np.float32)}
        first = last = None
        for _ in range(5):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            first = first if first is not None else float(v)
            last = float(v)
        assert last < first


class TestCellUnitBuilders:
    """gru_unit / lstm_unit (ref: operators/gru_unit_op, lstm_unit_op.h:64
    — gate order i, f(+forget_bias), o, g)."""

    def test_lstm_unit_matches_kernel_math(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            xl = fluid.data("xl", [-1, 6])
            hl = fluid.data("hl", [-1, 4])
            cl = fluid.data("cl", [-1, 4])
            h2, c2 = fluid.layers.lstm_unit(xl, hl, cl, forget_bias=1.0)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"xl": rng.randn(8, 6).astype(np.float32),
                "hl": rng.randn(8, 4).astype(np.float32),
                "cl": rng.randn(8, 4).astype(np.float32)}
        h2v, c2v = exe.run(main, feed=feed, fetch_list=[h2, c2])
        w = next(np.asarray(v) for k, v in main.scope.items()
                 if "lstm_unit" in k and np.asarray(v).ndim == 2)
        b = next((np.asarray(v) for k, v in main.scope.items()
                  if "lstm_unit" in k and np.asarray(v).ndim == 1), 0)
        z = np.concatenate([feed["xl"], feed["hl"]], -1) @ w + b
        sig = lambda t: 1 / (1 + np.exp(-t))  # noqa: E731
        i_, f_ = sig(z[:, :4]), sig(z[:, 4:8] + 1.0)
        o_, g_ = sig(z[:, 8:12]), np.tanh(z[:, 12:])
        c_exp = f_ * feed["cl"] + i_ * g_
        np.testing.assert_allclose(c2v, c_exp, atol=1e-4)
        np.testing.assert_allclose(h2v, o_ * np.tanh(c_exp), atol=1e-4)

    def test_both_units_train(self):
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            xg = fluid.data("xg", [-1, 12])
            hg = fluid.data("hg", [-1, 4])
            nh, rhp, gate = fluid.layers.gru_unit(xg, hg, size=12)
            xl = fluid.data("xl", [-1, 6])
            hl = fluid.data("hl", [-1, 4])
            cl = fluid.data("cl", [-1, 4])
            h2, c2 = fluid.layers.lstm_unit(xl, hl, cl)
            y = fluid.data("y", [-1, 4])
            loss = (fluid.layers.mean(
                fluid.layers.square_error_cost(nh, y))
                + fluid.layers.mean(
                    fluid.layers.square_error_cost(h2, y)))
            fluid.optimizer.AdamOptimizer(0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"xg": rng.randn(8, 12).astype(np.float32),
                "hg": rng.randn(8, 4).astype(np.float32),
                "xl": rng.randn(8, 6).astype(np.float32),
                "hl": rng.randn(8, 4).astype(np.float32),
                "cl": rng.randn(8, 4).astype(np.float32),
                "y": np.tanh(rng.randn(8, 4)).astype(np.float32)}
        first = last = None
        for _ in range(30):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            first = first if first is not None else float(v)
            last = float(v)
        assert last < first * 0.7


class TestDynamicRNNBuilders:
    """dynamic_lstm / dynamic_lstmp / dynamic_gru (ref: fluid/layers/
    rnn.py over operators/lstm_op, lstmp_op, gru_op) — dense-padded
    forms of the LoD fused RNNs, gate layout {c, i, f, o} with peepholes
    appended to the bias."""

    H = 4

    def test_dynamic_lstm_matches_peephole_formula(self):
        H = self.H
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            xl = fluid.data("xl", [-1, 5, 4 * H])
            hid, cell = fluid.layers.dynamic_lstm(xl, size=4 * H)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"xl": rng.randn(8, 5, 4 * H).astype(np.float32)}
        hv, cv = exe.run(main, feed=feed, fetch_list=[hid, cell])
        w = next(np.asarray(v) for k, v in main.scope.items()
                 if np.asarray(v).shape == (H, 4 * H))
        b = next(np.asarray(v) for k, v in main.scope.items()
                 if np.asarray(v).ndim == 2
                 and np.asarray(v).shape[0] == 1)[0]
        sig = lambda t: 1 / (1 + np.exp(-t))  # noqa: E731
        # step 0 (h0 = c0 = 0): i/f peepholes vanish; W_oc peeps c_t
        z0 = feed["xl"][:, 0] + b[:4 * H]
        zc, zi, zf, zo = (z0[:, :H], z0[:, H:2 * H], z0[:, 2 * H:3 * H],
                          z0[:, 3 * H:])
        c0 = sig(zi) * np.tanh(zc)
        h0 = sig(zo + b[6 * H:7 * H] * c0) * np.tanh(c0)
        np.testing.assert_allclose(cv[:, 0], c0, atol=1e-4)
        np.testing.assert_allclose(hv[:, 0], h0, atol=1e-4)
        # step 1 uses the recurrence
        z1 = feed["xl"][:, 1] + h0 @ w + b[:4 * H]
        zc, zi, zf, zo = (z1[:, :H], z1[:, H:2 * H], z1[:, 2 * H:3 * H],
                          z1[:, 3 * H:])
        i1 = sig(zi + b[4 * H:5 * H] * c0)
        f1 = sig(zf + b[5 * H:6 * H] * c0)
        c1 = f1 * c0 + i1 * np.tanh(zc)
        h1 = sig(zo + b[6 * H:7 * H] * c1) * np.tanh(c1)
        np.testing.assert_allclose(cv[:, 1], c1, atol=1e-4)
        np.testing.assert_allclose(hv[:, 1], h1, atol=1e-4)

    def test_dynamic_family_trains_and_reverse_runs(self):
        H = self.H
        main, startup = _programs()
        with fluid.program_guard(main, startup):
            xl = fluid.data("xl", [-1, 5, 4 * H])
            hid, cell = fluid.layers.dynamic_lstm(xl, size=4 * H,
                                                  is_reverse=True)
            xg = fluid.data("xg", [-1, 5, 3 * H])
            gh = fluid.layers.dynamic_gru(xg, size=H)
            xp = fluid.data("xp", [-1, 5, 4 * H])
            pr, pc = fluid.layers.dynamic_lstmp(xp, size=4 * H,
                                                proj_size=3)
            assert pr.shape[-1] == 3 and pc.shape[-1] == H
            y = fluid.data("y", [-1, H])
            loss = (fluid.layers.mean(
                fluid.layers.square_error_cost(hid[:, 0], y))
                + fluid.layers.mean(
                    fluid.layers.square_error_cost(gh[:, -1], y))
                + fluid.layers.mean(pr * pr) * 0.1)
            fluid.optimizer.AdamOptimizer(0.02).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"xl": rng.randn(8, 5, 4 * H).astype(np.float32),
                "xg": rng.randn(8, 5, 3 * H).astype(np.float32),
                "xp": rng.randn(8, 5, 4 * H).astype(np.float32),
                "y": np.tanh(rng.randn(8, H)).astype(np.float32)}
        first = last = None
        for _ in range(40):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            first = first if first is not None else float(v)
            last = float(v)
        assert last < first * 0.6

"""Sequence-parallel GPT integration: sep>1 attention matches the dense
sep=1 numerics, under both ring and Ulysses, standalone and through the
fleet strategy toggle.  (The kernel-level ring/Ulysses tests live in
test_attention.py; this file covers the MODEL integration VERDICT r1 called
an island.)"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _logits(net, ids):
    params = net.param_pytree()
    return np.asarray(nn.functional_call(net, params, ids, training=False))


@pytest.mark.parametrize("method", ["ring", "ulysses"])
def test_sp_forward_matches_dense(method):
    ids = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int32)

    paddle.seed(0)
    dense = GPTForCausalLM(gpt_tiny())
    ref = _logits(dense, ids)

    set_mesh(build_mesh(dp=2, sep=4))
    paddle.seed(0)
    sp = GPTForCausalLM(gpt_tiny(sequence_parallel=method))
    out = _logits(sp, ids)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_sp_train_step_matches_dense():
    ids = np.random.RandomState(0).randint(0, 128, (4, 16)).astype(np.int32)

    def losses(sequence_parallel, mesh_kw):
        set_mesh(build_mesh(**mesh_kw))
        paddle.seed(0)
        net = GPTForCausalLM(gpt_tiny(sequence_parallel=sequence_parallel))
        opt = popt.Adam(learning_rate=1e-2)
        m = paddle.Model(net)
        m.prepare(optimizer=opt, loss=net.loss)
        return [m.train_batch([ids], [ids])[0] for _ in range(3)]

    ref = losses(None, {})
    sp = losses("ring", dict(dp=2, sep=4))
    np.testing.assert_allclose(sp, ref, rtol=2e-4, atol=2e-5)


def test_sp_via_fleet_strategy():
    paddle.seed(0)
    strat = fleet.DistributedStrategy(
        dp_degree=2, sep_degree=2, tensor_parallel=True,
        tensor_parallel_configs={"tensor_parallel_degree": 2},
        sequence_parallel=True)
    fleet.init(is_collective=True, strategy=strat)
    net = GPTForCausalLM(gpt_tiny())
    opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
    model = paddle.Model(net)
    model.prepare(optimizer=opt, loss=net.loss)
    assert all(b.attn.sequence_parallel == "ring" for b in net.gpt.blocks)
    ids = np.random.RandomState(0).randint(0, 128, (4, 16)).astype(np.int32)
    loss, _ = model.train_batch([ids], [ids])
    assert np.isfinite(loss)


def test_sp_falls_back_on_custom_mask():
    """A custom attn_mask routes through the dense path (SP only supports
    the built-in causal mask) instead of silently mis-masking."""
    set_mesh(build_mesh(sep=4))
    paddle.seed(0)
    net = GPTForCausalLM(gpt_tiny(sequence_parallel="ring"))
    ids = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int32)
    mask = np.zeros((1, 1, 16, 16), np.float32)
    params = net.param_pytree()
    out_masked = nn.functional_call(net, params, ids, mask, training=False)
    assert np.isfinite(np.asarray(out_masked)).all()

"""paddle.jit surface + weight-averaging optimizers.

Reference capability: dygraph/jit.py to_static + TranslatedLayer
(dygraph_to_static ProgramTranslator:708), and fluid/optimizer.py
ExponentialMovingAverage:3443 / ModelAverage:3134 / Lookahead:4853.
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, optimizer as popt
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.optimizer import (
    ExponentialMovingAverage,
    Lookahead,
    ModelAverage,
)
from paddle_tpu.static import InputSpec


def _net():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


class TestToStatic:
    def test_layer_output_parity(self):
        net = _net()
        net.eval()
        static_net = jit.to_static(net)
        x = jnp.asarray(np.random.RandomState(0).randn(6, 4), jnp.float32)
        np.testing.assert_allclose(np.asarray(static_net(x)),
                                   np.asarray(net(x)), rtol=1e-6)

    def test_params_stay_live_through_training(self):
        """to_static must see updated weights (no baked constants)."""
        net = _net()
        static_net = jit.to_static(net)
        x = jnp.ones((2, 4))
        before = np.asarray(static_net(x))
        for _, p in net.named_parameters():
            p.value = p.value * 0.0
        after = np.asarray(static_net(x))
        assert not np.allclose(before, after)
        np.testing.assert_allclose(after, 0.0, atol=1e-6)

    def test_bn_buffers_update_eagerly(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 3), nn.BatchNorm1D(3))
        net.train()
        static_net = jit.to_static(net)
        bn = net[1]
        before = np.asarray(bn._mean.value).copy()
        static_net(jnp.asarray(
            np.random.RandomState(0).randn(8, 4), jnp.float32))
        assert not np.allclose(np.asarray(bn._mean.value), before)

    def test_pure_function(self):
        f = jit.to_static(lambda a, b: a * 2 + b)
        np.testing.assert_allclose(
            np.asarray(f(jnp.ones(3), jnp.ones(3))), 3.0)

    def test_decorator_with_spec_and_save_load(self, tmp_path):
        net = _net()
        wrapped = jit.to_static(net, input_spec=[InputSpec([None, 4],
                                                           "float32")])
        prefix = os.path.join(tmp_path, "m")
        jit.save(wrapped, prefix)
        loaded = jit.load(prefix)
        x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(loaded(x)),
                                   np.asarray(net.eval()(jnp.asarray(x))),
                                   rtol=1e-5, atol=1e-6)
        with pytest.raises(InvalidArgumentError, match="eval-only"):
            loaded.train()

    def test_save_without_spec_raises(self, tmp_path):
        with pytest.raises(InvalidArgumentError, match="input_spec"):
            jit.save(_net(), os.path.join(tmp_path, "m"))


class TestEMA:
    def test_shadow_tracks_and_bias_corrects(self):
        paddle.seed(0)
        lin = nn.Linear(1, 1, bias_attr=False)
        lin.weight.value = jnp.ones((1, 1))
        ema = ExponentialMovingAverage(lin, decay=0.5)
        # weights constant → corrected EMA equals the weight exactly
        for _ in range(3):
            ema.update()
        with ema.apply():
            np.testing.assert_allclose(np.asarray(lin.weight.value), 1.0,
                                       rtol=1e-6)
        np.testing.assert_allclose(np.asarray(lin.weight.value), 1.0)

    def test_apply_restores(self):
        net = _net()
        ema = ExponentialMovingAverage(net, decay=0.9)
        orig = {n: np.asarray(p.value).copy()
                for n, p in net.named_parameters()}
        ema.update()
        for _, p in net.named_parameters():
            p.value = p.value + 1.0
        with ema.apply():
            pass
        for n, p in net.named_parameters():
            np.testing.assert_allclose(np.asarray(p.value), orig[n] + 1.0)

    def test_ema_smooths_oscillation(self):
        lin = nn.Linear(1, 1, bias_attr=False)
        ema = ExponentialMovingAverage(lin, decay=0.99)
        for i in range(200):
            lin.weight.value = jnp.full((1, 1), 1.0 + (-1) ** i * 0.5)
            ema.update()
        with ema.apply():
            assert abs(float(lin.weight.value[0, 0]) - 1.0) < 0.1

    def test_apply_before_update_raises(self):
        ema = ExponentialMovingAverage(_net())
        with pytest.raises(InvalidArgumentError, match="update"):
            with ema.apply():
                pass


class TestModelAverage:
    def test_average_over_window(self):
        lin = nn.Linear(1, 1, bias_attr=False)
        ma = ModelAverage(lin, average_window_rate=1.0,
                          min_average_window=100, max_average_window=100)
        for v in (1.0, 2.0, 3.0, 4.0):
            lin.weight.value = jnp.full((1, 1), v)
            ma.update()
        with ma.apply():
            np.testing.assert_allclose(float(lin.weight.value[0, 0]), 2.5)
        np.testing.assert_allclose(float(lin.weight.value[0, 0]), 4.0)

    def test_window_rotation_bounds_memory_of_old_values(self):
        lin = nn.Linear(1, 1, bias_attr=False)
        ma = ModelAverage(lin, average_window_rate=0.5,
                          min_average_window=2, max_average_window=4)
        for i in range(40):
            lin.weight.value = jnp.full((1, 1), float(i))
            ma.update()
        with ma.apply():
            # early values must have rotated out: average is recent-ish
            assert float(lin.weight.value[0, 0]) > 25.0


class TestLookahead:
    def test_slow_fast_dynamics(self):
        """After k inner steps the params jump to the slow interpolation."""
        from paddle_tpu.nn.layer_base import Parameter

        w = Parameter(np.zeros(1, np.float32), name="w")
        inner = popt.SGD(learning_rate=1.0, parameters=[w])
        look = Lookahead(inner, alpha=0.5, k=2)
        params = {"w": jnp.zeros(1)}
        state = look.init(params)
        g = {"w": jnp.full(1, -1.0)}  # each fast step adds +1
        params, state = look.update(g, state, params)     # fast: 1
        np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
        params, state = look.update(g, state, params)     # fast: 2 → sync
        # slow = 0 + 0.5*(2-0) = 1; params snap to slow
        np.testing.assert_allclose(np.asarray(params["w"]), 1.0)
        params, state = look.update(g, state, params)     # fast: 2
        np.testing.assert_allclose(np.asarray(params["w"]), 2.0)

    def test_trains_under_model_and_jit(self):
        paddle.seed(0)
        net = _net()
        look = Lookahead(popt.Adam(learning_rate=1e-2), alpha=0.8, k=3)
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=look, loss=nn.CrossEntropyLoss())
        rng = np.random.RandomState(0)
        x = rng.randn(32, 4).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int32)
        losses = [m.train_batch([x], [y])[0] for _ in range(30)]
        assert losses[-1] < losses[0] * 0.7, losses

    def test_multi_precision_master_syncs(self):
        """The slow pull-back must land in the inner optimizer's f32 master
        slots too — otherwise step k+1 resumes the fast trajectory and
        Lookahead degenerates to the inner optimizer."""
        import jax.numpy as jnp
        from paddle_tpu.nn.layer_base import Parameter

        w = Parameter(np.zeros(1, np.float32), name="w")
        inner = popt.SGD(learning_rate=1.0, parameters=[w],
                         multi_precision=True)
        look = Lookahead(inner, alpha=0.5, k=2)
        params = {"w": jnp.zeros(1, jnp.bfloat16)}
        state = look.init(params)
        assert state["slow"]["w"].dtype == jnp.float32
        g = {"w": jnp.full(1, -1.0, jnp.bfloat16)}  # each fast step adds +1
        params, state = look.update(g, state, params)  # fast: 1
        params, state = look.update(g, state, params)  # fast: 2 → sync to 1
        np.testing.assert_allclose(
            np.asarray(params["w"], np.float32), 1.0)
        np.testing.assert_allclose(
            np.asarray(state["inner"]["slots"]["w"]["master"]), 1.0)
        # next step continues from the SYNCED point: 1 + 1 = 2, not 3
        params, state = look.update(g, state, params)
        np.testing.assert_allclose(
            np.asarray(params["w"], np.float32), 2.0)

    def test_validation(self):
        with pytest.raises(InvalidArgumentError):
            Lookahead(popt.SGD(), alpha=2.0)
        with pytest.raises(InvalidArgumentError):
            Lookahead(popt.SGD(), k=0)
        with pytest.raises(InvalidArgumentError):
            Lookahead("not an optimizer")

"""Book-style end-to-end convergence tests.

Reference test strategy (SURVEY §4): python/paddle/fluid/tests/book/ — 9
small train-to-threshold scripts (fit_a_line, recognize_digits, word2vec,
machine_translation…) asserting a loss/accuracy bar.  Same idea here,
wired through THIS framework's data path (text.datasets fixtures / native
ingest) and full Model API, on the 8-device CPU mesh where it adds
coverage.
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.io import TensorDataset


class TestFitALine:
    """book/test_fit_a_line.py: linear regression on UCI housing."""

    def test_converges(self, tmp_path):
        from paddle_tpu.text.datasets import UCIHousing

        # synthesize a housing.data in the real format: y = w·x + noise
        rng = np.random.RandomState(0)
        X = rng.rand(200, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        y = X @ w + 0.01 * rng.randn(200).astype(np.float32)
        table = np.concatenate([X, y[:, None]], axis=1)
        p = os.path.join(tmp_path, "housing.data")
        np.savetxt(p, table)

        train = UCIHousing(data_file=p, mode="train")
        feats = np.stack([s[0] for s in train])
        targets = np.stack([s[1] for s in train])

        paddle.seed(0)
        net = nn.Linear(13, 1)
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=nn.MSELoss())
        first = last = None
        for _ in range(60):
            loss, _ = model.train_batch([feats], [targets])
            first = loss if first is None else first
            last = loss
        assert last < first * 0.1, (first, last)


class TestWord2Vec:
    """book/test_word2vec.py: ngram LM over the imikolov pipeline."""

    def test_learns_deterministic_corpus(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text.datasets import Imikolov

        text = ("the cat sat on the mat\n" * 40).encode()
        tar_p = os.path.join(tmp_path, "simple-examples.tar.gz")
        with tarfile.open(tar_p, "w:gz") as t:
            for name in ("train", "valid"):
                info = tarfile.TarInfo(
                    f"./simple-examples/data/ptb.{name}.txt")
                info.size = len(text)
                t.addfile(info, io.BytesIO(text))

        ds = Imikolov(data_file=tar_p, data_type="NGRAM", window_size=3,
                      mode="train", min_word_freq=0)
        grams = np.stack([np.array(s) for s in ds])
        ctx, target = grams[:, :2].astype(np.int32), grams[:, 2].astype(np.int32)
        V = len(ds.word_idx)

        class NGram(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, 16)
                self.fc = nn.Linear(32, V)

            def forward(self, ctx):
                e = self.emb(ctx)  # [B, 2, 16]
                return self.fc(e.reshape(e.shape[0], -1))

        paddle.seed(0)
        net = NGram()
        model = paddle.Model(net, inputs=["ctx"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=nn.CrossEntropyLoss())
        for _ in range(40):
            loss, _ = model.train_batch([ctx], [target])
        # corpus is deterministic → the LM should be near-certain
        assert float(loss) < 0.2, float(loss)
        logits = model.predict_batch([ctx[:8]])
        acc = (np.argmax(np.asarray(logits), -1) == target[:8]).mean()
        assert acc == 1.0


class TestLabelSemanticRoles:
    """book/test_label_semantic_roles.py: sequence tagging trained through
    linear_chain_crf, decoded with Viterbi.  Ground truth comes from a
    Markov tag chain whose observations alias two tags — only the learned
    transitions can disambiguate, so CRF decoding must beat per-token
    argmax."""

    def test_crf_tagging_beats_pointwise(self):
        from paddle_tpu.nn import functional as F

        D, T, N, V = 4, 12, 256, 6
        rng = np.random.RandomState(0)
        # tags 0/1 emit observation 0; tags 2/3 emit their own symbol.
        # transitions: 0→{2}, 1→{3} strongly — context resolves the alias
        trans_true = np.array([
            [0.05, 0.05, 0.85, 0.05],
            [0.05, 0.05, 0.05, 0.85],
            [0.45, 0.45, 0.05, 0.05],
            [0.45, 0.45, 0.05, 0.05],
        ])
        obs_of_tag = {0: 0, 1: 0, 2: 2, 3: 3}
        tags = np.zeros((N, T), np.int32)
        toks = np.zeros((N, T), np.int32)
        for n in range(N):
            t0 = rng.randint(D)
            for t in range(T):
                tags[n, t] = t0
                toks[n, t] = obs_of_tag[t0]
                t0 = rng.choice(D, p=trans_true[t0])
        lengths = np.full(N, T, np.int32)

        class Tagger(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, 16)
                self.proj = nn.Linear(16, D)
                self.transition = self.create_parameter(
                    [D + 2, D],
                    default_initializer=nn.initializer.Normal(std=0.1))

            def forward(self, toks):
                # transition rides the outputs so the loss sees the traced
                # (differentiable) value, not the eager box
                return self.proj(self.emb(toks)), self.transition.value

        def crf_loss(emissions, transition, y, ln):
            return F.linear_chain_crf(emissions, transition, y, ln).mean()

        paddle.seed(0)
        net = Tagger()
        model = paddle.Model(net, inputs=["toks"], labels=["y", "len"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.1), loss=crf_loss)
        for _ in range(120):
            loss, _ = model.train_batch([toks], [tags, lengths])

        emissions, transition = net(jnp.asarray(toks))
        path = np.asarray(F.crf_decoding(emissions, transition,
                                         length=lengths))
        crf_acc = (path == tags).mean()
        pointwise_acc = (np.asarray(emissions).argmax(-1) == tags).mean()
        assert crf_acc > 0.85, crf_acc
        assert crf_acc > pointwise_acc + 0.05, (crf_acc, pointwise_acc)


class TestUnderstandSentiment:
    """book/test_understand_sentiment.py: text classification over the
    IMDB pipeline (synthetic corpus in the real aclImdb tar format)."""

    def test_classifies_synthetic_reviews(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text.datasets import Imdb

        rng = np.random.RandomState(0)
        pos_w = ["great", "love", "fun", "superb"]
        neg_w = ["bad", "awful", "boring", "dire"]
        fill = ["the", "movie", "a", "was", "plot"]

        def doc(words):
            toks = list(rng.choice(fill, 6)) + list(rng.choice(words, 3))
            rng.shuffle(toks)
            return " ".join(toks).encode()

        p = os.path.join(tmp_path, "aclImdb_v1.tar.gz")
        with tarfile.open(p, "w:gz") as t:
            for i in range(40):
                for sent, words in (("pos", pos_w), ("neg", neg_w)):
                    blob = doc(words)
                    info = tarfile.TarInfo(f"aclImdb/train/{sent}/{i}.txt")
                    info.size = len(blob)
                    t.addfile(info, io.BytesIO(blob))

        ds = Imdb(data_file=p, mode="train", cutoff=0)
        V = len(ds.word_idx)
        T = max(len(s[0]) for s in ds)
        X = np.zeros((len(ds), T), np.int64)
        y = np.zeros((len(ds),), np.int64)
        for i in range(len(ds)):
            toks, lab = ds[i]
            X[i, :len(toks)] = toks
            y[i] = int(lab)

        class SentimentNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V + 1, 16)
                self.fc = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                        nn.Linear(32, 2))

            def forward(self, x):
                return self.fc(self.emb(x).mean(axis=1))

        paddle.seed(0)
        net = SentimentNet()
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=nn.CrossEntropyLoss(),
                      metrics=[paddle.metric.Accuracy()])
        for _ in range(40):
            loss, metrics = model.train_batch([X], [y])
        assert metrics[0] > 0.95, metrics


class TestRecommenderSystem:
    """book/test_recommender_system.py: rating regression over the
    Movielens pipeline (two-tower embedding dot product)."""

    def test_learns_ratings(self, tmp_path):
        import zipfile

        from paddle_tpu.text.datasets import Movielens

        rng = np.random.RandomState(0)
        n_users, n_movies = 12, 12
        movies = "".join(f"{m}::Movie {m} (1999)::Drama\n"
                         for m in range(1, n_movies + 1))
        users = "".join(f"{u}::M::25::6::55117\n"
                        for u in range(1, n_users + 1))
        # structured preference: like iff same parity
        lines = []
        for u in range(1, n_users + 1):
            for m in rng.choice(range(1, n_movies + 1), 8, replace=False):
                r = 5 if (u + m) % 2 == 0 else 1
                lines.append(f"{u}::{m}::{r}::978300760\n")
        p = os.path.join(tmp_path, "ml-1m.zip")
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("ml-1m/movies.dat", movies)
            z.writestr("ml-1m/users.dat", users)
            z.writestr("ml-1m/ratings.dat", "".join(lines))

        ds = Movielens(data_file=p, mode="train", test_ratio=0.1,
                       rand_seed=0)
        uid = np.stack([s[0] for s in ds]).astype(np.int64).ravel()
        mid = np.stack([s[4] for s in ds]).astype(np.int64).ravel()
        rating = np.stack([s[-1] for s in ds]).astype(np.float32)

        class TwoTower(nn.Layer):
            def __init__(self):
                super().__init__()
                self.u = nn.Embedding(n_users + 1, 8)
                self.m = nn.Embedding(n_movies + 1, 8)

            def forward(self, uid, mid):
                return (self.u(uid) * self.m(mid)).sum(-1, keepdims=True)

        paddle.seed(0)
        net = TwoTower()
        model = paddle.Model(net, inputs=["uid", "mid"], labels=["r"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.1),
                      loss=nn.MSELoss())
        first = None
        for _ in range(80):
            loss, _ = model.train_batch([uid, mid], [rating])
            first = loss if first is None else first
        assert loss < first * 0.05, (first, loss)


class TestMachineTranslation:
    """book/test_machine_translation.py: seq2seq over the WMT16 pipeline
    (tiny copy task: source sentence → identical target sentence)."""

    def test_copy_task_converges(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text.datasets import WMT16

        rng = np.random.RandomState(0)
        words = ["w%d" % i for i in range(12)]
        lines = []
        for _ in range(64):
            sent = " ".join(rng.choice(words, size=5))
            lines.append(f"{sent}\t{sent}")
        blob = ("\n".join(lines) + "\n").encode()
        tar_p = os.path.join(tmp_path, "wmt16.tar.gz")
        with tarfile.open(tar_p, "w:gz") as t:
            for name in ("train", "val"):
                info = tarfile.TarInfo(f"wmt16/{name}")
                info.size = len(blob)
                t.addfile(info, io.BytesIO(blob))

        ds = WMT16(data_file=tar_p, mode="train", src_dict_size=20,
                   trg_dict_size=20, lang="en")
        src = np.stack([s[0] for s in ds]).astype(np.int32)   # [N, 7]
        trg_in = np.stack([s[1] for s in ds]).astype(np.int32)
        trg_next = np.stack([s[2] for s in ds]).astype(np.int32)
        V = len(ds.src_dict)

        class Seq2Seq(nn.Layer):
            """Tiny encoder-decoder with attention-free context."""

            def __init__(self):
                super().__init__()
                self.src_emb = nn.Embedding(V, 24)
                self.trg_emb = nn.Embedding(V, 24)
                self.proj = nn.Sequential(
                    nn.Linear(48, 64), nn.GELU(), nn.Linear(64, V))

            def forward(self, src, trg_in):
                ctx = self.src_emb(src).mean(axis=1, keepdims=True)  # [B,1,24]
                d = self.trg_emb(trg_in)                             # [B,T,24]
                ctx = jnp.broadcast_to(ctx, d.shape)
                return self.proj(jnp.concatenate([d, ctx], axis=-1))

            def loss(self, logits, labels):
                import jax

                logp = jax.nn.log_softmax(logits, axis=-1)
                picked = jnp.take_along_axis(
                    logp, jnp.asarray(labels)[..., None].astype(jnp.int32),
                    axis=-1)
                return -picked.mean()

        paddle.seed(0)
        net = Seq2Seq()
        model = paddle.Model(net, inputs=["src", "trg"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=net.loss)
        first = None
        for _ in range(150):
            loss, _ = model.train_batch([src, trg_in], [trg_next])
            first = loss if first is None else first
        assert loss < first * 0.3, (first, loss)

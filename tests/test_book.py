"""Book-style end-to-end convergence tests.

Reference test strategy (SURVEY §4): python/paddle/fluid/tests/book/ — 9
small train-to-threshold scripts (fit_a_line, recognize_digits, word2vec,
machine_translation…) asserting a loss/accuracy bar.  Same idea here,
wired through THIS framework's data path (text.datasets fixtures / native
ingest) and full Model API, on the 8-device CPU mesh where it adds
coverage.
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.io import TensorDataset


class TestFitALine:
    """book/test_fit_a_line.py: linear regression on UCI housing."""

    def test_converges(self, tmp_path):
        from paddle_tpu.text.datasets import UCIHousing

        # synthesize a housing.data in the real format: y = w·x + noise
        rng = np.random.RandomState(0)
        X = rng.rand(200, 13).astype(np.float32)
        w = rng.randn(13).astype(np.float32)
        y = X @ w + 0.01 * rng.randn(200).astype(np.float32)
        table = np.concatenate([X, y[:, None]], axis=1)
        p = os.path.join(tmp_path, "housing.data")
        np.savetxt(p, table)

        train = UCIHousing(data_file=p, mode="train")
        feats = np.stack([s[0] for s in train])
        targets = np.stack([s[1] for s in train])

        paddle.seed(0)
        net = nn.Linear(13, 1)
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=nn.MSELoss())
        first = last = None
        for _ in range(60):
            loss, _ = model.train_batch([feats], [targets])
            first = loss if first is None else first
            last = loss
        assert last < first * 0.1, (first, last)


class TestWord2Vec:
    """book/test_word2vec.py: ngram LM over the imikolov pipeline."""

    def test_learns_deterministic_corpus(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text.datasets import Imikolov

        text = ("the cat sat on the mat\n" * 40).encode()
        tar_p = os.path.join(tmp_path, "simple-examples.tar.gz")
        with tarfile.open(tar_p, "w:gz") as t:
            for name in ("train", "valid"):
                info = tarfile.TarInfo(
                    f"./simple-examples/data/ptb.{name}.txt")
                info.size = len(text)
                t.addfile(info, io.BytesIO(text))

        ds = Imikolov(data_file=tar_p, data_type="NGRAM", window_size=3,
                      mode="train", min_word_freq=0)
        grams = np.stack([np.array(s) for s in ds])
        ctx, target = grams[:, :2].astype(np.int32), grams[:, 2].astype(np.int32)
        V = len(ds.word_idx)

        class NGram(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, 16)
                self.fc = nn.Linear(32, V)

            def forward(self, ctx):
                e = self.emb(ctx)  # [B, 2, 16]
                return self.fc(e.reshape(e.shape[0], -1))

        paddle.seed(0)
        net = NGram()
        model = paddle.Model(net, inputs=["ctx"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=nn.CrossEntropyLoss())
        for _ in range(40):
            loss, _ = model.train_batch([ctx], [target])
        # corpus is deterministic → the LM should be near-certain
        assert float(loss) < 0.2, float(loss)
        logits = model.predict_batch([ctx[:8]])
        acc = (np.argmax(np.asarray(logits), -1) == target[:8]).mean()
        assert acc == 1.0


class TestMachineTranslation:
    """book/test_machine_translation.py: seq2seq over the WMT16 pipeline
    (tiny copy task: source sentence → identical target sentence)."""

    def test_copy_task_converges(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.text.datasets import WMT16

        rng = np.random.RandomState(0)
        words = ["w%d" % i for i in range(12)]
        lines = []
        for _ in range(64):
            sent = " ".join(rng.choice(words, size=5))
            lines.append(f"{sent}\t{sent}")
        blob = ("\n".join(lines) + "\n").encode()
        tar_p = os.path.join(tmp_path, "wmt16.tar.gz")
        with tarfile.open(tar_p, "w:gz") as t:
            for name in ("train", "val"):
                info = tarfile.TarInfo(f"wmt16/{name}")
                info.size = len(blob)
                t.addfile(info, io.BytesIO(blob))

        ds = WMT16(data_file=tar_p, mode="train", src_dict_size=20,
                   trg_dict_size=20, lang="en")
        src = np.stack([s[0] for s in ds]).astype(np.int32)   # [N, 7]
        trg_in = np.stack([s[1] for s in ds]).astype(np.int32)
        trg_next = np.stack([s[2] for s in ds]).astype(np.int32)
        V = len(ds.src_dict)

        class Seq2Seq(nn.Layer):
            """Tiny encoder-decoder with attention-free context."""

            def __init__(self):
                super().__init__()
                self.src_emb = nn.Embedding(V, 24)
                self.trg_emb = nn.Embedding(V, 24)
                self.proj = nn.Sequential(
                    nn.Linear(48, 64), nn.GELU(), nn.Linear(64, V))

            def forward(self, src, trg_in):
                ctx = self.src_emb(src).mean(axis=1, keepdims=True)  # [B,1,24]
                d = self.trg_emb(trg_in)                             # [B,T,24]
                ctx = jnp.broadcast_to(ctx, d.shape)
                return self.proj(jnp.concatenate([d, ctx], axis=-1))

            def loss(self, logits, labels):
                import jax

                logp = jax.nn.log_softmax(logits, axis=-1)
                picked = jnp.take_along_axis(
                    logp, jnp.asarray(labels)[..., None].astype(jnp.int32),
                    axis=-1)
                return -picked.mean()

        paddle.seed(0)
        net = Seq2Seq()
        model = paddle.Model(net, inputs=["src", "trg"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.05),
                      loss=net.loss)
        first = None
        for _ in range(150):
            loss, _ = model.train_batch([src, trg_in], [trg_next])
            first = loss if first is None else first
        assert loss < first * 0.3, (first, loss)

"""Concurrency analysis (C10xx) — static lock-order/race lint plus the
runtime lock-order sanitizer.

One deliberately-broken fixture per static rule (C1001/C1002/C1003/C1006),
each paired with a near-identical clean fixture that must stay silent; the
runtime half (C1004/C1005) is exercised with real threads but an injected
clock and zero sleeps; and the same zero-false-positive contract as the
model-zoo sweep: the whole ``paddle_tpu`` tree must come back clean.
"""
import os
import textwrap
import threading

import pytest

from paddle_tpu.analysis import (RetraceMonitor, check_concurrency_paths,
                                 check_concurrency_source)
from paddle_tpu.analysis.runner import main as analysis_main
from paddle_tpu.framework import locking
from paddle_tpu.framework.locking import (OrderedCondition, OrderedLock,
                                          OrderedRLock)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check(src):
    return check_concurrency_source(textwrap.dedent(src), "fixture.py")


def _rules(diags):
    return [d.rule for d in diags]


def _count(diags, rule):
    return sum(1 for d in diags if d.rule == rule)


# -- C1001: lock-order inversion ---------------------------------------------
class TestC1001LockOrderInversion:
    ABBA = """
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """

    def test_abba_fires(self):
        diags = _check(self.ABBA)
        assert _count(diags, "C1001") == 1
        (d,) = [d for d in diags if d.rule == "C1001"]
        assert "_a" in d.message and "_b" in d.message

    def test_consistent_order_is_silent(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """)
        assert _count(diags, "C1001") == 0

    def test_non_reentrant_self_nest_fires(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()

                def deep(self):
                    with self._a:
                        with self._a:
                            pass
            """)
        assert _count(diags, "C1001") == 1

    def test_rlock_self_nest_is_silent(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.RLock()

                def deep(self):
                    with self._a:
                        with self._a:
                            pass
            """)
        assert _count(diags, "C1001") == 0

    def test_suppression_mark_silences(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        # lock-order: two() only runs at shutdown
                        with self._a:
                            pass
            """)
        assert _count(diags, "C1001") == 0


# -- C1002: lock held across a blocking call ---------------------------------
class TestC1002BlockingUnderLock:
    def test_sleep_under_lock_fires(self):
        diags = _check("""
            import threading
            import time

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def tick(self):
                    with self._lock:
                        time.sleep(0.1)
            """)
        assert _count(diags, "C1002") == 1

    def test_sleep_outside_lock_is_silent(self):
        diags = _check("""
            import threading
            import time

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def tick(self):
                    with self._lock:
                        x = 1
                    time.sleep(0.1)
            """)
        assert _count(diags, "C1002") == 0

    def test_blocking_in_called_helper_fires_at_caller(self):
        # one-level self-call propagation: the blocking call is inside the
        # helper, the lock is held at the caller
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def _drain(self):
                    self.result.block_until_ready()

                def step(self):
                    with self._lock:
                        self._drain()
            """)
        assert _count(diags, "C1002") == 1


# -- C1003: unguarded cross-thread writes ------------------------------------
class TestC1003UnguardedSharedWrite:
    def test_thread_plus_caller_write_fires(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.value = 0
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    self.value = 1

                def set(self, v):
                    self.value = v
            """)
        assert _count(diags, "C1003") == 1
        (d,) = [d for d in diags if d.rule == "C1003"]
        assert "value" in d.message

    def test_guarded_writes_are_silent(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.value = 0
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    with self._lock:
                        self.value = 1

                def set(self, v):
                    with self._lock:
                        self.value = v
            """)
        assert _count(diags, "C1003") == 0

    def test_single_thread_attribute_is_silent(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._t = threading.Thread(target=self._loop)
                    self.value = 0

                def _loop(self):
                    self.value = 1
            """)
        assert _count(diags, "C1003") == 0

    def test_annotated_handoff_is_silent(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self.err = None
                    self._t = threading.Thread(target=self._loop)

                def _loop(self):
                    self.err = RuntimeError("boom")

                def close(self):
                    self._t.join()
                    # lock-order: join() above is the synchronization edge
                    self.err = None
            """)
        assert _count(diags, "C1003") == 0


# -- C1006: Condition.wait outside a predicate loop --------------------------
class TestC1006BareWait:
    def test_bare_wait_fires(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._cv = threading.Condition()

                def take(self):
                    with self._cv:
                        self._cv.wait()
            """)
        assert _count(diags, "C1006") == 1

    def test_predicate_loop_is_silent(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._items = []

                def take(self):
                    with self._cv:
                        while not self._items:
                            self._cv.wait()
                        return self._items.pop()
            """)
        assert _count(diags, "C1006") == 0

    def test_wait_for_is_exempt(self):
        diags = _check("""
            import threading

            class S:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._items = []

                def take(self):
                    with self._cv:
                        self._cv.wait_for(lambda: self._items)
            """)
        assert _count(diags, "C1006") == 0


# -- runtime sanitizer (C1004/C1005) -----------------------------------------
class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture()
def clock():
    c = _FakeClock()
    locking.enable(clock=c)
    locking.reset()
    yield c
    locking.disable()


class TestRuntimeSanitizer:
    def test_two_thread_abba_records_c1004(self, clock):
        a = OrderedLock("test.A")
        b = OrderedLock("test.B")
        errs = []

        def order_ab():
            with a:
                with b:
                    pass

        def order_ba():
            try:
                with b:
                    with a:  # closes B -> A -> B: recorded, not deadlocked
                        pass
            except Exception as e:  # pragma: no cover
                errs.append(e)

        # sequential threads with joins: the first teaches the A -> B
        # edge, the second inverts it; no sleeps, no real contention
        t1 = threading.Thread(target=order_ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=order_ba)
        t2.start()
        t2.join()

        assert not errs
        st = locking.stats()
        assert st["enabled"] and st["cycles"] == 1
        (v,) = [v for v in locking.violations() if v["rule"] == "C1004"]
        assert "test.A" in v["message"] and "test.B" in v["message"]

    def test_consistent_order_no_cycle(self, clock):
        a = OrderedLock("test.A")
        b = OrderedLock("test.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        st = locking.stats()
        assert st["cycles"] == 0 and st["acquires"] == 6
        assert st["edges"] == 1  # A -> B, deduped

    def test_long_hold_records_c1005(self, clock):
        lk = OrderedLock("test.slow")
        with lk:
            clock.t += 1.0  # 1000ms > default FLAGS_lock_hold_warn_ms=500
        st = locking.stats()
        assert st["long_holds"] == 1
        (v,) = [v for v in locking.violations() if v["rule"] == "C1005"]
        assert "test.slow" in v["message"]

    def test_warn_false_opts_out_of_c1005(self, clock):
        lk = OrderedLock("test.slow-ok", warn=False)
        with lk:
            clock.t += 1.0
        assert locking.stats()["long_holds"] == 0

    def test_rlock_reentry_is_edge_free(self, clock):
        lk = OrderedRLock("test.re")
        with lk:
            with lk:
                pass
        st = locking.stats()
        assert st["cycles"] == 0 and st["edges"] == 0

    def test_condition_wait_excluded_from_hold(self, clock):
        cv = OrderedCondition(name="test.cv")
        done = []

        def waiter():
            with cv:
                while not done:
                    cv.wait(timeout=60)

        t = threading.Thread(target=waiter)
        with cv:
            clock.t += 0.1  # pre-wait segment, under the warn limit
            t.start()
        # the waiter parks inside wait(); wall time there must not count
        with cv:
            done.append(True)
            cv.notify_all()
        t.join(60)
        assert not t.is_alive()
        assert locking.stats()["long_holds"] == 0

    def test_violation_surfaces_through_retrace_monitor(self, clock):
        with RetraceMonitor() as mon:
            a = OrderedLock("test.mon-A")
            b = OrderedLock("test.mon-B")

            def one():
                with a:
                    with b:
                        pass

            def two():
                with b:
                    with a:
                        pass

            for fn in (one, two):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
            assert mon.concurrency_stats("test.mon-A")["last_rule"] == "C1004"
        diags = [d for d in mon.diagnostics() if d.rule == "C1004"]
        assert len(diags) == 1
        assert "test.mon-A" in diags[0].message


class TestSanitizerOffPath:
    def test_disabled_stats_and_plain_delegation(self):
        assert not locking.active()
        st = locking.stats()
        assert st == {"enabled": False, "acquires": 0, "edges": 0,
                      "cycles": 0, "long_holds": 0}
        assert locking.violations() == []
        lk = OrderedLock("test.off")
        assert lk.acquire()
        assert lk.locked()
        lk.release()
        with lk:
            pass  # context manager path also delegates straight through

    def test_enable_disable_roundtrip(self):
        locking.enable()
        try:
            assert locking.active()
            locking.enable()  # idempotent
            with OrderedLock("test.round"):
                pass
            assert locking.stats()["acquires"] == 1
        finally:
            locking.disable()
        assert not locking.active()


# -- zero-false-positive sweep over the framework's own source ---------------
class TestZeroFalsePositives:
    def test_package_tree_is_clean(self):
        diags = check_concurrency_paths([os.path.join(REPO, "paddle_tpu")])
        assert diags == [], "\n".join(
            f"{d.rule} {d.location.file}:{d.location.line} {d.message}"
            for d in diags)

    def test_cli_sweep_exits_clean(self, capsys):
        rc = analysis_main(["--concurrency",
                            os.path.join(REPO, "paddle_tpu")])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "no findings" in out

"""static/jit/utils/incubate parity surface (round-2 audit closure)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, static, jit, utils
from paddle_tpu.framework.errors import UnimplementedError


class TestStatic:
    def test_data_returns_input_spec(self):
        spec = static.data("x", [None, 8], "float32")
        assert isinstance(spec, static.InputSpec)
        assert spec.name == "x" and spec.shape == (None, 8)

    def test_print_passthrough(self, capsys):
        x = jnp.asarray([1.0, 2.0])
        out = static.Print(x, message="dbg")
        np.testing.assert_array_equal(np.asarray(out), [1.0, 2.0])
        jax.effects_barrier()
        assert "dbg" in capsys.readouterr().out

    def test_py_func_under_jit(self):
        def host_twice(a):
            return np.asarray(a) * 2  # runs on host

        spec = static.InputSpec([3], "float32")

        @jax.jit
        def f(x):
            return static.py_func(host_twice, x, spec)

        np.testing.assert_allclose(
            np.asarray(f(jnp.asarray([1.0, 2.0, 3.0]))), [2.0, 4.0, 6.0])

    def test_py_func_backward_unimplemented(self):
        with pytest.raises(UnimplementedError):
            static.py_func(lambda x: x, jnp.zeros(2),
                           static.InputSpec([2]), backward_func=lambda g: g)

    def test_strategy_bags(self):
        bs = static.BuildStrategy()
        bs.fuse_all_reduce_ops = True
        assert bs.fuse_all_reduce_ops is True
        es = static.ExecutionStrategy()
        es.num_threads = 4
        assert es.num_threads == 4

    def test_program_machinery_real_and_residual_shims(self):
        # real now (static/graph.py): the 1.x build/run flow
        assert isinstance(static.Program(), static.Program)
        assert static.Executor() is not None
        assert isinstance(static.default_main_program(), static.Program)
        assert static.global_scope() is not None
        with static.program_guard(static.Program(), static.Program()):
            pass
        # still shims: program-rewrite passes jax.grad replaces
        for name in ["ParallelExecutor", "append_backward", "gradients"]:
            with pytest.raises(UnimplementedError):
                getattr(static, name)()

    def test_cpu_places_and_name_scope(self):
        places = static.cpu_places(2)
        assert len(places) == 2
        with static.name_scope("block"):
            pass
        with pytest.raises(UnimplementedError):
            static.cuda_places()

    def test_load_program_state(self, tmp_path):
        paddle.seed(0)
        lin = nn.Linear(3, 2)
        path = str(tmp_path / "m.pdparams")
        paddle.save(lin.state_dict(), path)
        state = static.load_program_state(str(tmp_path / "m"))
        assert "weight" in state and state["weight"].shape == (3, 2)

    def test_load_program_state_sniffs_header_not_extension(self, tmp_path):
        # ADVICE r4: one of our own paddle.save artifacts under a
        # non-.pdparams name must load via header sniff, not be routed to
        # the reference-format importer by its extension.
        paddle.seed(0)
        lin = nn.Linear(3, 2)
        path = str(tmp_path / "ckpt.bin")
        paddle.save(lin.state_dict(), path)
        state = static.load_program_state(path)
        assert "weight" in state and state["weight"].shape == (3, 2)

    def test_load_program_state_reference_pickle_any_name(self, tmp_path):
        # a reference-Paddle 2.x pickled state dict under a non-.pdparams
        # name must still route to the importer (pickle marker, no magic)
        import pickle
        path = str(tmp_path / "ref_ckpt.bin")
        with open(path, "wb") as f:
            pickle.dump({"weight": np.zeros((3, 2), np.float32)}, f,
                        protocol=2)
        state = static.load_program_state(path)
        assert state["weight"].shape == (3, 2)

    def test_load_program_state_missing_file_names_right_path(self, tmp_path):
        with pytest.raises(FileNotFoundError) as ei:
            static.load_program_state(str(tmp_path / "absent.pdparams"))
        assert "absent.pdparams.pdparams" not in str(ei.value)

    def test_create_global_var(self):
        v = static.create_global_var([2, 2], 1.5, "float32")
        assert not v.trainable
        np.testing.assert_allclose(np.asarray(v.value), 1.5)

    def test_static_nn_builders_real(self):
        from paddle_tpu.static import nn as snn

        # real in graph mode; outside a program the error names the layer
        with pytest.raises(Exception) as ei:
            snn.fc(None, 10)
        assert "paddle.nn.Linear" in str(ei.value)
        assert callable(snn.create_parameter)  # the real one
        with pytest.raises(UnimplementedError):  # residual shim tier
            snn.sparse_embedding(None, None)

    def test_weight_norm_param_attr_points_at_hook(self):
        with pytest.raises(UnimplementedError) as ei:
            static.WeightNormParamAttr(dim=0)
        assert "weight_norm" in str(ei.value)


class TestJit:
    def test_program_translator_toggle(self):
        paddle.seed(1)
        lin = nn.Linear(4, 2)
        compiled = jit.to_static(lin)
        x = jnp.ones((2, 4), jnp.float32)
        want = np.asarray(compiled(x))
        pt = jit.ProgramTranslator.get_instance()
        assert pt is jit.ProgramTranslator()
        try:
            pt.enable(False)
            assert not pt.enable_to_static
            np.testing.assert_allclose(np.asarray(compiled(x)), want,
                                       atol=1e-6)
        finally:
            pt.enable(True)

    def test_traced_layer_roundtrip(self, tmp_path):
        paddle.seed(2)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        net.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(3, 4), jnp.float32)
        out, traced = jit.TracedLayer.trace(net, [x])
        np.testing.assert_allclose(np.asarray(traced(x)), np.asarray(out),
                                   atol=1e-6)
        path = str(tmp_path / "traced")
        traced.save_inference_model(path)
        loaded = jit.load(path)
        np.testing.assert_allclose(np.asarray(loaded(np.asarray(x))),
                                   np.asarray(out), atol=1e-5)

    def test_verbosity_noops(self):
        jit.set_code_level(50)
        jit.set_verbosity(3)


class TestUtils:
    def test_unique_name(self):
        from paddle_tpu.utils import unique_name

        a = unique_name.generate("fc")
        b = unique_name.generate("fc")
        assert a != b and a.startswith("fc_")
        with unique_name.guard():
            c = unique_name.generate("fc")
            assert c == "fc_0"
        with unique_name.guard("pre_"):
            assert unique_name.generate("fc").startswith("pre_fc_")

    def test_require_version(self):
        utils.require_version("0.0.1")  # dev build passes
        with pytest.raises(TypeError):
            utils.require_version(1)

    def test_download_local_and_missing(self, tmp_path):
        f = tmp_path / "w.bin"
        f.write_bytes(b"abc")
        assert utils.download.get_path_from_url(str(f)) == str(f)
        with pytest.raises(RuntimeError) as ei:
            utils.download.get_weights_path_from_url(
                "https://example.com/nope.pdparams")
        assert "no network egress" in str(ei.value)

    def test_profiler_driver(self):
        opts = utils.ProfilerOptions({"batch_range": [0, 2]})
        with utils.Profiler(options=opts) as prof:
            assert utils.get_profiler() is prof
            prof.record_step()
            prof.record_step()  # hits batch_range[1] → stop

    def test_op_checker_and_load_op_library(self):
        checker = utils.OpLastCheckpointChecker()
        assert checker.get_version("matmul") == 0
        assert checker.get_op_attrs("matmul") == []
        with pytest.raises(UnimplementedError):
            utils.load_op_library("custom.so")


class TestIncubateReader:
    def test_shards_round_robin(self, monkeypatch):
        from paddle_tpu.incubate.reader import distributed_batch_reader

        def batches():
            for i in range(6):
                yield i

        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        assert list(distributed_batch_reader(batches)()) == [1, 3, 5]
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        assert list(distributed_batch_reader(batches)()) == [0, 2, 4]

    def test_single_process_passthrough(self, monkeypatch):
        from paddle_tpu.incubate.reader import distributed_batch_reader

        monkeypatch.delenv("PADDLE_TRAINERS_NUM", raising=False)
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        assert list(distributed_batch_reader(lambda: iter([7, 8]))()) == [7, 8]


class TestToStaticControlFlowContract:
    """VERDICT r3 #4: the to_static answer for data-dependent Python
    control flow — the callable control-flow forms compile and match
    eager, and a raw Python `if tensor:` raises an ACTIONABLE error (ref:
    program_translator.py:708, whose AST pass this contract replaces)."""

    def test_data_dependent_branch_compiles_and_matches_eager(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import jit, nn
        import paddle_tpu.fluid as fluid

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.pos = nn.Linear(4, 4)
                self.neg = nn.Linear(4, 4)

            def forward(self, x):
                # book-style data-dependent branch (mean sign routes)
                return fluid.layers.cond(
                    x.mean() > 0,
                    lambda: self.pos(x),
                    lambda: self.neg(x) * 2.0)

        net = Net()
        compiled = jit.to_static(net)
        xp = paddle.to_tensor(np.full((2, 4), 0.5, np.float32))
        xn = paddle.to_tensor(np.full((2, 4), -0.5, np.float32))
        for x in (xp, xn):
            eager = net(x)
            static_out = compiled(x)
            np.testing.assert_allclose(np.asarray(eager),
                                       np.asarray(static_out), rtol=1e-6)

    def test_data_dependent_while_compiles(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import jit
        import paddle_tpu.fluid as fluid
        import jax.numpy as jnp

        @jit.to_static
        def halve_until_small(x):
            def cond_fn(v):
                return jnp.max(jnp.abs(v)) > 1.0

            def body(v):
                return v / 2.0

            (out,) = fluid.layers.while_loop(cond_fn, body, [x])
            return out

        x = paddle.to_tensor(np.asarray([16.0, 3.0], np.float32))
        out = np.asarray(halve_until_small(x))
        assert np.max(np.abs(out)) <= 1.0
        np.testing.assert_allclose(out, [1.0, 0.1875], rtol=1e-6)

    def test_raw_python_if_raises_actionable_error(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import jit
        from paddle_tpu.framework.errors import InvalidArgumentError

        @jit.to_static
        def bad(x):
            if x.mean() > 0:  # Python branch on a traced value
                return x
            return -x

        with pytest.raises(InvalidArgumentError, match="cond"):
            bad(paddle.to_tensor(np.ones((2, 2), np.float32)))

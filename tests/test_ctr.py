"""Sharded-embedding CTR path (the parameter-server replacement).

Reference capability: PS-mode CTR training — DistributeTranspiler
(transpiler/distribute_transpiler.py:256) sharding embedding tables across
pserver nodes (large_scale_kv.h:773).  Here the table shards over the
``model`` mesh axis and ZeRO shards the slots; these tests prove the
capability on the 8-device CPU mesh: the model trains under
model×sharding×data axes, the table is genuinely distributed, and the
sharded trajectory matches single-path training.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import metric as pmetric, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.models import WideDeep, wide_deep_tiny


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _click_data(n=64, fields=4, vocab=64, dense=4, seed=0):
    """Learnable synthetic CTR data: click iff field-0 id is small."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab, size=(n, fields)).astype(np.int32)
    x = rng.randn(n, dense).astype(np.float32)
    y = (ids[:, :1] < vocab // 2).astype(np.float32)
    return ids, x, y


def _train(mp, sharding, dp, steps=8, seed=0):
    fleet._initialized = False
    strategy = fleet.DistributedStrategy(
        dp_degree=dp,
        sharding=sharding > 1, sharding_degree=sharding,
        tensor_parallel=mp > 1,
        tensor_parallel_configs={"tensor_parallel_degree": mp},
    )
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    net = wide_deep_tiny()
    opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-2))
    model = paddle.Model(net, inputs=["sparse", "dense"], labels=["label"])
    model.prepare(optimizer=opt, loss=net.loss)
    ids, x, y = _click_data()
    losses = []
    for _ in range(steps):
        loss, _ = model.train_batch([ids, x], [y])
        losses.append(loss)
    return net, model, np.asarray(losses)


class TestWideDeep:
    def test_forward_shapes(self):
        paddle.seed(0)
        net = wide_deep_tiny()
        ids, x, _ = _click_data(n=8)
        out = net(jnp.asarray(ids), jnp.asarray(x))
        assert out.shape == (8, 1)

    def test_loss_matches_bce_oracle(self):
        paddle.seed(0)
        net = wide_deep_tiny()
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(16, 1), jnp.float32)
        labels = jnp.asarray((rng.uniform(size=(16, 1)) < 0.5), jnp.float32)
        got = float(net.loss(logits, labels))
        p = 1.0 / (1.0 + np.exp(-np.asarray(logits)))
        want = -np.mean(np.asarray(labels) * np.log(p)
                        + (1 - np.asarray(labels)) * np.log(1 - p))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_trains_single_path(self):
        _, _, losses = _train(mp=1, sharding=1, dp=8)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.9, f"no learning: {losses}"

    def test_table_sharded_under_mp(self):
        """The embedding table must actually shard over `model` — the PS
        property: no chip holds the whole table."""
        net, _, losses = _train(mp=2, sharding=2, dp=2, steps=2)
        w = net.embedding.weight.value
        assert not w.sharding.is_fully_replicated, "table not distributed"
        shard_rows = {s.data.shape[0] for s in w.addressable_shards}
        assert shard_rows == {w.shape[0] // 2}, shard_rows
        assert np.isfinite(losses).all()

    def test_sharded_trajectory_matches_dense(self):
        """mp=2 × zero=2 × dp=2 training == pure-dp training, step for step
        (the correctness bar PS-mode could never hit exactly)."""
        _, _, ref = _train(mp=1, sharding=1, dp=8, steps=5)
        _, _, got = _train(mp=2, sharding=2, dp=2, steps=5)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_auc_metric_improves(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            dp_degree=4, tensor_parallel=True,
            tensor_parallel_configs={"tensor_parallel_degree": 2})
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = wide_deep_tiny()
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-2))
        model = paddle.Model(net, inputs=["sparse", "dense"], labels=["label"])
        model.prepare(optimizer=opt, loss=net.loss)
        train_ids, train_x, train_y = _click_data(n=512, seed=1)
        for step in range(24):
            lo = (step * 64) % 512
            model.train_batch(
                [train_ids[lo:lo + 64], train_x[lo:lo + 64]],
                [train_y[lo:lo + 64]])
        ids, x, y = _click_data(seed=3)
        auc = pmetric.Auc()
        logits = model.predict_batch([ids, x])
        probs = np.asarray(net.predict_proba(jnp.asarray(logits)))[..., 0]
        preds = np.stack([1 - probs, probs], axis=1)
        auc.update(preds, y)
        assert auc.accumulate() > 0.7, f"AUC {auc.accumulate()}"

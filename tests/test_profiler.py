"""Profiler + debug-flag wiring.

Reference capability: platform/profiler.h:40-212 (RecordEvent + the
printed event table), fluid/profiler.py (profiler context), and
FLAGS_check_nan_inf (platform/flags.cc:44 gating the nan sweep of
framework/details/nan_inf_utils.h:33).
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu import profiler as prof
from paddle_tpu.framework.flags import set_flags


@pytest.fixture(autouse=True)
def clean_profiler():
    prof.reset_profiler()
    yield
    prof.reset_profiler()
    set_flags({"check_nan_inf": False, "benchmark": False})


class TestChromeTracing:
    def test_exports_spans_json(self, tmp_path):
        import json

        prof.start_profiler()
        with prof.RecordEvent("train_step"):
            with prof.RecordEvent("forward"):
                pass
        prof.stop_profiler(profile_path=None)
        path = str(tmp_path / "timeline.json")
        n = prof.export_chrome_tracing(path)
        assert n == 2
        data = json.load(open(path))
        names = {e["name"] for e in data["traceEvents"]}
        assert names == {"train_step", "forward"}
        ev = data["traceEvents"][0]
        assert ev["ph"] == "X" and ev["dur"] >= 0

    def test_spans_only_recorded_while_profiling(self, tmp_path):
        with prof.RecordEvent("outside"):
            pass
        n = prof.export_chrome_tracing(str(tmp_path / "t.json"))
        assert n == 0


class TestRecordEvent:
    def test_accumulates_stats(self):
        for _ in range(3):
            with prof.RecordEvent("fwd"):
                jnp.ones((32, 32)).sum().block_until_ready()
        with prof.RecordEvent("bwd"):
            pass
        table = prof.summary()
        assert "fwd" in table and "bwd" in table
        assert "Calls" in table
        # fwd ran 3 times
        fwd_row = [l for l in table.splitlines() if l.startswith("fwd")][0]
        assert fwd_row.split()[1] == "3"

    def test_decorator_form(self):
        @prof.RecordEvent("work")
        def work(x):
            return x + 1

        assert work(1) == 2
        assert "work" in prof.summary()

    def test_sorted_key(self):
        with prof.RecordEvent("a"):
            pass
        for _ in range(5):
            with prof.RecordEvent("b"):
                pass
        lines = prof.summary(sorted_key="calls").splitlines()
        assert lines[1].startswith("b")


class TestProfilerContext:
    def test_device_trace_written(self, tmp_path):
        d = os.path.join(tmp_path, "trace")
        with prof.profiler(log_dir=d):
            with prof.RecordEvent("traced_region"):
                jnp.ones((64, 64)).sum().block_until_ready()
        found = []
        for root, _, files in os.walk(d):
            found += files
        assert any(f.endswith(".xplane.pb") for f in found), found

    def test_profile_path_written(self, tmp_path):
        p = os.path.join(tmp_path, "prof.txt")
        with prof.profiler(profile_path=p):
            with prof.RecordEvent("ev"):
                pass
        with open(p) as f:
            assert "ev" in f.read()

    def test_reset(self):
        with prof.RecordEvent("x"):
            pass
        prof.reset_profiler()
        assert prof.summary() == ""


class TestCheckNanInf:
    def _model(self, lr):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=popt.SGD(learning_rate=lr),
                  loss=nn.CrossEntropyLoss())
        return m

    def test_flag_catches_divergence(self):
        set_flags({"check_nan_inf": True})
        m = self._model(lr=0.01)
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        x[0, 0] = np.nan  # poisoned batch → NaN loss and grads
        y = np.zeros((8,), np.int32)
        with pytest.raises(RuntimeError, match="check_nan_inf"):
            for _ in range(3):
                m.train_batch([x], [y])

    def test_flag_off_no_raise(self):
        m = self._model(lr=1e12)
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32) * 100
        y = np.zeros((8,), np.int32)
        for _ in range(5):
            m.train_batch([x], [y])  # silently diverges — old behavior

    def test_benchmark_flag_runs(self):
        set_flags({"benchmark": True})
        m = self._model(lr=0.01)
        x = np.zeros((8, 4), np.float32)
        y = np.zeros((8,), np.int32)
        loss, _ = m.train_batch([x], [y])
        assert np.isfinite(loss)


class TestLifecycle:
    def test_double_start_raises(self):
        prof.start_profiler()
        try:
            with pytest.raises(RuntimeError, match="already running"):
                prof.start_profiler()
        finally:
            prof.stop_profiler()

    def test_stop_without_start_is_noop(self):
        assert prof.stop_profiler() == ""

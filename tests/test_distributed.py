"""Distributed tests on the simulated 8-device CPU mesh (conftest.py) —
the TPU-native analogue of the reference's localhost-subprocess collective
tests (test_collective_base.py fakes 2 ranks on one GPU; we fake 8 chips on
one host).  Covers: user collectives, mesh construction, DP training parity
vs single-device, ZeRO state sharding, and tensor-parallel layers."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import io as pio, nn, optimizer as popt, metric as pmetric
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh


@pytest.fixture(autouse=True)
def reset_mesh():
    """Each test starts from the default all-data mesh."""
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


N = 8  # conftest forces 8 host devices


class TestMesh:
    def test_default_mesh_all_data(self):
        m = dist.get_mesh()
        assert m.shape["data"] == N
        assert m.shape["model"] == 1

    def test_hybrid_mesh_shapes(self):
        m = build_mesh(dp=2, mp=2, sharding=2)
        assert m.shape == {"pipe": 1, "data": 2, "sharding": 2, "sep": 1, "model": 2}

    def test_bad_degrees_raise(self):
        with pytest.raises(Exception, match="device count"):
            build_mesh(dp=3, mp=2)


class TestCollectives:
    def test_all_reduce_sum(self):
        x = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
        out = dist.all_reduce(x)
        np.testing.assert_allclose(np.asarray(out), np.full((N, 1), 28.0))

    def test_all_reduce_max_min(self):
        x = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
        np.testing.assert_allclose(dist.all_reduce(x, op=dist.ReduceOp.MAX), 7.0)
        np.testing.assert_allclose(dist.all_reduce(x, op=dist.ReduceOp.MIN), 0.0)

    def test_all_gather(self):
        x = jnp.arange(N * 2, dtype=jnp.float32).reshape(N, 2)
        outs = dist.all_gather(x)
        assert len(outs) == N
        np.testing.assert_allclose(outs[3], [6.0, 7.0])
        # paddle-style out-list form
        lst = []
        dist.all_gather(lst, x)
        assert len(lst) == N

    def test_reduce_to_dst(self):
        x = jnp.ones((N, 3))
        out = np.asarray(dist.reduce(x, dst=2))
        np.testing.assert_allclose(out[2], 8.0)
        np.testing.assert_allclose(out[0], 1.0)

    def test_broadcast(self):
        x = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
        out = np.asarray(dist.broadcast(x, src=5))
        np.testing.assert_allclose(out, 5.0)

    def test_scatter(self):
        parts = [jnp.full((2,), float(i)) for i in range(N)]
        out = np.asarray(dist.scatter(None, parts, src=0))
        for i in range(N):
            np.testing.assert_allclose(out[i], float(i))

    def test_alltoall(self):
        x = jnp.arange(N * N, dtype=jnp.float32).reshape(N, N, 1)
        outs = dist.alltoall(x)
        ref = np.asarray(x).reshape(N, N)
        for i in range(N):
            np.testing.assert_allclose(np.asarray(outs[i]).ravel(), ref[:, i])

    def test_barrier_runs(self):
        dist.barrier()

    def test_group_axis_on_hybrid_mesh(self):
        set_mesh(build_mesh(dp=4, mp=2))
        x = jnp.arange(2, dtype=jnp.float32).reshape(2, 1)
        out = dist.all_reduce(x, group="model")
        np.testing.assert_allclose(np.asarray(out), 1.0)

    def test_env(self):
        env = dist.ParallelEnv()
        assert env.world_size == N
        assert dist.get_rank() == 0


def _make_data(rng, n=256, d=16, classes=4):
    W = rng.randn(d, classes).astype(np.float32)
    X = rng.randn(n, d).astype(np.float32)
    y = np.argmax(X @ W, 1).astype(np.int64)
    return X, y


class MLP(nn.Layer):
    def __init__(self, d=16, classes=4, hidden=32):
        super().__init__()
        self.fc1 = nn.Linear(d, hidden)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(hidden, classes)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def _train(model_net, rng_seed, strategy=None, epochs=4, lr=0.05):
    rng = np.random.RandomState(rng_seed)
    X, y = _make_data(rng)
    paddle.seed(0)
    opt = popt.Momentum(learning_rate=lr, parameters=None)
    if strategy is not None:
        fleet.init(is_collective=True, strategy=strategy)
        opt = fleet.distributed_optimizer(opt)
    model = paddle.Model(model_net)
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                  metrics=[pmetric.Accuracy()])
    ds = pio.TensorDataset([X, y.reshape(-1, 1)])
    model.fit(ds, batch_size=64, epochs=epochs, verbose=0, shuffle=False)
    logs = model.evaluate(ds, batch_size=64, verbose=0)
    return model, logs


class TestDataParallelTraining:
    def test_dp_matches_single_device(self):
        paddle.seed(42)
        net_a = MLP()
        sd = {k: np.asarray(v) for k, v in net_a.state_dict().items()}

        _, logs_single = _train(net_a, rng_seed=7, strategy=None)

        net_b = MLP()
        net_b.set_state_dict(sd)
        _, logs_dp = _train(net_b, rng_seed=7,
                            strategy=fleet.DistributedStrategy())
        # identical data order + identical init ⇒ same trajectory
        np.testing.assert_allclose(logs_dp["loss"], logs_single["loss"],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(logs_dp["acc"]), float(logs_single["acc"]),
                                   rtol=1e-5)

    def test_dp_params_replicated(self):
        net = MLP()
        model, _ = _train(net, rng_seed=3, strategy=fleet.DistributedStrategy(),
                          epochs=1)
        p = next(iter(net.parameters())).value
        assert p.sharding.is_fully_replicated

    def test_zero_shards_optimizer_state(self):
        net = MLP()
        strat = fleet.DistributedStrategy(sharding=True)
        model, logs = _train(net, rng_seed=5, strategy=strat, epochs=2)
        state = model._opt_state
        # velocity slots must be sharded over the 'sharding' axis
        sharded = 0
        for pname, slots in state["slots"].items():
            for sname, leaf in slots.items():
                if not leaf.sharding.is_fully_replicated:
                    sharded += 1
        assert sharded > 0, "ZeRO: no optimizer slot ended up sharded"
        # params stay replicated for the forward
        p = next(iter(net.parameters())).value
        assert p.sharding.is_fully_replicated

    def test_zero_matches_plain_dp(self):
        paddle.seed(42)
        net_a = MLP()
        sd = {k: np.asarray(v) for k, v in net_a.state_dict().items()}
        _, logs_dp = _train(net_a, rng_seed=11, strategy=fleet.DistributedStrategy())

        fleet._initialized = False
        net_b = MLP()
        net_b.set_state_dict(sd)
        _, logs_z = _train(net_b, rng_seed=11,
                           strategy=fleet.DistributedStrategy(sharding=True))
        np.testing.assert_allclose(logs_z["loss"], logs_dp["loss"], rtol=1e-4,
                                   atol=1e-5)

    def test_data_parallel_wrapper(self):
        net = MLP()
        dp = paddle.DataParallel(net)
        x = jnp.ones((4, 16))
        out = dp(x)
        assert out.shape == (4, 4)
        assert dp.scale_loss(1.5) == 1.5
        assert next(iter(net.parameters())).value.sharding.is_fully_replicated


class TestTensorParallel:
    def _tp_mesh(self):
        strat = fleet.DistributedStrategy(tensor_parallel=True,
                                          tensor_parallel_configs={"tensor_parallel_degree": 2})
        fleet.init(is_collective=True, strategy=strat)
        return strat

    def test_column_row_pair_matches_dense(self, rng):
        self._tp_mesh()
        paddle.seed(1)
        col = dist.meta_parallel.ColumnParallelLinear(16, 32, gather_output=False)
        row = dist.meta_parallel.RowParallelLinear(32, 8, input_is_parallel=True)
        x = jnp.asarray(rng.randn(4, 16).astype(np.float32))

        # dense reference from the same weights
        W1, b1 = col.weight.numpy(), col.bias.numpy()
        W2, b2 = row.weight.numpy(), row.bias.numpy()
        ref = np.asarray(x) @ W1 + b1
        ref = ref @ W2 + b2

        plan = fleet.ShardingPlan(col, None, None)
        plan.place_network()
        fleet.ShardingPlan(row, None, None).place_network()
        assert not col.weight.value.sharding.is_fully_replicated

        @jax.jit
        def step(x):
            return row(col(x))

        out = step(x)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_vocab_parallel_embedding(self, rng):
        self._tp_mesh()
        emb = dist.meta_parallel.VocabParallelEmbedding(64, 16)
        fleet.ShardingPlan(emb, None, None).place_network()
        ids = jnp.asarray([[1, 5], [63, 0]])

        @jax.jit
        def step(ids):
            return emb(ids)

        out = step(ids)
        ref = emb.weight.numpy()[np.asarray(ids)]
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)

    def test_tp_training_e2e(self):
        strat = fleet.DistributedStrategy(
            tensor_parallel=True,
            tensor_parallel_configs={"tensor_parallel_degree": 2})
        fleet.init(is_collective=True, strategy=strat)
        paddle.seed(3)

        class TPMLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = dist.meta_parallel.ColumnParallelLinear(16, 32, gather_output=False)
                self.act = nn.ReLU()
                self.fc2 = dist.meta_parallel.RowParallelLinear(32, 4, input_is_parallel=True)

            def forward(self, x):
                return self.fc2(self.act(self.fc1(x)))

        rng = np.random.RandomState(0)
        X, y = _make_data(rng, n=128)
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=5e-3))
        model = paddle.Model(TPMLP())
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                      metrics=[pmetric.Accuracy()])
        ds = pio.TensorDataset([X, y.reshape(-1, 1)])
        model.fit(ds, batch_size=64, epochs=30, verbose=0)
        logs = model.evaluate(ds, batch_size=64, verbose=0)
        assert logs["acc"] > 0.8, logs
        # weights sharded over model axis through training
        assert not model.network.fc1.weight.value.sharding.is_fully_replicated


class TestFleetApi:
    def test_worker_info(self):
        fleet.init(is_collective=True)
        assert fleet.worker_num() == 1
        assert fleet.worker_index() == 0
        assert fleet.is_first_worker()
        fleet.barrier_worker()

    def test_ps_mode_rejected(self):
        with pytest.raises(Exception, match="parameter-server"):
            fleet.init(is_collective=False)

    def test_distributed_optimizer_requires_init(self):
        fleet._initialized = False
        with pytest.raises(Exception, match="fleet.init"):
            fleet.distributed_optimizer(popt.SGD())


class TestReviewRegressions:
    def test_partial_batch_dropped_in_fit(self):
        """100 samples / batch 64: partial batch can't shard over 8 devices —
        fit must drop it instead of crashing."""
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        rng = np.random.RandomState(0)
        X, y = _make_data(rng, n=100)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.01))
        model = paddle.Model(MLP())
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
        ds = pio.TensorDataset([X, y.reshape(-1, 1)])
        model.fit(ds, batch_size=64, epochs=1, verbose=0)  # no crash

    def test_shard_batch_indivisible_raises_clearly(self):
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        plan = fleet.ShardingPlan(MLP(), popt.SGD(), fleet.get_strategy())
        with pytest.raises(Exception, match="divisible"):
            plan.shard_batch((np.zeros((36, 16), np.float32),))

    def test_dp_plus_sharding_hybrid_mesh(self):
        strat = fleet.DistributedStrategy(dp_degree=2, sharding=True)
        mesh = fleet.init(is_collective=True, strategy=strat)
        assert mesh.shape["data"] == 2 and mesh.shape["sharding"] == 4

    def test_opt_state_born_sharded(self):
        """ZeRO slots must never materialize replicated (init under jit with
        sharded out_shardings)."""
        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy(sharding=True))
        net = MLP()
        opt = fleet.distributed_optimizer(popt.Momentum(learning_rate=0.1))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
        rng = np.random.RandomState(0)
        X, y = _make_data(rng, n=64)
        model.train_batch([X], [y.reshape(-1, 1)])
        sharded = [
            leaf for slots in model._opt_state["slots"].values()
            for leaf in slots.values()
            if not leaf.sharding.is_fully_replicated
        ]
        assert sharded

    def test_eval_under_fleet_shards_batch(self):
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        net = MLP()
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.01))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                      metrics=[pmetric.Accuracy()])
        rng = np.random.RandomState(0)
        X, y = _make_data(rng, n=128)
        logs = model.evaluate(pio.TensorDataset([X, y.reshape(-1, 1)]),
                              batch_size=64, verbose=0)
        assert "acc" in logs

    def test_launch_module_exists(self):
        import importlib
        mod = importlib.import_module("paddle_tpu.distributed.launch")
        assert hasattr(mod, "launch")

    def test_evaluate_predict_drop_partial_under_plan(self):
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        rng = np.random.RandomState(0)
        X, y = _make_data(rng, n=100)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.01))
        model = paddle.Model(MLP())
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                      metrics=[pmetric.Accuracy()])
        ds = pio.TensorDataset([X, y.reshape(-1, 1)])
        model.evaluate(ds, batch_size=64, verbose=0)   # partial batch dropped
        model.predict(pio.TensorDataset([X]), batch_size=64)

    def test_evaluate_zero_batches_warns(self):
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        rng = np.random.RandomState(0)
        X, y = _make_data(rng, n=16)  # < one 64-batch
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.01))
        model = paddle.Model(MLP())
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
        with pytest.warns(RuntimeWarning, match="zero batches"):
            model.evaluate(pio.TensorDataset([X, y.reshape(-1, 1)]),
                           batch_size=64, verbose=0)

    def test_all_reduce_follows_mesh_change(self):
        """jit cache must key on the mesh: same shapes, different mesh."""
        set_mesh(build_mesh(devices=jax.devices()[:4]))
        x4 = jnp.ones((4, 1))
        out4 = dist.all_reduce(x4)
        np.testing.assert_allclose(np.asarray(out4), 4.0)
        set_mesh(build_mesh(devices=jax.devices()[4:]))
        out4b = dist.all_reduce(x4)
        np.testing.assert_allclose(np.asarray(out4b), 4.0)
        assert {d.id for d in out4b.devices()} == {d.id for d in jax.devices()[4:]}

    def test_oversubscribed_sharding_clear_error(self):
        with pytest.raises(Exception, match="exceed"):
            fleet.init(is_collective=True,
                       strategy=fleet.DistributedStrategy(sharding=True, mp_degree=16))

    def test_failed_distributed_optimizer_keeps_no_strategy(self):
        fleet._initialized = False
        fleet._strategy = None
        with pytest.raises(Exception):
            fleet.distributed_optimizer(
                popt.SGD(), strategy=fleet.DistributedStrategy(sharding=True))
        assert fleet.get_strategy() is None

    def test_predict_returns_all_samples_under_plan(self):
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        rng = np.random.RandomState(0)
        X, _ = _make_data(rng, n=100)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.01))
        model = paddle.Model(MLP())
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
        out = model.predict(pio.TensorDataset([X]), batch_size=64,
                            stack_outputs=True)
        assert np.asarray(out).shape[0] == 100  # padded + sliced, not dropped


class TestSyncBatchNorm:
    """VERDICT weak #4: SyncBatchNorm must actually sync.

    Reference: operators/sync_batch_norm_op.cu (NCCL partial sums).  Two
    TPU regimes are asserted: under shard_map the moments pmean over the
    bound data axes (and genuinely differ from per-shard local BN); under
    GSPMD jit the sharded-batch mean is already global."""

    def _global_oracle(self, x):
        m = x.mean(axis=(0, 2, 3))
        v = x.var(axis=(0, 2, 3))
        return (x - m[None, :, None, None]) / np.sqrt(v[None, :, None, None] + 1e-5)

    def test_shard_map_syncs_and_differs_from_local(self):
        from jax.sharding import PartitionSpec as P

        paddle.seed(0)
        sbn = nn.SyncBatchNorm(3)
        bn = nn.BatchNorm2D(3)
        mesh = dist.get_mesh()  # all-data
        # per-shard distributions differ wildly → local stats != global
        rng = np.random.RandomState(0)
        x = rng.randn(16, 3, 2, 2).astype(np.float32)
        x += np.arange(16)[:, None, None, None]  # shard means differ

        def synced(xl):
            # stateful layers run functionally under transforms — the
            # buffer updates come back as values, never leak as tracers
            return nn.functional_call(
                sbn, sbn.param_pytree(), xl, return_buffers=True)

        def local(xl):
            return bn(xl)

        xs = jnp.asarray(x)
        got_sync, new_bufs = dist.collective.shard_map(
            synced, mesh, (P("data"),),
            (P("data"), {n: P() for n, _ in sbn.named_buffers()}))(xs)
        got_local = dist.collective.shard_map(
            local, mesh, (P("data"),), P("data"))(xs)
        want = self._global_oracle(x)
        np.testing.assert_allclose(np.asarray(got_sync), want,
                                   rtol=1e-4, atol=1e-4)
        assert not np.allclose(np.asarray(got_local), want, atol=1e-2), \
            "local BN accidentally matched global stats — test is vacuous"
        # running stats: sbn accumulated GLOBAL moments
        np.testing.assert_allclose(
            np.asarray(new_bufs["_mean"]),
            0.1 * x.mean(axis=(0, 2, 3)), rtol=1e-4, atol=1e-4)

    def test_gspmd_batch_mean_is_global(self):
        """Under the fleet plan (jit/GSPMD) the sharded-batch moments are
        global by construction — SyncBatchNorm == full-batch oracle."""
        fleet.init(is_collective=True, strategy=fleet.DistributedStrategy())
        paddle.seed(0)
        net = nn.SyncBatchNorm(3)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.0))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=opt,
                      loss=lambda out, y: jnp.asarray(out).mean() * 0.0)
        rng = np.random.RandomState(1)
        x = rng.randn(16, 3, 2, 2).astype(np.float32)
        x += np.arange(16)[:, None, None, None]
        model.train_batch([x], [np.zeros((16, 1), np.float32)])
        np.testing.assert_allclose(
            np.asarray(net._mean.value), 0.1 * x.mean(axis=(0, 2, 3)),
            rtol=1e-4, atol=1e-4)

    def test_explicit_unbound_axis_raises(self):
        paddle.seed(0)
        sbn = nn.SyncBatchNorm(3, axis_name="dp")
        x = jnp.ones((4, 3, 2, 2))
        with pytest.raises(Exception, match="not bound"):
            jax.jit(lambda xx: sbn(xx))(x)

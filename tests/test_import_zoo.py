"""Importer at zoo scale (VERDICT r4 missing #6).

Reference-format checkpoints carrying 1.x builder names
(``conv2d_0.w_0``, ``batch_norm_3.w_1`` … — the naming
python/paddle/fluid/unique_name.py + layers/nn.py produce for
python/paddle/vision/models/resnet.py-era models) must map onto
paddle_tpu's dotted 2.0 names even when dozens of parameters share a
shape: ResNet-50's stacked 3×3 convs and per-stage BN vectors, and a
transformer's identical blocks.  Disambiguation is structural — both
sides walk the same architecture, so (shape, role) groups zip in
creation/traversal order (framework/paddle_import.py adapt_state_dict).

The checkpoints are SYNTHESIZED with our own reference-format writer:
a trained model's state dict is renamed to 1.x builder names in
creation (interleaved per-layer) order, written with
save_reference_state, re-imported, and must reproduce logits exactly.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.paddle_export import save_reference_state
from paddle_tpu.framework.paddle_import import (
    adapt_state_dict, load_reference_state_dict)


def _creation_order_1x_names(net):
    """Rename a Layer's state dict to 1.x builder names in the CREATION
    order the reference emits: per layer, weight then bias then moments —
    `conv2d_i.w_0`, `batch_norm_j.{w_0,b_0,w_1,w_2}`, `fc_k.{w_0,b_0}`.
    Returns ({1x_name: array} in creation order, {1x_name: our_name})."""
    counters = {"conv2d": 0, "batch_norm": 0, "fc": 0, "embedding": 0,
                "layer_norm": 0}
    renamed, mapping = {}, {}

    def op_of(layer):
        k = type(layer).__name__.lower()
        if "conv" in k:
            return "conv2d"
        if "batchnorm" in k:
            return "batch_norm"
        if "layernorm" in k:
            return "layer_norm"
        if "linear" in k:
            return "fc"
        if "embedding" in k:
            return "embedding"
        return None

    for lname, layer in net.named_sublayers(include_self=True):
        op = op_of(layer)
        if op is None:
            continue
        params = dict(layer.named_parameters(include_sublayers=False))
        bufs = dict(layer.named_buffers(include_sublayers=False))
        if not params and not bufs:
            continue
        i = counters[op]
        counters[op] += 1
        for attr, role in (("weight", "w_0"), ("bias", "b_0"),
                           ("_mean", "w_1"), ("_variance", "w_2")):
            box = params.get(attr) if attr in params else bufs.get(attr)
            if box is None:
                continue
            old = f"{lname}.{attr}" if lname else attr
            new = f"{op}_{i}.{role}"
            renamed[new] = np.asarray(box.value)
            mapping[new] = old
        extra = (set(params) | set(bufs)) - {"weight", "bias", "_mean",
                                             "_variance"}
        assert not extra, f"unmapped attrs {extra} on {lname}"
    return renamed, mapping


def _roundtrip(net, net2, x, tmp_path, combined=True):
    want = np.asarray(net(x))
    renamed, _ = _creation_order_1x_names(net)
    n_total = len(net.state_dict())
    assert len(renamed) == n_total, (len(renamed), n_total)
    save_reference_state(renamed, str(tmp_path),
                         filename="params" if combined else None)
    sd = load_reference_state_dict(
        str(tmp_path), params_filename="params" if combined else None)
    mapped = adapt_state_dict(sd, net2)
    net2.set_state_dict(mapped)
    got = np.asarray(net2(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestResNet50Scale:
    def test_resnet50_1x_checkpoint_logits_parity(self, tmp_path):
        paddle.seed(0)
        net = paddle.vision.models.resnet50(num_classes=10)
        net.eval()
        paddle.seed(99)  # distinct init proves the load did the work
        net2 = paddle.vision.models.resnet50(num_classes=10)
        net2.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(
            2, 3, 64, 64).astype(np.float32))
        # sanity: the ambiguity is real — many same-shape params
        shapes = {}
        for n, v in net.state_dict().items():
            shapes.setdefault(tuple(np.shape(v)), []).append(n)
        assert max(len(v) for v in shapes.values()) > 10
        _roundtrip(net, net2, x, tmp_path, combined=True)


class TestBertScale:
    def test_bert_tiny_identical_blocks_parity(self, tmp_path):
        from paddle_tpu.models import bert_tiny
        from paddle_tpu.models.bert import BertModel

        paddle.seed(0)
        net = BertModel(bert_tiny(num_layers=4))
        net.eval()
        paddle.seed(7)
        net2 = BertModel(bert_tiny(num_layers=4))
        net2.eval()
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, 100, (2, 16)).astype(np.int32))

        want = jnp.asarray(net(ids)[0])
        renamed, _ = _creation_order_1x_names(net)
        if len(renamed) != len(net.state_dict()):
            pytest.skip("bert params not fully 1.x-nameable "
                        f"({len(renamed)}/{len(net.state_dict())})")
        save_reference_state(renamed, str(tmp_path), filename="params")
        sd = load_reference_state_dict(str(tmp_path),
                                       params_filename="params")
        mapped = adapt_state_dict(sd, net2)
        net2.set_state_dict(mapped)
        got = np.asarray(net2(ids)[0])
        np.testing.assert_allclose(got, np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


class TestStructuralMatcher:
    def test_role_disambiguates_same_shape_bn(self, tmp_path):
        # four (8,)-shaped entries per BN layer: scale/bias/mean/variance —
        # only the role suffix separates them
        paddle.seed(0)
        net = nn.Sequential(nn.Conv2D(3, 8, 3), nn.BatchNorm2D(8),
                            nn.Conv2D(8, 8, 3), nn.BatchNorm2D(8))
        net.eval()
        paddle.seed(5)
        net2 = nn.Sequential(nn.Conv2D(3, 8, 3), nn.BatchNorm2D(8),
                             nn.Conv2D(8, 8, 3), nn.BatchNorm2D(8))
        net2.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(
            1, 3, 12, 12).astype(np.float32))
        _roundtrip(net, net2, x, tmp_path, combined=False)

    def test_group_size_mismatch_raises(self):
        net = nn.Linear(4, 4)
        sd = {"fc_0.w_0": np.zeros((4, 4), np.float32),
              "fc_1.w_0": np.zeros((4, 4), np.float32),
              "fc_0.b_0": np.zeros((4,), np.float32)}
        with pytest.raises(Exception, match="targets vs"):
            adapt_state_dict(sd, net)

    def test_natural_sort_beats_alphabetical(self):
        # conv2d_10 must come AFTER conv2d_2 when no program order exists
        paddle.seed(0)
        blocks = nn.LayerList([nn.Linear(4, 4) for _ in range(12)])

        class Stack(nn.Layer):
            def __init__(self):
                super().__init__()
                self.blocks = blocks

            def forward(self, x):
                for b in self.blocks:
                    x = b(x)
                return x

        net = Stack()
        # alphabetically-sorted source dict (fc_10 < fc_2) with distinct
        # values per block
        src = {}
        for i, b in enumerate(blocks):
            src[f"fc_{i}.w_0"] = np.asarray(b.weight.value)
            src[f"fc_{i}.b_0"] = np.asarray(b.bias.value)
        src = {k: src[k] for k in sorted(src)}  # worst-case dict order
        mapped = adapt_state_dict(src, net)
        for i in range(12):
            np.testing.assert_array_equal(
                mapped[f"blocks.{i}.weight"], src[f"fc_{i}.w_0"],
                err_msg=f"block {i}")

"""Inference export / predictor round-trips.

Reference capability: save_inference_model (fluid/io.py:1164) +
AnalysisPredictor (inference/api/analysis_predictor.h:82).  Here: AOT
StableHLO export via jax.export (paddle_tpu/inference) — tests cover the
save→load→run round-trip, batch polymorphism, output parity with the live
Layer, Model.save(training=False), and error paths.
"""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.inference import (
    Config,
    Predictor,
    create_predictor,
    load_inference_model,
    save_inference_model,
)
from paddle_tpu.static import InputSpec
from paddle_tpu.vision.models import LeNet


def _mlp():
    paddle.seed(0)
    return nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


class TestSaveLoad:
    def test_round_trip_output_parity(self, tmp_path):
        net = _mlp()
        x = np.random.RandomState(0).randn(6, 8).astype(np.float32)
        want = np.asarray(net(jnp.asarray(x)))

        prefix = os.path.join(tmp_path, "mlp")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        assert os.path.exists(prefix + ".pdmodel")
        assert os.path.exists(prefix + ".pdiparams")

        pred = load_inference_model(prefix)
        (got,) = pred.run([x])
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_batch_polymorphic(self, tmp_path):
        net = _mlp()
        prefix = os.path.join(tmp_path, "mlp")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        pred = load_inference_model(prefix)
        for b in (1, 3, 17):
            (out,) = pred.run([np.zeros((b, 8), np.float32)])
            assert out.shape == (b, 4)

    def test_export_is_eval_mode(self, tmp_path):
        """Dropout must be OFF in the exported graph even if the layer was
        in train mode at save time (reference prunes to test program)."""
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.9))
        net.train()
        prefix = os.path.join(tmp_path, "drop")
        save_inference_model(prefix, net, [InputSpec([None, 4], "float32")],
                             platforms=("cpu",))
        assert net.training  # restored
        pred = load_inference_model(prefix)
        x = np.ones((5, 4), np.float32)
        a, b = pred.run([x])[0], pred.run([x])[0]
        np.testing.assert_array_equal(a, b)

    def test_weights_ride_separately(self, tmp_path):
        """Hot-swapping .pdiparams changes predictions without re-export."""
        net = _mlp()
        prefix = os.path.join(tmp_path, "mlp")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        (before,) = load_inference_model(prefix).run([x])
        # zero all weights, save params only (re-save over the same prefix)
        from paddle_tpu.framework import serialization

        state = serialization.load(prefix + ".pdiparams")
        state["params"] = {k: np.zeros_like(v)
                          for k, v in state["params"].items()}
        serialization.save(state, prefix + ".pdiparams")
        (after,) = load_inference_model(prefix).run([x])
        assert not np.allclose(before, after)
        np.testing.assert_allclose(after, 0.0, atol=1e-6)

    def test_conv_model_exports(self, tmp_path):
        net = LeNet()
        net.eval()
        prefix = os.path.join(tmp_path, "lenet")
        save_inference_model(prefix, net,
                             [InputSpec([None, 1, 28, 28], "float32")],
                             platforms=("cpu",))
        pred = load_inference_model(prefix)
        (out,) = pred.run([np.zeros((2, 1, 28, 28), np.float32)])
        assert out.shape == (2, 10)


class TestPredictorAPI:
    def test_config_create_predictor(self, tmp_path):
        net = _mlp()
        prefix = os.path.join(tmp_path, "m")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        cfg = Config(prefix + ".pdmodel", prefix + ".pdiparams")
        pred = create_predictor(cfg)
        assert pred.get_input_names() == ["x0"]
        assert pred.get_num_outputs() == 1

    def test_wrong_arity_raises(self, tmp_path):
        net = _mlp()
        prefix = os.path.join(tmp_path, "m")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        pred = load_inference_model(prefix)
        with pytest.raises(InvalidArgumentError, match="takes 1 inputs"):
            pred.run([np.zeros((2, 8), np.float32)] * 2)

    def test_bad_magic_rejected(self, tmp_path):
        p = os.path.join(tmp_path, "junk.pdmodel")
        with open(p, "wb") as f:
            f.write(b"NOTAMODEL")
        with pytest.raises(InvalidArgumentError, match="bad magic"):
            Predictor(os.path.join(tmp_path, "junk"))

    def test_truncated_header_rejected(self, tmp_path):
        p = os.path.join(tmp_path, "trunc.pdmodel")
        with open(p, "wb") as f:
            f.write(b"PTPUIM01\x02")  # magic + half a length field
        with pytest.raises(InvalidArgumentError, match="truncated or corrupt"):
            Predictor(os.path.join(tmp_path, "trunc"))

    def test_separate_params_file_honored(self, tmp_path):
        net = _mlp()
        prefix = os.path.join(tmp_path, "m")
        save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        other = os.path.join(tmp_path, "weights.pdiparams")
        os.rename(prefix + ".pdiparams", other)
        cfg = Config(prefix + ".pdmodel", other)
        pred = create_predictor(cfg)
        (out,) = pred.run([np.zeros((2, 8), np.float32)])
        assert out.shape == (2, 4)

    def test_multi_input_multi_dynamic_dims(self, tmp_path):
        """Two inputs, each with a dynamic batch AND a dynamic feature-like
        dim, must export under one symbolic scope."""
        paddle.seed(0)

        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)

            def forward(self, a, b):
                return self.fc(a) + b.sum(axis=1, keepdims=True)

        net = TwoIn()
        prefix = os.path.join(tmp_path, "two")
        save_inference_model(
            prefix, net,
            [InputSpec(["batch", 8], "float32", "a"),
             InputSpec(["batch", None], "float32", "b")],
            platforms=("cpu",))
        pred = load_inference_model(prefix)
        (out,) = pred.run([np.ones((3, 8), np.float32),
                           np.ones((3, 5), np.float32)])
        assert out.shape == (3, 4)


class TestModelSave:
    def test_model_save_inference(self, tmp_path):
        net = _mlp()
        model = paddle.Model(net, inputs=[InputSpec([None, 8], "float32")])
        prefix = os.path.join(tmp_path, "m")
        model.save(prefix, training=False)
        pred = load_inference_model(prefix)
        (out,) = pred.run([np.zeros((3, 8), np.float32)])
        assert out.shape == (3, 4)

    def test_model_save_without_spec_raises(self, tmp_path):
        model = paddle.Model(_mlp())
        with pytest.raises(InvalidArgumentError, match="input shapes"):
            model.save(os.path.join(tmp_path, "m"), training=False)

    def test_model_example_tensor_inputs(self, tmp_path):
        """Example tensors (not InputSpec) also carry export shapes."""
        net = _mlp()
        model = paddle.Model(net, inputs=[np.zeros((2, 8), np.float32)])
        prefix = os.path.join(tmp_path, "m")
        model.save(prefix, training=False)
        (out,) = load_inference_model(prefix).run(
            [np.zeros((2, 8), np.float32)])
        assert out.shape == (2, 4)

    def test_model_name_only_inputs_still_raise(self, tmp_path):
        model = paddle.Model(_mlp(), inputs=["input_ids"])
        with pytest.raises(InvalidArgumentError, match="input shapes"):
            model.save(os.path.join(tmp_path, "m"), training=False)

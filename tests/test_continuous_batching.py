"""Slot-level continuous batching (serving/generation.py).

Covers the scheduler's contract: token identity with the legacy
run-batch-to-completion path AND uncached greedy under staggered
mid-decode admission; slot eviction/re-admission without KV
contamination; the closed compile set (``len(prompt_buckets) + 2``,
zero post-warmup recompiles); EOS; the ``FLAGS_continuous_batching``
legacy fallback; transient-failure restart; and analysis rule S603
(sustained slot starvation while the queue is non-empty).
"""
import time
import unittest

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.errors import UnavailableError
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.serving import GenerationEngine


class TestContinuousBatching(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        pt.seed(4321)
        cls.cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_position=64, dropout=0.0)
        cls.model = GPTForCausalLM(cls.cfg)
        cls.model.eval()

    def _ref_greedy(self, prompt, n, eos=None):
        import jax.numpy as jnp
        ids, outs = list(map(int, prompt)), []
        for _ in range(n):
            logits = np.asarray(self.model(jnp.asarray([ids], jnp.int32)))[0]
            nxt = int(np.argmax(logits[-1]))
            outs.append(nxt)
            ids.append(nxt)
            if eos is not None and nxt == eos:
                break
        return outs

    def test_token_identity_staggered_admission(self):
        # one long request pins a slot while shorts are admitted
        # mid-decode into the other slot as it recycles — every output
        # must match uncached greedy AND the legacy fixed-batch path
        prompts = [(np.arange(10) * 5 + 2) % 97, np.arange(3) % 97,
                   (np.arange(6) * 3) % 97, (np.arange(4) * 7 + 1) % 97,
                   (np.arange(5) * 11 + 3) % 97]
        budgets = [14, 3, 4, 5, 3]
        refs = [self._ref_greedy(p, b) for p, b in zip(prompts, budgets)]
        with GenerationEngine(self.model, prompt_buckets=[8, 16],
                              batch_size=2, continuous=True,
                              name="cb-stagger") as eng:
            self.assertEqual(eng.warmup(), 4)  # 2 admits + decode + evict
            futs = [eng.submit(prompts[0], budgets[0]),
                    eng.submit(prompts[1], budgets[1])]
            for p, b in zip(prompts[2:], budgets[2:]):
                time.sleep(0.02)  # long request is mid-decode by now
                futs.append(eng.submit(p, b))
            gens = [f.result(120) for f in futs]
            for g, ref in zip(gens, refs):
                self.assertEqual(g.tolist(), ref)
            # slot churn never reopened the compile set
            self.assertEqual(eng.compile_count, 4)
        with GenerationEngine(self.model, prompt_buckets=[8, 16],
                              batch_size=2, continuous=False,
                              name="cb-legacy") as leg:
            for p, b, ref in zip(prompts, budgets, refs):
                self.assertEqual(
                    leg.generate(p, b, timeout=120).tolist(), ref)

    def test_slot_reuse_has_no_kv_contamination(self):
        # batch_size=1: every request reuses THE one slot; admission must
        # fully replace the previous occupant's cache row
        prompts = [(np.arange(7) * 13 + 5) % 97, np.arange(2) % 97,
                   (np.arange(8) * 3 + 1) % 97]
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=1,
                              continuous=True, name="cb-reuse") as eng:
            self.assertEqual(eng.warmup(), 3)  # 1 admit + decode + evict
            for p in prompts:
                self.assertEqual(eng.generate(p, 5, timeout=120).tolist(),
                                 self._ref_greedy(p, 5))
            self.assertEqual(eng.compile_count, 3)
            st = eng.stats()
            self.assertEqual(st["admitted"], 3)
            self.assertGreater(st["decode_steps"], 0)
            self.assertIn("slot_occupancy", st)
            self.assertIn("queue_age_ms", st)

    def test_eos_stops_early(self):
        probe = self._ref_greedy(np.arange(4) % 97, 8)
        eos = probe[1]
        expect = probe[: probe.index(eos) + 1]
        self.assertLess(len(expect), 8)
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              continuous=True, eos_token_id=eos,
                              name="cb-eos") as eng:
            gen = eng.generate(np.arange(4) % 97, max_new_tokens=8,
                               timeout=120)
            self.assertEqual(gen.tolist(), expect)
            self.assertEqual(gen[-1], eos)

    def test_flag_fallback_to_legacy(self):
        set_flags({"continuous_batching": False})
        try:
            eng = GenerationEngine(self.model, prompt_buckets=[8],
                                   batch_size=1, name="cb-flag")
            try:
                self.assertFalse(eng.stats()["continuous"])
                self.assertIsNone(eng._thread)
                p = np.arange(3) % 97
                self.assertEqual(eng.generate(p, 3, timeout=120).tolist(),
                                 self._ref_greedy(p, 3))
            finally:
                eng.close()
        finally:
            set_flags({"continuous_batching": True})

    def test_transient_failure_restarts_and_tokens_survive(self):
        from paddle_tpu.resilience.faults import FaultPlan
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              continuous=True, circuit_breaker=False,
                              name="cb-restart") as eng:
            eng.warmup()
            p = (np.arange(5) * 9 + 4) % 97
            ref = self._ref_greedy(p, 6)
            self.assertEqual(eng.generate(p, 6, timeout=120).tolist(), ref)
            plan = FaultPlan.parse(
                "site=serving.decode,nth=1,error=TransientDeviceError")
            with plan:
                # admission trips the fault; greedy decode is
                # deterministic, so the restarted request regenerates the
                # exact same tokens
                self.assertEqual(
                    eng.generate(p, 6, timeout=120).tolist(), ref)
            self.assertEqual(plan.stats()["serving.decode"]["fired"], 1)
            self.assertGreaterEqual(eng.stats()["restarts"], 1)

    def test_s603_fires_on_starved_queue(self):
        from paddle_tpu.analysis import RetraceMonitor

        class _AlwaysOpen:  # deterministic stand-in for an open circuit
            def allow(self, key):
                return False

            def record_success(self, key):
                pass

            def record_failure(self, key):
                pass

        with RetraceMonitor(budget=8) as mon:
            eng = GenerationEngine(self.model, prompt_buckets=[8],
                                   batch_size=1, continuous=True,
                                   name="cb-starve")
            try:
                eng.warmup()
                eng.breaker = _AlwaysOpen()
                fut = eng.submit(np.arange(3) % 97, 4)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if eng.stats()["starved_steps_after_warm"] > 8:
                        break
                    time.sleep(0.02)
                self.assertGreater(
                    eng.stats()["starved_steps_after_warm"], 8)
                time.sleep(0.25)  # let a publish tick carry the gauges
                self.assertGreaterEqual(eng.stats()["queue_depth"], 1)
                diags = [d for d in mon.diagnostics() if d.rule == "S603"]
                self.assertTrue(diags, mon.diagnostics())
            finally:
                eng.close(drain=False, timeout=10)
            self.assertIsInstance(fut.exception(timeout=5),
                                  UnavailableError)


if __name__ == "__main__":
    unittest.main()

"""slim quantization: fake-quant numerics, QAT wrapping + fine-tune,
PTQ calibration, int8 layer accuracy, and export round-trip.

Reference parity targets: contrib/slim/quantization/imperative/qat.py:50,
quant_nn.py:32-500, post_training_quantization.py:120.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import optimizer as popt
from paddle_tpu.slim import (
    FakeQuantAbsMax,
    FakeQuantMovingAverage,
    ImperativeQuantAware,
    Int8Linear,
    PostTrainingQuantization,
    QuantizedConv2D,
    QuantizedLinear,
    fake_quant_dequant,
    quantize_to_int8,
)


class TestFakeQuantDequant:
    def test_formula_vs_numpy(self):
        # out = round(clip(x)/s*127)*s/127 (quant_nn.py FakeQuant formula)
        x = np.array([-2.0, -0.5, 0.0, 0.3, 0.77, 1.5], np.float32)
        s = 1.0
        out = np.asarray(fake_quant_dequant(jnp.asarray(x), s))
        exp = np.round(np.clip(x, -s, s) * 127) / 127
        np.testing.assert_allclose(out, exp, atol=1e-6)

    def test_straight_through_gradient(self):
        g = jax.grad(lambda x: fake_quant_dequant(x, 1.0).sum())(
            jnp.asarray([0.3, 2.0]))
        np.testing.assert_allclose(np.asarray(g), [1.0, 1.0])

    def test_quantization_error_bound(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.uniform(-3, 3, (64,)).astype(np.float32))
        s = float(jnp.max(jnp.abs(x)))
        out = fake_quant_dequant(x, s)
        assert float(jnp.max(jnp.abs(out - x))) <= s / 127 / 2 + 1e-6


class TestObservers:
    def test_moving_average_formula(self):
        # scale = (rate·accum + |x|max) / (rate·state + 1)
        fq = FakeQuantMovingAverage(moving_rate=0.9)
        fq.train()
        fq(jnp.asarray([2.0, -1.0]))
        np.testing.assert_allclose(
            float(fq.scale), (0.9 * 1.0 + 2.0) / (0.9 * 1.0 + 1), rtol=1e-6)
        fq(jnp.asarray([4.0]))
        accum = 0.9 * (0.9 + 2.0) + 4.0
        state = 0.9 * 1.9 + 1.0
        np.testing.assert_allclose(float(fq.scale), accum / state, rtol=1e-6)

    def test_eval_uses_stored_scale(self):
        fq = FakeQuantMovingAverage()
        fq.train()
        fq(jnp.asarray([1.0]))
        s = float(fq.scale)
        fq.eval()
        fq(jnp.asarray([100.0]))  # must NOT move the scale
        assert float(fq.scale) == s


def _mlp():
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _cnn():
    return nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                         nn.Conv2D(4, 2, 3, padding=1))


class TestImperativeQuantAware:
    def test_wraps_layers_in_place(self):
        m = _mlp()
        ImperativeQuantAware().quantize(m)
        assert isinstance(m[0], QuantizedLinear)
        assert isinstance(m[2], QuantizedLinear)
        c = _cnn()
        ImperativeQuantAware().quantize(c)
        assert isinstance(c[0], QuantizedConv2D)

    def test_qat_close_to_float(self):
        paddle.seed(0)
        m = _mlp()
        rng = np.random.RandomState(0)
        x = rng.uniform(-1, 1, (16, 8)).astype(np.float32)
        m.eval()
        ref = np.asarray(m(paddle.to_tensor(x)))
        ImperativeQuantAware().quantize(m)
        m.train()
        m(paddle.to_tensor(x))  # observe scales
        m.eval()
        out = np.asarray(m(paddle.to_tensor(x)))
        # int8 fake quant on a 2-layer MLP: small relative error
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05

    def test_qat_trains(self):
        # fine-tuning through the fake-quant STE must reduce loss
        paddle.seed(1)
        m = _mlp()
        ImperativeQuantAware().quantize(m)
        rng = np.random.RandomState(1)
        x = rng.uniform(-1, 1, (64, 8)).astype(np.float32)
        w = rng.uniform(-1, 1, (8, 4)).astype(np.float32)
        y = x @ w
        model = paddle.Model(m, inputs=["x"], labels=["y"])
        model.prepare(optimizer=popt.Adam(learning_rate=0.01),
                      loss=nn.MSELoss())
        losses = [float(model.train_batch([x], [y])[0]) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_convert_to_int8(self):
        paddle.seed(2)
        m = _mlp()
        qat = ImperativeQuantAware()
        qat.quantize(m)
        rng = np.random.RandomState(2)
        x = rng.uniform(-1, 1, (32, 8)).astype(np.float32)
        m.train()
        for _ in range(5):
            m(paddle.to_tensor(x))
        m.eval()
        ref = np.asarray(m(paddle.to_tensor(x)))
        qat.convert(m)
        assert isinstance(m[0], Int8Linear)
        out = np.asarray(m(paddle.to_tensor(x)))
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05


class TestQuantizedLeNet:
    def test_lenet_qat_and_ptq_within_tolerance(self):
        # the VERDICT's named case: a quantized LeNet stays within
        # tolerance of float on MNIST-shaped inputs
        from paddle_tpu.vision.models import LeNet

        paddle.seed(9)
        rng = np.random.RandomState(9)
        x = rng.uniform(0, 1, (8, 1, 28, 28)).astype(np.float32)
        net = LeNet()
        net.eval()
        ref = np.asarray(net(paddle.to_tensor(x)))

        ptq = PostTrainingQuantization(net)
        ptq.collect(paddle.to_tensor(x))
        qnet = ptq.quantize()
        out = np.asarray(qnet(paddle.to_tensor(x)))
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05
        # int8 layers really took over the convs and linears
        from paddle_tpu.slim import Int8Conv2D, Int8Linear

        kinds = [type(l) for _, l in qnet.named_sublayers()]
        assert Int8Conv2D in kinds and Int8Linear in kinds


class TestPostTrainingQuantization:
    def test_ptq_linear_close_to_float(self):
        paddle.seed(3)
        m = _mlp()
        rng = np.random.RandomState(3)
        calib = [rng.uniform(-1, 1, (16, 8)).astype(np.float32)
                 for _ in range(4)]
        m.eval()
        ref = np.asarray(m(paddle.to_tensor(calib[0])))
        ptq = PostTrainingQuantization(m)
        for b in calib:
            ptq.collect(paddle.to_tensor(b))
        qm = ptq.quantize()
        out = np.asarray(qm(paddle.to_tensor(calib[0])))
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05

    def test_ptq_conv(self):
        paddle.seed(4)
        m = _cnn()
        rng = np.random.RandomState(4)
        x = rng.uniform(-1, 1, (2, 1, 8, 8)).astype(np.float32)
        m.eval()
        ref = np.asarray(m(paddle.to_tensor(x)))
        ptq = PostTrainingQuantization(m)
        ptq.collect(paddle.to_tensor(x))
        qm = ptq.quantize()
        out = np.asarray(qm(paddle.to_tensor(x)))
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05

    def test_no_calibration_raises(self):
        m = _mlp()
        ptq = PostTrainingQuantization(m)
        with pytest.raises(Exception):
            ptq.quantize()


class TestInt8Numerics:
    def test_int8_linear_3d_input(self):
        # transformer-style [batch, seq, features] input must work
        rng = np.random.RandomState(8)
        lin = nn.Linear(6, 3)
        x = rng.uniform(-1, 1, (2, 4, 6)).astype(np.float32)
        q = Int8Linear.from_float(lin, float(np.abs(x).max()))
        out = np.asarray(q(paddle.to_tensor(x)))
        lin.eval()
        ref = np.asarray(lin(paddle.to_tensor(x)))
        assert out.shape == ref.shape
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05

    def test_convert_untrained_observer_rejected(self):
        m = _mlp()
        qat = ImperativeQuantAware()
        qat.quantize(m)
        with pytest.raises(Exception, match="never saw data"):
            qat.convert(m)

    def test_zero_act_scale_no_nan(self):
        lin = nn.Linear(4, 2)
        q = Int8Linear.from_float(lin, 0.0)  # degenerate calibration
        out = np.asarray(q(paddle.to_tensor(np.zeros((3, 4), np.float32))))
        assert np.isfinite(out).all()

    def test_convert_abs_max_activation_rejected(self):
        m = _mlp()
        qat = ImperativeQuantAware(activation_quantize_type="abs_max")
        qat.quantize(m)
        with pytest.raises(Exception, match="moving_average_abs_max"):
            qat.convert(m)

    def test_int8_matmul_int32_accumulate(self):
        # the quantized matmul must run on integer operands: compare the
        # int8 path against an explicit integer-arithmetic oracle
        rng = np.random.RandomState(5)
        lin = nn.Linear(6, 3)
        x = rng.uniform(-1, 1, (4, 6)).astype(np.float32)
        act_scale = float(np.abs(x).max())
        q = Int8Linear.from_float(lin, act_scale)
        out = np.asarray(q(paddle.to_tensor(x)))
        wq = np.asarray(q.w_q.value).astype(np.int32)
        ws = np.asarray(q.w_scale.value)
        xq = np.clip(np.round(x / act_scale * 127), -127, 127).astype(np.int32)
        acc = xq @ wq
        exp = acc.astype(np.float32) * (ws.reshape(1, -1)
                                        * act_scale / (127 * 127))
        exp = exp + np.asarray(lin.bias.value)
        np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-5)

    def test_quantize_to_int8_channel_wise(self):
        rng = np.random.RandomState(6)
        w = rng.uniform(-2, 2, (5, 7)).astype(np.float32)
        q, s = quantize_to_int8(w, channel_axis=1)
        assert q.dtype == jnp.int8
        recon = np.asarray(q).astype(np.float32) * np.asarray(s) / 127
        np.testing.assert_allclose(recon, w, atol=np.abs(w).max() / 127 + 1e-6)


class TestInt8Export:
    def test_export_reload_roundtrip(self, tmp_path):
        # int8 model → StableHLO export → reload → same outputs
        from paddle_tpu import inference

        paddle.seed(7)
        m = _mlp()
        rng = np.random.RandomState(7)
        x = rng.uniform(-1, 1, (8, 8)).astype(np.float32)
        ptq = PostTrainingQuantization(m)
        ptq.collect(paddle.to_tensor(x))
        qm = ptq.quantize()
        qm.eval()
        ref = np.asarray(qm(paddle.to_tensor(x)))

        from paddle_tpu.inference import Config, create_predictor, \
            save_inference_model
        from paddle_tpu.static import InputSpec

        prefix = os.path.join(str(tmp_path), "int8_model")
        save_inference_model(prefix, qm, [InputSpec([None, 8], "float32")],
                             platforms=("cpu",))
        cfg = Config(prefix + ".pdmodel", prefix + ".pdiparams")
        predictor = create_predictor(cfg)
        out = predictor.run([x])[0]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def _tiny_gpt(seed=5):
    # quantize_weights/export_quantized target the parallel-linear hot
    # paths (GPT qkv/out/fc1/fc2), not plain nn.Linear
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(seed)
    m = GPTForCausalLM(GPTConfig(vocab_size=53, hidden_size=32,
                                 num_layers=2, num_heads=4,
                                 max_position=32, dropout=0.0))
    m.eval()
    return m


@pytest.mark.fast  # cheap units in a SLOW_FILES file: tiny GPT, <5s
class TestQuantizedWeightExport:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_export_quantized_roundtrip(self, tmp_path, mode):
        # artifact + sha256 manifest; reloaded trees dequantize back to
        # the float weights within one quantization step
        import hashlib
        import json as _json

        from paddle_tpu.framework import serialization
        from paddle_tpu.slim import export_quantized

        m = _tiny_gpt()
        float_params = m.param_pytree()
        artifact = export_quantized(
            m, os.path.join(str(tmp_path), "m"), mode=mode)
        manifest = _json.load(open(artifact + ".manifest.json"))
        assert manifest["quantization"] == mode
        assert manifest["format"] == "paddle_tpu.quantized_weights.v1"
        digest = hashlib.sha256(open(artifact, "rb").read()).hexdigest()
        assert manifest["sha256"] == digest

        state = serialization.load(artifact)
        assert state["quantization"] == mode
        qdt = "int8" if mode == "int8" else "float8_e4m3fn"
        qkeys = [k for k, v in state["params"].items()
                 if str(np.asarray(v).dtype) == qdt]
        # qkv/out/fc1/fc2 per block, 2 blocks
        assert len(qkeys) == 8
        for k in qkeys:
            scale = np.asarray(
                state["buffers"][k.replace("weight", "weight_scale")])
            recon = np.asarray(state["params"][k], np.float32) * scale
            w = np.asarray(float_params[k])
            amax = np.abs(w).max(axis=tuple(range(w.ndim - 1)))
            tol = amax / 127 + 1e-6 if mode == "int8" else amax * 0.0625
            assert (np.abs(recon - w).max(
                axis=tuple(range(w.ndim - 1))) <= tol).all()
        # the model itself stays float (export is non-mutating) and
        # layernorms/embeddings/biases never quantize
        assert str(np.asarray(float_params[qkeys[0]]).dtype) == "float32"
        assert all(str(np.asarray(v).dtype) in ("float32", qdt)
                   for v in state["params"].values())

    def test_quantize_weights_fp8_forward_close(self):
        # in-place fp8 conversion: logits track float within the e4m3
        # mantissa budget, weights actually stored as float8_e4m3fn
        from paddle_tpu.slim import quantize_weights

        m = _tiny_gpt(seed=9)
        rng = np.random.RandomState(9)
        ids = paddle.to_tensor(
            rng.randint(1, 53, size=(2, 12)).astype(np.int32))
        ref = np.asarray(m(ids))
        quantize_weights(m, "fp8")
        qkv = m.gpt.blocks[0].attn.qkv
        assert str(jnp.asarray(qkv.weight).dtype) == "float8_e4m3fn"
        # scale buffers ride the per-layer buffer tree (swap contract)
        assert qkv._buffers["weight_scale"].value.shape == (
            jnp.asarray(qkv.weight).shape[-1],)
        out = np.asarray(m(ids))
        assert out.shape == ref.shape
        assert np.max(np.abs(out - ref)) <= 0.15 * np.abs(ref).max()

"""Multi-host launch/env wiring (mock form).

Reference capability: fleet launch env plumbing (fleet/launch_utils.py
PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS → trainer bootstrap; tested by
the reference's test_launch.sh).  TPU-native: those env vars must reach
``jax.distributed.initialize``.  Real multi-host needs multiple machines,
so initialize is captured by a stub — exactly how the reference fakes
multi-rank in test_collective_base.py.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu.distributed.env as penv
from paddle_tpu.distributed.parallel import launch, spawn


@pytest.fixture
def clean_env(monkeypatch):
    """Reset the module singleton + scrub trainer vars around each test."""
    penv._initialized = False
    for k in ("COORDINATOR_ADDRESS", "PADDLE_TRAINER_ENDPOINTS",
              "PADDLE_TRAINERS_NUM", "PADDLE_TRAINER_ID"):
        monkeypatch.delenv(k, raising=False)
    yield monkeypatch
    penv._initialized = False


@pytest.fixture
def capture_init(clean_env):
    calls = []

    def fake_initialize(coordinator_address=None, num_processes=None,
                        process_id=None, **kw):
        calls.append({"addr": coordinator_address, "nproc": num_processes,
                      "pid": process_id})

    clean_env.setattr(penv.jax.distributed, "initialize", fake_initialize)
    return calls


class TestInitParallelEnv:
    def test_single_host_is_noop(self, capture_init):
        env = penv.init_parallel_env()
        assert capture_init == []  # no rendezvous for one host
        assert env.rank == 0
        assert penv.is_initialized()

    def test_paddle_trainer_env_wires_rendezvous(self, clean_env, capture_init):
        clean_env.setenv("PADDLE_TRAINER_ENDPOINTS",
                         "10.0.0.1:6170,10.0.0.2:6170")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "2")
        clean_env.setenv("PADDLE_TRAINER_ID", "1")
        penv.init_parallel_env()
        assert capture_init == [
            {"addr": "10.0.0.1:6170", "nproc": 2, "pid": 1}]

    def test_coordinator_address_beats_endpoints(self, clean_env, capture_init):
        clean_env.setenv("COORDINATOR_ADDRESS", "coord:1234")
        clean_env.setenv("PADDLE_TRAINER_ENDPOINTS", "other:1,other:2")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "4")
        clean_env.setenv("PADDLE_TRAINER_ID", "3")
        penv.init_parallel_env()
        assert capture_init == [{"addr": "coord:1234", "nproc": 4, "pid": 3}]

    def test_explicit_args_beat_env(self, clean_env, capture_init):
        clean_env.setenv("PADDLE_TRAINERS_NUM", "8")
        penv.init_parallel_env(coordinator_address="a:1", num_processes=2,
                               process_id=1)
        assert capture_init == [{"addr": "a:1", "nproc": 2, "pid": 1}]

    def test_second_init_is_idempotent(self, clean_env, capture_init):
        clean_env.setenv("COORDINATOR_ADDRESS", "coord:1")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "2")
        penv.init_parallel_env()
        penv.init_parallel_env()
        assert len(capture_init) == 1

    def test_endpoints_env_surfaced(self, clean_env):
        clean_env.setenv("PADDLE_TRAINER_ENDPOINTS", "h1:1,h2:2")
        env = penv.ParallelEnv()
        assert env.trainer_endpoints == ["h1:1", "h2:2"]
        assert env.current_endpoint == "h1:1"


class TestLaunch:
    def test_launch_runs_script_with_env(self, clean_env, capture_init, tmp_path):
        script = os.path.join(tmp_path, "train.py")
        marker = os.path.join(tmp_path, "ran.txt")
        with open(script, "w") as f:
            f.write(
                "import sys, os\n"
                f"open({marker!r}, 'w').write(' '.join(sys.argv[1:]))\n")
        clean_env.setenv("COORDINATOR_ADDRESS", "c:9")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "2")
        clean_env.setenv("PADDLE_TRAINER_ID", "0")
        old_argv = list(sys.argv)
        try:
            rc = launch([script, "--lr", "0.1"])
        finally:
            sys.argv = old_argv
        assert rc == 0
        with open(marker) as f:
            assert f.read() == "--lr 0.1"
        assert capture_init == [{"addr": "c:9", "nproc": 2, "pid": 0}]

    def test_launch_no_script_usage(self, clean_env):
        assert launch([]) == 1

    def test_spawn_single_runs_func(self, capture_init):
        out = []
        spawn(lambda a: out.append(a), args=(7,))
        assert out == [7]

    def test_spawn_multi_on_one_host_errors(self, clean_env):
        with pytest.raises(Exception, match="multi-host"):
            spawn(lambda: None, nprocs=4)

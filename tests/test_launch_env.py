"""Multi-host launch/env wiring (mock form).

Reference capability: fleet launch env plumbing (fleet/launch_utils.py
PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS → trainer bootstrap; tested by
the reference's test_launch.sh).  TPU-native: those env vars must reach
``jax.distributed.initialize``.  Real multi-host needs multiple machines,
so initialize is captured by a stub — exactly how the reference fakes
multi-rank in test_collective_base.py.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu.distributed.env as penv
from paddle_tpu.distributed.parallel import launch, spawn


@pytest.fixture
def clean_env(monkeypatch):
    """Reset the module singleton + scrub trainer vars around each test.

    Also hermeticizes SPAWNED CHILDREN (launch/watch run real python
    subprocesses that inherit os.environ): with a TPU tunnel configured
    but down, an inherited ``PALLAS_AXON_POOL_IPS`` puts the child's jax
    init into a 25+ minute backend retry loop — the child must see a
    plain CPU environment regardless of the host's accelerator config.
    """
    penv._initialized = False
    for k in ("COORDINATOR_ADDRESS", "PADDLE_TRAINER_ENDPOINTS",
              "PADDLE_TRAINERS_NUM", "PADDLE_TRAINER_ID",
              "PALLAS_AXON_POOL_IPS", "TPU_SKIP_MDS_QUERY",
              "TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    yield monkeypatch
    penv._initialized = False


@pytest.fixture
def capture_init(clean_env):
    calls = []

    def fake_initialize(coordinator_address=None, num_processes=None,
                        process_id=None, **kw):
        calls.append({"addr": coordinator_address, "nproc": num_processes,
                      "pid": process_id})

    clean_env.setattr(penv.jax.distributed, "initialize", fake_initialize)
    return calls


class TestInitParallelEnv:
    def test_single_host_is_noop(self, capture_init):
        env = penv.init_parallel_env()
        assert capture_init == []  # no rendezvous for one host
        assert env.rank == 0
        assert penv.is_initialized()

    def test_paddle_trainer_env_wires_rendezvous(self, clean_env, capture_init):
        clean_env.setenv("PADDLE_TRAINER_ENDPOINTS",
                         "10.0.0.1:6170,10.0.0.2:6170")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "2")
        clean_env.setenv("PADDLE_TRAINER_ID", "1")
        penv.init_parallel_env()
        assert capture_init == [
            {"addr": "10.0.0.1:6170", "nproc": 2, "pid": 1}]

    def test_coordinator_address_beats_endpoints(self, clean_env, capture_init):
        clean_env.setenv("COORDINATOR_ADDRESS", "coord:1234")
        clean_env.setenv("PADDLE_TRAINER_ENDPOINTS", "other:1,other:2")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "4")
        clean_env.setenv("PADDLE_TRAINER_ID", "3")
        penv.init_parallel_env()
        assert capture_init == [{"addr": "coord:1234", "nproc": 4, "pid": 3}]

    def test_explicit_args_beat_env(self, clean_env, capture_init):
        clean_env.setenv("PADDLE_TRAINERS_NUM", "8")
        penv.init_parallel_env(coordinator_address="a:1", num_processes=2,
                               process_id=1)
        assert capture_init == [{"addr": "a:1", "nproc": 2, "pid": 1}]

    def test_second_init_is_idempotent(self, clean_env, capture_init):
        clean_env.setenv("COORDINATOR_ADDRESS", "coord:1")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "2")
        penv.init_parallel_env()
        penv.init_parallel_env()
        assert len(capture_init) == 1

    def test_endpoints_env_surfaced(self, clean_env):
        clean_env.setenv("PADDLE_TRAINER_ENDPOINTS", "h1:1,h2:2")
        env = penv.ParallelEnv()
        assert env.trainer_endpoints == ["h1:1", "h2:2"]
        assert env.current_endpoint == "h1:1"


class TestLaunch:
    def test_launch_runs_script_with_env(self, clean_env, capture_init, tmp_path):
        script = os.path.join(tmp_path, "train.py")
        marker = os.path.join(tmp_path, "ran.txt")
        with open(script, "w") as f:
            f.write(
                "import sys, os\n"
                f"open({marker!r}, 'w').write(' '.join(sys.argv[1:]))\n")
        clean_env.setenv("COORDINATOR_ADDRESS", "c:9")
        clean_env.setenv("PADDLE_TRAINERS_NUM", "2")
        clean_env.setenv("PADDLE_TRAINER_ID", "0")
        old_argv = list(sys.argv)
        try:
            rc = launch([script, "--lr", "0.1"])
        finally:
            sys.argv = old_argv
        assert rc == 0
        with open(marker) as f:
            assert f.read() == "--lr 0.1"
        assert capture_init == [{"addr": "c:9", "nproc": 2, "pid": 0}]

    def test_launch_no_script_usage(self, clean_env):
        assert launch([]) == 1

    def test_spawn_single_runs_func(self, capture_init):
        out = []
        spawn(lambda a: out.append(a), args=(7,))
        assert out == [7]

    def test_spawn_multi_on_one_host_errors(self, clean_env):
        with pytest.raises(Exception, match="multi-host"):
            spawn(lambda: None, nprocs=4)


class TestWatchdog:
    """Elastic-lite (reference: launch_utils.py trainer watch loop)."""

    def test_restart_then_success(self, clean_env, tmp_path):
        from paddle_tpu.distributed.parallel import watch
        from paddle_tpu.framework import monitor

        marker = os.path.join(tmp_path, "crashed-once")
        script = os.path.join(tmp_path, "flaky.py")
        with open(script, "w") as f:
            f.write(
                "import os, sys\n"
                f"m = {marker!r}\n"
                "if not os.path.exists(m):\n"
                "    open(m, 'w').close()\n"
                "    sys.exit(3)\n"  # first run: simulated preemption
                "sys.exit(0)\n")
        monitor.reset_stat("trainer_restarts")
        rc = watch([sys.executable, script], max_restarts=2, _sleep=0.01)
        assert rc == 0
        assert monitor.get_stat("trainer_restarts") == 1

    def test_budget_exhausted_propagates_rc(self, clean_env, tmp_path):
        from paddle_tpu.distributed.parallel import watch

        script = os.path.join(tmp_path, "dead.py")
        with open(script, "w") as f:
            f.write("import sys; sys.exit(7)\n")
        rc = watch([sys.executable, script], max_restarts=1, _sleep=0.01)
        assert rc == 7

    def test_launch_flag_parses(self, clean_env, capture_init, tmp_path):
        from paddle_tpu.distributed.parallel import launch

        script = os.path.join(tmp_path, "ok.py")
        with open(script, "w") as f:
            f.write("print('fine')\n")
        old_argv = list(sys.argv)
        try:
            assert launch(["--max-restarts=0", script]) == 0
            assert launch(["--bogus", script]) == 2
        finally:
            sys.argv = old_argv

    def test_watchdog_resume_end_to_end(self, clean_env, tmp_path):
        """Preempted trainer + auto-checkpoint: the restarted run resumes
        from the snapshot and finishes all epochs exactly once."""
        from paddle_tpu.distributed.parallel import watch

        log = os.path.join(tmp_path, "epochs.log")
        script = os.path.join(tmp_path, "train.py")
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(script, "w") as f:
            f.write(f'''
import os, sys
sys.path.insert(0, {repo_root!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.incubate.checkpoint import train_epoch_range

paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 2))
m = paddle.Model(net, inputs=["x"], labels=["y"])
m.prepare(optimizer=popt.SGD(learning_rate=0.1), loss=nn.CrossEntropyLoss())
x = np.zeros((4, 4), np.float32); y = np.zeros((4,), np.int32)
for epoch, acp in train_epoch_range(4, m, {os.path.join(tmp_path, "ck")!r}):
    m.train_batch([x], [y])
    with open({log!r}, "a") as fh:
        fh.write(f"{{epoch}}\\n")
    if epoch == 1 and os.environ.get("CRASH_ONCE") and not os.path.exists(
            {os.path.join(tmp_path, "crashed")!r}):
        # checkpoint writes are async: wait for epoch 0's commit (its meta
        # file) so the kill lands AFTER that commit, BEFORE epoch 1's —
        # the scenario under test, made deterministic
        import glob, time
        deadline = time.time() + 30
        while (not glob.glob({os.path.join(tmp_path, "ck")!r}
                             + "/ckpt-*/meta.pdmeta")
               and time.time() < deadline):
            time.sleep(0.01)
        open({os.path.join(tmp_path, "crashed")!r}, "w").close()
        os._exit(9)  # hard kill AFTER epoch-1 work, BEFORE its commit
''')
        env_backup = os.environ.get("CRASH_ONCE")
        os.environ["CRASH_ONCE"] = "1"
        try:
            rc = watch([sys.executable, script], max_restarts=1, _sleep=0.01)
        finally:
            if env_backup is None:
                os.environ.pop("CRASH_ONCE", None)
        assert rc == 0
        with open(log) as fh:
            epochs = [int(l) for l in fh.read().split()]
        # first run: 0,1 (epoch 1 uncommitted); resumed run: 1,2,3
        assert epochs == [0, 1, 1, 2, 3]

    def test_bad_flag_values_usage_not_traceback(self, clean_env):
        from paddle_tpu.distributed.parallel import launch

        assert launch(["--max-restarts"]) == 2        # missing value
        assert launch(["--max-restarts=abc", "s.py"]) == 2
        assert launch(["--max-restartsfoo=3", "s.py"]) == 2

    def test_no_restart_counts_zero(self, clean_env, tmp_path):
        from paddle_tpu.distributed.parallel import watch
        from paddle_tpu.framework import monitor

        script = os.path.join(tmp_path, "fail.py")
        with open(script, "w") as f:
            f.write("import sys; sys.exit(5)\n")
        monitor.reset_stat("trainer_restarts")
        assert watch([sys.executable, script], max_restarts=0,
                     _sleep=0.01) == 5
        assert monitor.get_stat("trainer_restarts") == 0


class TestValidateEnv:
    """Typed launch-env validation: every inconsistency raises
    InvalidArgumentError NAMING the offending variable, before it can
    surface as an opaque coordination-service failure."""

    @pytest.fixture(autouse=True)
    def _scrub(self, clean_env):
        for k in ("PADDLE_TPU_GANG_TRANSPORT", "PADDLE_TPU_GANG_DIR"):
            clean_env.delenv(k, raising=False)
        self.env = clean_env

    def _raises(self, match):
        from paddle_tpu.framework.errors import InvalidArgumentError
        return pytest.raises(InvalidArgumentError, match=match)

    def test_single_process_defaults(self):
        assert penv.validate_env() == (None, 1, 0)

    def test_non_integer_trainers_num_named(self):
        self.env.setenv("PADDLE_TRAINERS_NUM", "two")
        with self._raises("PADDLE_TRAINERS_NUM='two' is not an integer"):
            penv.validate_env()

    def test_non_integer_trainer_id_named(self):
        self.env.setenv("PADDLE_TRAINER_ID", "1.5")
        with self._raises("PADDLE_TRAINER_ID='1.5' is not an integer"):
            penv.validate_env()

    def test_zero_trainers_num_rejected(self):
        self.env.setenv("PADDLE_TRAINERS_NUM", "0")
        with self._raises("PADDLE_TRAINERS_NUM"):
            penv.validate_env()

    def test_rank_out_of_range(self):
        self.env.setenv("PADDLE_TRAINERS_NUM", "2")
        self.env.setenv("PADDLE_TRAINER_ID", "2")
        self.env.setenv("COORDINATOR_ADDRESS", "h:1234")
        with self._raises(r"PADDLE_TRAINER_ID=2 out of range \[0, 2\)"):
            penv.validate_env()

    def test_endpoint_count_mismatch_without_coordinator(self):
        self.env.setenv("PADDLE_TRAINERS_NUM", "3")
        self.env.setenv("PADDLE_TRAINER_ENDPOINTS", "a:1,b:2")
        with self._raises("every rank needs exactly one endpoint"):
            penv.validate_env()

    def test_endpoint_count_informational_with_coordinator(self):
        # with an explicit rendezvous address the endpoint list is
        # informational — a short list must NOT fail the launch
        self.env.setenv("PADDLE_TRAINERS_NUM", "3")
        self.env.setenv("PADDLE_TRAINER_ENDPOINTS", "a:1,b:2")
        self.env.setenv("COORDINATOR_ADDRESS", "a:1")
        addr, world, pid = penv.validate_env()
        assert (addr, world, pid) == ("a:1", 3, 0)

    def test_duplicate_endpoints_rejected(self):
        self.env.setenv("PADDLE_TRAINER_ENDPOINTS", "a:1,b:2,a:1")
        with self._raises("duplicate endpoint"):
            penv.validate_env()

    def test_malformed_address_names_source_var(self):
        self.env.setenv("COORDINATOR_ADDRESS", "no-port")
        with self._raises("COORDINATOR_ADDRESS='no-port' is not host:port"):
            penv.validate_env()
        self.env.delenv("COORDINATOR_ADDRESS")
        self.env.setenv("PADDLE_TRAINER_ENDPOINTS", "host:notaport")
        with self._raises("PADDLE_TRAINER_ENDPOINTS.*not host:port"):
            penv.validate_env()

    def test_bad_gang_transport_rejected(self):
        self.env.setenv("PADDLE_TPU_GANG_TRANSPORT", "tcp")
        with self._raises("PADDLE_TPU_GANG_TRANSPORT.*auto\\|jax\\|file"):
            penv.validate_env()

    def test_multi_host_needs_rendezvous(self):
        self.env.setenv("PADDLE_TRAINERS_NUM", "4")
        with self._raises("needs a rendezvous point"):
            penv.validate_env()

    def test_file_transport_needs_gang_dir(self):
        self.env.setenv("PADDLE_TRAINERS_NUM", "2")
        self.env.setenv("PADDLE_TPU_GANG_TRANSPORT", "file")
        with self._raises("PADDLE_TPU_GANG_DIR"):
            penv.validate_env()

    def test_file_transport_with_gang_dir_ok(self, tmp_path):
        self.env.setenv("PADDLE_TRAINERS_NUM", "2")
        self.env.setenv("PADDLE_TRAINER_ID", "1")
        self.env.setenv("PADDLE_TPU_GANG_TRANSPORT", "file")
        self.env.setenv("PADDLE_TPU_GANG_DIR", str(tmp_path))
        addr, world, pid = penv.validate_env()
        assert (world, pid) == (2, 1)

"""SelectedRows sparse-embedding gradients + lazy optimizer row updates.

Covers VERDICT r3 item #1: COO grads on Embedding(sparse=True) backward,
duplicate merging, Adam(lazy_mode=True)/SGD touching only seen rows,
grad-clip/master-weight composition, and the host-offload table
(ref: selected_rows.h:41, fluid/optimizer.py:2026, large_scale_kv.h:773).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import optimizer as popt
from paddle_tpu.framework.selected_rows import SelectedRows


VOCAB, DIM, B, F = 200, 8, 16, 3


def make_net(sparse, vocab=VOCAB, dim=DIM, padding_idx=None):
    paddle.seed(0)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(vocab, dim, sparse=sparse,
                                    padding_idx=padding_idx)
            self.fc = nn.Linear(dim, 1)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    return Net()


def mse(out, y):
    return ((out - y) ** 2).mean()


def batch(lo=0, hi=50, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(lo, hi, (B, F)).astype(np.int32)
    y = rng.randn(B, 1).astype(np.float32)
    return ids, y


def train_once(net, opt, ids, y, steps=1):
    model = paddle.Model(net, inputs=["ids"], labels=["y"])
    model.prepare(optimizer=opt, loss=mse)
    loss = None
    for _ in range(steps):
        loss, _ = model.train_batch([ids], [y])
    return float(np.asarray(loss)), model


class TestSelectedRows:
    def test_merged_dedupes_and_pads_with_sentinel(self):
        ids = jnp.array([3, 1, 3, 7, 1, 1])
        vals = jnp.arange(6 * 2, dtype=jnp.float32).reshape(6, 2)
        m = SelectedRows(ids, vals, height=10).merged()
        got = {int(i): np.asarray(v) for i, v in
               zip(m.ids, m.values) if int(i) < 10}
        assert set(got) == {1, 3, 7}
        np.testing.assert_allclose(got[3], vals[0] + vals[2])
        np.testing.assert_allclose(got[1], vals[1] + vals[4] + vals[5])
        np.testing.assert_allclose(got[7], vals[3])
        # padding slots carry the drop sentinel (== height) and zero values
        pad = np.asarray(m.ids) == 10
        assert pad.sum() == 3
        np.testing.assert_allclose(np.asarray(m.values)[pad], 0.0)

    def test_empty_rows_are_valid(self):
        # zero touched ids (e.g. an empty tail batch) must not crash
        sr = SelectedRows(jnp.zeros((0,), jnp.int32),
                          jnp.zeros((0, 4)), height=10)
        assert sr.merged() is sr
        assert sr.to_dense().shape == (10, 4)
        assert float(sr.l2_norm_sq()) == 0.0

    def test_to_dense_matches_scatter_add(self):
        ids = jnp.array([0, 2, 0])
        vals = jnp.array([[1.0, 1.0], [2.0, 2.0], [3.0, 3.0]])
        d = SelectedRows(ids, vals, height=4).to_dense()
        expect = np.zeros((4, 2), np.float32)
        expect[0] = [4, 4]
        expect[2] = [2, 2]
        np.testing.assert_allclose(np.asarray(d), expect)
        # merged().to_dense() is identical
        d2 = SelectedRows(ids, vals, height=4).merged().to_dense()
        np.testing.assert_allclose(np.asarray(d2), expect)


class TestLazyAdam:
    def test_single_step_parity_and_untouched_rows_frozen(self):
        ids, y = batch()
        net_s = make_net(sparse=True)
        w0 = np.asarray(net_s.emb.weight.value).copy()
        loss_s, model_s = train_once(
            net_s, popt.Adam(learning_rate=0.1, lazy_mode=True), ids, y)
        net_d = make_net(sparse=False)
        assert np.array_equal(w0, np.asarray(net_d.emb.weight.value))
        loss_d, _ = train_once(net_d, popt.Adam(learning_rate=0.1), ids, y)

        assert abs(loss_s - loss_d) < 1e-6
        w_s = np.asarray(net_s.emb.weight.value)
        w_d = np.asarray(net_d.emb.weight.value)
        touched = np.unique(ids)
        untouched = np.setdiff1d(np.arange(VOCAB), touched)
        np.testing.assert_allclose(w_s[touched], w_d[touched], atol=1e-6)
        # the lazy contract: untouched rows bit-identical to init
        assert np.array_equal(w_s[untouched], w0[untouched])
        # and their moments never materialized a nonzero value
        slots = model_s._opt_state["slots"]["emb.weight"]
        m1 = np.asarray(slots["moment1"])
        assert np.all(m1[untouched] == 0.0)
        assert np.any(m1[touched] != 0.0)

    def test_nonlazy_sparse_densifies_to_exact_dense_adam(self):
        # lazy_mode=False + sparse grad == reference non-lazy sparse Adam:
        # every row's moments decay, bit-equal to the dense path
        ids, y = batch()
        net_s = make_net(sparse=True)
        train_once(net_s, popt.Adam(learning_rate=0.1, lazy_mode=False),
                   ids, y, steps=3)
        net_d = make_net(sparse=False)
        train_once(net_d, popt.Adam(learning_rate=0.1), ids, y, steps=3)
        np.testing.assert_allclose(np.asarray(net_s.emb.weight.value),
                                   np.asarray(net_d.emb.weight.value),
                                   atol=1e-6)

    def test_lazy_multistep_touched_only_semantics(self):
        # step 1 touches ids<50, step 2 touches 100..150: a row first seen
        # at step 2 must update as a FIRST touch (its moments did not decay
        # during step 1)
        net = make_net(sparse=True)
        model = paddle.Model(net, inputs=["ids"], labels=["y"])
        opt = popt.Adam(learning_rate=0.1, lazy_mode=True)
        model.prepare(optimizer=opt, loss=mse)
        ids1, y1 = batch(0, 50, seed=0)
        ids2, y2 = batch(100, 150, seed=1)
        model.train_batch([ids1], [y1])
        w_after1 = np.asarray(net.emb.weight.value).copy()
        model.train_batch([ids2], [y2])
        w_after2 = np.asarray(net.emb.weight.value)
        t1 = np.unique(ids1)
        assert np.array_equal(w_after2[t1], w_after1[t1])  # untouched in s2

    def test_multi_precision_master_rows(self):
        ids, y = batch()
        net = make_net(sparse=True)
        net.emb.weight.value = net.emb.weight.value.astype(jnp.bfloat16)
        _, model = train_once(
            net, popt.Adam(learning_rate=0.1, lazy_mode=True,
                           multi_precision=True), ids, y)
        slots = model._opt_state["slots"]["emb.weight"]
        assert slots["master"].dtype == jnp.float32
        touched = np.unique(ids)
        master = np.asarray(slots["master"])
        w = np.asarray(net.emb.weight.value.astype(jnp.float32))
        np.testing.assert_allclose(w[touched], master[touched],
                                   atol=1e-2)  # bf16 cast error only

    def test_padding_idx_row_never_updates(self):
        pad = 0
        net = make_net(sparse=True, padding_idx=pad)
        w0 = np.asarray(net.emb.weight.value).copy()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 50, (B, F)).astype(np.int32)
        ids[:, 0] = pad  # every sample hits the padding id
        y = rng.randn(B, 1).astype(np.float32)
        train_once(net, popt.Adam(learning_rate=0.1, lazy_mode=True),
                   ids, y, steps=2)
        w = np.asarray(net.emb.weight.value)
        assert np.array_equal(w[pad], w0[pad])


class TestSparseSGDAndClip:
    def test_sgd_row_update_matches_dense(self):
        # without weight decay, dense SGD leaves untouched rows at -lr*0:
        # sparse row mode must be bit-compatible with the dense result
        ids, y = batch()
        net_s = make_net(sparse=True)
        train_once(net_s, popt.SGD(learning_rate=0.5), ids, y, steps=2)
        net_d = make_net(sparse=False)
        train_once(net_d, popt.SGD(learning_rate=0.5), ids, y, steps=2)
        np.testing.assert_allclose(np.asarray(net_s.emb.weight.value),
                                   np.asarray(net_d.emb.weight.value),
                                   atol=1e-6)

    def test_global_norm_clip_composes(self):
        ids, y = batch()
        clip = popt.clip.ClipGradByGlobalNorm(1e-3)  # tight → always active
        net_s = make_net(sparse=True)
        train_once(net_s, popt.Adam(learning_rate=0.1, lazy_mode=True,
                                    grad_clip=clip), ids, y)
        net_d = make_net(sparse=False)
        train_once(net_d, popt.Adam(learning_rate=0.1, grad_clip=clip),
                   ids, y)
        touched = np.unique(ids)
        np.testing.assert_allclose(
            np.asarray(net_s.emb.weight.value)[touched],
            np.asarray(net_d.emb.weight.value)[touched], atol=1e-6)

    def test_global_norm_clip_parity_with_heavy_padding(self):
        # ADVICE r4: the tape's delta at padded positions must carry a zero
        # cotangent — phantom rows would inflate the sparse global norm vs
        # the dense path (F.embedding blocks the padding gradient entirely),
        # over-clipping heavily padded batches.
        pad = 0
        rng = np.random.RandomState(1)
        ids = rng.randint(1, 50, (B, F)).astype(np.int32)
        ids[:, 1:] = pad  # 2/3 of every sample is padding
        y = rng.randn(B, 1).astype(np.float32)
        clip = popt.clip.ClipGradByGlobalNorm(1e-3)  # tight → always active
        net_s = make_net(sparse=True, padding_idx=pad)
        train_once(net_s, popt.Adam(learning_rate=0.1, lazy_mode=True,
                                    grad_clip=clip), ids, y)
        net_d = make_net(sparse=False, padding_idx=pad)
        train_once(net_d, popt.Adam(learning_rate=0.1, grad_clip=clip),
                   ids, y)
        touched = np.setdiff1d(np.unique(ids), [pad])
        np.testing.assert_allclose(
            np.asarray(net_s.emb.weight.value)[touched],
            np.asarray(net_d.emb.weight.value)[touched], atol=1e-6)

    def test_weight_decay_applies_to_touched_rows(self):
        ids, y = batch()
        net = make_net(sparse=True)
        w0 = np.asarray(net.emb.weight.value).copy()
        train_once(net, popt.Momentum(learning_rate=0.1, momentum=0.9,
                                      weight_decay=0.1), ids, y)
        w = np.asarray(net.emb.weight.value)
        untouched = np.setdiff1d(np.arange(VOCAB), np.unique(ids))
        # row mode: decay rides the row gradient; untouched rows stay put
        assert np.array_equal(w[untouched], w0[untouched])
        assert not np.allclose(w[np.unique(ids)], w0[np.unique(ids)])


class TestAdamWLazy:
    def test_decoupled_decay_touched_rows_only(self):
        ids, y = batch()
        net = make_net(sparse=True)
        w0 = np.asarray(net.emb.weight.value).copy()
        train_once(net, popt.AdamW(learning_rate=0.1, weight_decay=0.5,
                                   lazy_mode=True), ids, y)
        w = np.asarray(net.emb.weight.value)
        untouched = np.setdiff1d(np.arange(VOCAB), np.unique(ids))
        assert np.array_equal(w[untouched], w0[untouched])


class TestHostEmbeddingTable:
    def test_pull_push_adam_matches_device_lazy_adam(self):
        from paddle_tpu.incubate import HostEmbeddingTable

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 50, (B, F)).astype(np.int32)
        grads = rng.randn(B, F, DIM).astype(np.float32)

        host = HostEmbeddingTable(VOCAB, DIM, optimizer="adam",
                                  learning_rate=0.1, seed=3)
        w0 = np.asarray(host.table).copy()
        host.push(ids, grads)

        # device-side reference: lazy Adam on the same SelectedRows
        opt = popt.Adam(learning_rate=0.1, lazy_mode=True)
        params = {"t": jnp.asarray(w0)}
        state = opt.init(params)
        sr = SelectedRows(jnp.asarray(ids), jnp.asarray(grads), VOCAB)
        new_p, _ = opt.update({"t": sr}, state, params, lr=0.1)
        np.testing.assert_allclose(np.asarray(host.table),
                                   np.asarray(new_p["t"]), atol=1e-5)

    def test_pull_gathers_and_window_drops(self):
        from paddle_tpu.incubate import HostEmbeddingTable

        host = HostEmbeddingTable(100, 4, optimizer="sgd",
                                  learning_rate=1.0,
                                  vocab_range=(10, 60), seed=1)
        w0 = np.asarray(host.table).copy()
        rows = host.pull(np.array([[10, 59, 5]]))
        np.testing.assert_allclose(rows[0, 0], w0[0])
        np.testing.assert_allclose(rows[0, 1], w0[49])
        np.testing.assert_allclose(rows[0, 2], 0.0)  # out of window
        g = np.ones((1, 3, 4), np.float32)
        host.push(np.array([[10, 59, 5]]), g)
        np.testing.assert_allclose(np.asarray(host.table)[0], w0[0] - 1.0)
        np.testing.assert_allclose(np.asarray(host.table)[49], w0[49] - 1.0)

    def test_end_to_end_training_with_host_rows(self):
        """The full host-offload loop: pull rows, differentiate w.r.t. the
        pulled rows inside jit, push row grads back."""
        from paddle_tpu.incubate import HostEmbeddingTable

        paddle.seed(0)
        host = HostEmbeddingTable(1000, DIM, optimizer="adam",
                                  learning_rate=0.05, seed=2)
        fc = nn.Linear(DIM, 1)
        from paddle_tpu.nn.layer_base import functional_call

        params = {k: v.value for k, v in fc.named_parameters()}
        opt = popt.Adam(learning_rate=0.05)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, rows, y):
            def loss_fn(p, r):
                out = functional_call(fc, p, r.mean(axis=1))
                return ((out - y) ** 2).mean()

            (loss), (gp, grows) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(params, rows)
            return loss, gp, grows

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 1000, (B, F)).astype(np.int32)
        y = jnp.asarray(rng.randn(B, 1).astype(np.float32))
        losses = []
        for _ in range(12):
            rows = jnp.asarray(host.pull(ids))
            loss, gp, grows = step(params, rows, y)
            params, opt_state = opt.update(gp, opt_state, params, lr=0.05)
            host.push(ids, np.asarray(grows))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7  # it actually trains


class TestHostEmbeddingAsync:
    """The async overlap verbs (VERDICT r4 weak #3: pull/push must not sit
    synchronous on the step's critical path — ref communicator.h:268)."""

    def _table(self, **kw):
        from paddle_tpu.incubate import HostEmbeddingTable

        kw.setdefault("optimizer", "sgd")
        kw.setdefault("learning_rate", 1.0)
        kw.setdefault("seed", 3)
        return HostEmbeddingTable(200, 8, **kw)

    def test_async_fifo_matches_sync(self):
        rng = np.random.RandomState(0)
        batches = [(rng.randint(0, 200, (4, 3)).astype(np.int32),
                    rng.randn(4, 3, 8).astype(np.float32))
                   for _ in range(5)]
        sync = self._table()
        asy = self._table()
        pulls_s, pulls_a = [], []
        for ids, g in batches:
            pulls_s.append(sync.pull(ids))
            sync.push(ids, g)
            # strict ordering: pull enqueued BEFORE this batch's push
            # observes the previous pushes only — same as the sync path
            pulls_a.append(asy.pull_async(ids))
            asy.push_async(ids, g)
        asy.flush()
        for ps, pa in zip(pulls_s, pulls_a):
            np.testing.assert_array_equal(ps, pa.result())
        np.testing.assert_array_equal(np.asarray(sync.table),
                                      np.asarray(asy.table))

    def test_prefetch_is_one_step_stale(self):
        t = self._table()
        ids = np.array([7])
        before = t.pull(ids).copy()
        fut = t.pull_async(ids)          # prefetch enqueued FIRST
        t.push_async(ids, np.ones((1, 8), np.float32))
        t.flush()
        np.testing.assert_array_equal(fut.result(), before)  # stale read
        np.testing.assert_allclose(t.pull(ids), before - 1.0)

    def test_push_accepts_device_arrays(self):
        t = self._table()
        ids = np.array([1, 2])
        w0 = t.pull(ids).copy()
        t.push_async(ids, jnp.ones((2, 8)))  # D2H happens on the worker
        t.flush()
        np.testing.assert_allclose(t.pull(ids), w0 - 1.0)

    def test_worker_error_surfaces_and_state_dict_flushes(self):
        t = self._table()
        w0 = t.pull(np.array([1]))[0].copy()
        t.push_async(np.array([1]), np.ones((1, 8), np.float32))
        sd = t.state_dict()  # must include the in-flight push (lr=1 SGD)
        np.testing.assert_allclose(sd["table"][1], w0 - 1.0)
        t.push_async(np.array([1]), np.ones((1, 999), np.float32))  # bad
        with pytest.raises(Exception):
            t.flush()
        t.close()

    def test_failed_pull_future_not_raised_twice(self):
        t = self._table()
        fut = t.pull_async(np.array([[1.5]]))  # float ids → pull error
        with pytest.raises(Exception):
            fut.result()
        # the exception was delivered to its owner; later healthy calls
        # must not re-raise it
        w0 = t.pull(np.array([3]))[0].copy()
        t.push_async(np.array([3]), np.ones((1, 8), np.float32))
        t.flush()
        np.testing.assert_allclose(t.pull(np.array([3]))[0], w0 - 1.0)

    def test_geo_accumulate_exchange(self):
        """Two geo workers train locally, exchange 1/n-scaled deltas —
        both tables converge to the identical merged state
        (GeoCommunicator sparse path, communicator.h:413)."""
        a = self._table(geo=True)
        b = self._table(geo=True)
        np.testing.assert_array_equal(np.asarray(a.table),
                                      np.asarray(b.table))
        rng = np.random.RandomState(1)
        ids_a = rng.randint(0, 200, (4, 3)).astype(np.int32)
        ids_b = rng.randint(0, 200, (4, 3)).astype(np.int32)
        a.push(ids_a, rng.randn(4, 3, 8).astype(np.float32))
        b.push(ids_b, rng.randn(4, 3, 8).astype(np.float32))
        da_ids, da = a.pop_geo_deltas()
        db_ids, db = b.pop_geo_deltas()
        assert set(da_ids.tolist()) == set(np.unique(ids_a).tolist())
        # each side applies the PEER's half-scaled delta and halves its
        # own contribution by rolling back half of it
        a.merge_deltas(db_ids, db / 2)
        a.merge_deltas(da_ids, -da / 2)
        b.merge_deltas(da_ids, da / 2)
        b.merge_deltas(db_ids, -db / 2)
        np.testing.assert_allclose(np.asarray(a.table),
                                   np.asarray(b.table), atol=1e-6)
        # and the accumulators were cleared
        assert a.pop_geo_deltas()[0].size == 0

    def test_geo_records_applied_rounded_deltas(self):
        # fp16 tables must exchange the delta AFTER table-dtype rounding —
        # the full-precision difference would drift replicas apart
        t = self._table(geo=True, dtype=np.float16)
        ids = np.array([5])
        w0 = np.asarray(t.table)[5].astype(np.float32).copy()
        t.push(ids, np.full((1, 8), 1e-4, np.float32))  # sub-fp16-ulp step
        d_ids, d = t.pop_geo_deltas()
        applied = np.asarray(t.table)[5].astype(np.float32) - w0
        np.testing.assert_array_equal(d[0], applied)

    @pytest.mark.slow
    def test_million_row_table_step_time_is_o_k(self, tmp_path):
        """The scale gate (VERDICT r4 weak #7): a ≥1M×64 table must serve
        pull/push in time independent of the vocabulary — an O(vocab)
        regression (full-table scan/densify) shows up as ~16× here."""
        import time

        from paddle_tpu.incubate import HostEmbeddingTable

        def run(vocab, tag):
            t = HostEmbeddingTable(
                vocab, 64, optimizer="sgd", learning_rate=0.1,
                mmap_dir=str(tmp_path / tag),
                initializer=lambda table: None)  # zeros: sparse file
            rng = np.random.RandomState(0)
            ids = rng.randint(0, vocab, (20, 1024)).astype(np.int64)
            g = np.ones((1024, 64), np.float32)
            t.pull(ids[0]); t.push(ids[0], g)  # warmup / page-in
            t0 = time.perf_counter()
            for k in range(20):
                t.pull(ids[k])
                t.push(ids[k], g)
            dt = time.perf_counter() - t0
            # untouched rows stay exactly zero (never materialized)
            probe = np.setdiff1d(
                np.arange(vocab - 1000, vocab), ids.reshape(-1))[:8]
            np.testing.assert_array_equal(t.pull(probe), 0.0)
            return dt

        small = run(1 << 16, "small")       # 65k rows
        big = run(1 << 20, "big")           # 1M rows
        assert big < small * 3 + 0.25, (
            f"step time grew with vocab: 65k={small:.3f}s 1M={big:.3f}s — "
            "the O(touched-rows) property regressed")


class TestSparseCompressionComposition:
    """Embedding(sparse=True) × gradient-transforming fleet strategies
    (VERDICT r4 weak #5): SelectedRows leaves ride the sparse allreduce
    (framework/selected_rows.py all_gather_rows) while fp16_allreduce /
    DGC transform the dense leaves — the reference composes the same way
    (details/sparse_all_reduce_op_handle.cc:1)."""

    def _fleet_train(self, sparse, *, steps=3, seed=0, **strategy_kw):
        from paddle_tpu.distributed import fleet

        fleet._initialized = False
        strategy = fleet.DistributedStrategy(**strategy_kw)
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(seed)
        net = make_net(sparse=sparse)
        w0 = np.asarray(net.emb.weight.value).copy()
        if strategy_kw.get("dgc"):
            base = popt.Momentum(learning_rate=0.05, momentum=0.9)
        else:
            base = popt.SGD(learning_rate=0.05)
        opt = fleet.distributed_optimizer(base)
        model = paddle.Model(net, inputs=["ids"], labels=["y"])
        model.prepare(optimizer=opt, loss=mse)
        ids, y = batch()
        losses = [float(model.train_batch([ids], [y])[0])
                  for _ in range(steps)]
        return net, w0, ids, np.asarray(losses)

    def test_fp16_allreduce_sparse_matches_dense(self):
        net_s, w0, ids, ls = self._fleet_train(True, fp16_allreduce=True)
        net_d, _, _, ld = self._fleet_train(False, fp16_allreduce=True)
        np.testing.assert_allclose(ls, ld, rtol=2e-3, atol=2e-3)
        touched = np.unique(ids)
        np.testing.assert_allclose(
            np.asarray(net_s.emb.weight.value)[touched],
            np.asarray(net_d.emb.weight.value)[touched],
            rtol=2e-3, atol=2e-3)
        # sparse semantics preserved under the composition
        untouched = np.setdiff1d(np.arange(VOCAB), touched)
        np.testing.assert_array_equal(
            np.asarray(net_s.emb.weight.value)[untouched], w0[untouched])

    def test_dgc_warmup_sparse_matches_plain_dp_momentum(self):
        # dense warmup claims exact parity with plain DP Momentum; the
        # sparse table gets plain momentum on touched rows (never DGC'd)
        net_g, w0, ids, lg = self._fleet_train(
            True, dgc=True, dgc_configs={"rampup_begin_step": 100})
        net_p, _, _, lp = self._fleet_train(True)  # plain DP, Momentum
        # plain DP run must use Momentum too for parity
        from paddle_tpu.distributed import fleet

        fleet._initialized = False
        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy())
        paddle.seed(0)
        net_p = make_net(sparse=True)
        opt = fleet.distributed_optimizer(
            popt.Momentum(learning_rate=0.05, momentum=0.9))
        model = paddle.Model(net_p, inputs=["ids"], labels=["y"])
        model.prepare(optimizer=opt, loss=mse)
        ids2, y = batch()
        lp = np.asarray([float(model.train_batch([ids2], [y])[0])
                         for _ in range(3)])
        np.testing.assert_allclose(lg, lp, rtol=1e-5, atol=1e-6)
        touched = np.unique(ids)
        np.testing.assert_allclose(
            np.asarray(net_g.emb.weight.value)[touched],
            np.asarray(net_p.emb.weight.value)[touched],
            rtol=1e-5, atol=1e-6)
        untouched = np.setdiff1d(np.arange(VOCAB), touched)
        np.testing.assert_array_equal(
            np.asarray(net_g.emb.weight.value)[untouched], w0[untouched])

    def test_dgc_sparse_phase_trains_and_freezes_untouched(self):
        net, w0, ids, losses = self._fleet_train(
            True, steps=6, dgc=True,
            dgc_configs={"rampup_begin_step": 0, "sparsity": [0.5]})
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        untouched = np.setdiff1d(np.arange(VOCAB), np.unique(ids))
        np.testing.assert_array_equal(
            np.asarray(net.emb.weight.value)[untouched], w0[untouched])

"""Pipeline parallelism on the 8-device CPU mesh.

Reference capability: PipelineOptimizer (python/paddle/fluid/optimizer.py:3695)
+ SectionWorker (paddle/fluid/framework/section_worker.cc:82) — microbatch
scheduling across pipeline stages.  Here: GPipe via shard_map over the `pipe`
axis (distributed/pipeline_parallel.py); these tests assert exactness vs the
un-pipelined stack, gradient parity, and the hybrid pp×dp×tp training path.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.distributed.pipeline_parallel import pipeline_blocks
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _train_gpt(pp, dp, mp, steps=3, micro=None, seed=0):
    """Train a tiny GPT under the given hybrid degrees; return losses."""
    fleet._initialized = False
    strategy = fleet.DistributedStrategy(
        dp_degree=dp, pp_degree=pp,
        pipeline=pp > 1,
        pipeline_configs={"accumulate_steps": micro} if micro else {},
        tensor_parallel=mp > 1,
        tensor_parallel_configs={"tensor_parallel_degree": mp},
    )
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    net = GPTForCausalLM(gpt_tiny(num_layers=4))
    opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
    model = paddle.Model(net)
    model.prepare(optimizer=opt, loss=net.loss)
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(steps):
        ids = rng.randint(0, net.gpt.cfg.vocab_size, size=(8, 16)).astype(np.int32)
        loss, _ = model.train_batch([ids], [ids])
        losses.append(loss)
    return np.asarray(losses)


class TestPipelineBlocks:
    def test_forward_exact_vs_sequential(self):
        """pipeline_blocks == plain loop, bit-for-bit on f32 CPU."""
        set_mesh(build_mesh(pp=4))
        paddle.seed(0)
        blocks = nn.LayerList([nn.Linear(16, 16) for _ in range(8)])
        for b in blocks:
            b.eval()
        x = jnp.asarray(np.random.RandomState(1).randn(12, 16), jnp.float32)

        want = x
        for b in blocks:
            want = b(want)
        got = jax.jit(
            lambda xx: pipeline_blocks(blocks, xx, num_microbatches=3))(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_gradient_parity(self):
        """Grads through the pipeline schedule match the sequential stack."""
        set_mesh(build_mesh(pp=2))
        paddle.seed(0)
        blocks = nn.LayerList([nn.Linear(8, 8) for _ in range(4)])
        for b in blocks:
            b.eval()
        x = jnp.asarray(np.random.RandomState(2).randn(4, 8), jnp.float32)
        params = {n: p.value for n, p in blocks.named_parameters()}

        def run(fn):
            def loss(ps):
                boxes = dict(blocks.named_parameters())
                saved = {n: b.value for n, b in boxes.items()}
                try:
                    for n, v in ps.items():
                        boxes[n].value = v
                    h = fn(x)
                finally:
                    for n, v in saved.items():
                        boxes[n].value = v
                return (h ** 2).mean()

            return jax.jit(jax.value_and_grad(loss))(params)

        v_seq, g_seq = run(lambda xx: _apply_seq(blocks, xx))
        v_pp, g_pp = run(lambda xx: pipeline_blocks(blocks, xx,
                                                    num_microbatches=2))
        np.testing.assert_allclose(float(v_pp), float(v_seq), rtol=1e-6)
        for n in g_seq:
            np.testing.assert_allclose(np.asarray(g_pp[n]),
                                       np.asarray(g_seq[n]),
                                       rtol=1e-5, atol=1e-6)

    def test_bad_divisibility_raises(self):
        set_mesh(build_mesh(pp=4))
        blocks = nn.LayerList([nn.Linear(4, 4) for _ in range(6)])
        x = jnp.zeros((4, 4))
        with pytest.raises(Exception, match="not divisible"):
            pipeline_blocks(blocks, x)
        set_mesh(build_mesh(pp=2))
        blocks = nn.LayerList([nn.Linear(4, 4) for _ in range(2)])
        with pytest.raises(Exception, match="microbatch"):
            pipeline_blocks(blocks, jnp.zeros((5, 4)), num_microbatches=2)


def _apply_seq(blocks, x):
    for b in blocks:
        x = b(x)
    return x


class TestPipelineGPT:
    def test_pp2_loss_parity_vs_pp1(self):
        """tiny-GPT pp=2 trains with per-step loss parity vs pp=1."""
        ref = _train_gpt(pp=1, dp=8, mp=1)
        got = _train_gpt(pp=2, dp=4, mp=1, micro=2)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_pp2_dp2_tp2_hybrid(self):
        """The VERDICT acceptance config: pp=2 × dp=2 × tp=2 trains and
        matches the pure-DP trajectory."""
        ref = _train_gpt(pp=1, dp=8, mp=1)
        got = _train_gpt(pp=2, dp=2, mp=2, micro=2)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_microbatch_count_plumbed(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            dp_degree=4, pp_degree=2, pipeline=True,
            pipeline_configs={"accumulate_steps": 4})
        fleet.init(is_collective=True, strategy=strategy)
        net = GPTForCausalLM(gpt_tiny(num_layers=2))
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=net.loss)
        assert net.gpt.pipeline_microbatches == 4


class Test1F1B:
    """pipeline_train_step schedule='1f1b' vs gpipe vs sequential
    (ref: section_worker.cc:82-230 1F1B thread loop)."""

    def _blocks(self, n=8, d=8):
        paddle.seed(0)
        blocks = nn.LayerList([nn.Linear(d, d) for _ in range(n)])
        for b in blocks:
            b.eval()
        return blocks

    def _seq_loss_grads(self, blocks, x, y, loss_fn):
        params = {n: p.value for n, p in blocks.named_parameters()}

        def loss(ps):
            boxes = dict(blocks.named_parameters())
            saved = {n: b.value for n, b in boxes.items()}
            try:
                for n, v in ps.items():
                    boxes[n].value = v
                h = x
                for b in blocks:
                    h = b(h)
            finally:
                for n, v in saved.items():
                    boxes[n].value = v
            return loss_fn(h, y)

        return jax.value_and_grad(loss)(params)

    @pytest.mark.parametrize("M", [4, 6])
    def test_1f1b_matches_sequential_and_gpipe(self, M):
        from paddle_tpu.distributed.pipeline_parallel import (
            pipeline_train_step)

        set_mesh(build_mesh(pp=4))
        blocks = self._blocks()
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(12, 8), jnp.float32)
        y = jnp.asarray(rng.randn(12, 8), jnp.float32)

        def loss_fn(h, lbl):
            return ((h - lbl) ** 2).mean()

        v_seq, g_seq = self._seq_loss_grads(blocks, x, y, loss_fn)
        # per-block grads from the flat dict: keys are "<i>.weight" etc.
        l1, g1 = jax.jit(lambda xx, yy: pipeline_train_step(
            blocks, xx, yy, loss_fn, num_microbatches=M,
            schedule="1f1b"))(x, y)
        l2, g2 = jax.jit(lambda xx, yy: pipeline_train_step(
            blocks, xx, yy, loss_fn, num_microbatches=M,
            schedule="gpipe"))(x, y)
        np.testing.assert_allclose(float(l1), float(v_seq), rtol=1e-5)
        np.testing.assert_allclose(float(l2), float(v_seq), rtol=1e-5)
        for name in g1:  # stacked [L, ...] per within-block name
            for j in range(len(blocks)):
                np.testing.assert_allclose(
                    np.asarray(g1[name][j]), np.asarray(g_seq[f"{j}.{name}"]),
                    rtol=1e-4, atol=1e-5,
                    err_msg=f"1f1b grad {name}[{j}]")
                np.testing.assert_allclose(
                    np.asarray(g2[name][j]), np.asarray(g_seq[f"{j}.{name}"]),
                    rtol=1e-4, atol=1e-5,
                    err_msg=f"gpipe grad {name}[{j}]")

    def test_activation_memory_bounded_by_ring(self):
        """The 1F1B carry holds ring_buffer_slots(pp) = 2pp-1 activations
        per stage — CONSTANT in num_microbatches (GPipe's autodiff holds
        all M).  Asserted structurally on the jaxpr scan carry."""
        from paddle_tpu.distributed.pipeline_parallel import (
            pipeline_train_step, ring_buffer_slots)

        set_mesh(build_mesh(pp=4))
        pp = 4
        assert ring_buffer_slots(pp) == 7
        blocks = self._blocks()
        d = 8

        def loss_fn(h, lbl):
            return ((h - lbl) ** 2).mean()

        for M, B in ((8, 16), (32, 64)):
            x = jnp.zeros((B, d), jnp.float32)
            y = jnp.zeros((B, d), jnp.float32)
            jaxpr = jax.make_jaxpr(lambda xx, yy: pipeline_train_step(
                blocks, xx, yy, loss_fn, num_microbatches=M,
                schedule="1f1b"))(x, y)
            mb = B // M

            # find every scan and check carried activation stashes: any
            # carry aval shaped [k, mb, d] must have k == 2pp-1, never M
            def walk(jx, found):
                for eqn in jx.eqns:
                    if eqn.primitive.name == "scan":
                        n_carry = eqn.params["num_carry"]
                        for var in eqn.invars[eqn.params["num_consts"]:
                                              eqn.params["num_consts"]
                                              + n_carry]:
                            shp = tuple(var.aval.shape)
                            if len(shp) == 3 and shp[1:] == (mb, d):
                                found.append(shp[0])
                    for sub in eqn.params.values():
                        if hasattr(sub, "eqns"):  # raw Jaxpr (shard_map)
                            walk(sub, found)
                        elif hasattr(sub, "jaxpr"):  # ClosedJaxpr
                            walk(sub.jaxpr, found)
                return found

            sizes = walk(jaxpr.jaxpr, [])
            assert sizes, "no ring-buffer carry found"
            assert max(sizes) == ring_buffer_slots(pp), (M, sizes)
            assert max(sizes) < M or M <= ring_buffer_slots(pp)

    def test_1f1b_pp1_falls_back(self):
        from paddle_tpu.distributed.pipeline_parallel import (
            pipeline_train_step)

        set_mesh(build_mesh())  # no pipe axis
        blocks = self._blocks(4)
        x = jnp.asarray(np.random.RandomState(5).randn(4, 8), jnp.float32)
        y = jnp.zeros((4, 8), jnp.float32)
        loss, grads = pipeline_train_step(
            blocks, x, y, lambda h, l: ((h - l) ** 2).mean(),
            schedule="1f1b")
        v_seq, g_seq = self._seq_loss_grads(
            blocks, x, y, lambda h, l: ((h - l) ** 2).mean())
        np.testing.assert_allclose(float(loss), float(v_seq), rtol=1e-6)
        for name in grads:
            for j in range(len(blocks)):
                np.testing.assert_allclose(
                    np.asarray(grads[name][j]),
                    np.asarray(g_seq[f"{j}.{name}"]), rtol=1e-5, atol=1e-6,
                    err_msg=f"pp1 grad {name}[{j}]")

    def test_bad_schedule_raises(self):
        from paddle_tpu.distributed.pipeline_parallel import (
            pipeline_train_step)

        set_mesh(build_mesh(pp=2))
        blocks = self._blocks(4)
        x = jnp.zeros((4, 8), jnp.float32)
        with pytest.raises(Exception, match="schedule"):
            pipeline_train_step(blocks, x, x,
                                lambda h, l: (h ** 2).mean(),
                                schedule="interleaved")


class TestModel1F1B:
    """1F1B through the PRODUCTION path (VERDICT r3 #2): Model.prepare
    builds its train step from the interleaved schedule when
    pipeline_configs={"schedule": "1f1b"} (ref: section_worker.cc:82-230 is
    the reference's production pipeline loop)."""

    def _train(self, schedule, steps=3, micro=8, dropout=False):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            dp_degree=2, pp_degree=2, pipeline=True,
            pipeline_configs={"accumulate_steps": micro,
                              "schedule": schedule},
            tensor_parallel=True,
            tensor_parallel_configs={"tensor_parallel_degree": 2})
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = GPTForCausalLM(gpt_tiny(num_layers=4))
        if not dropout:
            net.eval()
            for b in net.gpt.blocks:
                b.eval()
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=net.loss)
        ids = np.random.RandomState(2).randint(
            0, net.gpt.cfg.vocab_size, size=(16, 16)).astype(np.int32)
        losses = []
        for _ in range(steps):
            loss, _ = model.train_batch([ids], [ids])
            losses.append(float(np.asarray(loss)))
        return losses

    def test_train_batch_runs_1f1b_with_gpipe_loss_parity_m8(self):
        l_1f1b = self._train("1f1b")
        l_gpipe = self._train("gpipe")
        np.testing.assert_allclose(l_1f1b, l_gpipe, atol=1e-4)
        assert l_1f1b[-1] < l_1f1b[0]

    def test_1f1b_with_dropout_descends(self):
        losses = self._train("1f1b", steps=4, dropout=True)
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_metrics_ride_the_1f1b_schedule(self):
        """Model.prepare(metrics=...) under 1F1B (VERDICT r4 weak #4): the
        last stage computes metric.compute per microbatch inside the
        schedule (ref SectionWorker metric fetches, section_worker.cc:82)
        and update() runs on the host with the concatenated rows — the
        accuracy must equal the GPipe path's full-batch computation."""
        from paddle_tpu import metric as pmetric

        class PipeMLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed = nn.Linear(8, 16)
                self.blocks = nn.LayerList(
                    [nn.Linear(16, 16) for _ in range(4)])
                self.head = nn.Linear(16, 4)

            def forward(self, x):
                h = self.embed(x)
                for b in self.blocks:
                    h = b(h)
                return self.head(h)

            def pipeline_decompose(self):
                return {"pre": lambda x: self.embed(x),
                        "blocks": list(self.blocks),
                        "post": lambda h: self.head(h)}

        rng = np.random.RandomState(3)
        x = rng.randn(8, 8).astype(np.float32)
        y = rng.randint(0, 4, (8, 1)).astype(np.int64)

        def run(schedule):
            fleet._initialized = False
            strategy = fleet.DistributedStrategy(
                pp_degree=2, pipeline=True,
                pipeline_configs={"schedule": schedule,
                                  "accumulate_steps": 2})
            fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            net = PipeMLP()
            opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.05))
            model = paddle.Model(net, inputs=["x"], labels=["y"])
            model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                          metrics=[pmetric.Accuracy()])
            loss, metrics = model.train_batch([x], [y])
            return loss, metrics, model._metrics[0].accumulate()

        loss_g, m_g, acc_g = run("gpipe")
        loss_i, m_i, acc_i = run("1f1b")
        np.testing.assert_allclose(loss_i, loss_g, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(m_i[0]), np.asarray(m_g[0]),
                                   rtol=1e-6)
        np.testing.assert_allclose(acc_i, acc_g, rtol=1e-6)

    def test_undecomposable_net_rejected(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            pp_degree=2, pipeline=True,
            pipeline_configs={"schedule": "1f1b"})
        fleet.init(is_collective=True, strategy=strategy)
        net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(Exception, match="pipeline_decompose"):
            model.prepare(optimizer=opt, loss=nn.MSELoss())

"""Pipeline parallelism on the 8-device CPU mesh.

Reference capability: PipelineOptimizer (python/paddle/fluid/optimizer.py:3695)
+ SectionWorker (paddle/fluid/framework/section_worker.cc:82) — microbatch
scheduling across pipeline stages.  Here: GPipe via shard_map over the `pipe`
axis (distributed/pipeline_parallel.py); these tests assert exactness vs the
un-pipelined stack, gradient parity, and the hybrid pp×dp×tp training path.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.distributed.pipeline_parallel import pipeline_blocks
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _train_gpt(pp, dp, mp, steps=3, micro=None, seed=0):
    """Train a tiny GPT under the given hybrid degrees; return losses."""
    fleet._initialized = False
    strategy = fleet.DistributedStrategy(
        dp_degree=dp, pp_degree=pp,
        pipeline=pp > 1,
        pipeline_configs={"accumulate_steps": micro} if micro else {},
        tensor_parallel=mp > 1,
        tensor_parallel_configs={"tensor_parallel_degree": mp},
    )
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    net = GPTForCausalLM(gpt_tiny(num_layers=4))
    opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
    model = paddle.Model(net)
    model.prepare(optimizer=opt, loss=net.loss)
    rng = np.random.RandomState(7)
    losses = []
    for _ in range(steps):
        ids = rng.randint(0, net.gpt.cfg.vocab_size, size=(8, 16)).astype(np.int32)
        loss, _ = model.train_batch([ids], [ids])
        losses.append(loss)
    return np.asarray(losses)


class TestPipelineBlocks:
    def test_forward_exact_vs_sequential(self):
        """pipeline_blocks == plain loop, bit-for-bit on f32 CPU."""
        set_mesh(build_mesh(pp=4))
        paddle.seed(0)
        blocks = nn.LayerList([nn.Linear(16, 16) for _ in range(8)])
        for b in blocks:
            b.eval()
        x = jnp.asarray(np.random.RandomState(1).randn(12, 16), jnp.float32)

        want = x
        for b in blocks:
            want = b(want)
        got = jax.jit(
            lambda xx: pipeline_blocks(blocks, xx, num_microbatches=3))(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)

    def test_gradient_parity(self):
        """Grads through the pipeline schedule match the sequential stack."""
        set_mesh(build_mesh(pp=2))
        paddle.seed(0)
        blocks = nn.LayerList([nn.Linear(8, 8) for _ in range(4)])
        for b in blocks:
            b.eval()
        x = jnp.asarray(np.random.RandomState(2).randn(4, 8), jnp.float32)
        params = {n: p.value for n, p in blocks.named_parameters()}

        def run(fn):
            def loss(ps):
                boxes = dict(blocks.named_parameters())
                saved = {n: b.value for n, b in boxes.items()}
                try:
                    for n, v in ps.items():
                        boxes[n].value = v
                    h = fn(x)
                finally:
                    for n, v in saved.items():
                        boxes[n].value = v
                return (h ** 2).mean()

            return jax.jit(jax.value_and_grad(loss))(params)

        v_seq, g_seq = run(lambda xx: _apply_seq(blocks, xx))
        v_pp, g_pp = run(lambda xx: pipeline_blocks(blocks, xx,
                                                    num_microbatches=2))
        np.testing.assert_allclose(float(v_pp), float(v_seq), rtol=1e-6)
        for n in g_seq:
            np.testing.assert_allclose(np.asarray(g_pp[n]),
                                       np.asarray(g_seq[n]),
                                       rtol=1e-5, atol=1e-6)

    def test_bad_divisibility_raises(self):
        set_mesh(build_mesh(pp=4))
        blocks = nn.LayerList([nn.Linear(4, 4) for _ in range(6)])
        x = jnp.zeros((4, 4))
        with pytest.raises(Exception, match="not divisible"):
            pipeline_blocks(blocks, x)
        set_mesh(build_mesh(pp=2))
        blocks = nn.LayerList([nn.Linear(4, 4) for _ in range(2)])
        with pytest.raises(Exception, match="microbatch"):
            pipeline_blocks(blocks, jnp.zeros((5, 4)), num_microbatches=2)


def _apply_seq(blocks, x):
    for b in blocks:
        x = b(x)
    return x


class TestPipelineGPT:
    def test_pp2_loss_parity_vs_pp1(self):
        """tiny-GPT pp=2 trains with per-step loss parity vs pp=1."""
        ref = _train_gpt(pp=1, dp=8, mp=1)
        got = _train_gpt(pp=2, dp=4, mp=1, micro=2)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_pp2_dp2_tp2_hybrid(self):
        """The VERDICT acceptance config: pp=2 × dp=2 × tp=2 trains and
        matches the pure-DP trajectory."""
        ref = _train_gpt(pp=1, dp=8, mp=1)
        got = _train_gpt(pp=2, dp=2, mp=2, micro=2)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)

    def test_microbatch_count_plumbed(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            dp_degree=4, pp_degree=2, pipeline=True,
            pipeline_configs={"accumulate_steps": 4})
        fleet.init(is_collective=True, strategy=strategy)
        net = GPTForCausalLM(gpt_tiny(num_layers=2))
        opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-3))
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=net.loss)
        assert net.gpt.pipeline_microbatches == 4

"""paddle_tpu.observability — registry, exporters, and step telemetry.

The contract under test (ISSUE 6): one typed labeled metrics registry is
the single sink for every telemetry island the repo has grown —
trace_events families re-published by the bridge, monitor counters pulled
by a collector, per-step training telemetry from the Executor hooks, and
per-request serving spans — exported as Prometheus text and periodic
JSONL, with the M901 (data-starved training) and M902 (HBM high-water)
analysis rules reading the same snapshots.
"""
import json
import math
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import observability as obs
from paddle_tpu import profiler as prof
from paddle_tpu.analysis import RetraceMonitor
from paddle_tpu.framework import monitor, trace_events
from paddle_tpu.observability import exporters, metrics, steptrace
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.static.graph import reset_default_programs


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    yield
    obs.disable()
    metrics.set_default_registry(metrics.MetricRegistry())


# -- registry semantics ------------------------------------------------------
class TestRegistry:
    def test_counter_inc_and_snapshot(self):
        r = metrics.MetricRegistry()
        c = r.counter("reqs_total", "requests")
        c.inc()
        c.inc(4)
        snap = r.snapshot()
        assert snap["reqs_total"]["type"] == "counter"
        assert snap["reqs_total"]["samples"] == [["reqs_total", {}, 5.0]]

    def test_counter_rejects_negative(self):
        r = metrics.MetricRegistry()
        with pytest.raises(ValueError):
            r.counter("c", "h").inc(-1)

    def test_labeled_children_are_distinct(self):
        r = metrics.MetricRegistry()
        g = r.gauge("depth", "queue depth", labelnames=("engine",))
        g.labels("a").set(3)
        g.labels("b").set(7)
        samples = {tuple(sorted(s[1].items())): s[2]
                   for s in r.snapshot()["depth"]["samples"]}
        assert samples[(("engine", "a"),)] == 3.0
        assert samples[(("engine", "b"),)] == 7.0

    def test_get_or_create_returns_same_metric(self):
        r = metrics.MetricRegistry()
        assert r.counter("c", "h") is r.counter("c", "h")

    def test_type_conflict_raises(self):
        r = metrics.MetricRegistry()
        r.counter("m", "h")
        with pytest.raises(ValueError):
            r.gauge("m", "h")

    def test_labelname_conflict_raises(self):
        r = metrics.MetricRegistry()
        r.gauge("g", "h", labelnames=("a",))
        with pytest.raises(ValueError):
            r.gauge("g", "h", labelnames=("b",))

    def test_histogram_buckets_cumulative(self):
        r = metrics.MetricRegistry()
        h = r.histogram("lat_ms", "latency", buckets=(1, 10, 100,
                                                      math.inf))
        for v in (0.5, 5, 5, 50, 5000):
            h.observe(v)
        by_le = {s[1]["le"]: s[2]
                 for s in r.snapshot()["lat_ms"]["samples"]
                 if s[0] == "lat_ms_bucket"}
        assert by_le == {"1": 1.0, "10": 3.0, "100": 4.0, "+Inf": 5.0}
        samples = {s[0]: s[2] for s in r.snapshot()["lat_ms"]["samples"]
                   if not s[0].endswith("_bucket")}
        assert samples["lat_ms_sum"] == pytest.approx(5060.5)
        assert samples["lat_ms_count"] == 5.0

    def test_sanitize_name(self):
        assert metrics.sanitize_name("a.b c-d") == "a_b_c_d"


# -- Prometheus exposition ---------------------------------------------------
class TestPrometheusRender:
    def test_golden_render(self):
        r = metrics.MetricRegistry()
        r.counter("steps_total", "steps run").inc(3)
        g = r.gauge("occ", "occupancy", labelnames=("engine",))
        g.labels('e"1').set(0.5)
        txt = exporters.render_prometheus(r)
        assert "# HELP steps_total steps run\n" in txt
        assert "# TYPE steps_total counter\n" in txt
        assert "steps_total 3\n" in txt
        # label values escaped per the 0.0.4 text format
        assert 'occ{engine="e\\"1"} 0.5' in txt

    def test_http_endpoint_serves_text(self):
        r = metrics.MetricRegistry()
        r.counter("hits_total", "hits").inc(2)
        exp = exporters.PrometheusExporter(r, port=-1)
        try:
            assert exp.port > 0
            resp = urllib.request.urlopen(exp.url, timeout=5)
            body = resp.read().decode()
            assert "text/plain" in resp.headers["Content-Type"]
            assert "hits_total 2" in body
        finally:
            exp.close()


# -- JSONL sink --------------------------------------------------------------
class TestJsonlSink:
    def test_write_merge(self, tmp_path):
        base = str(tmp_path / "obs.jsonl")
        for idx in (0, 1):
            r = metrics.MetricRegistry()
            r.counter("steps_total", "steps").inc(idx + 1)
            sink = exporters.JsonlSink(base, r, interval_s=3600,
                                       process_index=idx)
            sink.write_now()
            sink.close()
        p0 = exporters.process_jsonl_path(base, 0)
        recs = [json.loads(l) for l in open(p0)]
        assert recs[0]["process_index"] == 0
        assert recs[0]["metrics"]["steps_total"]["samples"][0][2] == 1.0
        merged = exporters.merge_jsonl(base)
        assert {r["process_index"] for r in merged} == {0, 1}
        assert [r["ts"] for r in merged] == sorted(r["ts"] for r in merged)

    def test_periodic_writes(self, tmp_path):
        base = str(tmp_path / "p.jsonl")
        r = metrics.MetricRegistry()
        sink = exporters.JsonlSink(base, r, interval_s=0.05,
                                   process_index=0)
        time.sleep(0.3)
        sink.close()
        lines = open(exporters.process_jsonl_path(base, 0)).readlines()
        assert len(lines) >= 2


# -- trace_events bridge -----------------------------------------------------
class TestBridge:
    def test_families_republished_as_gauges(self):
        r = metrics.MetricRegistry()
        metrics.install_bridge(r)
        try:
            trace_events.notify(("executor_cache", "executor#1"),
                                {"hits": 5, "misses": 2})
            trace_events.notify(("serving", "engine#1"),
                                {"queue_depth": 3})
            trace_events.notify(("resilience", "retry:r"),
                                {"retries": 1})
            trace_events.notify(("autotune", "flash_fwd"),
                                {"counters": {"searches": 4}})
            trace_events.notify(("steptrace", "train"),
                                {"steps": 7})
            snap = r.snapshot()
            def val(name):
                return snap[name]["samples"][0][2]
            assert val("paddle_tpu_executor_cache_hits") == 5.0
            assert val("paddle_tpu_serving_queue_depth") == 3.0
            assert val("paddle_tpu_resilience_retries") == 1.0
            # nested counter dicts flatten one level
            assert val("paddle_tpu_autotune_counters_searches") == 4.0
            assert val("paddle_tpu_steptrace_steps") == 7.0
            assert (snap["paddle_tpu_executor_cache_hits"]["samples"][0][1]
                    == {"executor": "executor#1"})
        finally:
            metrics.uninstall_bridge()

    def test_bridge_idempotent(self):
        r = metrics.MetricRegistry()
        metrics.install_bridge(r)
        metrics.install_bridge(r)
        try:
            trace_events.notify(("serving", "e"), {"requests": 1})
            # one observer registered, not two: gauge holds the value once
            assert (r.snapshot()["paddle_tpu_serving_requests"]
                    ["samples"][0][2]) == 1.0
        finally:
            metrics.uninstall_bridge()
        assert not metrics.bridge_installed()

    def test_monitor_collector(self):
        r = metrics.MetricRegistry()
        metrics.install_standard_collectors(r)
        monitor.stat_add("obs_test_stat", 11)
        snap = r.snapshot()
        vals = {s[1].get("stat"): s[2]
                for s in snap["paddle_tpu_monitor"]["samples"]}
        assert vals["obs_test_stat"] == 11.0


# -- satellite: trace_events observer isolation ------------------------------
class TestNotifyIsolation:
    def test_raising_subscriber_does_not_break_others(self):
        got = []
        before = trace_events.dropped_notifications()

        def bad(site, info):
            raise RuntimeError("observer bug")

        def good(site, info):
            got.append(site)

        trace_events.register(bad)
        trace_events.register(good)
        try:
            trace_events.notify(("serving", "e"), {"requests": 1})
        finally:
            trace_events.unregister(bad)
            trace_events.unregister(good)
        assert got == [("serving", "e")]
        assert trace_events.dropped_notifications() == before + 1


# -- satellite: profiler span cap -------------------------------------------
class TestSpanCap:
    def test_drops_counted_and_reported(self, tmp_path, monkeypatch):
        monkeypatch.setattr(prof, "_SPAN_CAP", 2)
        prof.reset_profiler()
        prof.start_profiler()
        for i in range(5):
            with prof.RecordEvent(f"s{i}"):
                pass
        prof.stop_profiler(profile_path=None)
        assert prof.dropped_spans() == 3
        assert "3 span(s) dropped" in prof.summary()
        path = str(tmp_path / "t.json")
        assert prof.export_chrome_tracing(path) == 2
        data = json.load(open(path))
        assert data["otherData"]["dropped_spans"] == 3
        prof.reset_profiler()
        assert prof.dropped_spans() == 0

    def test_record_span_noop_when_not_profiling(self):
        prof.reset_profiler()
        assert prof.record_span("x", time.perf_counter(), 1.0) is False


# -- satellite: serving quantile fix ----------------------------------------
class TestServingQuantile:
    def test_ceil_rank_known_values(self):
        from paddle_tpu.serving.metrics import _quantile as q
        vals = [1, 2, 3, 4]
        assert q(vals, 0.25) == 1
        assert q(vals, 0.5) == 2
        assert q(vals, 0.75) == 3
        assert q(vals, 0.99) == 4
        assert q(vals, 1.0) == 4
        assert q([7], 0.99) == 7
        assert q([], 0.5) == 0.0

    def test_observe_span_feeds_snapshot(self):
        m = ServingMetrics("qtest")
        for ms in (1.0, 2.0, 3.0, 4.0):
            m.observe_span(queue_ms=ms, execute_ms=10 * ms)
        snap = m.snapshot()
        assert snap["queue_p50_ms"] == 2.0
        assert snap["execute_p99_ms"] == 40.0


# -- serving spans in the chrome trace --------------------------------------
class TestServingSpans:
    def test_batcher_emits_queue_execute_spans(self, tmp_path):
        from paddle_tpu.serving.batcher import MicroBatcher

        prof.reset_profiler()
        prof.start_profiler()
        try:
            with MicroBatcher(lambda ins: 0,
                              lambda bucket, reqs: [0] * len(reqs),
                              max_batch_size=2, max_queue_delay_ms=1.0,
                              name="spantest") as mb:
                mb.submit(([1],)).result(10)
        finally:
            prof.stop_profiler(profile_path=None)
        path = str(tmp_path / "t.json")
        prof.export_chrome_tracing(path)
        evs = json.load(open(path))["traceEvents"]
        serving = [e for e in evs if e.get("cat") == "serving"]
        names = {e["name"] for e in serving}
        assert "spantest/queue" in names and "spantest/execute" in names
        spans = {e["args"]["span"] for e in serving}
        assert len(spans) == 1  # one request, one span id on both events
        prof.reset_profiler()


# -- steptrace ---------------------------------------------------------------
class TestStepTrace:
    def _train(self, n=4):
        paddle.seed(0)
        reset_default_programs()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            y = fluid.data("y", [-1, 1])
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        for _ in range(n):
            exe.run(main, feed={"x": rng.rand(8, 4).astype(np.float32),
                                "y": rng.rand(8, 1).astype(np.float32)},
                    fetch_list=[loss])
        reset_default_programs()

    def test_executor_run_feeds_telemetry(self):
        r = metrics.MetricRegistry()
        obs.enable(registry=r)
        self._train(n=4)
        st = steptrace.active()
        snap = st.snapshot()
        assert snap["steps"] == 4
        assert snap["examples"] == 32
        assert snap["warmup_dispatches"] == 1
        assert snap["steps_post_warm"] == 3
        assert snap["dispatch_ms"] > 0
        reg = r.snapshot()
        assert (reg["paddle_tpu_steps_total"]["samples"][0][2]) == 4.0
        assert (reg["paddle_tpu_examples_total"]["samples"][0][2]) == 32.0

    def test_data_wait_recorded_from_dataloader(self):
        r = metrics.MetricRegistry()
        obs.enable(registry=r)
        from paddle_tpu.io import DataLoader, TensorDataset

        ds = TensorDataset([np.arange(16, dtype=np.float32).reshape(16, 1),
                            np.zeros((16, 1), np.float32)])
        for _ in DataLoader(ds, batch_size=4):
            pass
        # the blocking get was timed at least once per batch
        count = [s[2] for s
                 in r.snapshot()["paddle_tpu_data_wait_ms"]["samples"]
                 if s[0] == "paddle_tpu_data_wait_ms_count"]
        assert count and count[0] >= 4

    def test_summary_section_renders(self):
        obs.enable()
        self._train(n=3)
        text = steptrace.render_summary_section()
        assert "Training telemetry" in text
        assert "data wait" in text
        # the profiler summary embeds the same section
        assert "Training telemetry" in prof.summary()

    def test_disabled_means_no_active_hook(self):
        assert steptrace._active is None
        assert steptrace.render_summary_section() == ""

    def test_estimate_flops_cpu(self):
        import jax

        f = jax.jit(lambda a, b: a @ b)
        x = np.ones((8, 8), np.float32)
        flops = steptrace.estimate_flops(f, x, x)
        assert flops and flops > 0


# -- analysis rules M901 / M902 ---------------------------------------------
class TestTelemetryRules:
    def test_m901_data_starved(self):
        with RetraceMonitor(budget=2) as mon:
            trace_events.notify(("steptrace", "train"), {
                "steps_post_warm": 10, "data_wait_ms": 900.0,
                "dispatch_ms": 50.0, "device_ms": 50.0,
                "hbm_peak_bytes": 0, "hbm_limit_bytes": 0,
                "hbm_threshold": 0.9,
            })
        diags = mon.diagnostics()
        assert [d.rule for d in diags] == ["M901"]
        assert "input pipeline" in diags[0].message
        assert mon.steptrace_stats("train")["steps_post_warm"] == 10

    def test_m901_quiet_when_device_bound(self):
        with RetraceMonitor(budget=2) as mon:
            trace_events.notify(("steptrace", "train"), {
                "steps_post_warm": 10, "data_wait_ms": 10.0,
                "dispatch_ms": 500.0, "device_ms": 400.0,
                "hbm_peak_bytes": 0, "hbm_limit_bytes": 0,
                "hbm_threshold": 0.9,
            })
        assert mon.diagnostics() == []

    def test_m902_hbm_high_water(self):
        G = 2 ** 30
        with RetraceMonitor() as mon:
            trace_events.notify(("steptrace", "train"), {
                "steps_post_warm": 1, "data_wait_ms": 0.0,
                "dispatch_ms": 1.0, "device_ms": 1.0,
                "hbm_peak_bytes": 15 * G, "hbm_limit_bytes": 16 * G,
                "hbm_threshold": 0.9,
            })
        diags = mon.diagnostics()
        assert [d.rule for d in diags] == ["M902"]
        assert "HBM" in diags[0].message

    def test_m902_quiet_below_threshold(self):
        G = 2 ** 30
        with RetraceMonitor() as mon:
            trace_events.notify(("steptrace", "train"), {
                "steps_post_warm": 1, "data_wait_ms": 0.0,
                "dispatch_ms": 1.0, "device_ms": 1.0,
                "hbm_peak_bytes": 8 * G, "hbm_limit_bytes": 16 * G,
                "hbm_threshold": 0.9,
            })
        assert mon.diagnostics() == []


# -- enable / disable lifecycle ----------------------------------------------
class TestLifecycle:
    def test_enable_disable_roundtrip(self, tmp_path):
        base = str(tmp_path / "m.jsonl")
        obs.enable(port=-1, jsonl=base, jsonl_interval_s=3600)
        status = obs.status()
        assert status["enabled"] and status["port"] > 0
        assert metrics.bridge_installed()
        assert steptrace.active() is not None
        obs.disable()
        status = obs.status()
        assert not status["enabled"] and status["port"] is None
        assert steptrace._active is None

    def test_maybe_enable_from_flags_off_by_default(self):
        assert obs.maybe_enable_from_flags() is False
        assert not obs.enabled()

    def test_maybe_enable_from_flags_port(self):
        from paddle_tpu.framework.flags import set_flags

        set_flags({"metrics_port": -1})
        try:
            assert obs.maybe_enable_from_flags() is True
            assert obs.status()["port"] > 0
        finally:
            set_flags({"metrics_port": 0})
            obs.disable()

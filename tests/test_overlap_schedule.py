"""Sharded-decode overlap schedules (distributed/collective.py dials +
tuning/plan_space.py measured search).

The dials are trace-time placement hints for GSPMD — semantics-
preserving by construction — so CPU equivalence (same values under
every schedule) plus search/cache/counter machinery is the whole
testable surface here; which schedule WINS is a real-chip question the
serving warmup answers (``GenerationEngine._tune_overlap_schedule``).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.collective import (all_reduce_finish,
                                               all_reduce_start,
                                               get_overlap_schedule,
                                               overlap_schedule,
                                               set_overlap_schedule)
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.tuning import engine, plan_space


@pytest.fixture(autouse=True)
def _clean_state():
    engine.clear_cache()
    engine.reset_counters()
    engine.reset_warm()
    yield
    set_overlap_schedule({k: 0 for k in get_overlap_schedule()})
    set_flags({"measured_search": "on", "kernel_tuning_cache": ""})
    engine.clear_cache()
    engine.reset_counters()
    engine.reset_warm()


class TestDialRegistry:
    def test_set_get_restore(self):
        assert get_overlap_schedule() == {"defer_row_reduce": 0,
                                          "mlp_collective_split": 0}
        prev = set_overlap_schedule(defer_row_reduce=1)
        assert prev["defer_row_reduce"] == 0
        assert get_overlap_schedule()["defer_row_reduce"] == 1
        set_overlap_schedule(prev)
        assert get_overlap_schedule()["defer_row_reduce"] == 0

    def test_unknown_dial_rejected(self):
        with pytest.raises(InvalidArgumentError):
            set_overlap_schedule(warp_speed=1)

    def test_context_manager_restores_on_error(self):
        with pytest.raises(RuntimeError):
            with overlap_schedule(mlp_collective_split=1):
                assert get_overlap_schedule()["mlp_collective_split"] == 1
                raise RuntimeError("trace failed")
        assert get_overlap_schedule()["mlp_collective_split"] == 0

    def test_start_finish_pair_is_a_psum(self):
        # the pair is a scheduling seam: the reduce's value is exactly
        # lax.psum, and work between start and finish is data-independent
        def f(x):
            h = all_reduce_start(x, "i")
            local = x * 2.0  # overlappable work
            return all_reduce_finish(h) + local

        x = jnp.arange(4.0)
        out = jax.vmap(f, axis_name="i")(x)
        np.testing.assert_allclose(np.asarray(out),
                                   x.sum() + 2.0 * np.asarray(x))


class TestScheduleEquivalence:
    def test_row_parallel_defer_is_value_preserving(self):
        from paddle_tpu.distributed.meta_parallel import RowParallelLinear

        layer = RowParallelLinear(16, 8)
        x = jnp.asarray(np.random.RandomState(0).randn(4, 16),
                        jnp.float32)
        base = jax.jit(layer)(x)
        with overlap_schedule(defer_row_reduce=1):
            deferred = jax.jit(layer)(x)
        np.testing.assert_array_equal(np.asarray(base),
                                      np.asarray(deferred))

    def test_gpt_forward_identical_under_every_schedule(self):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2,
                        num_heads=4, max_position=32, dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        ids = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
        base = np.asarray(model(ids))
        for cand in plan_space.decode_schedule_candidates()[1:]:
            with overlap_schedule(cand):
                out = np.asarray(model(ids))
            np.testing.assert_array_equal(base, out)


class TestMeasuredSearch:
    def test_candidates_full_product_base_first(self):
        cands = plan_space.decode_schedule_candidates()
        assert cands[0] == {"defer_row_reduce": 0,
                            "mlp_collective_split": 0}
        assert len(cands) == 4  # 2 dials x {0,1}, base deduped
        assert len({tuple(sorted(c.items())) for c in cands}) == 4

    def test_search_persists_and_replays(self, tmp_path):
        set_flags({"kernel_tuning_cache": str(tmp_path / "tune.json")})

        def score(cfg):  # deterministic: full overlap wins
            return 10.0 - 4.0 * cfg["defer_row_reduce"] \
                - 2.0 * cfg["mlp_collective_split"]

        win = plan_space.tune_decode_schedule("B8xT5xC256", measure=score)
        assert win == {"defer_row_reduce": 1, "mlp_collective_split": 1}
        c = engine.get_counters("decode_schedule:B8xT5xC256")
        assert c["searches"] == 1 and c["configs_timed"] == 4

        # warm replay: memory hit, zero further searches
        again = plan_space.tune_decode_schedule("B8xT5xC256", measure=score)
        assert again == win
        c = engine.get_counters("decode_schedule:B8xT5xC256")
        assert c["searches"] == 1 and c["hits"] == 1

        # cold-process replay: disk hit, zero searches (K701 stays
        # silent on a warm restart)
        engine.clear_cache(memory=True, disk=False)
        engine.reset_counters()
        disk = plan_space.tune_decode_schedule("B8xT5xC256", measure=score)
        assert disk == win
        c = engine.get_counters("decode_schedule:B8xT5xC256")
        assert c["searches"] == 0 and c["disk_hits"] == 1

    def test_search_off_returns_base_untimed(self):
        set_flags({"measured_search": "off"})
        calls = []
        win = plan_space.tune_decode_schedule(
            "off", measure=lambda cfg: calls.append(cfg) or 0.0)
        assert win == {"defer_row_reduce": 0, "mlp_collective_split": 0}
        assert not calls
        assert engine.get_counters("decode_schedule:off")["heuristic"] == 1

    def test_apply_returns_previous(self):
        prev = plan_space.apply_decode_schedule({"defer_row_reduce": 1})
        assert prev == {"defer_row_reduce": 0, "mlp_collective_split": 0}
        assert get_overlap_schedule() == {"defer_row_reduce": 1,
                                          "mlp_collective_split": 0}
        plan_space.apply_decode_schedule(prev)
        assert get_overlap_schedule()["defer_row_reduce"] == 0

"""Monitor stat registry + flag-consumer wiring.

Reference capability: platform/monitor.h:44 StatRegistry (STAT_ADD etc.)
and glog VLOG gated by verbosity.  Asserts real framework subsystems
actually bump the counters (train steps, checkpoint saves, staging bytes,
ingest samples) and that log_level/paddle_num_threads are consumed.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.framework import monitor
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.framework.logging import vlog


@pytest.fixture(autouse=True)
def clean():
    monitor.reset_stat()
    yield
    monitor.reset_stat()
    set_flags({"log_level": 0, "paddle_num_threads": 1})


class TestRegistry:
    def test_add_sub_get_reset(self):
        assert monitor.stat_add("x", 5) == 5
        assert monitor.stat_add("x") == 6
        assert monitor.stat_sub("x", 2) == 4
        assert monitor.get_stat("x") == 4
        assert monitor.get_stat("unknown") == 0
        monitor.stat_set("y", 9)
        assert monitor.all_stats() == {"x": 4, "y": 9}
        monitor.reset_stat("x")
        assert monitor.get_stat("x") == 0
        assert monitor.get_stat("y") == 9

    def test_train_steps_counted(self):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 2))
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=popt.SGD(learning_rate=0.1),
                  loss=nn.CrossEntropyLoss())
        x = np.zeros((4, 4), np.float32)
        y = np.zeros((4,), np.int32)
        before = monitor.get_stat("total_train_steps")
        for _ in range(3):
            m.train_batch([x], [y])
        assert monitor.get_stat("total_train_steps") == before + 3

    def test_checkpoint_saves_counted(self, tmp_path):
        from paddle_tpu.incubate.checkpoint import AutoCheckpoint

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 2))
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=popt.SGD(learning_rate=0.1),
                  loss=nn.CrossEntropyLoss())
        acp = AutoCheckpoint(m, os.path.join(tmp_path, "ck"),
                             async_save=False)
        acp.epoch_end(0)
        acp.epoch_end(1)
        assert monitor.get_stat("checkpoint_saves") == 2

    def test_staging_bytes_counted(self):
        from paddle_tpu.io import DataLoader, TensorDataset

        X = np.zeros((8, 4), np.float32)
        loader = DataLoader(TensorDataset([X]), batch_size=4)
        for _ in loader:
            pass
        assert monitor.get_stat("host_to_device_bytes") >= X.nbytes

    def test_ingest_samples_counted(self, tmp_path):
        from paddle_tpu.io import InMemoryDataset

        p = os.path.join(tmp_path, "a.txt")
        with open(p, "w") as f:
            f.write("1 2\n3 4\n")
        ds = InMemoryDataset(slots=[("x", 2, "float32")])
        ds.set_filelist([p])
        ds.load_into_memory()
        assert monitor.get_stat("ingest_samples") == 2


class TestFlagConsumers:
    def test_vlog_gated(self, capsys):
        vlog(1, "hidden %d", 1)
        assert capsys.readouterr().err == ""
        set_flags({"log_level": 2})
        vlog(1, "shown %d", 2)
        assert "shown 2" in capsys.readouterr().err

    def test_fleet_init_logs_mesh_at_v1(self, capsys):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh

        set_flags({"log_level": 1})
        fleet._initialized = False
        try:
            fleet.init(is_collective=True,
                       strategy=fleet.DistributedStrategy())
            assert "fleet.init: mesh" in capsys.readouterr().err
        finally:
            fleet._initialized = False
            fleet._strategy = None
            set_mesh(build_mesh())

    def test_paddle_num_threads_default(self, tmp_path):
        """InMemoryDataset honors FLAGS_paddle_num_threads as default."""
        from paddle_tpu.io import InMemoryDataset

        files = []
        for i in range(4):
            p = os.path.join(tmp_path, f"p{i}.txt")
            with open(p, "w") as f:
                f.write(f"{i} {i}\n")
            files.append(p)
        set_flags({"paddle_num_threads": 4})
        ds = InMemoryDataset(slots=[("x", 2, "float32")])
        ds.set_filelist(files)
        assert ds.load_into_memory() == 4  # thread_num=None → flag value

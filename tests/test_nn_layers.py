"""Layer system tests: registration, state_dict, functional_call/jit bridge,
and layer forward correctness."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def check(actual, expected, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(actual), expected, rtol=rtol, atol=atol)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)
        self.drop = nn.Dropout(0.5)

    def forward(self, x):
        return self.fc2(self.drop(F.relu(self.fc1(x))))


class TestLayerSystem:
    def test_registration_traversal(self):
        m = MLP()
        names = [n for n, _ in m.named_parameters()]
        assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
        assert len(m.parameters()) == 4
        assert len(m.sublayers()) == 3

    def test_state_dict_roundtrip(self):
        m1, m2 = MLP(), MLP()
        sd = m1.state_dict()
        assert set(sd) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
        m2.set_state_dict(sd)
        x = pt.randn([2, 4])
        m1.eval()
        m2.eval()
        check(m2(x), np.asarray(m1(x)))

    def test_state_dict_shape_mismatch(self):
        m = MLP()
        bad = {"fc1.weight": np.zeros((3, 3), np.float32)}
        with pytest.raises(Exception):
            m.set_state_dict(bad)

    def test_train_eval_modes(self):
        m = MLP()
        m.eval()
        assert not m.drop.training
        m.train()
        assert m.drop.training

    def test_eager_forward_dropout(self):
        pt.seed(0)
        m = MLP()
        x = pt.randn([16, 4])
        m.eval()
        out1 = np.asarray(m(x))
        out2 = np.asarray(m(x))
        np.testing.assert_array_equal(out1, out2)  # eval: deterministic
        m.train()
        o1 = np.asarray(m(x))
        o2 = np.asarray(m(x))
        assert not np.array_equal(o1, o2)  # train: dropout differs

    def test_functional_call_pure(self):
        m = MLP().eval()
        params = m.param_pytree()
        x = pt.randn([3, 4])
        out_direct = np.asarray(m(x))
        out_fc = np.asarray(nn.functional_call(m, params, x))
        np.testing.assert_array_equal(out_direct, out_fc)
        # substituting zeros changes output but not the layer's stored params
        zeros = {k: jnp.zeros_like(v) for k, v in params.items()}
        out_zero = nn.functional_call(m, zeros, x)
        check(out_zero, np.zeros((3, 2), np.float32))
        np.testing.assert_array_equal(np.asarray(m(x)), out_direct)

    def test_functional_call_jit_grad(self):
        m = MLP().eval()
        params = m.param_pytree()
        x = pt.randn([8, 4])
        y = pt.randn([8, 2])

        @jax.jit
        def loss_fn(p, x, y):
            pred = nn.functional_call(m, p, x)
            return jnp.mean((pred - y) ** 2)

        g = jax.grad(loss_fn)(params, x, y)
        assert set(g) == set(params)
        assert all(g[k].shape == params[k].shape for k in params)
        assert float(jnp.abs(g["fc1.weight"]).sum()) > 0

    def test_functional_call_rngs_deterministic(self):
        m = MLP().train()
        params = m.param_pytree()
        x = pt.randn([4, 4])
        k = jax.random.PRNGKey(0)
        o1 = np.asarray(nn.functional_call(m, params, x, rngs=k, training=True))
        o2 = np.asarray(nn.functional_call(m, params, x, rngs=k, training=True))
        np.testing.assert_array_equal(o1, o2)
        o3 = np.asarray(nn.functional_call(m, params, x, rngs=jax.random.PRNGKey(1), training=True))
        assert not np.array_equal(o1, o3)

    def test_bn_buffers_functional(self):
        bn = nn.BatchNorm2D(3)
        x = pt.randn([4, 3, 2, 2])
        params = bn.param_pytree()
        bufs = bn.buffer_pytree()
        out, new_bufs = nn.functional_call(bn, params, x, buffers=bufs,
                                           training=True, return_buffers=True)
        # captured functionally, eager state unchanged
        check(bn._mean.value, np.zeros(3, np.float32))
        assert not np.allclose(np.asarray(new_bufs["_mean"]), 0.0)
        # eager call mutates
        bn(x)
        assert not np.allclose(np.asarray(bn._mean.value), 0.0)

    def test_bn_under_jit(self):
        bn = nn.BatchNorm2D(3)
        params = bn.param_pytree()
        bufs = bn.buffer_pytree()

        @jax.jit
        def step(p, b, x):
            out, nb = nn.functional_call(bn, p, x, buffers=b, training=True,
                                         return_buffers=True)
            return out, nb

        x = pt.randn([4, 3, 2, 2])
        out, nb = step(params, bufs, x)
        assert out.shape == x.shape
        # no tracer leak into the layer
        assert isinstance(bn._mean.value, jax.Array)
        check(bn._mean.value, np.zeros(3, np.float32))

    def test_to_dtype(self):
        m = MLP()
        m.to(dtype="bfloat16")
        assert m.fc1.weight.dtype == pt.bfloat16

    def test_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        m(pt.ones([1, 2]))
        assert calls == [1]
        h.remove()
        m(pt.ones([1, 2]))
        assert calls == [1]


class TestContainers:
    def test_sequential(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        out = m(pt.randn([3, 4]))
        assert out.shape == (3, 2)
        assert len(m) == 3
        assert isinstance(m[1], nn.ReLU)

    def test_layer_list_dict(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4
        assert len(list(ll.parameters())) == 8
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        ld["b"] = nn.Linear(2, 3)
        assert "b" in ld and len(ld) == 2

    def test_parameter_list(self):
        pl = nn.ParameterList([nn.Parameter(jnp.ones((2,)))])
        pl.append(nn.Parameter(jnp.zeros((3,))))
        assert len(pl.parameters()) == 2


class TestLayers:
    def test_conv2d_layer(self):
        conv = nn.Conv2D(3, 8, 3, padding=1)
        out = conv(pt.randn([2, 3, 8, 8]))
        assert out.shape == (2, 8, 8, 8)
        assert conv.weight.shape == (8, 3, 3, 3)

    def test_conv_transpose_layer(self):
        conv = nn.Conv2DTranspose(4, 2, 3, stride=2)
        out = conv(pt.randn([1, 4, 5, 5]))
        assert out.shape == (1, 2, 11, 11)

    def test_bn_layer_stats_update(self):
        bn = nn.BatchNorm2D(2, momentum=0.5)
        x = pt.to_tensor(np.random.RandomState(0).rand(8, 2, 3, 3).astype(np.float32))
        bn.train()
        bn(x)
        mu = np.asarray(x).mean((0, 2, 3))
        check(bn._mean.value, 0.5 * mu, rtol=1e-4)
        bn.eval()
        out = bn(x)
        assert out.shape == x.shape

    def test_embedding_layer(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(pt.to_tensor([[1, 2], [0, 3]], "int64"))
        assert out.shape == (2, 2, 4)
        assert (np.asarray(out)[1, 0] == 0).all()

    def test_layernorm_layer(self):
        ln = nn.LayerNorm(6)
        out = ln(pt.randn([2, 3, 6]))
        arr = np.asarray(out)
        np.testing.assert_allclose(arr.mean(-1), 0, atol=1e-5)
        np.testing.assert_allclose(arr.std(-1), 1, atol=2e-2)

    def test_rnn_layers(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = pt.randn([3, 5, 4])  # (B, T, I)
        out, (h, c) = lstm(x)
        assert out.shape == (3, 5, 8)
        assert h.shape == (2, 3, 8) and c.shape == (2, 3, 8)

    def test_rnn_bidirectional(self):
        gru = nn.GRU(4, 6, direction="bidirect")
        out, h = gru(pt.randn([2, 7, 4]))
        assert out.shape == (2, 7, 12)
        assert h.shape == (2, 2, 6)

    def test_rnn_sequence_length(self):
        rnn = nn.SimpleRNN(3, 5)
        x = pt.randn([2, 6, 3])
        out, h = rnn(x, sequence_length=pt.to_tensor([6, 2], "int64"))
        arr = np.asarray(out)
        assert (arr[1, 2:] == 0).all()  # padded steps zeroed
        assert not (arr[1, :2] == 0).all()

    def test_lstm_cell(self):
        cell = nn.LSTMCell(3, 4)
        h, (h2, c2) = cell(pt.randn([2, 3]))
        assert h.shape == (2, 4)
        np.testing.assert_array_equal(np.asarray(h), np.asarray(h2))

    def test_transformer_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        enc.eval()
        out = enc(pt.randn([2, 5, 16]))
        assert out.shape == (2, 5, 16)

    def test_transformer_full(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32, dropout=0.0)
        model.eval()
        out = model(pt.randn([2, 4, 16]), pt.randn([2, 3, 16]))
        assert out.shape == (2, 3, 16)

    def test_mha_mask_and_cache(self):
        mha = nn.MultiHeadAttention(8, 2, dropout=0.0)
        mha.eval()
        q = pt.randn([1, 4, 8])
        mask = jnp.tril(jnp.ones((4, 4), bool))
        out = mha(q, attn_mask=mask)
        assert out.shape == (1, 4, 8)
        cache = mha.gen_cache(q)
        o1, cache = mha(q[:, :1], q[:, :1], q[:, :1], cache=cache)
        o2, cache = mha(q[:, 1:2], q[:, 1:2], q[:, 1:2], cache=cache)
        assert cache[0].shape == (1, 2, 2, 4)

    def test_transformer_jit_grad(self):
        layer = nn.TransformerEncoderLayer(8, 2, 16, dropout=0.0)
        layer.eval()
        params = layer.param_pytree()
        x = pt.randn([2, 3, 8])

        @jax.jit
        def loss(p, x):
            return jnp.sum(nn.functional_call(layer, p, x) ** 2)

        g = jax.grad(loss)(params, x)
        assert all(float(jnp.abs(v).sum()) > 0 for v in g.values())

    def test_groupnorm_prelu_spectral(self):
        gn = nn.GroupNorm(2, 4)
        assert gn(pt.randn([2, 4, 3, 3])).shape == (2, 4, 3, 3)
        pr = nn.PReLU(4)
        assert pr(pt.randn([2, 4, 2, 2])).shape == (2, 4, 2, 2)

    def test_initializers(self):
        from paddle_tpu.nn import initializer as I

        pt.seed(0)
        w = I.XavierUniform()((100, 100), "float32")
        limit = np.sqrt(6 / 200)
        arr = np.asarray(w)
        assert arr.min() >= -limit and arr.max() <= limit
        k = I.KaimingNormal()((100, 100), "float32")
        assert abs(np.asarray(k).std() - np.sqrt(2 / 100)) < 0.01
        c = I.Constant(3.0)((2, 2), "float32")
        check(c, np.full((2, 2), 3.0))
        a = I.Assign(np.eye(2))((2, 2), "float32")
        check(a, np.eye(2))

    def test_param_attr(self):
        lin = nn.Linear(2, 3, weight_attr=pt.ParamAttr(
            initializer=nn.initializer.Constant(0.5), trainable=False))
        check(lin.weight.value, np.full((2, 3), 0.5))
        assert not lin.weight.trainable
        assert len(lin.param_pytree(trainable_only=True)) == 1  # only bias


class TestRNNStateHelpers:
    """split_states/concat_states (reference: nn/layer/rnn.py:49,102)."""

    def test_roundtrip_single_component(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        # L=2 layers, D=2 directions, N=3 batch, C=4 hidden
        h = jnp.asarray(rng.randn(4, 3, 4), jnp.float32)
        cells = nn.split_states(h, bidirectional=True)
        assert len(cells) == 2 and len(cells[0]) == 2
        np.testing.assert_array_equal(
            np.asarray(nn.concat_states(cells, bidirectional=True)),
            np.asarray(h))

    def test_roundtrip_lstm_components(self):
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        h = jnp.asarray(rng.randn(4, 3, 4), jnp.float32)
        c = jnp.asarray(rng.randn(4, 3, 4), jnp.float32)
        cells = nn.split_states((h, c), bidirectional=False,
                                state_components=2)
        assert len(cells) == 4 and len(cells[0]) == 2
        back = nn.concat_states(cells, state_components=2)
        np.testing.assert_array_equal(np.asarray(back[0]), np.asarray(h))
        np.testing.assert_array_equal(np.asarray(back[1]), np.asarray(c))

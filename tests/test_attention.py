"""Long-context attention tests: pallas flash attention (interpret mode on
the CPU mesh exercises the exact kernel code), ring attention and Ulysses
on the 8-device mesh vs the naive full-attention oracle — forward AND
gradients."""
import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.ops import flash_attention
from paddle_tpu.ops.flash_attention import _naive_reference


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())


def make_qkv(rng, B=2, H=4, S=64, D=16, K=None):
    K = K or S
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, K, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, K, D).astype(np.float32))
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_naive(self, rng, causal):
        q, k, v = make_qkv(rng)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        ref = _naive_reference(q, k, v, causal, 1.0 / math.sqrt(16))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_multi_block_online_softmax(self, rng):
        # several kv blocks with extreme values stress the running max
        q, k, v = make_qkv(rng, S=64)
        q = q * 5.0
        out = flash_attention(q, k, v, block_q=16, block_k=8)
        ref = _naive_reference(q, k, v, False, 1.0 / math.sqrt(16))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_naive(self, rng, causal):
        q, k, v = make_qkv(rng, B=1, H=2, S=32, D=8)
        scale = 1.0 / math.sqrt(8)

        def f_flash(q, k, v):
            return (flash_attention(q, k, v, causal=causal, block_q=8,
                                    block_k=8) ** 2).sum()

        def f_ref(q, k, v):
            return (_naive_reference(q, k, v, causal, scale) ** 2).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_cross_attention_kv_longer(self, rng):
        q, k, v = make_qkv(rng, S=16, K=64)
        out = flash_attention(q, k, v, block_q=8, block_k=16)
        ref = _naive_reference(q, k, v, False, 1.0 / math.sqrt(16))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_q_position_offset(self, rng):
        """Offset causal masking: q rows at global positions 16..31."""
        q, k, v = make_qkv(rng, S=16, K=64)
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=16,
                              q_position_offset=16)
        ref = _naive_reference(q, k, v, True, 1.0 / math.sqrt(16), q_offset=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ragged_pad_and_mask(self, rng):
        # 24 % 16 != 0 → padded to block multiples + kv-length masking,
        # still the kernel path (there is no O(S²) fallback any more)
        q, k, v = make_qkv(rng, S=24)
        out = flash_attention(q, k, v, block_q=16, block_k=16)
        ref = _naive_reference(q, k, v, False, 1.0 / math.sqrt(16))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ragged_grads_match_naive(self, rng, causal):
        # grad parity through the padded kernel path, q-seq ≠ kv-seq,
        # neither block-aligned, non-aligned causal offset
        B, H, S, K, D = 1, 2, 25, 40, 16
        q = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, H, K, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, K, D)), jnp.float32)
        off = 7 if causal else 0

        def f_flash(q, k, v):
            return (flash_attention(q, k, v, causal=causal, block_q=16,
                                    block_k=16, q_position_offset=off)
                    ** 2).sum()

        def f_ref(q, k, v):
            return (_naive_reference(q, k, v, causal, 1.0 / math.sqrt(D),
                                     q_offset=off) ** 2).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    def test_backward_no_score_sized_tensors(self):
        # structural O(S) assertion: no [.., S, K] score-shaped aval may
        # appear anywhere in the vjp jaxpr — fwd and bwd are both Pallas
        # kernels, so scores live only in VMEM tiles
        B, H, S, D = 1, 1, 512, 32
        q = jnp.zeros((B, H, S, D))
        k = jnp.zeros((B, H, S, D))
        v = jnp.zeros((B, H, S, D))

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).sum()

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name == "pallas_call":
                    # kernel-internal tiles live in VMEM scratch; with
                    # block == S a single tile is legitimately S-sized —
                    # the assertion is about HBM-resident XLA values
                    continue
                for var in list(eqn.outvars) + list(eqn.invars):
                    aval = getattr(var, "aval", None)
                    if aval is not None and getattr(aval, "shape", None):
                        assert not (len(aval.shape) >= 2
                                    and aval.shape[-1] == S
                                    and aval.shape[-2] == S), (
                            f"score-sized tensor {aval.shape} in {eqn}")
                for sub in eqn.params.values():
                    if hasattr(sub, "eqns"):
                        walk(sub)
                    if hasattr(sub, "jaxpr") and hasattr(sub.jaxpr, "eqns"):
                        walk(sub.jaxpr)

        walk(jaxpr.jaxpr)

    def test_bf16_inputs(self, rng):
        q, k, v = make_qkv(rng)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        out = flash_attention(qb, kb, vb, block_q=16, block_k=16)
        assert out.dtype == jnp.bfloat16
        ref = _naive_reference(q, k, v, False, 1.0 / math.sqrt(16))
        np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                                   rtol=5e-2, atol=5e-2)


class TestRingAttention:
    def _mesh(self, sep=8):
        set_mesh(build_mesh(sep=sep, dp=1))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, rng, causal):
        self._mesh()
        q, k, v = make_qkv(rng, B=2, H=2, S=64, D=8)
        out = dist.ring_attention_sharded(q, k, v, causal=causal)
        ref = _naive_reference(q, k, v, causal, 1.0 / math.sqrt(8))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_extreme_scores_stable(self, rng):
        self._mesh()
        q, k, v = make_qkv(rng, B=1, H=1, S=32, D=8)
        q = q * 20.0  # large logits stress the lse merge
        out = dist.ring_attention_sharded(q, k, v, causal=True)
        ref = _naive_reference(q, k, v, True, 1.0 / math.sqrt(8))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_gradients_flow(self, rng):
        set_mesh(build_mesh(sep=4, dp=1, devices=jax.devices()[:4]))
        q, k, v = make_qkv(rng, B=1, H=2, S=16, D=8)

        def f(q, k, v):
            return (dist.ring_attention_sharded(q, k, v, causal=True) ** 2).sum()

        def f_ref(q, k, v):
            return (_naive_reference(q, k, v, True, 1.0 / math.sqrt(8)) ** 2).sum()

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    def test_jit_compiles(self, rng):
        self._mesh()
        q, k, v = make_qkv(rng, B=1, H=1, S=64, D=8)
        f = jax.jit(lambda q, k, v: dist.ring_attention_sharded(q, k, v))
        out = f(q, k, v)
        ref = _naive_reference(q, k, v, False, 1.0 / math.sqrt(8))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, rng, causal):
        set_mesh(build_mesh(sep=8, dp=1))
        q, k, v = make_qkv(rng, B=2, H=8, S=64, D=8)  # H divisible by 8
        out = dist.ulysses_attention_sharded(q, k, v, causal=causal)
        ref = _naive_reference(q, k, v, causal, 1.0 / math.sqrt(8))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_heads_not_divisible_raises(self, rng):
        set_mesh(build_mesh(sep=8, dp=1))
        q, k, v = make_qkv(rng, B=1, H=4, S=64, D=8)
        with pytest.raises(Exception, match="divisible"):
            dist.ulysses_attention_sharded(q, k, v)

    def test_gradients_flow(self, rng):
        set_mesh(build_mesh(sep=4, dp=1, devices=jax.devices()[:4]))
        q, k, v = make_qkv(rng, B=1, H=4, S=32, D=8)

        def f(q, k, v):
            return (dist.ulysses_attention_sharded(q, k, v) ** 2).sum()

        def f_ref(q, k, v):
            return (_naive_reference(q, k, v, False, 1.0 / math.sqrt(8)) ** 2).sum()

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)


class TestNonAlignedOffset:
    """ADVICE r1 (medium): a causal q_position_offset that isn't
    q-block-aligned must not go through the Pallas forward (it floors the
    offset to whole blocks → wrong mask, grads inconsistent with fwd)."""

    def test_non_block_aligned_offset_exact(self, rng):
        q, k, v = make_qkv(rng, S=16, K=64)
        for off in (3, 7, 13):  # none divisible by block_q=8
            out = flash_attention(q, k, v, causal=True, block_q=8,
                                  block_k=16, q_position_offset=off)
            ref = _naive_reference(q, k, v, True, 1.0 / math.sqrt(16),
                                   q_offset=off)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-4, atol=2e-5, err_msg=f"off={off}")

    def test_non_aligned_grads_consistent(self, rng):
        import jax

        q, k, v = make_qkv(rng, S=16, K=16)

        def loss_flash(q):
            return flash_attention(q, k, v, causal=True, block_q=8,
                                   block_k=8, q_position_offset=5).sum()

        def loss_ref(q):
            return _naive_reference(q, k, v, True, 1.0 / math.sqrt(16),
                                    q_offset=5).sum()

        g1 = jax.grad(loss_flash)(q)
        g2 = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=2e-4, atol=2e-4)


class TestRingOnFlashKernel:
    """VERDICT r3 #6: each ring step runs the Pallas flash kernel — no
    [S_local, S_local] dense score tensor exists in the ring step's jaxpr
    (fwd or bwd), and gradients stay exact (covered by
    TestRingAttention.test_gradients_flow against the dense oracle)."""

    def test_no_local_score_tensor_in_ring_jaxpr(self):
        import jax
        from paddle_tpu.distributed.mesh import build_mesh, set_mesh
        from paddle_tpu.distributed.collective import shard_map
        from paddle_tpu.distributed.sequence_parallel import ring_attention
        from jax.sharding import PartitionSpec as P

        mesh = build_mesh(sep=4)
        set_mesh(mesh)
        try:
            self._run(mesh)
        finally:
            set_mesh(build_mesh())

    def _run(self, mesh):
        import jax
        from paddle_tpu.distributed.collective import shard_map
        from paddle_tpu.distributed.sequence_parallel import ring_attention
        from jax.sharding import PartitionSpec as P

        B, H, S, D = 1, 2, 256, 32
        S_local = S // 4
        q = jnp.zeros((B, H, S, D))
        spec = P(None, None, "sep", None)

        def loss(q, k, v):
            def local(ql, kl, vl):
                return ring_attention(ql, kl, vl, axis_name="sep",
                                      causal=True)

            out = shard_map(local, mesh=mesh,
                            in_specs=(spec, spec, spec),
                            out_specs=spec)(q, k, v)
            return out.sum()

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)

        def walk(jx):
            for eqn in jx.eqns:
                if eqn.primitive.name == "pallas_call":
                    continue  # kernel VMEM tiles are the point
                for var in list(eqn.outvars) + list(eqn.invars):
                    aval = getattr(var, "aval", None)
                    shape = getattr(aval, "shape", None)
                    if shape and len(shape) >= 2:
                        assert not (shape[-1] == S_local
                                    and shape[-2] == S_local), (
                            f"S_local² score tensor {shape} in {eqn}")
                for sub in eqn.params.values():
                    for cj in (sub if isinstance(sub, (tuple, list))
                               else (sub,)):  # lax.cond branches: a tuple
                        inner = getattr(cj, "jaxpr", cj)
                        if hasattr(inner, "eqns"):
                            walk(inner)

        walk(jaxpr.jaxpr)

"""Cross-process serving: file-RPC engine transport + router peer liveness.

Reference capability: serving a pod where the Router fronts engines living
in OTHER host processes (fleet inference placement).  The transport is
:mod:`paddle_tpu.serving.remote` (same shared-directory contract as the
gang's FileTransport); host-death detection is the gang's
PeerHeartbeatMonitor wired into ``Router.bind_peer_liveness``.  The real
multi-process path (SIGKILLed server host, zero lost requests) runs in
``tools/pod_smoke.py``; these tests pin the in-process contracts.
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.framework.errors import UnavailableError
from paddle_tpu.serving import EngineServer, RemoteEngineProxy, Router


class _FakeEngine:
    """Minimal engine surface: synthetic_inputs + infer (+ submit for the
    Router's dispatch path)."""

    def __init__(self, tag="e", fail=False):
        self.tag = tag
        self.fail = fail
        self.calls = 0

    def synthetic_inputs(self, bucket=0):
        return [np.zeros((1, 2), np.float32)]

    def infer(self, inputs, timeout=None, **kw):
        self.calls += 1
        if self.fail:
            raise RuntimeError(f"{self.tag} exploded")
        return [np.asarray(inputs[0]) + 1.0]

    def submit(self, inputs, deadline_ms=None, trace_ctx=None, **kw):
        from concurrent.futures import Future

        fut = Future()
        try:
            fut.set_result(self.infer(inputs, **kw))
        except Exception as e:  # noqa: BLE001 — travels via the future
            fut.set_exception(e)
        return fut


class TestRemoteEngine:
    def test_round_trip(self, tmp_path):
        with EngineServer(_FakeEngine(), str(tmp_path), name="e0"):
            proxy = RemoteEngineProxy(str(tmp_path), "e0", timeout_s=10.0,
                                      hello_timeout_s=10.0)
            x = [np.full((1, 2), 3.0, np.float32)]
            out = proxy.infer(x, timeout=10.0)
            np.testing.assert_array_equal(out[0],
                                          np.full((1, 2), 4.0, np.float32))
            # synthetic inputs come from the server's hello file
            syn = proxy.synthetic_inputs()
            assert syn[0].shape == (1, 2)
            proxy.close()

    def test_server_exception_travels_to_client(self, tmp_path):
        with EngineServer(_FakeEngine(fail=True), str(tmp_path), name="e0"):
            proxy = RemoteEngineProxy(str(tmp_path), "e0", timeout_s=10.0,
                                      hello_timeout_s=10.0)
            with pytest.raises(RuntimeError, match="exploded"):
                proxy.infer([np.zeros((1, 2), np.float32)], timeout=10.0)
            proxy.close()

    def test_dead_server_unavailable_within_deadline(self, tmp_path):
        # server answers hello then dies: requests must fail with the
        # retryable UnavailableError inside the deadline, never hang
        srv = EngineServer(_FakeEngine(), str(tmp_path), name="e0").start()
        proxy = RemoteEngineProxy(str(tmp_path), "e0", timeout_s=1.0,
                                  hello_timeout_s=10.0)
        proxy.synthetic_inputs()
        srv.stop()
        t0 = time.monotonic()
        with pytest.raises(UnavailableError):
            # no per-request deadline: the proxy's 1s default applies
            proxy.infer([np.zeros((1, 2), np.float32)])
        assert time.monotonic() - t0 < 8
        proxy.close()

    def test_no_server_hello_times_out(self, tmp_path):
        proxy = RemoteEngineProxy(str(tmp_path), "ghost",
                                  hello_timeout_s=0.3)
        with pytest.raises(UnavailableError, match="hello"):
            proxy.synthetic_inputs()
        proxy.close()


class _FakeMonitor:
    def __init__(self, lost=()):
        self.lost = list(lost)
        self.raise_on_read = False

    def lost_workers(self):
        if self.raise_on_read:
            raise OSError("transport gone")
        return list(self.lost)


class TestRouterPeerLiveness:
    def _router(self):
        engines = [_FakeEngine("a"), _FakeEngine("b")]
        r = Router(engines, probe_interval_s=3600.0, probe_timeout_s=1.0,
                   close_engines=False)
        return r, engines

    def test_lost_process_evicts_owned_replica(self):
        r, _ = self._router()
        try:
            mon = _FakeMonitor()
            r.bind_peer_liveness(mon, {0: 1, 1: 2})  # replica -> process
            x = [np.zeros((1, 2), np.float32)]
            assert r.infer(x, timeout=10.0)
            mon.lost = [2]  # process hosting replica 1 died
            r.probe_now()
            snap = r.metrics.snapshot()
            assert snap["peer_evictions"] == 1
            # traffic keeps flowing through the surviving replica
            for _ in range(4):
                assert r.infer(x, timeout=10.0)
        finally:
            r.close()

    def test_healthy_processes_touch_nothing(self):
        r, _ = self._router()
        try:
            mon = _FakeMonitor(lost=[])
            r.bind_peer_liveness(mon, {0: 1, 1: 2})
            r.probe_now()
            assert r.metrics.snapshot()["peer_evictions"] == 0
        finally:
            r.close()

    def test_monitor_errors_are_advisory(self):
        # a broken liveness transport must not take the router down
        r, _ = self._router()
        try:
            mon = _FakeMonitor(lost=[2])
            mon.raise_on_read = True
            r.bind_peer_liveness(mon, {0: 1, 1: 2})
            r.probe_now()  # swallowed
            assert r.metrics.snapshot()["peer_evictions"] == 0
            assert r.infer([np.zeros((1, 2), np.float32)], timeout=10.0)
        finally:
            r.close()

    def test_unmapped_replicas_unaffected(self):
        r, _ = self._router()
        try:
            mon = _FakeMonitor(lost=[7])
            r.bind_peer_liveness(mon, {0: 7})  # replica 1 is local
            r.probe_now()
            assert r.metrics.snapshot()["peer_evictions"] == 1
            # replica 1 has no process mapping: still serving
            assert r.infer([np.zeros((1, 2), np.float32)], timeout=10.0)
        finally:
            r.close()

"""Elastic training supervisor: divergence rollback, exact-resume data
pipeline, and the collective/straggler watchdog.

Reference capability: launch_utils.py watch loop + heart_beat_monitor.h
kept trainers *alive*; nothing guarded the run's numerics or made resume
exact.  Tests here cover the three supervisor legs plus the satellites:
sampler/loader state round-trips, mid-epoch kill → bit-identical resume
under FLAGS_fault_plan (checkpoint.write and executor.dispatch sites),
NaN → single rollback, rollback loop → DivergenceError + rule F802,
wedged-collective deadline, restart-storm exit code, heartbeat failure
counter, and AMP skip events.
"""
import contextlib
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.framework import random as frandom
from paddle_tpu.framework import monitor
from paddle_tpu.framework.errors import (
    DivergenceError,
    InvalidArgumentError,
    TransientDeviceError,
)
from paddle_tpu.framework.flags import get_flags, set_flags
from paddle_tpu.incubate.checkpoint import AutoCheckpoint
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import TensorDataset
from paddle_tpu.io.sampler import (
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
)
from paddle_tpu.resilience import TrainingSupervisor
from paddle_tpu.resilience import supervisor as sup_mod
from paddle_tpu.resilience.faults import FaultPlan


def _model(seed=0):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2),
                  loss=nn.CrossEntropyLoss())
    return model


def _dataset(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = rng.randint(0, 2, size=(n,)).astype(np.int64)
    return TensorDataset([x, y])


def _loader(ds, batch_size=4, shuffle=True):
    return DataLoader(ds, batch_size=batch_size, shuffle=shuffle,
                      return_numpy=True)


@contextlib.contextmanager
def flags_guard(values):
    saved = get_flags(list(values))
    set_flags(values)
    try:
        yield
    finally:
        set_flags(saved)


@pytest.fixture
def fresh_sup_stats():
    """Zero the module-global supervisor counters for the test, restore
    after — F802 keys off cumulative snapshots, so leakage across tests
    would make the clean-path assertion meaningless."""
    with sup_mod._stats_lock:
        saved = dict(sup_mod._stats)
        for k in sup_mod._stats:
            sup_mod._stats[k] = 0
    yield
    with sup_mod._stats_lock:
        sup_mod._stats.clear()
        sup_mod._stats.update(saved)


# ---------------------------------------------------------------------------
# exact-resume state: samplers
# ---------------------------------------------------------------------------
class TestSamplerState:
    def test_random_sampler_replays_snapshotted_seed(self):
        ds = list(range(20))
        paddle.seed(5)
        s = RandomSampler(ds)
        order = list(s)
        state = s.state_dict()
        assert state["last_seed"] is not None
        s2 = RandomSampler(ds)
        s2.set_state_dict(state)
        assert list(s2) == order  # replay: same permutation, no fresh draw
        # the replay seed is consume-once: the next epoch draws fresh
        assert s2._replay_seed is None

    def test_replay_does_not_redraw_from_generator(self):
        ds = list(range(8))
        paddle.seed(9)
        s = RandomSampler(ds)
        list(s)
        count_after_draw = frandom.default_generator().get_state()["count"]
        s2 = RandomSampler(ds)
        s2.set_state_dict(s.state_dict())
        list(s2)
        assert (frandom.default_generator().get_state()["count"]
                == count_after_draw)

    def test_int_seed_generator_epoch_counter_round_trips(self):
        ds = list(range(12))
        s = RandomSampler(ds, generator=42)
        e1, e2 = list(s), list(s)
        assert e1 != e2  # per-epoch variation
        s2 = RandomSampler(ds, generator=42)
        s2.set_state_dict(s.state_dict())
        assert list(s2) == e2  # replays the LAST epoch's order
        assert list(s2) != e2  # then moves on

    def test_batch_sampler_skips_consumed_prefix(self):
        ds = _dataset(20)
        paddle.seed(3)
        bs = BatchSampler(dataset=ds, shuffle=True, batch_size=4)
        it = iter(bs)
        consumed = [next(it), next(it)]
        state = bs.state_dict()
        assert state["next_batch"] == 2
        rest_ref = list(it)  # remainder of THIS epoch's order
        bs2 = BatchSampler(dataset=ds, shuffle=True, batch_size=4)
        bs2.set_state_dict(state)
        assert list(bs2) == rest_ref

    def test_distributed_batch_sampler_state_round_trips(self):
        ds = _dataset(20)
        s = DistributedBatchSampler(ds, batch_size=4, num_replicas=2,
                                    rank=1, shuffle=True)
        s.set_epoch(7)
        full = list(s)
        it = iter(s)
        first = next(it)
        state = s.state_dict()
        assert state == {"epoch": 7, "next_batch": 1}
        s2 = DistributedBatchSampler(ds, batch_size=4, num_replicas=2,
                                     rank=1, shuffle=True)
        s2.set_state_dict(state)
        assert [first] + list(s2) == full


# ---------------------------------------------------------------------------
# exact-resume state: DataLoader
# ---------------------------------------------------------------------------
class TestDataLoaderState:
    def test_mid_epoch_snapshot_restores_bit_identical(self):
        ds = _dataset(20)
        loader = _loader(ds)
        paddle.seed(77)
        ref = [np.asarray(b[0]).copy() for b in loader]
        ref2 = [np.asarray(b[0]).copy() for b in loader]  # next epoch

        paddle.seed(77)
        it = iter(loader)
        got = [np.asarray(next(it)[0]).copy() for _ in range(2)]
        snap = loader.state_dict()
        rng_state = frandom.default_generator().get_state()

        # "new process": fresh loader over the same dataset
        loader2 = _loader(ds)
        frandom.default_generator().set_state(rng_state)
        loader2.set_state_dict(snap)
        got += [np.asarray(b[0]).copy() for b in loader2]
        got2 = [np.asarray(b[0]).copy() for b in loader2]

        assert len(got) == len(ref) and len(got2) == len(ref2)
        for a, b in zip(got + got2, ref + ref2):
            np.testing.assert_array_equal(a, b)

    def test_delivered_count_ignores_prefetch_runahead(self):
        ds = _dataset(32)
        loader = DataLoader(ds, batch_size=4, shuffle=False,
                            prefetch_factor=4)
        it = iter(loader)
        next(it), next(it)
        time.sleep(0.3)  # let the staging thread run ahead
        state = loader.state_dict()
        assert state["delivered"] == 2
        assert state["batch_sampler"]["next_batch"] == 2
        it.close()

    def test_exhausted_snapshot_arms_nothing(self):
        ds = _dataset(16)
        loader = _loader(ds)
        paddle.seed(11)
        list(loader)
        snap = loader.state_dict()
        assert snap["exhausted"] is True
        loader.set_state_dict(snap)
        assert loader._pending is None  # next epoch starts fresh

    def test_iterable_mode_rejects_state(self):
        from paddle_tpu.io.dataset import IterableDataset

        class Stream(IterableDataset):
            def __iter__(self):
                return iter(range(8))

        loader = DataLoader(Stream(), batch_size=2, return_numpy=True)
        with pytest.raises(InvalidArgumentError, match="IterableDataset"):
            loader.state_dict()
        with pytest.raises(InvalidArgumentError, match="IterableDataset"):
            loader.set_state_dict({})


# ---------------------------------------------------------------------------
# mid-epoch kill → bit-identical resume (FLAGS_fault_plan)
# ---------------------------------------------------------------------------
class TestMidEpochKillResume:
    def _train(self, d, ds, steps=None, fault=None, save_steps=3):
        """One training 'process': fresh model+loader+acp, resume, run the
        epoch loop.  Returns final params; a fault plan may abort it."""
        loader = _loader(ds)
        m = _model(seed=1)
        acp = AutoCheckpoint(m, d, save_steps=save_steps, async_save=False,
                             data_loader=loader)
        acp.resume()
        start = acp.last_epoch
        try:
            if fault is not None:
                fault.__enter__()
            for epoch in range(start, 2):
                for x, y in loader:
                    m.train_batch([x], [y])
                    acp.step(epoch)
                acp.epoch_end(epoch)
        finally:
            if fault is not None:
                fault.__exit__(None, None, None)
        acp.close()
        return {k: np.asarray(v)
                for k, v in m.network.state_dict().items()}

    def test_kill_at_checkpoint_write_resumes_bit_identical(self, tmp_path):
        ds = _dataset(24)
        paddle.seed(55)
        ref = self._train(os.path.join(tmp_path, "ref"), ds)

        # killed run: a fatal (non-transient) error fires inside the 3rd
        # checkpoint write — mid-epoch, after two committed saves
        paddle.seed(55)
        plan = FaultPlan.parse(
            "site=checkpoint.write,nth=3,error=RuntimeError")
        d = os.path.join(tmp_path, "kill")
        with pytest.raises(RuntimeError):
            self._train(d, ds, fault=plan)
        paddle.seed(999)  # resume must restore the checkpointed RNG, not
        #                   inherit whatever the fresh process seeded
        got = self._train(d, ds)
        assert ref.keys() == got.keys()
        for k in ref:
            np.testing.assert_array_equal(ref[k], got[k])

    def test_kill_at_executor_dispatch_resumes_bit_identical(self, tmp_path):
        """Same guarantee when the kill lands in the device dispatch of a
        static-graph train loop (Program.state_dict rides AutoCheckpoint
        through a duck-typed model)."""
        from types import SimpleNamespace

        from paddle_tpu import fluid

        ds = _dataset(24)

        class ProgState:
            """Adapter: scope names embed the process-global program index
            (`_7_fc.weight_2`), stable across real process restarts but
            not across the in-test rebuilds — strip it so the checkpoint
            keys match, as they would between fresh processes."""

            def __init__(self, prog):
                self._prog = prog

            @staticmethod
            def _strip(n):
                return n.split("_", 2)[2]

            def state_dict(self):
                return {self._strip(k): v
                        for k, v in self._prog.state_dict().items()}

            def set_state_dict(self, state):
                names = {self._strip(k): k for k in self._prog.state_dict()}
                self._prog.set_state_dict(
                    {names[k]: v for k, v in state.items() if k in names})
                return [k for k in state if k not in names]

        def run(d, fault=None):
            paddle.seed(21)
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.data("x", [-1, 4])
                y = fluid.data("y", [-1, 1])
                pred = fluid.layers.fc(input=x, size=1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            loader = _loader(ds)
            host = SimpleNamespace(network=ProgState(main), _opt_state=None,
                                   _optimizer=None)
            acp = AutoCheckpoint(host, d, save_steps=2, async_save=False,
                                 data_loader=loader)
            acp.resume()
            start = acp.last_epoch
            try:
                if fault is not None:
                    fault.__enter__()
                for epoch in range(start, 2):
                    for bx, by in loader:
                        exe.run(main,
                                feed={"x": bx,
                                      "y": np.asarray(by, np.float32)[:, None]},
                                fetch_list=[loss])
                        acp.step(epoch)
                    acp.epoch_end(epoch)
            finally:
                if fault is not None:
                    fault.__exit__(None, None, None)
            acp.close()
            return {k: np.asarray(v)
                    for k, v in host.network.state_dict().items()}

        ref = run(os.path.join(tmp_path, "ref"))
        plan = FaultPlan.parse(
            "site=executor.dispatch,nth=5,error=RuntimeError")
        d = os.path.join(tmp_path, "kill")
        with pytest.raises(RuntimeError):
            run(d, fault=plan)
        got = run(d)
        assert ref.keys() == got.keys()
        for k in ref:
            np.testing.assert_array_equal(ref[k], got[k])


# ---------------------------------------------------------------------------
# the supervisor itself
# ---------------------------------------------------------------------------
class TestTrainingSupervisor:
    def _run(self, d, ds, nan_at=None, sup_kw=None):
        loader = _loader(ds)
        m = _model(seed=1)
        acp = AutoCheckpoint(m, d, save_steps=3, async_save=False,
                             data_loader=loader)
        sup = TrainingSupervisor(acp, warmup_steps=2, **(sup_kw or {}))
        acp.resume()
        step = [0]
        injected = [False]
        losses = []
        for epoch in range(2):
            for x, y in sup.steps(loader, epoch):
                loss, _ = m.train_batch([x], [y])
                step[0] += 1
                lv = float(np.asarray(loss))
                if nan_at is not None and step[0] == nan_at and not injected[0]:
                    injected[0] = True
                    lv = float("nan")
                if sup.guard(lv):
                    losses.append(lv)
                    acp.step(epoch)
            acp.epoch_end(epoch)
        acp.close()
        return sup, losses

    def test_nan_batch_one_rollback_then_finishes(self, tmp_path,
                                                  fresh_sup_stats):
        paddle.seed(44)
        sup, losses = self._run(os.path.join(tmp_path, "ck"),
                                _dataset(32), nan_at=5)
        assert sup.rollbacks == 1
        assert len(sup.poisoned) == 1
        assert losses and all(np.isfinite(losses))
        st = sup_mod.stats()
        assert st["rollbacks"] == 1
        assert st["skipped_batches"] >= 1
        assert st["exact_resumes"] == 1
        assert st["fatal_divergences"] == 0

    def test_spike_trips_like_nan(self, tmp_path, fresh_sup_stats):
        paddle.seed(44)
        d = os.path.join(tmp_path, "ck")
        loader = _loader(_dataset(32))
        m = _model(seed=1)
        acp = AutoCheckpoint(m, d, save_steps=3, async_save=False,
                             data_loader=loader)
        sup = TrainingSupervisor(acp, warmup_steps=2, spike_factor=5.0)
        step = 0
        for x, y in sup.steps(loader, 0):
            loss, _ = m.train_batch([x], [y])
            step += 1
            lv = float(np.asarray(loss))
            if step == 4:
                lv = lv * 1000.0  # spike, finite
            if sup.guard(lv):
                acp.step(0)
        acp.close()
        assert sup.rollbacks == 1

    def test_rollback_loop_raises_divergence_error(self, tmp_path,
                                                   fresh_sup_stats):
        paddle.seed(44)
        with pytest.raises(DivergenceError, match="re-diverged"):
            self._always_nan(tmp_path)
        assert sup_mod.stats()["fatal_divergences"] == 1
        assert sup_mod.stats()["repeat_trips"] >= 1

    def _always_nan(self, tmp_path):
        loader = _loader(_dataset(32))
        m = _model(seed=1)
        acp = AutoCheckpoint(m, os.path.join(tmp_path, "loop"),
                             save_steps=100, async_save=False,
                             data_loader=loader)
        sup = TrainingSupervisor(acp, skip_batches=0)
        try:
            for x, y in sup.steps(loader, 0):
                m.train_batch([x], [y])
                if sup.guard(float("nan")):
                    acp.step(0)
        finally:
            acp.close()

    def test_no_checkpoint_is_fatal(self, tmp_path, fresh_sup_stats):
        loader = _loader(_dataset(16))
        m = _model(seed=1)
        acp = AutoCheckpoint(m, os.path.join(tmp_path, "ck"),
                             async_save=False, data_loader=loader)
        sup = TrainingSupervisor(acp)
        # bypass steps() (which commits a baseline): guard with no
        # committed checkpoint anywhere must raise, not loop
        with pytest.raises(DivergenceError, match="no committed"):
            sup.guard(float("nan"))

    def test_disabled_hooks_are_noops(self, tmp_path, fresh_sup_stats):
        ds = _dataset(16)
        loader = _loader(ds)
        m = _model(seed=1)
        acp = AutoCheckpoint(m, os.path.join(tmp_path, "ck"),
                             async_save=False, data_loader=loader)
        sup = TrainingSupervisor(acp, enable=False)
        paddle.seed(2)
        batches = list(sup.steps(loader, 0))
        assert len(batches) == len(loader)
        assert sup.guard(float("nan")) is True  # disabled: never trips
        assert sup.rollbacks == 0
        assert acp.latest_dir() is None  # no baseline committed
        assert sup_mod.stats()["rollbacks"] == 0

    def test_validation(self, tmp_path):
        acp = object()
        with pytest.raises(InvalidArgumentError):
            TrainingSupervisor(acp, spike_factor=1.0)
        with pytest.raises(InvalidArgumentError):
            TrainingSupervisor(acp, ema_beta=1.5)
        with pytest.raises(InvalidArgumentError):
            TrainingSupervisor(acp, max_rollbacks=0)


# ---------------------------------------------------------------------------
# collective/straggler watchdog
# ---------------------------------------------------------------------------
class TestCollectiveWatchdog:
    def test_wedged_collective_raises_within_deadline(self, fresh_sup_stats):
        import paddle_tpu.distributed as dist

        plan = FaultPlan.parse(
            "site=collective.call,every=1,latency_ms=5000")
        with flags_guard({"collective_timeout_s": 0.3}):
            with plan:
                t0 = time.monotonic()
                with pytest.raises(TransientDeviceError,
                                   match="collective_timeout_s"):
                    dist.all_reduce(np.ones((8, 2), np.float32))
                assert time.monotonic() - t0 < 3.0
        assert sup_mod.stats()["watchdog_trips"] == 1

    def test_watchdog_passes_healthy_collectives(self):
        import paddle_tpu.distributed as dist

        with flags_guard({"collective_timeout_s": 30.0}):
            out = dist.all_reduce(np.ones((8, 2), np.float32))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 8.0))

    def test_watchdog_propagates_worker_errors(self):
        import paddle_tpu.distributed as dist

        with flags_guard({"collective_timeout_s": 30.0}):
            with pytest.raises(InvalidArgumentError, match="leading dim"):
                dist.all_reduce(np.ones((3, 2), np.float32))

    def test_disabled_flag_is_plain_call(self):
        import paddle_tpu.distributed as dist

        out = dist.all_reduce(np.ones((8, 2), np.float32))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 2), 8.0))


# ---------------------------------------------------------------------------
# watch(): restart storm + backoff
# ---------------------------------------------------------------------------
class TestWatchRestartStorm:
    def test_storm_window_returns_distinct_exit_code(self, tmp_path):
        from paddle_tpu.distributed.parallel import (
            RESTART_STORM_EXIT_CODE, watch)

        script = os.path.join(tmp_path, "crash.py")
        with open(script, "w") as f:
            f.write("import sys; sys.exit(1)\n")
        rc = watch([sys.executable, script], max_restarts=50, _sleep=0.01,
                   backoff_cap=0.01, storm_window=60.0, storm_restarts=3)
        assert rc == RESTART_STORM_EXIT_CODE

    def test_storm_outside_window_does_not_trip(self, tmp_path):
        from paddle_tpu.distributed.parallel import watch

        script = os.path.join(tmp_path, "crash.py")
        with open(script, "w") as f:
            f.write("import sys; sys.exit(3)\n")
        # window so small consecutive restarts never land inside it
        rc = watch([sys.executable, script], max_restarts=2, _sleep=0.01,
                   backoff_cap=0.01, storm_window=1e-9, storm_restarts=2)
        assert rc == 3  # budget exhaustion, not the storm code

    def test_storm_params_validated(self):
        from paddle_tpu.distributed.parallel import watch

        with pytest.raises(InvalidArgumentError):
            watch(["true"], storm_window=1.0, storm_restarts=0)


# ---------------------------------------------------------------------------
# heartbeat write-failure counter
# ---------------------------------------------------------------------------
class TestHeartbeatFailureCounter:
    def test_suppressed_oserror_is_counted(self, tmp_path):
        from paddle_tpu.distributed.heartbeat import FileHeartbeat

        hb = FileHeartbeat(os.path.join(tmp_path, "hb"))
        blocker = os.path.join(tmp_path, "file")
        with open(blocker, "w"):
            pass
        hb.path = os.path.join(blocker, "hb")  # dirname is a regular file
        before = monitor.get_stat("heartbeat_write_failures")
        hb.beat()  # must not raise
        assert monitor.get_stat("heartbeat_write_failures") == before + 1


# ---------------------------------------------------------------------------
# AMP skip events
# ---------------------------------------------------------------------------
class TestAmpEvents:
    def test_skipped_steps_and_scale_published(self):
        from paddle_tpu.amp.grad_scaler import GradScaler
        from paddle_tpu.analysis import RetraceMonitor

        class Opt:
            def step(self, grads):
                self.last = grads

        with RetraceMonitor() as mon:
            sc = GradScaler(init_loss_scaling=8.0,
                            decr_every_n_nan_or_inf=1)
            opt = Opt()
            sc.step(opt, [np.ones((2,), np.float32)])
            sc.update()
            sc.step(opt, [np.array([np.nan, 1.0], np.float32)])
            sc.update()
            st = mon.amp_stats("grad_scaler")
        assert st["skipped_steps"] == 1
        assert st["scale"] == 4.0  # halved after the non-finite step
        assert not hasattr(opt, "last") or opt.last is not None

    def test_no_observer_publishes_nothing(self):
        from paddle_tpu.amp.grad_scaler import GradScaler
        from paddle_tpu.framework import trace_events

        class Opt:
            def step(self, grads):
                pass

        assert not trace_events.active()
        sc = GradScaler()
        sc.step(Opt(), [np.ones((2,), np.float32)])
        sc.update()  # just must not raise / not notify


# ---------------------------------------------------------------------------
# rule F802 + profiler section
# ---------------------------------------------------------------------------
class TestF802:
    def test_fires_on_rollback_loop_only(self, fresh_sup_stats):
        from paddle_tpu.analysis import RetraceMonitor

        with RetraceMonitor() as mon:
            sup_mod.record("rollbacks")  # one clean rollback: silent
            assert not [d for d in mon.diagnostics() if d.rule == "F802"]
            sup_mod.record("repeat_trips")  # same-target re-trip: fires
            diags = [d for d in mon.diagnostics() if d.rule == "F802"]
        assert diags
        assert "re-diverged" in diags[0].message
        assert diags[0].hint

    def test_profiler_section_renders_delta(self, fresh_sup_stats):
        from paddle_tpu import profiler

        profiler.reset_profiler()
        assert "Training supervisor" not in profiler.summary()
        sup_mod.record("rollbacks")
        out = profiler.summary()
        assert "Training supervisor" in out
        assert "rollbacks" in out


# ---------------------------------------------------------------------------
# prune pinning
# ---------------------------------------------------------------------------
class TestPrunePinning:
    def test_pinned_dir_survives_prune(self, tmp_path):
        m = _model(seed=1)
        d = os.path.join(tmp_path, "ck")
        acp = AutoCheckpoint(m, d, keep_max=1, async_save=False)
        acp.save(0)
        first = os.path.basename(acp.latest_dir())
        acp._pin(first)
        acp.save(0)
        acp.save(0)
        names = sorted(n for n in os.listdir(d) if n.startswith("ckpt-"))
        assert first in names          # pinned survived two prunes
        assert len(names) == 2         # pinned + the keep_max=1 newest
        acp._unpin(first)
        acp.save(0)
        names = sorted(n for n in os.listdir(d) if n.startswith("ckpt-"))
        assert first not in names      # unpinned: pruned on the next write
        assert len(names) == 1

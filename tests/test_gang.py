"""Host-lane gang collectives + gang-scheduled elastic restart decisions.

Reference capability: fleet elastic (python/paddle/distributed/fleet/elastic)
— pod membership handshakes, dead-peer detection, gang-wide restart.  Here
the control lane is :mod:`paddle_tpu.distributed.gang` (file/KV transports,
generation-fenced collectives) and the restart decision lives in
``watch(peer_monitor=...)``.  Real multi-process behavior is exercised by
``tools/pod_smoke.py``; these tests pin the unit-level contracts with
threads and fake monitors.
"""
import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.gang import (FileTransport, Gang, default_gang,
                                         mean_trees, set_gang)
from paddle_tpu.distributed.parallel import (GANG_RESTART_EXIT_CODE,
                                             RESTART_STORM_EXIT_CODE, watch)
from paddle_tpu.framework import monitor
from paddle_tpu.framework.errors import (InvalidArgumentError,
                                         TransientDeviceError)


def _run_gang(world, fn, transport, timeout=20.0):
    """Run ``fn(gang)`` on one thread per rank; returns per-rank results.

    Any rank raising re-raises in the caller (first error wins)."""
    results = [None] * world
    errors = []

    def _one(rank):
        g = Gang(rank, world, transport, name="t", default_timeout=timeout)
        try:
            results[rank] = fn(g)
        except BaseException as e:  # noqa: BLE001 — surfaced to caller
            errors.append((rank, e))

    threads = [threading.Thread(target=_one, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 30)
    if errors:
        raise errors[0][1]
    return results


class TestFileTransport:
    def test_put_get_delete_roundtrip(self, tmp_path):
        tr = FileTransport(str(tmp_path))
        assert tr.try_get("k") is None
        tr.put("k", b"v1")
        assert tr.try_get("k") == b"v1"
        tr.put("k", b"v2")  # atomic overwrite
        assert tr.try_get("k") == b"v2"
        tr.delete("k")
        assert tr.try_get("k") is None
        tr.delete("k")  # idempotent

    def test_keys_with_separators_are_flattened(self, tmp_path):
        tr = FileTransport(str(tmp_path))
        tr.put("a/b/c", b"x")
        assert tr.try_get("a/b/c") == b"x"
        # no nested directories created — keys map to flat files
        assert all(not p.is_dir() for p in tmp_path.iterdir())


class TestGangCollectives:
    def test_solo_gang_degenerates_to_local(self):
        g = Gang(0, 1)
        assert g.join() == "solo"
        assert g.all_gather_obj({"a": 1}) == [{"a": 1}]
        assert g.min_int(7) == 7
        g.barrier()  # no-op, must not hang

    def test_join_converges_on_shared_generation(self, tmp_path):
        tr = FileTransport(str(tmp_path))
        gens = _run_gang(3, lambda g: g.join(), tr)
        assert len(set(gens)) == 1 and gens[0] not in (None, "solo")

    def test_all_gather_is_rank_ordered(self, tmp_path):
        tr = FileTransport(str(tmp_path))

        def fn(g):
            g.join()
            return g.all_gather_obj({"rank": g.rank, "x": g.rank * 10})

        out = _run_gang(3, fn, tr)
        # every rank sees the identical rank-ordered list
        assert out[0] == out[1] == out[2]
        assert [d["rank"] for d in out[0]] == [0, 1, 2]

    def test_min_int_and_mean_tree(self, tmp_path):
        tr = FileTransport(str(tmp_path))

        def fn(g):
            g.join()
            agreed = g.min_int([5, 3, 9][g.rank])
            tree = {"w": np.full((2,), float(g.rank), np.float32)}
            mean = g.all_reduce_mean_tree(tree)
            return agreed, mean

        out = _run_gang(3, fn, tr)
        assert all(agreed == 3 for agreed, _ in out)
        for _, mean in out:
            np.testing.assert_array_equal(mean["w"],
                                          np.full((2,), 1.0, np.float32))

    def test_mean_trees_matches_rank_order_fold(self):
        trees = [{"w": np.float32(v)} for v in (0.1, 0.2, 0.7)]
        expected = (np.float32(0.1) + np.float32(0.2) + np.float32(0.7)) \
            / np.float32(3)
        got = mean_trees(trees)["w"]
        assert got == expected and got.dtype == np.float32

    def test_dead_peer_trips_watchdog_not_hang(self, tmp_path):
        tr = FileTransport(str(tmp_path))
        gens = _run_gang(2, lambda g: g.join(), tr)
        assert gens[0] == gens[1]
        # rank 0 alone enters a collective; rank 1 never contributes
        g0 = Gang(0, 2, tr, default_timeout=1.0)
        g0.join  # noqa: B018 — rejoining would stall; reuse files instead
        g0.generation = gens[0]
        g0._nonces = {}  # not testing fencing here
        t0 = time.monotonic()
        with pytest.raises(TransientDeviceError, match="rank"):
            g0.all_gather_obj({"x": 1}, timeout=1.0)
        assert time.monotonic() - t0 < 10

    def test_validation(self, tmp_path):
        with pytest.raises(InvalidArgumentError, match="world"):
            Gang(0, 0)
        with pytest.raises(InvalidArgumentError, match="rank"):
            Gang(5, 2, FileTransport(str(tmp_path)))
        with pytest.raises(InvalidArgumentError, match="transport"):
            Gang(0, 2)


class TestReincarnationFencing:
    """A peer that restarts mid-collective abandons the generation: the
    survivor must get TransientDeviceError (→ exit 76 under a watchdog),
    not block forever in a collective the dead incarnation can never
    finish — the livelock where a host relaunches faster than the peer
    heartbeat timeout."""

    def test_changed_peer_nonce_aborts_blocked_collective(self, tmp_path):
        tr = FileTransport(str(tmp_path))
        gens = _run_gang(2, lambda g: g.join(), tr)
        g0 = Gang(0, 2, tr, default_timeout=30.0)
        g0.generation = gens[0]
        g0._nonces = {0: tr.try_get("join.p0").decode(),
                      1: tr.try_get("join.p1").decode()}

        def _restart_peer():
            time.sleep(0.3)
            tr.put("join.p1", os.urandom(8).hex().encode())

        before = monitor.get_stat("gang_reincarnations")
        threading.Thread(target=_restart_peer, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(TransientDeviceError, match="restarted"):
            g0.all_gather_obj({"x": 1}, timeout=30.0)
        # aborted by fencing (~0.3s + poll), not by the 30s timeout
        assert time.monotonic() - t0 < 10
        assert monitor.get_stat("gang_reincarnations") == before + 1

    def test_unchanged_nonces_do_not_abort(self, tmp_path):
        tr = FileTransport(str(tmp_path))

        def fn(g):
            g.join()
            if g.rank == 1:
                time.sleep(0.5)  # long enough for several fencing polls
            return g.all_gather_obj(g.rank)

        out = _run_gang(2, fn, tr)
        assert out[0] == out[1] == [0, 1]

    def test_default_gang_uses_gang_dir(self, tmp_path, monkeypatch):
        import paddle_tpu.distributed.gang as gang_mod

        monkeypatch.setenv("PADDLE_TPU_GANG_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        prev = set_gang(None)
        try:
            g = default_gang("unit")
            assert g.world == 1 and g.join() == "solo"
        finally:
            set_gang(prev)


class _FakeMonitor:
    """Scripted peer monitor: pops one lost_workers() answer per call,
    repeating the last; records rearm() calls."""

    def __init__(self, script):
        self.script = list(script)
        self.rearms = 0

    def lost_workers(self):
        if len(self.script) > 1:
            return self.script.pop(0)
        return self.script[0]

    def rearm(self, grace=None):
        self.rearms += 1


class TestWatchGangDecisions:
    def _exit0_after_marker(self, tmp_path):
        """Command that sleeps forever on first run, exits 0 once the
        marker exists — one restart turns it into a success."""
        marker = tmp_path / "second"
        script = tmp_path / "t.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys, time
            if os.path.exists({str(marker)!r}):
                sys.exit(0)
            open({str(marker)!r}, "w").close()
            time.sleep(3600)
        """))
        return [sys.executable, str(script)]

    def test_lost_peer_kills_and_gang_restarts(self, tmp_path):
        # peer reads lost until the watchdog re-arms it after the gang
        # restart, and only once the first child has written its marker —
        # otherwise the kill can land before the marker exists and the
        # second attempt hangs instead of exiting 0
        marker = tmp_path / "second"

        class _LostUntilRearm(_FakeMonitor):
            def lost_workers(self):
                if self.rearms == 0 and marker.exists():
                    return [1]
                return []

        mon = _LostUntilRearm([[]])
        before = monitor.get_stat("gang_restores")
        t0 = time.monotonic()
        rc = watch(self._exit0_after_marker(tmp_path), max_restarts=0,
                   _sleep=0.05, peer_monitor=mon, gang_label="unit.lost")
        assert rc == 0  # gang restart did NOT consume the (zero) budget
        assert time.monotonic() - t0 < 30
        assert monitor.get_stat("gang_restores") == before + 1
        assert mon.rearms >= 1  # relaunch window must not re-flag the loss

    def test_healthy_peers_no_restart(self, tmp_path):
        mon = _FakeMonitor([[]])
        before = monitor.get_stat("gang_restores")
        script = tmp_path / "ok.py"
        script.write_text("import sys; sys.exit(0)")
        rc = watch([sys.executable, str(script)], max_restarts=0,
                   _sleep=0.05, peer_monitor=mon, gang_label="unit.ok")
        assert rc == 0
        assert monitor.get_stat("gang_restores") == before

    def test_gang_restart_storm_trips_breaker(self, tmp_path):
        mon = _FakeMonitor([[2]])  # peer permanently lost
        rc = watch([sys.executable, "-c", "import time; time.sleep(3600)"],
                   max_restarts=0, _sleep=0.05, storm_window=30.0,
                   storm_restarts=3, peer_monitor=mon,
                   gang_label="unit.storm")
        assert rc == RESTART_STORM_EXIT_CODE

    def test_child_exit_76_is_a_free_gang_restart(self, tmp_path):
        # trainer detected peer reincarnation itself (fencing) and exited
        # GANG_RESTART_EXIT_CODE: restart without burning the budget
        marker = tmp_path / "second"
        script = tmp_path / "t.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            if os.path.exists({str(marker)!r}):
                sys.exit(0)
            open({str(marker)!r}, "w").close()
            sys.exit({GANG_RESTART_EXIT_CODE})
        """))
        mon = _FakeMonitor([[]])
        before = monitor.get_stat("gang_restores")
        rc = watch([sys.executable, str(script)], max_restarts=0,
                   _sleep=0.05, peer_monitor=mon, gang_label="unit.rc76")
        assert rc == 0
        assert monitor.get_stat("gang_restores") == before + 1
        assert mon.rearms >= 1


class TestF803Retrace:
    def test_restore_storm_fires_f803(self, tmp_path):
        from paddle_tpu.analysis import RetraceMonitor

        with RetraceMonitor() as mon:
            rc = watch([sys.executable, "-c",
                        "import time; time.sleep(3600)"],
                       max_restarts=0, _sleep=0.05, storm_window=30.0,
                       storm_restarts=3, peer_monitor=_FakeMonitor([[1]]),
                       gang_label="f803.storm")
        assert rc == RESTART_STORM_EXIT_CODE
        f803 = [d for d in mon.diagnostics() if d.rule == "F803"]
        assert f803 and any("f803.storm" in d.message for d in f803)

    def test_healthy_watch_is_silent(self, tmp_path):
        from paddle_tpu.analysis import RetraceMonitor

        script = tmp_path / "ok.py"
        script.write_text("import sys; sys.exit(0)")
        with RetraceMonitor() as mon:
            rc = watch([sys.executable, str(script)], max_restarts=0,
                       peer_monitor=_FakeMonitor([[]]),
                       gang_label="f803.ok")
        assert rc == 0
        assert not [d for d in mon.diagnostics()
                    if d.rule == "F803" and "f803.ok" in d.message]

"""Batched multi-LoRA (paddle_tpu/lora/).

Covers the adapter-math contract: the batched ragged gather path must
match a dense-merged single-adapter reference (allclose — ``x@(W+AB)``
vs ``x@W + (x@A)@B`` associate differently); slot id ``-1`` must be
BITWISE the no-adapter model; export/load round-trips through the
sha256-manifested ``.pdlora`` artifact and rejects tampered bytes; and
adapter hot add/remove on a live engine edits only host-side buffer
leaves — zero recompiles.
"""
import os
import tempfile
import unittest

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.lora import (LoraAdapter, export_adapter, load_adapter,
                             merge_adapter, random_adapter)
from paddle_tpu.lora.batched import (adapter_capacity, clear_slot,
                                     write_adapter)
from paddle_tpu.lora.runtime import adapter_scope
from paddle_tpu.nn.layer_base import functional_call
from paddle_tpu.serving import GenerationEngine


def _install(model, slot, adapter):
    """Write an adapter into the EAGER model's buffer boxes (the engine
    does the same edit on its snapshotted flat tree)."""
    import jax.numpy as jnp
    new = write_adapter(model.buffer_pytree(), slot, adapter)
    for name, box in model.named_buffers():
        if name in new:
            box.value = jnp.asarray(new[name])


def _tiny_model(capacity=2, rank=4):
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    pt.seed(4321)
    cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=64, dropout=0.0,
                    lora_capacity=capacity, lora_rank=rank)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestAdapterMath(unittest.TestCase):
    def test_batched_gather_matches_dense_merged_reference(self):
        # one adapter in slot 0; a [B=2] batch scoping ids [0, 0] must
        # match the SAME model with W + AB*scale folded in densely
        model = _tiny_model()
        adp = random_adapter(model, "a0", rank=3, alpha=6.0, seed=7)
        _install(model, 0, adp)
        ids = np.array([[3, 9, 27, 5], [11, 2, 40, 8]], np.int32)
        import jax.numpy as jnp
        with adapter_scope(np.array([0, 0], np.int32)):
            got = np.asarray(model(jnp.asarray(ids)))
        merged = merge_adapter(model, adp)
        ref = np.asarray(functional_call(model, merged, jnp.asarray(ids)))
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
        # and the adapter actually moved the logits
        base = np.asarray(model(jnp.asarray(ids)))
        self.assertGreater(float(np.abs(got - base).max()), 1e-6)

    def test_slot_minus_one_is_bitwise_base(self):
        # with a NONZERO adapter installed, a -1 row must be bitwise the
        # unscoped model's output — the where-combine selects base rows,
        # never recomputes them
        model = _tiny_model()
        adp = random_adapter(model, "a0", rank=4, seed=3)
        _install(model, 1, adp)
        ids = np.array([[3, 9, 27, 5], [11, 2, 40, 8]], np.int32)
        import jax.numpy as jnp
        base = np.asarray(model(jnp.asarray(ids)))
        with adapter_scope(np.array([-1, -1], np.int32)):
            dead = np.asarray(model(jnp.asarray(ids)))
        self.assertTrue(np.array_equal(base, dead))
        # mixed batch: row 0 adapted, row 1 base — row 1 stays bitwise
        with adapter_scope(np.array([1, -1], np.int32)):
            mixed = np.asarray(model(jnp.asarray(ids)))
        self.assertTrue(np.array_equal(base[1], mixed[1]))
        self.assertGreater(float(np.abs(mixed[0] - base[0]).max()), 1e-6)

    def test_write_adapter_validation(self):
        model = _tiny_model(capacity=2, rank=4)
        bufs = model.buffer_pytree()
        self.assertEqual(adapter_capacity(bufs), 2)
        # rank above the table rank is rejected
        big = random_adapter(model, "big", rank=8, seed=1)
        with self.assertRaises(InvalidArgumentError):
            write_adapter(bufs, 0, big)
        # slot out of range
        ok = random_adapter(model, "ok", rank=2, seed=1)
        with self.assertRaises(InvalidArgumentError):
            write_adapter(bufs, 5, ok)
        # unknown site
        bad = LoraAdapter("bad", 2, 2.0, {
            "gpt.nowhere.qkv": (np.zeros((32, 2), np.float32),
                                np.zeros((2, 96), np.float32))})
        with self.assertRaises(InvalidArgumentError):
            write_adapter(bufs, 0, bad)
        # sub-rank adapters zero-pad: delta equals the unpadded math
        new = write_adapter(bufs, 0, ok)
        site = next(iter(ok.sites))
        a_tab = np.asarray(new[site + ".lora_A"])
        self.assertEqual(a_tab.shape[2], 4)
        self.assertTrue(np.all(a_tab[0, :, 2:] == 0))
        # and the original tree was not mutated
        self.assertTrue(np.all(np.asarray(bufs[site + ".lora_A"]) == 0))
        cleared = clear_slot(new, 0)
        self.assertTrue(np.all(np.asarray(cleared[site + ".lora_A"]) == 0))


class TestAdapterArtifact(unittest.TestCase):
    def test_export_load_roundtrip(self):
        model = _tiny_model()
        adp = random_adapter(model, "ship-me", rank=3, alpha=5.0, seed=11)
        with tempfile.TemporaryDirectory() as d:
            path = export_adapter(adp, os.path.join(d, "adp"))
            self.assertTrue(path.endswith(".pdlora"))
            self.assertTrue(os.path.exists(path + ".manifest.json"))
            back = load_adapter(path)
        self.assertEqual(back.name, "ship-me")
        self.assertEqual(back.rank, 3)
        self.assertEqual(back.alpha, 5.0)
        self.assertEqual(set(back.sites), set(adp.sites))
        for s, (a, b) in adp.sites.items():
            self.assertTrue(np.array_equal(a, back.sites[s][0]))
            self.assertTrue(np.array_equal(b, back.sites[s][1]))

    def test_load_rejects_tampered_and_unmanifested(self):
        model = _tiny_model()
        adp = random_adapter(model, "tamper", rank=2, seed=5)
        with tempfile.TemporaryDirectory() as d:
            path = export_adapter(adp, os.path.join(d, "adp"))
            with open(path, "r+b") as f:
                f.seek(-1, os.SEEK_END)
                last = f.read(1)
                f.seek(-1, os.SEEK_END)
                f.write(bytes([last[0] ^ 0xFF]))
            with self.assertRaises(InvalidArgumentError):
                load_adapter(path)  # sha256 mismatch
            os.remove(path + ".manifest.json")
            with self.assertRaises(InvalidArgumentError):
                load_adapter(path)  # no manifest = unverifiable


class TestHotSwap(unittest.TestCase):
    def test_hot_add_remove_zero_recompile(self):
        # install/remove adapters on a LIVE paged engine between
        # generations: outputs change, the compile set does not
        model = _tiny_model(capacity=2, rank=4)
        p = (np.arange(6) * 9 + 4) % 97
        with GenerationEngine(model, prompt_buckets=[8], batch_size=2,
                              cache_len=48, paged=True, kv_page_size=8,
                              name="lora-hot") as eng:
            n_tr = eng.warmup()
            base = eng.generate(p, 8, timeout=120).tolist()
            adp = random_adapter(model, "hot", rank=4, seed=9,
                                 alpha=32.0, std=0.2)
            eng.install_adapter(0, adp)
            self.assertEqual(eng.adapters, {0: "hot"})
            tuned = eng.generate(p, 8, timeout=120,
                                 adapter_id=0).tolist()
            # explicit -1 still serves the base model alongside
            self.assertEqual(
                eng.generate(p, 8, timeout=120, adapter_id=-1).tolist(),
                base)
            eng.remove_adapter(0)
            self.assertEqual(eng.adapters, {})
            # a cleared slot computes a zero delta -> base tokens
            self.assertEqual(
                eng.generate(p, 8, timeout=120, adapter_id=0).tolist(),
                base)
            self.assertEqual(eng.compile_count, n_tr)  # zero recompiles
            st = eng.stats()
            self.assertEqual(st["adapter_installs"], 1)
            self.assertEqual(st["adapter_removals"], 1)
        # the random adapter is strong enough to change greedy tokens at
        # least somewhere in the budget (seeded, deterministic)
        self.assertNotEqual(tuned, base)

    def test_submit_validates_adapter_id(self):
        model = _tiny_model(capacity=2)
        with GenerationEngine(model, prompt_buckets=[8], batch_size=2,
                              cache_len=48, paged=True, kv_page_size=8,
                              name="lora-val") as eng:
            eng.warmup()
            with self.assertRaises(InvalidArgumentError):
                eng.submit(np.arange(4) % 97, 4, adapter_id=7)


if __name__ == "__main__":
    unittest.main()

"""Test config: run the suite on a simulated 8-device CPU mesh.

Mirrors the reference's strategy of testing distributed paths without a real
cluster (reference: python/paddle/fluid/tests/unittests/test_dist_base.py
spawns localhost subprocesses; test_collective_base.py fakes 2 ranks on one
GPU).  The TPU-native equivalent is XLA's host-platform device partitioning:
8 virtual CPU devices let every pjit/shard_map path compile and execute.
"""
import os

# Must be set before jax import (8 virtual host devices for the mesh tests).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# Force CPU even when a TPU plugin was pre-registered by the environment
# (sitecustomize may override the JAX_PLATFORMS env var).
jax.config.update("jax_platforms", "cpu")

# Numeric tests compare against the numpy oracle: force exact f32 matmuls.
# The framework default (XLA "default" precision ≈ bf16 passes on TPU) is the
# perf-correct choice in production — it matches the reference's cuBLAS TF32
# default on A100.
jax.config.update("jax_default_matmul_precision", "highest")


# -- fast / slow lanes -------------------------------------------------------
# `pytest -m fast` is the <5-minute inner-loop lane; the full (~20 min,
# 1-core) suite stays the merge gate.  Files land in SLOW_FILES by measured
# wall time (per-file totals from --durations, 2026-07-31); everything else
# is auto-marked fast.  A file-level split keeps the list maintainable —
# re-run `pytest --durations=120` and update when a file's cost changes class.
SLOW_FILES = {
    "test_vision.py", "test_models.py", "test_attention.py",
    "test_sequence_parallel_model.py", "test_detection_targets.py",
    "test_detection.py", "test_io.py", "test_launch_env.py",
    "test_roi_extra.py", "test_pipeline.py", "test_strategies.py",
    "test_extension_ops.py", "test_distributed.py", "test_heartbeat.py",
    "test_nn_functional.py", "test_nn_layers.py", "test_fluid_compat.py",
    "test_crf.py", "test_slim.py", "test_sparse_embedding.py",
    "test_multiprocess_dp.py", "test_multiprocess_hybrid.py",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "fast: quick lane (pytest -m fast, <5 min total)")
    config.addinivalue_line(
        "markers", "slow: heavy tests excluded from the fast lane")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (item.get_closest_marker("fast")
                or item.get_closest_marker("slow")):
            continue  # an explicit per-test lane beats the file default
        fname = os.path.basename(item.nodeid.split("::")[0])
        item.add_marker(
            pytest.mark.slow if fname in SLOW_FILES else pytest.mark.fast)


@pytest.fixture
def rng():
    return np.random.RandomState(0)

"""Test config: run the suite on a simulated 8-device CPU mesh.

Mirrors the reference's strategy of testing distributed paths without a real
cluster (reference: python/paddle/fluid/tests/unittests/test_dist_base.py
spawns localhost subprocesses; test_collective_base.py fakes 2 ranks on one
GPU).  The TPU-native equivalent is XLA's host-platform device partitioning:
8 virtual CPU devices let every pjit/shard_map path compile and execute.
"""
import os

# Must be set before jax import (8 virtual host devices for the mesh tests).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# Force CPU even when a TPU plugin was pre-registered by the environment
# (sitecustomize may override the JAX_PLATFORMS env var).
jax.config.update("jax_platforms", "cpu")

# Numeric tests compare against the numpy oracle: force exact f32 matmuls.
# The framework default (XLA "default" precision ≈ bf16 passes on TPU) is the
# perf-correct choice in production — it matches the reference's cuBLAS TF32
# default on A100.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture
def rng():
    return np.random.RandomState(0)

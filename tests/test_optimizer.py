"""Optimizer tests: update rules vs numpy references (oracle style mirrors
the reference's OpTest for optimizer ops, e.g. test_adam_op.py which checks
the kernel against a numpy step), plus jit/eager parity and schedulers."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt_mod
from paddle_tpu.nn.layer_base import Parameter


def make_params(rng, shapes=((4, 3), (3,))):
    return {f"p{i}": jnp.asarray(rng.randn(*s).astype(np.float32)) for i, s in enumerate(shapes)}


def make_grads(rng, params):
    return {k: jnp.asarray(rng.randn(*v.shape).astype(np.float32)) for k, v in params.items()}


def run_steps(opt, params, grads_list, lr=None):
    state = opt.init(params)
    for g in grads_list:
        params, state = opt.update(g, state, params, lr=lr)
    return params, state


class TestRules:
    def test_sgd(self, rng):
        params = make_params(rng)
        grads = make_grads(rng, params)
        out, _ = run_steps(opt_mod.SGD(learning_rate=0.1), params, [grads])
        for k in params:
            np.testing.assert_allclose(out[k], np.asarray(params[k]) - 0.1 * np.asarray(grads[k]), rtol=1e-6)

    def test_momentum(self, rng):
        params = make_params(rng)
        g1, g2 = make_grads(rng, params), make_grads(rng, params)
        out, _ = run_steps(opt_mod.Momentum(learning_rate=0.1, momentum=0.9), params, [g1, g2])
        for k in params:
            v1 = np.asarray(g1[k])
            p1 = np.asarray(params[k]) - 0.1 * v1
            v2 = 0.9 * v1 + np.asarray(g2[k])
            p2 = p1 - 0.1 * v2
            np.testing.assert_allclose(out[k], p2, rtol=1e-6)

    def test_momentum_nesterov(self, rng):
        params = make_params(rng)
        g1 = make_grads(rng, params)
        out, _ = run_steps(opt_mod.Momentum(learning_rate=0.1, momentum=0.9, use_nesterov=True), params, [g1])
        for k in params:
            g = np.asarray(g1[k])
            v = g
            expect = np.asarray(params[k]) - (g + 0.9 * v) * 0.1
            np.testing.assert_allclose(out[k], expect, rtol=1e-6)

    def test_adam_two_steps(self, rng):
        params = make_params(rng)
        gs = [make_grads(rng, params) for _ in range(2)]
        out, _ = run_steps(opt_mod.Adam(learning_rate=0.01), params, gs)
        # numpy reference
        b1, b2, eps = 0.9, 0.999, 1e-8
        for k in params:
            p = np.asarray(params[k])
            m = np.zeros_like(p)
            v = np.zeros_like(p)
            for t, g_ in enumerate(gs, start=1):
                g = np.asarray(g_[k])
                m = b1 * m + (1 - b1) * g
                v = b2 * v + (1 - b2) * g * g
                mhat = m / (1 - b1 ** t)
                vhat = v / (1 - b2 ** t)
                p = p - 0.01 * mhat / (np.sqrt(vhat) + eps)
            np.testing.assert_allclose(out[k], p, rtol=1e-5)

    def test_adamw_decoupled_decay(self, rng):
        params = make_params(rng)
        grads = {k: jnp.zeros_like(v) for k, v in params.items()}
        out, _ = run_steps(opt_mod.AdamW(learning_rate=0.1, weight_decay=0.5), params, [grads])
        # zero grad → pure decay: p *= (1 - lr*coeff)
        for k in params:
            np.testing.assert_allclose(out[k], np.asarray(params[k]) * (1 - 0.1 * 0.5), rtol=1e-5)

    def test_adamw_decay_filter(self, rng):
        params = make_params(rng)
        grads = {k: jnp.zeros_like(v) for k, v in params.items()}
        opt = opt_mod.AdamW(learning_rate=0.1, weight_decay=0.5,
                            apply_decay_param_fun=lambda n: n == "p0")
        out, _ = run_steps(opt, params, [grads])
        np.testing.assert_allclose(out["p0"], np.asarray(params["p0"]) * 0.95, rtol=1e-5)
        np.testing.assert_allclose(out["p1"], np.asarray(params["p1"]), rtol=1e-6)

    def test_adagrad(self, rng):
        params = make_params(rng)
        g = make_grads(rng, params)
        out, _ = run_steps(opt_mod.Adagrad(learning_rate=0.1), params, [g])
        for k in params:
            gn = np.asarray(g[k])
            expect = np.asarray(params[k]) - 0.1 * gn / (np.sqrt(gn * gn) + 1e-6)
            np.testing.assert_allclose(out[k], expect, rtol=1e-5)

    def test_rmsprop(self, rng):
        params = make_params(rng)
        g = make_grads(rng, params)
        out, _ = run_steps(opt_mod.RMSProp(learning_rate=0.1, rho=0.95), params, [g])
        for k in params:
            gn = np.asarray(g[k])
            ms = 0.05 * gn * gn
            expect = np.asarray(params[k]) - 0.1 * gn / np.sqrt(ms + 1e-6)
            np.testing.assert_allclose(out[k], expect, rtol=1e-5)

    def test_adadelta(self, rng):
        params = make_params(rng)
        g = make_grads(rng, params)
        out, _ = run_steps(opt_mod.Adadelta(learning_rate=1.0, rho=0.95), params, [g])
        for k in params:
            gn = np.asarray(g[k])
            asg = 0.05 * gn * gn
            upd = gn * np.sqrt(1e-6) / np.sqrt(asg + 1e-6)
            expect = np.asarray(params[k]) - upd
            np.testing.assert_allclose(out[k], expect, rtol=1e-4)

    def test_adamax(self, rng):
        params = make_params(rng)
        g = make_grads(rng, params)
        out, _ = run_steps(opt_mod.Adamax(learning_rate=0.1), params, [g])
        for k in params:
            gn = np.asarray(g[k])
            m = 0.1 * gn
            u = np.abs(gn)
            expect = np.asarray(params[k]) - (0.1 / 0.1) * m / (u + 1e-8)
            np.testing.assert_allclose(out[k], expect, rtol=1e-4)

    def test_lamb_trust_ratio(self, rng):
        params = make_params(rng)
        g = make_grads(rng, params)
        out, _ = run_steps(opt_mod.Lamb(learning_rate=0.01, lamb_weight_decay=0.01), params, [g])
        b1, b2, eps = 0.9, 0.999, 1e-6
        for k in params:
            p = np.asarray(params[k]); gn = np.asarray(g[k])
            m = (1 - b1) * gn; v = (1 - b2) * gn * gn
            mhat = m / (1 - b1); vhat = v / (1 - b2)
            r = mhat / (np.sqrt(vhat) + eps)
            upd = r + 0.01 * p
            trust = np.linalg.norm(p) / np.linalg.norm(upd)
            expect = p - 0.01 * trust * upd
            np.testing.assert_allclose(out[k], expect, rtol=1e-4)

    def test_lars(self, rng):
        params = make_params(rng)
        g = make_grads(rng, params)
        opt = opt_mod.Lars(learning_rate=0.1, momentum=0.9, lars_coeff=0.001,
                           lars_weight_decay=0.0005)
        out, _ = run_steps(opt, params, [g])
        for k in params:
            p = np.asarray(params[k]); gn = np.asarray(g[k])
            wn = np.linalg.norm(p); gnorm = np.linalg.norm(gn)
            local_lr = 0.001 * wn / (gnorm + 0.0005 * wn)
            v = 0.1 * local_lr * (gn + 0.0005 * p)
            np.testing.assert_allclose(out[k], p - v, rtol=1e-4)

    def test_l2_weight_decay_as_grad(self, rng):
        params = make_params(rng)
        grads = {k: jnp.zeros_like(v) for k, v in params.items()}
        out, _ = run_steps(opt_mod.SGD(learning_rate=0.1, weight_decay=0.5), params, [grads])
        for k in params:
            np.testing.assert_allclose(out[k], np.asarray(params[k]) * (1 - 0.05), rtol=1e-5)


class TestClip:
    def test_global_norm(self, rng):
        g = {"a": jnp.ones((10,)) * 3.0, "b": jnp.ones((10,)) * 4.0}
        clipped = opt_mod.ClipGradByGlobalNorm(1.0)(g)
        total = np.sqrt(sum(np.sum(np.square(np.asarray(v))) for v in clipped.values()))
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)
        # direction preserved
        np.testing.assert_allclose(
            np.asarray(clipped["b"]) / np.asarray(clipped["a"]), 4.0 / 3.0, rtol=1e-5
        )

    def test_global_norm_noop_below_threshold(self):
        g = {"a": jnp.ones((2,)) * 0.1}
        clipped = opt_mod.ClipGradByGlobalNorm(10.0)(g)
        np.testing.assert_allclose(clipped["a"], 0.1, rtol=1e-6)

    def test_by_value(self):
        g = {"a": jnp.asarray([-5.0, 0.5, 5.0])}
        out = opt_mod.ClipGradByValue(1.0)(g)
        np.testing.assert_allclose(out["a"], [-1.0, 0.5, 1.0])

    def test_by_norm(self):
        g = {"a": jnp.asarray([3.0, 4.0])}
        out = opt_mod.ClipGradByNorm(1.0)(g)
        np.testing.assert_allclose(np.linalg.norm(out["a"]), 1.0, rtol=1e-6)

    def test_clip_in_optimizer(self, rng):
        params = make_params(rng)
        g = {k: jnp.full(v.shape, 100.0) for k, v in params.items()}
        opt = opt_mod.SGD(learning_rate=1.0, grad_clip=opt_mod.ClipGradByValue(0.1))
        out, _ = run_steps(opt, params, [g])
        for k in params:
            np.testing.assert_allclose(out[k], np.asarray(params[k]) - 0.1, rtol=1e-5)


class TestJitAndEager:
    def test_update_is_jittable_and_matches(self, rng):
        params = make_params(rng)
        gs = [make_grads(rng, params) for _ in range(3)]
        opt = opt_mod.Adam(learning_rate=0.01)

        eager_params, _ = run_steps(opt, params, gs)

        @jax.jit
        def step(p, s, g):
            return opt.update(g, s, p)

        p, s = params, opt.init(params)
        for g in gs:
            p, s = step(p, s, g)
        for k in params:
            np.testing.assert_allclose(p[k], eager_params[k], rtol=1e-6)

    def test_eager_step_with_parameter_boxes(self, rng):
        w = Parameter(rng.randn(3, 3).astype(np.float32), name="w")
        b = Parameter(rng.randn(3).astype(np.float32), name="b")
        opt = opt_mod.SGD(learning_rate=0.5, parameters=[w, b])
        g = {"w": jnp.ones((3, 3)), "b": jnp.ones((3,))}
        before = w.numpy().copy()
        opt.step(g)
        np.testing.assert_allclose(w.numpy(), before - 0.5, rtol=1e-6)

    def test_state_dict_roundtrip(self, rng):
        w = Parameter(rng.randn(3).astype(np.float32), name="w")
        opt = opt_mod.Adam(learning_rate=0.01, parameters=[w])
        opt.step({"w": jnp.ones((3,))})
        sd = opt.state_dict()
        assert "w.moment1" in sd and "count" in sd

        w2 = Parameter(rng.randn(3).astype(np.float32), name="w")
        opt2 = opt_mod.Adam(learning_rate=0.01, parameters=[w2])
        opt2.set_state_dict(sd)
        np.testing.assert_allclose(
            opt2._eager_state["slots"]["w"]["moment1"], sd["w.moment1"]
        )

    def test_multi_precision_master_weights(self, rng):
        p32 = rng.randn(8, 8).astype(np.float32)
        params = {"w": jnp.asarray(p32).astype(jnp.bfloat16)}
        g = {"w": jnp.asarray(rng.randn(8, 8).astype(np.float32) * 1e-3).astype(jnp.bfloat16)}
        opt = opt_mod.Momentum(learning_rate=0.01, multi_precision=True)
        state = opt.init(params)
        assert state["slots"]["w"]["master"].dtype == jnp.float32
        p, state = opt.update(g, state, params)
        assert p["w"].dtype == jnp.bfloat16
        # master accumulates small updates that bf16 param would lose
        for _ in range(50):
            p, state = opt.update(g, state, params)
        assert not np.allclose(
            np.asarray(state["slots"]["w"]["master"]), p32, atol=1e-4
        )

    def test_frozen_param_skipped(self, rng):
        params = make_params(rng)
        g = {"p0": jnp.ones_like(params["p0"])}  # p1 missing
        out, _ = run_steps(opt_mod.SGD(learning_rate=0.1), params, [g])
        np.testing.assert_allclose(out["p1"], params["p1"])


class TestSchedulers:
    def test_piecewise(self):
        s = opt_mod.lr.PiecewiseDecay(boundaries=[2, 5], values=[1.0, 0.5, 0.1])
        lrs = []
        for _ in range(7):
            lrs.append(s())
            s.step()
        assert lrs[:2] == [1.0, 1.0]
        assert lrs[2:5] == [0.5, 0.5, 0.5]
        assert lrs[5:] == [0.1, 0.1]

    def test_exponential(self):
        s = opt_mod.lr.ExponentialDecay(learning_rate=1.0, gamma=0.5)
        assert s() == 1.0
        s.step()
        assert s() == 0.5

    def test_cosine(self):
        s = opt_mod.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
        np.testing.assert_allclose(s(), 1.0)
        s.step(10)
        np.testing.assert_allclose(s(), 0.0, atol=1e-7)

    def test_noam_peak_at_warmup(self):
        s = opt_mod.lr.NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
        vals = []
        for i in range(1, 300):
            s.step(i)
            vals.append(s())
        assert np.argmax(vals) == 99  # peak at warmup boundary

    def test_linear_warmup(self):
        s = opt_mod.lr.LinearWarmup(learning_rate=0.5, warmup_steps=10, start_lr=0.0, end_lr=0.5)
        s.step(5)
        np.testing.assert_allclose(s(), 0.25)
        s.step(20)
        np.testing.assert_allclose(s(), 0.5)

    def test_multistep(self):
        s = opt_mod.lr.MultiStepDecay(learning_rate=1.0, milestones=[2, 4], gamma=0.1)
        s.step(3)
        np.testing.assert_allclose(s(), 0.1)
        s.step(5)
        np.testing.assert_allclose(s(), 0.01, rtol=1e-6)

    def test_step_decay(self):
        s = opt_mod.lr.StepDecay(learning_rate=1.0, step_size=3, gamma=0.5)
        s.step(7)
        np.testing.assert_allclose(s(), 0.25)

    def test_lambda(self):
        s = opt_mod.lr.LambdaDecay(learning_rate=2.0, lr_lambda=lambda e: 1.0 / (e + 1))
        s.step(3)
        np.testing.assert_allclose(s(), 0.5)

    def test_reduce_on_plateau(self):
        s = opt_mod.lr.ReduceOnPlateau(learning_rate=1.0, patience=1, factor=0.1)
        for loss in [1.0, 1.0, 1.0]:
            s.step(loss)
        np.testing.assert_allclose(s(), 0.1)

    def test_value_at_matches_eager(self):
        for s in [
            opt_mod.lr.ExponentialDecay(learning_rate=1.0, gamma=0.9),
            opt_mod.lr.NaturalExpDecay(learning_rate=1.0, gamma=0.1),
            opt_mod.lr.InverseTimeDecay(learning_rate=1.0, gamma=0.1),
            opt_mod.lr.PolynomialDecay(learning_rate=1.0, decay_steps=20),
            opt_mod.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=17),
            opt_mod.lr.StepDecay(learning_rate=1.0, step_size=4),
            opt_mod.lr.MultiStepDecay(learning_rate=1.0, milestones=[3, 9]),
            opt_mod.lr.NoamDecay(d_model=64, warmup_steps=5),
            opt_mod.lr.PiecewiseDecay(boundaries=[4], values=[1.0, 0.1]),
        ]:
            for step in [0, 1, 5, 11]:
                s.step(step)
                np.testing.assert_allclose(
                    float(s.value_at(jnp.asarray(step))), s(), rtol=1e-5,
                    err_msg=f"{type(s).__name__} step={step}",
                )

    def test_scheduler_drives_optimizer(self, rng):
        sched = opt_mod.lr.PiecewiseDecay(boundaries=[1], values=[1.0, 0.0])
        opt = opt_mod.SGD(learning_rate=sched)
        params = {"w": jnp.ones((2,))}
        state = opt.init(params)
        params, state = opt.update({"w": jnp.ones((2,))}, state, params)
        np.testing.assert_allclose(params["w"], 0.0)  # lr=1
        sched.step()
        params, state = opt.update({"w": jnp.ones((2,))}, state, params)
        np.testing.assert_allclose(params["w"], 0.0)  # lr=0 → unchanged


class TestTraining:
    def test_quadratic_convergence(self, rng):
        """All optimizers minimize a convex quadratic."""
        target = jnp.asarray(rng.randn(6).astype(np.float32))

        def loss_fn(params):
            return jnp.sum(jnp.square(params["w"] - target))

        for opt in [
            opt_mod.SGD(learning_rate=0.05),
            opt_mod.Momentum(learning_rate=0.02),
            opt_mod.Adam(learning_rate=0.3),
            opt_mod.AdamW(learning_rate=0.3, weight_decay=0.0),
            opt_mod.RMSProp(learning_rate=0.1),
            opt_mod.Adagrad(learning_rate=0.9),
            opt_mod.Adamax(learning_rate=0.5),
        ]:
            params = {"w": jnp.zeros(6)}
            state = opt.init(params)
            step = jax.jit(lambda p, s: opt.update(jax.grad(loss_fn)(p), s, p))
            for _ in range(200):
                params, state = step(params, state)
            assert float(loss_fn(params)) < 1e-2, type(opt).__name__


class TestReviewRegressions:
    """Regression tests for the code-review findings on this package."""

    def test_step_with_layer_named_grads(self, rng):
        """Grad dicts keyed by Layer.named_parameters names must update
        unnamed layer-created parameter boxes (positional remap)."""
        import paddle_tpu.nn as nn
        lin = nn.Linear(3, 2)
        opt = opt_mod.SGD(learning_rate=1.0, parameters=lin.parameters())
        before = {n: p.numpy().copy() for n, p in lin.named_parameters()}
        grads = {n: jnp.ones_like(p.value) for n, p in lin.named_parameters()}
        opt.step(grads)
        for n, p in lin.named_parameters():
            np.testing.assert_allclose(p.numpy(), before[n] - 1.0, rtol=1e-6)

    def test_step_rejects_unknown_grad_names(self, rng):
        w = Parameter(rng.randn(3).astype(np.float32), name="w")
        opt = opt_mod.SGD(learning_rate=1.0, parameters=[w])
        with pytest.raises(Exception):
            opt.step({"w": jnp.ones((3,)), "nope": jnp.ones((3,))})

    def test_positional_grads_align_with_trainable_only(self, rng):
        w = Parameter(rng.randn(2).astype(np.float32), name="w")
        frozen = Parameter(rng.randn(2).astype(np.float32), name="f", trainable=False)
        b = Parameter(rng.randn(2).astype(np.float32), name="b")
        opt = opt_mod.SGD(learning_rate=1.0, parameters=[w, frozen, b])
        fb, bb = frozen.numpy().copy(), b.numpy().copy()
        opt.step([jnp.ones((2,)), jnp.ones((2,))])  # grads for w, b only
        np.testing.assert_allclose(frozen.numpy(), fb)
        np.testing.assert_allclose(b.numpy(), bb - 1.0, rtol=1e-6)

    def test_jit_with_scheduler_requires_explicit_lr(self):
        sched = opt_mod.lr.ExponentialDecay(learning_rate=1.0, gamma=0.5)
        opt = opt_mod.SGD(learning_rate=sched)
        params = {"w": jnp.ones((2,))}
        state = opt.init(params)

        @jax.jit
        def bad(p, s, g):
            return opt.update(g, s, p)

        with pytest.raises(Exception, match="baked"):
            bad(params, state, {"w": jnp.ones((2,))})

        # explicit lr works and tracks the scheduler without retrace
        @jax.jit
        def good(p, s, g, lr):
            return opt.update(g, s, p, lr=lr)

        p, s = good(params, state, {"w": jnp.ones((2,))}, sched())
        np.testing.assert_allclose(p["w"], 0.0)
        sched.step()
        p, s = good(p, s, {"w": jnp.ones((2,))}, sched())
        np.testing.assert_allclose(p["w"], -0.5)

    def test_polynomial_cycle_value_at(self):
        s = opt_mod.lr.PolynomialDecay(1.0, decay_steps=10, cycle=True)
        s.step(15)
        np.testing.assert_allclose(float(s.value_at(jnp.asarray(15))), s(), rtol=1e-5)

    def test_linear_warmup_state_roundtrip(self):
        inner = opt_mod.lr.ExponentialDecay(learning_rate=1.0, gamma=0.5)
        s = opt_mod.lr.LinearWarmup(inner, warmup_steps=3, start_lr=0.0, end_lr=1.0)
        for _ in range(6):
            s.step()
        sd = s.state_dict()
        inner2 = opt_mod.lr.ExponentialDecay(learning_rate=1.0, gamma=0.5)
        s2 = opt_mod.lr.LinearWarmup(inner2, warmup_steps=3, start_lr=0.0, end_lr=1.0)
        s2.set_state_dict(sd)
        assert s2() == s()
        assert inner2.last_epoch == inner.last_epoch

    def test_state_dict_does_not_revert_hyperparams(self):
        s = opt_mod.lr.MultiStepDecay(learning_rate=1.0, milestones=[2, 4])
        sd = s.state_dict()
        assert "milestones" not in sd and "gamma" not in sd

    def test_functional_set_state_dict_raises(self):
        opt = opt_mod.Adam()
        with pytest.raises(Exception, match="functional"):
            opt.set_state_dict({"count": 3, "w.moment1": np.zeros(2)})

    def test_adamw_bf16_decay_effective(self, rng):
        # decay large enough to survive bf16 storage rounding: f32 math path
        p = {"w": jnp.full((4,), 1.0, dtype=jnp.bfloat16)}
        g = {"w": jnp.zeros((4,), dtype=jnp.bfloat16)}
        opt = opt_mod.AdamW(learning_rate=0.1, weight_decay=0.5)
        state = opt.init(p)
        x, state = opt.update(g, state, p)
        np.testing.assert_allclose(float(x["w"][0]), 0.95, rtol=1e-2)

        # tiny decay on bf16 storage needs master weights (multi_precision)
        opt2 = opt_mod.AdamW(learning_rate=0.1, weight_decay=0.01,
                             multi_precision=True)
        state2 = opt2.init(p)
        x2 = p
        for _ in range(10):
            x2, state2 = opt2.update(g, state2, x2)
        assert float(state2["slots"]["w"]["master"][0]) < 1.0 - 5e-3

    def test_lamb_exclude_fn(self, rng):
        params = make_params(rng)
        g = {k: jnp.zeros_like(v) for k, v in params.items()}
        opt = opt_mod.Lamb(learning_rate=0.1, lamb_weight_decay=0.5,
                           exclude_from_weight_decay_fn=lambda n: n == "p1")
        out, _ = run_steps(opt, params, [g])
        np.testing.assert_allclose(out["p1"], params["p1"])  # excluded: no decay
        assert not np.allclose(np.asarray(out["p0"]), np.asarray(params["p0"]))


class TestRegularizer:
    """paddle.regularizer L1Decay/L2Decay as optimizer weight_decay
    (reference: regularizer.py:20,82 over append_regularization_ops)."""

    def test_l2decay_object_equals_float_coeff(self):
        w0 = jnp.full((4,), 2.0)
        g = {"w": jnp.zeros((4,))}

        def run(wd):
            opt = opt_mod.Momentum(learning_rate=0.1, momentum=0.9,
                                weight_decay=wd)
            state = opt.init({"w": w0})
            p = {"w": w0}
            for _ in range(5):
                p, state = opt.update(g, state, p)
            return np.asarray(p["w"])

        np.testing.assert_allclose(run(0.01),
                                   run(paddle.regularizer.L2Decay(0.01)))

    def test_l1decay_gradient_is_sign(self):
        w0 = jnp.asarray([2.0, -3.0, 0.5, -0.1])
        opt = opt_mod.SGD(learning_rate=0.1,
                       weight_decay=paddle.regularizer.L1Decay(0.05))
        state = opt.init({"w": w0})
        p, _ = opt.update({"w": jnp.zeros_like(w0)}, state, {"w": w0})
        want = np.asarray(w0) - 0.1 * 0.05 * np.sign(np.asarray(w0))
        np.testing.assert_allclose(np.asarray(p["w"]), want, rtol=1e-6)

    def test_l1_drives_weights_to_zero(self):
        w = {"w": jnp.full((8,), 0.3)}
        opt = opt_mod.SGD(learning_rate=0.1,
                       weight_decay=paddle.regularizer.L1Decay(0.5))
        state = opt.init(w)
        for _ in range(200):
            w, state = opt.update({"w": jnp.zeros((8,))}, state, w)
        # pure L1 decay oscillates around zero within one step size
        assert np.abs(np.asarray(w["w"])).max() <= 0.1 * 0.5 + 1e-6


    def test_adamw_accepts_l2decay_rejects_l1(self):
        a = opt_mod.AdamW(learning_rate=1e-3,
                          weight_decay=paddle.regularizer.L2Decay(0.02))
        assert a._coeff == 0.02
        with pytest.raises(Exception, match="decoupled"):
            opt_mod.AdamW(weight_decay=paddle.regularizer.L1Decay(0.02))

"""Linear-chain CRF ops vs brute-force enumeration.

Reference capability: operators/linear_chain_crf_op.h (forward algorithm)
and crf_decoding_op.h (Viterbi) — the ops behind the label_semantic_roles
book test.  Small tag/time sizes let every path be enumerated exactly.
"""
import itertools

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.functional import (
    crf_decoding,
    linear_chain_crf,
    viterbi_decode,
)

D, T, B = 3, 4, 5


def _rand(seed=0):
    rng = np.random.RandomState(seed)
    emission = rng.randn(B, T, D).astype(np.float32)
    transition = rng.randn(D + 2, D).astype(np.float32)
    labels = rng.randint(0, D, (B, T)).astype(np.int32)
    lengths = np.array([T, T - 1, 2, 1, T], np.int32)
    return emission, transition, labels, lengths


def _path_score(e_b, transition, path):
    start, stop, trans = (transition[0], transition[1], transition[2:])
    s = start[path[0]] + e_b[0, path[0]]
    for t in range(1, len(path)):
        s += trans[path[t - 1], path[t]] + e_b[t, path[t]]
    return s + stop[path[-1]]


def _brute(e_b, transition, length):
    scores = {
        p: _path_score(e_b[:length], transition, p)
        for p in itertools.product(range(D), repeat=length)
    }
    arr = np.array(list(scores.values()))
    log_z = np.log(np.exp(arr - arr.max()).sum()) + arr.max()
    best = max(scores, key=scores.get)
    return log_z, np.array(best), scores[best]


class TestLinearChainCrf:
    def test_nll_matches_bruteforce(self):
        emission, transition, labels, lengths = _rand()
        nll = np.asarray(linear_chain_crf(emission, transition, labels,
                                          lengths))
        assert nll.shape == (B, 1)
        for b in range(B):
            L = lengths[b]
            log_z, _, _ = _brute(emission[b], transition, L)
            gold = _path_score(emission[b][:L], transition, labels[b][:L])
            np.testing.assert_allclose(nll[b, 0], log_z - gold, rtol=1e-5)

    def test_full_length_default(self):
        emission, transition, labels, _ = _rand()
        a = np.asarray(linear_chain_crf(emission, transition, labels))
        b = np.asarray(linear_chain_crf(emission, transition, labels,
                                        np.full(B, T, np.int32)))
        np.testing.assert_allclose(a, b)

    def test_gradients_flow_and_train(self):
        """Minimizing the NLL must drive p(gold) → 1 on a toy problem."""
        emission, transition, labels, lengths = _rand()
        trans = jnp.asarray(transition)
        em = jnp.asarray(emission)

        def loss(trans, em):
            return linear_chain_crf(em, trans, labels, lengths).mean()

        g = jax.grad(loss, argnums=(0, 1))(trans, em)
        assert all(np.isfinite(np.asarray(x)).all() for x in g)
        l0 = float(loss(trans, em))

        @jax.jit
        def sgd(trans, em):
            gt, ge = jax.grad(loss, argnums=(0, 1))(trans, em)
            return trans - 0.5 * gt, em - 0.5 * ge

        for _ in range(200):
            trans, em = sgd(trans, em)
        lN = float(loss(trans, em))
        assert lN < l0 * 0.1
        # decoded path now equals the gold labels inside each length
        path = np.asarray(crf_decoding(em, trans, length=lengths))
        for b in range(B):
            np.testing.assert_array_equal(path[b, :lengths[b]],
                                          labels[b, :lengths[b]])


class TestCrfGradients:
    def test_nll_grads_match_finite_differences(self):
        """OpTest.check_grad equivalent for the CRF forward algorithm —
        the reference hand-writes LinearChainCRFGradOpKernel; here the
        scan's VJP must match numeric gradients."""
        from grad_check import check_grad

        rng = np.random.RandomState(0)
        em = rng.randn(2, 3, D).astype(np.float64)
        tr = rng.randn(D + 2, D).astype(np.float64)
        y = rng.randint(0, D, (2, 3)).astype(np.int32)
        ln = np.array([3, 2], np.int32)

        def nll_em(e):
            return linear_chain_crf(e, jnp.asarray(tr), y, ln).sum()

        def nll_tr(t):
            return linear_chain_crf(jnp.asarray(em), t, y, ln).sum()

        check_grad(nll_em, [em])
        check_grad(nll_tr, [tr])


class TestViterbi:
    def test_matches_bruteforce(self):
        emission, transition, labels, lengths = _rand(1)
        path, score = viterbi_decode(emission, transition, lengths)
        path, score = np.asarray(path), np.asarray(score)
        for b in range(B):
            L = lengths[b]
            _, best, best_score = _brute(emission[b], transition, L)
            np.testing.assert_array_equal(path[b, :L], best)
            np.testing.assert_allclose(score[b], best_score, rtol=1e-5)
            assert (path[b, L:] == 0).all()

    def test_crf_decoding_agreement_mode(self):
        """Reference semantics (crf_decoding_op.h:70): 1 where the label
        AGREES with the best path, 0 elsewhere and beyond length."""
        emission, transition, _, lengths = _rand(2)
        path = np.asarray(crf_decoding(emission, transition,
                                       length=lengths))
        # feed the decoded path back as labels → all ones within lengths
        hit = np.asarray(crf_decoding(emission, transition, label=path,
                                      length=lengths))
        for b in range(B):
            assert (hit[b, :lengths[b]] == 1).all()
            assert (hit[b, lengths[b]:] == 0).all()
        # flip one in-length position → exactly that position reads 0
        wrong = path.copy()
        wrong[0, 0] = (wrong[0, 0] + 1) % D
        agree = np.asarray(crf_decoding(emission, transition, label=wrong,
                                        length=lengths))
        assert agree[0, 0] == 0
        assert agree.sum() == hit.sum() - 1

    def test_t1_edge(self):
        emission, transition, labels, _ = _rand(3)
        e1 = emission[:, :1]
        path, _ = viterbi_decode(e1, transition)
        start, stop = transition[0], transition[1]
        want = np.argmax(e1[:, 0] + start[None] + stop[None], axis=-1)
        np.testing.assert_array_equal(np.asarray(path)[:, 0], want)
        nll = np.asarray(linear_chain_crf(e1, transition, labels[:, :1]))
        assert np.isfinite(nll).all()

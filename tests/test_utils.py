"""paddle_tpu.utils — run_check, deprecated, try_import.

Reference capability: python/paddle/utils/ (install_check.py:134,
deprecated.py:31, lazy_import.py:19).
"""
import warnings

import pytest

import paddle_tpu as paddle


class TestUtils:
    def test_run_check_passes_and_restores_state(self, capsys):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.mesh import _global_mesh  # noqa: F401
        from paddle_tpu.framework import random as prandom

        paddle.seed(1234)
        key_before = prandom.get_rng_state()
        strategy_before = fleet._strategy
        paddle.utils.run_check()
        out = capsys.readouterr().out
        assert "installed successfully" in out
        assert "8" in out  # the 8-device CPU mesh exercises the DP leg
        # the sanity check must not perturb the session
        import numpy as np

        assert fleet._strategy is strategy_before
        np.testing.assert_array_equal(
            np.asarray(prandom.get_rng_state()),
            np.asarray(key_before))

    def test_deprecated_warns_and_documents(self):
        @paddle.utils.deprecated(since="0.1", update_to="paddle.new_api",
                                 reason="renamed")
        def old_api(x):
            """Old docstring."""
            return x + 1

        assert "deprecated since 0.1" in old_api.__doc__
        assert "paddle.new_api" in old_api.__doc__
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old_api(1) == 2
        assert any(issubclass(x.category, DeprecationWarning) for x in w)

    def test_try_import(self):
        mod = paddle.utils.try_import("math")
        assert mod.sqrt(4) == 2
        with pytest.raises(ImportError, match="pip install"):
            paddle.utils.try_import("definitely_not_a_module_xyz")


class TestCompatSysconfig:
    """paddle.compat (compat.py:36,120,193) + paddle.sysconfig."""

    def test_to_text_to_bytes(self):
        assert paddle.compat.to_text(b"abc") == "abc"
        assert paddle.compat.to_bytes("abc") == b"abc"
        assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]
        assert paddle.compat.to_bytes({"a"}) == {b"a"}
        # dicts convert keys AND values (reference compat.py:74)
        assert paddle.compat.to_text({b"k": b"v"}) == {"k": "v"}
        lst = [b"x"]
        out = paddle.compat.to_text(lst, inplace=True)
        assert out is lst and lst == ["x"]

    def test_round_half_away_from_zero(self):
        assert paddle.compat.round(0.5) == 1.0
        assert paddle.compat.round(-0.5) == -1.0
        assert paddle.compat.round(2.675, 2) == 2.68
        assert paddle.compat.round(0.0) == 0.0

    def test_misc(self):
        assert paddle.compat.floor_division(7, 2) == 3
        assert paddle.compat.get_exception_message(ValueError("x")) == "x"

    def test_sysconfig_paths(self):
        import os

        inc = paddle.sysconfig.get_include()
        assert os.path.isdir(inc)
        assert any(f.endswith(".cc") for f in os.listdir(inc))
        lib = paddle.sysconfig.get_lib()
        assert os.path.isdir(lib)  # must exist even before any native build

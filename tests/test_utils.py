"""paddle_tpu.utils — run_check, deprecated, try_import.

Reference capability: python/paddle/utils/ (install_check.py:134,
deprecated.py:31, lazy_import.py:19).
"""
import warnings

import pytest

import paddle_tpu as paddle


class TestUtils:
    def test_run_check_passes_and_restores_state(self, capsys):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.mesh import _global_mesh  # noqa: F401
        from paddle_tpu.framework import random as prandom

        paddle.seed(1234)
        key_before = prandom.get_rng_state()
        strategy_before = fleet._strategy
        paddle.utils.run_check()
        out = capsys.readouterr().out
        assert "installed successfully" in out
        assert "8" in out  # the 8-device CPU mesh exercises the DP leg
        # the sanity check must not perturb the session
        import numpy as np

        assert fleet._strategy is strategy_before
        np.testing.assert_array_equal(
            np.asarray(prandom.get_rng_state()),
            np.asarray(key_before))

    def test_deprecated_warns_and_documents(self):
        @paddle.utils.deprecated(since="0.1", update_to="paddle.new_api",
                                 reason="renamed")
        def old_api(x):
            """Old docstring."""
            return x + 1

        assert "deprecated since 0.1" in old_api.__doc__
        assert "paddle.new_api" in old_api.__doc__
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old_api(1) == 2
        assert any(issubclass(x.category, DeprecationWarning) for x in w)

    def test_try_import(self):
        mod = paddle.utils.try_import("math")
        assert mod.sqrt(4) == 2
        with pytest.raises(ImportError, match="pip install"):
            paddle.utils.try_import("definitely_not_a_module_xyz")

"""Paged KV cache + copy-on-write prefix sharing + speculative decoding
(serving/paging.py, serving/generation.py paged mode, models/gpt.py
``forward_paged``/``init_paged_cache``/``copy_pages``).

Covers the paged scheduler's contract: token identity with uncached
greedy AND the dense ring path under staggered mid-decode admission; the
closed paged compile set (``len(prompt_buckets) + 3`` with speculation
on — the extra trace is the ``[B, 1]`` no-draft fast step — zero
post-warmup retraces); CoW isolation (a sibling's divergent write never perturbs a
shared prefix page); speculative accept/reject bit-identity vs plain
greedy (including past the ring-wrap point where drafting disables);
pool-exhaustion preemption; ``PagePool`` accounting invariants; and
analysis rule S604 (admission starved by a page leak).
"""
import time
import unittest

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.errors import InvalidArgumentError, UnavailableError
from paddle_tpu.framework.flags import set_flags
from paddle_tpu.serving import GenerationEngine, PagePool


class TestPagePool(unittest.TestCase):
    def test_alloc_release_refcounts(self):
        pool = PagePool(num_slots=2, num_pages=8, page_size=4, max_len=16)
        self.assertEqual(pool.free_pages, 8)
        prompt = np.arange(6, dtype=np.int32)  # 2 pages
        pairs, shared = pool.admit(0, prompt)
        self.assertEqual((pairs, shared), ([], 0))
        self.assertEqual(pool.free_pages, 6)
        self.assertEqual(pool.pos_map[0, 5], 5)
        self.assertEqual(pool.pos_map[0, 6], -1)
        pool.release(0)
        self.assertEqual(pool.free_pages, 8)
        self.assertTrue((pool.table[0] == -1).all())
        self.assertEqual(pool.leaked_pages(), 0)

    def test_prefix_sharing_and_cow(self):
        pool = PagePool(num_slots=3, num_pages=12, page_size=4, max_len=16)
        prompt = np.arange(10, dtype=np.int32)  # pages 0-1 full, page 2 part
        pool.admit(0, prompt)
        pool.register_prefix("sys", 0, prompt)
        base = pool.free_pages
        # sibling shares 2 full pages, CoWs the partial boundary page
        sib = np.concatenate([prompt, [50, 51]]).astype(np.int32)
        pairs, shared = pool.admit(1, sib, prefix_key="sys")
        self.assertEqual(shared, 10)
        self.assertEqual(len(pairs), 1)  # the boundary-page copy
        self.assertEqual(pool.pages_needed(sib, "sys"), 1)
        self.assertEqual(pool.free_pages, base - 1)
        self.assertGreaterEqual(pool.shared_pages, 2)
        # full shared pages are mapped, not copied
        self.assertEqual(pool.table[1, 0], pool.table[0, 0])
        self.assertEqual(pool.table[1, 1], pool.table[0, 1])
        self.assertNotEqual(pool.table[1, 2], pool.table[0, 2])
        # divergent-token prompt must NOT share, even with the key
        other = np.arange(10, dtype=np.int32)[::-1].copy()
        pairs, shared = pool.admit(2, other, prefix_key="sys")
        self.assertEqual((pairs, shared), ([], 0))
        # the registry holds a ref on the boundary page, so the donor's
        # own next write CoWs it — registered prefix data stays pristine
        # for siblings admitted later
        old = int(pool.table[0, 2])
        pr = pool.ensure_writable(0, 10)
        self.assertIsNotNone(pr)
        self.assertEqual(pr[0], old)
        self.assertNotEqual(int(pool.table[0, 2]), old)
        # but a write into a FULL shared page (ring wrap) does CoW
        pr = pool.ensure_writable(1, 16)  # wraps to slot 0, page 0 shared
        self.assertIsNotNone(pr)
        self.assertEqual(pr[0], pool.table[0, 0])
        self.assertNotEqual(pool.table[1, 0], pool.table[0, 0])
        # registry pins pages past every holder's release
        pool.release(0), pool.release(1), pool.release(2)
        self.assertEqual(pool.leaked_pages(), 0)
        self.assertLess(pool.free_pages, 12)
        pool.drop_prefix("sys")
        self.assertEqual(pool.free_pages, 12)

    def test_exhaustion_raises_and_rolls_back(self):
        pool = PagePool(num_slots=2, num_pages=4, page_size=4, max_len=16)
        pool.admit(0, np.arange(12, dtype=np.int32))  # 3 pages
        with self.assertRaises(MemoryError):
            pool.admit(1, np.arange(8, dtype=np.int32))  # needs 2, 1 free
        # failed admission rolled back completely
        self.assertTrue((pool.table[1] == -1).all())
        self.assertEqual(pool.free_pages, 1)
        self.assertEqual(pool.leaked_pages(), 0)

    def test_geometry_validation(self):
        with self.assertRaises(ValueError):
            PagePool(num_slots=1, num_pages=8, page_size=5, max_len=16)
        with self.assertRaises(ValueError):  # pool can't hold one slot
            PagePool(num_slots=1, num_pages=2, page_size=4, max_len=16)


class TestPagedGeneration(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        pt.seed(4321)
        cls.cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_position=64, dropout=0.0)
        cls.model = GPTForCausalLM(cls.cfg)
        cls.model.eval()

    def _ref_greedy(self, prompt, n, eos=None):
        import jax.numpy as jnp
        ids, outs = list(map(int, prompt)), []
        for _ in range(n):
            logits = np.asarray(self.model(jnp.asarray([ids], jnp.int32)))[0]
            nxt = int(np.argmax(logits[-1]))
            outs.append(nxt)
            ids.append(nxt)
            if eos is not None and nxt == eos:
                break
        return outs

    def test_token_identity_staggered_admission(self):
        # the continuous-batching interleavings, paged: a long request
        # pins a slot while shorts churn through the other as pages
        # allocate and free underneath — every output must match
        # uncached greedy
        prompts = [(np.arange(10) * 5 + 2) % 97, np.arange(3) % 97,
                   (np.arange(6) * 3) % 97, (np.arange(4) * 7 + 1) % 97,
                   (np.arange(5) * 11 + 3) % 97]
        budgets = [14, 3, 4, 5, 3]
        refs = [self._ref_greedy(p, b) for p, b in zip(prompts, budgets)]
        with GenerationEngine(self.model, prompt_buckets=[8, 16],
                              batch_size=2, paged=True, kv_page_size=8,
                              speculative_k=3,
                              name="pg-stagger") as eng:
            # 2 admits + unified step + its [B, 1] fast trace + CoW op;
            # eviction is a host table edit with no executable
            self.assertEqual(eng.warmup(), 5)
            futs = [eng.submit(prompts[0], budgets[0]),
                    eng.submit(prompts[1], budgets[1])]
            for p, b in zip(prompts[2:], budgets[2:]):
                time.sleep(0.02)
                futs.append(eng.submit(p, b))
            gens = [f.result(120) for f in futs]
            for g, ref in zip(gens, refs):
                self.assertEqual(g.tolist(), ref)
            # page churn never reopened the compile set
            self.assertEqual(eng.compile_count, 5)
            st = eng.stats()
            self.assertTrue(st["paged"])
            self.assertEqual(st["kv_pages_free"],
                             eng._pool.num_pages)  # all returned
            self.assertEqual(st["kv_pages_leaked"], 0)

    def test_cow_prefix_sharing_isolation(self):
        # four requests share a system prompt under one prefix_key; the
        # prefix prefills once, siblings CoW the boundary page, and
        # every completion must still match uncached greedy computed
        # WITHOUT any sharing — divergent writes never reach a shared
        # page
        sys_p = (np.arange(11) * 7 + 3) % 97
        prompts = [np.concatenate([sys_p, e]).astype(np.int32)
                   for e in ([5, 9, 2], [5, 9, 2, 44], [61], [30, 8])]
        budgets = [6, 5, 8, 7]
        refs = [self._ref_greedy(p, b) for p, b in zip(prompts, budgets)]
        with GenerationEngine(self.model, prompt_buckets=[16],
                              batch_size=2, cache_len=64, paged=True,
                              kv_page_size=8, speculative_k=2,
                              name="pg-cow") as eng:
            eng.warmup()
            outs = []
            for p, b in zip(prompts, budgets):
                outs.append(eng.submit(p, b, prefix_key="sys",
                                       prefix_len=len(sys_p)))
            for o, ref in zip(outs, refs):
                self.assertEqual(o.result(120).tolist(), ref)
            st = eng.stats()
            # the boundary page was CoW'd for at least one sibling and
            # full prefix pages were actually mapped shared
            self.assertGreater(st["cow_copies"], 0)
            self.assertGreater(st["prefix_hits"], 0)
            self.assertEqual(st["kv_pages_leaked"], 0)
            # 1 admit + step + fast step + cow
            self.assertEqual(eng.compile_count, 4)

    def test_speculative_bit_identity_and_ring_wrap(self):
        # repetitive continuations make the n-gram proposer hit; accepted
        # AND rejected drafts must leave tokens bit-identical to the
        # dense ring engine — including past position C where drafting
        # disables and the window slides
        p = (np.arange(6) * 9 + 4) % 97
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              cache_len=32, paged=True, kv_page_size=8,
                              speculative_k=3, name="pg-spec") as eng, \
             GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              cache_len=32, paged=False,
                              name="pg-spec-dense") as dense:
            eng.warmup()
            dense.warmup()
            ref = dense.generate(p, 45, timeout=120).tolist()
            out = eng.generate(p, 45, timeout=120).tolist()
            self.assertEqual(out, ref)
            st = eng.stats()
            self.assertGreater(st["spec_drafted"], 0)
            self.assertGreaterEqual(st["spec_drafted"], st["spec_accepted"])
            # speculation paid off: fewer steps than tokens decoded
            self.assertLess(st["decode_steps"], 45)

    def test_pool_exhaustion_preempts_and_recovers(self):
        # a pool too small for both requests' full decode: the newest
        # slot is preempted mid-flight, requeued, and regenerated —
        # outputs still exact
        pa = (np.arange(4) * 13 + 1) % 97
        pb = (np.arange(4) * 5 + 2) % 97
        refs = [self._ref_greedy(pa, 26), self._ref_greedy(pb, 26)]
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              cache_len=32, paged=True, kv_page_size=4,
                              kv_pages=9, speculative_k=0,
                              circuit_breaker=False,
                              name="pg-preempt") as eng:
            eng.warmup()
            fa = eng.submit(pa, 26)
            fb = eng.submit(pb, 26)
            self.assertEqual(fa.result(120).tolist(), refs[0])
            self.assertEqual(fb.result(120).tolist(), refs[1])
            st = eng.stats()
            self.assertGreaterEqual(st["preempted"], 1)
            self.assertEqual(st["kv_pages_leaked"], 0)
            self.assertEqual(st["kv_pages_free"], 9)

    def test_transient_failure_restarts_rebuild_pool(self):
        from paddle_tpu.resilience.faults import FaultPlan
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              paged=True, kv_page_size=8, speculative_k=2,
                              circuit_breaker=False,
                              name="pg-restart") as eng:
            eng.warmup()
            p = (np.arange(5) * 9 + 4) % 97
            ref = self._ref_greedy(p, 6)
            self.assertEqual(eng.generate(p, 6, timeout=120).tolist(), ref)
            plan = FaultPlan.parse(
                "site=serving.decode,nth=1,error=TransientDeviceError")
            with plan:
                self.assertEqual(
                    eng.generate(p, 6, timeout=120).tolist(), ref)
            self.assertEqual(plan.stats()["serving.decode"]["fired"], 1)
            st = eng.stats()
            self.assertGreaterEqual(st["restarts"], 1)
            # the rebuilt pool starts clean
            self.assertEqual(st["kv_pages_leaked"], 0)

    def test_flag_and_mode_validation(self):
        set_flags({"paged_kv": True})
        try:
            eng = GenerationEngine(self.model, prompt_buckets=[8],
                                   batch_size=1, name="pg-flag")
            try:
                self.assertTrue(eng.stats()["paged"])
                p = np.arange(3) % 97
                self.assertEqual(eng.generate(p, 3, timeout=120).tolist(),
                                 self._ref_greedy(p, 3))
            finally:
                eng.close()
        finally:
            set_flags({"paged_kv": False})
        with self.assertRaises(InvalidArgumentError):
            GenerationEngine(self.model, prompt_buckets=[8], batch_size=1,
                             paged=True, continuous=False, name="pg-bad")

    def test_s604_fires_on_page_leak(self):
        from paddle_tpu.analysis import RetraceMonitor
        with RetraceMonitor(budget=8) as mon:
            eng = GenerationEngine(self.model, prompt_buckets=[8],
                                   batch_size=1, cache_len=32, paged=True,
                                   kv_page_size=8, name="pg-leak")
            try:
                eng.warmup()
                # inject a page leak: drain the free list with refcounts
                # held by no slot table and no prefix registry — exactly
                # the state a release/decref pairing bug produces
                pool = eng._pool
                while pool.alloc() is not None:
                    pass
                self.assertEqual(pool.free_pages, 0)
                self.assertGreater(pool.leaked_pages(), 0)
                fut = eng.submit(np.arange(3) % 97, 4)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if eng.stats()["starved_steps_after_warm"] > 8:
                        break
                    time.sleep(0.02)
                self.assertGreater(
                    eng.stats()["starved_steps_after_warm"], 8)
                time.sleep(0.25)  # let a publish tick carry the gauges
                diags = [d for d in mon.diagnostics() if d.rule == "S604"]
                self.assertTrue(diags, mon.diagnostics())
                self.assertIn("page leak", diags[0].message)
            finally:
                eng.close(drain=False, timeout=10)
            self.assertIsInstance(fut.exception(timeout=5),
                                  UnavailableError)


if __name__ == "__main__":
    unittest.main()

"""paddle_tpu.serving — bucketed dynamic batching + KV-cache generation.

Covers the serving contract end to end: bucket routing/padding, the
CLOSED compile set under mixed live traffic (the whole point of the
subsystem), token-identical KV-cache decode vs the uncached forward,
robustness (deadlines, load shedding, graceful drain, runner-failure
isolation), hot weight-swap with zero recompiles, metrics on the
trace_events bus, and the S601 bucket-miss analysis rule.
"""
import os
import tempfile
import threading
import time
import unittest

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.analysis import RetraceMonitor
from paddle_tpu.framework.errors import (
    ExecutionTimeoutError,
    InvalidArgumentError,
    UnavailableError,
)
from paddle_tpu.serving import (
    Bucket,
    BucketSet,
    GenerationEngine,
    InferenceEngine,
    MicroBatcher,
    as_bucket,
)


class TestBucketing(unittest.TestCase):
    def test_as_bucket_shorthand(self):
        self.assertEqual(as_bucket((64,)).shapes, ((64,),))
        self.assertEqual(as_bucket(((64, 8), (64,))).shapes, ((64, 8), (64,)))
        b = Bucket(((16,),), batch_size=32)
        self.assertIs(as_bucket(b), b)
        with self.assertRaises(InvalidArgumentError):
            as_bucket("nope")
        with self.assertRaises(InvalidArgumentError):
            Bucket(((0,),))

    def test_route_smallest_fit(self):
        bs = BucketSet([(64,), (16,), (256,)])
        self.assertEqual(bs.route(((10,),)), 1)   # 16 is the smallest fit
        self.assertEqual(bs.route(((16,),)), 1)
        self.assertEqual(bs.route(((17,),)), 0)   # next up: 64
        self.assertEqual(bs.route(((200,),)), 2)
        self.assertEqual(bs.route(((300,),)), -1)  # miss
        self.assertEqual(bs.route(((10, 2),)), -1)  # rank mismatch = miss

    def test_pad_request(self):
        bs = BucketSet([((8, 4),)], pad_value=7)
        out = bs.pad_request(0, [np.ones((3, 4), np.float32)])
        self.assertEqual(out[0].shape, (8, 4))
        np.testing.assert_array_equal(out[0][:3], 1.0)
        np.testing.assert_array_equal(out[0][3:], 7.0)


class TestMicroBatcher(unittest.TestCase):
    def _echo_batcher(self, **kw):
        # router: bucket by first-input length; runner: echo batch size
        return MicroBatcher(
            lambda ins: len(ins[0]),
            lambda bucket, reqs: [(bucket, len(reqs))] * len(reqs), **kw)

    def test_groups_same_bucket(self):
        with self._echo_batcher(max_batch_size=4,
                                max_queue_delay_ms=60.0) as mb:
            futs = [mb.submit(([0, 0],)) for _ in range(4)]
            self.assertEqual({f.result(10) for f in futs}, {(2, 4)})

    def test_delay_flushes_partial_batch(self):
        with self._echo_batcher(max_batch_size=64,
                                max_queue_delay_ms=10.0) as mb:
            self.assertEqual(mb.submit(([0],)).result(10), (1, 1))

    def test_deadline_expires_queued_request(self):
        release = threading.Event()

        def slow_runner(bucket, reqs):
            release.wait(10)
            return [None] * len(reqs)

        mb = MicroBatcher(lambda ins: 0, slow_runner,
                          max_batch_size=1, max_queue_delay_ms=0.0)
        try:
            blocker = mb.submit((np.zeros(1),))        # occupies the worker
            doomed = mb.submit((np.zeros(1),), deadline_ms=1.0)
            time.sleep(0.05)
            release.set()
            blocker.result(10)
            with self.assertRaises(ExecutionTimeoutError):
                doomed.result(10)
        finally:
            release.set()
            mb.close()

    def test_load_shedding(self):
        started, release = threading.Event(), threading.Event()

        def slow_runner(bucket, reqs):
            started.set()
            release.wait(10)
            return [None] * len(reqs)

        mb = MicroBatcher(lambda ins: 0, slow_runner,
                          max_batch_size=1, max_queue_delay_ms=0.0,
                          max_queue_depth=2)
        try:
            futs = [mb.submit((np.zeros(1),))]
            self.assertTrue(started.wait(10))  # worker is now busy
            futs += [mb.submit((np.zeros(1),)) for _ in range(2)]
            with self.assertRaises(UnavailableError):  # depth at limit
                mb.submit((np.zeros(1),))
            self.assertGreaterEqual(mb.metrics.snapshot()["shed"], 1)
            release.set()
            for f in futs:
                f.result(10)
        finally:
            release.set()
            mb.close()

    def test_runner_exception_fails_batch_not_worker(self):
        calls = []

        def runner(bucket, reqs):
            calls.append(bucket)
            if bucket == 13:
                raise RuntimeError("boom")
            return [bucket] * len(reqs)

        with MicroBatcher(lambda ins: len(ins[0]), runner,
                          max_batch_size=1, max_queue_delay_ms=0.0) as mb:
            bad = mb.submit(([0] * 13,))
            with self.assertRaises(RuntimeError):
                bad.result(10)
            self.assertEqual(mb.submit(([0],)).result(10), 1)  # still alive

    def test_graceful_drain_and_closed_submit(self):
        mb = self._echo_batcher(max_batch_size=2, max_queue_delay_ms=1.0)
        futs = [mb.submit(([0],)) for _ in range(5)]
        mb.close(drain=True, timeout=10)
        for f in futs:
            self.assertIsNotNone(f.result(0))  # all served before join
        with self.assertRaises(UnavailableError):
            mb.submit(([0],))


class _TinyNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)

    def forward(self, x):
        return self.fc(x)


def _export_tiny(tmpdir, name="m", seed=None):
    if seed is not None:
        pt.seed(seed)
    net = _TinyNet()
    prefix = os.path.join(tmpdir, name)
    pt.inference.save_inference_model(
        prefix, net, [pt.static.InputSpec([None, None, 8], "float32")])
    return prefix, net


class TestInferenceEngine(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        cls.tmp = tempfile.TemporaryDirectory()
        cls.prefix, cls.net = _export_tiny(cls.tmp.name, seed=1234)

    @classmethod
    def tearDownClass(cls):
        cls.tmp.cleanup()

    def _engine(self, **kw):
        kw.setdefault("max_batch_size", 4)
        kw.setdefault("max_queue_delay_ms", 2.0)
        return InferenceEngine(
            self.prefix, [Bucket(((4, 8),)), Bucket(((16, 8),))], **kw)

    def test_closed_compile_set_under_mixed_traffic(self):
        with self._engine() as eng:
            self.assertEqual(eng.warmup(), 2)  # one executable per bucket
            futs = [eng.submit([np.random.randn(n, 8).astype("float32")])
                    for n in (1, 3, 4, 2, 9, 16, 3, 11)]
            for f in futs:
                f.result(60)
            # mixed request shapes never minted a third executable
            self.assertEqual(eng.compile_count, 2)
            st = eng.stats()
            self.assertEqual(st["completed"], 8)
            self.assertEqual(st["bucket_misses"], 0)

    def test_outputs_match_direct_predictor_and_unpad(self):
        with self._engine() as eng:
            x = np.random.randn(3, 8).astype("float32")
            got = eng.infer([x], timeout=60)[0]
            want = np.asarray(self.net(x[None]))[0]
            self.assertEqual(got.shape, (3, 4))  # padding sliced back off
            np.testing.assert_allclose(got, want, atol=1e-5)

    def test_bucket_miss_rejected_or_fallback(self):
        with self._engine() as eng:
            with self.assertRaises(InvalidArgumentError):
                eng.infer([np.zeros((20, 8), np.float32)], timeout=60)
            self.assertEqual(eng.stats()["bucket_misses"], 1)
        with self._engine(allow_bucket_fallback=True) as eng:
            x = np.random.randn(20, 8).astype("float32")
            got = eng.infer([x], timeout=60)[0]
            np.testing.assert_allclose(
                got, np.asarray(self.net(x[None]))[0], atol=1e-5)
            st = eng.stats()
            self.assertEqual(st["bucket_misses"], 1)
            self.assertEqual(st["fallback_runs"], 1)

    def test_hot_weight_swap_zero_recompiles(self):
        prefix2, net2 = _export_tiny(self.tmp.name, "m2", seed=5678)
        with self._engine() as eng:
            eng.warmup()
            x = np.random.randn(3, 8).astype("float32")
            before = eng.infer([x], timeout=60)[0]
            eng.swap_weights(prefix2 + ".pdiparams")
            after = eng.infer([x], timeout=60)[0]
            self.assertEqual(eng.compile_count, 2)  # swap compiled nothing
            np.testing.assert_allclose(
                after, np.asarray(net2(x[None]))[0], atol=1e-5)
            self.assertFalse(np.allclose(after, before, atol=1e-5))

    def test_swap_rejects_mismatched_state(self):
        bad = os.path.join(self.tmp.name, "bad.pdiparams")
        other = nn.Linear(3, 3)
        pt.save({"params": other.param_pytree(),
                 "buffers": other.buffer_pytree()}, bad)
        with self._engine() as eng:
            with self.assertRaises(InvalidArgumentError):
                eng.swap_weights(bad)

    def test_metrics_published_on_bus(self):
        with RetraceMonitor(budget=8) as mon, self._engine() as eng:
            eng.infer([np.zeros((2, 8), np.float32)], timeout=60)
            stats = mon.serving_stats(eng.name)
            self.assertEqual(stats["completed"], 1)
            self.assertGreater(stats["p50_ms"], 0.0)
            self.assertIn("batch_occupancy", stats)

    def test_s601_bucket_miss_churn(self):
        with RetraceMonitor(budget=2) as mon, self._engine() as eng:
            for _ in range(4):  # 4 misses > budget 2
                with self.assertRaises(InvalidArgumentError):
                    eng.infer([np.zeros((99, 8), np.float32)], timeout=60)
            diags = mon.diagnostics()
        s601 = [d for d in diags if d.rule == "S601"]
        self.assertEqual(len(s601), 1)
        self.assertIn("4 bucket misses", s601[0].message)
        # under budget: silent
        with RetraceMonitor(budget=8) as mon, self._engine() as eng:
            with self.assertRaises(InvalidArgumentError):
                eng.infer([np.zeros((99, 8), np.float32)], timeout=60)
            self.assertEqual([d for d in mon.diagnostics()
                              if d.rule == "S601"], [])


class TestGenerationEngine(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        pt.seed(4321)
        cls.cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_position=64, dropout=0.0)
        cls.model = GPTForCausalLM(cls.cfg)
        cls.model.eval()

    def _ref_greedy(self, prompt, n, eos=None):
        import jax.numpy as jnp
        ids, outs = list(map(int, prompt)), []
        for _ in range(n):
            logits = np.asarray(self.model(jnp.asarray([ids], jnp.int32)))[0]
            nxt = int(np.argmax(logits[-1]))
            outs.append(nxt)
            ids.append(nxt)
            if eos is not None and nxt == eos:
                break
        return outs

    def test_token_identical_and_closed_compile_set(self):
        # continuous=False pins the legacy run-batch-to-completion path;
        # the continuous scheduler has its own suite
        # (test_continuous_batching.py)
        with GenerationEngine(self.model, prompt_buckets=[8, 16],
                              batch_size=2, max_queue_delay_ms=2.0,
                              continuous=False) as eng:
            self.assertEqual(eng.warmup(), 3)  # 2 prefill buckets + 1 decode
            prompts = [np.arange(5) % 97, (np.arange(7) * 3) % 97,
                       (np.arange(11) * 5 + 2) % 97]
            futs = [eng.submit(p, max_new_tokens=5) for p in prompts]
            gens = [f.result(120) for f in futs]
            for p, g in zip(prompts, gens):
                self.assertEqual(g.tolist(), self._ref_greedy(p, 5))
            # ragged prompts + many decode steps never reopened the set
            self.assertEqual(eng.compile_count, 3)
            st = eng.stats()
            self.assertEqual(st["tokens"], 15)
            self.assertGreater(st["tokens_per_s"], 0.0)

    def test_eos_stops_early(self):
        probe = self._ref_greedy(np.arange(4) % 97, 8)
        eos = probe[1]  # stop at this token's FIRST occurrence
        expect = probe[: probe.index(eos) + 1]
        self.assertLess(len(expect), 8)
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=1,
                              max_queue_delay_ms=1.0, continuous=False,
                              eos_token_id=eos) as eng:
            gen = eng.generate(np.arange(4) % 97, max_new_tokens=8,
                               timeout=120)
            self.assertEqual(gen.tolist(), expect)
            self.assertEqual(gen[-1], eos)

    def test_prompt_over_largest_bucket_is_a_miss(self):
        with GenerationEngine(self.model, prompt_buckets=[8],
                              batch_size=1) as eng:
            with self.assertRaises(InvalidArgumentError):
                eng.submit(np.zeros(9, np.int32))
            self.assertEqual(eng.stats()["bucket_misses"], 1)


if __name__ == "__main__":
    unittest.main()

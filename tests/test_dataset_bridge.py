"""paddle.dataset 1.x reader creators + paddle.batch.

Reference capability: python/paddle/dataset/ (module-level train()/test()
reader creators) and python/paddle/batch.py:18 — here thin bridges over
the class datasets, composable with paddle.reader decorators.
"""
import gzip
import os
import struct

import numpy as np
import pytest

import paddle_tpu as paddle


def _write_idx(tmpdir, n=16):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 255, (n, 28, 28), dtype=np.uint8)
    labels = rng.randint(0, 10, (n,)).astype(np.uint8)
    img_path = os.path.join(tmpdir, "imgs.gz")
    lbl_path = os.path.join(tmpdir, "lbls.gz")
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path, labels


class TestDatasetBridge:
    def test_mnist_reader_1x_format(self, tmp_path):
        img, lbl, labels = _write_idx(str(tmp_path))
        r = paddle.dataset.mnist.train(image_file=img, label_file=lbl)
        samples = list(r())
        assert len(samples) == 16
        x, y = samples[3]
        # documented 1.x format: flattened pixels in [-1, 1], int label
        assert x.shape == (784,) and x.dtype == np.float32
        assert -1.0 <= x.min() and x.max() <= 1.0
        assert y == int(labels[3])

    def test_uci_housing_reader(self, tmp_path):
        rng = np.random.RandomState(0)
        table = np.concatenate(
            [rng.rand(50, 13), rng.rand(50, 1)], axis=1)
        p = os.path.join(tmp_path, "housing.data")
        np.savetxt(p, table)
        r = paddle.dataset.uci_housing.train(data_file=p)
        x, y = next(iter(r()))
        assert x.shape == (13,) and y.shape == (1,)

    def test_composes_with_reader_decorators_and_batch(self, tmp_path):
        img, lbl, _ = _write_idx(str(tmp_path))
        r = paddle.dataset.mnist.train(image_file=img, label_file=lbl)
        pipe = paddle.batch(
            paddle.reader.shuffle(r, buf_size=8), batch_size=4,
            drop_last=True)
        batches = list(pipe())
        assert len(batches) == 4
        assert len(batches[0]) == 4
        assert batches[0][0][0].shape == (784,)

    def test_batch_drop_last(self):
        r = lambda: iter(range(10))
        assert len(list(paddle.batch(r, 4)())) == 3
        assert len(list(paddle.batch(r, 4, drop_last=True)())) == 2

    def test_batch_validates_size(self):
        from paddle_tpu.framework.errors import InvalidArgumentError

        with pytest.raises(InvalidArgumentError, match="positive"):
            paddle.batch(lambda: iter(range(4)), 0)
        with pytest.raises(InvalidArgumentError, match="positive"):
            paddle.batch(lambda: iter(range(4)), -2)

    def test_dataset_cached_across_epochs(self, tmp_path, monkeypatch):
        """reader() per epoch must not reconstruct the Dataset (vocab/
        archive rescans)."""
        img, lbl, _ = _write_idx(str(tmp_path))
        import paddle_tpu.vision.datasets as V

        calls = []
        orig = V.MNIST.__init__

        def counting(self, *a, **k):
            calls.append(1)
            return orig(self, *a, **k)

        monkeypatch.setattr(V.MNIST, "__init__", counting)
        r = paddle.dataset.mnist.train(image_file=img, label_file=lbl)
        list(r())
        list(r())
        assert len(calls) == 1

    def test_imdb_word_idx_checked(self, tmp_path):
        import io
        import tarfile

        from paddle_tpu.framework.errors import InvalidArgumentError

        p = os.path.join(tmp_path, "aclImdb_v1.tar.gz")
        docs = {"aclImdb/train/pos/0.txt": b"a great movie",
                "aclImdb/train/neg/0.txt": b"a bad movie"}
        with tarfile.open(p, "w:gz") as t:
            for name, data in docs.items():
                info = tarfile.TarInfo(name)
                info.size = len(data)
                t.addfile(info, io.BytesIO(data))

        # the documented pattern: dict from word_dict() matches
        d = paddle.dataset.imdb.word_dict(data_file=p, cutoff=0)
        r = paddle.dataset.imdb.train(word_idx=d, data_file=p, cutoff=0)
        assert len(list(r())) == 2
        # a custom dict must fail loudly, not silently re-encode
        bad = {"a": 0, "great": 1}
        r2 = paddle.dataset.imdb.train(word_idx=bad, data_file=p, cutoff=0)
        with pytest.raises(InvalidArgumentError, match="word_idx"):
            next(iter(r2()))

    def test_fetch_raises_actionable(self):
        with pytest.raises(RuntimeError, match="egress"):
            paddle.dataset.mnist.fetch()

    def test_lazy_construction(self, tmp_path):
        """train() must be cheap — the dataset opens at iteration, so a
        missing file errors on reader(), not on creator construction."""
        from paddle_tpu.framework.errors import NotFoundError

        r = paddle.dataset.uci_housing.train(
            data_file=os.path.join(tmp_path, "nope.data"))
        with pytest.raises(NotFoundError):
            next(iter(r()))

    def test_all_modules_importable(self):
        for m in ["mnist", "cifar", "uci_housing", "imdb", "imikolov",
                  "movielens", "conll05", "flowers", "voc2012", "wmt14",
                  "wmt16"]:
            assert hasattr(paddle.dataset, m)

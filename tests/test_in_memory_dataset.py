"""Native ingest engine (C++ InMemoryDataset).

Reference capability: framework/data_set.h:157 (InMemoryDataset — file-
sharded multithreaded load, global shuffle) + data_feed.h:302
(InMemoryDataFeed batch assembly).  Oracle: numpy parsing of the same
files.  Also exercises the end-to-end CTR path: native ingest feeding
WideDeep training.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.errors import InvalidArgumentError, NotFoundError
from paddle_tpu.io import InMemoryDataset


def _write_parts(tmp_path, n_files=4, rows_per_file=25, ncols=6, seed=0):
    rng = np.random.RandomState(seed)
    files, all_rows = [], []
    for i in range(n_files):
        rows = np.round(rng.randn(rows_per_file, ncols) * 100, 3)
        rows[:, -1] = rng.randint(0, 2, rows_per_file)  # int label col
        p = os.path.join(tmp_path, f"part-{i}.txt")
        sep = "," if i % 2 else " "  # both separators are valid
        with open(p, "w") as f:
            for r in rows:
                f.write(sep.join(repr(float(v)) for v in r) + "\n")
        files.append(p)
        all_rows.append(rows)
    return files, np.concatenate(all_rows)


def _dataset():
    return InMemoryDataset(slots=[("feat", 5, "float32"),
                                  ("label", 1, "int64")])


class TestLoad:
    def test_load_matches_numpy_oracle(self, tmp_path):
        files, oracle = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        n = ds.load_into_memory(thread_num=3)
        assert n == 100 and len(ds) == 100
        batches = list(ds.batch_iter(batch_size=100))
        assert len(batches) == 1
        feat, label = batches[0]
        assert feat.dtype == np.float32 and label.dtype == np.int64
        got = np.concatenate([feat.astype(np.float64),
                              label.astype(np.float64)], axis=1)
        # unshuffled load preserves within-thread file order but thread
        # merge order is deterministic round-robin → compare as sorted sets
        np.testing.assert_allclose(
            np.sort(got, axis=0), np.sort(oracle, axis=0), rtol=1e-6)

    def test_multithreaded_equals_single(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        a, b = _dataset(), _dataset()
        a.set_filelist(files)
        a.load_into_memory(thread_num=1)
        b.set_filelist(files)
        b.load_into_memory(thread_num=4)
        ga = np.concatenate(
            [np.concatenate(t, axis=None) for t in a.batch_iter(1000)])
        gb = np.concatenate(
            [np.concatenate(t, axis=None) for t in b.batch_iter(1000)])
        np.testing.assert_allclose(np.sort(ga), np.sort(gb))

    def test_incremental_load_appends(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files[:2])
        assert ds.load_into_memory() == 50
        ds.set_filelist(files[2:])
        assert ds.load_into_memory() == 50
        assert len(ds) == 100

    def test_missing_file_error(self, tmp_path):
        ds = _dataset()
        ds.set_filelist([os.path.join(tmp_path, "nope.txt")])
        with pytest.raises(NotFoundError, match="cannot open"):
            ds.load_into_memory()

    def test_bad_column_count_names_line(self, tmp_path):
        p = os.path.join(tmp_path, "bad.txt")
        with open(p, "w") as f:
            f.write("1 2 3 4 5 6\n1 2 3\n")
        ds = _dataset()
        ds.set_filelist([p])
        with pytest.raises(InvalidArgumentError, match="bad.txt:2"):
            ds.load_into_memory()

    def test_unparsable_field_error(self, tmp_path):
        p = os.path.join(tmp_path, "junk.txt")
        with open(p, "w") as f:
            f.write("1 2 three 4 5 6\n")
        ds = _dataset()
        ds.set_filelist([p])
        with pytest.raises(InvalidArgumentError, match="unparsable"):
            ds.load_into_memory()

    def test_release_memory(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.release_memory()
        assert len(ds) == 0


class TestShuffleAndBatch:
    def test_global_shuffle_deterministic_and_complete(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        before = [t[0].copy() for t in ds.batch_iter(100)][0]
        ds.global_shuffle(seed=7)
        s1 = [t[0].copy() for t in ds.batch_iter(100)][0]
        ds.global_shuffle(seed=7)
        s2 = [t[0].copy() for t in ds.batch_iter(100)][0]
        np.testing.assert_array_equal(s1, s2)  # same seed → same order
        assert not np.array_equal(s1, before)  # actually shuffled
        np.testing.assert_allclose(np.sort(s1, axis=0),
                                   np.sort(before, axis=0))  # same multiset

    def test_batch_shapes_and_drop_last(self, tmp_path):
        files, _ = _write_parts(tmp_path)  # 100 samples
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        sizes = [t[0].shape[0] for t in ds.batch_iter(32)]
        assert sizes == [32, 32, 32, 4]
        sizes = [t[0].shape[0] for t in ds.batch_iter(32, drop_last=True)]
        assert sizes == [32, 32, 32]

    def test_epoch_restarts(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        assert sum(1 for _ in ds.batch_iter(10)) == 10
        assert sum(1 for _ in ds.batch_iter(10)) == 10  # restartable

    def test_sample_iteration_refused(self, tmp_path):
        ds = _dataset()
        with pytest.raises(InvalidArgumentError, match="batch_iter"):
            iter(ds)


class TestEndToEnd:
    def test_ctr_training_from_native_ingest(self, tmp_path):
        """The reference's train_from_dataset capability: CTR files →
        native ingest → Wide&Deep training, loss decreases."""
        from paddle_tpu import optimizer as popt
        from paddle_tpu.models import wide_deep_tiny

        rng = np.random.RandomState(0)
        files = []
        for i in range(2):
            p = os.path.join(tmp_path, f"ctr-{i}.txt")
            with open(p, "w") as f:
                for _ in range(128):
                    ids = rng.randint(0, 64, size=4)
                    dense = np.round(rng.randn(4), 4)
                    label = int(ids[0] < 32)
                    f.write(" ".join(map(str, list(ids) + list(dense)
                                         + [label])) + "\n")
            files.append(p)

        ds = InMemoryDataset(slots=[("sparse", 4, "int32"),
                                    ("dense", 4, "float32"),
                                    ("label", 1, "float32")])
        ds.set_filelist(files)
        assert ds.load_into_memory(thread_num=2) == 256
        ds.global_shuffle(seed=1)

        paddle.seed(0)
        net = wide_deep_tiny()
        model = paddle.Model(net, inputs=["sparse", "dense"],
                             labels=["label"])
        model.prepare(optimizer=popt.Adam(learning_rate=1e-2),
                      loss=net.loss)
        losses = []
        for _ in range(8):
            for sparse, dense, label in ds.batch_iter(64):
                loss, _ = model.train_batch([sparse, dense], [label])
                losses.append(loss)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, losses


class TestReviewRegressions:
    def test_unterminated_final_line_buffer_multiple(self, tmp_path):
        """A final line with no newline whose length is an exact multiple
        of the 64KiB read buffer must not be dropped."""
        p = os.path.join(tmp_path, "edge.txt")
        ncols = 6
        first = " ".join(["1.0"] * ncols) + "\n"
        # craft a last line of exactly 2*(65535) bytes, 6 numeric fields
        target = 2 * 65535
        fields = ["2.0"] * (ncols - 1)
        base = " ".join(fields) + " "
        pad_len = target - len(base)
        last = base + "3." + "0" * (pad_len - 2)
        assert len(last) == target
        with open(p, "w") as f:
            f.write(first)
            f.write(last)  # NO trailing newline
        ds = _dataset()
        ds.set_filelist([p])
        assert ds.load_into_memory() == 2

    def test_error_message_not_stale(self, tmp_path):
        """A failed load must not shadow the NEXT failure's message."""
        ds = _dataset()
        ds.set_filelist([os.path.join(tmp_path, "missing.txt")])
        with pytest.raises(NotFoundError, match="cannot open"):
            ds.load_into_memory()
        bad = os.path.join(tmp_path, "bad.txt")
        with open(bad, "w") as f:
            f.write("1 2 3\n")
        ds.set_filelist([bad])
        with pytest.raises(InvalidArgumentError, match="bad.txt:1"):
            ds.load_into_memory()

    def test_concurrent_iterators_independent(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        it1 = ds.batch_iter(10)
        it2 = ds.batch_iter(10)
        a1 = next(it1)[0]
        b1 = next(it2)[0]
        a2 = next(it1)[0]
        np.testing.assert_array_equal(a1, b1)  # both start at position 0
        assert not np.array_equal(a1, a2)
        assert sum(1 for _ in it1) == 8  # it1 continues its own epoch

    def test_batch_iter_validates_eagerly(self, tmp_path):
        ds = _dataset()
        with pytest.raises(InvalidArgumentError, match="batch_size"):
            ds.batch_iter(0)  # raises at call, not at first next()

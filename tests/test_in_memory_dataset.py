"""Native ingest engine (C++ InMemoryDataset).

Reference capability: framework/data_set.h:157 (InMemoryDataset — file-
sharded multithreaded load, global shuffle) + data_feed.h:302
(InMemoryDataFeed batch assembly).  Oracle: numpy parsing of the same
files.  Also exercises the end-to-end CTR path: native ingest feeding
WideDeep training.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.errors import InvalidArgumentError, NotFoundError
from paddle_tpu.io import InMemoryDataset


def _write_parts(tmp_path, n_files=4, rows_per_file=25, ncols=6, seed=0):
    rng = np.random.RandomState(seed)
    files, all_rows = [], []
    for i in range(n_files):
        rows = np.round(rng.randn(rows_per_file, ncols) * 100, 3)
        rows[:, -1] = rng.randint(0, 2, rows_per_file)  # int label col
        p = os.path.join(tmp_path, f"part-{i}.txt")
        sep = "," if i % 2 else " "  # both separators are valid
        with open(p, "w") as f:
            for r in rows:
                f.write(sep.join(repr(float(v)) for v in r) + "\n")
        files.append(p)
        all_rows.append(rows)
    return files, np.concatenate(all_rows)


def _dataset():
    return InMemoryDataset(slots=[("feat", 5, "float32"),
                                  ("label", 1, "int64")])


class TestLoad:
    def test_load_matches_numpy_oracle(self, tmp_path):
        files, oracle = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        n = ds.load_into_memory(thread_num=3)
        assert n == 100 and len(ds) == 100
        batches = list(ds.batch_iter(batch_size=100))
        assert len(batches) == 1
        feat, label = batches[0]
        assert feat.dtype == np.float32 and label.dtype == np.int64
        got = np.concatenate([feat.astype(np.float64),
                              label.astype(np.float64)], axis=1)
        # unshuffled load preserves within-thread file order but thread
        # merge order is deterministic round-robin → compare as sorted sets
        np.testing.assert_allclose(
            np.sort(got, axis=0), np.sort(oracle, axis=0), rtol=1e-6)

    def test_multithreaded_equals_single(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        a, b = _dataset(), _dataset()
        a.set_filelist(files)
        a.load_into_memory(thread_num=1)
        b.set_filelist(files)
        b.load_into_memory(thread_num=4)
        ga = np.concatenate(
            [np.concatenate(t, axis=None) for t in a.batch_iter(1000)])
        gb = np.concatenate(
            [np.concatenate(t, axis=None) for t in b.batch_iter(1000)])
        np.testing.assert_allclose(np.sort(ga), np.sort(gb))

    def test_incremental_load_appends(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files[:2])
        assert ds.load_into_memory() == 50
        ds.set_filelist(files[2:])
        assert ds.load_into_memory() == 50
        assert len(ds) == 100

    def test_missing_file_error(self, tmp_path):
        ds = _dataset()
        ds.set_filelist([os.path.join(tmp_path, "nope.txt")])
        with pytest.raises(NotFoundError, match="cannot open"):
            ds.load_into_memory()

    def test_bad_column_count_names_line(self, tmp_path):
        p = os.path.join(tmp_path, "bad.txt")
        with open(p, "w") as f:
            f.write("1 2 3 4 5 6\n1 2 3\n")
        ds = _dataset()
        ds.set_filelist([p])
        with pytest.raises(InvalidArgumentError, match="bad.txt:2"):
            ds.load_into_memory()

    def test_unparsable_field_error(self, tmp_path):
        p = os.path.join(tmp_path, "junk.txt")
        with open(p, "w") as f:
            f.write("1 2 three 4 5 6\n")
        ds = _dataset()
        ds.set_filelist([p])
        with pytest.raises(InvalidArgumentError, match="unparsable"):
            ds.load_into_memory()

    def test_release_memory(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.release_memory()
        assert len(ds) == 0


class TestShuffleAndBatch:
    def test_global_shuffle_deterministic_and_complete(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        before = [t[0].copy() for t in ds.batch_iter(100)][0]
        ds.global_shuffle(seed=7)
        s1 = [t[0].copy() for t in ds.batch_iter(100)][0]
        ds.global_shuffle(seed=7)
        s2 = [t[0].copy() for t in ds.batch_iter(100)][0]
        np.testing.assert_array_equal(s1, s2)  # same seed → same order
        assert not np.array_equal(s1, before)  # actually shuffled
        np.testing.assert_allclose(np.sort(s1, axis=0),
                                   np.sort(before, axis=0))  # same multiset

    def test_batch_shapes_and_drop_last(self, tmp_path):
        files, _ = _write_parts(tmp_path)  # 100 samples
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        sizes = [t[0].shape[0] for t in ds.batch_iter(32)]
        assert sizes == [32, 32, 32, 4]
        sizes = [t[0].shape[0] for t in ds.batch_iter(32, drop_last=True)]
        assert sizes == [32, 32, 32]

    def test_epoch_restarts(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        assert sum(1 for _ in ds.batch_iter(10)) == 10
        assert sum(1 for _ in ds.batch_iter(10)) == 10  # restartable

    def test_sample_iteration_refused(self, tmp_path):
        ds = _dataset()
        with pytest.raises(InvalidArgumentError, match="batch_iter"):
            iter(ds)


class TestEndToEnd:
    def test_ctr_training_from_native_ingest(self, tmp_path):
        """The reference's train_from_dataset capability: CTR files →
        native ingest → Wide&Deep training, loss decreases."""
        from paddle_tpu import optimizer as popt
        from paddle_tpu.models import wide_deep_tiny

        rng = np.random.RandomState(0)
        files = []
        for i in range(2):
            p = os.path.join(tmp_path, f"ctr-{i}.txt")
            with open(p, "w") as f:
                for _ in range(128):
                    ids = rng.randint(0, 64, size=4)
                    dense = np.round(rng.randn(4), 4)
                    label = int(ids[0] < 32)
                    f.write(" ".join(map(str, list(ids) + list(dense)
                                         + [label])) + "\n")
            files.append(p)

        ds = InMemoryDataset(slots=[("sparse", 4, "int32"),
                                    ("dense", 4, "float32"),
                                    ("label", 1, "float32")])
        ds.set_filelist(files)
        assert ds.load_into_memory(thread_num=2) == 256
        ds.global_shuffle(seed=1)

        paddle.seed(0)
        net = wide_deep_tiny()
        model = paddle.Model(net, inputs=["sparse", "dense"],
                             labels=["label"])
        model.prepare(optimizer=popt.Adam(learning_rate=1e-2),
                      loss=net.loss)
        losses = []
        for _ in range(8):
            for sparse, dense, label in ds.batch_iter(64):
                loss, _ = model.train_batch([sparse, dense], [label])
                losses.append(loss)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, losses


class TestReviewRegressions:
    def test_unterminated_final_line_buffer_multiple(self, tmp_path):
        """A final line with no newline whose length is an exact multiple
        of the 64KiB read buffer must not be dropped."""
        p = os.path.join(tmp_path, "edge.txt")
        ncols = 6
        first = " ".join(["1.0"] * ncols) + "\n"
        # craft a last line of exactly 2*(65535) bytes, 6 numeric fields
        target = 2 * 65535
        fields = ["2.0"] * (ncols - 1)
        base = " ".join(fields) + " "
        pad_len = target - len(base)
        last = base + "3." + "0" * (pad_len - 2)
        assert len(last) == target
        with open(p, "w") as f:
            f.write(first)
            f.write(last)  # NO trailing newline
        ds = _dataset()
        ds.set_filelist([p])
        assert ds.load_into_memory() == 2

    def test_error_message_not_stale(self, tmp_path):
        """A failed load must not shadow the NEXT failure's message."""
        ds = _dataset()
        ds.set_filelist([os.path.join(tmp_path, "missing.txt")])
        with pytest.raises(NotFoundError, match="cannot open"):
            ds.load_into_memory()
        bad = os.path.join(tmp_path, "bad.txt")
        with open(bad, "w") as f:
            f.write("1 2 3\n")
        ds.set_filelist([bad])
        with pytest.raises(InvalidArgumentError, match="bad.txt:1"):
            ds.load_into_memory()

    def test_concurrent_iterators_independent(self, tmp_path):
        files, _ = _write_parts(tmp_path)
        ds = _dataset()
        ds.set_filelist(files)
        ds.load_into_memory()
        it1 = ds.batch_iter(10)
        it2 = ds.batch_iter(10)
        a1 = next(it1)[0]
        b1 = next(it2)[0]
        a2 = next(it1)[0]
        np.testing.assert_array_equal(a1, b1)  # both start at position 0
        assert not np.array_equal(a1, a2)
        assert sum(1 for _ in it1) == 8  # it1 continues its own epoch

    def test_batch_iter_validates_eagerly(self, tmp_path):
        ds = _dataset()
        with pytest.raises(InvalidArgumentError, match="batch_size"):
            ds.batch_iter(0)  # raises at call, not at first next()


class TestMultiSlotDataset:
    """Typed MultiSlot ingest (ref: data_feed.h:302 MultiSlotDataFeed) —
    the `<count> v...` per-slot line format DataGenerator emits."""

    def _write(self, tmp_path, lines, name="part.txt"):
        p = tmp_path / name
        p.write_text("\n".join(lines) + "\n")
        return str(p)

    def _ds(self):
        from paddle_tpu.io import MultiSlotInMemoryDataset

        return MultiSlotInMemoryDataset(
            slots=[("ids", "int64", 4),      # variable-length sparse ids
                   ("dense", "float32", 3),  # fixed dense features
                   ("label", "int64", 1)])

    def test_parse_types_padding_lengths(self, tmp_path):
        ds = self._ds()
        f = self._write(tmp_path, [
            "2 11 22 3 0.5 1.5 2.5 1 1",
            "4 1 2 3 4 3 9.0 8.0 7.0 1 0",
            "0 3 1.0 2.0 3.0 1 1",          # empty ids slot
        ])
        ds.set_filelist([f])
        assert ds.load_into_memory(thread_num=2) == 3
        batches = list(ds.batch_iter(batch_size=3, return_lens=True))
        assert len(batches) == 1
        (ids, id_lens), (dense, _), (label, _) = batches[0]
        assert ids.dtype == np.int64 and dense.dtype == np.float32
        np.testing.assert_array_equal(id_lens, [2, 4, 0])
        np.testing.assert_array_equal(ids[0], [11, 22, 0, 0])  # zero pad
        np.testing.assert_array_equal(ids[1], [1, 2, 3, 4])
        np.testing.assert_allclose(dense[1], [9.0, 8.0, 7.0])
        np.testing.assert_array_equal(label.ravel(), [1, 0, 1])

    def test_int64_ids_exact_at_full_width(self, tmp_path):
        # the dense f64 store rounds ids past 2^53; the typed store must not
        big = 2 ** 62 + 12345
        ds = self._ds()
        f = self._write(tmp_path, [f"1 {big} 3 0 0 0 1 7"])
        ds.set_filelist([f])
        ds.load_into_memory()
        ids, _, _ = next(iter(ds.batch_iter(1)))
        assert int(ids[0, 0]) == big

    def test_shuffle_and_multifile(self, tmp_path):
        files = []
        for k in range(4):
            files.append(self._write(
                tmp_path,
                [f"1 {k * 10 + i} 3 0 0 0 1 0" for i in range(10)],
                name=f"part-{k}.txt"))
        ds = self._ds()
        ds.set_filelist(files)
        assert ds.load_into_memory(thread_num=4) == 40
        ds.global_shuffle(seed=7)
        rows = []
        for ids, dense, label in ds.batch_iter(8):
            rows.extend(int(v) for v in ids[:, 0])
        assert sorted(rows) == sorted(
            k * 10 + i for k in range(4) for i in range(10))
        assert rows != sorted(rows)  # actually shuffled

    def test_overlong_slot_rejected(self, tmp_path):
        ds = self._ds()
        f = self._write(tmp_path, ["9 1 2 3 4 5 6 7 8 9 3 0 0 0 1 0"])
        ds.set_filelist([f])
        with pytest.raises(Exception, match="outside"):
            ds.load_into_memory()

    def test_int64_overflow_rejected(self, tmp_path):
        # 2^64+1 must be rejected, not silently wrap to 1
        ds = self._ds()
        f = self._write(tmp_path,
                        ["1 18446744073709551617 3 0 0 0 1 0"])
        ds.set_filelist([f])
        with pytest.raises(Exception, match="unparsable"):
            ds.load_into_memory()

    def test_malformed_line_rejected(self, tmp_path):
        ds = self._ds()
        f = self._write(tmp_path, ["2 1 x 3 0 0 0 1 0"])
        ds.set_filelist([f])
        with pytest.raises(Exception, match="unparsable"):
            ds.load_into_memory()

    def test_data_generator_roundtrip(self, tmp_path):
        # the fleet DataGenerator's MultiSlot output parses natively
        from paddle_tpu.distributed.fleet.data_generator import (
            MultiSlotDataGenerator)

        class Gen(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def reader():
                    k = int(line)
                    yield [("ids", [k, k + 1]), ("dense", [0.5, 1.5, 2.5]),
                           ("label", [k % 2])]
                return reader

        import io as _io

        out_path = tmp_path / "gen.txt"
        buf = _io.StringIO()
        Gen().run_from_stdin(source=_io.StringIO("3\n8\n"), out=buf)
        out_path.write_text(buf.getvalue())
        from paddle_tpu.io import MultiSlotInMemoryDataset

        ds = MultiSlotInMemoryDataset(
            slots=[("ids", "int64", 4), ("dense", "float32", 3),
                   ("label", "int64", 1)])
        ds.set_filelist([str(out_path)])
        assert ds.load_into_memory() == 2
        (ids, lens), (dense, _), _ = next(
            iter(ds.batch_iter(2, return_lens=True)))
        np.testing.assert_array_equal(lens, [2, 2])
        np.testing.assert_array_equal(ids[0, :2], [3, 4])
        np.testing.assert_allclose(dense[0], [0.5, 1.5, 2.5], rtol=1e-6)

    def test_native_beats_python_loader(self, tmp_path):
        # the reason this engine is C++ (data_feed.h): parse throughput.
        # Modest margin here to stay robust on shared CI; see
        # tools/bench_ingest.py for the real (>=5x) numbers.
        import time

        rng = np.random.RandomState(0)
        files = []
        for k in range(4):
            lines = []
            for _ in range(4000):
                ids = rng.randint(0, 10 ** 9, size=3)
                dense = rng.rand(3)
                lines.append(
                    f"3 {ids[0]} {ids[1]} {ids[2]} "
                    f"3 {dense[0]:.6f} {dense[1]:.6f} {dense[2]:.6f} "
                    f"1 {k % 2}")
            files.append(self._write(tmp_path, lines, name=f"b{k}.txt"))

        from paddle_tpu.io import MultiSlotInMemoryDataset

        ds = MultiSlotInMemoryDataset(
            slots=[("ids", "int64", 3), ("dense", "float32", 3),
                   ("label", "int64", 1)])
        ds.set_filelist(files)
        t0 = time.perf_counter()
        n = ds.load_into_memory(thread_num=4)
        t_native = time.perf_counter() - t0
        assert n == 16000

        def python_loader(paths):
            out = []
            for p in paths:
                with open(p) as f:
                    for line in f:
                        toks = line.split()
                        row, i = [], 0
                        while i < len(toks):
                            c = int(toks[i])
                            row.append(toks[i + 1:i + 1 + c])
                            i += 1 + c
                        out.append(row)
            return out

        t0 = time.perf_counter()
        ref = python_loader(files)
        t_python = time.perf_counter() - t0
        assert len(ref) == 16000
        assert t_native < t_python, (t_native, t_python)

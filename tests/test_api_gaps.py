"""API-parity additions: weight_norm, legacy layers, chunk_eval/mean_iou,
clip fns, aliases (round-2 namespace audit closure)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import metric as M
from paddle_tpu.nn import functional_call


class TestWeightNorm:
    def test_apply_preserves_forward(self):
        paddle.seed(0)
        lin = nn.Linear(6, 4)
        x = jnp.asarray(np.random.RandomState(0).randn(3, 6), jnp.float32)
        before = np.asarray(lin(x))
        nn.weight_norm(lin, "weight", dim=0)
        after = np.asarray(lin(x))
        np.testing.assert_allclose(after, before, atol=1e-5)
        names = dict(lin.named_parameters())
        assert "weight_g" in names and "weight_v" in names
        assert not names["weight"].trainable

    def test_grads_flow_to_g_and_v(self):
        paddle.seed(1)
        lin = nn.Linear(5, 3)
        nn.weight_norm(lin, "weight", dim=0)
        x = jnp.ones((2, 5), jnp.float32)
        params = lin.param_pytree(trainable_only=True)
        assert set(params) == {"weight_g", "weight_v", "bias"}

        def loss(p):
            return jnp.sum(functional_call(lin, p, x) ** 2)

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["weight_g"]).sum()) > 0
        assert float(jnp.abs(g["weight_v"]).sum()) > 0

    def test_no_tracer_leak_after_jit(self):
        paddle.seed(2)
        lin = nn.Linear(4, 4)
        nn.weight_norm(lin)
        x = jnp.ones((2, 4), jnp.float32)
        params = lin.param_pytree(trainable_only=True)
        jax.jit(lambda p, x: functional_call(lin, p, x))(params, x)
        # every box must hold a concrete array after the traced call
        for _, p in lin.named_parameters():
            np.asarray(p.value)

    def test_remove_restores_single_param(self):
        paddle.seed(3)
        lin = nn.Linear(4, 2)
        x = jnp.asarray(np.random.RandomState(1).randn(2, 4), jnp.float32)
        nn.weight_norm(lin, dim=1)
        mid = np.asarray(lin(x))
        nn.remove_weight_norm(lin)
        names = dict(lin.named_parameters())
        assert "weight_g" not in names and names["weight"].trainable
        np.testing.assert_allclose(np.asarray(lin(x)), mid, atol=1e-5)

    def test_dim_none_scalar_g(self):
        lin = nn.Linear(4, 2)
        nn.weight_norm(lin, dim=None)
        assert dict(lin.named_parameters())["weight_g"].shape == ()


class TestLegacyLayers:
    def test_pool2d_max_avg_global(self):
        x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 8, 8),
                        jnp.float32)
        out = nn.Pool2D(pool_size=2, pool_type="max", pool_stride=2)(x)
        assert out.shape == (2, 3, 4, 4)
        out = nn.Pool2D(pool_size=2, pool_type="avg", pool_stride=2)(x)
        assert out.shape == (2, 3, 4, 4)
        g = nn.Pool2D(pool_type="avg", global_pooling=True)(x)
        np.testing.assert_allclose(np.asarray(g)[..., 0, 0],
                                   np.asarray(x).mean((2, 3)), atol=1e-6)

    def test_bilinear_tensor_product(self):
        paddle.seed(4)
        layer = nn.BilinearTensorProduct(4, 5, 3, act="sigmoid")
        x = jnp.ones((2, 4), jnp.float32)
        y = jnp.ones((2, 5), jnp.float32)
        out = np.asarray(layer(x, y))
        assert out.shape == (2, 3)
        assert (out > 0).all() and (out < 1).all()  # sigmoid range

    def test_clip_fns(self):
        x = jnp.asarray([-2.0, 0.5, 3.0])
        np.testing.assert_allclose(np.asarray(nn.clip(x, -1.0, 1.0)),
                                   [-1.0, 0.5, 1.0])
        big = jnp.asarray([3.0, 4.0])  # norm 5
        clipped = nn.clip_by_norm(big, 1.0)
        np.testing.assert_allclose(np.asarray(clipped), [0.6, 0.8],
                                   atol=1e-6)
        small = jnp.asarray([0.3, 0.4])  # norm .5 < max_norm → unchanged
        np.testing.assert_allclose(np.asarray(nn.clip_by_norm(small, 1.0)),
                                   [0.3, 0.4], atol=1e-6)


class TestChunkEval:
    def test_iob_ner_example(self):
        """The docstring NER example (fluid/layers/nn.py:1060): IOB, 3
        chunk types; ids: B-ORG=0 I-ORG=1 B-PER=2 I-PER=3 B-LOC=4
        I-LOC=5 O=6."""
        label = [[2, 3, 6, 6, 0, 1, 1, 1, 6, 4]]
        pred = [[2, 3, 6, 6, 0, 1, 6, 1, 6, 4]]  # breaks the ORG chunk
        p, r, f1, ni, nl, nc = M.chunk_eval(pred, label, "IOB", 3)
        # label chunks: PER[0-1] ORG[4-7] LOC[9]; pred: PER[0-1] ORG[4-5]
        # I-ORG[7] LOC[9] → 4 inferred, 2 correct (PER, LOC)
        assert nl == 3 and ni == 4 and nc == 2
        np.testing.assert_allclose(p, 0.5)
        np.testing.assert_allclose(r, 2 / 3, rtol=1e-6)
        np.testing.assert_allclose(f1, 2 * 0.5 * (2 / 3) / (0.5 + 2 / 3),
                                   rtol=1e-6)

    def test_perfect_and_seq_length(self):
        label = np.array([[0, 1, 6, 2, 3, 0, 0, 0]])
        p, r, f1, ni, nl, nc = M.chunk_eval(label, label, "IOB", 3,
                                            seq_length=[5])
        assert p == r == f1 == 1.0
        assert ni == nl == nc == 2  # padding region excluded

    def test_excluded_types(self):
        label = [[2, 3, 0, 1]]  # PER chunk + ORG chunk
        _, _, _, ni, nl, nc = M.chunk_eval(label, label, "IOB", 3,
                                           excluded_chunk_types=[0])
        assert ni == nl == nc == 1  # ORG (type 0) excluded

    @pytest.mark.parametrize("scheme,labels,n", [
        ("IOBES", [[0, 1, 2, 8, 3]], 2),  # B I E O S (2 types, T=4)
        ("plain", [[0, 2, 1, 1, 2]], 2),  # each non-O type-run is a chunk
        ("IOE", [[0, 1, 4, 0, 1]], 2),    # I E O I E (2 types, T=2)
    ])
    def test_schemes(self, scheme, labels, n):
        _, _, _, ni, nl, nc = M.chunk_eval(labels, labels, scheme, 2)
        assert ni == nl == nc == n


class TestMeanIou:
    def test_vs_confusion_oracle(self):
        rng = np.random.RandomState(0)
        pred = rng.randint(0, 5, size=(200,))
        lab = rng.randint(0, 5, size=(200,))
        miou, wrong, correct = M.mean_iou(pred, lab, 5)
        correct_np = np.zeros(5, np.int64)
        wrong_np = np.zeros(5, np.int64)
        for p, l in zip(pred, lab):
            if p == l:
                correct_np[p] += 1
            else:
                wrong_np[p] += 1
                wrong_np[l] += 1
        np.testing.assert_array_equal(np.asarray(correct), correct_np)
        np.testing.assert_array_equal(np.asarray(wrong), wrong_np)
        denom = np.maximum(correct_np + wrong_np, 1)
        valid = (correct_np + wrong_np) > 0
        want = (correct_np / denom).sum() / max(valid.sum(), 1)
        np.testing.assert_allclose(float(miou), want, rtol=1e-6)

    def test_perfect(self):
        lab = np.array([0, 1, 2, 1])
        miou, _, correct = M.mean_iou(lab, lab, 3)
        assert float(miou) == 1.0
        np.testing.assert_array_equal(np.asarray(correct), [1, 2, 1])


class TestAliases:
    def test_metric_metrics_module(self):
        from paddle_tpu.metric import metrics

        assert metrics.Accuracy is M.Accuracy
        with pytest.raises(AttributeError):
            metrics.nope

    def test_tensor_reverse_floor_mod(self):
        x = jnp.asarray([[1, 2], [3, 4]])
        np.testing.assert_array_equal(np.asarray(paddle.reverse(x, [0])),
                                      [[3, 4], [1, 2]])
        np.testing.assert_array_equal(
            np.asarray(paddle.floor_mod(jnp.asarray([7, -7]),
                                        jnp.asarray([3, 3]))), [1, 2])

    def test_misc_top_level(self):
        assert paddle.in_dynamic_mode() and paddle.in_dygraph_mode()
        assert paddle.get_cudnn_version() is None
        paddle.check_import_scipy()
        paddle.monkey_patch_math_varbase()
        s = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(s)
        with pytest.raises(Exception):
            paddle.grad(None, None)

    def test_get_worker_info_main_process(self):
        from paddle_tpu.io import get_worker_info

        assert get_worker_info() is None

    def test_nn_functional_assign(self):
        from paddle_tpu.nn import functional as F

        np.testing.assert_array_equal(
            np.asarray(F.assign(np.array([1.0, 2.0]))), [1.0, 2.0])

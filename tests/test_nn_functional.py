"""nn.functional correctness vs numpy oracles + gradient checks."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn.functional as F

from grad_check import check_grad


def check(actual, expected, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(actual), expected, rtol=rtol, atol=atol)


class TestActivations:
    def setup_method(self):
        self.x = np.random.RandomState(0).randn(3, 5).astype(np.float32)

    def test_basic(self):
        x = pt.to_tensor(self.x)
        check(F.relu(x), np.maximum(self.x, 0))
        check(F.relu6(x), np.clip(self.x, 0, 6))
        check(F.leaky_relu(x, 0.1), np.where(self.x > 0, self.x, 0.1 * self.x))
        check(F.elu(x), np.where(self.x > 0, self.x, np.expm1(self.x)), rtol=1e-4)
        check(F.softsign(x), self.x / (1 + np.abs(self.x)), rtol=1e-5)
        check(F.hardtanh(x), np.clip(self.x, -1, 1))
        check(F.hardswish(x), self.x * np.clip(self.x + 3, 0, 6) / 6, rtol=1e-4, atol=1e-5)

    def test_softmax_lse(self):
        x = pt.to_tensor(self.x)
        e = np.exp(self.x - self.x.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        check(F.softmax(x), sm, rtol=1e-4)
        check(F.log_softmax(x), np.log(sm), rtol=1e-4, atol=1e-5)

    def test_gelu(self):
        from scipy.stats import norm as snorm

        x = pt.to_tensor(self.x)
        exact = self.x * snorm.cdf(self.x)
        check(F.gelu(x), exact, rtol=1e-3, atol=1e-4)

    def test_shrinks(self):
        x = pt.to_tensor(self.x)
        check(F.hardshrink(x, 0.5), np.where(np.abs(self.x) > 0.5, self.x, 0))
        expected = np.where(self.x > 0.5, self.x - 0.5, np.where(self.x < -0.5, self.x + 0.5, 0))
        check(F.softshrink(x, 0.5), expected, rtol=1e-5)

    def test_glu_maxout(self):
        x = pt.to_tensor(self.x[:, :4])
        a, b = self.x[:, :2], self.x[:, 2:4]
        check(F.glu(x), a * (1 / (1 + np.exp(-b))), rtol=1e-4)
        m = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(1, 6, 2))
        out = F.maxout(m, groups=2, axis=1)
        assert out.shape == (1, 3, 2)

    def test_grad_activations(self):
        x = self.x[:2, :3]
        check_grad(lambda a: jnp.sum(F.gelu(a)), [x])
        check_grad(lambda a: jnp.sum(F.softmax(a) ** 2), [x])
        check_grad(lambda a: jnp.sum(F.silu(a)), [x])


class TestLinearConv:
    def test_linear(self):
        rs = np.random.RandomState(1)
        x = rs.rand(4, 3).astype(np.float32)
        w = rs.rand(3, 5).astype(np.float32)
        b = rs.rand(5).astype(np.float32)
        check(F.linear(pt.to_tensor(x), pt.to_tensor(w), pt.to_tensor(b)),
              x @ w + b, rtol=1e-5)

    def test_conv2d_vs_scipy(self):
        from scipy.signal import correlate2d

        rs = np.random.RandomState(2)
        x = rs.rand(1, 1, 6, 6).astype(np.float32)
        w = rs.rand(1, 1, 3, 3).astype(np.float32)
        out = F.conv2d(pt.to_tensor(x), pt.to_tensor(w))
        expected = correlate2d(x[0, 0], w[0, 0], mode="valid")
        check(out[0, 0], expected, rtol=1e-4, atol=1e-5)

    def test_conv2d_stride_pad_groups(self):
        rs = np.random.RandomState(3)
        x = rs.rand(2, 4, 8, 8).astype(np.float32)
        w = rs.rand(6, 2, 3, 3).astype(np.float32)
        out = F.conv2d(pt.to_tensor(x), pt.to_tensor(w), stride=2, padding=1, groups=2)
        assert out.shape == (2, 6, 4, 4)

    def test_conv2d_nhwc(self):
        rs = np.random.RandomState(4)
        x = rs.rand(1, 5, 5, 3).astype(np.float32)
        w = rs.rand(2, 3, 3, 3).astype(np.float32)
        out = F.conv2d(pt.to_tensor(x), pt.to_tensor(w), data_format="NHWC")
        assert out.shape == (1, 3, 3, 2)

    def test_conv2d_transpose(self):
        rs = np.random.RandomState(5)
        x = rs.rand(1, 2, 4, 4).astype(np.float32)
        w = rs.rand(2, 3, 3, 3).astype(np.float32)  # (C_in, C_out, kh, kw)
        out = F.conv2d_transpose(pt.to_tensor(x), pt.to_tensor(w), stride=2)
        assert out.shape == (1, 3, 9, 9)
        # parity: transpose-conv is the gradient of conv w.r.t. input
        def conv_sum(xin):
            wt = jnp.transpose(jnp.asarray(w), (1, 0, 2, 3))  # OIHW for fwd
            return jnp.sum(F.conv2d(xin, wt, stride=2))

    def test_conv_grad(self):
        rs = np.random.RandomState(6)
        x = rs.rand(1, 1, 5, 5).astype(np.float64)
        w = rs.rand(1, 1, 3, 3).astype(np.float64)
        check_grad(lambda a, b: jnp.sum(F.conv2d(a, b) ** 2), [x, w], idx=0)
        check_grad(lambda a, b: jnp.sum(F.conv2d(a, b) ** 2), [x, w], idx=1)

    def test_conv1d_3d(self):
        rs = np.random.RandomState(7)
        x1 = rs.rand(2, 3, 10).astype(np.float32)
        w1 = rs.rand(4, 3, 3).astype(np.float32)
        assert F.conv1d(pt.to_tensor(x1), pt.to_tensor(w1), padding=1).shape == (2, 4, 10)
        x3 = rs.rand(1, 2, 4, 4, 4).astype(np.float32)
        w3 = rs.rand(3, 2, 2, 2, 2).astype(np.float32)
        assert F.conv3d(pt.to_tensor(x3), pt.to_tensor(w3)).shape == (1, 3, 3, 3, 3)


class TestPooling:
    def setup_method(self):
        self.x = np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4)

    def test_max_pool2d(self):
        out = F.max_pool2d(pt.to_tensor(self.x), 2)
        expected = self.x.reshape(1, 2, 2, 2, 2, 2).max((3, 5))
        check(out, expected)

    def test_avg_pool2d(self):
        out = F.avg_pool2d(pt.to_tensor(self.x), 2)
        expected = self.x.reshape(1, 2, 2, 2, 2, 2).mean((3, 5))
        check(out, expected)

    def test_avg_pool_pad_exclusive(self):
        x = np.ones((1, 1, 2, 2), np.float32)
        out = F.avg_pool2d(pt.to_tensor(x), 2, stride=1, padding=1, exclusive=True)
        # corners average over 1 valid element → still 1.0
        check(out, np.ones((1, 1, 3, 3), np.float32))
        out2 = F.avg_pool2d(pt.to_tensor(x), 2, stride=1, padding=1, exclusive=False)
        assert np.asarray(out2)[0, 0, 0, 0] == 0.25

    def test_max_pool_ceil(self):
        x = np.arange(25, dtype=np.float32).reshape(1, 1, 5, 5)
        out = F.max_pool2d(pt.to_tensor(x), 2, stride=2, ceil_mode=True)
        assert out.shape == (1, 1, 3, 3)

    def test_adaptive(self):
        out = F.adaptive_avg_pool2d(pt.to_tensor(self.x), 1)
        check(out, self.x.mean((2, 3), keepdims=True))
        out = F.adaptive_avg_pool2d(pt.to_tensor(self.x), (2, 2))
        check(out, self.x.reshape(1, 2, 2, 2, 2, 2).mean((3, 5)))
        # uneven
        x = np.arange(10, dtype=np.float32).reshape(1, 1, 10, 1)
        out = F.adaptive_avg_pool2d(pt.to_tensor(x), (3, 1))
        assert out.shape == (1, 1, 3, 1)

    def test_return_mask(self):
        out, idx = F.max_pool2d(pt.to_tensor(self.x), 2, return_mask=True)
        assert idx.shape == out.shape
        # max of first window of channel 0 is at flat position 5
        assert int(np.asarray(idx)[0, 0, 0, 0]) == 5


class TestNorms:
    def test_layer_norm(self):
        rs = np.random.RandomState(8)
        x = rs.rand(4, 6).astype(np.float32)
        g = rs.rand(6).astype(np.float32)
        b = rs.rand(6).astype(np.float32)
        out = F.layer_norm(pt.to_tensor(x), 6, pt.to_tensor(g), pt.to_tensor(b))
        mu = x.mean(-1, keepdims=True)
        sig = x.var(-1, keepdims=True)
        expected = (x - mu) / np.sqrt(sig + 1e-5) * g + b
        check(out, expected, rtol=1e-4, atol=1e-5)

    def test_batch_norm_train_eval(self):
        rs = np.random.RandomState(9)
        x = rs.rand(4, 3, 2, 2).astype(np.float32)
        rm = np.zeros(3, np.float32)
        rv = np.ones(3, np.float32)
        out, nm, nv = F.batch_norm(pt.to_tensor(x), rm, rv, training=True, momentum=0.9)
        mu = x.mean((0, 2, 3))
        var = x.var((0, 2, 3))
        check(out, (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(var.reshape(1, 3, 1, 1) + 1e-5),
              rtol=1e-4, atol=1e-4)
        check(nm, 0.9 * rm + 0.1 * mu, rtol=1e-4)
        check(nv, 0.9 * rv + 0.1 * var, rtol=1e-4)
        out_eval = F.batch_norm(pt.to_tensor(x), pt.to_tensor(mu), pt.to_tensor(var), training=False)
        check(out_eval, (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(var.reshape(1, 3, 1, 1) + 1e-5),
              rtol=1e-4, atol=1e-4)

    def test_conv_amp_mixed_dtype_casts(self):
        # f32 inputs into bf16 weights compute in bf16 (AMP convention),
        # for plain AND transpose convs
        import jax.numpy as jnp

        rs = np.random.RandomState(20)
        x = jnp.asarray(rs.rand(1, 2, 6, 6).astype(np.float32))
        w = jnp.asarray(rs.rand(3, 2, 3, 3), jnp.bfloat16)
        out = F.conv2d(x, w, padding=1)
        assert out.dtype == jnp.bfloat16
        wt = jnp.asarray(rs.rand(2, 3, 3, 3), jnp.bfloat16)
        out_t = F.conv2d_transpose(x, wt, stride=2)
        assert out_t.dtype == jnp.bfloat16

    def test_batch_norm_bf16_fast_path(self):
        # AMP path: one-pass f32-accumulated stats + folded bf16 normalize
        # must track the f32 two-pass oracle, and the functional stat update
        # must preserve the running buffers' dtype (scan-carry invariant).
        import jax.numpy as jnp

        rs = np.random.RandomState(11)
        x = rs.normal(2.0, 1.5, (8, 5, 4, 4)).astype(np.float32)
        rm = rs.rand(5).astype(np.float32)
        rv = (1 + rs.rand(5)).astype(np.float32)
        g = rs.rand(5).astype(np.float32)
        b = rs.rand(5).astype(np.float32)
        ref, nm_ref, nv_ref = F.batch_norm(
            pt.to_tensor(x), rm, rv, pt.to_tensor(g), pt.to_tensor(b),
            training=True)
        xb = jnp.asarray(x, jnp.bfloat16)
        out, nm, nv = F.batch_norm(xb, rm, rv, pt.to_tensor(g),
                                   pt.to_tensor(b), training=True)
        assert jnp.asarray(out).dtype == jnp.bfloat16
        assert np.asarray(nm).dtype == np.float32  # running dtype preserved
        check(np.asarray(out, np.float32), np.asarray(ref), rtol=0.06,
              atol=0.06)
        check(nm, nm_ref, rtol=1e-2, atol=1e-2)
        check(nv, nv_ref, rtol=2e-2, atol=2e-2)
        # bf16 running buffers stay bf16 after the update
        _, nm2, _ = F.batch_norm(xb, jnp.asarray(rm, jnp.bfloat16),
                                 jnp.asarray(rv, jnp.bfloat16), training=True)
        assert jnp.asarray(nm2).dtype == jnp.bfloat16

    def test_batch_norm_nhwc(self):
        rs = np.random.RandomState(12)
        x = rs.rand(4, 3, 2, 2).astype(np.float32)
        out_nchw, nm1, nv1 = F.batch_norm(pt.to_tensor(x), np.zeros(3, np.float32),
                                          np.ones(3, np.float32), training=True)
        out_nhwc, nm2, nv2 = F.batch_norm(
            pt.to_tensor(x.transpose(0, 2, 3, 1)), np.zeros(3, np.float32),
            np.ones(3, np.float32), training=True, data_format="NHWC")
        check(np.asarray(out_nhwc).transpose(0, 3, 1, 2), np.asarray(out_nchw),
              rtol=1e-5, atol=1e-6)
        check(nm2, np.asarray(nm1), rtol=1e-5)
        check(nv2, np.asarray(nv1), rtol=1e-5)

    def test_group_instance_norm(self):
        rs = np.random.RandomState(10)
        x = rs.rand(2, 4, 3, 3).astype(np.float32)
        out = F.group_norm(pt.to_tensor(x), 2)
        g = x.reshape(2, 2, 2, 3, 3)
        mu = g.mean((2, 3, 4), keepdims=True)
        var = g.var((2, 3, 4), keepdims=True)
        check(out, ((g - mu) / np.sqrt(var + 1e-5)).reshape(x.shape), rtol=1e-4, atol=1e-4)
        out_in = F.instance_norm(pt.to_tensor(x))
        mu_i = x.mean((2, 3), keepdims=True)
        var_i = x.var((2, 3), keepdims=True)
        check(out_in, (x - mu_i) / np.sqrt(var_i + 1e-5), rtol=1e-4, atol=1e-4)

    def test_normalize(self):
        x = np.array([[3.0, 4.0]], np.float32)
        check(F.normalize(pt.to_tensor(x), axis=1), x / 5.0, rtol=1e-5)


class TestDropoutEmbedding:
    def test_dropout_train_scale(self):
        pt.seed(0)
        x = pt.ones([1000])
        out = np.asarray(F.dropout(x, p=0.3, training=True))
        kept = out != 0
        assert 0.6 < kept.mean() < 0.8
        np.testing.assert_allclose(out[kept], 1 / 0.7, rtol=1e-5)
        out_eval = F.dropout(x, p=0.3, training=False)
        check(out_eval, np.ones(1000, np.float32))

    def test_dropout_axis(self):
        pt.seed(1)
        x = pt.ones([8, 16])
        out = np.asarray(F.dropout(x, p=0.5, axis=0, training=True))
        # whole rows are zero or scaled
        for r in out:
            assert (r == 0).all() or np.allclose(r, 2.0)

    def test_embedding(self):
        w = np.arange(12, dtype=np.float32).reshape(4, 3)
        out = F.embedding(pt.to_tensor([1, 3], "int64"), pt.to_tensor(w))
        check(out, w[[1, 3]])
        out_pad = F.embedding(pt.to_tensor([0, 1], "int64"), pt.to_tensor(w), padding_idx=0)
        assert (np.asarray(out_pad)[0] == 0).all()

    def test_interpolate(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = F.interpolate(pt.to_tensor(x), size=[2, 2], mode="nearest")
        check(out, x[:, :, ::2, ::2])
        out_b = F.interpolate(pt.to_tensor(x), scale_factor=2, mode="bilinear", align_corners=True)
        assert out_b.shape == (1, 1, 8, 8)
        check(np.asarray(out_b)[0, 0, 0, [0, -1]], [0.0, 3.0], rtol=1e-5)

    def test_pixel_shuffle(self):
        x = np.arange(8, dtype=np.float32).reshape(1, 4, 1, 2)
        out = F.pixel_shuffle(pt.to_tensor(x), 2)
        assert out.shape == (1, 1, 2, 4)

    def test_unfold_fold_roundtrip(self):
        x = np.random.RandomState(11).rand(1, 2, 4, 4).astype(np.float32)
        cols = F.unfold(pt.to_tensor(x), 2, strides=2)
        assert cols.shape == (1, 8, 4)
        back = F.fold(cols, (4, 4), 2, strides=2)
        check(back, x, rtol=1e-6)

    def test_sequence_mask(self):
        out = F.sequence_mask(pt.to_tensor([2, 0, 3], "int64"), maxlen=4)
        check(out, [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])


class TestLosses:
    def test_cross_entropy(self):
        rs = np.random.RandomState(12)
        logits = rs.rand(4, 5).astype(np.float32)
        labels = np.array([0, 2, 1, 4])
        out = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels, "int64"))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        logp = np.log(e / e.sum(-1, keepdims=True))
        expected = -logp[np.arange(4), labels].mean()
        check(out, expected, rtol=1e-5)

    def test_cross_entropy_ignore_soft(self):
        rs = np.random.RandomState(13)
        logits = rs.rand(4, 3).astype(np.float32)
        labels = np.array([0, -100, 1, 2])
        out = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels, "int64"),
                              ignore_index=-100)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        logp = np.log(e / e.sum(-1, keepdims=True))
        valid = labels != -100
        expected = -logp[np.arange(4), np.clip(labels, 0, 2)][valid].mean()
        check(out, expected, rtol=1e-5)
        soft = np.array([[0.5, 0.5, 0.0]] * 4, np.float32)
        out_soft = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(soft), soft_label=True)
        check(out_soft, (-soft * logp).sum(-1).mean(), rtol=1e-5)

    def test_bce(self):
        p = np.array([0.2, 0.8], np.float32)
        y = np.array([0.0, 1.0], np.float32)
        out = F.binary_cross_entropy(pt.to_tensor(p), pt.to_tensor(y))
        expected = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        check(out, expected, rtol=1e-5)
        logit = np.array([-1.0, 2.0], np.float32)
        out2 = F.binary_cross_entropy_with_logits(pt.to_tensor(logit), pt.to_tensor(y))
        sp = 1 / (1 + np.exp(-logit))
        expected2 = -(y * np.log(sp) + (1 - y) * np.log(1 - sp)).mean()
        check(out2, expected2, rtol=1e-4)

    def test_mse_l1_smooth(self):
        a = np.array([1.0, 2.0], np.float32)
        b = np.array([1.5, 4.0], np.float32)
        check(F.mse_loss(pt.to_tensor(a), pt.to_tensor(b)), ((a - b) ** 2).mean(), rtol=1e-6)
        check(F.l1_loss(pt.to_tensor(a), pt.to_tensor(b)), np.abs(a - b).mean(), rtol=1e-6)
        check(F.smooth_l1_loss(pt.to_tensor(a), pt.to_tensor(b)),
              np.mean([0.5 * 0.25, 1.5]), rtol=1e-5)

    def test_kl_nll(self):
        rs = np.random.RandomState(14)
        p = rs.dirichlet(np.ones(3), 2).astype(np.float32)
        logq = np.log(rs.dirichlet(np.ones(3), 2).astype(np.float32))
        out = F.kl_div(pt.to_tensor(logq), pt.to_tensor(p), reduction="sum")
        expected = (p * (np.log(p) - logq)).sum()
        check(out, expected, rtol=1e-4)
        nll = F.nll_loss(pt.to_tensor(logq), pt.to_tensor([0, 2], "int64"))
        check(nll, -(logq[0, 0] + logq[1, 2]) / 2, rtol=1e-5)

    def test_loss_grads(self):
        rs = np.random.RandomState(15)
        logits = rs.rand(3, 4)
        labels = np.array([0, 1, 3])
        check_grad(lambda a: F.cross_entropy(a, jnp.asarray(labels)), [logits])
        check_grad(lambda a: F.mse_loss(a, jnp.zeros((3, 4))), [logits])

    def test_ctc_loss(self):
        # simple case: T=3, C=3 (blank=0), label "1"
        logp = np.log(np.full((3, 1, 3), 1 / 3, np.float32))
        loss = F.ctc_loss(pt.to_tensor(logp), pt.to_tensor([[1]], "int64"),
                          pt.to_tensor([3], "int64"), pt.to_tensor([1], "int64"),
                          reduction="none")
        # paths emitting '1': positions of 1 among 3 frames with blanks:
        # number of valid CTC paths for single label over T=3 = 7? compute:
        # alignments: 1--, -1-, --1, 11-, -11, 111, 1-1(invalid? 1,blank,1 decodes "11"? no: 1,_,1 -> "11"!? for single '1' invalid)
        # valid: {1bb,b1b,bb1,11b,b11,111,1b b? } = 6... probability = n_paths*(1/27)
        val = float(np.asarray(loss).reshape(-1)[0])
        n_paths = np.exp(-val) * 27
        assert abs(n_paths - round(n_paths)) < 1e-3  # integer path count sanity
        assert 5 <= round(n_paths) <= 7

    def test_scaled_dot_product_attention(self):
        rs = np.random.RandomState(16)
        q = rs.rand(2, 4, 2, 8).astype(np.float32)
        k = rs.rand(2, 4, 2, 8).astype(np.float32)
        v = rs.rand(2, 4, 2, 8).astype(np.float32)
        out = F.scaled_dot_product_attention(q, k, v)
        # numpy reference
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        logits = qt @ kt.transpose(0, 1, 3, 2) / np.sqrt(8)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expected = (p @ vt).transpose(0, 2, 1, 3)
        check(out, expected, rtol=1e-4, atol=1e-5)

    def test_sdpa_causal(self):
        rs = np.random.RandomState(17)
        q = rs.rand(1, 3, 1, 4).astype(np.float32)
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        # first position attends only to itself → equals v[0]
        check(np.asarray(out)[0, 0, 0], q[0, 0, 0], rtol=1e-5)

"""Sharded checkpoint save/restore on the 8-device mesh.

Reference capability: per-shard PS table persistence
(distributed_ops/checkpoint_notify_op.cc:65 + large_scale_kv shard save).
Here: orbax per-shard format driven by jax shardings — saved distributed,
restored straight onto the target sharding.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.framework.errors import NotFoundError
from paddle_tpu.incubate.sharded_checkpoint import (
    latest_step,
    restore_sharded,
    save_sharded,
)


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())


class TestShardedCheckpoint:
    def test_round_trip_preserves_sharding(self, tmp_path):
        mesh = build_mesh(dp=4, mp=2)
        set_mesh(mesh)
        w = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("data", "model")))
        b = jax.device_put(jnp.ones(8), NamedSharding(mesh, P()))
        state = {"params": {"w": w, "b": b}, "step": jnp.asarray(3)}
        d = os.path.join(tmp_path, "ck")
        save_sharded(d, state, step=10)
        assert latest_step(d) == 10

        out = restore_sharded(d, like=state)
        np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                      np.asarray(w))
        np.testing.assert_array_equal(np.asarray(out["params"]["b"]), 1.0)
        # restored ONTO the distributed sharding, not gathered
        assert out["params"]["w"].sharding.is_equivalent_to(w.sharding, 2)

    def test_latest_step_and_multiple(self, tmp_path):
        d = os.path.join(tmp_path, "ck")
        s1 = {"x": jnp.zeros(4)}
        save_sharded(d, s1, step=1)
        save_sharded(d, {"x": jnp.ones(4)}, step=2)
        assert latest_step(d) == 2
        out = restore_sharded(d, like=s1)  # latest by default
        np.testing.assert_array_equal(np.asarray(out["x"]), 1.0)
        out1 = restore_sharded(d, like=s1, step=1)
        np.testing.assert_array_equal(np.asarray(out1["x"]), 0.0)

    def test_keep_max_prunes(self, tmp_path):
        d = os.path.join(tmp_path, "ck")
        for s in range(4):
            save_sharded(d, {"x": jnp.full(2, s)}, step=s, keep_max=2)
        steps = sorted(int(n) for n in os.listdir(d) if n.isdigit())
        assert steps == [2, 3]

    def test_missing_raises(self, tmp_path):
        with pytest.raises(NotFoundError, match="no sharded checkpoint"):
            restore_sharded(os.path.join(tmp_path, "nope"))

    def test_model_state_round_trip(self, tmp_path):
        """Full Model train state through the sharded path under a plan."""
        from paddle_tpu import nn, optimizer as popt
        from paddle_tpu.distributed import fleet

        fleet._initialized = False
        strategy = fleet.DistributedStrategy(sharding=True)
        fleet.init(is_collective=True, strategy=strategy)
        try:
            paddle.seed(0)
            net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
            opt = fleet.distributed_optimizer(popt.Adam(learning_rate=1e-2))
            model = paddle.Model(net, inputs=["x"], labels=["y"])
            model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
            rng = np.random.RandomState(0)
            x = rng.randn(16, 8).astype(np.float32)
            y = rng.randint(0, 2, (16,)).astype(np.int32)
            model.train_batch([x], [y])

            state = {"params": model.network.param_pytree(),
                     "opt": model._opt_state}
            d = os.path.join(tmp_path, "ck")
            save_sharded(d, state, step=1)
            # ZeRO slots restore onto their sharded layout
            out = restore_sharded(d, like=state)
            for name, slots in out["opt"]["slots"].items():
                for sname, v in slots.items():
                    ref = state["opt"]["slots"][name][sname]
                    np.testing.assert_allclose(np.asarray(v), np.asarray(ref))
                    assert v.sharding.is_equivalent_to(ref.sharding, v.ndim)
        finally:
            fleet._initialized = False
            fleet._strategy = None

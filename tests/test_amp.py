"""AMP tests: autocast white/black policy, O2 decorate, GradScaler state
machine vs the reference's update_loss_scaling_op semantics, and jit-safe
guarded updates (mirrors test_amp_* / test_imperative_auto_mixed_precision
unittests)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import amp, nn, optimizer as popt
from paddle_tpu.nn.layer_base import Parameter


class TestAutoCast:
    def test_linear_bf16_under_o1(self):
        lin = nn.Linear(4, 4)
        x = jnp.ones((2, 4), jnp.float32)
        with amp.auto_cast():
            out = lin(x)
        assert out.dtype == jnp.bfloat16
        # outside: f32 again
        assert lin(x).dtype == jnp.float32

    def test_blacklist_stays_f32(self):
        ln = nn.LayerNorm(4)
        x = jnp.ones((2, 4), jnp.bfloat16)
        with amp.auto_cast():
            out = ln(x)
        assert out.dtype == jnp.float32

    def test_custom_lists(self):
        lin = nn.Linear(4, 4)
        x = jnp.ones((2, 4), jnp.float32)
        with amp.auto_cast(custom_black_list=["Linear"]):
            out = lin(x)
        assert out.dtype == jnp.float32

    def test_disabled(self):
        lin = nn.Linear(4, 4)
        x = jnp.ones((2, 4), jnp.float32)
        with amp.auto_cast(enable=False):
            assert lin(x).dtype == jnp.float32

    def test_nesting_restores(self):
        lin = nn.Linear(4, 4)
        x = jnp.ones((2, 4), jnp.float32)
        with amp.auto_cast():
            with amp.auto_cast(enable=False):
                assert lin(x).dtype == jnp.float32
            assert lin(x).dtype == jnp.bfloat16
        assert lin(x).dtype == jnp.float32

    def test_works_under_jit(self):
        lin = nn.Linear(4, 4)

        @jax.jit
        def f(x):
            with amp.auto_cast():
                return lin(x)

        assert f(jnp.ones((2, 4))).dtype == jnp.bfloat16

    def test_decorate_o2(self):
        net = nn.Linear(4, 4)
        opt = popt.Adam(parameters=net.parameters())
        net2, opt2 = amp.decorate(models=net, optimizers=opt)
        assert net.weight.dtype == jnp.bfloat16
        assert opt._multi_precision


class TestGradScaler:
    def test_scale_and_unscale(self):
        s = amp.GradScaler(init_loss_scaling=4.0)
        loss = jnp.asarray(2.0)
        assert float(s.scale(loss)) == 8.0
        grads, inf = s.unscale_and_check([jnp.asarray([8.0])], s._state)
        np.testing.assert_allclose(grads[0], 2.0)
        assert not bool(inf)

    def test_inf_detection(self):
        s = amp.GradScaler(init_loss_scaling=2.0)
        _, inf = s.unscale_and_check([jnp.asarray([jnp.inf])], s._state)
        assert bool(inf)
        _, nan = s.unscale_and_check([jnp.asarray([jnp.nan])], s._state)
        assert bool(nan)

    def test_skip_update_on_inf_and_shrink(self):
        w = Parameter(np.ones(2, np.float32), name="w")
        opt = popt.SGD(learning_rate=1.0, parameters=[w])
        s = amp.GradScaler(init_loss_scaling=8.0, decr_every_n_nan_or_inf=1)
        s.step(opt, {"w": jnp.asarray([np.inf, 1.0])})
        s.update()
        np.testing.assert_allclose(w.numpy(), 1.0)  # skipped
        assert s.get_loss_scaling() == 4.0  # halved

    def test_growth_after_n_good_steps(self):
        w = Parameter(np.ones(2, np.float32), name="w")
        opt = popt.SGD(learning_rate=0.0, parameters=[w])
        s = amp.GradScaler(init_loss_scaling=2.0, incr_every_n_steps=3)
        for _ in range(3):
            s.step(opt, {"w": jnp.ones(2)})
            s.update()
        assert s.get_loss_scaling() == 4.0

    def test_functional_guarded_update_jit(self):
        opt = popt.SGD(learning_rate=1.0)
        s = amp.GradScaler(init_loss_scaling=2.0, decr_every_n_nan_or_inf=1)
        params = {"w": jnp.ones(2)}
        opt_state = opt.init(params)
        sstate = s.init_state()

        @jax.jit
        def guarded(grads, params, opt_state, sstate):
            return s.guarded_update(opt, grads, opt_state, params, sstate)

        # finite step: applied (grads are scaled by 2, unscale → 1)
        p, o, st, inf = guarded({"w": jnp.full(2, 2.0)}, params, opt_state, sstate)
        np.testing.assert_allclose(p["w"], 0.0)
        assert not bool(inf)
        # inf step: skipped, scale halves
        p2, o2, st2, inf2 = guarded({"w": jnp.asarray([jnp.inf, 0.0])}, p, o, st)
        np.testing.assert_allclose(p2["w"], 0.0)
        assert bool(inf2)
        assert float(st2["scale"]) == 1.0

    def test_disabled_passthrough(self):
        w = Parameter(np.ones(2, np.float32), name="w")
        opt = popt.SGD(learning_rate=1.0, parameters=[w])
        s = amp.GradScaler(enable=False)
        assert float(s.scale(jnp.asarray(3.0))) == 3.0
        s.step(opt, {"w": jnp.ones(2)})
        np.testing.assert_allclose(w.numpy(), 0.0)

    def test_state_dict_roundtrip(self):
        s = amp.GradScaler(init_loss_scaling=16.0)
        sd = s.state_dict()
        s2 = amp.GradScaler()
        s2.load_state_dict(sd)
        assert s2.get_loss_scaling() == 16.0


class TestModelAmp:
    def test_fit_with_amp_o1_converges(self, rng):
        W = rng.randn(16, 4).astype(np.float32)
        X = rng.randn(256, 16).astype(np.float32)
        y = np.argmax(X @ W, 1).astype(np.int64)

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(16, 32)
                self.act = nn.ReLU()
                self.fc2 = nn.Linear(32, 4)

            def forward(self, x):
                return self.fc2(self.act(self.fc1(x)))

        from paddle_tpu import io as pio, metric as pmetric

        paddle.seed(0)
        model = paddle.Model(MLP())
        model.prepare(optimizer=popt.Adam(learning_rate=5e-3),
                      loss=nn.CrossEntropyLoss(),
                      metrics=[pmetric.Accuracy()],
                      amp_configs={"level": "O1"})
        ds = pio.TensorDataset([X, y.reshape(-1, 1)])
        model.fit(ds, batch_size=64, epochs=20, verbose=0)
        logs = model.evaluate(ds, batch_size=64, verbose=0)
        assert logs["acc"] > 0.9, logs


class TestReviewRegressions:
    def test_white_layer_weights_cast_bf16(self):
        """The matmul must run in bf16: bf16 input × f32 weight would promote
        back to f32 (the original bug — zero mixed-precision benefit)."""
        lin = nn.Linear(4, 4)

        def f(x):
            with amp.auto_cast():
                return lin(x)

        jaxpr = str(jax.make_jaxpr(f)(jnp.ones((2, 4))))
        import re
        # weight enters as f32 const/arg but must be converted before the dot
        assert "bf16" in jaxpr
        dots = [l for l in jaxpr.splitlines() if "dot_general" in l]
        assert dots and all("f32[4,4]" not in d for d in dots), dots

    def test_kwargs_cast(self):
        class KW(nn.Layer):
            def forward(self, x=None):
                return x

        KW.__name__ = "Linear"  # force white-list membership
        layer = KW()
        with amp.auto_cast():
            out = layer(x=jnp.ones((2,), jnp.float32))
        assert out.dtype == jnp.bfloat16

    def test_o2_casts_unlisted_layers(self):
        class Custom(nn.Layer):
            def forward(self, x):
                return x

        layer = Custom()
        x = jnp.ones((2,), jnp.float32)
        with amp.auto_cast(level="O1"):
            assert layer(x).dtype == jnp.float32  # not white-listed
        with amp.auto_cast(level="O2"):
            assert layer(x).dtype == jnp.bfloat16  # O2: everything
        ln = nn.LayerNorm(2)
        with amp.auto_cast(level="O2"):
            assert ln(x).dtype == jnp.float32  # black list still wins

    def test_param_boxes_restored_after_call(self):
        lin = nn.Linear(4, 4)
        with amp.auto_cast():
            lin(jnp.ones((2, 4)))
        assert lin.weight.dtype == jnp.float32

    def test_amp_configs_string_form(self, rng):
        X = rng.randn(32, 8).astype(np.float32)
        y = rng.randint(0, 2, (32, 1)).astype(np.int64)
        model = paddle.Model(nn.Linear(8, 2))
        model.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.CrossEntropyLoss(), amp_configs="O1")
        model.train_batch([X], [y])  # no crash

    def test_amp_configs_scaler_keys_ignored(self, rng):
        X = rng.randn(16, 8).astype(np.float32)
        y = rng.randint(0, 2, (16, 1)).astype(np.int64)
        model = paddle.Model(nn.Linear(8, 2))
        model.prepare(optimizer=popt.SGD(learning_rate=0.1),
                      loss=nn.CrossEntropyLoss(),
                      amp_configs={"level": "O1", "init_loss_scaling": 512,
                                   "use_fp16_guard": False})
        model.train_batch([X], [y])  # no crash

    def test_is_use_dynamic_loss_scaling(self):
        s = amp.GradScaler(enable=True, use_dynamic_loss_scaling=False)
        assert not s.is_use_dynamic_loss_scaling()
        assert s.is_enable()


class TestGradScalerInputs:
    def test_generator_grads_not_silently_dropped(self):
        """ADVICE r1: a generator grads input used to produce an empty
        value list → silent no-op step."""
        w = Parameter(np.ones(2, np.float32), name="w")
        opt = popt.SGD(learning_rate=1.0, parameters=[w])
        s = amp.GradScaler(init_loss_scaling=2.0)
        s.step(opt, (g for g in [jnp.ones(2) * 2.0]))
        np.testing.assert_allclose(w.numpy(), 0.0)  # 1 - 1.0*(2/2)

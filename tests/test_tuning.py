"""Measured-search engine beyond kernels (paddle_tpu.tuning): plan-space
enumeration + check_plan pre-filtering, deterministic serving-space
search over a fixed trace, v2 disk-cache round-trips for both spaces,
stale-schema tolerance, scope-aware clearing, and K701 on post-warm
plan/serving searches.

All on CPU — plan/serving measures are injected deterministic scorers
(wall-clock scoring would make winner selection flaky), which exercises
the full search/cache/counter machinery; the replay-timing path is
gated end-to-end in tools/tune_smoke.py.
"""
import json
from types import SimpleNamespace

import numpy as np
import pytest

from paddle_tpu.framework.flags import set_flags
from paddle_tpu.tuning import engine, plan_space, serving_space
from paddle_tpu.tuning.trace import RequestTrace, TraceRecorder


@pytest.fixture(autouse=True)
def _clean_tuner_state():
    """Each test starts cold (memory caches, counters, warm flag) and
    leaves the flags at their defaults."""
    engine.clear_cache()
    engine.reset_counters()
    engine.reset_warm()
    yield
    set_flags({"kernel_autotune": "on", "kernel_tuning_cache": "",
               "measured_search": "on"})
    engine.clear_cache()
    engine.reset_counters()
    engine.reset_warm()


def _mesh(**axes):
    """check_plan and the key builder only read ``mesh.shape``, so a
    stub carries any axis geometry on a single-device CPU test host."""
    shape = {"pipe": 1, "data": 1, "sharding": 1, "sep": 1, "expert": 1,
             "model": 1}
    shape.update(axes)
    return SimpleNamespace(shape=shape)


SHAPES = {"fc.weight": (10, 16), "fc.bias": (16,), "emb.weight": (32, 16)}


def _score_plan(cfg):
    """Deterministic plan scorer: sharding 'emb' over model wins, every
    collective dial at base."""
    ms = 10.0
    if cfg["axes"].get("emb") == "model":
        ms -= 5.0
    ms += cfg["fp16_allreduce"] + cfg["allreduce_bucket_mb"] / 100.0
    ms += 0.0 if cfg["overlap_grad_sync"] else 1.0
    return ms


def _score_serving(cfg):
    """Deterministic serving scorer: batch_size 16 with a 2 ms delay
    wins."""
    return (abs(cfg["batch_size"] - 16) * 0.5
            + abs(cfg["max_queue_delay_ms"] - 2.0)
            + 10.0 / cfg["buckets"][-1])


BASE_SERVING = {"buckets": [16, 48], "batch_size": 8,
                "max_queue_delay_ms": 1.0}


class TestPlanSpace:
    def test_enumeration_prefiltered_by_check_plan(self):
        """With model=4, any candidate putting 'model' on the fc group is
        invalid (fc.weight dim0=10 and dim1=16: first dim >= 4 is 10,
        10 % 4 != 0 → P502) and must be dropped BEFORE measurement."""
        mesh = _mesh(model=4)
        groups = plan_space.param_groups(SHAPES)
        cands = plan_space.plan_candidates(groups, mesh)
        bad = [c for c in cands if c["axes"].get("fc") == "model"]
        assert bad, "space must propose the invalid assignment"
        assert all(not plan_space.is_valid_candidate(c, groups, mesh)
                   for c in bad)
        good = [c for c in cands if c["axes"].get("emb") == "model"
                and c["axes"].get("fc") == "none"]
        assert good, "space must keep the valid assignment"
        assert all(plan_space.is_valid_candidate(c, groups, mesh)
                   for c in good)

    def test_expert_axis_proposed_and_p506_prefiltered(self):
        """With expert=4 in the mesh the space proposes the 'expert'
        axis like any other, but P506 rejects it on non-expert parameter
        groups before any measurement ('emb.weight' dim0=32 divides by 4,
        so only the name rule can catch it); a stacked expert-weight
        group keeps the assignment."""
        mesh = _mesh(expert=4)
        groups = plan_space.param_groups(SHAPES)
        cands = plan_space.plan_candidates(groups, mesh)
        on_expert = [c for c in cands
                     if c["axes"].get("emb") == "expert"]
        assert on_expert, "space must propose the expert axis"
        assert all(not plan_space.is_valid_candidate(c, groups, mesh)
                   for c in on_expert)
        moe_groups = plan_space.param_groups(
            {"expert_fc1.w": (4, 16, 32), "expert_b1.b": (4, 32)})
        mcands = plan_space.plan_candidates(moe_groups, mesh)
        good = [c for c in mcands
                if set(c["axes"].values()) == {"expert"}]
        assert good, "space must propose expert sharding for experts"
        assert all(plan_space.is_valid_candidate(c, moe_groups, mesh)
                   for c in good)

    def test_search_skips_prefiltered_and_picks_valid_winner(self):
        set_flags({"kernel_tuning_cache": "off"})
        details = {}
        won = plan_space.tune_plan(
            "t-plan", shapes=SHAPES, mesh=_mesh(model=4),
            measure=_score_plan, details=details)
        assert won["axes"]["emb"] == "model"
        assert won["axes"]["fc"] == "none"
        assert details["event"] == "search"
        assert details["n_prefiltered"] > 0
        c = engine.get_counters("t-plan")
        assert c["searches"] == 1
        assert c["prefiltered"] == details["n_prefiltered"]
        # every measured candidate passed the filter
        assert c["configs_timed"] + c["prefiltered"] == \
            details["n_candidates"]

    def test_measured_search_off_returns_base_untimed(self):
        set_flags({"measured_search": "off", "kernel_tuning_cache": "off"})
        timed = []
        won = plan_space.tune_plan(
            "t-plan-off", shapes=SHAPES, mesh=_mesh(model=4),
            measure=lambda cfg: timed.append(cfg) or 1.0)
        assert timed == []
        assert won["axes"] == {"emb": "none", "fc": "none"}
        assert engine.get_counters("t-plan-off")["heuristic"] == 1

    def test_apply_plan_sets_strategy_dials(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        strat = DistributedStrategy()
        cfg = {"axes": {}, "fp16_allreduce": 1, "allreduce_bucket_mb": 64,
               "overlap_grad_sync": 0}
        plan_space.apply_plan(cfg, strategy=strat)
        assert strat.fp16_allreduce is True
        assert strat.allreduce_bucket_mb == 64
        assert strat.overlap_grad_sync is False

    def test_apply_plan_annotates_network_params(self):
        import paddle_tpu as paddle
        net = paddle.nn.Linear(16, 8)
        mesh = _mesh(model=4)
        cfg = {"axes": {"weight": "model", "bias": "none"}}
        plan_space.apply_plan(cfg, network=net, mesh=mesh)
        specs = {n: getattr(b, "partition_spec", None)
                 for n, b in net.named_parameters()}
        assert specs["weight"] == ("model",)  # dim0=16 divisible by 4
        assert specs["bias"] is None


class TestServingSpace:
    def test_search_deterministic_under_fixed_trace(self):
        set_flags({"kernel_tuning_cache": "off"})
        trace = RequestTrace.synthetic(n=8, seed=3)
        winners = []
        for _ in range(2):
            engine.clear_cache()
            engine.reset_counters()
            winners.append(serving_space.tune_serving(
                "t-serve", BASE_SERVING, trace=trace,
                measure=_score_serving))
        assert winners[0] == winners[1]
        # coordinate sweep: the dominant dial moves, the rest stay base
        assert winners[0]["batch_size"] == 16
        assert winners[0]["max_queue_delay_ms"] == 1.0

    def test_trace_key_binds_workload(self):
        t1 = RequestTrace.synthetic(n=8, seed=3)
        t2 = RequestTrace.synthetic(n=8, seed=4)
        assert t1.key() == RequestTrace.synthetic(n=8, seed=3).key()
        assert t1.key() != t2.key()

    def test_trace_save_load_round_trip(self, tmp_path):
        t = RequestTrace.synthetic(n=6, seed=5)
        p = str(tmp_path / "trace.json")
        t.save(p)
        back = RequestTrace.load(p)
        assert len(back) == len(t)
        for (p1, n1), (p2, n2) in zip(t, back):
            assert n1 == n2 and np.array_equal(p1, p2)
        assert back.key() == t.key()

    def test_recorder_wraps_submit(self):
        rec = TraceRecorder()
        calls = []
        submit = rec.wrap(lambda p, n: calls.append((p, n)) or "fut")
        assert submit(np.arange(4), 7) == "fut"
        assert len(rec) == 1 and len(calls) == 1
        tr = rec.trace()
        assert tr.entries[0][1] == 7

    def test_latency_budget_rejects_candidate(self):
        set_flags({"kernel_tuning_cache": "off"})

        def measure(cfg):
            if cfg["batch_size"] >= 16:  # "fast but blows p99"
                raise engine.CandidateError("p99 over budget")
            return abs(cfg["batch_size"] - 16)

        won = serving_space.tune_serving(
            "t-budget", BASE_SERVING, trace=RequestTrace.synthetic(n=4),
            measure=measure)
        assert won["batch_size"] == 8  # best that fits the budget
        assert engine.get_counters("t-budget")["search_failures"] >= 1


class TestDiskCache:
    def test_round_trips_both_spaces_across_processes(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        set_flags({"kernel_tuning_cache": path})
        trace = RequestTrace.synthetic(n=8, seed=3)
        plan_won = plan_space.tune_plan(
            "t-plan", shapes=SHAPES, mesh=_mesh(model=4),
            measure=_score_plan)
        serve_won = serving_space.tune_serving(
            "t-serve", BASE_SERVING, trace=trace, measure=_score_serving)
        data = json.load(open(path))
        assert data["version"] == engine.SCHEMA_VERSION
        spaces = sorted(e["space"] for e in data["entries"].values())
        assert spaces == ["plan", "serving"]
        assert all(e["version"] == engine.SCHEMA_VERSION
                   for e in data["entries"].values())
        # "restarted process": memory gone, disk stays — zero searches
        engine.clear_cache(memory=True, disk=False)
        engine.reset_counters()
        boom = lambda cfg: (_ for _ in ()).throw(  # noqa: E731
            AssertionError("measured after restart"))
        assert plan_space.tune_plan(
            "t-plan", shapes=SHAPES, mesh=_mesh(model=4),
            measure=boom) == plan_won
        assert serving_space.tune_serving(
            "t-serve", BASE_SERVING, trace=trace, measure=boom) == serve_won
        for name in ("t-plan", "t-serve"):
            c = engine.get_counters(name)
            assert c["disk_hits"] == 1 and c["searches"] == 0

    def test_stale_schema_entries_ignored(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        # a PR-4-era kernel-only cache: no version/space fields
        stale = {"version": 1, "entries": {
            "flash_fwd|128x64:float32|TPU v4": {
                "kernel": "flash_fwd", "config": {"block_q": 512},
                "best_ms": 1.0}}}
        with open(path, "w") as f:
            json.dump(stale, f)
        set_flags({"kernel_tuning_cache": path})
        assert engine._disk_entries() == {}  # ignored, not a crash
        won = plan_space.tune_plan(
            "t-plan", shapes=SHAPES, mesh=_mesh(model=4),
            measure=_score_plan)
        assert engine.get_counters("t-plan")["searches"] == 1
        data = json.load(open(path))
        # the stale entry was dropped on rewrite, the winner persisted
        assert all(e["version"] == engine.SCHEMA_VERSION
                   for e in data["entries"].values())
        assert [e["config"] for e in data["entries"].values()] == [won]

    def test_clear_cache_scoped_by_space(self, tmp_path):
        path = str(tmp_path / "tuning.json")
        set_flags({"kernel_tuning_cache": path})
        trace = RequestTrace.synthetic(n=8, seed=3)
        plan_space.tune_plan("t-plan", shapes=SHAPES, mesh=_mesh(model=4),
                             measure=_score_plan)
        serving_space.tune_serving("t-serve", BASE_SERVING, trace=trace,
                                   measure=_score_serving)
        engine.clear_cache(disk=True, space="serving")
        data = json.load(open(path))
        spaces = [e["space"] for e in data["entries"].values()]
        assert spaces == ["plan"]
        # memory scoped too: plan resolves as a hit, serving re-searches
        engine.reset_counters()
        plan_space.tune_plan("t-plan", shapes=SHAPES, mesh=_mesh(model=4),
                             measure=_score_plan)
        serving_space.tune_serving("t-serve", BASE_SERVING, trace=trace,
                                   measure=_score_serving)
        assert engine.get_counters("t-plan")["hits"] == 1
        assert engine.get_counters("t-serve")["searches"] == 1


class TestMeasure:
    def test_measure_ms_warm_call_plus_best_of_n(self):
        calls = []
        ms = engine.measure_ms(lambda: calls.append(1), repeats=3)
        assert len(calls) == 4  # 1 untimed warm + best-of-3
        assert ms >= 0.0


class TestServingHotPath:
    def test_k701_fires_on_post_warm_plan_search(self):
        from paddle_tpu.analysis import RetraceMonitor
        set_flags({"kernel_tuning_cache": "off"})
        with RetraceMonitor() as mon:
            engine.mark_warm()
            plan_space.tune_plan("t-plan", shapes=SHAPES,
                                 mesh=_mesh(model=4), measure=_score_plan)
        stats = mon.autotune_stats("t-plan")
        assert stats["counters"]["searches_after_warm"] == 1
        assert stats["space"] == "plan"
        k701 = [d for d in mon.diagnostics() if d.rule == "K701"]
        assert len(k701) == 1
        assert "t-plan" in k701[0].message
        assert "sharding plan" in k701[0].message

    def test_k701_silent_on_post_warm_cache_hit(self):
        from paddle_tpu.analysis import RetraceMonitor
        set_flags({"kernel_tuning_cache": "off"})
        # tuned cold (pre-warm), then resolved again on the hot path
        plan_space.tune_plan("t-plan", shapes=SHAPES, mesh=_mesh(model=4),
                             measure=_score_plan)
        with RetraceMonitor() as mon:
            engine.mark_warm()
            plan_space.tune_plan("t-plan", shapes=SHAPES,
                                 mesh=_mesh(model=4), measure=_score_plan)
        assert mon.autotune_stats("t-plan")["event"] == "hit"
        assert not [d for d in mon.diagnostics() if d.rule == "K701"]


class TestFromTuned:
    def test_generation_engine_from_tuned_maps_config(self):
        import paddle_tpu as paddle
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import GenerationEngine

        paddle.seed(7)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
            max_position=64, dropout=0.0))
        cfg = {"buckets": [8, 16], "batch_size": 3,
               "max_queue_delay_ms": 2.5, "speculative_k": 2}
        with GenerationEngine.from_tuned(model, cfg,
                                         name="tuned-test") as eng:
            assert eng._buckets == [8, 16]
            assert eng._batch == 3
            assert eng._spec_k == 2
            assert eng.name == "tuned-test"

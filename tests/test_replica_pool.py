"""paddle_tpu.serving pool + scenarios — the closed autoscaling loop.

Covers the replica lifecycle actuator end to end: dynamic fleet
membership under live traffic (``Router.add_replica`` entering through
the half-open probe/admit path, ``remove_replica`` retiring through
graceful drain without losing in-flight work, balancing staying correct
as N changes), the :class:`ReplicaPool` decision gauntlet (hysteresis
streaks, cooldown, min/max bounds, stale ``ScaleSignal.seq`` discard,
thrash detection feeding analysis rule S605), the
``Router.on_scale_signal`` hook-error accounting, ``SloEngine``
sequence stamping, scenario-generator determinism, and the open-loop
runner's loss accounting.  The real-engine disaggregation path is
exercised by ``tools/scenario_smoke.py``; the slow lane here drives a
real paged fleet through the pool for the hand-off identity check.
"""
import threading
import time
import unittest
from concurrent.futures import Future

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.analysis import RetraceMonitor
from paddle_tpu.framework import trace_events
from paddle_tpu.framework.errors import (
    InvalidArgumentError,
    TransientDeviceError,
    UnavailableError,
)
from paddle_tpu.observability.slo import Objective, ScaleSignal, SloEngine
from paddle_tpu.resilience import retry as _retry_mod
from paddle_tpu.serving import (
    DisaggServer,
    GenerationEngine,
    KVHandoff,
    ReplicaPool,
    Router,
    diurnal,
    flash_crowd,
    heavy_tail,
    poison,
    run_scenario,
)
from paddle_tpu.serving.replica import DRAINED, HEALTHY


class FakeEngine:
    """Duck-typed engine: synchronous futures by default, manual
    resolution (``manual=True``) for drain/in-flight tests."""

    def __init__(self, result="ok", manual=False, probe_fail=False):
        self.result = result
        self.manual = manual
        self.probe_fail = probe_fail
        self.pending = []
        self.calls = 0
        self.warmed = 0
        self.closed = False

    def synthetic_inputs(self):
        return [np.zeros((1,), np.float32)]

    def infer(self, inputs, timeout=None):
        if self.probe_fail:
            raise TransientDeviceError("probe failed")
        return [self.result]

    def submit(self, inputs, deadline_ms=None, **kw):
        self.calls += 1
        f = Future()
        if self.manual:
            self.pending.append((f, inputs))
        else:
            f.set_result((self.result, inputs))
        return f

    def resolve_all(self):
        for f, inputs in self.pending:
            f.set_result((self.result, inputs))
        self.pending = []

    def warmup(self):
        self.warmed += 1
        return 3

    def close(self, drain=True, timeout=None):
        self.closed = True


def _sig(direction, seq, at=0.0):
    return ScaleSignal(direction, "test", "obj", 1.0, at, seq)


def _inputs():
    return [np.zeros((1,), np.float32)]


class RouterMembershipTest(unittest.TestCase):
    """Satellite: dynamic fleet membership under live traffic."""

    def test_add_replica_enters_via_probe_and_serves(self):
        e0 = FakeEngine()
        r = Router([e0], name="mem-add")
        try:
            idx = r.add_replica(FakeEngine(result="new"))
            self.assertEqual(idx, 1)
            self.assertEqual(len(r.replicas), 2)
            self.assertEqual(r.replica(idx).state, HEALTHY)
            snap = r.stats()
            self.assertEqual(snap["replicas_added"], 1)
            self.assertGreaterEqual(snap["readmissions"], 1)
        finally:
            r.close()

    def test_add_replica_probe_failure_backs_out(self):
        r = Router([FakeEngine()], name="mem-bad")
        try:
            with self.assertRaises(UnavailableError):
                r.add_replica(FakeEngine(probe_fail=True))
            self.assertEqual(len(r.replicas), 1)
            # the backed-out index is never recycled
            idx = r.add_replica(FakeEngine())
            self.assertEqual(idx, 2)
        finally:
            r.close()

    def test_add_remove_under_live_traffic_zero_loss(self):
        """Membership churn with requests in flight: every accepted
        future resolves, balancing spreads onto the newcomer."""
        e0, e1 = FakeEngine(manual=True), FakeEngine(manual=True)
        r = Router([e0, e1], policy="least", name="mem-live")
        try:
            futs = [r.submit(_inputs()) for _ in range(4)]
            new = FakeEngine(result="new")  # instant completion
            idx = r.add_replica(new)
            # both incumbents hold 2 in-flight each; least-outstanding
            # must prefer the empty newcomer now
            futs += [r.submit(_inputs()) for _ in range(3)]
            self.assertGreaterEqual(new.calls, 3)
            e0.resolve_all()
            e1.resolve_all()
            for f in futs:
                f.result(timeout=5)
            # retire the newcomer under traffic: drain-then-remove
            self.assertTrue(r.remove_replica(idx, timeout=5))
            self.assertEqual(len(r.replicas), 2)
            self.assertEqual(r.stats()["replicas_removed"], 1)
            f = r.submit(_inputs())
            e0.resolve_all()
            e1.resolve_all()
            f.result(timeout=5)
        finally:
            r.close()

    def test_remove_drains_in_flight_work_first(self):
        """remove_replica on a replica holding in-flight work blocks in
        drain until the work resolves — nothing is dropped."""
        e0, e1 = FakeEngine(manual=True), FakeEngine(manual=True)
        r = Router([e0, e1], policy="least", name="mem-drain")
        try:
            # least-outstanding ties break by index: first submit lands
            # on e0, second on e1
            futs = [r.submit(_inputs()), r.submit(_inputs())]
            self.assertTrue(e1.pending)
            done = []
            t = threading.Thread(
                target=lambda: done.append(r.remove_replica(1, timeout=10)))
            t.start()
            time.sleep(0.15)
            self.assertTrue(t.is_alive())  # drain is waiting on e1
            e1.resolve_all()
            t.join(timeout=5)
            self.assertEqual(done, [True])
            self.assertEqual(len(r.replicas), 1)
            e0.resolve_all()
            for f in futs:
                f.result(timeout=5)
        finally:
            r.close()

    def test_remove_timeout_aborts_and_restores(self):
        e0, e1 = FakeEngine(manual=True), FakeEngine(manual=True)
        r = Router([e0, e1], policy="least", name="mem-abort")
        try:
            futs = [r.submit(_inputs()), r.submit(_inputs())]
            self.assertTrue(e1.pending)
            self.assertFalse(r.remove_replica(1, timeout=0.1))
            self.assertEqual(len(r.replicas), 2)
            self.assertEqual(r.replica(1).state, HEALTHY)
            e0.resolve_all()
            e1.resolve_all()
            for f in futs:
                f.result(timeout=5)
            self.assertTrue(r.remove_replica(1, timeout=5))
        finally:
            r.close()

    def test_p2c_stays_correct_as_fleet_changes(self):
        engines = [FakeEngine() for _ in range(2)]
        r = Router(engines, policy="p2c", name="mem-p2c")
        try:
            added = [r.add_replica(FakeEngine()) for _ in range(2)]
            for _ in range(40):
                r.submit(_inputs()).result(timeout=5)
            r.remove_replica(added[0], timeout=5)
            r.remove_replica(0, timeout=5)
            for _ in range(40):
                r.submit(_inputs()).result(timeout=5)
            self.assertEqual(len(r.replicas), 2)
        finally:
            r.close()

    def test_scale_hook_errors_counted_not_raised(self):
        """Satellite: a throwing scale hook is swallowed AND visible."""
        r = Router([FakeEngine()], name="hook-err")
        try:
            seen = []
            r.register_scale_hook(
                lambda s: (_ for _ in ()).throw(RuntimeError("boom")))
            r.register_scale_hook(seen.append)
            r.on_scale_signal(_sig("up", 1))
            r.on_scale_signal(_sig("steady", 2))
            self.assertEqual(len(seen), 2)  # later hooks still ran
            snap = r.stats()
            self.assertEqual(snap["scale_hook_errors"], 2)
            self.assertEqual(snap["scale_up_signals"], 1)
        finally:
            r.close()


class ReplicaPoolTest(unittest.TestCase):
    """The actuator's decision gauntlet, on an injected clock."""

    def _pool(self, **kw):
        self.t = [100.0]
        self.made = []

        def factory():
            e = FakeEngine()
            self.made.append(e)
            return e

        self.router = Router([FakeEngine()], name=f"pl-{id(self)}")
        defaults = dict(min_replicas=1, max_replicas=3, cooldown_s=10.0,
                        up_consecutive=1, down_consecutive=2,
                        thrash_window_s=20.0, async_actions=False,
                        clock=lambda: self.t[0])
        defaults.update(kw)
        return ReplicaPool(self.router, factory, **defaults)

    def test_scale_up_warms_before_admission(self):
        pool = self._pool()
        try:
            self.router.on_scale_signal(_sig("up", 1))
            self.assertEqual(len(self.router.replicas), 2)
            self.assertEqual(self.made[0].warmed, 1)
            snap = pool.stats()
            self.assertEqual(snap["scale_ups"], 1)
            self.assertEqual(snap["warmup_compiles"], 3)
        finally:
            self.router.close()

    def test_cooldown_bounds_and_hysteresis(self):
        pool = self._pool()
        try:
            self.router.on_scale_signal(_sig("up", 1))
            self.router.on_scale_signal(_sig("up", 2))  # inside cooldown
            self.assertEqual(pool.stats()["deferred_cooldown"], 1)
            self.t[0] += 11
            self.router.on_scale_signal(_sig("up", 3))
            self.assertEqual(len(self.router.replicas), 3)
            self.t[0] += 11
            self.router.on_scale_signal(_sig("up", 4))  # at max
            self.assertEqual(pool.stats()["deferred_bounds"], 1)
            self.t[0] += 11
            self.router.on_scale_signal(_sig("down", 5))  # streak 1 < 2
            self.assertEqual(pool.stats()["deferred_streak"], 1)
            self.router.on_scale_signal(_sig("down", 6))
            self.assertEqual(len(self.router.replicas), 2)
            self.assertEqual(pool.stats()["scale_downs"], 1)
            # the pool retires its own engines and closes them
            self.assertTrue(self.made[-1].closed)
        finally:
            self.router.close()

    def test_stale_seq_discarded(self):
        pool = self._pool()
        try:
            self.router.on_scale_signal(_sig("up", 5))
            self.t[0] += 11
            self.router.on_scale_signal(_sig("up", 5))  # replayed
            self.router.on_scale_signal(_sig("up", 3))  # reordered
            self.assertEqual(pool.stats()["stale_signals"], 2)
            self.assertEqual(len(self.router.replicas), 2)
            # unsequenced signals (seq -1) are never treated as stale
            self.router.on_scale_signal(_sig("up", -1))
            self.assertEqual(len(self.router.replicas), 3)
        finally:
            self.router.close()

    def test_steady_resets_streaks(self):
        pool = self._pool(down_consecutive=2)
        try:
            self.t[0] += 11
            self.router.on_scale_signal(_sig("up", 1))
            self.t[0] += 11
            self.router.on_scale_signal(_sig("down", 2))
            self.router.on_scale_signal(_sig("steady", 3))
            self.router.on_scale_signal(_sig("down", 4))
            # streak was reset by steady: still only 1 consecutive down
            self.assertEqual(len(self.router.replicas), 2)
            self.assertEqual(pool.stats()["deferred_streak"], 2)
        finally:
            self.router.close()

    def test_thrash_detection_feeds_s605(self):
        was_warm = _retry_mod._warm
        _retry_mod.mark_warm()
        mon = RetraceMonitor().install()
        pool = self._pool(cooldown_s=0.0, down_consecutive=1,
                          thrash_window_s=1e9)
        try:
            self.router.on_scale_signal(_sig("up", 1))
            self.router.on_scale_signal(_sig("down", 2))  # reversal 1
            self.router.on_scale_signal(_sig("up", 3))    # reversal 2
            snap = pool.stats()
            self.assertEqual(snap["thrash_events"], 2)
            self.assertEqual(snap["thrash_events_after_warm"], 2)
            rules = [d.rule for d in mon.diagnostics()]
            self.assertIn("S605", rules)
            self.assertIn(pool.name, mon.pool_stats())
        finally:
            _retry_mod._warm = was_warm
            mon.uninstall()
            self.router.close()

    def test_no_s605_below_two_thrashes(self):
        was_warm = _retry_mod._warm
        _retry_mod.mark_warm()
        mon = RetraceMonitor().install()
        pool = self._pool(cooldown_s=0.0, down_consecutive=1,
                          thrash_window_s=1e9)
        try:
            self.router.on_scale_signal(_sig("up", 1))
            self.router.on_scale_signal(_sig("down", 2))  # one reversal
            self.assertEqual(pool.stats()["thrash_events_after_warm"], 1)
            self.assertNotIn("S605",
                             [d.rule for d in mon.diagnostics()])
        finally:
            _retry_mod._warm = was_warm
            mon.uninstall()
            self.router.close()

    def test_drain_abort_keeps_replica(self):
        """A replica that cannot drain in time stays in the fleet."""
        t = [0.0]
        e0 = FakeEngine(manual=True)
        stuck = FakeEngine(manual=True)
        router = Router([e0, stuck], policy="least", name="pl-stuck")
        pool = ReplicaPool(router, FakeEngine, min_replicas=1,
                           max_replicas=3, cooldown_s=0.0,
                           up_consecutive=1, down_consecutive=1,
                           drain_timeout_s=0.1, async_actions=False,
                           clock=lambda: t[0])
        try:
            futs = [router.submit(_inputs()), router.submit(_inputs())]
            self.assertTrue(stuck.pending)
            router.on_scale_signal(_sig("down", 1))
            snap = pool.stats()
            self.assertEqual(snap["drain_aborts"], 1)
            self.assertEqual(snap["scale_downs"], 0)
            self.assertEqual(len(router.replicas), 2)
            e0.resolve_all()
            stuck.resolve_all()
            for f in futs:
                f.result(timeout=5)
        finally:
            router.close()

    def test_closed_pool_ignores_signals(self):
        pool = self._pool()
        try:
            pool.close()
            self.router.on_scale_signal(_sig("up", 1))
            self.assertEqual(len(self.router.replicas), 1)
            self.assertEqual(pool.stats()["scale_ups"], 0)
        finally:
            self.router.close()

    def test_pool_publishes_trace_events(self):
        seen = {}
        def listener(site, info):
            if site[0] == "pool":
                seen[site[1]] = info
        trace_events.register(listener)
        pool = self._pool()
        try:
            self.router.on_scale_signal(_sig("up", 1))
            self.assertIn(pool.name, seen)
            self.assertEqual(seen[pool.name]["scale_ups"], 1)
        finally:
            trace_events.unregister(listener)
            self.router.close()


class SloSequenceTest(unittest.TestCase):
    """Satellite: ScaleSignal.seq is stamped monotonically per tick."""

    def test_seq_monotonic_across_ticks(self):
        eng = SloEngine([Objective.latency("p99", threshold_ms=50.0,
                                           engine="nosuch")])
        sigs = []
        eng.on_scale(sigs.append)
        try:
            for _ in range(3):
                eng.tick()
            self.assertEqual([s.seq for s in sigs], [1, 2, 3])
        finally:
            eng.close()

    def test_default_seq_is_unsequenced(self):
        self.assertEqual(ScaleSignal("up", "r", "o", 1.0, 0.0).seq, -1)


class FakeTarget:
    """Instant-result submit target for runner accounting tests."""

    def __init__(self, max_len=64):
        self.max_len = max_len
        self.calls = 0

    def submit(self, prompt, max_new_tokens=32, deadline_ms=None, **kw):
        self.calls += 1
        if len(prompt) > self.max_len:
            raise InvalidArgumentError("prompt exceeds largest bucket")
        f = Future()
        f.set_result(np.arange(max_new_tokens, dtype=np.int32))
        return f


class ScenarioTest(unittest.TestCase):
    def test_generators_deterministic(self):
        for gen in (diurnal, flash_crowd, heavy_tail, poison):
            a = gen(duration_s=5.0, seed=7)
            b = gen(duration_s=5.0, seed=7)
            c = gen(duration_s=5.0, seed=8)
            self.assertEqual(a, b)
            self.assertNotEqual(a.events, c.events)
            self.assertTrue(all(x.t <= y.t for x, y in
                                zip(a.events, a.events[1:])))

    def test_runner_accounting_and_poison(self):
        scn = poison(duration_s=2.0, rps=8.0, poison_frac=0.4,
                     oversize_len=999, seed=3)
        tgt = FakeTarget(max_len=64)
        ticks = []
        rep = run_scenario(tgt, scn, time_scale=0.01, tick=ticks.append,
                           tick_s=0.5)
        n_poison = sum(1 for e in scn.events if e.poison)
        self.assertGreater(n_poison, 0)
        self.assertEqual(rep["rejected"], n_poison)
        self.assertEqual(rep["poison_accepted"], 0)
        self.assertEqual(rep["lost"], 0)
        self.assertEqual(rep["failed"], 0)
        self.assertEqual(rep["accepted"], len(scn.events) - n_poison)
        self.assertEqual(rep["completed"], rep["accepted"])
        self.assertEqual(len(rep["records"]), len(scn.events))
        self.assertEqual(ticks, [0.5, 1.0, 1.5, 2.0])

    def test_runner_prompts_reproducible(self):
        scn = diurnal(duration_s=2.0, seed=5)
        tgt = FakeTarget()
        r1 = run_scenario(tgt, scn, time_scale=0.0)
        r2 = run_scenario(tgt, scn, time_scale=0.0)
        self.assertEqual([r["tokens"] for r in r1["records"]],
                         [r["tokens"] for r in r2["records"]])


@pytest.mark.slow
class PoolEndToEndSlowTest(unittest.TestCase):
    """Real paged fleet: pool-grown replicas serve bit-identical tokens,
    and the prefill->decode hand-off survives a scenario sweep."""

    @classmethod
    def _model(cls):
        pt.seed(11)
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        m = GPTForCausalLM(GPTConfig(vocab_size=97, hidden_size=64,
                                     num_layers=2, num_heads=4,
                                     max_position=256, dropout=0.0))
        m.eval()
        return m

    def test_handoff_identity_through_disagg_server(self):
        model = self._model()

        def eng(role, name):
            return GenerationEngine(model, prompt_buckets=[8, 16],
                                    batch_size=2, continuous=True,
                                    paged=True, kv_page_size=16,
                                    role=role, name=name)

        colo = eng("any", "e2e-colo")
        ds = DisaggServer(eng("prefill", "e2e-pre"),
                          eng("decode", "e2e-dec"), name="e2e-ds")
        colo.warmup()
        ds.warmup()
        try:
            rng = np.random.RandomState(0)
            for L, N in ((5, 6), (12, 4), (3, 1), (16, 8)):
                prompt = rng.randint(1, 97, size=(L,)).astype(np.int32)
                ref = colo.generate(prompt, N, timeout=60)
                got = ds.generate(prompt, max_new_tokens=N, timeout=60)
                np.testing.assert_array_equal(ref, got)
            self.assertEqual(ds.stats()["handoffs"], 4)
            h = ds.prefill.submit(np.arange(1, 5, dtype=np.int32), 4,
                                  handoff=True).result(60)
            self.assertIsInstance(h, KVHandoff)
        finally:
            colo.close()
            ds.close()

    def test_pool_grows_real_fleet_under_scenario(self):
        model = self._model()
        made = []

        def factory():
            e = GenerationEngine(model, prompt_buckets=[8, 16],
                                 batch_size=2, continuous=True, paged=True,
                                 kv_page_size=16,
                                 name=f"e2e-g{len(made)}")
            made.append(e)
            return e

        router = Router([factory()], name="e2e-rt")
        pool = ReplicaPool(router, factory, min_replicas=1, max_replicas=2,
                           cooldown_s=0.5, up_consecutive=1,
                           down_consecutive=1, async_actions=False,
                           name="e2e-pool")
        router.warmup()
        try:
            seq = [0]

            def tick(_t):
                seq[0] += 1
                router.on_scale_signal(_sig("up", seq[0], at=time.time()))

            scn = diurnal(duration_s=3.0, base_rps=4.0, peak_rps=8.0,
                          prompt_len=(4, 12), max_new_tokens=(2, 4),
                          seed=17)
            rep = run_scenario(router, scn, tick=tick, tick_s=0.5,
                               result_timeout_s=120.0)
            self.assertEqual(rep["lost"], 0)
            self.assertEqual(rep["failed"], 0)
            self.assertEqual(pool.stats()["scale_ups"], 1)  # bounded at 2
            self.assertEqual(len(router.replicas), 2)
            # the pool-grown replica warmed off-path: compile set closed
            for e in made:
                self.assertEqual(e.compile_count, len([8, 16]) + 3)
        finally:
            pool.close()
            router.close(timeout=30)


if __name__ == "__main__":
    unittest.main()

"""Quantized serving path: KV page numerics, CoW on quantized pools,
hot-swap without recompiles, and the serving-space quantization dial.

The expensive end-to-end properties (margin-accounted token agreement,
equal-HBM resident slots, rolling swap across a router) live in
tools/quant_smoke.py; these are the cheap unit contracts underneath.
"""
import os
import tempfile

import numpy as np
import pytest

import jax
import jax.monitoring
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import slim
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, _quantize_kv
from paddle_tpu.serving import GenerationEngine

CACHE, PAGE = 32, 8

_XLA_COMPILES = [0]
jax.monitoring.register_event_listener(
    lambda name, **kw: _XLA_COMPILES.__setitem__(0, _XLA_COMPILES[0] + 1)
    if name == "/jax/compilation_cache/compile_requests_use_cache" else None)


def _model(seed=3):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=53, hidden_size=32, num_layers=2,
                    num_heads=4, max_position=CACHE, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestQuantizedKVPages:
    def test_quantize_kv_roundtrip_bounds(self):
        rng = np.random.RandomState(0)
        t = jnp.asarray(rng.randn(6, 4, 8).astype(np.float32))
        amax = np.max(np.abs(np.asarray(t)), axis=-1)  # [N, H]
        for qdt, tol in ((jnp.int8, amax / 127 / 2 + 1e-6),
                         (jnp.float8_e4m3fn, amax * 0.0625)):
            q, s = _quantize_kv(t, qdt)
            assert q.dtype == jnp.dtype(qdt)
            assert s.shape == (6, 4) and s.dtype == jnp.float32
            recon = np.asarray(q, np.float32) * np.asarray(s)[..., None]
            err = np.max(np.abs(recon - np.asarray(t)), axis=-1)
            assert (err <= tol).all()

    def test_fp8_overflow_clips_not_nan(self):
        # e4m3fn has no inf: an unclipped cast of the abs-max element
        # would round up past 448 and land on NaN
        q, s = _quantize_kv(jnp.full((1, 1, 4), 1e4, jnp.float32),
                            jnp.float8_e4m3fn)
        assert np.isfinite(np.asarray(q, np.float32)).all()

    def test_pool_gather_scatter_preserves_bits(self):
        # hand-off contract: quantized pages move pool→pool without a
        # float round-trip — the adopting pool stores the same bits
        gpt = _model().gpt
        rng = np.random.RandomState(1)
        pool_a = gpt.init_paged_cache(4, PAGE, dtype=jnp.int8)
        kv = jnp.asarray(rng.randn(PAGE, 4, 8).astype(np.float32))
        q, s = _quantize_kv(kv, jnp.int8)
        layers = []
        for l in pool_a["layers"]:
            layers.append({
                "k": l["k"].at[1].set(jnp.transpose(q, (1, 0, 2))),
                "v": l["v"].at[1].set(jnp.transpose(q, (1, 0, 2))),
                "k_scale": l["k_scale"].at[1].set(jnp.transpose(s)),
                "v_scale": l["v_scale"].at[1].set(jnp.transpose(s)),
            })
        pool_a = {"layers": layers}
        exported = gpt.gather_pages(pool_a, jnp.asarray([1], jnp.int32))
        assert isinstance(exported, tuple)  # (pages, scales) pair
        pages, scales = exported
        assert pages.dtype == jnp.int8 and scales.dtype == jnp.float32
        pool_b = gpt.init_paged_cache(4, PAGE, dtype=jnp.int8)
        pool_b = gpt.scatter_pages(pool_b, exported,
                                   jnp.asarray([2], jnp.int32))
        re_pages, re_scales = gpt.gather_pages(
            pool_b, jnp.asarray([2], jnp.int32))
        np.testing.assert_array_equal(np.asarray(re_pages),
                                      np.asarray(pages))
        np.testing.assert_array_equal(np.asarray(re_scales),
                                      np.asarray(scales))

    def test_scatter_quantized_pool_requires_scales(self):
        gpt = _model().gpt
        pool = gpt.init_paged_cache(4, PAGE, dtype=jnp.int8)
        bare = jnp.zeros((2, 2, 1, 4, PAGE, 8), jnp.int8)
        with pytest.raises(ValueError):
            gpt.scatter_pages(pool, bare, jnp.asarray([0], jnp.int32))

    def test_copy_pages_covers_scale_planes(self):
        # CoW on a quantized pool: the page copy must move k/v AND their
        # scale planes, or the copied page dequantizes with zero scales
        gpt = _model().gpt
        pool = gpt.init_paged_cache(4, PAGE, dtype=jnp.int8)
        l0 = pool["layers"][0]
        l0 = dict(l0, k=l0["k"].at[0].set(7),
                  k_scale=l0["k_scale"].at[0].set(0.5))
        pool = {"layers": [l0] + pool["layers"][1:]}
        out = gpt.copy_pages(pool, jnp.asarray([0], jnp.int32),
                             jnp.asarray([3], jnp.int32))
        ol0 = out["layers"][0]
        np.testing.assert_array_equal(np.asarray(ol0["k"][3]),
                                      np.asarray(l0["k"][0]))
        np.testing.assert_array_equal(np.asarray(ol0["k_scale"][3]),
                                      np.asarray(l0["k_scale"][0]))


class TestQuantizedEngine:
    def test_bad_mode_rejected(self):
        from paddle_tpu.framework.errors import InvalidArgumentError
        with pytest.raises(InvalidArgumentError):
            GenerationEngine(_model(), prompt_buckets=[16], batch_size=2,
                             cache_len=CACHE, quantized="int4")

    def test_serving_space_has_quantization_dial(self):
        from paddle_tpu.tuning.serving_space import DIAL_SWEEPS
        assert DIAL_SWEEPS["quantization"] == ("none", "int8", "fp8")

    def test_hot_swap_zero_recompile(self, tmp_path):
        # swap_weights with an export_quantized artifact: outputs change,
        # XLA compiles nothing (same tree, same per-leaf shape/dtype)
        donor = _model(seed=11)
        artifact = slim.export_quantized(
            donor, os.path.join(str(tmp_path), "donor"), mode="int8")
        prompt = np.arange(1, 9, dtype=np.int32)
        with GenerationEngine(_model(), prompt_buckets=[16], batch_size=2,
                              cache_len=CACHE, continuous=True,
                              speculative_k=0, quantized="int8",
                              name="tq-swap") as eng:
            eng.warmup()
            before = eng.submit(prompt, 4).result(60).tolist()
            x0 = _XLA_COMPILES[0]
            eng.swap_weights(artifact)
            after = eng.submit(prompt, 4).result(60).tolist()
            assert _XLA_COMPILES[0] - x0 == 0
            assert before != after  # donor weights actually serving
            assert eng.stats()["quantization"] == "int8"

    def test_swap_rejects_mode_mismatch(self, tmp_path):
        from paddle_tpu.framework.errors import InvalidArgumentError
        donor = _model(seed=11)
        artifact = slim.export_quantized(
            donor, os.path.join(str(tmp_path), "donor8"), mode="fp8")
        with GenerationEngine(_model(), prompt_buckets=[16], batch_size=2,
                              cache_len=CACHE, continuous=True,
                              speculative_k=0, quantized="int8",
                              name="tq-mismatch") as eng:
            with pytest.raises(InvalidArgumentError):
                eng.swap_weights(artifact)


class TestQuantizedMatmulKernel:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_all_candidates_match_dequant_reference(self, mode):
        # acceptance gate: every autotune tile candidate computes the
        # same answer as dequantize-then-matmul (fwd; inference path)
        from paddle_tpu.ops.quantized_matmul import (_qmm_pallas, _space,
                                                     quantize_activations)
        from paddle_tpu.slim.quantization import _quantize_weight

        M, K, N = 256, 64, 256
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(M, K).astype(np.float32))
        w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1)
        bias = jnp.asarray(rng.randn(N).astype(np.float32) * 0.01)
        xq, x_scale = quantize_activations(x, mode)
        wq, w_scale = _quantize_weight(w, mode)
        scale = (x_scale * w_scale).astype(jnp.float32)  # folded epilogue
        ref = (np.asarray(xq, np.float32) @ np.asarray(wq, np.float32)
               ) * np.asarray(scale) + np.asarray(bias)

        cands = _space(xq, wq, scale, bias)
        assert len(cands) > 1, "want a real candidate sweep"
        for cfg in cands:
            out = np.asarray(_qmm_pallas(xq, wq, scale, bias, **cfg))
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5,
                                       err_msg=str(cfg))

"""fleet.metrics — globally-reduced eval metrics.

Reference capability: distributed/fleet/metrics/metric.py (gloo
all_reduce over scope tensors).  Single-process aggregation reduces to
identity, so correctness is checked against direct numpy formulas; the
bucketed AUC is validated against an exact rank-based AUC.
"""
import numpy as np
import pytest

from paddle_tpu.distributed.fleet import metrics


class TestReductions:
    def test_sum_max_min_identity_single_process(self):
        x = np.array([1.0, 2.0, 3.0])
        np.testing.assert_allclose(metrics.sum(x), x)
        np.testing.assert_allclose(metrics.max(x), x)
        np.testing.assert_allclose(metrics.min(x), x)

    def test_scalar_inputs(self):
        assert float(metrics.sum(2.5)) == 2.5

    def test_mae_mse_rmse_acc(self):
        # 4 instances with abs errors 1,2,3,4 → mae 2.5; sq errors → mse
        assert metrics.mae(np.array([10.0]), 4) == 2.5
        assert metrics.mse(np.array([30.0]), 4) == 7.5
        np.testing.assert_allclose(metrics.rmse(np.array([30.0]), 4),
                                   np.sqrt(7.5))
        assert metrics.acc(np.array([3.0]), np.array([4.0])) == 0.75

    def test_zero_denominators(self):
        assert metrics.mae(np.array([0.0]), 0) == 0.0
        assert metrics.acc(np.array([0.0]), np.array([0.0])) == 0.0


class TestAuc:
    @staticmethod
    def _exact_auc(scores, labels):
        """P(score_pos > score_neg) + 0.5 P(equal) by brute force."""
        pos = scores[labels == 1]
        neg = scores[labels == 0]
        wins = (pos[:, None] > neg[None, :]).sum()
        ties = (pos[:, None] == neg[None, :]).sum()
        return (wins + 0.5 * ties) / (len(pos) * len(neg))

    def test_bucketed_matches_exact(self):
        rng = np.random.RandomState(0)
        n, buckets = 5000, 1000
        labels = (rng.uniform(size=n) < 0.3).astype(int)
        # separable-ish scores so AUC is far from 0.5
        scores = np.clip(rng.normal(0.35 + 0.25 * labels, 0.15), 0, 0.999)
        idx = (scores * buckets).astype(int)
        stat_pos = np.bincount(idx[labels == 1], minlength=buckets)
        stat_neg = np.bincount(idx[labels == 0], minlength=buckets)
        got = metrics.auc(stat_pos.astype(float), stat_neg.astype(float))
        # bucketing quantizes scores → compare against the exact AUC of the
        # QUANTIZED scores, which the bucket trapezoid reproduces exactly
        want = self._exact_auc(idx, labels)
        np.testing.assert_allclose(got, want, rtol=1e-12)
        assert got > 0.8

    def test_reference_shape_convention(self):
        # the reference passes [1, num_bucket] arrays (metric.py:202)
        stat_pos = np.array([[0.0, 1.0, 2.0]])
        stat_neg = np.array([[2.0, 1.0, 0.0]])
        got = metrics.auc(stat_pos, stat_neg)
        scores = np.array([1, 2, 2, 0, 0, 1])
        labels = np.array([1, 1, 1, 0, 0, 0])
        np.testing.assert_allclose(got, self._exact_auc(scores, labels))

    def test_degenerate_single_class(self):
        assert metrics.auc(np.zeros(10), np.ones(10)) == 0.5
        assert metrics.auc(np.ones(10), np.zeros(10)) == 0.5

"""Numeric-vs-analytic gradient harness.

TPU-native equivalent of the reference's OpTest.check_grad
(python/paddle/fluid/tests/unittests/op_test.py:1282 — compares analytic grad
kernels against central finite differences, delta=0.005).  Here the analytic
side is jax.grad over the same function, which exercises our op
implementations' VJPs through XLA.
"""
import numpy as np
import jax
import jax.numpy as jnp


def numeric_grad(fn, args, idx=0, delta=5e-3):
    """Central finite differences w.r.t. args[idx] of scalar fn(*args)."""
    args = [np.asarray(a, np.float64) for a in args]
    x = args[idx]
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        f_hi = float(fn(*[jnp.asarray(a) for a in args]))
        flat[i] = orig - delta
        f_lo = float(fn(*[jnp.asarray(a) for a in args]))
        flat[i] = orig
        gflat[i] = (f_hi - f_lo) / (2 * delta)
    return g


def check_grad(fn, args, idx=0, rtol=1e-2, atol=1e-3, delta=5e-3):
    """Assert jax.grad(fn) matches finite differences (f64 for accuracy)."""
    args64 = [jnp.asarray(np.asarray(a, np.float64)) for a in args]
    analytic = np.asarray(jax.grad(fn, argnums=idx)(*args64))
    numeric = numeric_grad(fn, args, idx=idx, delta=delta)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)

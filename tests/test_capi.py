"""C inference ABI: a real C program links the shared library, loads an
exported model and matches the Python predictor's output.

Reference capability: paddle/fluid/inference/capi (C prediction ABI) +
go/paddle/predictor.go (its cgo wrapper — same wrapping applies here).
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import Config, create_predictor, save_inference_model
from paddle_tpu.native import c_api_path
from paddle_tpu.static import InputSpec

C_SRC = r"""
#include <stdio.h>
#include <stdlib.h>
#include "paddle_tpu_c.h"

int main(int argc, char** argv) {
    void* pred = pd_predictor_create(argv[1], argv[2]);
    if (!pred) { fprintf(stderr, "create: %s\n", pd_last_error()); return 2; }
    float in[2 * 8];
    for (int i = 0; i < 16; i++) in[i] = (float)i / 16.0f - 0.5f;
    const float* inputs[1] = {in};
    int64_t shape[2] = {2, 8};
    const int64_t* shapes[1] = {shape};
    int ndims[1] = {2};
    float* out = NULL;
    int64_t out_shape[8];
    int out_ndim = 0;
    int rc = pd_predictor_run(pred, inputs, shapes, ndims, 1,
                              &out, out_shape, 8, &out_ndim);
    if (rc != 0) { fprintf(stderr, "run: %s\n", pd_last_error()); return 3; }
    long long numel = 1;
    for (int d = 0; d < out_ndim; d++) numel *= out_shape[d];
    printf("%d\n", out_ndim);
    for (int d = 0; d < out_ndim; d++) printf("%lld\n", (long long)out_shape[d]);
    for (long long i = 0; i < numel; i++) printf("%.6f\n", out[i]);
    pd_free(out);
    pd_predictor_destroy(pred);
    return 0;
}
"""


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    td = tmp_path_factory.mktemp("capi_model")
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    prefix = os.path.join(str(td), "m")
    save_inference_model(prefix, net, [InputSpec([None, 8], "float32")],
                         platforms=("cpu",))
    return prefix


def test_c_program_matches_python_predictor(exported_model, tmp_path):
    lib = c_api_path()
    hdr_dir = os.path.dirname(os.path.abspath(
        __import__("paddle_tpu.native", fromlist=["x"]).__file__))
    csrc = tmp_path / "main.c"
    csrc.write_text(C_SRC)
    exe = tmp_path / "capi_demo"
    build = subprocess.run(
        ["gcc", str(csrc), lib, f"-I{hdr_dir}", "-o", str(exe),
         f"-Wl,-rpath,{os.path.dirname(lib)}"],
        capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    env = dict(os.environ,
               PYTHONPATH=os.getcwd() + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               PADDLE_TPU_C_PLATFORM="cpu")
    run = subprocess.run(
        [str(exe), exported_model + ".pdmodel", exported_model + ".pdiparams"],
        capture_output=True, text=True, env=env, timeout=300)
    assert run.returncode == 0, (run.stdout, run.stderr)
    lines = run.stdout.strip().splitlines()
    ndim = int(lines[0])
    shape = tuple(int(v) for v in lines[1:1 + ndim])
    vals = np.array([float(v) for v in lines[1 + ndim:]],
                    np.float32).reshape(shape)

    x = (np.arange(16, dtype=np.float32) / 16.0 - 0.5).reshape(2, 8)
    cfg = Config(exported_model + ".pdmodel", exported_model + ".pdiparams")
    ref = np.asarray(create_predictor(cfg).run([x])[0])
    assert shape == ref.shape
    np.testing.assert_allclose(vals, ref, rtol=1e-4, atol=1e-5)


def test_create_error_reported(tmp_path):
    lib = c_api_path()
    assert os.path.exists(lib)
    # error surface is covered through the C program path above; here just
    # assert the library exports the full ABI
    out = subprocess.run(["nm", "-D", lib], capture_output=True, text=True)
    for sym in ("pd_predictor_create", "pd_predictor_run",
                "pd_predictor_destroy", "pd_last_error", "pd_free"):
        assert sym in out.stdout

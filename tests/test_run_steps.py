"""Fused multi-step execution: Executor.run_steps / StaticFunction.run_steps.

The contract under test (ISSUE 2): N chained optimizer steps inside one
jitted lax.scan produce params / optimizer state / buffers numerically
matching N sequential Executor.run calls — with the per-step host work
(lr schedules, RNG keys) moved into the traced loop — while issuing exactly
ONE device dispatch per chain.  Plus the compile-cache hygiene riding along:
the per-Executor LRU bound, hit/miss/eviction counters on trace_events, and
the analysis.retrace R403 cache-churn rule.
"""
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.framework import trace_events
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.static.graph import reset_default_programs


@pytest.fixture(autouse=True)
def _fresh_programs():
    import paddle_tpu as paddle

    paddle.seed(0)  # builder param init draws from the global generator
    reset_default_programs()
    yield
    reset_default_programs()


def _key(name):
    # param names embed the program idx (_<idx>_<prefix>_<i>); strip it so
    # params from independently-built identical programs can be compared
    return name.split("_", 2)[2]


def _params(prog):
    return {_key(k): np.asarray(v) for k, v in prog.parameters_numpy().items()}


def _mlp(opt_factory):
    import paddle_tpu as paddle

    paddle.seed(0)  # identical init across the programs a test builds
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 13])
        y = fluid.data("y", [-1, 1])
        h = fluid.layers.fc(x, 8, act="relu")
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = opt_factory()
        opt.minimize(loss)
    return main, startup, loss, opt


def _batches(n, bs=8, din=13):
    rng = np.random.RandomState(0)
    return (rng.rand(n, bs, din).astype(np.float32),
            rng.rand(n, bs, 1).astype(np.float32))


class TestRunStepsEquivalence:
    def _run_both(self, opt_factory, n=5):
        X, Y = _batches(n)
        main, startup, loss, opt_a = _mlp(opt_factory)
        exe = fluid.Executor()
        exe.run(startup)
        seq = [float(exe.run(main, feed={"x": X[t], "y": Y[t]},
                             fetch_list=[loss])[0]) for t in range(n)]

        main2, startup2, loss2, opt_b = _mlp(opt_factory)
        exe2 = fluid.Executor()
        exe2.run(startup2)
        fused, = exe2.run_steps(main2, feed={"x": X, "y": Y},
                                fetch_list=[loss2])
        return seq, np.asarray(fused), _params(main), _params(main2), \
            opt_a, opt_b, exe2

    def test_sgd_matches_sequential(self):
        seq, fused, pa, pb, _, _, exe2 = self._run_both(
            lambda: fluid.optimizer.SGD(learning_rate=0.1))
        np.testing.assert_allclose(fused.ravel(), seq, rtol=1e-5, atol=1e-6)
        for k, v in pb.items():
            np.testing.assert_allclose(v, pa[k], rtol=1e-5, atol=1e-6)

    def test_adam_matches_sequential(self):
        seq, fused, pa, pb, _, _, _ = self._run_both(
            lambda: fluid.optimizer.AdamOptimizer(learning_rate=0.01))
        np.testing.assert_allclose(fused.ravel(), seq, rtol=1e-5, atol=1e-6)
        for k, v in pb.items():
            np.testing.assert_allclose(v, pa[k], rtol=1e-5, atol=1e-6)

    def test_one_dispatch_per_chain(self):
        X, Y = _batches(6)
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)  # empty startup: no device dispatch
        assert exe.dispatches == 0
        exe.run_steps(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        assert exe.dispatches == 1
        exe.run_steps(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        assert exe.dispatches == 2
        stats = exe.cache_stats()
        assert stats["hits"] == 1 and stats["misses"] == 1

    def test_graph_mode_scheduler_matches_sequential(self):
        # StepDecay has a closed-form value_at -> the lr is computed
        # in-graph as value_at(base_epoch + t)
        import paddle_tpu.optimizer as popt

        assert popt.lr.StepDecay(0.1, step_size=2).supports_in_graph()
        seq, fused, pa, pb, opt_a, opt_b, _ = self._run_both(
            lambda: fluid.optimizer.SGD(
                popt.lr.StepDecay(0.1, step_size=2, gamma=0.5)), n=6)
        np.testing.assert_allclose(fused.ravel(), seq, rtol=1e-5, atol=1e-6)
        for k, v in pb.items():
            np.testing.assert_allclose(v, pa[k], rtol=1e-5, atol=1e-6)
        # host scheduler advanced N steps, same as the sequential lane
        assert opt_b.lr_scheduler.last_epoch == opt_a.lr_scheduler.last_epoch

    def test_host_fallback_scheduler_matches_sequential(self):
        # LambdaDecay runs arbitrary Python -> no in-graph form; the lr
        # sequence is precomputed on host and scanned
        import paddle_tpu.optimizer as popt

        assert not popt.lr.LambdaDecay(
            0.1, lr_lambda=lambda e: 0.9 ** e).supports_in_graph()
        seq, fused, pa, pb, opt_a, opt_b, _ = self._run_both(
            lambda: fluid.optimizer.SGD(
                popt.lr.LambdaDecay(0.1, lr_lambda=lambda e: 0.9 ** e)), n=6)
        np.testing.assert_allclose(fused.ravel(), seq, rtol=1e-5, atol=1e-6)
        for k, v in pb.items():
            np.testing.assert_allclose(v, pa[k], rtol=1e-5, atol=1e-6)
        assert opt_b.lr_scheduler.last_epoch == opt_a.lr_scheduler.last_epoch

    def test_bn_buffers_match_sequential(self):
        import paddle_tpu as paddle

        def build():
            paddle.seed(0)
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.data("x", [-1, 6])
                y = fluid.data("y", [-1, 1])
                b = fluid.layers.batch_norm(fluid.layers.fc(x, 8))
                pred = fluid.layers.fc(b, 1)
                loss = fluid.layers.mean(
                    fluid.layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
            return main, startup, loss

        n = 4
        rng = np.random.RandomState(0)
        X = rng.rand(n, 16, 6).astype(np.float32)
        Y = rng.rand(n, 16, 1).astype(np.float32)

        main, startup, loss = build()
        exe = fluid.Executor()
        exe.run(startup)
        bufs0 = {k: np.asarray(v) for k, v in main.buffers.items()}
        for t in range(n):
            exe.run(main, feed={"x": X[t], "y": Y[t]}, fetch_list=[loss])
        seq_bufs = {_key(k): np.asarray(v) for k, v in main.buffers.items()}
        assert any(not np.array_equal(bufs0[k], np.asarray(v))
                   for k, v in main.buffers.items())  # stats really moved

        main2, startup2, loss2 = build()
        exe2 = fluid.Executor()
        exe2.run(startup2)
        exe2.run_steps(main2, feed={"x": X, "y": Y}, fetch_list=[loss2])
        for k, v in main2.buffers.items():
            np.testing.assert_allclose(np.asarray(v), seq_bufs[_key(k)],
                                       rtol=1e-5, atol=1e-6)
        for k, v in _params(main2).items():
            np.testing.assert_allclose(v, _params(main)[k],
                                       rtol=1e-5, atol=1e-6)


class TestRunStepsAPI:
    def test_fetch_every_subsamples(self):
        X, Y = _batches(6)
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)
        all_losses, = exe.run_steps(main, feed={"x": X, "y": Y},
                                    fetch_list=[loss])

        main2, startup2, loss2, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe2 = fluid.Executor()
        exe2.run(startup2)
        sub, = exe2.run_steps(main2, feed={"x": X, "y": Y},
                              fetch_list=[loss2], fetch_every=2)
        assert np.asarray(sub).shape[0] == 3
        np.testing.assert_allclose(np.asarray(sub),
                                   np.asarray(all_losses)[1::2],
                                   rtol=1e-5, atol=1e-6)

    def test_iterator_of_feed_dicts(self):
        X, Y = _batches(4)
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)
        stacked, = exe.run_steps(main, feed={"x": X, "y": Y},
                                 fetch_list=[loss])

        main2, startup2, loss2, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe2 = fluid.Executor()
        exe2.run(startup2)
        it = ({"x": X[t], "y": Y[t]} for t in range(4))
        from_iter, = exe2.run_steps(main2, feed=it, fetch_list=[loss2])
        np.testing.assert_allclose(np.asarray(from_iter),
                                   np.asarray(stacked), rtol=1e-6)

    def test_constant_feeds_not_stacked(self):
        n = 3
        X, Y = _batches(n)
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)
        # y constant across the chain: pass it UNstacked
        out, = exe.run_steps(main, feed={"x": X, "y": Y[0]},
                             fetch_list=[loss], constant_feeds=("y",))
        assert np.asarray(out).shape == (n,)

    def test_strategy_default_chain_length(self):
        from paddle_tpu.static import ExecutionStrategy

        n = 4
        X, Y = _batches(n)
        strat = ExecutionStrategy()
        strat.num_iteration_per_run = n
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor(strategy=strat)
        exe.run(startup)
        # all-constant feeds + no iterations=: length comes from strategy
        out, = exe.run_steps(main, feed={"x": X[0], "y": Y[0]},
                             fetch_list=[loss],
                             constant_feeds=("x", "y"))
        assert np.asarray(out).shape == (n,)

    def test_requires_optimizer(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [-1, 4])
            fluid.layers.fc(x, 2)
        with pytest.raises(InvalidArgumentError, match="minimize"):
            fluid.Executor().run_steps(
                main, feed={"x": np.zeros((3, 8, 4), np.float32)})

    def test_mismatched_leading_dim_rejected(self):
        X, Y = _batches(4)
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)
        with pytest.raises(InvalidArgumentError, match="leading dim"):
            exe.run_steps(main, feed={"x": X, "y": Y[:2]},
                          fetch_list=[loss])


class TestCompileCache:
    def test_lru_eviction_at_cap(self):
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor(cache_capacity=2)
        exe.run(startup)
        rng = np.random.RandomState(0)

        def run(bs):
            exe.run(main, feed={"x": rng.rand(bs, 13).astype(np.float32),
                                "y": rng.rand(bs, 1).astype(np.float32)},
                    fetch_list=[loss])

        for bs in (4, 8, 16):  # 3 geometries through a capacity-2 cache
            run(bs)
        s = exe.cache_stats()
        assert s == {**s, "misses": 3, "evictions": 1, "size": 2}
        run(4)  # evicted (LRU) -> miss again
        assert exe.cache_stats()["misses"] == 4
        run(4)  # now resident -> hit
        assert exe.cache_stats()["hits"] == 1

    def test_counters_published_on_trace_events(self):
        events = []
        obs = lambda site, info: events.append((site, info))  # noqa: E731
        trace_events.register(obs)
        try:
            X, Y = _batches(2)
            main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
            exe = fluid.Executor()
            exe.run(startup)
            exe.run_steps(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        finally:
            trace_events.unregister(obs)
        cache_ev = [(s, i) for s, i in events if s[0] == "executor_cache"]
        assert cache_ev, [s for s, _ in events]
        site, info = cache_ev[-1]
        assert site[1].startswith("executor#")
        assert info["misses"] == 1 and info["dispatches"] == 1
        # the run_steps compile also published a signature event
        assert any(s[0] == "executor" and i.get("mode", "").startswith(
            "run_steps") for s, i in events)

    def test_retrace_monitor_reports_r403_on_churn(self):
        from paddle_tpu.analysis import RetraceMonitor

        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor(cache_capacity=1)
        exe.run(startup)
        rng = np.random.RandomState(0)
        with RetraceMonitor(budget=2) as mon:
            for bs in (4, 8, 16, 4, 8, 16):  # churn through capacity 1
                exe.run(main,
                        feed={"x": rng.rand(bs, 13).astype(np.float32),
                              "y": rng.rand(bs, 1).astype(np.float32)},
                        fetch_list=[loss])
        diags = mon.diagnostics()
        r403 = [d for d in diags if d.rule == "R403"]
        assert len(r403) == 1
        assert "evicted" in r403[0].message
        assert "executor_cache_capacity" in r403[0].hint
        assert mon.cache_stats()  # accessor exposes the snapshots

    def test_no_r403_below_budget(self):
        from paddle_tpu.analysis import RetraceMonitor

        X, Y = _batches(3)
        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)
        with RetraceMonitor(budget=8) as mon:
            for _ in range(5):  # steady-state: one signature, zero evictions
                exe.run(main, feed={"x": X[0], "y": Y[0]},
                        fetch_list=[loss])
        assert not [d for d in mon.diagnostics() if d.rule == "R403"]
        # and the counter events did NOT inflate R402 either
        assert not [d for d in mon.diagnostics() if d.rule == "R402"]


class TestDataLoaderSuperbatch:
    def test_superbatch_stacks_k_batches(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset

        class DS(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.full((3,), i, np.float32), np.int32(i)

        dl = DataLoader(DS(), batch_size=2, superbatch=2, return_numpy=True)
        items = list(dl)
        # 5 batches of 2 -> superbatches of 2, 2, and a trailing 1
        shapes = [[np.asarray(f).shape for f in it] for it in items]
        assert shapes == [[(2, 2, 3), (2, 2)], [(2, 2, 3), (2, 2)],
                          [(1, 2, 3), (1, 2)]]
        np.testing.assert_array_equal(np.asarray(items[0][1]),
                                      [[0, 1], [2, 3]])

    def test_superbatch_feeds_run_steps(self):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import Dataset

        rng = np.random.RandomState(0)
        Xd = rng.rand(32, 13).astype(np.float32)
        Yd = rng.rand(32, 1).astype(np.float32)

        class DS(Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                return Xd[i], Yd[i]

        main, startup, loss, _ = _mlp(lambda: fluid.optimizer.SGD(0.1))
        exe = fluid.Executor()
        exe.run(startup)
        dl = DataLoader(DS(), batch_size=8, superbatch=4, return_numpy=True)
        for xb, yb in dl:  # one fused dispatch per superbatch
            out, = exe.run_steps(main, feed={"x": xb, "y": yb},
                                 fetch_list=[loss])
            assert np.asarray(out).shape == (4,)
        assert exe.dispatches == 1  # 32 samples / (8*4) = one superbatch


class TestStaticFunctionRunSteps:
    def _net(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)
                self.bn = nn.BatchNorm1D(4)

            def forward(self, x):
                return self.bn(self.fc(x))

        paddle.seed(0)
        net = Net()
        net.train()
        return net

    def test_matches_eager_sequential_with_bn(self):
        import paddle_tpu as paddle
        from paddle_tpu import jit

        rng = np.random.RandomState(0)
        X = rng.rand(5, 16, 8).astype(np.float32)

        net_a = self._net()
        seq = [np.asarray(net_a(paddle.to_tensor(X[t]))) for t in range(5)]
        bufs_a = {k: np.asarray(v.value)
                  for k, v in dict(net_a.named_buffers()).items()}

        net_b = self._net()
        out = jit.to_static(net_b).run_steps(X)
        assert np.asarray(out).shape == (5, 16, 4)
        for t in range(5):
            np.testing.assert_allclose(np.asarray(out)[t], seq[t],
                                       rtol=1e-5, atol=1e-6)
        for k, v in dict(net_b.named_buffers()).items():
            np.testing.assert_allclose(np.asarray(v.value), bufs_a[k],
                                       rtol=1e-5, atol=1e-6)

    def test_iterations_kwarg_on_call(self):
        from paddle_tpu import jit

        rng = np.random.RandomState(0)
        X = rng.rand(3, 16, 8).astype(np.float32)
        net = self._net()
        sf = jit.to_static(net)
        out = sf(X, iterations=3)
        assert np.asarray(out).shape == (3, 16, 4)

    def test_fetch_every(self):
        from paddle_tpu import jit

        rng = np.random.RandomState(0)
        X = rng.rand(6, 16, 8).astype(np.float32)
        out = jit.to_static(self._net()).run_steps(X, fetch_every=3)
        assert np.asarray(out).shape == (2, 16, 4)

    def test_eager_fallback_when_to_static_disabled(self):
        from paddle_tpu import jit

        rng = np.random.RandomState(0)
        X = rng.rand(3, 16, 8).astype(np.float32)
        net = self._net()
        sf = jit.to_static(net)
        fused = np.asarray(sf.run_steps(X))
        net2 = self._net()
        sf2 = jit.to_static(net2)
        jit.ProgramTranslator().enable(False)
        try:
            eager = np.asarray(sf2.run_steps(X))
        finally:
            jit.ProgramTranslator().enable(True)
        np.testing.assert_allclose(eager, fused, rtol=1e-5, atol=1e-6)

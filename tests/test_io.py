"""IO tests: save/load roundtrip, datasets, samplers, DataLoader paths
(sync, multiprocess workers, iterable, device staging).  Mirrors the
reference's test_dataloader_* / test_batch_sampler unittests."""
import os

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import io as pio
from paddle_tpu import nn


class TestSaveLoad:
    def test_state_dict_roundtrip(self, tmp_path, rng):
        layer = nn.Linear(4, 3)
        path = str(tmp_path / "model.pdparams")
        paddle.save(layer.state_dict(), path)
        loaded = paddle.load(path)
        layer2 = nn.Linear(4, 3)
        layer2.set_state_dict(loaded)
        for (n1, p1), (n2, p2) in zip(layer.named_parameters(), layer2.named_parameters()):
            np.testing.assert_allclose(p1.numpy(), p2.numpy())

    def test_nested_containers(self, tmp_path):
        obj = {"a": [jnp.ones((2, 2)), 3, "s"], "b": {"c": np.zeros(3)}, "d": None}
        path = str(tmp_path / "obj.pkl")
        paddle.save(obj, path)
        out = paddle.load(path)
        np.testing.assert_allclose(out["a"][0], 1.0)
        assert out["a"][1] == 3 and out["a"][2] == "s" and out["d"] is None

    def test_optimizer_state_roundtrip(self, tmp_path, rng):
        from paddle_tpu import optimizer as O

        layer = nn.Linear(3, 3)
        opt = O.Adam(parameters=layer.parameters())
        grads = {n: jnp.ones_like(p.value) for n, p in layer.named_parameters()}
        opt.step(grads)
        path = str(tmp_path / "opt.pdopt")
        paddle.save(opt.state_dict(), path)
        opt2 = O.Adam(parameters=nn.Linear(3, 3).parameters())
        opt2.set_state_dict(paddle.load(path))
        assert int(opt2._eager_state["count"]) == 1

    def test_load_missing_raises(self, tmp_path):
        with pytest.raises(Exception, match="exist"):
            paddle.load(str(tmp_path / "nope.pdparams"))

    def test_load_foreign_file_raises(self, tmp_path):
        p = tmp_path / "junk.bin"
        p.write_bytes(b"garbage-not-a-checkpoint")
        with pytest.raises(Exception, match="magic"):
            paddle.load(str(p))

    def test_atomic_save_creates_dirs(self, tmp_path):
        path = str(tmp_path / "deep" / "nested" / "m.pdparams")
        paddle.save({"x": np.ones(2)}, path)
        assert os.path.exists(path)
        assert not os.path.exists(path + ".tmp")


class SquareDataset(pio.Dataset):
    def __init__(self, n=20):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray([i], dtype=np.float32), np.asarray(i * i, dtype=np.float32)


class BadDataset(pio.Dataset):
    """Raises from workers (module scope: spawn workers pickle the dataset)."""

    def __len__(self):
        return 4

    def __getitem__(self, i):
        raise ValueError("boom-from-worker")


class TestDatasets:
    def test_tensor_dataset(self, rng):
        x, y = rng.randn(10, 3).astype(np.float32), rng.randn(10).astype(np.float32)
        ds = pio.TensorDataset([x, y])
        assert len(ds) == 10
        np.testing.assert_allclose(ds[3][0], x[3])
        np.testing.assert_allclose(ds[3][1], y[3])

    def test_concat_subset_split(self):
        a, b = SquareDataset(5), SquareDataset(7)
        cat = pio.ConcatDataset([a, b])
        assert len(cat) == 12
        np.testing.assert_allclose(cat[6][0], [1.0])  # second dataset idx 1
        sub = pio.Subset(cat, [0, 6])
        assert len(sub) == 2
        parts = pio.random_split(SquareDataset(10), [7, 3])
        assert len(parts[0]) == 7 and len(parts[1]) == 3
        all_idx = sorted(parts[0].indices + parts[1].indices)
        assert all_idx == list(range(10))

    def test_compose(self):
        ds = pio.ComposeDataset([SquareDataset(4), SquareDataset(4)])
        sample = ds[2]
        assert len(sample) == 4

    def test_chain(self):
        class It(pio.IterableDataset):
            def __init__(self, lo, hi):
                self.lo, self.hi = lo, hi

            def __iter__(self):
                return iter(range(self.lo, self.hi))

        out = list(pio.ChainDataset([It(0, 3), It(10, 12)]))
        assert out == [0, 1, 2, 10, 11]


class TestSamplers:
    def test_sequence(self):
        assert list(pio.SequenceSampler(SquareDataset(4))) == [0, 1, 2, 3]

    def test_random_permutes(self):
        out = list(pio.RandomSampler(SquareDataset(50)))
        assert sorted(out) == list(range(50)) and out != list(range(50))

    def test_weighted(self):
        s = pio.WeightedRandomSampler([0.0, 1.0, 0.0], num_samples=20)
        assert all(i == 1 for i in s)

    def test_batch_sampler(self):
        bs = pio.BatchSampler(dataset=SquareDataset(10), batch_size=3)
        batches = list(bs)
        assert len(bs) == 4 and len(batches) == 4
        assert batches[-1] == [9]
        bs = pio.BatchSampler(dataset=SquareDataset(10), batch_size=3, drop_last=True)
        assert len(list(bs)) == 3 == len(bs)

    def test_distributed_batch_sampler_disjoint_covering(self):
        n, reps = 20, 4
        seen = []
        for rank in range(reps):
            s = pio.DistributedBatchSampler(
                SquareDataset(n), batch_size=2, num_replicas=reps, rank=rank
            )
            idx = [i for b in s for i in b]
            assert len(idx) == 5
            seen.extend(idx)
        assert sorted(seen) == list(range(n))

    def test_distributed_shuffle_consistent_across_ranks(self):
        perms = []
        for rank in range(2):
            s = pio.DistributedBatchSampler(
                SquareDataset(10), batch_size=5, num_replicas=2, rank=rank, shuffle=True
            )
            s.set_epoch(3)
            perms.append([i for b in s for i in b])
        assert not set(perms[0]) & set(perms[1])
        s.set_epoch(4)
        assert [i for b in s for i in b] != perms[1]


class TestDataLoader:
    def test_sync_loader_shapes(self):
        dl = pio.DataLoader(SquareDataset(10), batch_size=4, return_numpy=True)
        batches = list(dl)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == (4, 1) and y.shape == (4,)
        np.testing.assert_allclose(batches[-1][0][:, 0], [8, 9])

    def test_device_staging_returns_jax_arrays(self):
        import jax

        dl = pio.DataLoader(SquareDataset(6), batch_size=3)
        for x, y in dl:
            assert isinstance(x, jax.Array)

    def test_shuffle_epoch_differs(self):
        dl = pio.DataLoader(SquareDataset(30), batch_size=30, shuffle=True, return_numpy=True)
        (a,) = [b[1] for b in dl]
        (b,) = [b[1] for b in dl]
        assert sorted(a.tolist()) == sorted(b.tolist())
        assert a.tolist() != b.tolist()

    def test_multiprocess_workers_match_sync(self):
        sync = [b[1] for b in pio.DataLoader(SquareDataset(17), batch_size=4, return_numpy=True)]
        mp = [
            b[1]
            for b in pio.DataLoader(
                SquareDataset(17), batch_size=4, num_workers=2, return_numpy=True
            )
        ]
        assert len(sync) == len(mp)
        for s, m in zip(sync, mp):
            np.testing.assert_allclose(s, m)

    def test_worker_exception_propagates(self):
        dl = pio.DataLoader(BadDataset(), batch_size=2, num_workers=1, return_numpy=True)
        with pytest.raises(Exception, match="boom"):
            list(dl)

    def test_iterable_dataset(self):
        class Stream(pio.IterableDataset):
            def __iter__(self):
                for i in range(7):
                    yield np.asarray([i], np.float32)

        dl = pio.DataLoader(Stream(), batch_size=3, return_numpy=True)
        batches = list(dl)
        assert [b.shape[0] for b in batches] == [3, 3, 1]
        dl = pio.DataLoader(Stream(), batch_size=3, drop_last=True, return_numpy=True)
        assert [b.shape[0] for b in dl] == [3, 3]

    def test_dict_collate(self):
        class D(pio.Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                return {"x": np.ones(2, np.float32) * i, "n": i}

        dl = pio.DataLoader(D(), batch_size=2, return_numpy=True)
        b = next(iter(dl))
        assert b["x"].shape == (2, 2) and b["n"].tolist() == [0, 1]

    def test_custom_collate_and_sampler(self):
        dl = pio.DataLoader(
            SquareDataset(8),
            batch_size=2,
            sampler=pio.SequenceSampler(SquareDataset(8)),
            collate_fn=lambda batch: len(batch),
            return_numpy=True,
        )
        assert list(dl) == [2, 2, 2, 2]

    def test_training_with_dataloader_e2e(self, rng):
        """Linear regression learns y=2x from a DataLoader feed."""
        import jax

        X = rng.randn(64, 1).astype(np.float32)
        Y = 2.0 * X
        ds = pio.TensorDataset([X, Y])
        dl = pio.DataLoader(ds, batch_size=16, shuffle=True)
        from paddle_tpu import optimizer as O

        w = nn.Parameter(np.zeros((1, 1), np.float32), name="w")
        opt = O.SGD(learning_rate=0.1, parameters=[w])

        def loss_fn(params, x, y):
            return jnp.mean((x @ params["w"] - y) ** 2)

        gfn = jax.jit(jax.grad(loss_fn))
        for _ in range(10):
            for x, y in dl:
                opt.step(gfn({"w": w.value}, x, y))
        np.testing.assert_allclose(float(w.value[0, 0]), 2.0, rtol=1e-3)


def _record_worker_id(wid):
    # spawn workers write their id to a tempfile named by pid-independent env
    import os, tempfile
    with open(os.path.join(os.environ["PTPU_TEST_WIDDIR"], f"w{wid}"), "w") as f:
        f.write(str(wid))


class TestReviewRegressions:
    def test_distinct_worker_ids(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTPU_TEST_WIDDIR", str(tmp_path))
        dl = pio.DataLoader(SquareDataset(8), batch_size=2, num_workers=2,
                            worker_init_fn=_record_worker_id, return_numpy=True)
        list(dl)
        ids = sorted(p.name for p in tmp_path.iterdir())
        assert ids == ["w0", "w1"]

    def test_early_break_shuts_down_pool(self):
        import multiprocessing, gc
        before = len(multiprocessing.active_children())
        dl = pio.DataLoader(SquareDataset(40), batch_size=2, num_workers=2)
        it = iter(dl)
        next(it)
        it.close()
        gc.collect()
        import time
        deadline = time.time() + 15
        while time.time() < deadline:
            if len(multiprocessing.active_children()) <= before:
                break
            time.sleep(0.2)
        assert len(multiprocessing.active_children()) <= before

    def test_random_sampler_generator_varies_per_epoch(self):
        from paddle_tpu.framework.random import Generator
        s = pio.RandomSampler(SquareDataset(30), generator=Generator(7))
        a, b = list(s), list(s)
        assert sorted(a) == sorted(b) == list(range(30))
        assert a != b

    def test_random_sampler_int_seed_varies_per_epoch(self):
        s = pio.RandomSampler(SquareDataset(30), generator=7)
        assert list(s) != list(s)

    def test_distributed_sampler_tiny_dataset_pads(self):
        s = pio.DistributedBatchSampler(SquareDataset(1), batch_size=1,
                                        num_replicas=3, rank=2)
        assert [i for b in s for i in b] == [0]

    def test_iterable_num_workers_warns(self):
        class Stream(pio.IterableDataset):
            def __iter__(self):
                return iter(range(3))

        with pytest.warns(RuntimeWarning, match="num_workers"):
            dl = pio.DataLoader(Stream(), batch_size=2, num_workers=4, return_numpy=True)
        assert dl.num_workers == 0


class TestReferenceCompatLoad:
    def test_headerless_reference_pickle_loads(self, tmp_path):
        """ADVICE r1: reference paddle.save files are plain pickles with no
        magic header — load() accepts them."""
        import pickle

        from paddle_tpu.framework import serialization

        p = os.path.join(tmp_path, "ref.pdparams")
        state = {"w": np.arange(4, dtype=np.float32)}
        with open(p, "wb") as f:
            pickle.dump(state, f, protocol=2)
        out = serialization.load(p)
        np.testing.assert_array_equal(out["w"], state["w"])

    def test_garbage_still_rejected(self, tmp_path):
        from paddle_tpu.framework import serialization

        p = os.path.join(tmp_path, "junk.pdparams")
        with open(p, "wb") as f:
            f.write(b"\x00\x01garbage not a pickle")
        with pytest.raises(Exception, match="neither"):
            serialization.load(p)

    def test_foreign_extension_never_unpickled(self, tmp_path):
        """The compat fallback is gated to .pdparams/.pdopt — any other
        extension is rejected BEFORE the unpickler runs."""
        import pickle

        from paddle_tpu.framework import serialization

        p = os.path.join(tmp_path, "model.pkl")
        with open(p, "wb") as f:
            pickle.dump({"w": np.ones(2)}, f)
        with pytest.raises(Exception, match="pdparams"):
            serialization.load(p)

"""paddle_tpu.serving router — the multi-replica control plane.

Covers the control-plane contract: least-outstanding / p2c balancing,
transparent failover losing zero ACCEPTED requests (under async replica
failures, injected ``router.dispatch`` faults, and injected
``serving.runner`` faults through real engines), deterministic hedging
with an injectable timer and a respected budget, circuit-trip →
half-open-probe recovery on an injectable clock, zero-downtime drain and
rolling weight swap (no stale-weight result, no rejected traffic),
SIGTERM drain-all, per-replica telemetry (trace_events family, analysis
rule S602, observability gauges, profiler summary) — plus regression
tests for the batcher's deadline-bounded retry and drain-timeout close.
"""
import os
import signal
import tempfile
import threading
import time
import unittest
from concurrent.futures import Future

import numpy as np

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.analysis import RetraceMonitor
from paddle_tpu.framework.errors import (
    InvalidArgumentError,
    TransientDeviceError,
    UnavailableError,
)
from paddle_tpu.resilience import FaultPlan, FaultRule, RetryPolicy
from paddle_tpu.resilience import retry as _retry_mod
from paddle_tpu.serving import InferenceEngine, MicroBatcher, Router
from paddle_tpu.serving.replica import (
    DRAINED,
    DRAINING,
    HEALTHY,
    UNHEALTHY,
)


class FakeEngine:
    """Duck-typed engine: synchronous futures by default, manual
    resolution (``manual=True``) for hedging/drain tests."""

    def __init__(self, result="ok", fail_with=None, manual=False,
                 probe_fail=False):
        self.result = result
        self.fail_with = fail_with   # exception INSTANCE → async failure
        self.raise_sync = None       # exception INSTANCE → submit raises
        self.manual = manual
        self.probe_fail = probe_fail
        self.pending = []            # unresolved futures (manual mode)
        self.calls = 0
        self.version = "v1"
        self.closed = False

    # router probe hooks
    def synthetic_inputs(self):
        return [np.zeros((1,), np.float32)]

    def infer(self, inputs, timeout=None):
        if self.probe_fail:
            raise TransientDeviceError("probe failed")
        return [self.result]

    def submit(self, inputs, deadline_ms=None, **kw):
        self.calls += 1
        if self.raise_sync is not None:
            raise self.raise_sync
        f = Future()
        if self.manual:
            self.pending.append((f, inputs))
            return f
        if self.fail_with is not None:
            f.set_exception(self.fail_with)
        else:
            f.set_result((self.result, self.version, inputs))
        return f

    def resolve(self, i=0):
        f, inputs = self.pending.pop(i)
        f.set_result((self.result, self.version, inputs))

    def swap_weights(self, params_file):
        self.version = params_file

    def close(self, drain=True, timeout=None):
        self.closed = True


def make_router(engines, **kw):
    kw.setdefault("probe_interval_s", None)  # no background thread
    kw.setdefault("circuit_kw", {"failure_threshold": 1.0, "window": 2,
                                 "cooldown_ms": 60_000,
                                 "half_open_probes": 1})
    return Router(engines, **kw)


class TestRouterBalancing(unittest.TestCase):
    def test_validation(self):
        with self.assertRaises(InvalidArgumentError):
            Router([])
        with self.assertRaises(InvalidArgumentError):
            make_router([FakeEngine()], policy="round_robin")
        with self.assertRaises(InvalidArgumentError):
            make_router([FakeEngine()], hedge_budget_frac=1.5)

    def test_least_outstanding_prefers_idle_replica(self):
        busy, idle = FakeEngine(manual=True), FakeEngine(manual=True)
        r = make_router([busy, idle], policy="least")
        try:
            r.submit(1)              # both idle → lowest index (busy)
            for _ in range(3):
                r.submit(2)          # busy has 1 outstanding → idle wins
            self.assertEqual(busy.calls, 2)  # 1 primary + 1 balanced back
            self.assertEqual(idle.calls, 2)
        finally:
            for e in (busy, idle):
                while e.pending:
                    e.resolve()
            r.close()

    def test_p2c_spreads_load(self):
        engines = [FakeEngine() for _ in range(4)]
        r = make_router(engines, policy="p2c", seed=7)
        try:
            for i in range(80):
                r.infer(i, timeout=5)
            touched = sum(1 for e in engines if e.calls > 0)
            self.assertGreaterEqual(touched, 3)  # not pinned to one replica
        finally:
            r.close()

    def test_probe_required_for_active_probing(self):
        class Bare:
            def submit(self, inputs, deadline_ms=None):
                f = Future(); f.set_result(inputs); return f

        with self.assertRaises(InvalidArgumentError):
            Router([Bare()], probe_interval_s=1.0)
        r = Router([Bare()], probe_interval_s=None)  # passive-only is fine
        r.close()


class TestRouterFailover(unittest.TestCase):
    def test_async_replica_failure_loses_zero_accepted_requests(self):
        bad = FakeEngine(fail_with=TransientDeviceError("replica dead"))
        engines = [bad, FakeEngine(), FakeEngine()]
        r = make_router(engines)
        try:
            for i in range(20):
                got = r.infer(i, timeout=5)
                self.assertEqual(got[0], "ok")
            s = r.stats()
            self.assertEqual(s["accepted"], 20)
            self.assertEqual(s["rejected"], 0)
            self.assertEqual(s["completed"], 20)
            self.assertEqual(s["errors"], 0)
            self.assertGreater(s["failovers"], 0)
            # the breaker tripped the dead replica out of rotation
            self.assertEqual(r.replica(0).state, UNHEALTHY)
            self.assertGreaterEqual(s["replica_flaps"], 1)
        finally:
            r.close()

    def test_router_dispatch_fault_injection_zero_loss(self):
        engines = [FakeEngine(), FakeEngine(), FakeEngine()]
        r = make_router(engines,
                        circuit_kw={"failure_threshold": 1.0, "window": 50,
                                    "cooldown_ms": 60_000})
        plan = FaultPlan([FaultRule("router.dispatch", every=2,
                                    error="UnavailableError")])
        try:
            with plan:
                for i in range(12):
                    self.assertEqual(r.infer(i, timeout=5)[0], "ok")
            self.assertEqual(plan.stats()["router.dispatch"]["fired"], 11)
            s = r.stats()
            self.assertEqual(s["completed"], 12)
            self.assertEqual(s["errors"], 0)
            self.assertGreater(s["dispatch_failovers"], 0)
        finally:
            r.close()

    def test_sync_client_error_rejects_without_failover(self):
        eng = FakeEngine()
        eng.raise_sync = InvalidArgumentError("bad shape")
        r = make_router([eng, FakeEngine()])
        try:
            with self.assertRaises(InvalidArgumentError):
                r.submit(1)
            s = r.stats()
            self.assertEqual(s["rejected"], 1)
            self.assertEqual(s["accepted"], 0)
            self.assertEqual(s["dispatch_failovers"], 0)
        finally:
            r.close()

    def test_all_replicas_failing_fails_future_not_worker(self):
        err = TransientDeviceError("everything is down")
        r = make_router([FakeEngine(fail_with=err),
                         FakeEngine(fail_with=err)])
        try:
            fut = r.submit(1)
            with self.assertRaises(TransientDeviceError):
                fut.result(5)
            # ACCEPTED but failed after exhausting both replicas; the
            # router itself still serves once a replica works again
            s = r.stats()
            self.assertEqual(s["accepted"], 1)
            self.assertEqual(s["errors"], 1)
        finally:
            r.close()

    def test_no_healthy_replica_sheds_at_submit(self):
        r = make_router([FakeEngine()])
        try:
            r.drain(0, timeout=1)
            with self.assertRaises(UnavailableError):
                r.submit(1)
            self.assertEqual(r.stats()["rejected"], 1)
        finally:
            r.close()


class ManualTimer:
    """Recorded in a list instead of running; the test fires it."""

    fired = None  # set per-test

    def __init__(self, delay_s, fn):
        self.delay_s = delay_s
        self.fn = fn
        self.cancelled = False
        self.daemon = True

    def start(self):
        ManualTimer.fired.append(self)

    def cancel(self):
        self.cancelled = True


class TestRouterHedging(unittest.TestCase):
    def setUp(self):
        ManualTimer.fired = []

    def _hedged_router(self, engines, **kw):
        kw.setdefault("hedge_delay_ms", 1000.0)
        return make_router(engines, hedge=True, timer_factory=ManualTimer,
                           **kw)

    def test_hedge_first_result_wins_and_budget_respected(self):
        slow, fast = FakeEngine(manual=True), FakeEngine(manual=True)
        fast.result = "hedged"
        r = self._hedged_router([slow, fast], policy="least",
                                hedge_budget_frac=0.01)
        try:
            fut = r.submit(1)
            self.assertEqual(len(ManualTimer.fired), 1)
            ManualTimer.fired[0].fn()          # hedge delay elapses
            self.assertEqual(fast.calls, 1)    # hedge went to the other one
            fast.resolve()                     # hedge finishes first
            self.assertEqual(fut.result(5)[0], "hedged")
            slow.resolve()                     # straggler result discarded
            s = r.stats()
            self.assertEqual(s["hedges"], 1)
            self.assertEqual(s["hedge_wins"], 1)

            # budget: 2 requests at frac 0.01 → max(1, 0.02) = 1 hedge
            fut2 = r.submit(2)
            ManualTimer.fired[1].fn()
            self.assertEqual(r.stats()["hedge_denied"], 1)
            slow.resolve() if slow.pending else fast.resolve()
            fut2.result(5)
        finally:
            r.close(drain=False)

    def test_completion_cancels_pending_hedge_timer(self):
        eng = FakeEngine(manual=True)
        r = self._hedged_router([eng, FakeEngine(manual=True)],
                                policy="least")
        try:
            fut = r.submit(1)
            self.assertEqual(len(ManualTimer.fired), 1)
            eng.resolve()  # primary completes before the hedge delay
            self.assertEqual(fut.result(5)[0], "ok")
            self.assertTrue(ManualTimer.fired[0].cancelled)
            ManualTimer.fired[0].fn()  # late fire: no-op, future is done
            self.assertEqual(r.stats()["hedges"], 0)

            # a request completing synchronously never schedules a timer
            sync = FakeEngine()
            self.assertEqual(r.replicas[1].engine.pending, [])
            r2 = self._hedged_router([sync, FakeEngine()])
            r2.infer(2, timeout=5)
            r2.close()
            self.assertEqual(len(ManualTimer.fired), 1)
        finally:
            r.close(drain=False)

    def test_hedge_failure_never_fails_the_primary(self):
        primary = FakeEngine(manual=True)
        hedge = FakeEngine(fail_with=TransientDeviceError("hedge died"))
        r = self._hedged_router([primary, hedge], policy="least")
        try:
            fut = r.submit(1)
            ManualTimer.fired[0].fn()   # hedge dispatch fails instantly
            self.assertFalse(fut.done())  # primary still owns the flight
            primary.resolve()
            self.assertEqual(fut.result(5)[0], "ok")
            self.assertEqual(r.stats()["errors"], 0)
        finally:
            r.close()

    def test_no_delay_signal_means_no_hedge(self):
        # p99-derived delay with zero traffic history → nothing scheduled
        a, b = FakeEngine(manual=True), FakeEngine(manual=True)
        r = make_router([a, b], hedge=True, hedge_delay_ms=None,
                        policy="least", timer_factory=ManualTimer)
        try:
            fut = r.submit(1)
            self.assertEqual(ManualTimer.fired, [])
            a.resolve()
            fut.result(5)
        finally:
            r.close()


class TestRouterHealth(unittest.TestCase):
    def test_circuit_trip_then_half_open_probe_readmission(self):
        now = [0.0]
        clock = lambda: now[0]  # noqa: E731
        eng = FakeEngine(fail_with=TransientDeviceError("flaky"))
        r = make_router([eng, FakeEngine()],
                        circuit_kw={"failure_threshold": 1.0, "window": 2,
                                    "cooldown_ms": 5000.0,
                                    "half_open_probes": 1, "clock": clock})
        try:
            for i in range(4):
                r.infer(i, timeout=5)
            self.assertEqual(r.replica(0).state, UNHEALTHY)
            self.assertEqual(r.healthy_count(), 1)

            r.probe_now()  # cooldown not elapsed → no probe admitted
            self.assertEqual(r.replica(0).state, UNHEALTHY)

            eng.fail_with = None      # replica recovers...
            now[0] = 6.0              # ...and the cooldown elapses
            r.probe_now()             # half-open probe succeeds
            self.assertEqual(r.replica(0).state, HEALTHY)
            self.assertGreaterEqual(r.stats()["readmissions"], 1)
            rep = r.replica(0).snapshot()
            self.assertGreaterEqual(rep["probes"], 1)
            self.assertGreaterEqual(rep["readmissions"], 1)
        finally:
            r.close()

    def test_failed_half_open_probe_keeps_replica_out(self):
        now = [0.0]
        eng = FakeEngine(fail_with=TransientDeviceError("down"),
                         probe_fail=True)
        r = make_router([eng, FakeEngine()],
                        circuit_kw={"failure_threshold": 1.0, "window": 2,
                                    "cooldown_ms": 1000.0,
                                    "clock": lambda: now[0]})
        try:
            for i in range(4):
                r.infer(i, timeout=5)
            self.assertEqual(r.replica(0).state, UNHEALTHY)
            now[0] = 2.0
            r.probe_now()  # probe fails → circuit re-opens
            self.assertEqual(r.replica(0).state, UNHEALTHY)
            self.assertGreaterEqual(r.stats()["probe_failures"], 1)
        finally:
            r.close()

    def test_probe_failures_trip_an_idle_replica(self):
        eng = FakeEngine(probe_fail=True)
        r = make_router([eng, FakeEngine()])
        try:
            r.probe_now()
            r.probe_now()  # window=2 fills with probe failures → trip
            self.assertEqual(r.replica(0).state, UNHEALTHY)
            self.assertEqual(r.replica(1).state, HEALTHY)
        finally:
            r.close()

    def test_background_health_thread_probes(self):
        eng = FakeEngine()
        r = make_router([eng], probe_interval_s=0.02)
        try:
            deadline = time.monotonic() + 5
            while (r.stats()["probes"] == 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            self.assertGreater(r.stats()["probes"], 0)
        finally:
            r.close()

    def test_background_sweep_never_overlaps_warmup(self):
        # regression: the health thread starts at construction, so a probe
        # could compile through a replica's batcher while warmup() traces
        # over the (possibly shared) model — a JAX tracer leak.  The probe
        # gate must hold sweeps out for the whole warmup pass.
        in_warmup = threading.Event()
        overlaps = []

        class SlowWarmup(FakeEngine):
            def warmup(self):
                in_warmup.set()
                time.sleep(0.05)
                in_warmup.clear()
                return 1

        def probe(engine):
            if in_warmup.is_set():
                overlaps.append(engine)

        r = make_router([SlowWarmup(), SlowWarmup()],
                        probe_interval_s=0.005, probe_fn=probe)
        try:
            deadline = time.monotonic() + 5
            while (r.stats()["probes"] == 0      # sweeps demonstrably live
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            self.assertEqual(r.warmup(), 2)
            self.assertEqual(overlaps, [])
        finally:
            r.close()


class TestRouterDrainAndSwap(unittest.TestCase):
    def test_drain_stops_admissions_then_admit_restores(self):
        a, b = FakeEngine(), FakeEngine()
        r = make_router([a, b])
        try:
            self.assertTrue(r.drain(0, timeout=1))
            self.assertEqual(r.replica(0).state, DRAINED)
            before = a.calls
            for i in range(5):
                r.infer(i, timeout=5)
            self.assertEqual(a.calls, before)  # all traffic went to b
            self.assertTrue(r.admit(0))
            self.assertEqual(r.replica(0).state, HEALTHY)
        finally:
            r.close()

    def test_drain_waits_for_in_flight_requests(self):
        eng = FakeEngine(manual=True)
        r = make_router([eng])
        try:
            fut = r.submit(1)
            done = []
            t = threading.Thread(
                target=lambda: done.append(r.drain(0, timeout=5)))
            t.start()
            time.sleep(0.05)
            self.assertEqual(r.replica(0).state, DRAINING)
            eng.resolve()               # in-flight request finishes
            t.join(5)
            self.assertEqual(done, [True])
            self.assertEqual(fut.result(1)[0], "ok")
        finally:
            r.close()

    def test_rolling_swap_no_downtime_no_stale_results(self):
        engines = [FakeEngine() for _ in range(3)]
        r = make_router(engines)
        stop = threading.Event()
        failures = []

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    r.infer(i, timeout=5)
                except Exception as e:  # noqa: BLE001 — the assertion
                    failures.append(e)
                i += 1

        t = threading.Thread(target=traffic)
        t.start()
        try:
            time.sleep(0.05)
            swapped = r.swap_weights_rolling("v2", drain_timeout=5)
            self.assertEqual(swapped, 3)
        finally:
            stop.set()
            t.join(5)
        try:
            # zero rejected/failed requests during the roll
            self.assertEqual(failures, [])
            self.assertEqual(r.stats()["rejected"], 0)
            # every replica serves the new weights; no stale result ever
            for i in range(9):
                self.assertEqual(r.infer(i, timeout=5)[1], "v2")
            self.assertEqual(r.stats()["weight_swaps"], 3)
            self.assertEqual(r.healthy_count(), 3)
        finally:
            r.close()

    def test_swap_drain_timeout_aborts_and_keeps_replica_serving(self):
        stuck = FakeEngine(manual=True)
        r = make_router([stuck, FakeEngine()])
        try:
            r.submit(1)  # wedged in-flight request on replica 0
            with self.assertRaises(UnavailableError):
                r.swap_weights_rolling("v2", drain_timeout=0.05)
            self.assertEqual(r.replica(0).state, HEALTHY)  # not a hole
            self.assertEqual(stuck.version, "v1")  # swap never ran
        finally:
            stuck.resolve()
            r.close()

    def test_custom_swap_fn_for_generation_style_engines(self):
        class Reloadable(FakeEngine):
            def swap_weights(self, params_file):
                raise AssertionError("swap_fn must be used instead")

            def reload(self):
                self.version = "reloaded"

        engs = [Reloadable(), Reloadable()]
        r = make_router(engs)
        try:
            r.swap_weights_rolling(swap_fn=lambda e: e.reload())
            self.assertEqual([e.version for e in engs],
                             ["reloaded", "reloaded"])
            with self.assertRaises(InvalidArgumentError):
                r.swap_weights_rolling()  # neither params_file nor swap_fn
        finally:
            r.close()

    def test_sigterm_drains_all_replicas_then_exits_clean(self):
        from paddle_tpu.resilience.preemption import PREEMPTION_EXIT_CODE

        r = make_router([FakeEngine(), FakeEngine()])
        exits = []
        handler = r.install_sigterm_drain(timeout=5)
        handler._exit = exits.append
        try:
            handler._on_sigterm(signal.SIGTERM, None)
            self.assertEqual(exits, [PREEMPTION_EXIT_CODE])
            self.assertTrue(all(rep.state == DRAINED
                                for rep in r.replicas))
        finally:
            handler.uninstall()
            r.close(drain=False)

    def test_close_closes_owned_engines(self):
        engines = [FakeEngine(), FakeEngine()]
        r = make_router(engines)
        r.close()
        self.assertTrue(all(e.closed for e in engines))
        with self.assertRaises(UnavailableError):
            r.submit(1)


def _export_tiny(tmpdir, name, seed=0):
    class _TinyNet(nn.Layer):
        def __init__(self):
            super().__init__()
            pt.seed(seed)
            self.fc = nn.Linear(8, 4)

        def forward(self, x):
            return self.fc(x)

    prefix = os.path.join(tmpdir, name)
    pt.inference.save_inference_model(
        prefix, _TinyNet(),
        [pt.static.InputSpec([None, None, 8], "float32")])
    return prefix


class TestRouterRealEngines(unittest.TestCase):
    """End-to-end over real InferenceEngine replicas with faults injected
    at the ``serving.runner`` seam (the chaos-smoke scenario in-process)."""

    def test_runner_faults_lose_zero_accepted_requests(self):
        from paddle_tpu.serving import Bucket

        with tempfile.TemporaryDirectory() as td:
            prefix = _export_tiny(td, "m")
            engines = [
                InferenceEngine(prefix, [Bucket(((4, 8),))],
                                max_queue_delay_ms=0.0,
                                retry_transient=False,
                                circuit_breaker=False,
                                name=f"router-test-eng{i}")
                for i in range(3)]
            r = make_router(
                engines,
                circuit_kw={"failure_threshold": 1.0, "window": 50,
                            "cooldown_ms": 60_000})
            x = np.ones((2, 8), np.float32)
            try:
                want = r.infer([x], timeout=30)[0]  # warm + reference
                plan = FaultPlan([FaultRule("serving.runner", every=3,
                                            times=4)])
                with plan:
                    for _ in range(12):
                        got = r.infer([x], timeout=30)[0]
                        np.testing.assert_allclose(got, want, rtol=1e-5)
                self.assertEqual(plan.stats()["serving.runner"]["fired"], 4)
                s = r.stats()
                self.assertEqual(s["errors"], 0)
                self.assertEqual(s["rejected"], 0)
                self.assertGreaterEqual(s["failovers"], 1)
            finally:
                r.close()


class TestRouterTelemetry(unittest.TestCase):
    def test_replica_events_feed_router_family_not_signature_dedup(self):
        with RetraceMonitor(budget=3) as mon:
            bad = FakeEngine(fail_with=TransientDeviceError("dead"))
            r = make_router([bad, FakeEngine()], name="telemetry-router")
            try:
                for i in range(8):
                    r.infer(i, timeout=5)
                stats = mon.router_stats()
                self.assertIn("telemetry-router[0]", stats)
                self.assertEqual(stats["telemetry-router[0]"]["state"],
                                 UNHEALTHY)
                self.assertIn("state_code", stats["telemetry-router[0]"])
                # router counters ride the ("serving", name) family
                snap = mon.serving_stats("telemetry-router")
                self.assertEqual(snap.get("router"), 1)
                # replica snapshots never leak into R401/R402 dedup
                self.assertEqual([d for d in mon.diagnostics()
                                  if d.rule in ("R401", "R402")], [])
            finally:
                r.close()

    def test_s602_fires_on_replica_flapping_after_warmup(self):
        was_warm = _retry_mod._warm
        _retry_mod.mark_warm()
        try:
            with RetraceMonitor() as mon:
                eng = FakeEngine()
                r = make_router(
                    [eng, FakeEngine()], name="flappy",
                    circuit_kw={"failure_threshold": 1.0, "window": 1,
                                "cooldown_ms": 60_000})
                try:
                    for i in range(3):  # trip → re-admit → trip …
                        eng.fail_with = TransientDeviceError("flap")
                        r.infer(i, timeout=5)
                        self.assertEqual(r.replica(0).state, UNHEALTHY)
                        eng.fail_with = None
                        self.assertTrue(r.admit(0))
                    rules = [d.rule for d in mon.diagnostics()]
                    self.assertIn("S602", rules)
                finally:
                    r.close()
        finally:
            _retry_mod._warm = was_warm

    def test_s602_fires_on_hedge_storm(self):
        was_warm = _retry_mod._warm
        _retry_mod.mark_warm()
        ManualTimer.fired = []
        try:
            with RetraceMonitor(budget=2) as mon:
                r = make_router([FakeEngine(manual=True),
                                 FakeEngine(manual=True)],
                                name="stormy", hedge=True,
                                hedge_delay_ms=1000.0,
                                hedge_budget_frac=0.01,
                                timer_factory=ManualTimer)
                try:
                    futs = [r.submit(i) for i in range(5)]
                    for t in list(ManualTimer.fired):
                        t.fn()  # 1 hedge allowed, 4 denied (> budget 2)
                    self.assertGreater(r.stats()["hedge_denied_after_warm"],
                                       2)
                    self.assertIn("S602",
                                  [d.rule for d in mon.diagnostics()])
                finally:
                    for rep in r.replicas:
                        while rep.engine.pending:
                            rep.engine.resolve()
                    for f in futs:
                        f.result(5)
                    r.close()
        finally:
            _retry_mod._warm = was_warm

    def test_observability_bridge_exports_replica_gauges(self):
        from paddle_tpu.observability import (
            MetricRegistry,
            install_bridge,
            uninstall_bridge,
        )
        from paddle_tpu.observability.exporters import render_prometheus

        uninstall_bridge()
        reg = MetricRegistry()
        install_bridge(reg)
        try:
            r = make_router([FakeEngine()], name="obs-router")
            try:
                r.infer(1, timeout=5)
                r.probe_now()
            finally:
                r.close()
            text = render_prometheus(reg)
            self.assertIn("paddle_tpu_router_state_code", text)
            self.assertIn('replica="obs-router[0]"', text)
            self.assertIn("paddle_tpu_serving_failovers", text)
        finally:
            uninstall_bridge()

    def test_profiler_summary_has_router_section(self):
        r = make_router([FakeEngine(), FakeEngine()], name="summary-router")
        try:
            r.infer(1, timeout=5)
            text = pt.profiler.summary()
            self.assertIn("Serving router", text)
            self.assertIn("summary-router", text)
        finally:
            r.close()


class TestBatcherRegressions(unittest.TestCase):
    """The two batcher fixes shipped with the router."""

    def test_retry_backoff_bounded_by_request_deadline(self):
        # a persistently failing runner + a generous retry policy must
        # surface the failure within the REQUEST's deadline, not after
        # the policy's full backoff schedule
        policy = RetryPolicy(max_attempts=100, backoff_ms=100.0,
                             jitter=0.0, name="router-test-deadline")
        mb = MicroBatcher(
            lambda ins: 0,
            lambda bucket, reqs: (_ for _ in ()).throw(
                TransientDeviceError("always down")),
            max_batch_size=1, max_queue_delay_ms=0.0, retry=policy,
            name="deadline-batcher")
        try:
            t0 = time.monotonic()
            fut = mb.submit((1,), deadline_ms=250.0)
            with self.assertRaises(TransientDeviceError):
                fut.result(10)
            self.assertLess(time.monotonic() - t0, 5.0)
            stats = _retry_mod.stats("router-test-deadline")
            self.assertGreaterEqual(stats["deadline_giveups"], 1)
            self.assertLess(stats["attempts"], 20)
        finally:
            mb.close(drain=False, timeout=1)

    def test_close_drain_timeout_fails_queued_not_in_flight(self):
        release = threading.Event()

        def wedged_runner(bucket, reqs):
            release.wait(30)
            return [("served", bucket)] * len(reqs)

        mb = MicroBatcher(lambda ins: ins[0], wedged_runner,
                          max_batch_size=1, max_queue_delay_ms=0.0,
                          name="wedged-batcher")
        in_flight = mb.submit((0,))
        time.sleep(0.1)          # let the worker pick it up and wedge
        queued = mb.submit((1,))  # different bucket: stays queued
        t0 = time.monotonic()
        mb.close(drain=True, timeout=0.3)
        self.assertLess(time.monotonic() - t0, 5.0)  # close returned
        # the QUEUED request fails instead of leaking a pending future
        with self.assertRaises(UnavailableError):
            queued.result(1)
        self.assertEqual(mb.metrics.snapshot()["drain_timeout"], 1)
        # the in-flight batch keeps its outcome when the worker unsticks
        release.set()
        self.assertEqual(in_flight.result(10), ("served", 0))


if __name__ == "__main__":
    unittest.main()

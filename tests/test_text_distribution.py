"""paddle_tpu.distribution + paddle_tpu.text.datasets.

Reference capability: python/paddle/distribution.py (Distribution/Uniform/
Normal/Categorical) and python/paddle/text/datasets/ (UCIHousing, Imdb,
Imikolov, Movielens, WMT14, WMT16, Conll05st).  Dataset tests build tiny
fixture files in the reference's exact on-disk formats (no egress here).
"""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import Categorical, Normal, Uniform
from paddle_tpu.text.datasets import (
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)


class TestDistributions:
    def test_normal_log_prob_oracle(self):
        n = Normal(1.0, 2.0)
        x = np.linspace(-3, 5, 7)
        got = np.asarray(n.log_prob(x))
        want = (-((x - 1.0) ** 2) / 8.0 - np.log(2.0)
                - 0.5 * np.log(2 * np.pi))
        np.testing.assert_allclose(got, want, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(n.probs(x)), np.exp(want),
                                   rtol=1e-5)

    def test_normal_entropy_and_kl(self):
        a, b = Normal(0.0, 1.0), Normal(2.0, 3.0)
        np.testing.assert_allclose(
            float(a.entropy()), 0.5 + 0.5 * np.log(2 * np.pi), rtol=1e-6)
        assert float(a.kl_divergence(a)) == pytest.approx(0.0, abs=1e-7)
        # KL(N(0,1)||N(2,3)) closed form
        want = 0.5 * (1 / 9 + 4 / 9 - 1 - np.log(1 / 9))
        np.testing.assert_allclose(float(a.kl_divergence(b)), want, rtol=1e-5)

    def test_normal_sampling_moments(self):
        paddle.seed(0)
        s = np.asarray(Normal(3.0, 0.5).sample((20000,)))
        assert abs(s.mean() - 3.0) < 0.02
        assert abs(s.std() - 0.5) < 0.02

    def test_uniform(self):
        u = Uniform(-1.0, 3.0)
        np.testing.assert_allclose(float(u.entropy()), np.log(4.0), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(u.probs(np.array([0.0, 5.0]))), [0.25, 0.0])
        paddle.seed(1)
        s = np.asarray(u.sample((8000,)))
        assert s.min() >= -1.0 and s.max() < 3.0
        assert abs(s.mean() - 1.0) < 0.05

    def test_categorical(self):
        logits = np.log(np.array([[0.2, 0.3, 0.5]], np.float32))
        c = Categorical(logits)
        np.testing.assert_allclose(
            np.asarray(c.probs(np.array([2]))), [0.5], rtol=1e-5)
        want_ent = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        np.testing.assert_allclose(np.asarray(c.entropy()), [want_ent],
                                   rtol=1e-5)
        assert float(c.kl_divergence(c).sum()) == pytest.approx(0.0, abs=1e-6)
        paddle.seed(2)
        s = np.asarray(c.sample((30000,)))
        freq = np.bincount(s.ravel(), minlength=3) / s.size
        np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)


# --------------------------------------------------------------------------
# dataset fixtures in the reference's on-disk formats
# --------------------------------------------------------------------------
def _add_tar_bytes(tar, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))


class TestUCIHousing:
    def test_load_and_split(self, tmp_path):
        rng = np.random.RandomState(0)
        table = rng.rand(50, 14).astype(np.float32)
        p = os.path.join(tmp_path, "housing.data")
        np.savetxt(p, table)
        train = UCIHousing(data_file=p, mode="train")
        test = UCIHousing(data_file=p, mode="test")
        assert len(train) == 40 and len(test) == 10
        feat, tgt = train[0]
        assert feat.shape == (13,) and tgt.shape == (1,)

    def test_missing_file_clear_error(self, tmp_path):
        with pytest.raises(Exception, match="cannot download"):
            UCIHousing(data_file=None, mode="train")


class TestImdb:
    def _make_tar(self, tmp_path):
        p = os.path.join(tmp_path, "aclImdb_v1.tar.gz")
        docs = {
            "aclImdb/train/pos/0.txt": b"a great great movie",
            "aclImdb/train/neg/0.txt": b"a bad movie indeed",
            "aclImdb/test/pos/0.txt": b"great fun",
            "aclImdb/test/neg/0.txt": b"bad bad bad",
        }
        with tarfile.open(p, "w:gz") as t:
            for name, data in docs.items():
                _add_tar_bytes(t, name, data)
        return p

    def test_word_dict_and_labels(self, tmp_path):
        p = self._make_tar(tmp_path)
        ds = Imdb(data_file=p, mode="train", cutoff=1)
        # freq > 1 in train: 'a'(2), 'great'(2), 'movie'(2)
        assert set(ds.word_idx) == {"a", "great", "movie", "<unk>"}
        assert len(ds) == 2
        docs = {tuple(ds[i][0].tolist()): int(ds[i][1]) for i in range(2)}
        # pos doc → label 0; neg doc → label 1
        labels = sorted(docs.values())
        assert labels == [0, 1]

    def test_test_mode(self, tmp_path):
        ds = Imdb(data_file=self._make_tar(tmp_path), mode="test", cutoff=1)
        assert len(ds) == 2


class TestImikolov:
    def _make_tar(self, tmp_path):
        p = os.path.join(tmp_path, "simple-examples.tar.gz")
        train = b"the cat sat\nthe dog sat\n"
        valid = b"the cat ran\n"
        with tarfile.open(p, "w:gz") as t:
            _add_tar_bytes(t, "./simple-examples/data/ptb.train.txt", train)
            _add_tar_bytes(t, "./simple-examples/data/ptb.valid.txt", valid)
        return p

    def test_ngram(self, tmp_path):
        ds = Imikolov(data_file=self._make_tar(tmp_path), data_type="NGRAM",
                      window_size=2, mode="train", min_word_freq=0)
        # each train line: <s> w w w <e> → 4 bigrams, 2 lines → 8
        assert len(ds) == 8
        a, b = ds[0], ds[1]
        assert a[1] == b[0]  # sliding window

    def test_seq(self, tmp_path):
        ds = Imikolov(data_file=self._make_tar(tmp_path), data_type="SEQ",
                      window_size=-1, mode="train", min_word_freq=0)
        src, trg = ds[0]
        assert src[0] == ds.word_idx["<s>"]
        assert trg[-1] == ds.word_idx["<e>"]
        np.testing.assert_array_equal(src[1:], trg[:-1])


class TestMovielens:
    def _make_zip(self, tmp_path):
        p = os.path.join(tmp_path, "ml-1m.zip")
        movies = "1::Toy Story (1995)::Animation|Comedy\n2::Heat (1995)::Action\n"
        users = "1::M::25::6::55117\n2::F::35::3::55117\n"
        ratings = "".join(f"{u}::{m}::{r}::978300760\n"
                          for u, m, r in [(1, 1, 5), (1, 2, 3), (2, 1, 4),
                                          (2, 2, 2)] * 10)
        with zipfile.ZipFile(p, "w") as z:
            z.writestr("ml-1m/movies.dat", movies)
            z.writestr("ml-1m/users.dat", users)
            z.writestr("ml-1m/ratings.dat", ratings)
        return p

    def test_loads_and_splits(self, tmp_path):
        p = self._make_zip(tmp_path)
        train = Movielens(data_file=p, mode="train", test_ratio=0.25,
                          rand_seed=0)
        test = Movielens(data_file=p, mode="test", test_ratio=0.25,
                         rand_seed=0)
        assert len(train) + len(test) == 40
        sample = train[0]
        assert len(sample) == 8  # uid,gender,age,job, mid,cats,title, rating
        assert sample[-1].shape == (1,)
        assert -5.0 <= float(sample[-1][0]) <= 5.0


class TestWMT:
    def _wmt14_tar(self, tmp_path):
        p = os.path.join(tmp_path, "wmt14.tgz")
        src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
        trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
        train = b"hello world\tbonjour monde\nhello\tbonjour\n"
        with tarfile.open(p, "w:gz") as t:
            _add_tar_bytes(t, "wmt14/src.dict", src_dict)
            _add_tar_bytes(t, "wmt14/trg.dict", trg_dict)
            _add_tar_bytes(t, "train/train", train)
        return p

    def test_wmt14(self, tmp_path):
        ds = WMT14(data_file=self._wmt14_tar(tmp_path), mode="train",
                   dict_size=5)
        assert len(ds) == 2
        src, trg, trg_next = ds[0]
        sdict, tdict = ds.get_dict()
        assert src.tolist() == [sdict["<s>"], sdict["hello"],
                                sdict["world"], sdict["<e>"]]
        assert trg.tolist()[0] == tdict["<s>"]
        assert trg_next.tolist()[-1] == tdict["<e>"]

    def test_wmt16(self, tmp_path):
        p = os.path.join(tmp_path, "wmt16.tar.gz")
        train = b"hello world\thallo welt\nworld world\twelt welt\n"
        with tarfile.open(p, "w:gz") as t:
            _add_tar_bytes(t, "wmt16/train", train)
            _add_tar_bytes(t, "wmt16/val", b"hello\thallo\n")
        ds = WMT16(data_file=p, mode="val", src_dict_size=10,
                   trg_dict_size=10, lang="en")
        assert len(ds) == 1
        src, trg, trg_next = ds[0]
        assert src[0] == ds.src_dict["<s>"] and src[-1] == ds.src_dict["<e>"]
        # 'world' appears 3x in train → first corpus word after the marks
        assert ds.src_dict["world"] == 3


class TestConll05:
    def _fixture(self, tmp_path):
        words = b"The\ncat\nsat\n\n"
        # props: col0 = verb lemma rows; one predicate column
        props = b"-\t*\nsit\t(V*)\n-\t(A1*)\n\n"
        tar_p = os.path.join(tmp_path, "conll05st-tests.tar.gz")
        with tarfile.open(tar_p, "w:gz") as t:
            _add_tar_bytes(
                t, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                gzip.compress(words))
            _add_tar_bytes(
                t, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                gzip.compress(props))
        wd = os.path.join(tmp_path, "wordDict.txt")
        vd = os.path.join(tmp_path, "verbDict.txt")
        td = os.path.join(tmp_path, "targetDict.txt")
        with open(wd, "w") as f:
            f.write("the\ncat\nsat\nThe\n")
        with open(vd, "w") as f:
            f.write("sit\n")
        with open(td, "w") as f:
            f.write("B-V\nI-V\nB-A1\nI-A1\nO\n")
        return tar_p, wd, vd, td

    def test_srl_sample(self, tmp_path):
        tar_p, wd, vd, td = self._fixture(tmp_path)
        ds = Conll05st(data_file=tar_p, word_dict_file=wd, verb_dict_file=vd,
                       target_dict_file=td)
        assert len(ds) == 1
        cols = ds[0]
        assert len(cols) == 9
        word_idx, *ctx, pred_idx, mark, label_idx = cols
        assert word_idx.shape == (3,)
        assert mark.tolist().count(1) == 3  # verb @1: ctx -1,0,+1 in range
        labels = ds.labels[0]
        assert labels == ["O", "B-V", "B-A1"]
        assert pred_idx.tolist() == [0, 0, 0]

"""Mixture-of-experts (paddle_tpu/moe + ops/grouped_matmul).

Covers the routed-FFN contracts the dryrun moe leg gates at mesh scale,
on a single CPU host: deterministic routing under a fixed seed (jittered
gating included), the slot-major-then-token capacity tie-break, dense
equivalence (identically initialized experts + top-1 + ample capacity ⇒
loss AND gradients bit-identical to the dense MLP), the grouped-matmul
kernel vs its masked-einsum reference (forward and backward, every
autotune tile candidate), expert-sharded decode through the continuous
engine (0-expert config token-identical to the plain dense model; MoE
config publishes the routing counters), and analysis rule S606
(fire on sustained overflow / dead experts, silent when healthy).
"""
import time
import unittest

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.analysis import RetraceMonitor
from paddle_tpu.framework import trace_events
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, gpt_tiny
from paddle_tpu.moe import MoELayer
from paddle_tpu.moe import stats as moe_stats
from paddle_tpu.nn.layer_base import functional_call
from paddle_tpu.serving import GenerationEngine


class _Cfg:
    """Minimal duck-typed config for a bare MoELayer."""

    def __init__(self, D=8, F=16, E=2, k=1, cf=1.0, jitter=0.0):
        self.hidden_size, self.intermediate_size, self.dropout = D, F, 0.0
        self.moe_experts, self.moe_top_k = E, k
        self.moe_capacity_factor, self.moe_jitter = cf, jitter


class TestRouting(unittest.TestCase):
    def test_eval_routing_deterministic(self):
        pt.seed(3)
        lyr = MoELayer(_Cfg(E=4, k=2, cf=2.0))
        lyr.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(6, 8),
                        jnp.float32)
        a, b = np.asarray(lyr(x)), np.asarray(lyr(x))
        self.assertEqual(a.tobytes(), b.tobytes())

    def test_jittered_routing_deterministic_under_fixed_key(self):
        pt.seed(3)
        lyr = MoELayer(_Cfg(E=4, k=2, cf=2.0, jitter=0.05))
        x = jnp.asarray(np.random.RandomState(0).randn(6, 8),
                        jnp.float32)
        params = {k: v.value for k, v in lyr.named_parameters()}

        def run(key):
            return np.asarray(functional_call(
                lyr, params, x, rngs=key, training=True))

        same = run(jax.random.PRNGKey(7))
        self.assertEqual(same.tobytes(), run(jax.random.PRNGKey(7)).tobytes())
        # a different key draws different jitter — the output must move
        # (jitter that does nothing would silently disable GShard §3.1)
        self.assertNotEqual(same.tobytes(),
                            run(jax.random.PRNGKey(8)).tobytes())

    def test_capacity_tiebreak_slot_major_then_token(self):
        """C=1 per expert, 2 tokens x top-2: a token's FIRST choice beats
        any token's SECOND choice for the same expert, and within a
        choice rank the earlier token wins.  Marker-bias experts (zero
        matmuls, per-expert constant output) read the surviving
        (token, choice) pairs straight out of the combine."""
        pt.seed(0)
        lyr = MoELayer(_Cfg(D=2, F=4, E=2, k=2, cf=0.5))
        lyr.eval()
        self.assertEqual(lyr.capacity(2), 1)
        # x = eye ⇒ logits row n = gate row n; logits = ln(p) so softmax
        # returns exactly p (up to fp): token0 prefers e1 (.6) then e0
        # (.4); token1 e0 (.9) then e1 (.1)
        lyr.gate.value = jnp.log(jnp.asarray([[0.4, 0.6], [0.9, 0.1]],
                                             jnp.float32))
        lyr.expert_fc1.value = jnp.zeros_like(lyr.expert_fc1.value)
        lyr.expert_fc2.value = jnp.zeros_like(lyr.expert_fc2.value)
        # expert e outputs the constant e+1 in every lane
        lyr.expert_b2.value = jnp.asarray([[1.0, 1.0], [2.0, 2.0]],
                                          jnp.float32)
        x = jnp.eye(2, dtype=jnp.float32)
        with moe_stats.collect() as ms:
            y = np.asarray(lyr(x))
        counts = np.asarray(ms.counts(2))
        # every expert saw 2 selections, kept 1, dropped 1
        np.testing.assert_array_equal(counts[0], [1, 1])
        np.testing.assert_array_equal(counts[1], [1, 1])
        # token0: e1 slot kept via 1st choice (weight .6); its 2nd-choice
        # e0 slot lost to token1's FIRST choice — slot-major order
        np.testing.assert_allclose(y[0], [0.6 * 2.0] * 2, rtol=1e-5)
        # token1: e0 kept via 1st choice (weight .9); 2nd-choice e1 slot
        # lost to token0's 1st choice
        np.testing.assert_allclose(y[1], [0.9 * 1.0] * 2, rtol=1e-5)

    def test_balance_loss_unit_when_balanced(self):
        """A router that spreads tokens uniformly scores aux ≈ 1."""
        pt.seed(1)
        lyr = MoELayer(_Cfg(D=4, F=8, E=4, k=1, cf=4.0))
        lyr.eval()
        lyr.gate.value = jnp.zeros_like(lyr.gate.value)  # uniform probs
        x = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)
        with moe_stats.collect() as ms:
            lyr(x)
        self.assertAlmostEqual(float(ms.total_aux()), 1.0, places=5)


class TestDenseParity(unittest.TestCase):
    def test_forward_and_backward_bit_identical_to_dense_mlp(self):
        """Identically initialized experts + top-1 + capacity ≥ tokens:
        the routed model IS the dense model, bit for bit, both ways."""
        E = 4
        pt.seed(0)
        net_d = GPTForCausalLM(gpt_tiny())
        pt.seed(0)
        net_m = GPTForCausalLM(gpt_tiny(
            moe_experts=E, moe_top_k=1, moe_capacity_factor=float(2 * E),
            moe_jitter=0.0, moe_balance_weight=0.0))
        dense = dict(net_d.named_parameters())
        for name, box in net_m.named_parameters():
            if name in dense:
                box.value = dense[name].value
        for bd, bm in zip(net_d.gpt.blocks, net_m.gpt.blocks):
            D, F = bd.mlp.fc1.weight.value.shape
            bm.mlp.expert_fc1.value = jnp.broadcast_to(
                bd.mlp.fc1.weight.value, (E, D, F)) + 0.0
            bm.mlp.expert_b1.value = jnp.broadcast_to(
                bd.mlp.fc1.bias.value, (E, F)) + 0.0
            bm.mlp.expert_fc2.value = jnp.broadcast_to(
                bd.mlp.fc2.weight.value, (E, F, D)) + 0.0
            bm.mlp.expert_b2.value = jnp.broadcast_to(
                bd.mlp.fc2.bias.value, (E, D)) + 0.0

        ids = np.random.RandomState(5).randint(
            0, net_d.gpt.cfg.vocab_size, size=(2, 12)).astype(np.int32)
        key = jax.random.PRNGKey(0)

        def lossfn(net):
            def f(params):
                return functional_call(
                    net, params, rngs=key, training=True,
                    call=lambda: net.loss(net(jnp.asarray(ids)), ids))
            return f

        pd = {k: v.value for k, v in dense.items()}
        pm = {k: v.value for k, v in dict(net_m.named_parameters()).items()}
        ld, gd = jax.jit(jax.value_and_grad(lossfn(net_d)))(pd)
        lm, gm = jax.jit(jax.value_and_grad(lossfn(net_m)))(pm)
        self.assertEqual(np.asarray(ld).tobytes(), np.asarray(lm).tobytes())
        for name in pd:
            if ".mlp." in name:
                continue  # different parameterization; compared via sum
            self.assertEqual(np.asarray(gd[name]).tobytes(),
                             np.asarray(gm[name]).tobytes(),
                             f"grad for {name} not bit-identical")
        # gradients flow through dispatch into every expert weight, and
        # the expert copies' grads sum back to the dense MLP grad
        g = gm["gpt.blocks.0.mlp.expert_fc1"]
        self.assertGreater(float(jnp.abs(g).max()), 0.0)
        np.testing.assert_allclose(
            np.asarray(g).sum(0),
            np.asarray(gd["gpt.blocks.0.mlp.fc1.weight"]),
            rtol=1e-5, atol=1e-6)


class TestGroupedMatmul(unittest.TestCase):
    def test_matches_masked_einsum_fwd_bwd_all_candidates(self):
        from paddle_tpu.ops.grouped_matmul import _space, grouped_matmul

        E, C, D, F = 3, 80, 16, 160
        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(E, C, D), jnp.float32)
        w = jnp.asarray(rng.randn(E, D, F), jnp.float32)
        gs = jnp.asarray([80, 37, 0], jnp.int32)
        mask = (np.arange(C)[None, :] < np.asarray(gs)[:, None]
                ).astype(np.float32)[..., None]

        def ref(x, w):
            return jnp.einsum("ecd,edf->ecf", x * jnp.asarray(mask), w)

        ry = ref(x, w)
        rgx, rgw = jax.grad(lambda x, w: ref(x, w).sum(), argnums=(0, 1))(
            x, w)
        cands = _space(x, w, gs)
        self.assertGreater(len(cands), 1, "want a real candidate sweep")
        for cfg in cands:
            y = grouped_matmul(x, w, gs, **cfg)
            np.testing.assert_allclose(np.asarray(y), np.asarray(ry),
                                       rtol=1e-5, atol=1e-5, err_msg=str(cfg))
            # padding rows are exactly zero — combine may trust them
            self.assertEqual(float(jnp.abs(y[1, 37:]).max()), 0.0)
            self.assertEqual(float(jnp.abs(y[2]).max()), 0.0)
            gx, gw = jax.grad(
                lambda x, w: grouped_matmul(x, w, gs, **cfg).sum(),
                argnums=(0, 1))(x, w)
            np.testing.assert_allclose(np.asarray(gx), np.asarray(rgx),
                                       rtol=1e-5, atol=1e-5, err_msg=str(cfg))
            np.testing.assert_allclose(np.asarray(gw), np.asarray(rgw),
                                       rtol=1e-5, atol=1e-5, err_msg=str(cfg))

    def test_autotuned_default_blocks(self):
        from paddle_tpu.ops.grouped_matmul import grouped_matmul

        rng = np.random.RandomState(12)
        x = jnp.asarray(rng.randn(2, 8, 4), jnp.float32)
        w = jnp.asarray(rng.randn(2, 4, 4), jnp.float32)
        gs = jnp.asarray([5, 2], jnp.int32)
        y = np.asarray(grouped_matmul(x, w, gs))  # blocks from the tuner
        mask = (np.arange(8)[None, :] < np.asarray(gs)[:, None]
                ).astype(np.float32)[..., None]
        ref = np.einsum("ecd,edf->ecf", np.asarray(x) * mask, np.asarray(w))
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


class TestExpertShardedDecode(unittest.TestCase):
    def _greedy_ref(self, model, prompt, n):
        ids, outs = list(map(int, prompt)), []
        for _ in range(n):
            logits = np.asarray(model(jnp.asarray([ids], jnp.int32)))[0]
            outs.append(int(np.argmax(logits[-1])))
            ids.append(outs[-1])
        return outs

    def _model(self, experts):
        pt.seed(9)
        cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2,
                        num_heads=4, max_position=64, dropout=0.0,
                        moe_experts=experts, moe_top_k=2,
                        moe_capacity_factor=float(max(experts, 1)),
                        moe_jitter=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        return model

    def test_zero_expert_config_token_identical_to_dense(self):
        """moe_experts=0 must be EXACTLY the dense engine: same tokens,
        no moe counters, no tap installed."""
        model = self._model(0)
        prompts = [np.random.RandomState(k).randint(1, 61, size=3 + k)
                   .astype(np.int32) for k in range(3)]
        with GenerationEngine(model, prompt_buckets=[8], batch_size=2,
                              continuous=True, name="moe-t-dense") as eng:
            eng.warmup()
            outs = [eng.submit(p, 6).result(300).tolist() for p in prompts]
            st = eng.stats()
        for p, o in zip(prompts, outs):
            self.assertEqual(o, self._greedy_ref(model, p, 6))
        self.assertFalse([k for k in st if k.startswith("moe_")], st)

    def test_moe_decode_identity_and_counters(self):
        """Ample capacity (cf = E ⇒ zero drops) makes batched routing
        per-token independent: engine tokens must equal the eager greedy
        reference, with the routing counters flowing on the bus."""
        model = self._model(4)
        prompts = [np.random.RandomState(k).randint(1, 61, size=3 + k)
                   .astype(np.int32) for k in range(3)]
        with GenerationEngine(model, prompt_buckets=[8], batch_size=2,
                              continuous=True, name="moe-t-routed") as eng:
            eng.warmup()
            compiles0 = eng.compile_count
            outs = [eng.submit(p, 6).result(300).tolist() for p in prompts]
            time.sleep(0.05)  # one-step-deferred harvest
            st = eng.stats()
            self.assertEqual(eng.compile_count, compiles0,
                             "post-warmup recompile on the MoE step")
        for p, o in zip(prompts, outs):
            self.assertEqual(o, self._greedy_ref(model, p, 6))
        self.assertGreater(int(st["moe_routed_tokens"]), 0)
        self.assertEqual(int(st["moe_dropped_tokens"]), 0)
        self.assertEqual(float(st.get("moe_overflow_frac", 0.0)), 0.0)


class TestRuleS606(unittest.TestCase):
    BASE = {"admitted": 1, "moe_routed_tokens": 500,
            "moe_dropped_tokens": 0, "moe_sampled_steps_after_warm": 20,
            "moe_overflow_steps_after_warm": 0, "moe_dead_experts": 0}

    def _diags(self, **over):
        snap = dict(self.BASE, **over)
        with RetraceMonitor() as mon:
            trace_events.notify(("serving", "moe-fake"), snap)
            return [d for d in mon.diagnostics() if d.rule == "S606"]

    def test_fires_on_sustained_overflow(self):
        diags = self._diags(moe_dropped_tokens=300,
                            moe_overflow_steps_after_warm=15)
        self.assertEqual(len(diags), 1)
        self.assertIn("overflowed expert capacity", diags[0].message)
        self.assertIn("moe_capacity_factor", diags[0].hint)

    def test_fires_on_dead_experts(self):
        diags = self._diags(moe_dead_experts=2)
        self.assertEqual(len(diags), 1)
        self.assertIn("dead expert", diags[0].message)

    def test_silent_when_healthy(self):
        self.assertEqual(self._diags(), [])

    def test_silent_before_sample_floor(self):
        """A couple of overflow steps right after warmup are traffic
        skew, not a provisioning bug — below 8 sampled steps the rule
        must hold its fire."""
        diags = self._diags(moe_sampled_steps_after_warm=4,
                            moe_overflow_steps_after_warm=4,
                            moe_dead_experts=1)
        self.assertEqual(diags, [])


if __name__ == "__main__":
    unittest.main()

"""Seq2seq decoding: gather_tree, BeamSearchDecoder, dynamic_decode.

Reference behavior: fluid/layers/rnn.py:864 (BeamSearchDecoder), :1567
(dynamic_decode); operators/gather_tree_op.h:27 (backtrace kernel —
replicated in numpy as the oracle, per SURVEY §4 OpTest style).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def _gather_tree_np(ids, parents):
    """Numpy oracle transcribing gather_tree_op.h:27 semantics."""
    T, B, W = ids.shape
    out = np.zeros_like(ids)
    for b in range(B):
        for k in range(W):
            out[T - 1, b, k] = ids[T - 1, b, k]
            parent = parents[T - 1, b, k]
            for t in range(T - 2, -1, -1):
                out[t, b, k] = ids[t, b, parent]
                parent = parents[t, b, parent]
    return out


class TestGatherTree:
    def test_matches_kernel_oracle(self):
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 23, size=(6, 3, 4)).astype(np.int64)
        parents = rng.randint(0, 4, size=(6, 3, 4)).astype(np.int64)
        out = F.gather_tree(ids, parents)
        np.testing.assert_array_equal(np.asarray(out),
                                      _gather_tree_np(ids, parents))

    def test_reference_docstring_example(self):
        # fluid/layers/nn.py gather_tree doc example
        ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
                       np.int64)
        parents = np.array(
            [[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], np.int64)
        expected = np.array(
            [[[2, 2], [1, 6]], [[3, 3], [6, 1]], [[0, 1], [9, 0]]], np.int64)
        np.testing.assert_array_equal(np.asarray(F.gather_tree(ids, parents)),
                                      expected)

    def test_jit(self):
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 11, size=(5, 2, 3)).astype(np.int64)
        parents = rng.randint(0, 3, size=(5, 2, 3)).astype(np.int64)
        out = jax.jit(F.gather_tree)(ids, parents)
        np.testing.assert_array_equal(np.asarray(out),
                                      _gather_tree_np(ids, parents))


def _make_decoder(vocab=17, hidden=16, beam=4, end_token=1):
    paddle.seed(7)
    embedder = nn.Embedding(vocab, hidden)
    out_layer = nn.Linear(hidden, vocab)
    cell = nn.GRUCell(input_size=hidden, hidden_size=hidden)
    decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=end_token,
                                   beam_size=beam, embedding_fn=embedder,
                                   output_fn=out_layer)
    return decoder, cell


class TestBeamSearchDecode:
    def test_shapes_and_types(self):
        beam, batch, hidden = 4, 3, 16
        decoder, cell = _make_decoder(beam=beam, hidden=hidden)
        init = jnp.zeros((batch, hidden), jnp.float32)
        (outputs, final_states), = [nn.dynamic_decode(decoder, inits=init,
                                                      max_step_num=9)]
        # predicted_ids backtraced via gather_tree: [batch, T, beam]
        assert outputs.shape[0] == batch and outputs.shape[2] == beam
        assert outputs.shape[1] <= 10
        assert np.issubdtype(np.asarray(outputs).dtype, np.integer)
        assert final_states.lengths.shape == (batch, beam)

    def test_time_major_and_lengths(self):
        decoder, _ = _make_decoder()
        init = jnp.zeros((2, 16), jnp.float32)
        outputs, final_states, lengths = nn.dynamic_decode(
            decoder, inits=init, max_step_num=7, output_time_major=True,
            return_length=True)
        assert outputs.shape[1] == 2  # [T, batch, beam]
        assert lengths.shape == (2, 4)
        assert int(np.max(np.asarray(lengths))) <= outputs.shape[0]

    def test_beams_sorted_and_finished_padding(self):
        """Top beam has the best accumulated score; finished beams keep
        emitting end_token (mass forced onto EOS, rnn.py:1025)."""
        decoder, _ = _make_decoder(end_token=1)
        init = jnp.zeros((5, 16), jnp.float32)
        outputs, final_states = nn.dynamic_decode(decoder, inits=init,
                                                  max_step_num=19)
        log_probs = np.asarray(final_states.log_probs)
        assert (np.diff(log_probs, axis=1) <= 1e-5).all(), \
            "beams not sorted by score"
        ids = np.asarray(outputs)  # [batch, T, beam]
        lengths = np.asarray(final_states.lengths)
        fin = np.asarray(final_states.finished)
        for b in range(ids.shape[0]):
            for k in range(ids.shape[2]):
                if fin[b, k]:
                    L = lengths[b, k]
                    assert (ids[b, L - 1:, k] == 1).all(), \
                        "finished beam must be EOS-padded"

    def test_jit_compiles_single_while(self):
        decoder, _ = _make_decoder()

        @jax.jit
        def decode(init):
            out, states = nn.dynamic_decode(decoder, inits=init,
                                            max_step_num=9)
            return out, states.lengths

        init = jnp.zeros((2, 16), jnp.float32)
        out, lengths = decode(init)
        assert out.shape == (2, 10, 4)  # static T under jit
        out2, _ = decode(init + 0)  # cache hit, same shapes
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_beam1_matches_greedy(self):
        """beam_size=1 beam search IS greedy decoding — verify against a
        hand-rolled argmax loop over the same cell/embedder."""
        vocab, hidden = 13, 8
        paddle.seed(11)
        embedder = nn.Embedding(vocab, hidden)
        out_layer = nn.Linear(hidden, vocab)
        cell = nn.GRUCell(input_size=hidden, hidden_size=hidden)
        decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                       beam_size=1, embedding_fn=embedder,
                                       output_fn=out_layer)
        init = jnp.asarray(np.random.RandomState(3).randn(2, hidden),
                           jnp.float32)
        outputs, _ = nn.dynamic_decode(decoder, inits=init, max_step_num=11)
        got = np.asarray(outputs)[:, :, 0]  # [batch, T]

        # greedy oracle
        state = init
        tok = jnp.zeros((2,), jnp.int64)
        want = []
        done = np.zeros(2, bool)
        for _ in range(got.shape[1]):
            h, state = cell(embedder(tok), state)
            logits = np.asarray(out_layer(h))
            nxt = logits.argmax(-1)
            nxt = np.where(done, 1, nxt)
            want.append(nxt)
            done |= nxt == 1
            tok = jnp.asarray(nxt, jnp.int64)
        want = np.stack(want, 1)
        np.testing.assert_array_equal(got, want)

    def test_tile_beam_merge_with_batch(self):
        x = np.arange(6).reshape(3, 2).astype(np.float32)
        tiled = nn.BeamSearchDecoder.tile_beam_merge_with_batch(x, 2)
        assert tiled.shape == (6, 2)
        np.testing.assert_array_equal(np.asarray(tiled)[0],
                                      np.asarray(tiled)[1])

    def test_jit_early_finish_matches_eager(self):
        """Under jit the output buffer keeps its full [max_steps] length;
        the tail past the early exit must be inert padding (EOS ids,
        identity parents) so backtraced sequences match the eager run."""
        decoder, _ = _make_decoder(end_token=1)
        init = jnp.zeros((3, 16), jnp.float32)
        eager_out, eager_states = nn.dynamic_decode(decoder, inits=init,
                                                    max_step_num=30)
        jit_out, jit_states = jax.jit(
            lambda i: nn.dynamic_decode(decoder, inits=i,
                                        max_step_num=30))(init)
        eager_np = np.asarray(eager_out)
        jit_np = np.asarray(jit_out)
        T = eager_np.shape[1]
        np.testing.assert_array_equal(jit_np[:, :T], eager_np)
        assert (jit_np[:, T:] == 1).all(), "tail must be EOS padding"
        np.testing.assert_allclose(np.asarray(jit_states.log_probs),
                                   np.asarray(eager_states.log_probs),
                                   atol=1e-5)

    def test_trained_seq2seq_beam_decodes_copy_task(self):
        """Book-test parity (reference book/test_machine_translation.py
        decode path): train a GRU encoder-decoder on a copy task, then
        beam-search decode with BeamSearchDecoder + dynamic_decode and
        check the top beam reproduces the source."""
        import jax

        import paddle_tpu as paddle
        from paddle_tpu import optimizer as popt
        from paddle_tpu.nn import functional_call

        V, H, T = 12, 32, 5
        BOS, EOS = 0, 1
        rng = np.random.RandomState(0)
        src = rng.randint(2, V, size=(64, T)).astype(np.int32)
        trg_in = np.concatenate(
            [np.full((64, 1), BOS, np.int32), src[:, :-1]], axis=1)

        class Seq2Seq(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(V, H)
                self.enc = nn.GRU(H, H)
                self.cell = nn.GRUCell(H, H)
                self.out = nn.Linear(H, V)

            def encode(self, s):
                _, h = self.enc(self.emb(s))
                return h[0]  # [B, H]

            def forward(self, s, t_in):
                h = self.encode(s)
                xs = self.emb(t_in)  # [B, T, H]

                def step(carry, xt):
                    o, c = self.cell(xt, carry)
                    return c, o

                h_fin, outs = jax.lax.scan(
                    step, h, jnp.swapaxes(xs, 0, 1))
                return self.out(jnp.swapaxes(outs, 0, 1))

            def loss(self, logits, labels):
                lp = jax.nn.log_softmax(logits, -1)
                picked = jnp.take_along_axis(
                    lp, jnp.asarray(labels)[..., None].astype(jnp.int32), -1)
                return -picked.mean()

        paddle.seed(3)
        net = Seq2Seq()
        opt = popt.Adam(learning_rate=0.02, parameters=net.parameters())
        grad_fn = jax.jit(jax.value_and_grad(
            lambda p: net.loss(functional_call(net, p, src, trg_in), src)))
        for i in range(120):
            loss, g = grad_fn(net.param_pytree(trainable_only=True))
            opt.step(g)
        assert float(loss) < 0.15, f"copy task failed to train: {loss}"

        decoder = nn.BeamSearchDecoder(
            net.cell, start_token=BOS, end_token=EOS, beam_size=3,
            embedding_fn=net.emb, output_fn=net.out)
        h0 = net.encode(jnp.asarray(src[:8]))
        outputs, _ = nn.dynamic_decode(decoder, inits=h0,
                                       max_step_num=T - 1)
        top = np.asarray(outputs)[:, :, 0]  # [8, T] best beam
        acc = (top[:, :T] == src[:8, : top.shape[1]]).mean()
        assert acc > 0.9, f"beam decode accuracy {acc}"

    def test_early_exit_eager_slices_time(self):
        """Eagerly, outputs are sliced to the steps actually run — an
        immediately-finishing decode is short even with a large cap."""
        vocab, hidden = 7, 8
        paddle.seed(5)
        cell = nn.GRUCell(input_size=hidden, hidden_size=hidden)
        embedder = nn.Embedding(vocab, hidden)

        def force_eos(h):  # every step scores EOS (=1) highest
            base = jnp.full(h.shape[:-1] + (vocab,), -5.0, h.dtype)
            return base.at[..., 1].set(5.0)

        decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                       beam_size=2, embedding_fn=embedder,
                                       output_fn=force_eos)
        outputs, _ = nn.dynamic_decode(
            decoder, inits=jnp.zeros((2, hidden), jnp.float32),
            max_step_num=199)
        assert outputs.shape[1] <= 3, \
            f"early exit failed, decoded {outputs.shape[1]} steps"


class TestDecodeHelpers:
    """The pre-2.0 sampling-helper family (ref: fluid/layers/rnn.py
    DecodeHelper:1659 / TrainingHelper:1728 / GreedyEmbeddingHelper:1881 /
    SampleEmbeddingHelper:2012 / BasicDecoder:2113) over dynamic_decode's
    compiled while-loop."""

    def _parts(self):
        paddle.seed(0)
        B, T, D, V = 4, 6, 8, 12
        return (B, T, D, V, nn.GRUCell(D, D), nn.Embedding(V, D),
                nn.Linear(D, V))

    def test_training_helper_teacher_forcing_parity(self):
        B, T, D, V, cell, emb, proj = self._parts()
        rng = np.random.RandomState(0)
        X = rng.randn(B, T, D).astype(np.float32)
        seqlen = np.array([6, 4, 6, 2])
        dec = nn.BasicDecoder(cell, nn.TrainingHelper(jnp.asarray(X),
                                                      seqlen),
                              output_fn=lambda o: proj(o))
        h0 = jnp.zeros((B, D))
        outs, _, lens = nn.dynamic_decode(dec, inits=h0, max_step_num=T - 1,
                                          return_length=True)
        np.testing.assert_array_equal(np.asarray(lens), seqlen)
        co = np.asarray(outs.cell_outputs)
        h = h0
        for t in range(co.shape[1]):
            o, h = cell(jnp.asarray(X[:, t]), h)
            np.testing.assert_allclose(co[:, t], np.asarray(proj(o)),
                                       atol=1e-5)
        # sample ids are argmax of the projected outputs
        np.testing.assert_array_equal(
            np.asarray(outs.sample_ids)[:, 0],
            np.argmax(co[:, 0], axis=-1))

    def test_greedy_embedding_helper_stops_at_end_token(self):
        B, T, D, V, cell, emb, proj = self._parts()

        # a rigged output_fn that always emits end_token after step 1
        def out_fn(o):
            logits = proj(o)
            return logits.at[:, 1].add(1e4)  # end_token = 1 dominates

        dec = nn.BasicDecoder(
            cell, nn.GreedyEmbeddingHelper(lambda ids: emb(ids),
                                           np.zeros(B, np.int64), 1),
            output_fn=out_fn)
        outs, _, lens = nn.dynamic_decode(dec, inits=jnp.zeros((B, D)),
                                          max_step_num=5,
                                          return_length=True)
        assert np.asarray(outs.sample_ids)[:, 0].tolist() == [1] * B
        assert np.asarray(lens).max() <= 2  # finished right away

    def test_sample_embedding_helper_valid_and_seeded(self):
        B, T, D, V, cell, emb, proj = self._parts()

        def mk(seed):
            dec = nn.BasicDecoder(
                cell, nn.SampleEmbeddingHelper(lambda ids: emb(ids),
                                               np.zeros(B, np.int64), 1,
                                               seed=seed),
                output_fn=lambda o: proj(o))
            outs, _, _ = nn.dynamic_decode(dec, inits=jnp.zeros((B, D)),
                                           max_step_num=5,
                                           return_length=True)
            return np.asarray(outs.sample_ids)

        a, b, c = mk(3), mk(3), mk(4)
        assert a.min() >= 0 and a.max() < V
        np.testing.assert_array_equal(a, b)  # same seed → same samples
        assert not np.array_equal(a, c)      # different seed differs

"""Detection ops: iou_similarity, bipartite_match, target_assign,
mine_hard_examples, box_coder, ssd_loss, prior_box.

Oracles transcribe the reference kernels in numpy (SURVEY §4 OpTest
style): operators/detection/{iou_similarity_op.h, bipartite_match_op.cc,
mine_hard_examples_op.cc, box_coder_op.h}.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.framework.errors import InvalidArgumentError


def _iou_np(x, y, normalized=True):
    off = 0.0 if normalized else 1.0
    out = np.zeros((x.shape[0], y.shape[0]), np.float64)
    for i, a in enumerate(x):
        for j, b in enumerate(y):
            iw = min(a[2], b[2]) - max(a[0], b[0]) + off
            ih = min(a[3], b[3]) - max(a[1], b[1]) + off
            inter = max(iw, 0) * max(ih, 0)
            ua = ((a[2] - a[0] + off) * (a[3] - a[1] + off)
                  + (b[2] - b[0] + off) * (b[3] - b[1] + off) - inter)
            out[i, j] = inter / ua if ua > 0 else 0.0
    return out


def _bipartite_np(dist, match_type="bipartite", threshold=0.5):
    """Transcribes BipartiteMatch + ArgMaxMatch (bipartite_match_op.cc)."""
    G, P = dist.shape
    match = np.full(P, -1, np.int32)
    mdist = np.zeros(P, dist.dtype)
    row_pool = list(range(G))
    while row_pool:
        best = (-1, -1, -1.0)
        for j in range(P):
            if match[j] != -1:
                continue
            for i in row_pool:
                if dist[i, j] < 1e-6:
                    continue
                if dist[i, j] > best[2]:
                    best = (i, j, dist[i, j])
        if best[0] == -1:
            break
        match[best[1]] = best[0]
        mdist[best[1]] = best[2]
        row_pool.remove(best[0])
    if match_type == "per_prediction":
        for j in range(P):
            if match[j] != -1:
                continue
            cand = [(dist[i, j], i) for i in range(G)
                    if dist[i, j] >= max(threshold, 1e-6)]
            if cand:
                d, i = max(cand)
                match[j] = i
                mdist[j] = d
    return match, mdist


class TestIouSimilarity:
    def test_reference_doc_example(self):
        x = np.array([[0.5, 0.5, 2.0, 2.0], [0., 0., 1.0, 1.0]], np.float32)
        y = np.array([[1.0, 1.0, 2.5, 2.5]], np.float32)
        out = np.asarray(F.iou_similarity(x, y))
        np.testing.assert_allclose(out, [[0.2857143], [0.0]], atol=1e-6)

    @pytest.mark.parametrize("normalized", [True, False])
    def test_vs_oracle(self, normalized):
        rng = np.random.RandomState(0)
        mins = rng.uniform(0, 5, size=(7, 2))
        x = np.concatenate([mins, mins + rng.uniform(0.5, 4, (7, 2))], 1)
        mins = rng.uniform(0, 5, size=(9, 2))
        y = np.concatenate([mins, mins + rng.uniform(0.5, 4, (9, 2))], 1)
        out = np.asarray(F.iou_similarity(x.astype(np.float32),
                                          y.astype(np.float32),
                                          box_normalized=normalized))
        np.testing.assert_allclose(out, _iou_np(x, y, normalized), atol=1e-5)


class TestBipartiteMatch:
    @pytest.mark.parametrize("match_type", ["bipartite", "per_prediction"])
    def test_vs_oracle(self, match_type):
        rng = np.random.RandomState(1)
        for _ in range(4):
            dist = rng.uniform(0, 1, size=(5, 12)).astype(np.float32)
            dist[rng.uniform(size=dist.shape) < 0.3] = 0.0
            idx, d = F.bipartite_match(dist, match_type, 0.5)
            widx, wd = _bipartite_np(dist, match_type, 0.5)
            np.testing.assert_array_equal(np.asarray(idx)[0], widx)
            np.testing.assert_allclose(np.asarray(d)[0], wd, atol=1e-6)

    def test_each_gt_matched_once(self):
        rng = np.random.RandomState(2)
        dist = rng.uniform(0.1, 1, size=(4, 10)).astype(np.float32)
        idx, _ = F.bipartite_match(dist)
        matched = np.asarray(idx)[0]
        pos = matched[matched != -1]
        assert len(np.unique(pos)) == len(pos) == 4


class TestTargetAssign:
    def test_labels_and_weights(self):
        labels = jnp.asarray([[[3], [5]]], jnp.int64)  # [1, G=2, 1]
        match = jnp.asarray([[0, -1, 1, -1]], jnp.int32)
        out, w = F.target_assign(labels, match, mismatch_value=0)
        np.testing.assert_array_equal(np.asarray(out)[0, :, 0], [3, 0, 5, 0])
        np.testing.assert_array_equal(np.asarray(w)[0, :, 0], [1, 0, 1, 0])

    def test_negative_mask_weights(self):
        labels = jnp.zeros((1, 2, 1), jnp.int64)
        match = jnp.asarray([[0, -1, -1, 1]], jnp.int32)
        neg = jnp.asarray([[False, True, False, False]])
        _, w = F.target_assign(labels, match, negative_mask=neg)
        np.testing.assert_array_equal(np.asarray(w)[0, :, 0], [1, 1, 0, 1])

    def test_per_prior_gather(self):
        x = jnp.asarray(np.arange(2 * 3 * 4 * 4).reshape(2, 3, 4, 4),
                        jnp.float32)  # [N, G, P, K]
        match = jnp.asarray([[2, -1, 0, 1], [-1, 1, 1, -1]], jnp.int32)
        out, _ = F.target_assign(x, match, mismatch_value=-9)
        xn = np.asarray(x)
        for n in range(2):
            for p in range(4):
                m = np.asarray(match)[n, p]
                want = xn[n, m, p] if m != -1 else np.full(4, -9.0)
                np.testing.assert_array_equal(np.asarray(out)[n, p], want)


class TestMineHardExamples:
    def test_quota_and_ordering(self):
        """2 positives, ratio 1.5 → 3 negatives, the highest-loss eligible."""
        cls_loss = jnp.asarray(
            [[0.1, 0.9, 0.5, 0.7, 0.3, 0.2, 0.8, 0.4]], jnp.float32)
        match = jnp.asarray([[0, -1, -1, -1, -1, -1, 1, -1]], jnp.int32)
        dist = jnp.asarray([[0.9, 0.1, 0.2, 0.1, 0.1, 0.7, 0.8, 0.1]],
                           jnp.float32)
        neg, updated = F.mine_hard_examples(
            cls_loss, match, dist, neg_pos_ratio=1.5, neg_dist_threshold=0.5)
        # eligible: cols 1,2,3,4,7 (unmatched & dist<0.5); top-3 by loss:
        # col1 (.9), col3 (.7), col2 (.5)
        np.testing.assert_array_equal(
            np.asarray(neg)[0],
            [False, True, True, True, False, False, False, False])
        np.testing.assert_array_equal(np.asarray(updated), np.asarray(match))


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(3)
        mins = rng.uniform(0, 5, (6, 2))
        priors = np.concatenate([mins, mins + rng.uniform(1, 3, (6, 2))],
                                1).astype(np.float32)
        mins = rng.uniform(0, 5, (4, 2))
        targets = np.concatenate([mins, mins + rng.uniform(1, 3, (4, 2))],
                                 1).astype(np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        enc = F.box_coder(priors, var, targets)  # [4, 6, 4]
        assert enc.shape == (4, 6, 4)
        dec = F.box_coder(priors, var, enc, code_type="decode_center_size")
        # decoding each target's own encoding against the same prior
        # recovers the target box
        for g in range(4):
            for p in range(6):
                np.testing.assert_allclose(np.asarray(dec)[g, p],
                                           targets[g], atol=1e-4)


class TestBoxCoderAxisVar:
    def _boxes(self, rng, n):
        mins = rng.uniform(0, 5, (n, 2))
        return np.concatenate([mins, mins + rng.uniform(1, 3, (n, 2))],
                              1).astype(np.float32)

    def _decode_np(self, priors, var, target, axis):
        """Transcribes DecodeCenterSize (box_coder_op.h:119-185)."""
        R, C, _ = target.shape
        out = np.zeros_like(target)
        for i in range(R):
            for j in range(C):
                k = j if axis == 0 else i
                pw = priors[k, 2] - priors[k, 0]
                ph = priors[k, 3] - priors[k, 1]
                px = priors[k, 0] + pw / 2
                py = priors[k, 1] + ph / 2
                v = var if var.ndim == 1 else var[k]
                cx = v[0] * target[i, j, 0] * pw + px
                cy = v[1] * target[i, j, 1] * ph + py
                w = np.exp(v[2] * target[i, j, 2]) * pw
                h = np.exp(v[3] * target[i, j, 3]) * ph
                out[i, j] = [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]
        return out

    @pytest.mark.parametrize("axis", [0, 1])
    @pytest.mark.parametrize("per_prior", [False, True])
    def test_decode_axis_vs_oracle(self, axis, per_prior):
        rng = np.random.RandomState(7)
        R, C = 5, 6
        P = C if axis == 0 else R
        priors = self._boxes(rng, P)
        var = (rng.uniform(0.05, 0.3, (P, 4)).astype(np.float32) if per_prior
               else np.array([0.1, 0.1, 0.2, 0.2], np.float32))
        target = rng.uniform(-0.5, 0.5, (R, C, 4)).astype(np.float32)
        out = np.asarray(F.box_coder(priors, var, target,
                                     code_type="decode_center_size",
                                     axis=axis))
        np.testing.assert_allclose(out, self._decode_np(priors, var, target,
                                                        axis), atol=1e-4)

    def test_encode_per_prior_var(self):
        rng = np.random.RandomState(8)
        priors = self._boxes(rng, 6)
        targets = self._boxes(rng, 4)
        pvar = rng.uniform(0.05, 0.3, (6, 4)).astype(np.float32)
        enc = np.asarray(F.box_coder(priors, pvar, targets))
        enc1 = np.asarray(F.box_coder(priors, None, targets))
        np.testing.assert_allclose(enc, enc1 / pvar[None], atol=1e-5)

    def test_bad_var_shape_raises(self):
        rng = np.random.RandomState(9)
        priors = self._boxes(rng, 3)
        with pytest.raises(Exception):
            F.box_coder(priors, np.ones((3, 3), np.float32), priors)


class TestBipartiteDefaultThreshold:
    def test_default_is_half(self):
        # op attr dist_threshold defaults to 0.5 (bipartite_match_op.cc);
        # a prior whose best IoU is 0.1 must stay unmatched by default
        dist = np.array([[0.9, 0.1, 0.0],
                         [0.0, 0.0, 0.0]], np.float32)
        idx, _ = F.bipartite_match(dist, "per_prediction")
        np.testing.assert_array_equal(np.asarray(idx)[0], [0, -1, -1])
        idx2, _ = F.bipartite_match(dist, "per_prediction", 0.05)
        np.testing.assert_array_equal(np.asarray(idx2)[0], [0, 0, -1])


class TestSsdLoss:
    def _inputs(self, N=2, P=8, C=4, G=3):
        rng = np.random.RandomState(4)
        loc = rng.randn(N, P, 4).astype(np.float32)
        conf = rng.randn(N, P, C).astype(np.float32)
        mins = rng.uniform(0, 0.6, (N, G, 2))
        gt_box = np.concatenate(
            [mins, mins + rng.uniform(0.1, 0.4, (N, G, 2))], -1
        ).astype(np.float32)
        gt_box[1, 2] = 0  # padded gt row — must be inert
        gt_label = rng.randint(1, C, size=(N, G)).astype(np.int64)
        mins = rng.uniform(0, 0.7, (P, 2))
        priors = np.concatenate([mins, mins + rng.uniform(0.1, 0.4, (P, 2))],
                                -1).astype(np.float32)
        pvar = np.tile(np.array([[0.1, 0.1, 0.2, 0.2]], np.float32), (P, 1))
        return loc, conf, gt_box, gt_label, priors, pvar

    def test_shape_finite_positive(self):
        loc, conf, gt_box, gt_label, priors, pvar = self._inputs()
        loss = F.ssd_loss(loc, conf, gt_box, gt_label, priors, pvar[0])
        assert loss.shape == (2, 1)
        assert np.isfinite(np.asarray(loss)).all()
        assert (np.asarray(loss) > 0).all()

    def test_differentiable_and_jits(self):
        loc, conf, gt_box, gt_label, priors, pvar = self._inputs()

        @jax.jit
        def total(loc, conf):
            return jnp.sum(F.ssd_loss(loc, conf, gt_box, gt_label, priors,
                                      pvar[0]))

        g_loc, g_conf = jax.grad(total, argnums=(0, 1))(
            jnp.asarray(loc), jnp.asarray(conf))
        assert np.isfinite(np.asarray(g_loc)).all()
        assert np.isfinite(np.asarray(g_conf)).all()
        assert float(jnp.abs(g_conf).sum()) > 0

    def test_perfect_predictions_lower_loss(self):
        loc, conf, gt_box, gt_label, priors, pvar = self._inputs()
        base = float(F.ssd_loss(loc, conf, gt_box, gt_label, priors,
                                pvar[0]).sum())
        enc = np.asarray(F.box_coder(priors, pvar[0], gt_box))  # [N,G,P,4]
        iou = np.asarray(F.iou_similarity(gt_box, priors))
        midx, _ = F.bipartite_match(iou, "per_prediction", 0.5)
        midx = np.asarray(midx)
        loc2 = loc.copy()
        conf2 = np.full_like(conf, -8.0)
        conf2[..., 0] = 8.0  # background everywhere...
        for n in range(loc.shape[0]):
            for p in range(loc.shape[1]):
                if midx[n, p] != -1:
                    loc2[n, p] = enc[n, midx[n, p], p]
                    conf2[n, p, :] = -8.0
                    conf2[n, p, gt_label[n, midx[n, p]]] = 8.0  # ...true class
        better = float(F.ssd_loss(loc2, conf2, gt_box, gt_label, priors,
                                  pvar[0]).sum())
        assert better < base * 0.25, (better, base)


def _nms_np(boxes, scores, score_thr, top_k, iou_thr, eta):
    """Transcribes NMSFast (multiclass_nms_op.cc:139-192)."""
    order = np.argsort(-scores, kind="stable")
    if top_k is not None and top_k >= 0:
        order = order[:top_k]
    order = [i for i in order if scores[i] > score_thr]
    selected = []
    thr = iou_thr
    for i in order:
        keep = True
        for j in selected:
            if _iou_np(boxes[i:i + 1], boxes[j:j + 1])[0, 0] > thr:
                keep = False
                break
        if keep:
            selected.append(i)
            if eta < 1 and thr > 0.5:
                thr *= eta
    return selected


class TestNms:
    @pytest.mark.parametrize("eta", [1.0, 0.9])
    def test_vs_oracle(self, eta):
        rng = np.random.RandomState(0)
        for _ in range(3):
            mins = rng.uniform(0, 10, (20, 2))
            boxes = np.concatenate([mins, mins + rng.uniform(1, 6, (20, 2))],
                                   1).astype(np.float32)
            scores = rng.uniform(0, 1, 20).astype(np.float32)
            keep = np.asarray(F.nms(boxes, scores, score_threshold=0.1,
                                    nms_top_k=15, nms_threshold=0.4,
                                    nms_eta=eta))
            want = _nms_np(boxes, scores, 0.1, 15, 0.4, eta)
            np.testing.assert_array_equal(np.where(keep)[0], sorted(want))

    def test_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 10.5, 10.5],
                          [20, 20, 30, 30]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = np.asarray(F.nms(boxes, scores, nms_threshold=0.5))
        np.testing.assert_array_equal(keep, [True, False, True])


class TestMulticlassNms:
    def test_end_to_end(self):
        rng = np.random.RandomState(1)
        N, M, C = 2, 12, 4
        mins = rng.uniform(0, 10, (N, M, 2))
        boxes = np.concatenate([mins, mins + rng.uniform(1, 5, (N, M, 2))],
                               -1).astype(np.float32)
        scores = rng.uniform(0, 1, (N, C, M)).astype(np.float32)
        out, nums = F.multiclass_nms(boxes, scores, score_threshold=0.3,
                                     nms_top_k=10, keep_top_k=5,
                                     nms_threshold=0.4, return_num=True)
        assert out.shape == (N, 5, 6)
        o = np.asarray(out)
        n = np.asarray(nums)
        for i in range(N):
            rows = o[i, :n[i]]
            assert (rows[:, 0] != 0).all(), "background must be excluded"
            assert (np.diff(rows[:, 1]) <= 1e-6).all(), "sorted by score"
            assert (o[i, n[i]:] == -1).all(), "padding rows are -1"
            # every kept row agrees with a single-class oracle run
            for lab in np.unique(rows[:, 0]):
                sel = _nms_np(boxes[i], scores[i, int(lab)], 0.3, 10, 0.4, 1.0)
                kept_boxes = rows[rows[:, 0] == lab][:, 2:]
                for kb in kept_boxes:
                    assert any(np.allclose(kb, boxes[i, s], atol=1e-5)
                               for s in sel)

    def test_jit(self):
        rng = np.random.RandomState(2)
        boxes = np.sort(rng.uniform(0, 9, (1, 6, 4)), -1).astype(np.float32)
        scores = rng.uniform(0, 1, (1, 3, 6)).astype(np.float32)
        f = jax.jit(lambda b, s: F.multiclass_nms(
            b, s, score_threshold=0.2, nms_top_k=6, keep_top_k=4))
        assert f(boxes, scores).shape == (1, 4, 6)


class TestDetectionOutput:
    def test_decode_then_nms(self):
        rng = np.random.RandomState(3)
        M, C = 8, 3
        mins = rng.uniform(0, 0.6, (M, 2))
        priors = np.concatenate([mins, mins + rng.uniform(0.1, 0.3, (M, 2))],
                                -1).astype(np.float32)
        pvar = np.tile(np.array([[0.1, 0.1, 0.2, 0.2]], np.float32), (M, 1))
        loc = np.zeros((1, M, 4), np.float32)  # zero offsets → priors
        scores = rng.uniform(0, 1, (1, M, C)).astype(np.float32)
        out, nums = F.detection_output(loc, scores, priors, pvar,
                                       keep_top_k=6, return_index=True)
        o = np.asarray(out)[0]
        n = int(np.asarray(nums)[0])
        assert n > 0
        for row in o[:n]:  # zero offsets decode back to the prior boxes
            assert any(np.allclose(row[2:], p, atol=1e-4) for p in priors)

    def test_scores_are_softmaxed(self):
        """The reference softmaxes logits before NMS (detection.py:720):
        output scores must be probabilities, and a large negative logit
        with the rest even MORE negative must still pass the 0.01
        threshold (its probability is ~1)."""
        M, C = 4, 3
        mins = np.array([[0.0, 0.0], [0.3, 0.3], [0.6, 0.6], [0.1, 0.7]],
                        np.float32)
        priors = np.concatenate([mins, mins + 0.2], -1)
        pvar = np.tile(np.array([[0.1, 0.1, 0.2, 0.2]], np.float32), (M, 1))
        loc = np.zeros((1, M, 4), np.float32)
        logits = np.full((1, M, C), -30.0, np.float32)
        logits[0, :, 1] = -10.0  # class 1 dominates despite raw value < 0
        out, nums = F.detection_output(loc, logits, priors, pvar,
                                       keep_top_k=4, return_index=True)
        n = int(np.asarray(nums)[0])
        assert n > 0, "softmaxed scores must clear the 0.01 threshold"
        rows = np.asarray(out)[0, :n]
        assert (rows[:, 1] > 0.9).all(), "scores must be probabilities"
        assert (rows[:, 0] == 1).all() and (rows[:, 0] != 0).all()


def _matrix_nms_np(boxes, scores, score_thr, post_thr, top_k, gaussian,
                   sigma):
    """Transcribes NMSMatrix (matrix_nms_op.cc:100-166), one class."""
    order = [i for i in np.argsort(-scores, kind="stable")
             if scores[i] > score_thr]
    if top_k > -1:
        order = order[:top_k]
    if not order:
        return [], []
    n = len(order)
    iou = _iou_np(boxes[order], boxes[order])
    iou_max = np.zeros(n)
    for i in range(1, n):
        iou_max[i] = iou[i, :i].max()
    sel, ds_out = [], []
    if scores[order[0]] > post_thr:
        sel.append(order[0])
        ds_out.append(scores[order[0]])
    for i in range(1, n):
        decay = 1.0
        for j in range(i):
            if gaussian:
                d = np.exp((iou_max[j] ** 2 - iou[i, j] ** 2) * sigma)
            else:
                d = (1 - iou[i, j]) / (1 - iou_max[j])
            decay = min(decay, d)
        ds = decay * scores[order[i]]
        if ds > post_thr:
            sel.append(order[i])
            ds_out.append(ds)
    return sel, ds_out


class TestMatrixNms:
    @pytest.mark.parametrize("gaussian", [False, True])
    def test_vs_oracle_single_class(self, gaussian):
        rng = np.random.RandomState(0)
        mins = rng.uniform(0, 0.6, (10, 2))
        boxes = np.concatenate([mins, mins + rng.uniform(0.1, 0.4, (10, 2))],
                               -1).astype(np.float32)
        scores = rng.uniform(0, 1, (1, 2, 10)).astype(np.float32)
        scores[0, 0] = 0.0  # background row (excluded)
        out, nums = F.matrix_nms(boxes[None], scores, score_threshold=0.2,
                                 post_threshold=0.1, nms_top_k=8,
                                 keep_top_k=8, use_gaussian=gaussian,
                                 background_label=0, return_rois_num=True)
        sel, ds = _matrix_nms_np(boxes, scores[0, 1], 0.2, 0.1, 8,
                                 gaussian, 2.0)
        n = int(np.asarray(nums)[0])
        assert n == len(sel)
        got = np.asarray(out)[0, :n]
        np.testing.assert_allclose(np.sort(got[:, 1])[::-1],
                                   np.sort(ds)[::-1], atol=1e-5)
        for row in got:
            assert row[0] == 1
            assert any(np.allclose(row[2:], boxes[s], atol=1e-5)
                       for s in sel)

    def test_decays_overlapping(self):
        """A near-duplicate of a higher-scored box is heavily decayed."""
        boxes = np.array([[0, 0, 1, 1], [0.01, 0, 1, 1],
                          [2, 2, 3, 3]], np.float32)[None]
        scores = np.array([[[0.9, 0.85, 0.8]]], np.float32)  # one class
        out = F.matrix_nms(boxes, scores, score_threshold=0.0,
                           post_threshold=0.0, nms_top_k=-1, keep_top_k=3,
                           background_label=-1)
        o = np.asarray(out)[0]
        by_box = {tuple(round(float(v), 2) for v in r[2:]): r[1]
                  for r in o if r[0] >= 0}
        assert by_box[(0.0, 0.0, 1.0, 1.0)] > 0.89
        assert by_box[(2.0, 2.0, 3.0, 3.0)] > 0.79  # disjoint: no decay
        assert by_box[(0.01, 0.0, 1.0, 1.0)] < 0.1  # near-dup: crushed

    def test_jit(self):
        boxes = jnp.asarray(np.sort(np.random.RandomState(1).rand(1, 6, 4),
                                    -1), jnp.float32)
        scores = jnp.asarray(np.random.RandomState(2).rand(1, 3, 6),
                             jnp.float32)
        f = jax.jit(lambda b, s: F.matrix_nms(
            b, s, 0.1, 0.05, nms_top_k=6, keep_top_k=4))
        assert f(boxes, scores).shape == (1, 4, 6)

    def test_return_index_points_at_boxes(self):
        boxes = np.array([[[0, 0, 1, 1], [2, 2, 3, 3]]], np.float32)
        scores = np.array([[[0.2, 0.9]]], np.float32)
        out, index = F.matrix_nms(boxes, scores, 0.0, 0.0, nms_top_k=-1,
                                  keep_top_k=2, background_label=-1,
                                  return_index=True)
        o, ix = np.asarray(out), np.asarray(index)
        assert ix[0, 0] == 1  # highest score is box 1
        np.testing.assert_allclose(o[0, 0, 2:], boxes[0, ix[0, 0]])


class TestDensityPriorBox:
    def test_shapes_and_counts(self):
        feat = jnp.zeros((1, 8, 4, 4))
        img = jnp.zeros((1, 3, 32, 32))
        boxes, var = F.density_prior_box(
            feat, img, densities=[2, 1], fixed_sizes=[4.0, 8.0],
            fixed_ratios=[1.0, 2.0], clip=True)
        # K = Σ ratios·density² = 2·4 + 2·1 = 10
        assert boxes.shape == (4, 4, 10, 4)
        assert var.shape == boxes.shape
        b = np.asarray(boxes)
        assert (b >= 0).all() and (b <= 1).all()

    def test_density_grid_centers(self):
        """density=2 lays a 2x2 sub-grid shifted by step_average/2
        (density_prior_box_op.h:91-101)."""
        feat = jnp.zeros((1, 1, 1, 1))
        img = jnp.zeros((1, 3, 8, 8))
        boxes, _ = F.density_prior_box(
            feat, img, densities=[2], fixed_sizes=[2.0], fixed_ratios=[1.0])
        b = np.asarray(boxes)[0, 0]  # [4, 4]
        centers = ((b[:, :2] + b[:, 2:]) / 2) * 8
        # cell center (4,4), step_avg 8, shift 4 → centers at 2 and 6
        want = {(2.0, 2.0), (6.0, 2.0), (2.0, 6.0), (6.0, 6.0)}
        got = {tuple(np.round(c, 4)) for c in centers}
        assert got == want

    def test_flatten_to_2d(self):
        feat = jnp.zeros((1, 1, 2, 3))
        img = jnp.zeros((1, 3, 16, 16))
        boxes, var = F.density_prior_box(
            feat, img, densities=[1], fixed_sizes=[4.0], fixed_ratios=[1.0],
            flatten_to_2d=True)
        assert boxes.shape == (6, 4) and var.shape == (6, 4)


class TestAnchorGenerator:
    def test_kernel_arithmetic(self):
        """First cell, ratio 1, size 32, stride 16: base 16x16 rounded,
        scaled by 2 → 32x32 centered at offset*(stride-1)=7.5."""
        feat = jnp.zeros((1, 8, 2, 2))
        anchors, var = F.anchor_generator(feat, anchor_sizes=[32, 64],
                                          aspect_ratios=[1.0, 2.0],
                                          stride=[16.0, 16.0])
        assert anchors.shape == (2, 2, 4, 4) and var.shape == anchors.shape
        a = np.asarray(anchors)[0, 0, 0]
        np.testing.assert_allclose(a, [7.5 - 15.5, 7.5 - 15.5,
                                       7.5 + 15.5, 7.5 + 15.5])
        # ratio 2: base_w = round(sqrt(256/2)) = 11, base_h = 22
        a2 = np.asarray(anchors)[0, 0, 2]
        np.testing.assert_allclose(a2[2] - a2[0] + 1, 22.0)  # 32/16*11
        np.testing.assert_allclose(a2[3] - a2[1] + 1, 44.0)

    def test_centers_march_with_stride(self):
        feat = jnp.zeros((1, 1, 2, 3))
        anchors, _ = F.anchor_generator(feat, [32], [1.0],
                                        stride=[16.0, 16.0])
        a = np.asarray(anchors)
        cx = (a[..., 0] + a[..., 2]) / 2
        np.testing.assert_allclose(cx[0, 1] - cx[0, 0], 16.0)


class TestGenerateProposals:
    def _setup(self, N=1, A=2, H=3, W=3):
        rng = np.random.RandomState(0)
        scores = rng.uniform(0, 1, (N, A, H, W)).astype(np.float32)
        deltas = (rng.randn(N, 4 * A, H, W) * 0.1).astype(np.float32)
        im_info = np.array([[48.0, 48.0, 1.0]] * N, np.float32)
        feat = jnp.zeros((N, 1, H, W))
        anchors, var = F.anchor_generator(feat, [16], [1.0, 2.0],
                                          stride=[16.0, 16.0])
        return scores, deltas, im_info, anchors, var

    def test_shapes_counts_and_window(self):
        scores, deltas, im_info, anchors, var = self._setup()
        rois, probs, nums = F.generate_proposals(
            scores, deltas, im_info, anchors, var, pre_nms_top_n=12,
            post_nms_top_n=6, nms_thresh=0.7, min_size=2.0,
            return_rois_num=True)
        assert rois.shape == (1, 6, 4) and probs.shape == (1, 6, 1)
        n = int(np.asarray(nums)[0])
        assert 0 < n <= 6
        r = np.asarray(rois)[0, :n]
        assert (r[:, 0] >= 0).all() and (r[:, 2] <= 47).all()
        assert (r[:, 1] >= 0).all() and (r[:, 3] <= 47).all()
        p = np.asarray(probs)[0, :n, 0]
        assert (np.diff(p) <= 1e-6).all(), "sorted by score"
        assert (np.asarray(probs)[0, n:, 0] == -1).all()

    def test_nms_suppresses_duplicate_anchors(self):
        """All-zero deltas → proposals equal the anchors; two identical
        aspect-1 anchors per cell collapse to one proposal."""
        N, H, W = 1, 2, 2
        scores = np.random.RandomState(1).uniform(
            0.2, 1, (N, 2, H, W)).astype(np.float32)
        deltas = np.zeros((N, 8, H, W), np.float32)
        im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
        feat = jnp.zeros((N, 1, H, W))
        anchors, var = F.anchor_generator(feat, [16, 16], [1.0],
                                          stride=[16.0, 16.0])
        _, _, nums = F.generate_proposals(
            scores, deltas, im_info, anchors, var, pre_nms_top_n=-1,
            post_nms_top_n=8, nms_thresh=0.5, min_size=1.0,
            return_rois_num=True)
        assert int(np.asarray(nums)[0]) == H * W  # one per cell, not two

    def test_min_size_filters(self):
        scores, deltas, im_info, anchors, var = self._setup()
        _, _, n_all = F.generate_proposals(
            scores, deltas, im_info, anchors, var, post_nms_top_n=18,
            nms_thresh=0.99, min_size=1.0, return_rois_num=True)
        _, _, n_big = F.generate_proposals(
            scores, deltas, im_info, anchors, var, post_nms_top_n=18,
            nms_thresh=0.99, min_size=30.0, return_rois_num=True)
        assert int(np.asarray(n_big)[0]) < int(np.asarray(n_all)[0])

    def test_jit(self):
        scores, deltas, im_info, anchors, var = self._setup()
        f = jax.jit(lambda s, d, i: F.generate_proposals(
            s, d, i, anchors, var, pre_nms_top_n=10, post_nms_top_n=5))
        rois, probs = f(scores, deltas, im_info)
        assert rois.shape == (1, 5, 4)


class TestBoxDecoderAndAssign:
    def test_decode_and_best_class(self):
        rng = np.random.RandomState(0)
        R, C = 4, 3
        mins = rng.uniform(0, 20, (R, 2))
        priors = np.concatenate([mins, mins + rng.uniform(4, 10, (R, 2))],
                                -1).astype(np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        deltas = (rng.randn(R, C * 4) * 0.2).astype(np.float32)
        scores = rng.uniform(0, 1, (R, C)).astype(np.float32)
        decoded, assigned = F.box_decoder_and_assign(priors, var, deltas,
                                                     scores)
        assert decoded.shape == (R, C * 4) and assigned.shape == (R, 4)
        dec = np.asarray(decoded).reshape(R, C, 4)
        # zero deltas for one (roi, class): decode must return the prior
        # in +1-pixel center-size convention
        deltas0 = deltas.copy()
        deltas0[0, 4:8] = 0.0
        dec0 = np.asarray(F.box_decoder_and_assign(
            priors, var, deltas0, scores)[0]).reshape(R, C, 4)
        np.testing.assert_allclose(dec0[0, 1], priors[0], atol=1e-4)
        # assigned row = decoded box of argmax non-background class
        best = scores[:, 1:].argmax(1) + 1
        for r in range(R):
            np.testing.assert_allclose(np.asarray(assigned)[r],
                                       dec[r, best[r]], atol=1e-5)

    def test_single_class_keeps_prior(self):
        priors = np.array([[0, 0, 10, 10]], np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        _, assigned = F.box_decoder_and_assign(
            priors, var, np.ones((1, 4), np.float32),
            np.ones((1, 1), np.float32))
        np.testing.assert_allclose(np.asarray(assigned)[0], priors[0])


class TestFpnRouting:
    def test_distribute_levels_and_restore(self):
        """16/32/64px boxes route to the min level, 256px to the refer
        level (distribute_fpn_proposals_op.h:110-113 formula)."""
        rois = np.array([[0, 0, 15, 15], [0, 0, 63, 63],
                         [0, 0, 255, 255], [0, 0, 31, 31]], np.float32)
        multi, restore, counts = F.distribute_fpn_proposals(
            rois, 2, 5, 4, 224)
        assert [int(c) for c in counts] == [3, 0, 1, 0]
        np.testing.assert_array_equal(np.asarray(restore).ravel(),
                                      [0, 1, 3, 2])
        lvl2 = np.asarray(multi[0])
        np.testing.assert_allclose(lvl2[0], rois[0])
        np.testing.assert_allclose(lvl2[2], rois[3])  # compacted order
        np.testing.assert_allclose(np.asarray(multi[2])[0], rois[2])
        assert (lvl2[3] == 0).all(), "padding rows are zero"

    def test_distribute_rois_num_masks_padding(self):
        # zero-padded rows (area 1 after the +1 convention) must not be
        # routed to min_level as real ROIs when rois_num says they are pad
        rois = np.array([[0, 0, 15, 15], [0, 0, 255, 255],
                         [0, 0, 0, 0], [0, 0, 0, 0]], np.float32)
        multi, restore, counts = F.distribute_fpn_proposals(
            rois, 2, 5, 4, 224, rois_num=2)
        assert [int(c) for c in counts] == [1, 0, 1, 0]
        np.testing.assert_array_equal(np.asarray(restore).ravel()[:2], [0, 1])
        # without rois_num the padding rows (wrongly) land on min_level —
        # the documented dense-contract hazard this argument exists to fix
        _, _, counts_no = F.distribute_fpn_proposals(rois, 2, 5, 4, 224)
        assert int(counts_no[0]) == 3
        # per-image [N] counts over PACKED rois (valid prefix) also work
        multi2, _, counts2 = F.distribute_fpn_proposals(
            rois, 2, 5, 4, 224, rois_num=np.array([1, 1]))
        assert [int(c) for c in counts2] == [1, 0, 1, 0]

    def test_distribute_blocked_input(self):
        # [N, K, 4] per-image padded blocks straight from generate_proposals:
        # each block's padding tail masks independently (interleaved padding)
        blocks = np.zeros((2, 4, 4), np.float32)
        blocks[0, 0] = [0, 0, 15, 15]
        blocks[0, 1] = [0, 0, 31, 31]   # img0: 2 valid + 2 pad
        blocks[1, :4] = [[0, 0, 63, 63], [0, 0, 255, 255],
                         [0, 0, 15, 15], [0, 0, 199, 199]]  # img1: 4 valid
        multi, restore, counts = F.distribute_fpn_proposals(
            blocks, 2, 5, 4, 224, rois_num=np.array([2, 4]))
        # valid rois: 16,32 (img0) + 64,256,16,200 (img1) → lvl2: 16,32,64,16
        # lvl4: 200 → actually 200px → lvl4; 256 → lvl4
        assert sum(int(c) for c in counts) == 6
        assert int(counts[0]) == 4  # 15/31/63/15-px boxes at min level
        # image-0 padding rows routed nowhere
        lvl2 = np.asarray(multi[0])
        np.testing.assert_allclose(lvl2[0], blocks[0, 0])
        np.testing.assert_allclose(lvl2[1], blocks[0, 1])
        np.testing.assert_allclose(lvl2[2], blocks[1, 0])
        np.testing.assert_allclose(lvl2[3], blocks[1, 2])

    def test_collect_top_k_across_levels(self):
        rois = np.array([[0, 0, 15, 15], [0, 0, 63, 63],
                         [0, 0, 255, 255], [0, 0, 31, 31]], np.float32)
        multi, _, counts = F.distribute_fpn_proposals(rois, 2, 5, 4, 224)
        scores = [np.full(4, 0.1 * (i + 1), np.float32)
                  for i in range(4)]
        scores[0][1] = 0.9  # the 64px box wins
        out, n = F.collect_fpn_proposals(
            [np.asarray(m) for m in multi], scores, 2, 5, 2,
            rois_num_per_level=[int(c) for c in counts])
        assert int(n) == 2
        np.testing.assert_allclose(np.asarray(out)[0], rois[1])
        # padded level entries (masked to -inf) must never be collected
        out4, n4 = F.collect_fpn_proposals(
            [np.asarray(m) for m in multi], scores, 2, 5, 16,
            rois_num_per_level=[int(c) for c in counts])
        assert int(n4) == 4


class TestBoxClip:
    def test_clips_to_image(self):
        boxes = np.array([[[-5.0, -2.0, 50.0, 60.0],
                           [1.0, 2.0, 3.0, 4.0]]], np.float32)
        im_info = np.array([[40.0, 30.0, 1.0]], np.float32)  # h=40 w=30
        out = np.asarray(F.box_clip(boxes, im_info))
        np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 29.0, 39.0])
        np.testing.assert_allclose(out[0, 1], [1.0, 2.0, 3.0, 4.0])


def _roi_align_np(feat, rois, batch_ids, ph, pw, scale, ratio):
    """Transcribes roi_align_op.h:140-240 (fixed sampling grid)."""
    R = rois.shape[0]
    C, H, W = feat.shape[1:]
    out = np.zeros((R, C, ph, pw), np.float64)

    def bilinear(img, y, x):
        if y < -1.0 or y > H or x < -1.0 or x > W:
            return np.zeros(C)
        y, x = max(y, 0.0), max(x, 0.0)
        yl, xl = min(int(np.floor(y)), H - 1), min(int(np.floor(x)), W - 1)
        if yl >= H - 1:
            y = yl = H - 1
        if xl >= W - 1:
            x = xl = W - 1
        yh, xh = min(yl + 1, H - 1), min(xl + 1, W - 1)
        ly, lx = y - yl, x - xl
        return (img[:, yl, xl] * (1 - ly) * (1 - lx)
                + img[:, yl, xh] * (1 - ly) * lx
                + img[:, yh, xl] * ly * (1 - lx)
                + img[:, yh, xh] * ly * lx)

    for r in range(R):
        x0, y0, x1, y1 = rois[r] * scale
        rw = max(x1 - x0, 1.0)
        rh = max(y1 - y0, 1.0)
        bw, bh = rw / pw, rh / ph
        img = feat[batch_ids[r]]
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(C)
                for iy in range(ratio):
                    for ix in range(ratio):
                        y = y0 + i * bh + (iy + 0.5) * bh / ratio
                        x = x0 + j * bw + (ix + 0.5) * bw / ratio
                        acc += bilinear(img, y, x)
                out[r, :, i, j] = acc / (ratio * ratio)
    return out


class TestRoiAlign:
    def test_vs_oracle(self):
        rng = np.random.RandomState(0)
        feat = rng.randn(2, 3, 8, 8).astype(np.float32)
        rois = np.array([[1.0, 1.0, 6.0, 6.0], [0.0, 0.0, 3.5, 5.0],
                         [2.0, 3.0, 7.0, 7.5]], np.float32)
        rois_num = np.array([2, 1], np.int32)
        out = F.roi_align(feat, rois, pooled_height=2, pooled_width=2,
                          spatial_scale=0.5, sampling_ratio=2,
                          rois_num=rois_num)
        want = _roi_align_np(feat, rois, [0, 0, 1], 2, 2, 0.5, 2)
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)

    def test_jit_and_grad(self):
        feat = jnp.asarray(np.random.RandomState(1).randn(1, 2, 6, 6),
                           jnp.float32)
        rois = jnp.asarray([[0.0, 0.0, 4.0, 4.0]], jnp.float32)
        g = jax.grad(lambda f: jnp.sum(F.roi_align(
            f, rois, 2, 2, 1.0, 2) ** 2))(feat)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0


class TestRoiPool:
    def test_max_per_bin(self):
        """A ROI covering the whole map with 1x1 pooling is a global max."""
        rng = np.random.RandomState(2)
        feat = rng.randn(1, 2, 6, 6).astype(np.float32)
        rois = np.array([[0.0, 0.0, 5.0, 5.0]], np.float32)
        out = F.roi_pool(feat, rois, 1, 1, 1.0)
        np.testing.assert_allclose(np.asarray(out)[0, :, 0, 0],
                                   feat[0].max(axis=(1, 2)), atol=1e-6)

    def test_bin_partition(self):
        """2x2 pooling over a 4x4 ROI: each bin is a 2x2 quadrant max
        (roi_pool_op.h integer partition)."""
        feat = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0.0, 0.0, 3.0, 3.0]], np.float32)
        out = np.asarray(F.roi_pool(feat, rois, 2, 2, 1.0))[0, 0]
        np.testing.assert_allclose(out, [[5.0, 7.0], [13.0, 15.0]])

    def test_rois_num_batching(self):
        feat = np.zeros((2, 1, 4, 4), np.float32)
        feat[1] = 7.0
        rois = np.array([[0, 0, 3, 3], [0, 0, 3, 3]], np.float32)
        out = np.asarray(F.roi_pool(feat, rois, 1, 1, 1.0,
                                    rois_num=np.array([1, 1])))
        assert out[0, 0, 0, 0] == 0.0 and out[1, 0, 0, 0] == 7.0


class TestPsroiPool:
    def test_position_sensitive_channels(self):
        """Each output bin reads ONLY its dedicated channel: channel
        (c·PH+ph)·PW+pw filled with a marker shows up at exactly
        (c, ph, pw)."""
        C, PH, PW, H, W = 2, 2, 2, 4, 4
        x = np.zeros((1, C * PH * PW, H, W), np.float32)
        for c in range(C):
            for ph in range(PH):
                for pw in range(PW):
                    x[0, (c * PH + ph) * PW + pw] = 100 * c + 10 * ph + pw
        rois = np.array([[0, 0, 3, 3]], np.float32)
        out = np.asarray(F.psroi_pool(x, rois, C, 1.0, PH, PW))
        for c in range(C):
            for ph in range(PH):
                for pw in range(PW):
                    np.testing.assert_allclose(out[0, c, ph, pw],
                                               100 * c + 10 * ph + pw)

    def test_bin_average_oracle(self):
        """1-channel output, 2x2 bins over a 4x4 ROI: each bin is the
        mean of its quadrant in its dedicated channel."""
        PH = PW = 2
        x = np.zeros((1, 4, 4, 4), np.float32)
        x[0, 0] = np.arange(16).reshape(4, 4)  # channel for (0,0,0)
        rois = np.array([[0, 0, 3, 3]], np.float32)
        out = np.asarray(F.psroi_pool(x, rois, 1, 1.0, PH, PW))
        np.testing.assert_allclose(out[0, 0, 0, 0],
                                   np.arange(16).reshape(4, 4)[:2, :2].mean())

    def test_channel_validation_and_batching(self):
        with pytest.raises(InvalidArgumentError):
            F.psroi_pool(np.zeros((1, 7, 4, 4), np.float32),
                         np.zeros((1, 4), np.float32), 2, 1.0, 2, 2)
        x = np.zeros((2, 4, 4, 4), np.float32)
        x[1] = 5.0
        rois = np.array([[0, 0, 3, 3], [0, 0, 3, 3]], np.float32)
        out = np.asarray(F.psroi_pool(x, rois, 1, 1.0, 2, 2,
                                      rois_num=np.array([1, 1])))
        assert out[0].max() == 0.0 and out[1].min() == 5.0


class TestSigmoidFocalLoss:
    def _oracle(self, x, label, fg, gamma, alpha):
        N, C = x.shape
        out = np.zeros_like(x, np.float64)
        fg = max(fg, 1)
        for i in range(N):
            for d in range(C):
                g = label[i, 0]
                c_pos = float(g == d + 1)
                c_neg = float((g != -1) and (g != d + 1))
                p = 1.0 / (1.0 + np.exp(-x[i, d]))
                term_pos = (1 - p) ** gamma * np.log(max(p, 1e-37))
                xx = x[i, d]
                term_neg = p ** gamma * (
                    -xx * (xx >= 0) - np.log1p(np.exp(xx - 2 * xx * (xx >= 0))))
                out[i, d] = (-c_pos * term_pos * alpha / fg
                             - c_neg * term_neg * (1 - alpha) / fg)
        return out

    def test_vs_oracle(self):
        rng = np.random.RandomState(3)
        x = rng.randn(6, 4).astype(np.float32) * 3
        label = np.array([[1], [0], [3], [-1], [4], [2]], np.int32)
        out = F.sigmoid_focal_loss(x, label, fg_num=4)
        want = self._oracle(x, label, 4, 2.0, 0.25)
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)
        # ignored rows (label -1) contribute nothing
        assert np.abs(np.asarray(out)[3]).sum() == 0

    def test_grad_finite(self):
        x = jnp.asarray(np.random.RandomState(4).randn(3, 5), jnp.float32)
        label = jnp.asarray([[2], [0], [5]], jnp.int32)
        g = jax.grad(lambda t: jnp.sum(F.sigmoid_focal_loss(t, label, 2)))(x)
        assert np.isfinite(np.asarray(g)).all()


class TestYoloBox:
    def test_decode_geometry(self):
        """Zero logits put each box center at (cell+0.5)/grid of the image
        and size anchor*img/input; conf = 0.5 passes a 0.3 threshold."""
        N, A, C, H, W = 1, 2, 3, 2, 2
        x = np.zeros((N, A * (5 + C), H, W), np.float32)
        img_size = np.array([[64, 64]], np.int32)
        anchors = [10, 14, 23, 27]
        boxes, scores = F.yolo_box(x, img_size, anchors, C,
                                   conf_thresh=0.3, downsample_ratio=32)
        assert boxes.shape == (1, A * H * W, 4)
        assert scores.shape == (1, A * H * W, C)
        b = np.asarray(boxes)
        # first anchor, cell (0,0): center (0.5/2)*64 = 16, size 10/64*64=10
        cx = (b[0, 0, 0] + b[0, 0, 2]) / 2
        cy = (b[0, 0, 1] + b[0, 0, 3]) / 2
        np.testing.assert_allclose([cx, cy], [16.0, 16.0], atol=1e-4)
        np.testing.assert_allclose(b[0, 0, 2] - b[0, 0, 0], 10.0, atol=1e-4)
        # scores = sigmoid(0) * sigmoid(0) = 0.25
        np.testing.assert_allclose(np.asarray(scores)[0, 0], 0.25, atol=1e-5)

    def test_conf_threshold_zeroes(self):
        N, A, C, H, W = 1, 1, 2, 1, 1
        x = np.zeros((N, A * (5 + C), H, W), np.float32)
        x[0, 4] = -10.0  # conf ≈ 0 → below threshold
        boxes, scores = F.yolo_box(x, np.array([[32, 32]], np.int32),
                                   [10, 10], C, conf_thresh=0.5,
                                   downsample_ratio=32)
        assert np.abs(np.asarray(boxes)).sum() == 0
        assert np.abs(np.asarray(scores)).sum() == 0

    def test_clip_bbox(self):
        N, A, C, H, W = 1, 1, 1, 1, 1
        x = np.zeros((N, A * (5 + C), H, W), np.float32)
        x[0, 2] = 3.0  # exp(3) * anchor → much wider than the image
        boxes, _ = F.yolo_box(x, np.array([[32, 32]], np.int32), [30, 30],
                              C, conf_thresh=0.1, downsample_ratio=32)
        b = np.asarray(boxes)[0, 0]
        assert b[0] >= 0 and b[2] <= 31.0


def _yolov3_loss_np(x, gt_box, gt_label, anchors, anchor_mask, C,
                    ignore_thresh, downsample, gt_score, label_smooth):
    """Transcribes Yolov3LossKernel::Compute (yolov3_loss_op.h:255-320)."""
    def sce(v, t):
        return max(v, 0) - v * t + np.log1p(np.exp(-abs(v)))

    def iou_c(b1, b2):
        ow = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2) - max(
            b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
        oh = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2) - max(
            b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
        inter = 0.0 if ow < 0 or oh < 0 else ow * oh
        return inter / (b1[2] * b1[3] + b2[2] * b2[3] - inter)

    N, _, H, W = x.shape
    A = len(anchor_mask)
    B = gt_box.shape[1]
    an_num = len(anchors) // 2
    in_size = downsample * H
    t = x.reshape(N, A, 5 + C, H, W)
    if label_smooth:
        d = min(1.0 / C, 1.0 / 40)
        pos, neg = 1 - d, d
    else:
        pos, neg = 1.0, 0.0
    loss = np.zeros(N)
    for n in range(N):
        obj = np.zeros((A, H, W))
        for a in range(A):
            for j in range(H):
                for i in range(W):
                    px = (i + 1 / (1 + np.exp(-t[n, a, 0, j, i]))) / W
                    py = (j + 1 / (1 + np.exp(-t[n, a, 1, j, i]))) / H
                    pw = (np.exp(t[n, a, 2, j, i])
                          * anchors[2 * anchor_mask[a]] / in_size)
                    ph = (np.exp(t[n, a, 3, j, i])
                          * anchors[2 * anchor_mask[a] + 1] / in_size)
                    best = 0.0
                    for b in range(B):
                        if gt_box[n, b, 2] <= 0 or gt_box[n, b, 3] <= 0:
                            continue
                        best = max(best, iou_c((px, py, pw, ph),
                                               gt_box[n, b]))
                    if best > ignore_thresh:
                        obj[a, j, i] = -1
        for b in range(B):
            if gt_box[n, b, 2] <= 0 or gt_box[n, b, 3] <= 0:
                continue
            gx, gy, gw, gh = gt_box[n, b]
            gi, gj = int(gx * W), int(gy * H)
            best_iou, best_n = 0.0, 0
            for an in range(an_num):
                iou = iou_c((0, 0, anchors[2 * an] / in_size,
                             anchors[2 * an + 1] / in_size),
                            (0, 0, gw, gh))
                if iou > best_iou:
                    best_iou, best_n = iou, an
            if best_n not in anchor_mask:
                continue
            a = anchor_mask.index(best_n)
            s = gt_score[n, b]
            tx, ty = gx * W - gi, gy * H - gj
            tw = np.log(gw * in_size / anchors[2 * best_n])
            th = np.log(gh * in_size / anchors[2 * best_n + 1])
            sc = (2.0 - gw * gh) * s
            loss[n] += (sce(t[n, a, 0, gj, gi], tx)
                        + sce(t[n, a, 1, gj, gi], ty)
                        + abs(t[n, a, 2, gj, gi] - tw)
                        + abs(t[n, a, 3, gj, gi] - th)) * sc
            obj[a, gj, gi] = s
            for c in range(C):
                tgt = pos if c == gt_label[n, b] else neg
                loss[n] += sce(t[n, a, 5 + c, gj, gi], tgt) * s
        for a in range(A):
            for j in range(H):
                for i in range(W):
                    o = obj[a, j, i]
                    if o > 1e-5:
                        loss[n] += sce(t[n, a, 4, j, i], 1.0) * o
                    elif o > -0.5:
                        loss[n] += sce(t[n, a, 4, j, i], 0.0)
    return loss


class TestYolov3Loss:
    def _inputs(self, N=2, H=4, W=4, C=3, B=3):
        rng = np.random.RandomState(0)
        anchors = [10, 13, 16, 30, 33, 23, 30, 61]
        anchor_mask = [1, 2]
        A = len(anchor_mask)
        x = (rng.randn(N, A * (5 + C), H, W) * 0.5).astype(np.float32)
        gt = rng.uniform(0.2, 0.8, (N, B, 4)).astype(np.float32)
        gt[:, :, 2:] = rng.uniform(0.05, 0.4, (N, B, 2))
        gt[1, 2] = 0.0  # padding row must be inert
        lab = rng.randint(0, C, (N, B)).astype(np.int32)
        score = rng.uniform(0.5, 1.0, (N, B)).astype(np.float32)
        return x, gt, lab, anchors, anchor_mask, C, score

    @pytest.mark.parametrize("smooth", [True, False])
    def test_vs_oracle(self, smooth):
        x, gt, lab, anchors, mask, C, score = self._inputs()
        out = F.yolov3_loss(x, gt, lab, anchors, mask, C,
                            ignore_thresh=0.5, downsample_ratio=32,
                            gt_score=score, use_label_smooth=smooth)
        want = _yolov3_loss_np(x, gt, lab, anchors, mask, C, 0.5, 32,
                               score, smooth)
        np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4)

    def test_default_score_and_grad(self):
        x, gt, lab, anchors, mask, C, _ = self._inputs()
        g = jax.grad(lambda t: jnp.sum(F.yolov3_loss(
            t, gt, lab, anchors, mask, C, 0.5, 32)))(jnp.asarray(x))
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0

    def test_jit(self):
        x, gt, lab, anchors, mask, C, score = self._inputs()
        f = jax.jit(lambda x, gt, lab, score: F.yolov3_loss(
            x, gt, lab, anchors, mask, C, 0.5, 32, gt_score=score))
        out = f(x, gt, lab, score)
        assert out.shape == (2,) and np.isfinite(np.asarray(out)).all()


class TestPriorBox:
    def test_shapes_and_ranges(self):
        feat = jnp.zeros((1, 8, 4, 6))
        img = jnp.zeros((1, 3, 32, 48))
        boxes, var = F.prior_box(feat, img, min_sizes=[8.0], max_sizes=[16.0],
                                 aspect_ratios=[2.0], flip=True, clip=True)
        # K = 1 (ar=1,min) + 1 (max) + 2 (ar=2, 1/2) = 4
        assert boxes.shape == (4, 6, 4, 4)
        assert var.shape == boxes.shape
        b = np.asarray(boxes)
        assert (b >= 0).all() and (b <= 1).all()
        assert (b[..., 2] >= b[..., 0]).all()

    def test_centers_follow_offset(self):
        feat = jnp.zeros((1, 1, 2, 2))
        img = jnp.zeros((1, 3, 20, 20))
        boxes, _ = F.prior_box(feat, img, min_sizes=[4.0])
        b = np.asarray(boxes)
        cx = (b[..., 0] + b[..., 2]) / 2 * 20
        np.testing.assert_allclose(cx[0, :, 0], [5.0, 15.0], atol=1e-5)


class TestLocalityAwareNms:
    def test_merges_overlapping_run(self):
        """Three near-identical consecutive boxes merge into one
        score-weighted box with accumulated score; a disjoint box
        survives separately."""
        boxes = np.array([[[0, 0, 10, 10], [0.2, 0, 10.2, 10],
                           [0.4, 0, 10.4, 10], [30, 30, 40, 40]]],
                         np.float32)
        scores = np.array([[[0.5, 0.3, 0.2, 0.9]]], np.float32)
        out = np.asarray(F.locality_aware_nms(
            boxes, scores, score_threshold=0.05, nms_top_k=-1,
            keep_top_k=4, nms_threshold=0.5))
        rows = out[0][out[0][:, 0] >= 0]
        assert len(rows) == 2
        by_score = rows[np.argsort(-rows[:, 1])]
        np.testing.assert_allclose(by_score[0, 1], 1.0, atol=1e-5)  # merged
        # weighted x-min: (0*.5 + (0.2*.3+(0*.5))/.8*... sequential merge:
        # head after b1: x=(0.2*.3+0*.5)/.8=0.075, s=.8; after b2:
        # x=(0.4*.2+0.075*.8)/1.0 = 0.14
        np.testing.assert_allclose(by_score[0, 2], 0.14, atol=1e-4)
        np.testing.assert_allclose(by_score[1, 2:], [30, 30, 40, 40])

    def test_single_class_enforced(self):
        with pytest.raises(InvalidArgumentError):
            F.locality_aware_nms(np.zeros((1, 2, 4), np.float32),
                                 np.zeros((1, 3, 2), np.float32),
                                 0.1, -1, 2)


class TestRetinanetDetectionOutput:
    """fluid.layers.retinanet_detection_output (ref:
    operators/detection/retinanet_detection_output_op.cc) — eager
    post-processor: per-level top-k decode + merged per-class NMS."""

    def test_decode_threshold_and_nms(self):
        import paddle_tpu.fluid as fluid

        # one image, 2 levels; identity deltas decode to the anchors
        anchors_l0 = np.array([[0, 0, 9, 9], [0, 0, 9, 9],
                               [30, 30, 39, 39]], np.float32)
        anchors_l1 = np.array([[50, 50, 69, 69]], np.float32)
        bboxes_l0 = np.zeros((1, 3, 4), np.float32)
        bboxes_l1 = np.zeros((1, 1, 4), np.float32)
        # class 0 scores: two overlapping anchors (NMS keeps one) + one far
        scores_l0 = np.array([[[0.9, 0.0], [0.8, 0.0],
                               [0.0, 0.7]]], np.float32)
        # highest level: BELOW score_threshold but kept (threshold 0 rule)
        scores_l1 = np.array([[[0.01, 0.0]]], np.float32)
        im_info = np.array([[100, 100, 1.0]], np.float32)

        outs = fluid.layers.retinanet_detection_output(
            [bboxes_l0, bboxes_l1], [scores_l0, scores_l1],
            [anchors_l0, anchors_l1], im_info,
            score_threshold=0.05, nms_threshold=0.3, keep_top_k=100)
        det = outs[0]
        # kept: one of the two overlapping class-1 boxes, the far class-2
        # box, and the highest-level low-score box (+ the 0.0-score
        # entries are below even the 0-threshold? 0.0 > 0.0 is False ✓)
        labels = sorted(det[:, 0].tolist())
        assert labels == [1.0, 1.0, 2.0], det
        # best detection first, decoded box == its anchor
        assert det[0, 1] == np.float32(0.9)
        np.testing.assert_allclose(det[0, 2:], [0, 0, 9, 9], atol=1e-4)
        # suppressed: the 0.8 duplicate of the same anchor
        assert not np.any(np.isclose(det[:, 1], 0.8))

    def test_im_scale_and_clipping(self):
        import paddle_tpu.fluid as fluid

        anchors = np.array([[0, 0, 19, 19]], np.float32)
        bboxes = np.zeros((1, 1, 4), np.float32)
        scores = np.ones((1, 1, 1), np.float32)
        # im_info height/width are SCALED dims; scale 2 → original 10x10,
        # decoded box /2 then clipped to 9
        im_info = np.array([[20, 20, 2.0]], np.float32)
        outs = fluid.layers.retinanet_detection_output(
            [bboxes], [scores], [anchors], im_info)
        det = outs[0]
        np.testing.assert_allclose(det[0, 2:], [0, 0, 9, 9], atol=1e-4)

"""Flagship model tests (GPT decoder, BERT encoder): shapes, causality,
masking, loss semantics, tiny-scale convergence, TP-sharded training parity
(mirrors the reference's dist_transformer.py model-level tests)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.models import (
    BertForPretraining,
    BertForSequenceClassification,
    GPTForCausalLM,
    bert_tiny,
    gpt_tiny,
)


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False


class TestGPT:
    def test_forward_shapes(self):
        paddle.seed(0)
        net = GPTForCausalLM(gpt_tiny())
        ids = jnp.asarray(np.random.randint(0, 128, (2, 10)), jnp.int32)
        logits = net(ids)
        assert logits.shape == (2, 10, 128)

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        paddle.seed(0)
        net = GPTForCausalLM(gpt_tiny())
        net.eval()
        rng = np.random.RandomState(0)
        ids_a = rng.randint(0, 128, (1, 12)).astype(np.int32)
        ids_b = ids_a.copy()
        ids_b[0, -1] = (ids_b[0, -1] + 1) % 128
        la = np.asarray(net(jnp.asarray(ids_a)))
        lb = np.asarray(net(jnp.asarray(ids_b)))
        np.testing.assert_allclose(la[0, :-1], lb[0, :-1], atol=1e-5)
        assert not np.allclose(la[0, -1], lb[0, -1])

    def test_loss_decreases(self):
        paddle.seed(0)
        cfg = gpt_tiny(num_layers=1, hidden_size=16, num_heads=2)
        net = GPTForCausalLM(cfg)
        # repetitive sequence is learnable
        ids = np.tile(np.arange(8, dtype=np.int32), (4, 2))
        model = paddle.Model(net)
        model.prepare(optimizer=popt.Adam(learning_rate=1e-2), loss=net.loss)
        l0, _ = model.train_batch([ids], [ids])
        for _ in range(60):
            l1, _ = model.train_batch([ids], [ids])
        assert l1 < l0 * 0.5, (l0, l1)

    def test_tied_lm_head(self):
        net = GPTForCausalLM(gpt_tiny())
        names = [n for n, _ in net.named_parameters()]
        assert not any("lm_head" in n for n in names)  # tied to wte


class TestBert:
    def test_forward_shapes(self):
        paddle.seed(0)
        net = BertForPretraining(bert_tiny())
        ids = jnp.asarray(np.random.randint(0, 128, (2, 12)), jnp.int32)
        mlm, nsp = net(ids)
        assert mlm.shape == (2, 12, 128)
        assert nsp.shape == (2, 2)

    def test_attention_mask_blocks_pad(self):
        """Masked (pad) positions must not influence unmasked outputs."""
        paddle.seed(0)
        net = BertForSequenceClassification(bert_tiny(), num_classes=3)
        net.eval()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (1, 10)).astype(np.int32)
        mask = np.ones((1, 10), np.float32)
        mask[0, 7:] = 0.0
        out_a = np.asarray(net(jnp.asarray(ids), attention_mask=jnp.asarray(mask)))
        ids2 = ids.copy()
        ids2[0, 8] = (ids2[0, 8] + 3) % 128  # change a padded token
        out_b = np.asarray(net(jnp.asarray(ids2), attention_mask=jnp.asarray(mask)))
        np.testing.assert_allclose(out_a, out_b, atol=1e-5)

    def test_mlm_loss_ignores_unmasked(self):
        paddle.seed(0)
        net = BertForPretraining(bert_tiny())
        ids = jnp.asarray(np.random.randint(0, 128, (2, 8)), jnp.int32)
        mlm, nsp = net(ids)
        labels_none = np.full((2, 8), -100, np.int64)
        labels_none[0, 2] = 5
        nsp_labels = np.zeros((2, 1), np.int64)
        l1 = float(net.loss(mlm, nsp, jnp.asarray(labels_none), jnp.asarray(nsp_labels)))
        assert np.isfinite(l1)
        # all-ignored MLM → only NSP contributes
        all_ignored = np.full((2, 8), -100, np.int64)
        l2 = float(net.loss(mlm, nsp, jnp.asarray(all_ignored), jnp.asarray(nsp_labels)))
        assert l2 < l1 + 10  # finite, no nan from 0/0

    def test_classification_trains(self):
        paddle.seed(0)
        net = BertForSequenceClassification(bert_tiny(num_layers=1), num_classes=2)
        rng = np.random.RandomState(0)
        # class = token[0] parity
        ids = rng.randint(0, 128, (32, 8)).astype(np.int32)
        y = (ids[:, 0] % 2).astype(np.int64).reshape(-1, 1)
        model = paddle.Model(net)
        model.prepare(optimizer=popt.Adam(learning_rate=1e-3),
                      loss=nn.CrossEntropyLoss())
        l0, _ = model.train_batch([ids], [y])
        for _ in range(80):
            l1, _ = model.train_batch([ids], [y])
        assert l1 < l0, (l0, l1)

    def test_question_answering_finetunes(self):
        """BASELINE config 3 (SQuAD fine-tune shape): the QA head learns to
        point start/end at a marker token's span."""
        from paddle_tpu.models import BertForQuestionAnswering

        paddle.seed(0)
        net = BertForQuestionAnswering(bert_tiny(num_layers=1))
        rng = np.random.RandomState(0)
        B, S, MARK = 32, 12, 7
        ids = rng.randint(8, 128, (B, S)).astype(np.int32)
        starts = rng.randint(0, S - 1, (B,))
        for i, s in enumerate(starts):
            ids[i, s] = MARK
            ids[i, s + 1] = MARK
        start_pos = starts.astype(np.int64)[:, None]
        end_pos = (starts + 1).astype(np.int64)[:, None]

        model = paddle.Model(net, inputs=["ids"], labels=["s", "e"])
        model.prepare(optimizer=popt.Adam(learning_rate=2e-3),
                      loss=net.loss)
        l0, _ = model.train_batch([ids], [start_pos, end_pos])
        for _ in range(120):
            l1, _ = model.train_batch([ids], [start_pos, end_pos])
        assert l1 < l0 * 0.3, (l0, l1)
        start_logits, end_logits = net(jnp.asarray(ids))
        acc_s = (np.asarray(start_logits).argmax(-1) == starts).mean()
        acc_e = (np.asarray(end_logits).argmax(-1) == starts + 1).mean()
        assert acc_s > 0.8 and acc_e > 0.8, (acc_s, acc_e)

    def test_qa_loss_ignores_truncated_answers(self):
        """Positions beyond the sequence (truncated answers) must be
        skipped, not clamped toward the last token."""
        from paddle_tpu.models import BertForQuestionAnswering

        rng = np.random.RandomState(0)
        s_log = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        e_log = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        in_range = np.array([1, 2, 3, 4], np.int64)[:, None]
        base = BertForQuestionAnswering.loss(s_log, e_log, in_range,
                                             in_range)
        # one example's answer truncated away → OOB position
        oob = in_range.copy()
        oob[0, 0] = 400
        mixed = BertForQuestionAnswering.loss(s_log, e_log, oob, in_range)
        assert np.isfinite(float(mixed))
        assert float(mixed) != float(base)
        # exact decomposition: the OOB start example is dropped from the
        # start-CE mean; the end-CE still averages all four
        import paddle_tpu.nn.functional as F

        want = 0.5 * (float(F.cross_entropy(s_log[1:], in_range[1:]))
                      + float(F.cross_entropy(e_log, in_range)))
        np.testing.assert_allclose(float(mixed), want, rtol=1e-6)


class TestGPTFlashRouting:
    def test_use_flash_gate(self):
        import jax

        from paddle_tpu.models.gpt import GPTConfig, ParallelAttention

        attn = ParallelAttention(GPTConfig(hidden_size=64, num_heads=1,
                                           dropout=0.1))
        on_tpu = jax.default_backend() == "tpu"
        attn.eval()  # dropout inactive → gate may open
        assert attn._use_flash(4096, None) == on_tpu
        attn.train()  # probs-dropout active → flash must stay off
        assert attn._use_flash(4096, None) is False
        attn.eval()
        assert attn._use_flash(2048, None) is False       # below gate
        assert attn._use_flash(4096, object()) is False   # extra mask
        assert attn._use_flash(4104, None) is False       # ragged blocks

        attn0 = ParallelAttention(GPTConfig(hidden_size=64, num_heads=1,
                                            dropout=0.0))
        attn0.train()  # no dropout configured → train mode is fine
        assert attn0._use_flash(4096, None) == on_tpu

    def test_flash_branch_matches_dense_in_model(self, monkeypatch):
        """Force the gate open and run ParallelAttention.forward through
        the kernel branch (Pallas interpret mode off-TPU) — it must agree
        with the dense einsum branch."""
        from paddle_tpu.models.gpt import GPTConfig, ParallelAttention

        paddle.seed(0)
        attn = ParallelAttention(GPTConfig(hidden_size=128, num_heads=2,
                                           dropout=0.0))
        attn.eval()
        x = jnp.asarray(np.random.RandomState(0).randn(1, 256, 128),
                        jnp.float32)
        dense = np.asarray(attn(x))
        monkeypatch.setattr(ParallelAttention, "_use_flash",
                            lambda self, S, m: m is None)
        flash = np.asarray(attn(x))
        np.testing.assert_allclose(flash, dense, rtol=2e-4, atol=2e-5)


class TestTPParity:
    def test_gpt_tp_matches_single(self):
        """TP=2 forward must equal the single-device forward with the same
        weights (megatron sharding is mathematically transparent)."""
        paddle.seed(0)
        net = GPTForCausalLM(gpt_tiny())
        net.eval()
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 8)), jnp.int32)
        ref = np.asarray(net(ids))

        strat = fleet.DistributedStrategy(
            tensor_parallel=True,
            tensor_parallel_configs={"tensor_parallel_degree": 2})
        fleet.init(is_collective=True, strategy=strat)
        fleet.distributed_model(net)
        assert not net.gpt.blocks[0].attn.qkv.weight.value.sharding.is_fully_replicated

        @jax.jit
        def fwd(ids):
            return net(ids)

        out = np.asarray(fwd(ids))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)

    def test_entry_compiles_tiny_proxy(self):
        """entry() builds BERT-base (heavy); validate the same path at tiny
        scale + check entry()'s structure lazily."""
        import __graft_entry__ as g

        fn_args = None  # full entry() exercised by the driver on TPU
        net = BertForSequenceClassification(bert_tiny(), num_classes=2)
        net.eval()
        params = net.param_pytree()

        def fn(params, ids):
            return nn.functional_call(net, params, ids, training=False)

        ids = jnp.asarray(np.random.randint(0, 128, (2, 16)), jnp.int32)
        out = jax.jit(fn)(params, ids)
        assert out.shape == (2, 2)

"""Dense sequence_* ops (LoD family on padded batches + lengths).

Oracle style: hand-computed ragged examples transcribing the reference
docstring cases (fluid/layers/sequence_lod.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.framework.errors import InvalidArgumentError


def _batch():
    """Rows: [1,3], [2,4,6], [5] (padded to T=3) — the reference
    sequence_pool docstring example reshaped dense."""
    x = np.array([[[1.0], [3.0], [0.0]],
                  [[2.0], [4.0], [6.0]],
                  [[5.0], [0.0], [0.0]]], np.float32)
    lengths = np.array([2, 3, 1])
    return jnp.asarray(x), jnp.asarray(lengths)


class TestSequencePool:
    @pytest.mark.parametrize("ptype,want", [
        ("sum", [4.0, 12.0, 5.0]),
        ("average", [2.0, 4.0, 5.0]),
        ("sqrt", [4.0 / np.sqrt(2), 12.0 / np.sqrt(3), 5.0]),
        ("max", [3.0, 6.0, 5.0]),
        ("first", [1.0, 2.0, 5.0]),
        ("last", [3.0, 6.0, 5.0]),
    ])
    def test_pool_types(self, ptype, want):
        x, lengths = _batch()
        out = F.sequence_pool(x, ptype, lengths=lengths)
        np.testing.assert_allclose(np.asarray(out)[:, 0], want, atol=1e-6)

    def test_empty_sequence_pad_value(self):
        x = jnp.zeros((2, 3, 1), jnp.float32)
        out = F.sequence_pool(x, "max", pad_value=-7.0,
                              lengths=jnp.asarray([0, 2]))
        assert float(out[0, 0]) == -7.0

    def test_first_last_step_aliases(self):
        x, lengths = _batch()
        np.testing.assert_allclose(
            np.asarray(F.sequence_first_step(x, lengths))[:, 0],
            [1.0, 2.0, 5.0])
        np.testing.assert_allclose(
            np.asarray(F.sequence_last_step(x, lengths))[:, 0],
            [3.0, 6.0, 5.0])

    def test_bad_pool_type(self):
        x, lengths = _batch()
        with pytest.raises(InvalidArgumentError):
            F.sequence_pool(x, "median", lengths=lengths)


class TestSequenceSoftmaxReverse:
    def test_softmax_masks_padding(self):
        x, lengths = _batch()
        out = np.asarray(F.sequence_softmax(x[..., 0], lengths=lengths))
        np.testing.assert_allclose(out.sum(1), 1.0, atol=1e-6)
        assert out[0, 2] == 0.0 and out[2, 1] == 0.0

    def test_reverse_valid_prefix_only(self):
        x, lengths = _batch()
        out = np.asarray(F.sequence_reverse(x, lengths=lengths))[..., 0]
        np.testing.assert_allclose(out[0], [3.0, 1.0, 0.0])
        np.testing.assert_allclose(out[1], [6.0, 4.0, 2.0])
        np.testing.assert_allclose(out[2], [5.0, 0.0, 0.0])

    def test_reverse_no_lengths_flips(self):
        x = jnp.asarray(np.arange(6).reshape(1, 6), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(F.sequence_reverse(x)), [[5, 4, 3, 2, 1, 0]])


class TestSequenceEnumerate:
    def test_reference_docstring_case(self):
        """x rows [1,2,3], [4,5]; win 2 → windows with pad 0 at the row
        ends (sequence_lod.py:1246)."""
        x = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int64)
        out = np.asarray(F.sequence_enumerate(x, 2,
                                              lengths=jnp.asarray([3, 2])))
        np.testing.assert_array_equal(
            out[0], [[1, 2], [2, 3], [3, 0]])
        np.testing.assert_array_equal(
            out[1], [[4, 5], [5, 0], [0, 0]])


class TestSequencePadUnpadConcat:
    def test_pad_extends_and_trims(self):
        x, lengths = _batch()
        padded, lens = F.sequence_pad(x, -1.0, maxlen=5, lengths=lengths)
        assert padded.shape == (3, 5, 1)
        assert float(padded[0, 2, 0]) == -1.0
        np.testing.assert_array_equal(np.asarray(lens), [2, 3, 1])
        trimmed, lens2 = F.sequence_pad(x, 0.0, maxlen=2, lengths=lengths)
        assert trimmed.shape == (3, 2, 1)
        np.testing.assert_array_equal(np.asarray(lens2), [2, 2, 1])

    def test_unpad_zeroes_padding(self):
        x = jnp.ones((2, 3), jnp.float32)
        out = F.sequence_unpad(x, jnp.asarray([1, 3]))
        np.testing.assert_allclose(np.asarray(out),
                                   [[1, 0, 0], [1, 1, 1]])

    def test_concat_compacts_ragged_rows(self):
        a = jnp.asarray([[[1.0], [2.0]], [[7.0], [0.0]]])
        b = jnp.asarray([[[3.0]], [[8.0]]])
        out = F.sequence_concat(
            [a, b], lengths=[jnp.asarray([2, 1]), jnp.asarray([1, 1])])
        np.testing.assert_allclose(np.asarray(out)[0, :, 0], [1, 2, 3])
        np.testing.assert_allclose(np.asarray(out)[1, :2, 0], [7, 8])

    def test_concat_dense_fastpath(self):
        a = jnp.ones((2, 2, 1))
        b = jnp.zeros((2, 1, 1))
        out = F.sequence_concat([a, b])
        assert out.shape == (2, 3, 1)


class TestSequenceExpand:
    def test_expand_as(self):
        x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        y = jnp.zeros((2, 3, 5))
        out = F.sequence_expand_as(x, y)
        assert out.shape == (2, 3, 2)
        np.testing.assert_allclose(np.asarray(out)[1, 2], [3.0, 4.0])

    def test_expand_eager(self):
        x = jnp.asarray([[1.0], [2.0]])
        out = F.sequence_expand(x, jnp.asarray([2, 3]))
        assert out.shape == (2, 3, 1)


class TestSliceScatterReshape:
    def test_slice_per_row_offsets(self):
        x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 4, 3))
        out = np.asarray(F.sequence_slice(x, [1, 0], 2))
        np.testing.assert_allclose(out[0], np.asarray(x)[0, 1:3])
        np.testing.assert_allclose(out[1], np.asarray(x)[1, 0:2])
        with pytest.raises(InvalidArgumentError):
            F.sequence_slice(x, [0, 0], jnp.asarray([1, 2]))
        # window past the row end must error (reference contract)
        with pytest.raises(InvalidArgumentError):
            F.sequence_slice(x, [3, 0], 2)

    def test_reshape_rejects_row_data_loss(self):
        x = jnp.zeros((1, 4, 3))
        with pytest.raises(InvalidArgumentError):
            F.sequence_reshape(x, 6, lengths=[3])  # 9 elems % 6 != 0

    def test_scatter_adds_and_masks(self):
        base = jnp.ones((2, 4), jnp.float32)
        out = np.asarray(F.sequence_scatter(
            base, [[0, 2], [1, 3]], 2 * jnp.ones((2, 2)), lengths=[2, 1]))
        np.testing.assert_allclose(out[0], [3, 1, 3, 1])
        np.testing.assert_allclose(out[1], [1, 3, 1, 1])  # 2nd update dropped

    def test_reshape_rescales_lengths(self):
        x = jnp.zeros((2, 4, 3))
        out, lens = F.sequence_reshape(x, 6, lengths=[4, 2])
        assert out.shape == (2, 2, 6)
        np.testing.assert_array_equal(np.asarray(lens), [2, 1])
        with pytest.raises(InvalidArgumentError):
            F.sequence_reshape(jnp.zeros((1, 3, 3)), 7)


class TestJitability:
    def test_pool_softmax_reverse_jit(self):
        x, lengths = _batch()

        @jax.jit
        def f(x, lengths):
            a = F.sequence_pool(x, "max", lengths=lengths)
            b = F.sequence_softmax(x[..., 0], lengths=lengths)
            c = F.sequence_reverse(x, lengths=lengths)
            return a, b, c

        a, b, c = f(x, lengths)
        assert np.isfinite(np.asarray(a)).all()
        assert np.isfinite(np.asarray(b)).all()

    def test_grad_through_pool(self):
        x, lengths = _batch()
        g = jax.grad(lambda t: jnp.sum(
            F.sequence_pool(t, "average", lengths=lengths)))(x)
        gn = np.asarray(g)[..., 0]
        assert gn[0, 2] == 0.0, "padding must get zero grad"
        np.testing.assert_allclose(gn[0, 0], 0.5, atol=1e-6)

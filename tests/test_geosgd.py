"""Geo-SGD (distributed/fleet/geosgd.py).

Parity model: the reference's Geo-SGD strategy
(transpiler/geo_sgd_transpiler.py:1, communicator.h:413 GeoCommunicator):
k local steps per replica, then parameter-DELTA push/merge — replicas
keep their drift (no reset-to-average), the server copy accumulates the
mean drift.  First-window equivalence with LocalSGD is exact and is the
cross-check the implementation is built around.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.mesh import build_mesh, set_mesh
from paddle_tpu.framework.errors import UnimplementedError


@pytest.fixture(autouse=True)
def reset_mesh():
    set_mesh(build_mesh())
    yield
    set_mesh(build_mesh())
    fleet._initialized = False
    fleet._strategy = None


def _make_model(strategy_kw, seed=0, lr=0.1):
    fleet._initialized = False
    strategy = fleet.DistributedStrategy(**strategy_kw)
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 1))
    opt = fleet.distributed_optimizer(popt.SGD(learning_rate=lr))
    model = paddle.Model(net, inputs=["x"], labels=["y"])
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    return model


def _batches(n, seed=3):
    rng = np.random.RandomState(seed)
    return [(rng.randn(16, 6).astype(np.float32),
             rng.randn(16, 1).astype(np.float32)) for _ in range(n)]


class TestGeoSgd:
    def test_pure_async_still_raises_with_migration_paths(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(a_sync=True)
        fleet.init(is_collective=True, strategy=strategy)
        with pytest.raises(UnimplementedError) as ei:
            fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        msg = str(ei.value)
        assert "Geo-SGD" in msg and "localsgd" in msg \
            and "HostEmbeddingTable" in msg

    def test_first_window_matches_localsgd(self):
        """From a common start, geo's global after the FIRST sync equals
        LocalSGD's average (snapshot == global ⇒ global + mean(local −
        snapshot) = mean(local)); both run identical per-replica steps."""
        k = 3
        batches = _batches(k)
        geo = _make_model({"a_sync": True, "a_sync_configs": {"k_steps": k}})
        lsgd = _make_model({"localsgd": True,
                            "localsgd_configs": {"k_steps": k,
                                                 "begin_step": 1}})
        from paddle_tpu.distributed.fleet.geosgd import GeoSgdPlan
        from paddle_tpu.distributed.fleet.localsgd import LocalSGDPlan

        assert isinstance(geo._plan, GeoSgdPlan)
        assert isinstance(lsgd._plan, LocalSGDPlan)
        assert not isinstance(lsgd._plan, GeoSgdPlan)

        for x, y in batches:
            lg, _ = geo.train_batch([x], [y])
            ll, _ = lsgd.train_batch([x], [y])
            np.testing.assert_allclose(lg, ll, rtol=1e-6)
        pg, _ = geo._pull_state()
        pl, _ = lsgd._pull_state()
        for name in pg:
            np.testing.assert_allclose(np.asarray(pg[name]),
                                       np.asarray(pl[name]),
                                       rtol=1e-6, atol=1e-7,
                                       err_msg=name)

    def test_replicas_keep_drift_after_sync(self):
        """The geo property: after a sync, per-replica locals are NOT equal
        to the global (LocalSGD resets them; geo only merges the drift)."""
        k = 2
        geo = _make_model({"a_sync": True, "a_sync_configs": {"k_steps": k}})
        for x, y in _batches(k):
            geo.train_batch([x], [y])
        local = geo._opt_state["local"]["params"]
        g, _ = geo._pull_state()
        name = next(iter(g))
        stacked = np.asarray(local[name])  # [ndp, ...]
        assert stacked.shape[0] >= 2
        # replica 0 differs from replica 1 (each saw a different shard)
        assert not np.allclose(stacked[0], stacked[1]), \
            "replicas collapsed — geo must not reset locals"
        # and neither equals the global
        assert not np.allclose(stacked[0], np.asarray(g[name]))
        # snapshot tracks the post-merge locals
        snap = np.asarray(geo._opt_state["local"]["snapshot"][name])
        np.testing.assert_allclose(snap, stacked, rtol=1e-6)

    def test_trains_to_low_loss(self):
        geo = _make_model({"a_sync": True,
                           "a_sync_configs": {"k_steps": 4}}, lr=0.05)
        rng = np.random.RandomState(0)
        X = rng.randn(32, 6).astype(np.float32)
        W = rng.randn(6, 1).astype(np.float32)
        Y = X @ W
        losses = [float(geo.train_batch([X], [Y])[0]) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])

    def test_buffers_averaged_and_reseeded_on_sync(self):
        """BN running stats have no delta semantics: at a sync the locals
        must be replaced by the cross-replica average (the LocalSGD rule),
        or per-replica stats drift forever."""
        k = 2
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            a_sync=True, a_sync_configs={"k_steps": k})
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(6, 8), nn.BatchNorm1D(8),
                            nn.Linear(8, 1))
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.05))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        model.prepare(optimizer=opt, loss=nn.MSELoss())
        for x, y in _batches(k):
            model.train_batch([x], [y])
        local_b = model._opt_state["local"]["buffers"]
        _, g_bufs = model._pull_state()
        name = next(n for n in g_bufs if "mean" in n or "variance" in n)
        stacked = np.asarray(local_b[name])
        for r in range(stacked.shape[0]):
            np.testing.assert_allclose(stacked[r], np.asarray(g_bufs[name]),
                                       rtol=1e-6,
                                       err_msg=f"replica {r} not re-seeded")

    def test_hybrid_mesh_error_names_geo(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            a_sync=True, a_sync_configs={"k_steps": 2},
            tensor_parallel=True,
            tensor_parallel_configs={"tensor_parallel_degree": 2})
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = nn.Linear(4, 1)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(Exception, match="Geo-SGD"):
            model.prepare(optimizer=opt, loss=nn.MSELoss())

    def test_exclusive_with_localsgd(self):
        fleet._initialized = False
        strategy = fleet.DistributedStrategy(
            a_sync=True, a_sync_configs={"k_steps": 2}, localsgd=True)
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        net = nn.Linear(4, 1)
        opt = fleet.distributed_optimizer(popt.SGD(learning_rate=0.1))
        model = paddle.Model(net, inputs=["x"], labels=["y"])
        with pytest.raises(Exception, match="exclusive"):
            model.prepare(optimizer=opt, loss=nn.MSELoss())

    def test_no_param_collective_between_syncs(self):
        """Between pushes the compiled local step carries only the loss
        pmean — no parameter collective; the sync step carries the delta
        pmeans.  The communication saving is structural, not simulated."""
        k = 4
        geo = _make_model({"a_sync": True, "a_sync_configs": {"k_steps": k}})
        x, y = _batches(1)[0]
        geo.train_batch([x], [y])  # t=1: local step → compiles (False, 2)

        params, buffers = geo._pull_state()
        key = jax.random.PRNGKey(0)
        lr = jnp.asarray(0.1, jnp.float32)

        def count_collectives(sync):
            fn = geo._train_step.make(sync, 2)
            jaxpr = jax.make_jaxpr(fn)(
                params, geo._opt_state, buffers, key, lr,
                jnp.asarray(x), jnp.asarray(y))
            n = 0

            def walk(jx):
                nonlocal n
                for eqn in jx.eqns:
                    if "psum" in eqn.primitive.name:
                        n += 1
                    for sub in eqn.params.values():
                        if hasattr(sub, "eqns"):
                            walk(sub)
                        elif hasattr(sub, "jaxpr"):
                            walk(sub.jaxpr)

            walk(jaxpr.jaxpr)
            return n

        local_n = count_collectives(False)
        sync_n = count_collectives(True)
        assert local_n == 1, f"local step has {local_n} collectives (loss only expected)"
        assert sync_n > local_n

"""paddle.fluid 1.x compatibility namespace (layers wrappers, dygraph
classes, optimizer spellings, metrics accumulators)."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import fluid
from paddle_tpu.framework.errors import UnimplementedError


class TestLayersWrappers:
    def test_reduce_family(self):
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(fluid.layers.reduce_sum(x, dim=1)), [3.0, 12.0])
        out = fluid.layers.reduce_mean(x, dim=0, keep_dim=True)
        assert out.shape == (1, 3)
        assert float(fluid.layers.reduce_max(x)) == 5.0

    def test_elementwise_axis_broadcast(self):
        """1.x axis semantics: y aligns to x starting at `axis`."""
        x = np.ones((2, 3, 4), np.float32)
        y = np.arange(3, dtype=np.float32)
        out = np.asarray(fluid.layers.elementwise_add(x, y, axis=1))
        np.testing.assert_allclose(out[0, :, 0], [1.0, 2.0, 3.0])
        out = fluid.layers.elementwise_mul(x, y, axis=1, act="relu")
        assert out.shape == (2, 3, 4)

    def test_matmul_and_mul(self):
        a = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        b = np.random.RandomState(1).randn(3, 5).astype(np.float32)
        out = np.asarray(fluid.layers.matmul(a, b, transpose_x=True,
                                             alpha=2.0))
        np.testing.assert_allclose(out, 2.0 * a.T @ b, atol=1e-5)
        c = np.random.RandomState(2).randn(2, 3, 4).astype(np.float32)
        d = np.random.RandomState(3).randn(12, 5).astype(np.float32)
        out = np.asarray(fluid.layers.mul(c, d, x_num_col_dims=1))
        np.testing.assert_allclose(out, c.reshape(2, 12) @ d, atol=1e-4)

    def test_misc_wrappers(self):
        np.testing.assert_allclose(
            np.asarray(fluid.layers.fill_constant([2, 2], "float32", 3.0)),
            np.full((2, 2), 3.0))
        one = fluid.layers.one_hot(np.array([[1], [0]], np.int64), 3)
        np.testing.assert_allclose(np.asarray(one),
                                   [[0, 1, 0], [1, 0, 0]], atol=1e-6)
        out = fluid.layers.scale(np.ones(2, np.float32), scale=3.0,
                                 bias=1.0, bias_after_scale=False)
        np.testing.assert_allclose(np.asarray(out), [6.0, 6.0])
        sm = fluid.layers.softmax(np.zeros((2, 4), np.float32))
        np.testing.assert_allclose(np.asarray(sm), 0.25, atol=1e-6)
        r = fluid.layers.range(0, 6, 2, "int32")
        np.testing.assert_array_equal(np.asarray(r), [0, 2, 4])
        assert not bool(fluid.layers.has_nan(np.zeros(2)))

    def test_smooth_l1_matches_rowsum(self):
        x = np.array([[0.0, 2.0]], np.float32)
        y = np.array([[0.5, 0.0]], np.float32)
        out = np.asarray(fluid.layers.smooth_l1(x, y))
        want = 0.5 * 0.5 ** 2 + (2.0 - 0.5)
        np.testing.assert_allclose(out, [[want]], atol=1e-6)

    def test_sigmoid_ce_ignore_index(self):
        x = np.zeros((1, 3), np.float32)
        lab = np.array([[1, 0, -100]], np.float32)
        out = np.asarray(fluid.layers.sigmoid_cross_entropy_with_logits(
            x, lab, ignore_index=-100))
        assert out[0, 2] == 0.0 and out[0, 0] > 0

    def test_ctc_greedy_decoder(self):
        # argmax path: [1,1,blank,2,2,blank] → merged [1,2]
        T, C = 6, 4
        probs = np.full((1, T, C), -5.0, np.float32)
        path = [1, 1, 3, 2, 2, 3]  # blank=3
        for t, c in enumerate(path):
            probs[0, t, c] = 5.0
        out, lens = fluid.layers.ctc_greedy_decoder(probs, blank=3)
        assert int(lens[0, 0]) == 2
        np.testing.assert_array_equal(np.asarray(out)[0, :2], [1, 2])

    def test_edit_distance(self):
        a = np.array([[1, 2, 3, 0]], np.int64)
        b = np.array([[1, 3, 3, 0]], np.int64)
        d, n = fluid.layers.edit_distance(a, b, normalized=False,
                                          input_length=[3],
                                          label_length=[3])
        assert float(np.asarray(d)[0, 0]) == 1.0
        assert int(np.asarray(n)[0]) == 1

    def test_static_only_shims_raise_with_hint(self):
        # fc is REAL in graph mode now (static/builders.py); outside a
        # program it raises pointing at both routes
        with pytest.raises(Exception) as ei:
            fluid.layers.fc(None, size=10)
        assert "paddle.nn.Linear" in str(ei.value)
        with pytest.raises(UnimplementedError):
            fluid.layers.lod_reset(None, None)
        with pytest.raises(AttributeError):
            fluid.layers.not_a_real_op

    def test_sequence_pool_dense(self):
        """sequence_* upgraded from shims to dense implementations —
        1.x positional args still bind correctly (is_test 3rd)."""
        x = np.array([[[1.0], [3.0]], [[2.0], [0.0]]], np.float32)
        out = fluid.layers.sequence_pool(x, "sum", False,
                                         lengths=np.array([2, 1]))
        np.testing.assert_allclose(np.asarray(out)[:, 0], [4.0, 2.0])

    def test_detection_reexports(self):
        assert fluid.layers.iou_similarity is not None
        assert callable(fluid.layers.multiclass_nms)


class TestDygraph1x:
    def test_linear_act(self):
        paddle.seed(0)
        with fluid.dygraph.guard():
            lin = fluid.dygraph.Linear(4, 3, act="relu")
            out = lin(jnp.asarray(np.random.RandomState(0).randn(2, 4),
                                  jnp.float32))
            assert out.shape == (2, 3)
            assert (np.asarray(out) >= 0).all()

    def test_conv_bn_pipeline(self):
        paddle.seed(1)
        conv = fluid.dygraph.Conv2D(3, 8, 3, padding=1, act="relu")
        bn = fluid.dygraph.BatchNorm(8)
        x = jnp.asarray(np.random.RandomState(1).randn(2, 3, 8, 8),
                        jnp.float32)
        out = bn(conv(x))
        assert out.shape == (2, 8, 8, 8)

    def test_embedding_1x_size(self):
        paddle.seed(2)
        emb = fluid.dygraph.Embedding(size=[10, 4])
        out = emb(jnp.asarray([[1, 2]], jnp.int64))
        assert out.shape == (1, 2, 4)
        with pytest.raises(UnimplementedError):
            fluid.dygraph.Embedding(size=[10, 4], is_distributed=True)

    def test_prelu_modes(self):
        paddle.seed(3)
        x = jnp.asarray([[-1.0, 2.0]], jnp.float32)
        out = fluid.dygraph.PRelu("all")(x)
        np.testing.assert_allclose(np.asarray(out), [[-0.25, 2.0]],
                                   atol=1e-6)
        p = fluid.dygraph.PRelu("channel", channel=4)
        assert p.weight.shape == (4,)

    def test_gru_unit_step(self):
        paddle.seed(4)
        H = 5
        cell = fluid.dygraph.GRUUnit(3 * H)
        x = jnp.asarray(np.random.RandomState(2).randn(2, 3 * H), jnp.float32)
        h = jnp.zeros((2, H), jnp.float32)
        new_h, rhp, gate = cell(x, h)
        assert new_h.shape == (2, H)
        assert gate.shape == (2, 3 * H)
        assert np.isfinite(np.asarray(new_h)).all()

    def test_nce_loss(self):
        paddle.seed(5)
        nce = fluid.dygraph.NCE(num_total_classes=20, dim=6,
                                num_neg_samples=4)
        x = jnp.asarray(np.random.RandomState(3).randn(3, 6), jnp.float32)
        lab = jnp.asarray([[1], [2], [3]], jnp.int64)
        loss = nce(x, lab)
        assert loss.shape == (3, 1)
        assert (np.asarray(loss) > 0).all()

    def test_save_dygraph_classifies_opt_state(self, tmp_path):
        import os
        from paddle_tpu import nn

        paddle.seed(20)
        net = nn.Linear(2, 1)
        opt = fluid.optimizer.AdamOptimizer(
            0.001, parameter_list=net.parameters())
        opt.step({n: jnp.ones_like(v) for n, v in
                  net.param_pytree(trainable_only=True).items()})
        prefix = str(tmp_path / "adam")
        fluid.dygraph.save_dygraph(opt.state_dict(), prefix)
        assert os.path.exists(prefix + ".pdopt"), \
            "optimizer state must go to .pdopt, not .pdparams"

    def test_save_load_dygraph(self, tmp_path):
        paddle.seed(6)
        lin = fluid.dygraph.Linear(3, 2)
        prefix = str(tmp_path / "ckpt")
        fluid.dygraph.save_dygraph(lin.state_dict(), prefix)
        params, opt = fluid.dygraph.load_dygraph(prefix)
        assert opt is None
        lin2 = fluid.dygraph.Linear(3, 2)
        lin2.set_state_dict(params)
        x = jnp.ones((1, 3), jnp.float32)
        np.testing.assert_allclose(np.asarray(lin(x)), np.asarray(lin2(x)),
                                   atol=1e-6)


class TestFluidOptimizer:
    def test_1x_spellings_construct_and_step(self):
        paddle.seed(7)
        from paddle_tpu import nn

        net = nn.Linear(4, 1)
        opt = fluid.optimizer.SGDOptimizer(
            learning_rate=0.1, parameter_list=net.parameters())
        before = np.asarray(net.weight.value).copy()
        grads = {n: jnp.ones_like(v)
                 for n, v in net.param_pytree(trainable_only=True).items()}
        opt.step(grads)
        after = np.asarray(net.weight.value)
        np.testing.assert_allclose(after, before - 0.1, atol=1e-6)

    def test_momentum_positional(self):
        from paddle_tpu import nn

        net = nn.Linear(2, 1)
        opt = fluid.optimizer.MomentumOptimizer(0.1, 0.9,
                                                parameter_list=net.parameters())
        assert opt._momentum == 0.9

    def test_two_layers_no_name_collision(self):
        """Two root-level Linears stamp the same dotted names; the
        optimizer must still update all four parameters."""
        from paddle_tpu import nn

        paddle.seed(21)
        l1, l2 = nn.Linear(3, 3), nn.Linear(3, 3)
        opt = fluid.optimizer.SGDOptimizer(
            0.5, parameter_list=l1.parameters() + l2.parameters())
        before = [np.asarray(p.value).copy()
                  for p in l1.parameters() + l2.parameters()]
        opt.step([jnp.ones_like(p.value)
                  for p in l1.parameters() + l2.parameters()])
        after = [np.asarray(p.value)
                 for p in l1.parameters() + l2.parameters()]
        for b, a in zip(before, after):
            np.testing.assert_allclose(a, b - 0.5, atol=1e-6)

    def test_program_rewriters_raise(self):
        for name in ["PipelineOptimizer", "RecomputeOptimizer",
                     "GradientMergeOptimizer", "DGCMomentumOptimizer"]:
            with pytest.raises(UnimplementedError):
                getattr(fluid.optimizer, name)(None)


class TestFtrl:
    def _oracle(self, w, g, sq, lin, lr, l1, l2):
        """ftrl_op.h:74-100 with lr_power=-0.5."""
        new_sq = sq + g * g
        lin = lin + g - (np.sqrt(new_sq) - np.sqrt(sq)) / lr * w
        x = np.sign(lin) * l1 - lin
        y = np.sqrt(new_sq) / lr + 2 * l2
        w = np.where(np.abs(lin) > l1, x / y, 0.0)
        return w, new_sq, lin

    def test_matches_kernel_oracle(self):
        from paddle_tpu import optimizer as popt
        from paddle_tpu import nn

        paddle.seed(8)
        net = nn.Linear(3, 1, bias_attr=False)
        opt = popt.Ftrl(learning_rate=0.1, l1=0.01, l2=0.1,
                        parameters=net.parameters())
        rng = np.random.RandomState(4)
        w = np.asarray(net.weight.value).astype(np.float64)
        sq = np.zeros_like(w)
        lin = np.zeros_like(w)
        for i in range(3):
            g = rng.randn(*w.shape).astype(np.float32)
            opt.step({"weight": jnp.asarray(g)})
            w, sq, lin = self._oracle(w, g.astype(np.float64), sq, lin,
                                      0.1, 0.01, 0.1)
        np.testing.assert_allclose(np.asarray(net.weight.value), w,
                                   atol=1e-5)

    def test_trains(self):
        from paddle_tpu import optimizer as popt
        from paddle_tpu import nn
        import jax

        paddle.seed(9)
        net = nn.Linear(4, 1)
        opt = popt.Ftrl(learning_rate=0.5, parameters=net.parameters())
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(64, 4), jnp.float32)
        true_w = jnp.asarray(rng.randn(4, 1), jnp.float32)
        y = x @ true_w

        from paddle_tpu.nn import functional_call

        def loss_fn(p):
            return jnp.mean((functional_call(net, p, x) - y) ** 2)

        first = None
        for _ in range(30):
            p = net.param_pytree(trainable_only=True)
            val, g = jax.value_and_grad(loss_fn)(p)
            first = first if first is not None else float(val)
            opt.step(g)
        assert float(val) < first * 0.5, (first, float(val))


class TestFluidMetrics:
    def test_accuracy_weighted_mean(self):
        m = fluid.metrics.Accuracy()
        m.update(0.8, 10)
        m.update(0.6, 30)
        np.testing.assert_allclose(m.eval(), (8 + 18) / 40)
        with pytest.raises(Exception):
            fluid.metrics.Accuracy().eval()

    def test_chunk_evaluator_roundtrip(self):
        from paddle_tpu import metric as M

        label = [[2, 3, 6, 6, 0, 1, 1, 1, 6, 4]]
        pred = [[2, 3, 6, 6, 0, 1, 6, 1, 6, 4]]
        _, _, _, ni, nl, nc = M.chunk_eval(pred, label, "IOB", 3)
        ev = fluid.metrics.ChunkEvaluator()
        ev.update(ni, nl, nc)
        p, r, f1 = ev.eval()
        np.testing.assert_allclose(p, 0.5)
        np.testing.assert_allclose(r, 2 / 3, rtol=1e-6)

    def test_edit_distance_metric(self):
        m = fluid.metrics.EditDistance()
        m.update([1.0, 0.0], 2)
        avg, err = m.eval()
        assert avg == 0.5 and err == 0.5

    def test_detection_map_perfect_and_miss(self):
        """One perfect detection + one total miss on two images →
        AP(class 1) = 1, AP(class 2) = 0 → mAP 0.5 (both versions)."""
        gt_boxes = [np.array([[0.1, 0.1, 0.5, 0.5]]),
                    np.array([[0.2, 0.2, 0.6, 0.6]])]
        gt_labels = [np.array([1]), np.array([2])]
        det = [np.array([[1, 0.9, 0.1, 0.1, 0.5, 0.5]]),   # exact hit
               np.array([[2, 0.8, 0.7, 0.7, 0.9, 0.9]])]   # no overlap
        for version in ("integral", "11point"):
            m = fluid.metrics.DetectionMAP(class_num=3, ap_version=version)
            m.update(det, gt_labels, gt_boxes)
            np.testing.assert_allclose(m.eval(), 0.5, atol=1e-6)

    def test_detection_map_duplicate_counts_once(self):
        """Two detections on one GT: the higher-scored is TP, the
        duplicate is FP (visited-GT rule, detection_map_op.h:406-412)."""
        gt_boxes = [np.array([[0.1, 0.1, 0.5, 0.5]])]
        gt_labels = [np.array([1])]
        det = [np.array([[1, 0.9, 0.1, 0.1, 0.5, 0.5],
                         [1, 0.7, 0.12, 0.1, 0.5, 0.5]])]
        m = fluid.metrics.DetectionMAP(class_num=2)
        m.update(det, gt_labels, gt_boxes)
        # precision at the TP point is 1.0, recall reaches 1.0 there
        np.testing.assert_allclose(m.eval(), 1.0, atol=1e-6)

    def test_detection_map_nms_padding_skipped(self):
        gt_boxes = [np.array([[0.0, 0.0, 0.5, 0.5]])]
        gt_labels = [np.array([1])]
        det = [np.array([[1, 0.9, 0.0, 0.0, 0.5, 0.5],
                         [-1, -1, -1, -1, -1, -1]])]  # multiclass_nms pad
        m = fluid.metrics.DetectionMAP(class_num=2)
        m.update(det, gt_labels, gt_boxes)
        np.testing.assert_allclose(m.eval(), 1.0, atol=1e-6)

    def test_detection_map_difficult_excluded(self):
        gt_boxes = [np.array([[0.1, 0.1, 0.5, 0.5],
                              [0.6, 0.6, 0.9, 0.9]])]
        gt_labels = [np.array([1, 1])]
        difficult = [np.array([0, 1])]
        det = [np.array([[1, 0.9, 0.1, 0.1, 0.5, 0.5]])]
        m = fluid.metrics.DetectionMAP(class_num=2,
                                       evaluate_difficult=False)
        m.update(det, gt_labels, gt_boxes, difficult=difficult)
        # difficult GT excluded from the positive count → full recall
        np.testing.assert_allclose(m.eval(), 1.0, atol=1e-6)

    def test_composite(self):
        c = fluid.metrics.CompositeMetric()
        c.add_metric(fluid.metrics.Precision())
        c.add_metric(fluid.metrics.Recall())
        c.update(np.array([1.0, 0.0, 1.0]), np.array([1, 0, 0]))
        p, r = c.eval()
        assert p == 0.5 and r == 1.0


class TestFluidRoot:
    def test_places_and_param_attr(self):
        fluid.CPUPlace()
        fluid.ParamAttr(name="w")
        assert fluid.in_dygraph_mode()

    def test_program_machinery_is_real_now(self):
        # the lazy-graph Program/Executor (static/graph.py) replaced the
        # round-3 shims
        exe = fluid.Executor(fluid.CPUPlace())
        assert exe is not None
        prog = fluid.default_main_program()
        assert isinstance(prog, fluid.Program)
        with pytest.raises(UnimplementedError):
            fluid.create_lod_tensor([[1]], [[1]])

    def test_initializer_and_clip_aliases(self):
        assert fluid.initializer.ConstantInitializer is \
            fluid.initializer.Constant
        x = fluid.initializer.Xavier(uniform=True)
        assert type(x).__name__ == "XavierUniform"
        m = fluid.initializer.MSRA()  # ref default: uniform=True (:639)
        assert type(m).__name__ == "KaimingUniform"
        assert type(fluid.initializer.MSRA(uniform=False)).__name__ == \
            "KaimingNormal"
        assert fluid.clip.GradientClipByNorm is fluid.clip.ClipGradByNorm
        with pytest.raises(UnimplementedError):
            fluid.clip.set_gradient_clip(None)

    def test_core_shim(self):
        assert isinstance(fluid.core.globals(), dict)
        with pytest.raises(UnimplementedError):
            fluid.core.ops.conv2d
        assert fluid.core.get_cuda_device_count() == 0

    def test_io_reader_decorators(self):
        r = fluid.io.buffered(lambda: iter([1, 2, 3]), 2)
        assert list(r()) == [1, 2, 3]
        # save_persistables is REAL since r5 (reference binary format) —
        # full round-trip coverage lives in tests/test_paddle_export.py


class TestLrDecayFunctions:
    """1.x fluid.layers lr decays return 2.0 schedulers with the exact
    1.x per-step formulas (ref: fluid/layers/learning_rate_scheduler.py)."""

    def _trace(self, sched, steps):
        vals = []
        for _ in range(steps):
            vals.append(float(sched()))
            sched.step()
        return np.asarray(vals)

    def test_exponential_decay(self):
        from paddle_tpu.fluid import layers as fl

        s = fl.exponential_decay(0.1, decay_steps=4, decay_rate=0.5)
        got = self._trace(s, 9)
        want = 0.1 * 0.5 ** (np.arange(9) / 4)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        s2 = fl.exponential_decay(0.1, 4, 0.5, staircase=True)
        got2 = self._trace(s2, 9)
        want2 = 0.1 * 0.5 ** np.floor(np.arange(9) / 4)
        np.testing.assert_allclose(got2, want2, rtol=1e-6)

    def test_natural_exp_and_inverse_time(self):
        from paddle_tpu.fluid import layers as fl

        g1 = self._trace(fl.natural_exp_decay(1.0, 2, 0.5), 5)
        np.testing.assert_allclose(g1, np.exp(-0.5 * np.arange(5) / 2),
                                   rtol=1e-6)
        g2 = self._trace(fl.inverse_time_decay(1.0, 2, 0.5), 5)
        np.testing.assert_allclose(g2, 1 / (1 + 0.5 * np.arange(5) / 2),
                                   rtol=1e-6)

    def test_cosine_decay(self):
        from paddle_tpu.fluid import layers as fl

        got = self._trace(fl.cosine_decay(2.0, step_each_epoch=3, epochs=4),
                          12)
        want = 2.0 * 0.5 * (np.cos(np.floor(np.arange(12) / 3)
                                   * np.pi / 4) + 1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_piecewise_noam_warmup_poly_resolve(self):
        from paddle_tpu.fluid import layers as fl
        from paddle_tpu.optimizer import lr as plr

        assert isinstance(fl.piecewise_decay([2, 4], [1.0, 0.5, 0.1]),
                          plr.PiecewiseDecay)
        assert isinstance(fl.noam_decay(512, 4000), plr.NoamDecay)
        assert isinstance(fl.linear_lr_warmup(0.1, 10, 0.0, 0.1),
                          plr.LinearWarmup)
        assert isinstance(fl.polynomial_decay(0.1, 100), plr.PolynomialDecay)

    def test_value_at_functional_mode(self):
        # continuous decays map to closed-form schedulers with value_at
        import jax.numpy as jnp

        from paddle_tpu.fluid import layers as fl

        for sched, formula in [
            (fl.exponential_decay(0.1, 4, 0.5),
             lambda t: 0.1 * 0.5 ** (t / 4)),
            (fl.natural_exp_decay(1.0, 2, 0.5),
             lambda t: np.exp(-0.5 * t / 2)),
            (fl.inverse_time_decay(1.0, 2, 0.5),
             lambda t: 1 / (1 + 0.5 * t / 2)),
        ]:
            v = float(sched.value_at(jnp.asarray(6)))
            np.testing.assert_allclose(v, formula(6.0), rtol=1e-6)

    def test_warmup_inner_scheduler_on_global_step(self):
        # 1.x semantics: LINEAR ramp start->end during warmup, then the
        # inner decay evaluated at the GLOBAL step
        import jax.numpy as jnp

        from paddle_tpu.fluid import layers as fl

        inner = fl.exponential_decay(0.1, decay_steps=2, decay_rate=0.5)
        s = fl.linear_lr_warmup(inner, warmup_steps=4, start_lr=0.0,
                                end_lr=0.1)
        vals = self._trace(s, 7)
        np.testing.assert_allclose(vals[0], 0.0, atol=1e-9)
        # mid-warmup: linear, NOT decay-modulated (1.x linear_step)
        np.testing.assert_allclose(vals[2], 0.05, rtol=1e-6)
        # step 4 (first post-warmup): 0.1 * 0.5^(4/2) = 0.025, NOT 0.1
        np.testing.assert_allclose(vals[4], 0.1 * 0.5 ** 2, rtol=1e-6)
        # the caller-held inner scheduler is not corrupted by reads
        assert inner.last_epoch == 0
        # functional mode works through the warmup wrapper
        np.testing.assert_allclose(float(s.value_at(jnp.asarray(2))), 0.05,
                                   rtol=1e-6)
        np.testing.assert_allclose(float(s.value_at(jnp.asarray(6))),
                                   0.1 * 0.5 ** 3, rtol=1e-6)

    def test_warmup_lambda_inner_value_at_error_names_wrapper(self):
        import jax.numpy as jnp
        import pytest as _pytest

        from paddle_tpu.fluid import layers as fl

        s = fl.linear_lr_warmup(fl.cosine_decay(0.1, 10, 10), 4, 0.0, 0.1)
        with _pytest.raises(NotImplementedError, match="linear_lr_warmup"):
            s.value_at(jnp.asarray(2))

    def test_usable_as_optimizer_lr(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer as popt
        from paddle_tpu.fluid import layers as fl

        paddle.seed(0)
        net = nn.Linear(4, 1)
        sched = fl.exponential_decay(0.1, 2, 0.5)
        m = paddle.Model(net, inputs=["x"], labels=["y"])
        m.prepare(optimizer=popt.SGD(learning_rate=sched),
                  loss=nn.MSELoss())
        x = np.zeros((4, 4), np.float32)
        y = np.zeros((4, 1), np.float32)
        loss, _ = m.train_batch([x], [y])
        assert np.isfinite(loss)


class TestSimilarityFocus:
    def test_reference_docstring_example(self):
        x = np.array([[[[0.8, 0.1], [0.4, 0.5]],
                       [[0.9, 0.7], [0.9, 0.9]],
                       [[0.8, 0.9], [0.1, 0.2]]],
                      [[[0.2, 0.5], [0.3, 0.4]],
                       [[0.9, 0.7], [0.8, 0.4]],
                       [[0.0, 0.2], [0.4, 0.7]]]], np.float32)
        out = np.asarray(fluid.layers.similarity_focus(x, axis=1,
                                                       indexes=[0]))
        exp0 = np.array([[1, 0], [0, 1]], np.float32)
        exp1 = np.array([[0, 1], [1, 0]], np.float32)
        for c in range(3):  # broadcast along the channel axis
            np.testing.assert_array_equal(out[0, c], exp0)
            np.testing.assert_array_equal(out[1, c], exp1)

    def test_multi_index_or(self):
        rng = np.random.RandomState(0)
        x = rng.rand(1, 3, 4, 4).astype(np.float32)
        a = np.asarray(fluid.layers.similarity_focus(x, 1, [0]))
        b = np.asarray(fluid.layers.similarity_focus(x, 1, [2]))
        both = np.asarray(fluid.layers.similarity_focus(x, 1, [0, 2]))
        np.testing.assert_array_equal(both, np.maximum(a, b))

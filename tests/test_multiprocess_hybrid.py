"""Multi-process coverage beyond pure DP (VERDICT r4 missing #2).

Reference test strategy: python/paddle/fluid/tests/unittests/
test_dist_base.py:578-769 — localhost trainer subprocesses running REAL
hybrid strategies, compared loss-for-loss against the single-process run.
Here:

* dp×tp: 2 processes × 2 CPU devices each = one 4-device global mesh
  (dp=2 × model=2) training VocabParallelEmbedding + Column/RowParallel
  MLP — parity vs the SAME strategy in one 4-device process;
* sharded-checkpoint save in 2 processes → resume in 2 processes AND
  re-sharded into 1 process (orbax per-process shards);
* kill-one-process heartbeat drill: the watchdog names exactly the dead
  trainer while the survivor keeps beating;
* HostEmbeddingTable vocab_range sharding across 2 processes: each owns
  half the vocabulary, both see the full id batch, the assembled result
  equals one full-table process.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn(script, rank, nprocs, port, local_devices, extra_env, tmp_path):
    path = str(tmp_path / f"worker_{rank}.py")
    if not os.path.exists(path):
        with open(path, "w") as f:
            f.write(script)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel in workers
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={local_devices}",
        "PADDLE_TRAINER_ENDPOINTS": f"127.0.0.1:{port}",
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_TRAINER_ID": str(rank),
    })
    env.update(extra_env)
    return subprocess.Popen([sys.executable, path], env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)


def _join(procs, what, timeout=300):
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(
                timeout=max(1.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{what} hung")
        outs.append(stdout.decode())
        assert p.returncode == 0, f"{what} rank failed:\n" + outs[-1][-3000:]
    return outs


# ---------------------------------------------------------------------------
# (a) + (b): dp×tp hybrid training, checkpoint, resume
# ---------------------------------------------------------------------------
HYBRID_WORKER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer as popt
from paddle_tpu.distributed import env as penv
from paddle_tpu.distributed import fleet, meta_parallel as mp
from paddle_tpu.incubate.sharded_checkpoint import (restore_sharded,
                                                    save_sharded)

nprocs = int(os.environ["PADDLE_TRAINERS_NUM"])
rank = int(os.environ["PADDLE_TRAINER_ID"])
if nprocs > 1:
    penv.init_parallel_env()
assert jax.device_count() == 4, jax.device_count()

fleet._initialized = False
strategy = fleet.DistributedStrategy(
    dp_degree=2, tensor_parallel=True,
    tensor_parallel_configs={{"tensor_parallel_degree": 2}})
fleet.init(is_collective=True, strategy=strategy)

paddle.seed(0)


class TPNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = mp.VocabParallelEmbedding(64, 16)
        self.fc1 = mp.ColumnParallelLinear(16, 32, gather_output=False)
        self.act = nn.ReLU()
        self.fc2 = mp.RowParallelLinear(32, 1, input_is_parallel=True)

    def forward(self, ids):
        return self.fc2(self.act(self.fc1(self.emb(ids).mean(axis=1))))


net = TPNet()
opt = fleet.distributed_optimizer(popt.Adam(learning_rate=0.05))
model = paddle.Model(net, inputs=["ids"], labels=["y"])
model.prepare(optimizer=opt, loss=nn.MSELoss())

rng = np.random.RandomState(1)
ids = rng.randint(0, 64, (8, 4)).astype(np.int32)
y = rng.randn(8, 1).astype(np.float32)

ckpt = os.environ.get("PT_CKPT")
phase = os.environ["PT_PHASE"]

if phase == "resume":
    params, buffers = model._pull_state()
    model._ensure_opt_state(params, buffers)
    like = {{"params": params, "opt": model._opt_state}}
    st = restore_sharded(ckpt, like=like)
    model._push_state(st["params"], buffers)
    model._opt_state = st["opt"]

steps = int(os.environ.get("PT_STEPS", "3"))
losses = []
for _ in range(steps):
    loss, _ = model.train_batch([ids], [y])
    losses.append(float(np.asarray(loss)))

if phase == "train" and ckpt:
    params, buffers = model._pull_state()
    save_sharded(ckpt, {{"params": params, "opt": model._opt_state}},
                 step=steps)

if rank == 0:
    with open(os.environ["PT_OUT"], "w") as f:
        json.dump(losses, f)
print("worker", rank, "phase", phase, "done", losses)
"""


def _run_hybrid(tmp_path, tag, nprocs, phase, ckpt=None, steps=3):
    port = _free_port()
    out = str(tmp_path / f"losses_{tag}.json")
    sub = tmp_path / tag
    sub.mkdir(exist_ok=True)
    extra = {"PT_OUT": out, "PT_PHASE": phase, "PT_STEPS": str(steps)}
    if ckpt:
        extra["PT_CKPT"] = ckpt
    local_devices = 4 // nprocs
    procs = [_spawn(HYBRID_WORKER.format(repo=REPO), r, nprocs, port,
                    local_devices, extra, sub)
             for r in range(nprocs)]
    _join(procs, f"hybrid {tag}")
    with open(out) as f:
        return json.load(f)


class TestHybridDpTp:
    def test_two_process_dp_tp_matches_single_process(self, tmp_path):
        dist = _run_hybrid(tmp_path, "dist", nprocs=2, phase="train")
        single = _run_hybrid(tmp_path, "single", nprocs=1, phase="train")
        assert len(dist) == 3 and all(np.isfinite(dist))
        np.testing.assert_allclose(dist, single, rtol=1e-5, atol=1e-6)

    def test_sharded_checkpoint_resume_2proc_and_resharded_1proc(
            self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        _run_hybrid(tmp_path, "phase_a", nprocs=2, phase="train",
                    ckpt=ckpt, steps=3)
        # resume in TWO processes
        b2 = _run_hybrid(tmp_path, "phase_b2", nprocs=2, phase="resume",
                         ckpt=ckpt, steps=2)
        # resume RE-SHARDED into one process
        b1 = _run_hybrid(tmp_path, "phase_b1", nprocs=1, phase="resume",
                         ckpt=ckpt, steps=2)
        np.testing.assert_allclose(b2, b1, rtol=1e-5, atol=1e-6)
        # and resuming actually continued training (params moved): losses
        # differ from a fresh run's first steps
        fresh = _run_hybrid(tmp_path, "fresh", nprocs=1, phase="train",
                            steps=2)
        assert not np.allclose(b1, fresh, rtol=1e-4), (b1, fresh)


# ---------------------------------------------------------------------------
# (c) kill-one-process heartbeat drill
# ---------------------------------------------------------------------------
BEAT_WORKER = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.heartbeat import FileHeartbeat

hb = FileHeartbeat(os.environ["PT_HB"])
for _ in range(600):
    hb.beat()
    time.sleep(0.05)
"""


class TestKillDrill:
    def test_watchdog_names_the_dead_trainer(self, tmp_path):
        from paddle_tpu.distributed.heartbeat import (FileHeartbeat,
                                                      HeartBeatMonitor)

        script = BEAT_WORKER.format(repo=REPO)
        procs = []
        hb_paths = []
        for rank in range(2):
            path = str(tmp_path / f"beat{rank}")
            hb_paths.append(path)
            p = tmp_path / f"beater_{rank}.py"
            with open(p, "w") as f:
                f.write(script)
            env = dict(os.environ)
            env["PT_HB"] = path
            procs.append(subprocess.Popen([sys.executable, str(p)],
                                          env=env, cwd=REPO))
        try:
            deadline = time.time() + 60
            while not all(os.path.exists(h) for h in hb_paths):
                assert time.time() < deadline, "beaters never started"
                time.sleep(0.05)

            mon = HeartBeatMonitor(workers=2, timeout=1.0,
                                   interval=0.1).start()
            readers = [FileHeartbeat(h) for h in hb_paths]

            def bridge():
                for i, r in enumerate(readers):
                    if r.age() < 0.5:
                        mon.update(i)

            # both alive for a while
            for _ in range(20):
                bridge()
                time.sleep(0.05)
            assert mon.lost_workers() == []

            procs[1].send_signal(signal.SIGKILL)  # the drill
            procs[1].wait()
            deadline = time.time() + 20
            while mon.lost_workers() != [1]:
                assert time.time() < deadline, (
                    f"watchdog missed the kill: {mon.lost_workers()}")
                bridge()
                time.sleep(0.05)
            assert mon.lost_workers() == [1]  # survivor never flagged
            mon.stop()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()


# ---------------------------------------------------------------------------
# (d) HostEmbeddingTable vocab_range across 2 processes
# ---------------------------------------------------------------------------
SHARD_WORKER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
import numpy as np
from paddle_tpu.incubate import HostEmbeddingTable

rank = int(os.environ["PADDLE_TRAINER_ID"])
VOCAB, DIM = 128, 8
lo, hi = (0, 64) if rank == 0 else (64, 128)
t = HostEmbeddingTable(VOCAB, DIM, optimizer="sgd", learning_rate=1.0,
                       vocab_range=(lo, hi), seed=7)

rng = np.random.RandomState(3)
ids = rng.randint(0, VOCAB, (6, 4)).astype(np.int64)   # FULL id batch
grads = rng.randn(6, 4, DIM).astype(np.float32)

rows = t.pull(ids)           # out-of-window rows are zeros
t.push(ids, grads)           # out-of-window pushes are dropped
np.savez(os.environ["PT_OUT"], rows=rows,
         table=np.asarray(t.table), lo=lo, hi=hi)
print("shard worker", rank, "done")
"""


class TestVocabRangeTwoProcesses:
    def test_shards_assemble_to_full_table(self, tmp_path):
        script = SHARD_WORKER.format(repo=REPO)
        outs = [str(tmp_path / f"shard{r}.npz") for r in range(2)]
        procs = []
        for rank in range(2):
            p = tmp_path / f"shard_{rank}.py"
            with open(p, "w") as f:
                f.write(script)
            env = dict(os.environ)
            env.update({"PADDLE_TRAINER_ID": str(rank),
                        "PT_OUT": outs[rank]})
            procs.append(subprocess.Popen([sys.executable, str(p)],
                                          env=env, cwd=REPO,
                                          stdout=subprocess.PIPE,
                                          stderr=subprocess.STDOUT))
        _join(procs, "vocab_range shards", timeout=120)

        from paddle_tpu.incubate import HostEmbeddingTable

        VOCAB, DIM = 128, 8
        full = HostEmbeddingTable(VOCAB, DIM, optimizer="sgd",
                                  learning_rate=1.0, seed=7)
        rng = np.random.RandomState(3)
        ids = rng.randint(0, VOCAB, (6, 4)).astype(np.int64)
        grads = rng.randn(6, 4, DIM).astype(np.float32)
        want_rows = full.pull(ids)
        full.push(ids, grads)

        d0, d1 = np.load(outs[0]), np.load(outs[1])
        # each worker sees only its window; summed pulls = the full gather
        # (seed=7 gives every worker the SAME global init, sliced locally —
        # the multi-host bootstrap contract)
        np.testing.assert_allclose(d0["rows"] + d1["rows"], want_rows,
                                   atol=1e-6)
        assembled = np.concatenate([d0["table"], d1["table"]], axis=0)
        np.testing.assert_allclose(assembled, np.asarray(full.table),
                                   atol=1e-6)

"""Multi-tenant serving (serving/tenancy.py + the engine integration).

Covers the TenantScheduler contract: stride-order weighted fairness
(2:1 weights admit 2:1 under contention), budget throttling/deferral,
budget preemption with bit-identical regeneration through the paged
engine, mixed-adapter serving on a CLOSED compile set, analysis rule
S607 (in-budget starvation / dead adapters) fire + silent, and the
tenant-label cardinality cap (a tenant-id flood lands in the
``__overflow__`` metric child, never an unbounded label set).
"""
import time
import unittest

import numpy as np

import paddle_tpu as pt
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.serving import GenerationEngine, TenantScheduler, TenantSpec


class TestTenantScheduler(unittest.TestCase):
    def test_stride_order_is_weighted_and_deterministic(self):
        ten = TenantScheduler([TenantSpec("a", weight=2.0),
                               TenantSpec("b", weight=1.0)])
        items = [("a", i) for i in range(3)] + [("b", i) for i in range(3)]
        admissible, deferred = ten.schedule(
            list(items), tenant_of=lambda it: it[0])
        self.assertEqual(deferred, [])
        # stride simulation: both passes start at 0, ties break by name;
        # weight-2 "a" advances half as fast so it lands 2 admissions
        # for every 1 of "b", per-tenant FIFO preserved
        self.assertEqual([t for t, _ in admissible],
                         ["a", "b", "a", "a", "b", "b"])
        self.assertEqual([i for t, i in admissible if t == "a"], [0, 1, 2])
        self.assertEqual([i for t, i in admissible if t == "b"], [0, 1, 2])

    def test_untagged_items_go_first_fcfs(self):
        ten = TenantScheduler([TenantSpec("a")])
        admissible, deferred = ten.schedule(
            [("a", 0), (None, 0), ("ghost", 1)],
            tenant_of=lambda it: it[0])
        self.assertEqual(deferred, [])
        # untagged and unknown-tenant items bypass the stride pick
        self.assertEqual(admissible, [(None, 0), ("ghost", 1), ("a", 0)])

    def test_budget_throttles_and_refills(self):
        ten = TenantScheduler([TenantSpec("flood", token_budget=2),
                               TenantSpec("ok")])
        self.assertFalse(ten.is_throttled("flood"))
        ten.charge("flood", 2)
        self.assertTrue(ten.is_throttled("flood"))
        self.assertEqual(ten.over_budget(), ["flood"])
        admissible, deferred = ten.schedule(
            [("flood", 0), ("ok", 0), ("flood", 1)],
            tenant_of=lambda it: it[0])
        self.assertEqual(admissible, [("ok", 0)])
        self.assertEqual(deferred, [("flood", 0), ("flood", 1)])
        # no refill_per_s: the bucket is a hard one-shot cap
        self.assertTrue(ten.is_throttled("flood"))
        snap = ten.snapshot()
        self.assertTrue(snap["flood"]["over_budget"])
        self.assertEqual(snap["flood"]["tokens"], 2)

    def test_validation(self):
        with self.assertRaises(InvalidArgumentError):
            TenantScheduler([TenantSpec("x", weight=0.0)])
        with self.assertRaises(InvalidArgumentError):
            TenantScheduler([TenantSpec("x", token_budget=0)])
        ten = TenantScheduler()
        with self.assertRaises(InvalidArgumentError):
            ten.spec("nobody")

    def test_slo_objectives(self):
        ten = TenantScheduler([TenantSpec("gold", slo_ms=250.0),
                               TenantSpec("free")])
        objs = ten.slo_objectives("eng#1")
        self.assertEqual(len(objs), 1)  # only the declared SLO
        self.assertIn("gold", objs[0].name)


class TestEngineTenancy(unittest.TestCase):
    @classmethod
    def setUpClass(cls):
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        pt.seed(4321)
        cls.cfg = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                            num_heads=4, max_position=64, dropout=0.0,
                            lora_capacity=2, lora_rank=4)
        cls.model = GPTForCausalLM(cls.cfg)
        cls.model.eval()

    def _adapters(self):
        from paddle_tpu.lora import random_adapter
        return [random_adapter(self.model, f"t{i}", rank=4, seed=20 + i,
                               alpha=32.0, std=0.2) for i in range(2)]

    def test_mixed_adapters_bit_identical_to_serial_closed_compile_set(self):
        # three tenants (two adapters + base) interleaved on ONE engine:
        # every completion must be bitwise the per-tenant serial run,
        # and the mixed traffic must not reopen the compile set
        ten = TenantScheduler([
            TenantSpec("acme", weight=2.0, adapter_id=0),
            TenantSpec("globex", adapter_id=1),
            TenantSpec("base", adapter_id=-1)])
        prompts = [(np.arange(5) * 11 + 3) % 97, np.arange(4) % 97,
                   (np.arange(6) * 3 + 1) % 97]
        a0, a1 = self._adapters()

        def build(name, tenancy=None):
            eng = GenerationEngine(self.model, prompt_buckets=[8],
                                   batch_size=2, cache_len=48, paged=True,
                                   kv_page_size=8, tenancy=tenancy,
                                   name=name)
            eng.install_adapter(0, a0)
            eng.install_adapter(1, a1)
            eng.warmup()
            return eng

        refs = {}
        with build("ten-serial") as ser:
            for tn, aid in (("acme", 0), ("globex", 1), ("base", -1)):
                refs[tn] = [ser.generate(p, 6, timeout=120,
                                         adapter_id=aid).tolist()
                            for p in prompts]
        with build("ten-mixed", tenancy=ten) as eng:
            n_tr = eng.compile_count
            futs = [(tn, i, eng.submit(p, 6, tenant=tn))
                    for i, p in enumerate(prompts)
                    for tn in ("acme", "globex", "base")]
            for tn, i, f in futs:
                self.assertEqual(f.result(120).tolist(), refs[tn][i],
                                 f"tenant {tn} prompt {i}")
            self.assertEqual(eng.compile_count, n_tr)
            st = eng.stats()
            self.assertEqual(st["completed"], 9)
        # adapters actually differentiate the tenants
        self.assertNotEqual(refs["acme"], refs["base"])
        self.assertNotEqual(refs["acme"], refs["globex"])

    def test_budget_preemption_regenerates_bit_identically(self):
        # drain the tenant's bucket mid-decode: the engine must preempt
        # its live slot (pages released), then re-admit after refill and
        # regenerate EXACTLY the greedy tokens of an uncontended run
        ten = TenantScheduler([
            TenantSpec("metered", token_budget=50, refill_per_s=500.0)])
        p = (np.arange(6) * 9 + 4) % 97
        with GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                              cache_len=48, paged=True, kv_page_size=8,
                              tenancy=ten, name="ten-preempt") as eng:
            eng.warmup()
            ref = eng.generate(p, 20, timeout=120).tolist()  # untagged
            base_steps = eng.stats()["decode_steps"]
            fut = eng.submit(p, 20, tenant="metered")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:  # wait until mid-decode
                if eng.stats()["decode_steps"] > base_steps:
                    break
                time.sleep(0.002)
            ten.charge("metered", 200)  # empty the bucket -> preempt
            self.assertEqual(fut.result(120).tolist(), ref)
            st = eng.stats()
            self.assertGreaterEqual(st["tenant_preempted"], 1)
            self.assertGreaterEqual(ten.snapshot()["metered"]["preempted"],
                                    1)
            self.assertEqual(st["kv_pages_leaked"], 0)

    def test_tenancy_requires_paged(self):
        ten = TenantScheduler([TenantSpec("a")])
        with self.assertRaises(InvalidArgumentError):
            GenerationEngine(self.model, prompt_buckets=[8], batch_size=2,
                             continuous=True, paged=False, tenancy=ten,
                             name="ten-dense")


class TestS607(unittest.TestCase):
    def test_fires_on_in_budget_starvation(self):
        from paddle_tpu.analysis import RetraceMonitor
        from paddle_tpu.framework import trace_events
        with RetraceMonitor(budget=8) as mon:
            trace_events.notify(("tenancy", "eng#t"), {
                "decode_steps_after_warm": 200, "adapters_installed": 0,
                "adapters_dead": 0,
                "tenants": {"victim": {
                    "weight": 1.0, "queued": 3, "admitted": 1,
                    "starved_after_warm": 40, "over_budget": False}}})
        self.assertEqual(mon.tenancy_stats("eng#t")["tenants"]["victim"]
                         ["starved_after_warm"], 40)
        diags = [d for d in mon.diagnostics() if d.rule == "S607"]
        self.assertEqual(len(diags), 1)
        self.assertIn("victim", diags[0].message)
        self.assertIn("weighted-fair", diags[0].message)

    def test_fires_on_dead_adapters(self):
        from paddle_tpu.analysis import RetraceMonitor
        from paddle_tpu.framework import trace_events
        with RetraceMonitor() as mon:
            trace_events.notify(("tenancy", "eng#d"), {
                "decode_steps_after_warm": 120, "adapters_installed": 3,
                "adapters_dead": 2, "tenants": {}})
        diags = [d for d in mon.diagnostics() if d.rule == "S607"]
        self.assertEqual(len(diags), 1)
        self.assertIn("never matched", diags[0].message)

    def test_silent_on_throttled_and_healthy(self):
        from paddle_tpu.analysis import RetraceMonitor
        from paddle_tpu.framework import trace_events
        with RetraceMonitor(budget=8) as mon:
            trace_events.notify(("tenancy", "eng#ok"), {
                "decode_steps_after_warm": 200, "adapters_installed": 2,
                "adapters_dead": 0,
                "tenants": {
                    # over-budget waiting = throttling by design
                    "flooder": {"weight": 1.0, "queued": 9, "admitted": 2,
                                "starved_after_warm": 90,
                                "over_budget": True},
                    # in-budget and promptly served
                    "gold": {"weight": 2.0, "queued": 0, "admitted": 5,
                             "starved_after_warm": 2,
                             "over_budget": False}}})
        self.assertEqual(
            [d for d in mon.diagnostics() if d.rule == "S607"], [])


class TestTenantLabelCap(unittest.TestCase):
    def test_tenant_flood_lands_in_overflow_child(self):
        # a malicious/buggy client inventing tenant ids must not blow up
        # the label space: past the cap every new tenant routes to the
        # __overflow__ child and the drop counter ticks
        import paddle_tpu.observability as obs
        from paddle_tpu.observability.metrics import (
            DROPPED_LABELS_COUNTER, MetricRegistry, set_default_registry)
        from paddle_tpu.serving.metrics import ServingMetrics
        reg = MetricRegistry(max_label_children=4)
        was_enabled = obs._enabled
        set_default_registry(reg)
        obs._enabled = True
        try:
            sm = ServingMetrics("ovf#0")
            for i in range(10):
                sm.observe_tenant(f"tenant-{i}", 5.0, 3)
            fam = reg.get("paddle_tpu_serving_tenant_latency_ms")
            self.assertIsNotNone(fam)
            kids = [values for values, _ in fam.children()]
            self.assertIn(("__overflow__",), kids)
            self.assertLessEqual(len(kids), 5)  # cap + overflow child
            dropped = reg.get(DROPPED_LABELS_COUNTER)
            self.assertIsNotNone(dropped)
            total = sum(v for _, _, v in dropped.expose())
            self.assertGreaterEqual(total, 6)
        finally:
            obs._enabled = was_enabled
            set_default_registry(None)


if __name__ == "__main__":
    unittest.main()

"""End-to-end request tracing + SLO burn-rate engine.

Covers the tracing layer (span trees, ring-buffer bounds, off-means-off,
JSONL export + cross-process chrome merge, profiler timeline merge),
its propagation through MicroBatcher and Router (failover and hedge
attempts as sibling spans; the hedge loser never double-counts into
latency quantiles), the SLO engine (latency / availability / throughput
objectives, multi-window burn-rate alerting on an injected clock, scale
signals delivered through the Router hook, ``paddle_tpu_slo_*`` gauges,
analysis rule M903, the profiler "SLO" section), and the satellite
hardening: per-metric label-cardinality caps with drop accounting and a
crash-tolerant deterministic ``merge_jsonl``.
"""
import json
import os
import tempfile
import threading
import time
import unittest
from concurrent.futures import Future

import numpy as np

from paddle_tpu import observability as obs
from paddle_tpu import profiler
from paddle_tpu.analysis import RetraceMonitor
from paddle_tpu.framework import trace_events
from paddle_tpu.framework.errors import (
    InvalidArgumentError,
    TransientDeviceError,
)
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import slo as slo_mod
from paddle_tpu.observability import tracing
from paddle_tpu.observability.exporters import merge_jsonl
from paddle_tpu.observability.slo import Objective, SloEngine
from paddle_tpu.resilience import retry as _retry_mod
from paddle_tpu.serving import MicroBatcher, Router
from paddle_tpu.serving.metrics import ServingMetrics


class FakeEngine:
    """Duck-typed replica engine (mirrors test_router's)."""

    def __init__(self, result="ok", fail_with=None, manual=False):
        self.result = result
        self.fail_with = fail_with
        self.manual = manual
        self.pending = []
        self.calls = 0
        self.trace_ctxs = []

    def synthetic_inputs(self):
        return [np.zeros((1,), np.float32)]

    def infer(self, inputs, timeout=None):
        return [self.result]

    def submit(self, inputs, deadline_ms=None, trace_ctx=None, **kw):
        self.calls += 1
        self.trace_ctxs.append(trace_ctx)
        f = Future()
        if self.manual:
            self.pending.append(f)
            return f
        if self.fail_with is not None:
            f.set_exception(self.fail_with)
        else:
            f.set_result(self.result)
        return f

    def resolve(self, i=0):
        self.pending.pop(i).set_result(self.result)


def make_router(engines, **kw):
    kw.setdefault("probe_interval_s", None)
    kw.setdefault("circuit_kw", {"failure_threshold": 1.0, "window": 2,
                                 "cooldown_ms": 60_000,
                                 "half_open_probes": 1})
    return Router(engines, **kw)


class TracingTestCase(unittest.TestCase):
    def setUp(self):
        obs.disable()
        obs_metrics.set_default_registry(obs_metrics.MetricRegistry())

    def tearDown(self):
        obs.disable()
        obs_metrics.set_default_registry(obs_metrics.MetricRegistry())


class TestTracer(TracingTestCase):
    def test_span_tree_shares_trace_id(self):
        tr = tracing.enable(capacity=64)
        root = tr.start_trace("router/submit", kind="request", router="r")
        child = tr.start_span("router/dispatch", root.context(),
                              kind="primary", replica="r[0]")
        child.end(outcome="ok")
        tr.record("batcher/queue", child.context(), time.monotonic(), 1.0,
                  kind="queue")
        root.end(outcome="ok")
        spans = tr.spans()
        self.assertEqual(len(spans), 3)
        self.assertEqual(len({s["trace_id"] for s in spans}), 1)
        by_name = {s["name"]: s for s in spans}
        self.assertIsNone(by_name["router/submit"]["parent_id"])
        self.assertEqual(by_name["router/dispatch"]["parent_id"],
                         by_name["router/submit"]["span_id"])
        self.assertEqual(by_name["batcher/queue"]["parent_id"],
                         by_name["router/dispatch"]["span_id"])
        self.assertEqual(by_name["router/dispatch"]["args"]["outcome"],
                         "ok")

    def test_span_end_is_idempotent(self):
        tr = tracing.enable(capacity=64)
        s = tr.start_trace("x")
        s.end(outcome="ok")
        s.end(outcome="error:late")  # the losing close must not re-record
        spans = tr.spans()
        self.assertEqual(len(spans), 1)
        self.assertEqual(spans[0]["args"]["outcome"], "ok")

    def test_ring_buffer_caps_and_counts_drops(self):
        tr = tracing.enable(capacity=4)
        root = tr.start_trace("root")
        for i in range(10):
            tr.record(f"s{i}", root.context(), time.monotonic(), 0.1)
        st = tr.stats()
        self.assertEqual(st["buffered"], 4)
        self.assertEqual(st["dropped"], 6)
        self.assertEqual([s["name"] for s in tr.spans()],
                         ["s6", "s7", "s8", "s9"])

    def test_enable_is_idempotent_and_disable_clears(self):
        tr = tracing.enable(capacity=8)
        self.assertIs(tracing.enable(), tr)
        self.assertIs(tracing._active, tr)
        tracing.disable()
        self.assertIsNone(tracing._active)
        self.assertIsNone(tracing.active())

    def test_export_jsonl_and_merge_chrome(self):
        tr = tracing.enable(capacity=64)
        root = tr.start_trace("root")
        tr.record("child", root.context(), time.monotonic(), 2.0)
        root.end()
        with tempfile.TemporaryDirectory() as d:
            base = os.path.join(d, "trace.jsonl")
            p = tracing.export_jsonl(base, process_index=0)
            self.assertTrue(p.endswith(".p0.jsonl"))
            out = os.path.join(d, "merged.json")
            n = tracing.merge_chrome(base, out)
            self.assertEqual(n, 2)
            doc = json.load(open(out))
            names = {e["name"] for e in doc["traceEvents"]}
            self.assertEqual(names, {"root", "child"})
            for e in doc["traceEvents"]:
                self.assertIn("trace_id", e["args"])

    def test_profiler_chrome_export_includes_trace_spans(self):
        tr = tracing.enable(capacity=64)
        root = tr.start_trace("traced/request")
        root.end()
        with profiler.profiler():
            with profiler.RecordEvent("host/work"):
                pass
        with tempfile.TemporaryDirectory() as d:
            out = os.path.join(d, "chrome.json")
            profiler.export_chrome_tracing(out)
            names = {e["name"] for e in json.load(open(out))["traceEvents"]}
        self.assertIn("host/work", names)
        self.assertIn("traced/request", names)


class TestBatcherTracing(TracingTestCase):
    def test_batcher_records_queue_and_execute_spans(self):
        tr = tracing.enable(capacity=64)
        root = tr.start_trace("router/submit")
        mb = MicroBatcher(lambda inputs: 0,
                          lambda bucket, reqs: [r.inputs for r in reqs],
                          max_queue_delay_ms=0.0, name="trace-eng")
        try:
            fut = mb.submit((1,), trace_ctx=root.context())
            fut.result(5.0)
        finally:
            mb.close()
        names = {s["name"]: s for s in tr.spans()}
        self.assertIn("batcher/queue", names)
        self.assertIn("batcher/execute", names)
        for n in ("batcher/queue", "batcher/execute"):
            self.assertEqual(names[n]["trace_id"], root.trace_id)
            self.assertEqual(names[n]["parent_id"], root.span_id)
            self.assertEqual(names[n]["args"]["engine"], "trace-eng")

    def test_tracing_off_records_nothing(self):
        self.assertIsNone(tracing._active)
        mb = MicroBatcher(lambda inputs: 0,
                          lambda bucket, reqs: [0 for _ in reqs],
                          max_queue_delay_ms=0.0)
        try:
            mb.submit((1,)).result(5.0)
        finally:
            mb.close()
        tr = tracing.enable(capacity=8)  # fresh tracer, after the fact
        self.assertEqual(tr.stats()["recorded"], 0)


class TestRouterTracing(TracingTestCase):
    def test_submit_creates_root_and_dispatch_spans(self):
        tr = tracing.enable(capacity=64)
        e = FakeEngine()
        r = make_router([e])
        try:
            r.submit(1).result(5.0)
        finally:
            r.close()
        spans = {s["name"]: s for s in tr.spans()}
        self.assertIn("router/submit", spans)
        self.assertIn("router/dispatch", spans)
        self.assertEqual(spans["router/dispatch"]["parent_id"],
                         spans["router/submit"]["span_id"])
        self.assertEqual(spans["router/submit"]["args"]["winner"],
                         "primary")
        # the engine received the attempt span as its trace parent
        self.assertEqual(e.trace_ctxs[0].span_id,
                         spans["router/dispatch"]["span_id"])

    def test_engines_see_no_trace_kwarg_when_tracing_off(self):
        class Strict:
            def __init__(self):
                self.calls = 0

            def submit(self, inputs, deadline_ms=None):  # no **kw
                self.calls += 1
                f = Future()
                f.set_result("ok")
                return f

        r = make_router([Strict()])
        try:
            self.assertEqual(r.submit(1).result(5.0), "ok")
        finally:
            r.close()

    def test_failover_attempts_are_sibling_spans(self):
        tr = tracing.enable(capacity=64)
        bad = FakeEngine(fail_with=TransientDeviceError("boom"))
        good = FakeEngine(result="recovered")
        r = make_router([bad, good], policy="least")
        try:
            self.assertEqual(r.submit(1).result(5.0), "recovered")
        finally:
            r.close()
        dispatches = [s for s in tr.spans()
                      if s["name"] == "router/dispatch"]
        self.assertEqual(len(dispatches), 2)
        self.assertEqual(len({s["parent_id"] for s in dispatches}), 1)
        outcomes = {s["kind"]: s["args"]["outcome"] for s in dispatches}
        self.assertEqual(outcomes["primary"],
                         "error:TransientDeviceError")
        self.assertEqual(outcomes["failover"], "ok")
        root = [s for s in tr.spans() if s["name"] == "router/submit"][0]
        self.assertEqual(root["args"]["winner"], "failover")

    def test_hedge_loser_span_without_double_counting(self):
        tr = tracing.enable(capacity=64)
        a, b = FakeEngine(manual=True), FakeEngine(manual=True)
        timers = []

        class T:
            def __init__(self, fn):
                self.fn = fn

            def start(self):
                timers.append(self)

            def cancel(self):
                pass

        r = make_router([a, b], policy="least", hedge=True,
                        hedge_delay_ms=1.0,
                        timer_factory=lambda d, fn: T(fn))
        try:
            fut = r.submit(1)
            timers[0].fn()                      # fire the hedge now
            self.assertEqual(a.calls + b.calls, 2)
            primary = a if a.pending else b
            hedge = b if primary is a else a
            primary.resolve()                   # primary wins the race
            fut.result(5.0)
            hedge.resolve()                     # loser completes late
            for _ in range(100):                # let the callback land
                if any(rep.snapshot().get("lost_races")
                       for rep in r.replicas):
                    break
                time.sleep(0.01)
            snap = r.metrics.snapshot()
            self.assertEqual(snap["completed"], 1)
            self.assertEqual(snap["hedges"], 1)
            self.assertEqual(snap["hedge_wins"], 0)
            # exactly ONE latency sample — the loser never double-counts
            self.assertEqual(len(r.metrics._latency_ms), 1)
            self.assertEqual(sum(rep.snapshot().get("lost_races", 0)
                                 for rep in r.replicas), 1)
        finally:
            r.close()
        dispatches = [s for s in tr.spans()
                      if s["name"] == "router/dispatch"]
        self.assertEqual(len(dispatches), 2)
        outcomes = sorted(s["args"]["outcome"] for s in dispatches)
        self.assertEqual(outcomes, ["lost", "ok"])
        kinds = {s["kind"] for s in dispatches}
        self.assertEqual(kinds, {"primary", "hedge"})

    def test_hedge_loser_skips_latency_histogram(self):
        obs.enable()  # registry mirror on: winner-only observation
        a, b = FakeEngine(manual=True), FakeEngine(manual=True)
        timers = []

        class T:
            def __init__(self, fn):
                self.fn = fn

            def start(self):
                timers.append(self)

            def cancel(self):
                pass

        r = make_router([a, b], policy="least", hedge=True,
                        hedge_delay_ms=1.0,
                        timer_factory=lambda d, fn: T(fn))
        try:
            fut = r.submit(1)
            timers[0].fn()
            (a if a.pending else b).resolve()
            fut.result(5.0)
            (a if a.pending else b).resolve()
            time.sleep(0.05)
            hist = obs.default_registry().get(
                "paddle_tpu_serving_latency_ms")
            self.assertIsNotNone(hist)
            child = dict(hist.children())[(r.name,)]
            self.assertEqual(child.count, 1)
        finally:
            r.close()

    def test_rejected_submit_closes_root_span(self):
        tr = tracing.enable(capacity=64)
        e = FakeEngine()
        e.raise_sync = InvalidArgumentError("bad input")
        e.submit = lambda *a, **k: (_ for _ in ()).throw(
            InvalidArgumentError("bad input"))
        r = make_router([e])
        try:
            with self.assertRaises(InvalidArgumentError):
                r.submit(1)
        finally:
            r.close()
        root = [s for s in tr.spans() if s["name"] == "router/submit"]
        self.assertEqual(len(root), 1)
        self.assertTrue(
            root[0]["args"]["outcome"].startswith("rejected:"))


class TestScaleHooks(TracingTestCase):
    def test_router_counts_and_fans_out_signals(self):
        r = make_router([FakeEngine()])
        got = []
        try:
            r.register_scale_hook(got.append)
            up = slo_mod.ScaleSignal("up", "burning", "p99", 14.4, 0.0)
            r.on_scale_signal(up)
            r.on_scale_signal(
                slo_mod.ScaleSignal("down", "quiet", "", 0.0, 1.0))
            r.on_scale_signal(
                slo_mod.ScaleSignal("steady", "ok", "", 0.2, 2.0))
            snap = r.metrics.snapshot()
            self.assertEqual(snap["scale_up_signals"], 1)
            self.assertEqual(snap["scale_down_signals"], 1)
            self.assertEqual(snap["scale_steady_signals"], 1)
            self.assertEqual([s.direction for s in got],
                             ["up", "down", "steady"])
        finally:
            r.close()

    def test_broken_hook_does_not_break_delivery(self):
        r = make_router([FakeEngine()])
        got = []
        try:
            r.register_scale_hook(
                lambda s: (_ for _ in ()).throw(RuntimeError("boom")))
            r.register_scale_hook(got.append)
            r.on_scale_signal(slo_mod.ScaleSignal("up", "", "", 1.0, 0.0))
            self.assertEqual(len(got), 1)
        finally:
            r.close()


class TestSloEngine(TracingTestCase):
    def _latency_engine(self, reg, clk, goal=0.99,
                        windows=((60.0, 10.0, 10.0),), **kw):
        return SloEngine(
            [Objective.latency("p99_latency", threshold_ms=50.0,
                               engine="e1", goal=goal, windows=windows)],
            registry=reg, clock=lambda: clk[0], **kw)

    def test_objective_validation(self):
        with self.assertRaises(InvalidArgumentError):
            Objective("x", "latency", goal=1.5)
        with self.assertRaises(InvalidArgumentError):
            Objective("x", "latency", goal=0.99,
                      windows=((10.0, 60.0, 14.4),))  # short >= long
        with self.assertRaises(InvalidArgumentError):
            SloEngine([])
        o = Objective.latency("p", threshold_ms=50)
        with self.assertRaises(InvalidArgumentError):
            SloEngine([o, Objective.latency("p", threshold_ms=10)])

    def test_latency_burn_rate_alert_and_recovery(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        eng = self._latency_engine(reg, clk)
        h = reg.histogram("paddle_tpu_serving_latency_ms", "",
                          ("engine",))
        for _ in range(100):
            h.labels("e1").observe(5.0)
        snap = eng.tick()
        self.assertEqual(snap["p99_latency_alert"], 0)
        clk[0] += 5.0
        for _ in range(100):
            h.labels("e1").observe(500.0)  # 50% bad -> 50x burn
        snap = eng.tick()
        self.assertEqual(snap["p99_latency_alert"], 1)
        self.assertGreater(snap["p99_latency_burn"], 10.0)
        self.assertEqual(snap["last_signal"], "up")
        # recovery: a long healthy stretch drains both windows
        for _ in range(30):
            clk[0] += 5.0
            for _ in range(200):
                h.labels("e1").observe(5.0)
            snap = eng.tick()
        self.assertEqual(snap["p99_latency_alert"], 0)
        eng.close()

    def test_slo_gauges_exported(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        eng = self._latency_engine(reg, clk)
        reg.histogram("paddle_tpu_serving_latency_ms", "",
                      ("engine",)).labels("e1").observe(5.0)
        eng.tick()
        for name in ("paddle_tpu_slo_burn_rate", "paddle_tpu_slo_alert",
                     "paddle_tpu_slo_goal", "paddle_tpu_slo_good_ratio",
                     "paddle_tpu_slo_scale_signal"):
            self.assertIsNotNone(reg.get(name), name)
        g = reg.get("paddle_tpu_slo_goal")
        self.assertEqual(
            dict(g.children())[(eng.name, "p99_latency")].value, 0.99)
        eng.close()

    def test_availability_objective_from_bus_snapshots(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        eng = SloEngine(
            [Objective.availability("avail", site="e1", goal=0.9,
                                    windows=((60.0, 10.0, 5.0),))],
            registry=reg, clock=lambda: clk[0])
        eng.install()
        try:
            trace_events.notify(("serving", "e1"),
                                {"completed": 100, "errors": 0})
            eng.tick()
            clk[0] += 5.0
            trace_events.notify(("serving", "e1"),
                                {"completed": 100, "errors": 80,
                                 "shed": 20})
            snap = eng.tick()
            self.assertEqual(snap["avail_alert"], 1)
            self.assertEqual(snap["last_signal"], "up")
        finally:
            eng.close()

    def test_throughput_floor_objective(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        eng = SloEngine(
            [Objective.throughput("tps", site="e1",
                                  floor_tokens_per_s=100.0, goal=0.5,
                                  windows=((60.0, 10.0, 1.5),))],
            registry=reg, clock=lambda: clk[0])
        eng.install()
        try:
            tokens = 0
            for _ in range(4):  # every tick below the floor spends budget
                tokens += 10
                trace_events.notify(
                    ("serving", "e1"),
                    {"tokens": tokens, "tokens_per_s": 20.0})
                eng.tick()
                clk[0] += 3.0
            snap = eng.snapshot()
            self.assertEqual(snap["tps_alert"], 1)
            # idle ticks (tokens unchanged) must NOT spend budget
            before = dict(eng._thr_cum)
            eng.tick()
            self.assertEqual(eng._thr_cum, before)
        finally:
            eng.close()

    def test_scale_signal_down_after_quiet_full_window(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        eng = self._latency_engine(reg, clk,
                                   windows=((20.0, 5.0, 10.0),))
        h = reg.histogram("paddle_tpu_serving_latency_ms", "",
                          ("engine",))
        sigs = []
        eng.on_scale(sigs.append)
        for _ in range(10):
            for _ in range(50):
                h.labels("e1").observe(5.0)
            eng.tick()
            clk[0] += 5.0
        self.assertEqual(sigs[-1].direction, "down")
        self.assertIn("steady", [s.direction for s in sigs])
        eng.close()

    def test_bind_router_delivers_signals(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        r = make_router([FakeEngine()])
        eng = self._latency_engine(reg, clk)
        try:
            eng.bind_router(r)
            h = reg.histogram("paddle_tpu_serving_latency_ms", "",
                              ("engine",))
            for _ in range(100):
                h.labels("e1").observe(500.0)
            eng.tick()
            clk[0] += 5.0
            for _ in range(100):
                h.labels("e1").observe(500.0)
            eng.tick()
            self.assertGreaterEqual(
                r.metrics.snapshot()["scale_up_signals"], 1)
        finally:
            eng.close()
            r.close()

    def test_m903_fires_after_warm_burn(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        was_warm = _retry_mod._warm
        mon = RetraceMonitor().install()
        eng = self._latency_engine(reg, clk)
        eng.install()
        try:
            _retry_mod.mark_warm()
            h = reg.histogram("paddle_tpu_serving_latency_ms", "",
                              ("engine",))
            for _ in range(100):
                h.labels("e1").observe(500.0)
            eng.tick()
            clk[0] += 5.0
            for _ in range(100):
                h.labels("e1").observe(500.0)
            eng.tick()
            stats = mon.slo_stats(eng.name)
            self.assertGreaterEqual(stats.get("alerts_after_warm", 0), 1)
            rules = [d.rule for d in mon.diagnostics()]
            self.assertIn("M903", rules)
            m903 = [d for d in mon.diagnostics() if d.rule == "M903"][0]
            self.assertIn("budget", m903.message)
        finally:
            _retry_mod._warm = was_warm
            eng.close()
            mon.uninstall()

    def test_no_m903_when_alerts_precede_warmup(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        was_warm = _retry_mod._warm
        mon = RetraceMonitor().install()
        eng = self._latency_engine(reg, clk)
        eng.install()
        try:
            _retry_mod._warm = False
            h = reg.histogram("paddle_tpu_serving_latency_ms", "",
                              ("engine",))
            for _ in range(100):
                h.labels("e1").observe(500.0)
            eng.tick()
            clk[0] += 5.0
            for _ in range(100):
                h.labels("e1").observe(500.0)
            eng.tick()
            self.assertNotIn("M903",
                             [d.rule for d in mon.diagnostics()])
        finally:
            _retry_mod._warm = was_warm
            eng.close()
            mon.uninstall()

    def test_profiler_summary_has_slo_section(self):
        reg = obs_metrics.MetricRegistry()
        clk = [0.0]
        eng = self._latency_engine(reg, clk)
        reg.histogram("paddle_tpu_serving_latency_ms", "",
                      ("engine",)).labels("e1").observe(5.0)
        eng.tick()
        text = profiler.summary()
        self.assertIn("SLO", text)
        self.assertIn("p99_latency", text)
        eng.close()

    def test_start_stop_background_thread(self):
        reg = obs_metrics.MetricRegistry()
        eng = SloEngine(
            [Objective.latency("p", threshold_ms=50.0, engine="e1")],
            registry=reg)
        eng.start(interval_s=0.01)
        for _ in range(200):
            if eng.snapshot()["ticks"] > 0:
                break
            time.sleep(0.01)
        self.assertGreater(eng.snapshot()["ticks"], 0)
        eng.close()
        self.assertIsNone(eng._thread)


class TestLabelCardinalityCap(TracingTestCase):
    def test_counter_overflow_routes_and_counts(self):
        reg = obs_metrics.MetricRegistry(max_label_children=2)
        c = reg.counter("t_total", "", ("k",))
        c.labels("a").inc()
        c.labels("b").inc()
        c.labels("c").inc()      # past the cap
        c.labels("d").inc(2.0)   # shares the overflow child
        c.labels("a").inc()      # existing children stay addressable
        samples = {tuple(sorted(l.items())): v for _, l, v in c.expose()}
        self.assertEqual(samples[(("k", "a"),)], 2.0)
        self.assertEqual(
            samples[(("k", "other"), ("overflow", "true"))], 3.0)
        drop = reg.get(obs_metrics.DROPPED_LABELS_COUNTER)
        self.assertEqual(
            {l["metric"]: v for _, l, v in drop.expose()},
            {"t_total": 2.0})

    def test_histogram_overflow_exposes_overflow_child(self):
        reg = obs_metrics.MetricRegistry(max_label_children=1)
        h = reg.histogram("h_ms", "", ("k",))
        h.labels("a").observe(1.0)
        h.labels("b").observe(2.0)
        rows = h.expose()
        over = [l for _, l, _ in rows if l.get("overflow") == "true"]
        self.assertTrue(over)
        self.assertTrue(all(l["k"] == "other" for l in over))

    def test_drop_counter_itself_is_uncapped(self):
        reg = obs_metrics.MetricRegistry(max_label_children=1)
        for i in range(5):
            c = reg.counter(f"m{i}_total", "", ("k",))
            c.labels("a").inc()
            c.labels("b").inc()  # each metric overflows once
        drop = reg.get(obs_metrics.DROPPED_LABELS_COUNTER)
        self.assertEqual(len(drop.children()), 5)

    def test_unlabeled_metrics_unaffected(self):
        reg = obs_metrics.MetricRegistry(max_label_children=1)
        g = reg.gauge("g1", "")
        g.set(7.0)
        self.assertEqual(g.expose(), [("g1", {}, 7.0)])


class TestMergeJsonl(TracingTestCase):
    def test_skips_truncated_lines_and_sorts_deterministically(self):
        with tempfile.TemporaryDirectory() as d:
            base = os.path.join(d, "m.jsonl")
            with open(os.path.join(d, "m.p0.jsonl"), "w") as f:
                f.write(json.dumps({"ts": 2.0, "process_index": 0}) + "\n")
                f.write(json.dumps({"ts": 1.0, "process_index": 0}) + "\n")
                f.write('{"ts": 3.0, "process_in')  # killed mid-write
            with open(os.path.join(d, "m.p1.jsonl"), "w") as f:
                f.write(json.dumps({"ts": 1.0, "process_index": 1}) + "\n")
                f.write("\n")
            recs = merge_jsonl(base)
            self.assertEqual(len(recs), 3)  # truncated line skipped
            self.assertEqual([(r["ts"], r["process_index"]) for r in recs],
                             [(1.0, 0), (1.0, 1), (2.0, 0)])
            # same input -> byte-identical merged output
            out1 = os.path.join(d, "o1.jsonl")
            out2 = os.path.join(d, "o2.jsonl")
            merge_jsonl(base, out1)
            merge_jsonl(base, out2)
            self.assertEqual(open(out1).read(), open(out2).read())


class TestServingLatencyMirror(TracingTestCase):
    def test_observe_latency_feeds_registry_histogram(self):
        obs.enable()
        m = ServingMetrics("mirror-eng")
        m.observe_latency_ms(12.0)
        m.observe_latency_ms(700.0)
        h = obs.default_registry().get("paddle_tpu_serving_latency_ms")
        child = dict(h.children())[("mirror-eng",)]
        self.assertEqual(child.count, 2)
        self.assertAlmostEqual(child.sum, 712.0)

    def test_off_means_no_histogram(self):
        m = ServingMetrics("quiet-eng")
        m.observe_latency_ms(12.0)
        self.assertIsNone(
            obs.default_registry().get("paddle_tpu_serving_latency_ms"))


if __name__ == "__main__":
    unittest.main()

"""row_conv, diag_embed, hsigmoid_loss + small tensor utilities.

Reference capability: nn/functional/extension.py:151 (row_conv),
diag_embed_op, hierarchical_sigmoid_op + matrix_bit_code.h (hsigmoid),
tensor/math.py add_n/addcmul, tensor/random.py gaussian,
tensor/to_string.py printoptions.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn.functional import diag_embed, hsigmoid_loss, row_conv


class TestRowConv:
    def test_matches_loop_oracle(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 6, 3).astype(np.float32)
        w = rng.randn(4, 3).astype(np.float32)
        got = np.asarray(row_conv(x, w))
        want = np.zeros_like(x)
        for t in range(6):
            for j in range(4):
                if t + j < 6:
                    want[:, t] += x[:, t + j] * w[j]
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_act(self):
        x = np.ones((1, 2, 2), np.float32)
        w = np.ones((1, 2), np.float32)
        out = np.asarray(row_conv(x, w, act="sigmoid"))
        np.testing.assert_allclose(out, 1 / (1 + np.exp(-1.0)), rtol=1e-6)


class TestDiagEmbed:
    def test_basic(self):
        x = np.array([[1.0, 2.0, 3.0]], np.float32)
        out = np.asarray(diag_embed(x))
        assert out.shape == (1, 3, 3)
        np.testing.assert_allclose(out[0], np.diag([1.0, 2.0, 3.0]))

    @pytest.mark.parametrize("offset", [-2, -1, 1, 2])
    def test_offsets(self, offset):
        x = np.arange(1.0, 4.0, dtype=np.float32)
        out = np.asarray(diag_embed(x, offset=offset))
        np.testing.assert_allclose(out, np.diag(x, k=offset))

    def test_dims(self):
        x = np.ones((2, 3), np.float32)
        out = diag_embed(x, dim1=0, dim2=2)
        assert out.shape == (3, 2, 3)


class TestHSigmoid:
    @staticmethod
    def _oracle(x, y, C, w, b):
        """Walk the SimpleCode path per sample (matrix_bit_code.h:119)."""
        out = np.zeros((x.shape[0], 1))
        for n in range(x.shape[0]):
            c = int(y[n]) + C
            length = c.bit_length() - 1
            for bit in range(length):
                idx = (c >> (bit + 1)) - 1
                t = float((c >> bit) & 1)
                z = float(w[idx] @ x[n] + (b[idx] if b is not None else 0.0))
                p = 1.0 / (1.0 + math.exp(-z))
                out[n, 0] -= t * math.log(p) + (1 - t) * math.log(1 - p)
        return out

    def test_matches_path_oracle(self):
        rng = np.random.RandomState(0)
        N, D, C = 8, 5, 7
        x = rng.randn(N, D).astype(np.float32)
        y = rng.randint(0, C, (N,))
        w = 0.3 * rng.randn(C - 1, D).astype(np.float32)
        b = 0.1 * rng.randn(C - 1).astype(np.float32)
        got = np.asarray(hsigmoid_loss(x, y, C, w, b))
        np.testing.assert_allclose(got, self._oracle(x, y, C, w, b),
                                   rtol=1e-4, atol=1e-5)

    def test_no_bias_and_pow2_classes(self):
        rng = np.random.RandomState(1)
        N, D, C = 6, 4, 8
        x = rng.randn(N, D).astype(np.float32)
        y = rng.randint(0, C, (N,))
        w = 0.3 * rng.randn(C - 1, D).astype(np.float32)
        got = np.asarray(hsigmoid_loss(x, y, C, w))
        np.testing.assert_allclose(got, self._oracle(x, y, C, w, None),
                                   rtol=1e-4, atol=1e-5)

    def test_custom_path(self):
        """path_table/path_code mode reproduces the default tree when fed
        the same codes."""
        rng = np.random.RandomState(2)
        N, D, C = 5, 4, 6
        x = rng.randn(N, D).astype(np.float32)
        y = rng.randint(0, C, (N,))
        w = 0.3 * rng.randn(C - 1, D).astype(np.float32)
        L = max(int(y_n + C).bit_length() - 1 for y_n in y)
        table = -np.ones((N, L), np.int32)
        code = np.zeros((N, L), np.float32)
        for n in range(N):
            c = int(y[n]) + C
            for bit in range(c.bit_length() - 1):
                table[n, bit] = (c >> (bit + 1)) - 1
                code[n, bit] = (c >> bit) & 1
        got = np.asarray(hsigmoid_loss(x, y, C, w, path_table=table,
                                       path_code=code))
        want = np.asarray(hsigmoid_loss(x, y, C, w))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_large_num_classes_exact_bit_length(self):
        """Near powers of two a float32 log2 rounds the path length up —
        the integer bit-length must stay exact (the large-vocab regime is
        what hierarchical softmax exists for)."""
        C = 1 << 20
        rng = np.random.RandomState(4)
        N, D = 2, 4
        x = rng.randn(N, D).astype(np.float32)
        y = np.array([C - 1, 0])  # c = 2^21 - 1 (float32 log2 → 21.0) and 2^20
        w = np.zeros((C - 1, D), np.float32)
        # put recognizable weights on the true path nodes only
        for n in range(N):
            c = int(y[n]) + C
            for bit in range(c.bit_length() - 1):
                w[(c >> (bit + 1)) - 1] = rng.randn(D)
        got = np.asarray(hsigmoid_loss(x, y, C, w))
        np.testing.assert_allclose(got, self._oracle(x, y, C, w, None),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_match_finite_differences(self):
        """OpTest.check_grad equivalent for the hierarchical sigmoid —
        the reference hand-writes HierarchicalSigmoidGradOpKernel."""
        from grad_check import check_grad

        rng = np.random.RandomState(5)
        N, D, C = 3, 4, 6
        x = rng.randn(N, D).astype(np.float64)
        y = rng.randint(0, C, (N,))
        w = 0.3 * rng.randn(C - 1, D).astype(np.float64)
        b = 0.1 * rng.randn(C - 1).astype(np.float64)

        check_grad(lambda a: hsigmoid_loss(a, y, C, jnp.asarray(w),
                                           jnp.asarray(b)).sum(), [x])
        check_grad(lambda a: hsigmoid_loss(jnp.asarray(x), y, C, a,
                                           jnp.asarray(b)).sum(), [w])
        check_grad(lambda a: hsigmoid_loss(jnp.asarray(x), y, C,
                                           jnp.asarray(w), a).sum(), [b])

    def test_trains(self):
        """hsigmoid as an LM head: gradient descent drives the loss down
        and the implied class scores identify the gold class."""
        rng = np.random.RandomState(3)
        N, D, C = 64, 12, 10
        y = rng.randint(0, C, (N,))
        x = np.eye(C, D, dtype=np.float32)[y] + \
            0.1 * rng.randn(N, D).astype(np.float32)
        w = jnp.asarray(0.1 * rng.randn(C - 1, D).astype(np.float32))
        b = jnp.zeros((C - 1,))

        def loss(w, b):
            return hsigmoid_loss(x, y, C, w, b).mean()

        l0 = float(loss(w, b))
        step = jax.jit(lambda w, b: tuple(
            p - 0.5 * g for p, g in zip((w, b), jax.grad(loss, (0, 1))(w, b))))
        for _ in range(150):
            w, b = step(w, b)
        assert float(loss(w, b)) < l0 * 0.3


class TestLayers:
    def test_pairwise_distance(self):
        from paddle_tpu import nn

        rng = np.random.RandomState(0)
        x = rng.randn(4, 6).astype(np.float32)
        y = rng.randn(4, 6).astype(np.float32)
        d = np.asarray(nn.PairwiseDistance(p=2.0, epsilon=0.0)(x, y))
        np.testing.assert_allclose(d, np.linalg.norm(x - y, axis=-1),
                                   rtol=1e-5)
        d1 = np.asarray(nn.PairwiseDistance(p=1.0, epsilon=0.0,
                                            keepdim=True)(x, y))
        assert d1.shape == (4, 1)
        np.testing.assert_allclose(
            d1[:, 0], np.abs(x - y).sum(-1), rtol=1e-5)

    def test_row_conv_layer(self):
        from paddle_tpu import nn

        paddle.seed(0)
        layer = nn.RowConv(num_channels=3, future_context_size=2,
                           activation="relu")
        out = layer(np.ones((2, 5, 3), np.float32))
        assert out.shape == (2, 5, 3)
        want = row_conv(np.ones((2, 5, 3), np.float32),
                        np.asarray(layer.weight.value), act="relu")
        np.testing.assert_allclose(np.asarray(out), np.asarray(want))

    def test_hsigmoid_layer_trains(self):
        from paddle_tpu import nn
        from paddle_tpu import optimizer as popt

        paddle.seed(0)
        rng = np.random.RandomState(0)
        N, D, C = 32, 12, 10
        y = rng.randint(0, C, (N,))
        x = np.eye(C, D, dtype=np.float32)[y] + \
            0.1 * rng.randn(N, D).astype(np.float32)
        layer = nn.HSigmoidLoss(feature_size=D, num_classes=C)
        m = paddle.Model(layer, inputs=["x", "y"], labels=[])
        m.prepare(optimizer=popt.Adam(learning_rate=0.1),
                  loss=lambda out: out.mean())
        l0 = m.train_batch([x, y], [])[0]
        for _ in range(60):
            l1 = m.train_batch([x, y], [])[0]
        assert l1 < l0 * 0.5, (l0, l1)

    def test_rnn_base_alias(self):
        from paddle_tpu import nn

        assert issubclass(nn.LSTM, nn.RNNBase)

    def test_rnn_base_mode_constructor(self):
        """Reference signature RNNBase(mode, input_size, hidden_size)."""
        import jax.numpy as jnp
        from paddle_tpu import nn
        from paddle_tpu.framework.errors import InvalidArgumentError

        paddle.seed(0)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 4),
                        jnp.float32)
        out, (h, c) = nn.RNNBase("LSTM", 4, 8)(x)
        assert out.shape == (2, 5, 8) and h.shape == c.shape == (1, 2, 8)
        out, h = nn.RNNBase("GRU", 4, 8)(x)
        assert out.shape == (2, 5, 8)
        with pytest.raises(InvalidArgumentError, match="mode"):
            nn.RNNBase("FOO", 4, 8)

    def test_hsigmoid_custom_tree_full_weight_rows(self):
        """is_custom=True sizes weights [num_classes, D] — a custom tree
        may address node id num_classes-1 (reference nn/layer/loss.py)."""
        from paddle_tpu import nn

        paddle.seed(0)
        C, D = 6, 4
        layer = nn.HSigmoidLoss(D, C, is_custom=True)
        assert layer.weight.value.shape == (C, D)
        table = np.full((2, 3), C - 1, np.int32)  # max node id everywhere
        code = np.ones((2, 3), np.float32)
        out = layer(np.random.RandomState(0).randn(2, D).astype(np.float32),
                    np.zeros(2, np.int64), path_table=table, path_code=code)
        assert np.isfinite(np.asarray(out)).all()
        with pytest.raises(Exception, match="path_table"):
            layer(np.zeros((2, D), np.float32), np.zeros(2, np.int64))


class TestTensorUtilities:
    def test_add_n(self):
        a, b, c = (np.full((2, 2), v, np.float32) for v in (1, 2, 3))
        np.testing.assert_allclose(np.asarray(paddle.add_n([a, b, c])), 6.0)
        np.testing.assert_allclose(np.asarray(paddle.add_n(a)), 1.0)

    def test_addcmul(self):
        x = np.ones((2,), np.float32)
        np.testing.assert_allclose(
            np.asarray(paddle.addcmul(x, 2 * x, 3 * x, value=0.5)), 4.0)

    def test_gaussian(self):
        paddle.seed(0)
        a = paddle.gaussian([1000], mean=2.0, std=0.5)
        assert abs(float(a.mean()) - 2.0) < 0.1
        assert abs(float(np.asarray(a).std()) - 0.5) < 0.1
        assert paddle.gaussian([2], dtype="float64").dtype == jnp.float64

    def test_static_mode_real(self):
        paddle.disable_static()  # common 2.0 preamble — must be a no-op
        assert paddle.in_dygraph_mode()
        # the 1.x preamble now actually enters graph-building mode
        # (static/graph.py): static.data returns a Program Variable
        paddle.enable_static()
        try:
            assert not paddle.in_dygraph_mode()
            v = paddle.static.data("x_mode", [-1, 3])
            from paddle_tpu.static.graph import Variable as GraphVar

            assert isinstance(v, GraphVar)
            assert paddle.static.Executor() is not None
            assert isinstance(paddle.static.Program(),
                              paddle.static.Program)
        finally:
            paddle.disable_static()
        assert paddle.in_dygraph_mode()
        with pytest.raises(AttributeError):
            paddle.static.definitely_not_an_api
        spec = paddle.static.InputSpec([2, 3])
        assert spec.shape == (2, 3)
        assert "InputSpec(shape=(2, 3)" in repr(spec)

    def test_top_level_parity_shims(self):
        assert paddle.in_dygraph_mode() is True
        paddle.enable_dygraph()
        paddle.disable_dygraph()
        assert paddle.is_compiled_with_xpu() is False
        assert float(paddle.floor_mod(np.array([7]), np.array([3]))[0]) == 1
        np.testing.assert_allclose(
            np.asarray(paddle.crop_tensor(np.arange(9.0).reshape(3, 3),
                                          shape=[2, 2], offsets=[1, 1])),
            [[4.0, 5.0], [7.0, 8.0]])

    def test_create_parameter_trains_standalone(self):
        from paddle_tpu import optimizer as popt

        paddle.seed(0)
        w = paddle.create_parameter([4, 3])
        b = paddle.create_parameter([3], is_bias=True)
        assert w.value.shape == (4, 3)
        assert np.abs(np.asarray(b.value)).sum() == 0  # bias zero-init
        before = np.asarray(w.value).copy()
        opt = popt.SGD(learning_rate=0.1, parameters=[w, b])
        opt.step({"w": np.ones((4, 3), np.float32),
                  "b": np.ones((3,), np.float32)})
        assert not np.allclose(before, np.asarray(w.value))
        # ParamAttr(trainable=False) must be honored (shared with
        # Layer.create_parameter via build_parameter)
        from paddle_tpu import nn

        frozen = paddle.create_parameter(
            [2], attr=nn.ParamAttr(trainable=False))
        assert frozen.trainable is False

    def test_printoptions_and_to_string(self):
        try:
            paddle.set_printoptions(precision=2, threshold=5)
            s = paddle.to_string(np.array([1.23456, 2.34567]))
            assert "1.23" in s and "1.2346" not in s
            assert "shape=[2]" in s
            # print(tensor) goes through numpy's global options — they
            # must be affected too (the reference's primary use)
            assert "1.23" in repr(np.array([1.23456]))
        finally:
            paddle.set_printoptions(precision=8, threshold=1000)

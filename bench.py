"""Benchmarks: the BASELINE.md configs, one JSON line per measured config.

North star (BASELINE.json): ResNet-50 imgs/sec/chip and BERT-base seq/sec/chip
>= 0.9x the stock CUDA build on A100, identical converged accuracy.  The
reference publishes no in-tree numbers (BASELINE.md), so the A100 constants
below stand in from the public NVIDIA DeepLearningExamples results.

Config map (BASELINE.md "Benchmark configs to reproduce"):
  1. MNIST MLP smoke          -> converged-accuracy gate (the reference's own
                                 CI gate form: test_recognize_digits.py:126)
  2. ResNet-50 AMP            -> imgs/sec/chip vs A100_REF_IMG_PER_SEC
  3. BERT-base                -> seq/sec/chip vs A100_REF_SEQ_PER_SEC
  4. 8-chip DP ResNet-50      -> NOT measurable here: this environment exposes
                                 exactly one real chip (the 8-device mesh is
                                 CPU-virtual, see __graft_entry__.dryrun_multichip)
  5. Wide&Deep CTR            -> converged-AUC gate on learnable synthetic
                                 clickthrough (PS capability = sharded tables)

Measurement notes:
  * BERT keeps the round-1/2 methodology (per-step dispatch, best of 3
    windows) for round-over-round comparability.
  * ResNet-50 chains N train steps inside one jitted lax.scan and fetches one
    scalar: the real chip sits behind a network tunnel whose per-dispatch RTT
    (~1s) swamps a ~50ms step.  scan-chaining measures device throughput the
    way a real TPU training loop (local host, compiled loop) would see it.
    Measured artifact size: per-step dispatch reads 60 img/s where the device
    does 2.5k img/s.
  * ResNet runs data_format="NHWC" (the TPU-preferred layout the vision
    models expose) with bf16 params + f32 master weights - the AMP-equivalent
    of the reference's AMP O1 CUDA runs.

The last line is a combined headline: geomean of the two throughput ratios.
"""
import json
import math
import sys
import time

import numpy as np

# Public NVIDIA DeepLearningExamples BERT-base phase-1 (seq 128, AMP, 1xA100)
# pretraining throughput is ~1.1k seq/s.
A100_REF_SEQ_PER_SEC = 1100.0
# Public NVIDIA DeepLearningExamples ResNet-50 v1.5 mixed-precision training,
# single A100: ~2.5k img/s.
A100_REF_IMG_PER_SEC = 2500.0
# Reference CI accuracy gate for the MNIST book test
# (python/paddle/fluid/tests/book/test_recognize_digits.py:126 asserts the
# trained accuracy threshold).
MNIST_ACC_GATE = 0.97
# Synthetic-clickthrough AUC gate for the CTR config (the reference's CTR CI
# runs are loss-decrease asserts).  The task is deliberately noisy — labels
# are Bernoulli draws from a latent logit, Bayes-optimal AUC ~0.91 — so the
# measured AUC sits strictly inside (gate, 1.0) and actually tracks
# convergence quality instead of saturating at the ceiling.
CTR_AUC_GATE = 0.8

# Peak dense bf16 matmul throughput of the chip the bench runs on, used for
# the MFU lines.  v5e ≈ 197 TFLOP/s; override via PADDLE_TPU_PEAK_TFLOPS when
# the driver moves to other hardware.
import os as _os
TPU_PEAK_TFLOPS = float(_os.environ.get("PADDLE_TPU_PEAK_TFLOPS", "197"))

# Model FLOPs per training unit (fwd+bwd ≈ 3× fwd):
#   BERT-base: 6 * 110e6 params * 128 tokens ≈ 84.5 GFLOP / sequence
#   ResNet-50: 3 * ~4.1 GFLOP fwd @224 ≈ 12.3 GFLOP / image
BERT_TRAIN_GFLOP_PER_SEQ = 84.5
RESNET50_TRAIN_GFLOP_PER_IMG = 12.3


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": round(float(value), 4), "unit": unit,
            "vs_baseline": round(float(vs_baseline), 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)
    return line


def bench_bert():
    """Config 3: BERT-base MLM+NSP pretraining step, per-step dispatch."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models import BertForPretraining, bert_base

    BATCH, SEQ, MAX_PRED, WARMUP, ITERS, WINDOWS = 256, 128, 20, 3, 10, 3

    paddle.seed(0)
    cfg = bert_base()
    net = BertForPretraining(cfg).astype("bfloat16")
    opt = popt.AdamW(learning_rate=1e-4, weight_decay=0.01,
                     multi_precision=True)
    model = paddle.Model(
        net,
        inputs=["input_ids", "token_type_ids", "attention_mask",
                "masked_positions"],
        labels=["mlm_labels", "nsp_labels"])
    model.prepare(optimizer=opt, loss=net.loss)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    token_type = (rng.uniform(size=(BATCH, SEQ)) < 0.5).astype(np.int32)
    attn_mask = np.ones((BATCH, SEQ), np.int32)
    positions = np.stack([
        np.sort(rng.choice(SEQ, MAX_PRED, replace=False))
        for _ in range(BATCH)]).astype(np.int32)
    mlm_labels = np.take_along_axis(ids, positions, axis=1)
    nsp_labels = rng.randint(0, 2, size=(BATCH, 1)).astype(np.int32)

    def step():
        loss, _ = model._train_batch_device(
            [ids, token_type, attn_mask, positions],
            [mlm_labels, nsp_labels])
        return loss

    for _ in range(WARMUP):
        loss = step()
    float(loss)  # D2H read truly waits (block_until_ready is a no-op on the
    #              remote-tunnel backend)

    best_dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step()
        final = float(loss)  # steps are param-chained; the last loss waits
        dt = time.perf_counter() - t0  # for the whole window
        assert np.isfinite(final)
        best_dt = min(best_dt, dt)

    seq_per_sec = BATCH * ITERS / best_dt
    tflops = seq_per_sec * BERT_TRAIN_GFLOP_PER_SEQ / 1e3
    return _emit("bert_base_train_seq_per_sec_per_chip", round(seq_per_sec, 2),
                 "seq/s", seq_per_sec / A100_REF_SEQ_PER_SEC,
                 method="per_step_dispatch",
                 achieved_tflops=round(tflops, 1),
                 mfu=round(tflops / TPU_PEAK_TFLOPS, 3))


def bench_resnet50():
    """Config 2: ResNet-50 AMP train step, scan-chained on device."""
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.nn.layer_base import functional_call
    from paddle_tpu.vision.models import resnet50

    BATCH, N_STEPS, WINDOWS = 128, 60, 3  # long windows amortize
    # the ~0.3s tunnel dispatch RTT to <1% of the measurement

    paddle.seed(0)
    # stem_space_to_depth: the 7x7/s2 stem re-expressed as 4x4/s1 on 2x2
    # space-to-depth input (exact same math; vision/models/resnet.py) —
    # C=3 of 128 MXU lanes was the single worst-utilization conv
    net = resnet50(data_format="NHWC",
                   stem_space_to_depth=True).astype("bfloat16")
    params = {k: v.value for k, v in net.named_parameters()}
    bufs = {k: v.value for k, v in net.named_buffers()}
    opt = popt.Momentum(learning_rate=0.1, momentum=0.9, multi_precision=True,
                        weight_decay=1e-4)
    opt_state = opt.init(params)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.uniform(-1, 1, (BATCH, 224, 224, 3))
                    .astype(ml_dtypes.bfloat16))
    y = jnp.asarray(rng.randint(0, 1000, (BATCH, 1)))
    loss_layer = paddle.nn.CrossEntropyLoss()

    def loss_fn(p, b):
        out, nb = functional_call(net, p, x, buffers=b, training=True,
                                  return_buffers=True)
        return loss_layer(out.astype(jnp.float32), y), nb

    @jax.jit
    def run_window(p, os_, b):
        def body(carry, _):
            p, os_, b = carry
            (lv, nb), g = jax.value_and_grad(loss_fn, has_aux=True)(p, b)
            p2, os2 = opt.update(g, os_, p, lr=0.1)
            return (p2, os2, nb), lv
        (p, os_, b), losses = jax.lax.scan(body, (p, os_, b), None,
                                           length=N_STEPS)
        return losses[-1]

    final = float(run_window(params, opt_state, bufs))  # compile + warm
    assert np.isfinite(final)
    best_dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        final = float(run_window(params, opt_state, bufs))
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        best_dt = min(best_dt, dt)

    img_per_sec = BATCH * N_STEPS / best_dt
    tflops = img_per_sec * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3
    return _emit("resnet50_train_img_per_sec_per_chip", round(img_per_sec, 1),
                 "img/s", img_per_sec / A100_REF_IMG_PER_SEC,
                 method="scan_chained",
                 achieved_tflops=round(tflops, 1),
                 mfu=round(tflops / TPU_PEAK_TFLOPS, 3))


def bench_mnist():
    """Config 1: MNIST-shaped MLP smoke - converged-accuracy gate.

    No egress, so the data is synthetic MNIST-shaped: 10 fixed prototype
    images + pixel noise.  The gate form mirrors the reference CI
    (test_recognize_digits.py:126): train briefly, assert accuracy.
    """
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu import optimizer as popt
    from paddle_tpu.nn.layer_base import functional_call

    paddle.seed(0)
    rng = np.random.RandomState(0)
    protos = rng.uniform(0, 1, (10, 784)).astype(np.float32)

    def batch(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, 10, n)
        x = protos[y] + r.normal(0, 0.35, (n, 784)).astype(np.float32)
        return (x - 0.5).astype(np.float32), y

    net = nn.Sequential(nn.Linear(784, 128), nn.ReLU(),
                        nn.Linear(128, 64), nn.ReLU(), nn.Linear(64, 10))
    params = {k: v.value for k, v in net.named_parameters()}
    opt = popt.SGD(learning_rate=0.05)
    opt_state = opt.init(params)
    xs, ys = batch(4096, 1)
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    def loss_fn(p, x, y):
        logits = functional_call(net, p, x)
        lp = jax.nn.log_softmax(logits, -1)
        return -jnp.take_along_axis(lp, y[:, None], 1).mean()

    @jax.jit
    def train(p, os_):
        def body(carry, _):
            p, os_ = carry
            g = jax.grad(loss_fn)(p, xs, ys)
            p2, os2 = opt.update(g, os_, p, lr=0.05)
            return (p2, os2), ()
        (p, os_), _ = jax.lax.scan(body, (p, os_), None, length=150)
        return p

    p = train(params, opt_state)
    xt, yt = batch(2048, 2)
    pred = np.asarray(jax.jit(functional_call, static_argnums=0)(net, p,
                                                                jnp.asarray(xt)))
    acc = float((pred.argmax(-1) == yt).mean())
    return _emit("mnist_mlp_smoke_accuracy", acc, "accuracy",
                 acc / MNIST_ACC_GATE)


def bench_ctr():
    """Config 5: Wide&Deep CTR - converged-AUC gate on noisy synthetic clicks.

    Labels are Bernoulli draws from a latent logit (per-id effect + linear
    dense effect); Bayes-optimal AUC on held-out data is ~0.91, so a healthy
    converged model lands ~0.85-0.90 — strictly inside (gate, 1.0)."""
    import paddle_tpu as paddle
    from paddle_tpu import metric as pmetric
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models import wide_deep_tiny

    paddle.seed(0)
    rng = np.random.RandomState(0)
    n, fields, vocab, dense = 4096, 4, 64, 4
    table = rng.randn(vocab)
    w_dense = rng.randn(dense) * 0.5

    def make(n, r):
        ids = r.randint(0, vocab, size=(n, fields)).astype(np.int32)
        xd = r.randn(n, dense).astype(np.float32)
        s = 2.0 * (table[ids[:, 0]] + xd @ w_dense)[:, None]
        y = (r.uniform(size=(n, 1)) < 1 / (1 + np.exp(-s))).astype(np.float32)
        return ids, xd, y

    ids, xd, y = make(n, rng)
    ids_t, xd_t, y_t = make(n, np.random.RandomState(7))

    # sparse=True + lazy_mode: the SelectedRows O(touched-rows) path — the
    # production CTR configuration (tools/bench_sparse_embedding.py measures
    # its vocab-independence)
    net = wide_deep_tiny(sparse=True)
    model = paddle.Model(net, inputs=["sparse", "dense"], labels=["label"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2, lazy_mode=True),
                  loss=net.loss)
    for _ in range(120):
        loss, _ = model.train_batch([ids, xd], [y])

    import jax
    logits = np.asarray(model.predict_batch([ids_t, xd_t])).reshape(-1)
    prob = np.asarray(jax.nn.sigmoid(logits))  # Auc buckets expect [0,1]
    auc = pmetric.Auc()
    auc.update(np.stack([1 - prob, prob], -1), y_t)
    a = float(auc.accumulate())
    return _emit("wide_deep_ctr_auc", a, "auc", a / CTR_AUC_GATE,
                 bayes_auc=0.91)


def bench_flash_32k():
    """Long-context headline: 32k-token causal flash attention fwd+bwd on
    one chip (the triangle-grid Pallas kernels, ops/flash_attention.py).
    vs_baseline is the round-3 measurement (139 ms) — >1 means faster."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from paddle_tpu.ops.flash_attention import flash_attention

    B, H, S, D = 1, 8, 32768, 128
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(B, H, S, D).astype(ml_dtypes.bfloat16))
    q, k, v = mk(), mk(), mk()

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(q, k, v)
    float(g[0].astype(jnp.float32).sum())  # compile + warm
    N, best = 10, float("inf")
    for _ in range(3):
        t0 = _time.perf_counter()
        for _ in range(N):
            g = step(q, k, v)
        float(g[0].astype(jnp.float32).sum())
        best = min(best, (_time.perf_counter() - t0) / N)
    ms = best * 1e3
    # train FLOPs: fwd+bwd ≈ 3.5× fwd; causal halves the score work
    tflops = 3.5 * 2 * B * H * S * S * D * 2 * 0.5 / best / 1e12
    return _emit("flash_attention_32k_causal_fwd_bwd_ms", round(ms, 1),
                 "ms", 139.0 / ms, achieved_tflops=round(tflops, 1),
                 mfu=round(tflops / TPU_PEAK_TFLOPS, 3))


def main():
    results, failed = {}, []
    for name, fn in [("bert", bench_bert), ("resnet50", bench_resnet50),
                     ("mnist", bench_mnist), ("ctr", bench_ctr),
                     ("flash32k", bench_flash_32k)]:
        try:
            results[name] = fn()
        except Exception as e:  # keep later configs running; failure visible
            failed.append(name)
            print(f"bench config {name!r} FAILED: {e!r}", file=sys.stderr)
    if "bert" in results and "resnet50" in results:
        g = math.sqrt(results["bert"]["vs_baseline"]
                      * results["resnet50"]["vs_baseline"])
        _emit("train_throughput_geomean_vs_a100", g, "ratio", g,
              bert_seq_per_sec=results["bert"]["value"],
              resnet50_img_per_sec=results["resnet50"]["value"],
              # the two inputs use different dispatch methodologies (see the
              # per-config "method" fields); the geomean is a headline, not a
              # like-for-like comparison.
              methods={"bert": "per_step_dispatch",
                       "resnet50": "scan_chained"})
    if failed:
        sys.exit(1)  # a green exit code must mean every config was measured


if __name__ == "__main__":
    main()

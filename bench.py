"""Benchmark: BERT-base training throughput, seq/sec on one chip.

North star (BASELINE.json): BERT-base seq/sec/chip ≥ 0.9× the stock CUDA
build on A100.  The reference publishes no in-tree numbers (BASELINE.md);
``A100_REF_SEQ_PER_SEC`` (~1100 seq/s) stands in for the public NVIDIA
DeepLearningExamples BERT-base phase-1 (seq 128, AMP, 1×A100) pretraining
throughput — vs_baseline is measured/1100.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import numpy as np

# Public NVIDIA DeepLearningExamples BERT-base phase-1 (seq 128, AMP, 1×A100)
# pretraining throughput is ~1.1k seq/s; used as the "stock CUDA on A100"
# stand-in since the reference repo publishes no numbers (BASELINE.md).
A100_REF_SEQ_PER_SEC = 1100.0

# AMP-equivalent config (reference benchmarks run AMP O1 on CUDA): bf16
# params+activations with f32 master weights in the optimizer.  Standard
# phase-1 MLM task shape: the decoder runs over max_predictions_per_seq
# masked positions (the A100 baseline does the same), not the full sequence.
BATCH = 256
SEQ = 128
MAX_PRED = 20
WARMUP = 3
ITERS = 10
WINDOWS = 3  # timing windows; report the best — external interference on
#              the shared tunnel backend only ever slows a window down


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models import GPTConfig  # noqa: F401  (import check)
    from paddle_tpu.models import BertForPretraining, bert_base

    paddle.seed(0)
    cfg = bert_base()
    net = BertForPretraining(cfg).astype("bfloat16")

    opt = popt.AdamW(learning_rate=1e-4, weight_decay=0.01,
                     multi_precision=True)
    model = paddle.Model(
        net,
        inputs=["input_ids", "token_type_ids", "attention_mask",
                "masked_positions"],
        labels=["mlm_labels", "nsp_labels"])
    model.prepare(optimizer=opt, loss=net.loss)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    token_type = (rng.uniform(size=(BATCH, SEQ)) < 0.5).astype(np.int32)
    attn_mask = np.ones((BATCH, SEQ), np.int32)
    positions = np.stack([
        np.sort(rng.choice(SEQ, MAX_PRED, replace=False))
        for _ in range(BATCH)]).astype(np.int32)
    mlm_labels = np.take_along_axis(ids, positions, axis=1)  # [B, MAX_PRED]
    nsp_labels = rng.randint(0, 2, size=(BATCH, 1)).astype(np.int32)

    def step():
        loss, _ = model._train_batch_device(
            [ids, token_type, attn_mask, positions],
            [mlm_labels, nsp_labels])
        return loss

    for _ in range(WARMUP):
        loss = step()
    float(loss)  # value fetch: block_until_ready is a no-op on remote-tunnel
                 # backends, only a D2H read truly waits for execution

    best_dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step()
        final = float(loss)  # steps are param-chained; fetching the last
        dt = time.perf_counter() - t0  # loss waits for the whole sequence
        assert np.isfinite(final)
        best_dt = min(best_dt, dt)

    seq_per_sec = BATCH * ITERS / best_dt
    print(json.dumps({
        "metric": "bert_base_train_seq_per_sec_per_chip",
        "value": round(seq_per_sec, 2),
        "unit": "seq/s",
        "vs_baseline": round(seq_per_sec / A100_REF_SEQ_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()

"""Benchmarks: the BASELINE.md configs, one JSON line per measured config.

North star (BASELINE.json): ResNet-50 imgs/sec/chip and BERT-base seq/sec/chip
>= 0.9x the stock CUDA build on A100, identical converged accuracy.  The
reference publishes no in-tree numbers (BASELINE.md), so the A100 constants
below stand in from the public NVIDIA DeepLearningExamples results.

Config map (BASELINE.md "Benchmark configs to reproduce"):
  1. MNIST MLP smoke          -> converged-accuracy gate (the reference's own
                                 CI gate form: test_recognize_digits.py:126)
  2. ResNet-50 AMP            -> imgs/sec/chip vs A100_REF_IMG_PER_SEC
  3. BERT-base                -> seq/sec/chip vs A100_REF_SEQ_PER_SEC
  4. 8-chip DP ResNet-50      -> NOT measurable here: this environment exposes
                                 exactly one real chip (the 8-device mesh is
                                 CPU-virtual, see __graft_entry__.dryrun_multichip)
  5. Wide&Deep CTR            -> converged-AUC gate on learnable synthetic
                                 clickthrough (PS capability = sharded tables)

Measurement notes:
  * Train configs (BERT, ResNet, MNIST) run through the framework's fused
    multi-step API — ``Executor.run_steps(program, feed, fetch_list,
    iterations=N, fetch_every=N)`` — which chains N optimizer steps inside
    ONE jitted lax.scan and fetches a single scalar, so a window is one
    device dispatch.  The real chip sits behind a network tunnel whose
    per-dispatch RTT (~1s) swamps a ~50ms step; per-step dispatch reads
    60 img/s where the device does 2.5k img/s.  Fused chaining measures
    device throughput the way a real TPU training loop (local host,
    compiled loop) would see it.  NOTE: BERT switched from per-step
    dispatch (rounds 1-5; round 5 timed out at rc=124) to the fused path —
    the per-config "method" field records the change for round-over-round
    comparison.
  * ResNet runs data_format="NHWC" (the TPU-preferred layout the vision
    models expose) with bf16 params + f32 master weights - the AMP-equivalent
    of the reference's AMP O1 CUDA runs.
  * Every config runs under its own wall-clock budget
    (PADDLE_TPU_BENCH_BUDGET_S, default 600s).  A config that exhausts it
    emits a partial "<name>_partial" JSON line with status="timeout" and
    the round keeps going — one slow config no longer loses the whole
    round's output (the BENCH_r05.json rc=124 / parsed:null failure mode).

The last line is a combined headline: geomean of the two throughput ratios.
"""
import contextlib
import json
import math
import re
import signal
import sys
import time

import numpy as np

# Public NVIDIA DeepLearningExamples BERT-base phase-1 (seq 128, AMP, 1xA100)
# pretraining throughput is ~1.1k seq/s.
A100_REF_SEQ_PER_SEC = 1100.0
# Public NVIDIA DeepLearningExamples ResNet-50 v1.5 mixed-precision training,
# single A100: ~2.5k img/s.
A100_REF_IMG_PER_SEC = 2500.0
# Reference CI accuracy gate for the MNIST book test
# (python/paddle/fluid/tests/book/test_recognize_digits.py:126 asserts the
# trained accuracy threshold).
MNIST_ACC_GATE = 0.97
# Synthetic-clickthrough AUC gate for the CTR config (the reference's CTR CI
# runs are loss-decrease asserts).  The task is deliberately noisy — labels
# are Bernoulli draws from a latent logit, Bayes-optimal AUC ~0.91 — so the
# measured AUC sits strictly inside (gate, 1.0) and actually tracks
# convergence quality instead of saturating at the ceiling.
CTR_AUC_GATE = 0.8

# Peak dense bf16 matmul throughput of the chip the bench runs on, used for
# the MFU lines.  v5e ≈ 197 TFLOP/s; override via PADDLE_TPU_PEAK_TFLOPS when
# the driver moves to other hardware.
import os as _os
TPU_PEAK_TFLOPS = float(_os.environ.get("PADDLE_TPU_PEAK_TFLOPS", "197"))

# Model FLOPs per training unit (fwd+bwd ≈ 3× fwd):
#   BERT-base: 6 * 110e6 params * 128 tokens ≈ 84.5 GFLOP / sequence
#   ResNet-50: 3 * ~4.1 GFLOP fwd @224 ≈ 12.3 GFLOP / image
BERT_TRAIN_GFLOP_PER_SEQ = 84.5
RESNET50_TRAIN_GFLOP_PER_IMG = 12.3


def _emit(metric, value, unit, vs_baseline, **extra):
    line = {"metric": metric, "value": round(float(value), 4), "unit": unit,
            "vs_baseline": round(float(vs_baseline), 3)}
    line.update(extra)
    print(json.dumps(line), flush=True)
    try:  # mirror into FLAGS_metrics_jsonl (no-op when the flag is unset)
        from paddle_tpu.observability import exporters as _obs_exp

        _obs_exp.append_jsonl_record(dict(line, kind="bench"))
    except Exception:
        pass
    return line


class BenchTimeout(Exception):
    """A config exhausted its wall-clock budget (partial line emitted)."""

    def __init__(self, seconds):
        self.seconds = seconds
        super().__init__(f"wall-clock budget of {seconds:g}s exhausted")


@contextlib.contextmanager
def _wall_clock_budget(seconds):
    """Raise BenchTimeout in the main thread after ``seconds`` of wall
    clock — the per-config bound that keeps one stuck config (device
    unreachable, compile stall) from eating the whole round.  No-op when
    seconds <= 0 or the platform lacks setitimer (non-POSIX)."""
    if seconds <= 0 or not hasattr(signal, "setitimer"):
        yield
        return

    def on_alarm(signum, frame):
        raise BenchTimeout(seconds)

    prev = signal.signal(signal.SIGALRM, on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev)


# A dead accelerator surfaces as PJRT init failures of this shape — once
# seen, every remaining device config would fail the same slow way
# (each burning its full budget waiting on the tunnel), so the round
# short-circuits instead.
_BACKEND_DEAD_RE = re.compile(r"nable to initialize backend|UNAVAILABLE")


def _probe_backend(budget_s):
    """One bounded ``jax.devices()`` up front: returns ``(platform, None)``
    when a backend came up, ``(None, reason)`` when init failed or hung.
    Bounded at min(budget, 120s) — a dead tunnel otherwise blocks the
    first config for its whole budget before the failure is visible."""
    cap = min(budget_s, 120.0) if budget_s > 0 else 120.0
    try:
        with _wall_clock_budget(cap):
            import jax

            return jax.devices()[0].platform, None
    except BenchTimeout:
        return None, f"backend init exceeded {cap:g}s"
    except Exception as e:  # PJRT raises RuntimeError subclasses; be broad
        return None, repr(e)


def bench_bert():
    """Config 3: BERT-base MLM+NSP pretraining, fused multi-step chain
    (Executor.run_steps — one dispatch per N_STEPS window)."""
    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models import BertForPretraining, bert_base
    from paddle_tpu.static.builders import layer_op
    from paddle_tpu.static.graph import record_call

    BATCH, SEQ, MAX_PRED, N_STEPS, WINDOWS = 256, 128, 20, 10, 3

    paddle.seed(0)
    cfg = bert_base()
    net = BertForPretraining(cfg).astype("bfloat16")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids_v = fluid.data("input_ids", [BATCH, SEQ], "int32")
        tt_v = fluid.data("token_type_ids", [BATCH, SEQ], "int32")
        am_v = fluid.data("attention_mask", [BATCH, SEQ], "int32")
        mp_v = fluid.data("masked_positions", [BATCH, MAX_PRED], "int32")
        mlm_y = fluid.data("mlm_labels", [BATCH, MAX_PRED], "int32")
        nsp_y = fluid.data("nsp_labels", [BATCH, 1], "int32")
        mlm_logits, nsp_logits = layer_op(
            net, ids_v, prefix="bert", extra_args=(tt_v, am_v, mp_v))
        loss = record_call(net.loss, mlm_logits, nsp_logits, mlm_y, nsp_y,
                           prefix="bert_loss")
        popt.AdamW(learning_rate=1e-4, weight_decay=0.01,
                   multi_precision=True).minimize(loss)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)
    positions = np.stack([
        np.sort(rng.choice(SEQ, MAX_PRED, replace=False))
        for _ in range(BATCH)]).astype(np.int32)
    feeds = {
        "input_ids": ids,
        "token_type_ids": (rng.uniform(size=(BATCH, SEQ)) < 0.5)
        .astype(np.int32),
        "attention_mask": np.ones((BATCH, SEQ), np.int32),
        "masked_positions": positions,
        "mlm_labels": np.take_along_axis(ids, positions, axis=1),
        "nsp_labels": rng.randint(0, 2, size=(BATCH, 1)).astype(np.int32),
    }

    exe = fluid.Executor()
    exe.run(startup)

    def window():  # one device dispatch: N_STEPS chained optimizer steps
        out, = exe.run_steps(main, feed=feeds, fetch_list=[loss],
                             iterations=N_STEPS, fetch_every=N_STEPS,
                             constant_feeds=tuple(feeds))
        return float(np.asarray(out)[-1])  # D2H read truly waits

    final = window()  # compile + warm
    assert np.isfinite(final)
    best_dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        final = window()
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        best_dt = min(best_dt, dt)

    seq_per_sec = BATCH * N_STEPS / best_dt
    tflops = seq_per_sec * BERT_TRAIN_GFLOP_PER_SEQ / 1e3
    return _emit("bert_base_train_seq_per_sec_per_chip", round(seq_per_sec, 2),
                 "seq/s", seq_per_sec / A100_REF_SEQ_PER_SEC,
                 method="run_steps_fused", chain_len=N_STEPS,
                 achieved_tflops=round(tflops, 1),
                 mfu=round(tflops / TPU_PEAK_TFLOPS, 3))


def bench_resnet50():
    """Config 2: ResNet-50 AMP train, fused multi-step chain
    (Executor.run_steps — one dispatch per N_STEPS window)."""
    import jax.numpy as jnp
    import ml_dtypes

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu import optimizer as popt
    from paddle_tpu.static.builders import layer_op
    from paddle_tpu.static.graph import record_call
    from paddle_tpu.vision.models import resnet50

    BATCH, N_STEPS, WINDOWS = 128, 60, 3  # long windows amortize
    # the ~0.3s tunnel dispatch RTT to <1% of the measurement

    paddle.seed(0)
    # stem_space_to_depth: the 7x7/s2 stem re-expressed as 4x4/s1 on 2x2
    # space-to-depth input (exact same math; vision/models/resnet.py) —
    # C=3 of 128 MXU lanes was the single worst-utilization conv
    net = resnet50(data_format="NHWC",
                   stem_space_to_depth=True).astype("bfloat16")
    loss_layer = paddle.nn.CrossEntropyLoss()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("image", [BATCH, 224, 224, 3], "bfloat16")
        label = fluid.data("label", [BATCH, 1], "int32")
        logits = layer_op(net, img, prefix="resnet50")
        loss = record_call(
            lambda o, y: loss_layer(o.astype(jnp.float32), y),
            logits, label, prefix="xent")
        popt.Momentum(learning_rate=0.1, momentum=0.9, multi_precision=True,
                      weight_decay=1e-4).minimize(loss)

    rng = np.random.RandomState(0)
    feeds = {"image": rng.uniform(-1, 1, (BATCH, 224, 224, 3))
             .astype(ml_dtypes.bfloat16),
             "label": rng.randint(0, 1000, (BATCH, 1)).astype(np.int32)}

    exe = fluid.Executor()
    exe.run(startup)

    def window():  # one device dispatch: N_STEPS chained optimizer steps
        out, = exe.run_steps(main, feed=feeds, fetch_list=[loss],
                             iterations=N_STEPS, fetch_every=N_STEPS,
                             constant_feeds=("image", "label"))
        return float(np.asarray(out)[-1])

    final = window()  # compile + warm
    assert np.isfinite(final)
    best_dt = float("inf")
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        final = window()
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        best_dt = min(best_dt, dt)

    img_per_sec = BATCH * N_STEPS / best_dt
    tflops = img_per_sec * RESNET50_TRAIN_GFLOP_PER_IMG / 1e3
    return _emit("resnet50_train_img_per_sec_per_chip", round(img_per_sec, 1),
                 "img/s", img_per_sec / A100_REF_IMG_PER_SEC,
                 method="run_steps_fused", chain_len=N_STEPS,
                 achieved_tflops=round(tflops, 1),
                 mfu=round(tflops / TPU_PEAK_TFLOPS, 3))


def bench_mnist():
    """Config 1: MNIST-shaped MLP smoke - converged-accuracy gate.

    No egress, so the data is synthetic MNIST-shaped: 10 fixed prototype
    images + pixel noise.  The gate form mirrors the reference CI
    (test_recognize_digits.py:126): train briefly, assert accuracy.
    """
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.fluid as fluid
    from paddle_tpu import nn
    from paddle_tpu import optimizer as popt
    from paddle_tpu.static.builders import layer_op
    from paddle_tpu.static.graph import record_call

    paddle.seed(0)
    rng = np.random.RandomState(0)
    protos = rng.uniform(0, 1, (10, 784)).astype(np.float32)

    def batch(n, seed):
        r = np.random.RandomState(seed)
        y = r.randint(0, 10, n).astype(np.int32)
        x = protos[y] + r.normal(0, 0.35, (n, 784)).astype(np.float32)
        return (x - 0.5).astype(np.float32), y

    net = nn.Sequential(nn.Linear(784, 128), nn.ReLU(),
                        nn.Linear(128, 64), nn.ReLU(), nn.Linear(64, 10))

    def nll(logits, y):
        lp = jax.nn.log_softmax(logits, -1)
        return -jnp.take_along_axis(lp, y[:, None], 1).mean()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 784])
        y = fluid.data("y", [-1], "int32")
        logits = layer_op(net, x, prefix="mlp")
        loss = record_call(nll, logits, y, prefix="nll")
        popt.SGD(learning_rate=0.05).minimize(loss)

    xs, ys = batch(4096, 1)
    exe = fluid.Executor()
    exe.run(startup)
    # full 150-step training run: ONE device dispatch
    exe.run_steps(main, feed={"x": xs, "y": ys}, fetch_list=[loss],
                  iterations=150, fetch_every=150, constant_feeds=("x", "y"))

    xt, yt = batch(2048, 2)
    test_prog = main.clone(for_test=True)
    pred, = exe.run(test_prog, feed={"x": xt, "y": yt}, fetch_list=[logits])
    acc = float((np.asarray(pred).argmax(-1) == yt).mean())
    return _emit("mnist_mlp_smoke_accuracy", acc, "accuracy",
                 acc / MNIST_ACC_GATE, method="run_steps_fused")


def bench_ctr():
    """Config 5: Wide&Deep CTR - converged-AUC gate on noisy synthetic clicks.

    Labels are Bernoulli draws from a latent logit (per-id effect + linear
    dense effect); Bayes-optimal AUC on held-out data is ~0.91, so a healthy
    converged model lands ~0.85-0.90 — strictly inside (gate, 1.0)."""
    import paddle_tpu as paddle
    from paddle_tpu import metric as pmetric
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models import wide_deep_tiny

    paddle.seed(0)
    rng = np.random.RandomState(0)
    n, fields, vocab, dense = 4096, 4, 64, 4
    table = rng.randn(vocab)
    w_dense = rng.randn(dense) * 0.5

    def make(n, r):
        ids = r.randint(0, vocab, size=(n, fields)).astype(np.int32)
        xd = r.randn(n, dense).astype(np.float32)
        s = 2.0 * (table[ids[:, 0]] + xd @ w_dense)[:, None]
        y = (r.uniform(size=(n, 1)) < 1 / (1 + np.exp(-s))).astype(np.float32)
        return ids, xd, y

    ids, xd, y = make(n, rng)
    ids_t, xd_t, y_t = make(n, np.random.RandomState(7))

    # sparse=True + lazy_mode: the SelectedRows O(touched-rows) path — the
    # production CTR configuration (tools/bench_sparse_embedding.py measures
    # its vocab-independence)
    net = wide_deep_tiny(sparse=True)
    model = paddle.Model(net, inputs=["sparse", "dense"], labels=["label"])
    model.prepare(optimizer=popt.Adam(learning_rate=1e-2, lazy_mode=True),
                  loss=net.loss)
    for _ in range(120):
        loss, _ = model.train_batch([ids, xd], [y])

    import jax
    logits = np.asarray(model.predict_batch([ids_t, xd_t])).reshape(-1)
    prob = np.asarray(jax.nn.sigmoid(logits))  # Auc buckets expect [0,1]
    auc = pmetric.Auc()
    auc.update(np.stack([1 - prob, prob], -1), y_t)
    a = float(auc.accumulate())
    return _emit("wide_deep_ctr_auc", a, "auc", a / CTR_AUC_GATE,
                 bayes_auc=0.91)


def bench_flash_32k():
    """Long-context headline: 32k-token causal flash attention fwd+bwd on
    one chip (the triangle-grid Pallas kernels, ops/flash_attention.py).
    vs_baseline is the round-3 measurement (139 ms) — >1 means faster."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from paddle_tpu.ops.flash_attention import flash_attention

    B, H, S, D = 1, 8, 32768, 128
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(B, H, S, D).astype(ml_dtypes.bfloat16))
    q, k, v = mk(), mk(), mk()

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(q, k, v)
    float(g[0].astype(jnp.float32).sum())  # compile + warm
    N, best = 10, float("inf")
    for _ in range(3):
        t0 = _time.perf_counter()
        for _ in range(N):
            g = step(q, k, v)
        float(g[0].astype(jnp.float32).sum())
        best = min(best, (_time.perf_counter() - t0) / N)
    ms = best * 1e3
    # train FLOPs: fwd+bwd ≈ 3.5× fwd; causal halves the score work
    tflops = 3.5 * 2 * B * H * S * S * D * 2 * 0.5 / best / 1e12
    return _emit("flash_attention_32k_causal_fwd_bwd_ms", round(ms, 1),
                 "ms", 139.0 / ms, achieved_tflops=round(tflops, 1),
                 mfu=round(tflops / TPU_PEAK_TFLOPS, 3))


def bench_gpt_generate():
    """Serving headline: slot-level continuous-batching decode throughput
    over a fixed-seed sweep of mixed prompt/output lengths.  vs_baseline
    is the legacy run-batch-to-completion scheduler on the IDENTICAL
    workload (same model, same requests, same submission order) — >1
    means continuous batching is faster end-to-end."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine

    paddle.seed(1234)
    cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                    num_heads=8, max_position=512, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    # ragged on both axes: prompts 4..48 tokens, outputs 4..64 tokens —
    # the spread the legacy scheduler pays head-of-line blocking on.
    # RequestTrace.synthetic replicates the historical inline RandomState
    # draws bit-identically, and the same trace drives the serving-config
    # measured search (tools/tune_smoke.py), so bench and tuner score the
    # identical workload.
    from paddle_tpu.tuning import RequestTrace, replay as _replay

    trace = RequestTrace.synthetic()
    trace_out = _os.environ.get("PADDLE_TPU_TRACE_OUT", "")
    if trace_out:
        trace.save(trace_out)

    def run(continuous, paged=False):
        with GenerationEngine(
                model, prompt_buckets=[16, 48], batch_size=8,
                max_queue_delay_ms=1.0, continuous=continuous,
                paged=paged,
                name=f"bench-gen-"
                     f"{'paged' if paged else 'cont' if continuous else 'legacy'}"
        ) as eng:
            eng.warmup()
            stats = _replay(eng, trace)
            return stats["tokens_per_sec"], stats["mean_ms"], eng.stats()

    legacy_tps, legacy_lat, _ = run(False)
    tps, lat_ms, _ = run(True)
    # paged KV + speculative decoding on the identical workload (default
    # pool = the same HBM the dense ring uses; no shared prefixes here,
    # so this isolates the paging/speculation overhead-vs-win alone)
    paged_tps, paged_lat, psnap = run(True, paged=True)
    return _emit("gpt_generate_tokens_per_sec", round(tps, 1), "tok/s",
                 tps / legacy_tps,
                 legacy_tokens_per_sec=round(legacy_tps, 1),
                 paged_tokens_per_sec=round(paged_tps, 1),
                 mean_latency_ms=round(float(lat_ms), 1),
                 legacy_mean_latency_ms=round(float(legacy_lat), 1),
                 paged_mean_latency_ms=round(float(paged_lat), 1),
                 # last-step latency breakdown (serving/metrics.py gauges:
                 # measured step wall time split by the engine's
                 # bandwidth-roofline attention share) — the number the
                 # paged-flash kernel moves on TPU
                 paged_decode_attn_ms=round(
                     float(psnap.get("decode_attn_ms", 0.0)), 3),
                 paged_decode_rest_ms=round(
                     float(psnap.get("decode_rest_ms", 0.0)), 3),
                 requests=len(trace), new_tokens=trace.total_new_tokens,
                 method="continuous_batching_vs_legacy")


def _bench_gpt_generate_quant(mode):
    """Quantized serving headline for one mode ('int8' / 'fp8'): the same
    seeded RequestTrace as bench_gpt_generate through a paged continuous
    engine quantized end-to-end (weights via ops.quantized_matmul, KV
    pages stored at the low precision with per-token scales) vs the
    float engine on the IDENTICAL workload.  vs_baseline is quantized
    tokens/s over float tokens/s; the line also reports the KV pool's
    measured HBM high-water at both precisions (the resident-slot
    economics) and a quantized-vs-float kernel microbench at a serving
    Linear shape."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.tuning import RequestTrace, replay as _replay

    paddle.seed(1234)
    cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                    num_heads=8, max_position=512, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    trace = RequestTrace.synthetic()

    def run(quantized):
        with GenerationEngine(
                model, prompt_buckets=[16, 48], batch_size=8,
                max_queue_delay_ms=1.0, continuous=True, paged=True,
                quantized=quantized,
                name=f"bench-gen-{quantized or 'float'}") as eng:
            eng.warmup()
            stats = _replay(eng, trace)
            pool = model.gpt.init_paged_cache(
                eng._kv_pages, eng._page, dtype=eng._kv_qdtype())
            pool_bytes = sum(int(t.nbytes) for layer in pool["layers"]
                             for t in layer.values())
            return stats["tokens_per_sec"], stats["mean_ms"], pool_bytes

    float_tps, float_lat, float_bytes = run(None)
    tps, lat_ms, pool_bytes = run(mode)

    # kernel microbench: the quantized Linear hot path vs the float
    # matmul it replaces, at a decode-step shape (warm, blocked timing)
    from paddle_tpu.ops.quantized_matmul import quantized_linear
    from paddle_tpu.slim.quantization import _quantize_weight

    M, K, N = 64, cfg.hidden_size, 4 * cfg.hidden_size
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(M, K).astype(np.float32))
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.02)
    wq, scale = _quantize_weight(w, mode)
    qf = jax.jit(lambda a: quantized_linear(a, wq, scale))
    ff = jax.jit(lambda a: a @ w)

    def best_ms(fn):
        np.asarray(fn(x))  # compile
        best = None
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(fn(x))
            dt = (time.perf_counter() - t0) * 1e3
            best = dt if best is None else min(best, dt)
        return best

    kq_ms, kf_ms = best_ms(qf), best_ms(ff)
    return _emit(f"gpt_generate_{mode}_tokens_per_sec", round(tps, 1),
                 "tok/s", tps / float_tps,
                 float_tokens_per_sec=round(float_tps, 1),
                 mean_latency_ms=round(float(lat_ms), 1),
                 float_mean_latency_ms=round(float(float_lat), 1),
                 kv_pool_bytes=pool_bytes,
                 float_kv_pool_bytes=float_bytes,
                 kv_hbm_ratio=round(pool_bytes / float_bytes, 3),
                 kernel_quant_ms=round(kq_ms, 3),
                 kernel_float_ms=round(kf_ms, 3),
                 kernel_speedup=round(kf_ms / kq_ms, 2),
                 requests=len(trace), new_tokens=trace.total_new_tokens,
                 method="quantized_vs_float_same_trace")


def bench_gpt_generate_int8():
    return _bench_gpt_generate_quant("int8")


def bench_gpt_generate_fp8():
    return _bench_gpt_generate_quant("fp8")


def bench_gpt_generate_multilora():
    """Multi-tenant LoRA serving headline: the same seeded RequestTrace
    as bench_gpt_generate through a paged continuous engine carrying a
    fixed-capacity adapter table at N in {1, 4, 16} installed adapters
    (requests round-robin over the slots, one tenant per slot) vs the
    base-only engine (lora_capacity=0) on the IDENTICAL workload.
    vs_baseline is 16-adapter tokens/s over base tokens/s — the cost of
    serving 16 tenants' adapters from ONE engine instead of 16 replicas.
    The line also reports per-tenant p99 at each capacity (worst slot)
    and a kernel microbench of the per-step adapter gather (compacted
    grouped lora_delta) against the base matmul it rides on — the
    adapter-gather share of a decode-step linear."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.lora import random_adapter
    from paddle_tpu.tuning import RequestTrace

    trace = RequestTrace.synthetic()
    hidden, rank = 256, 8

    def run(cap):
        paddle.seed(1234)
        cfg = GPTConfig(vocab_size=8192, hidden_size=hidden, num_layers=4,
                        num_heads=8, max_position=512, dropout=0.0,
                        lora_capacity=cap, lora_rank=rank)
        model = GPTForCausalLM(cfg)
        model.eval()
        with GenerationEngine(
                model, prompt_buckets=[16, 48], batch_size=8,
                max_queue_delay_ms=1.0, continuous=True, paged=True,
                name=f"bench-gen-lora{cap}") as eng:
            for s in range(cap):
                eng.install_adapter(s, random_adapter(
                    model, f"bench-a{s}", rank=rank, seed=100 + s))
            eng.warmup()
            lat = {}
            futs = []
            t0 = time.perf_counter()
            for i, (prompt, max_new) in enumerate(trace):
                aid = (i % cap) if cap else -1
                tn = f"tenant-{aid}" if aid >= 0 else "base"
                ts = time.perf_counter()
                kw = {"adapter_id": aid} if cap else {}
                f = eng.submit(prompt, max_new, **kw)
                f.add_done_callback(
                    lambda _, ts=ts, tn=tn: lat.setdefault(tn, []).append(
                        time.perf_counter() - ts))
                futs.append(f)
            tokens = sum(len(f.result(600)) for f in futs)
            seconds = time.perf_counter() - t0
        p99 = {tn: float(np.percentile(np.asarray(v) * 1e3, 99))
               for tn, v in lat.items()}
        return tokens / max(seconds, 1e-9), p99

    base_tps, base_p99 = run(0)
    by_cap = {cap: run(cap) for cap in (1, 4, 16)}

    # kernel microbench: the compacted grouped adapter gather (lora_delta)
    # at a decode-step linear shape, against the base matmul it augments —
    # the marginal per-step cost of a 16-slot table (warm, blocked timing)
    from paddle_tpu.lora.batched import lora_delta

    B, cap16 = 8, 16
    rng = np.random.RandomState(7)
    A = jnp.asarray(rng.randn(cap16, hidden, rank).astype(np.float32) * 0.02)
    Bw = jnp.asarray(rng.randn(cap16, rank, hidden).astype(np.float32) * 0.02)
    scale = jnp.ones((cap16,), jnp.float32)
    ids = jnp.asarray(np.arange(B) % cap16, np.int32)
    w = jnp.asarray(rng.randn(hidden, hidden).astype(np.float32) * 0.02)
    x = jnp.asarray(rng.randn(B, hidden).astype(np.float32))
    gf = jax.jit(lambda a: lora_delta(A, Bw, scale, a, ids)[0])
    bf = jax.jit(lambda a: a @ w)

    def best_ms(fn):
        np.asarray(fn(x))  # compile
        best = None
        for _ in range(5):
            t0 = time.perf_counter()
            np.asarray(fn(x))
            dt = (time.perf_counter() - t0) * 1e3
            best = dt if best is None else min(best, dt)
        return best

    gather_ms, base_ms = best_ms(gf), best_ms(bf)
    tps16 = by_cap[16][0]
    return _emit("gpt_generate_multilora_tokens_per_sec", round(tps16, 1),
                 "tok/s", tps16 / base_tps,
                 base_tokens_per_sec=round(base_tps, 1),
                 tokens_per_sec_1=round(by_cap[1][0], 1),
                 tokens_per_sec_4=round(by_cap[4][0], 1),
                 tokens_per_sec_16=round(tps16, 1),
                 base_p99_ms=round(max(base_p99.values()), 1),
                 tenant_p99_ms_worst_1=round(max(by_cap[1][1].values()), 1),
                 tenant_p99_ms_worst_4=round(max(by_cap[4][1].values()), 1),
                 tenant_p99_ms_worst_16=round(max(by_cap[16][1].values()), 1),
                 adapter_gather_ms=round(gather_ms, 3),
                 base_matmul_ms=round(base_ms, 3),
                 adapter_gather_share=round(
                     gather_ms / max(gather_ms + base_ms, 1e-9), 3),
                 requests=len(trace), new_tokens=trace.total_new_tokens,
                 method="multilora_vs_base_same_trace")


def bench_gpt_moe():
    """Expert-parallel training headline: a 8-expert top-2 MoE GPT vs the
    dense GPT it drops into, trained on the IDENTICAL token budget (same
    batch, sequence length, steps, data).  vs_baseline is dense step time
    over MoE step time — >1 means the routed model steps faster than the
    dense one of the same *activated* width; the line also reports the
    expert overflow fraction (capacity-dropped tokens / routed tokens) at
    the trained router, the quantity moe_capacity_factor trades against
    step time."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as popt
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.moe import stats as moe_stats

    B, S, STEPS, WARM = 8, 128, 20, 3
    rng = np.random.RandomState(17)
    batches = [rng.randint(0, 8192, size=(B, S)).astype(np.int32)
               for _ in range(STEPS + WARM)]

    def run(experts):
        paddle.seed(77)
        cfg = GPTConfig(vocab_size=8192, hidden_size=256, num_layers=4,
                        num_heads=8, max_position=S, dropout=0.0,
                        moe_experts=experts, moe_top_k=2)
        net = GPTForCausalLM(cfg)
        model = paddle.Model(net)
        model.prepare(optimizer=popt.Adam(learning_rate=1e-4),
                      loss=net.loss)
        for ids in batches[:WARM]:  # compile + adam-state warm
            model.train_batch([ids], [ids])
        t0 = time.perf_counter()
        for ids in batches[WARM:]:
            loss, _ = model.train_batch([ids], [ids])
        step_ms = (time.perf_counter() - t0) / STEPS * 1e3
        overflow = 0.0
        if experts:
            # overflow at the trained router: eager forward under a stats
            # collector (GPTModel, not the ForCausalLM wrapper — the
            # wrapper opens its own inner collector for the aux loss)
            net.eval()
            with moe_stats.collect() as ms:
                net.gpt(jnp.asarray(batches[-1]))
            counts = ms.counts(experts)
            routed, dropped = int(counts[0].sum()), int(counts[1].sum())
            overflow = dropped / max(routed + dropped, 1)
        return step_ms, float(loss), overflow

    dense_ms, dense_loss, _ = run(0)
    moe_ms, moe_loss, overflow = run(8)
    return _emit("gpt_moe_train_step_ms", round(moe_ms, 1), "ms",
                 dense_ms / moe_ms,
                 dense_step_ms=round(dense_ms, 1),
                 experts=8, top_k=2,
                 tokens_per_step=B * S, steps=STEPS,
                 expert_overflow_frac=round(overflow, 4),
                 moe_loss=round(moe_loss, 3),
                 dense_loss=round(dense_loss, 3),
                 method="train_batch_same_token_budget")


def main():
    budget_s = float(_os.environ.get("PADDLE_TPU_BENCH_BUDGET_S", "600"))
    allow_cpu = _os.environ.get(
        "PADDLE_TPU_BENCH_ALLOW_CPU", "") not in ("", "0")
    platform, probe_err = _probe_backend(budget_s)
    backend_dead = (probe_err is not None
                    or (platform == "cpu" and not allow_cpu))
    dead_reason = probe_err
    if backend_dead and dead_reason is None:
        dead_reason = ("jax initialized platform='cpu' — no accelerator; "
                       "set PADDLE_TPU_BENCH_ALLOW_CPU=1 to measure anyway")
    results, failed = {}, []
    for name, fn in [("bert", bench_bert), ("resnet50", bench_resnet50),
                     ("mnist", bench_mnist), ("ctr", bench_ctr),
                     ("flash32k", bench_flash_32k),
                     ("gpt_generate", bench_gpt_generate),
                     ("gpt_generate_int8", bench_gpt_generate_int8),
                     ("gpt_generate_fp8", bench_gpt_generate_fp8),
                     ("gpt_generate_multilora", bench_gpt_generate_multilora),
                     ("gpt_moe", bench_gpt_moe)]:
        if backend_dead:
            # fail fast: don't let each remaining config rediscover the
            # dead backend at one full budget apiece
            failed.append(name)
            _emit(f"{name}_failed", 0.0, "s", 0.0,
                  status="backend_unavailable", reason=dead_reason)
            continue
        t0 = time.perf_counter()
        try:
            with _wall_clock_budget(budget_s):
                results[name] = fn()
        except BenchTimeout:
            # a partial line keeps the round parseable (BENCH_r05.json's
            # rc=124 left parsed:null) and names the config that stalled
            failed.append(name)
            _emit(f"{name}_partial", time.perf_counter() - t0, "s", 0.0,
                  status="timeout", budget_s=budget_s)
        except Exception as e:  # keep later configs running; failure visible
            failed.append(name)
            print(f"bench config {name!r} FAILED: {e!r}", file=sys.stderr)
            if _BACKEND_DEAD_RE.search(repr(e)):
                backend_dead = True
                dead_reason = repr(e)
    if "bert" in results and "resnet50" in results:
        g = math.sqrt(results["bert"]["vs_baseline"]
                      * results["resnet50"]["vs_baseline"])
        _emit("train_throughput_geomean_vs_a100", g, "ratio", g,
              bert_seq_per_sec=results["bert"]["value"],
              resnet50_img_per_sec=results["resnet50"]["value"],
              methods={"bert": "run_steps_fused",
                       "resnet50": "run_steps_fused"})
    # the summary line ALWAYS lands, whatever died above — a round with no
    # final JSON line is indistinguishable from a crashed driver
    status = ("backend_unavailable" if backend_dead
              else "partial" if failed else "ok")
    extra = {"reason": dead_reason} if backend_dead else {}
    _emit("bench_summary", len(results), "configs",
          1.0 if status == "ok" else 0.0, status=status,
          measured=sorted(results), failed=failed, **extra)
    if failed:
        sys.exit(1)  # a green exit code must mean every config was measured


if __name__ == "__main__":
    main()
